"""KV-router plane tests: radix indexer, selector cost fn, recorder, and an
end-to-end routed two-worker deployment over the mocker (the reference's
router testbed — reference: lib/llm/tests/kv_manager.rs drives the mocker).
"""

import asyncio

import pytest

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.llm.kv_router.indexer import KvIndexer, RadixTree
from dynamo_tpu.llm.kv_router.metrics_aggregator import ProcessedEndpoints
from dynamo_tpu.llm.kv_router.protocols import (
    ForwardPassMetrics,
    KvCacheEventData,
    RouterEvent,
)
from dynamo_tpu.llm.kv_router.publisher import (
    KvEventPublisher,
    WorkerMetricsPublisher,
)
from dynamo_tpu.llm.kv_router.recorder import KvRecorder
from dynamo_tpu.llm.kv_router.router import KvRouter
from dynamo_tpu.llm.kv_router.scheduler import (
    DefaultWorkerSelector,
    KvRouterConfig,
)
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.llm.tokens import TokenBlockSequence
from dynamo_tpu.mocker import MockerConfig, MockerEngine
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.egress import PushRouter, RouterMode
from dynamo_tpu.runtime.engine import Context

pytestmark = pytest.mark.anyio


def _stored(hashes, parent=None):
    return KvCacheEventData(kind="stored", block_hashes=hashes, parent_hash=parent)


class TestRadixTree:
    def test_overlap_and_contiguity(self):
        t = RadixTree()
        t.apply_event(1, _stored([10, 11, 12]))
        t.apply_event(2, _stored([10, 11]))
        # worker 2 holds a NON-contiguous later block — must not count.
        t.apply_event(2, _stored([13], parent=12))
        assert t.find_matches([10, 11, 12, 13]) == {1: 3, 2: 2}
        assert t.find_matches([99]) == {}

    def test_removed_and_prune(self):
        t = RadixTree()
        t.apply_event(1, _stored([10, 11]))
        t.apply_event(1, KvCacheEventData(kind="removed", block_hashes=[11]))
        assert t.find_matches([10, 11]) == {1: 1}
        t.apply_event(1, KvCacheEventData(kind="removed", block_hashes=[10]))
        assert t.num_blocks == 0

    def test_remove_worker(self):
        t = RadixTree()
        t.apply_event(1, _stored([10, 11]))
        t.apply_event(2, _stored([10]))
        t.remove_worker(1)
        assert t.find_matches([10, 11]) == {2: 1}
        assert t.workers() == [2]


class TestSelector:
    def _endpoints(self, **workers):
        return ProcessedEndpoints(
            metrics={
                wid: ForwardPassMetrics(
                    kv_active_blocks=active,
                    kv_total_blocks=100,
                    num_requests_waiting=waiting,
                )
                for wid, (active, waiting) in workers.items()
            }
        )

    def test_overlap_wins(self):
        sel = DefaultWorkerSelector(KvRouterConfig(), seed=0)
        eps = self._endpoints(**{"1": (0, 0), "2": (0, 0)})
        eps.metrics = {1: eps.metrics["1"], 2: eps.metrics["2"]}
        d = sel.select(eps, {2: 4}, isl=64)
        assert d.worker_id == 2 and d.overlap_blocks == 4

    def test_load_penalty(self):
        sel = DefaultWorkerSelector(KvRouterConfig(), seed=0)
        eps = self._endpoints(**{"1": (90, 5), "2": (10, 0)})
        eps.metrics = {1: eps.metrics["1"], 2: eps.metrics["2"]}
        assert sel.select(eps, {}, isl=64).worker_id == 2

    def test_predicted_load_spreads_burst(self):
        """Back-to-back identical requests without a scrape in between must
        not all pile on one worker (reference: scheduler.rs:214)."""
        sel = DefaultWorkerSelector(KvRouterConfig(), seed=0)
        eps = self._endpoints(**{"1": (0, 0), "2": (0, 0)})
        eps.metrics = {1: eps.metrics["1"], 2: eps.metrics["2"]}
        chosen = {sel.select(eps, {}, isl=640).worker_id for _ in range(8)}
        assert chosen == {1, 2}


async def test_kv_indexer_async():
    idx = KvIndexer().start()
    idx.apply(RouterEvent(7, _stored([1, 2])))
    assert await idx.find_matches([1, 2, 3]) == {7: 2}
    idx.remove_worker(7)
    assert await idx.find_matches([1, 2]) == {}
    await idx.stop()


def test_recorder_roundtrip(tmp_path):
    path = tmp_path / "events.jsonl"
    rec = KvRecorder(path)
    rec.record(RouterEvent(1, _stored([5, 6])))
    rec.record(RouterEvent(1, KvCacheEventData(kind="removed", block_hashes=[6])))
    rec.close()

    tree = RadixTree()
    n = asyncio.run(
        KvRecorder.send_events(
            path, lambda ev: tree.apply_event(ev.worker_id, ev.event)
        )
    )
    assert n == 2
    assert tree.find_matches([5, 6]) == {1: 1}


class _Counting:
    def __init__(self, inner):
        self.inner = inner
        self.count = 0

    def generate(self, request):
        self.count += 1
        return self.inner.generate(request)


async def _spawn_worker(drt, component, seed):
    cfg = EngineConfig(
        model=ModelConfig.tiny_test(),
        num_blocks=64,
        max_num_seqs=4,
        max_model_len=256,
    )
    engine = MockerEngine(cfg, MockerConfig(seed=seed))
    wm = WorkerMetricsPublisher()
    pub = KvEventPublisher(drt, component, drt.primary_lease_id)
    engine._external_kv_event = pub.publish_engine_event
    engine._on_metrics = wm.publish
    await engine.start()
    counting = _Counting(engine)
    await component.endpoint("generate").serve(counting)
    await wm.create_endpoint(component)
    return engine, counting


async def test_routed_two_worker_prefix_affinity():
    """Two mocker workers; identical prompts must stick to one worker via
    radix overlap; a different prompt may go anywhere."""
    drt_a = await DistributedRuntime.in_process()
    drt_b = await DistributedRuntime.in_process(
        store=drt_a.store, bus=drt_a.bus, runtime=drt_a.runtime
    )
    comp_a = drt_a.namespace("test").component("worker")
    comp_b = drt_b.namespace("test").component("worker")
    eng_a, cnt_a = await _spawn_worker(drt_a, comp_a, seed=1)
    eng_b, cnt_b = await _spawn_worker(drt_b, comp_b, seed=2)

    router = await KvRouter(drt_a, comp_a).start()
    push = await PushRouter.create(
        drt_a,
        "test.worker.generate",
        mode=RouterMode.KV,
        selector=router.selector_fn,
    )

    async def send(prompt):
        req = PreprocessedRequest(
            token_ids=prompt,
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=4, ignore_eos=True),
        )
        out = []
        async for item in push.generate(Context(req.to_wire())):
            out.append(item)
        return out

    prompt = list(range(64))  # 4 full blocks
    await send(prompt)
    await asyncio.sleep(0.2)  # let KV events propagate to the indexer
    first = (cnt_a.count, cnt_b.count)
    assert sum(first) == 1

    # The winner now has registered prefix blocks; overlap must pin the
    # next identical prompt to it.
    hashes = TokenBlockSequence.from_tokens(prompt, block_size=16).sequence_hashes()
    overlaps = await router.indexer.find_matches(hashes)
    assert len(overlaps) == 1
    winner_count = cnt_a if first[0] else cnt_b
    await send(prompt)
    assert winner_count.count == 2

    await eng_a.stop()
    await eng_b.stop()
    await router.stop()
    await drt_a.shutdown()


async def test_router_service_standalone():
    """Standalone RouterService (reference: components/router/src/main.rs):
    clients address the router component's endpoint; the service forwards
    each request to the KV-best worker and relays the stream. Prefix
    affinity must hold through the extra hop, and a custom selector can
    replace the default cost function."""
    from dynamo_tpu.llm.router_service import RouterService

    drt_a = await DistributedRuntime.in_process()
    drt_b = await DistributedRuntime.in_process(
        store=drt_a.store, bus=drt_a.bus, runtime=drt_a.runtime
    )
    comp_a = drt_a.namespace("svc").component("worker")
    comp_b = drt_b.namespace("svc").component("worker")
    eng_a, cnt_a = await _spawn_worker(drt_a, comp_a, seed=1)
    eng_b, cnt_b = await _spawn_worker(drt_b, comp_b, seed=2)

    service = await RouterService(drt_a, "svc.worker.generate").start()
    # Clients see only the router component's endpoint.
    push = await PushRouter.create(
        drt_a, service.endpoint_path, mode=RouterMode.ROUND_ROBIN
    )

    async def send(prompt):
        req = PreprocessedRequest(
            token_ids=prompt,
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=4, ignore_eos=True),
        )
        out = []
        async for item in push.generate(Context(req.to_wire())):
            out.append(item)
        return out

    prompt = list(range(64))
    out = await send(prompt)
    assert out and sum(len(o.get("token_ids", [])) for o in out) == 4
    await asyncio.sleep(0.2)  # KV events -> indexer
    assert cnt_a.count + cnt_b.count == 1
    winner = cnt_a if cnt_a.count else cnt_b
    await send(prompt)
    assert winner.count == 2  # affinity survives the router hop

    # stop() deregisters: the routed endpoint's instance set empties, so
    # a fresh client finds nothing to route to.
    await service.stop()
    from dynamo_tpu.runtime.egress import Client
    from dynamo_tpu.runtime.component import EndpointId

    client = await Client.create(
        drt_a, EndpointId.parse(service.endpoint_path)
    )
    assert client.instances() == []

    # Custom selector (reference: CustomWorkerSelector, router main.rs:59):
    # pin everything to one worker regardless of overlap/load.
    pinned = drt_b.primary_lease_id

    class PinSelector(DefaultWorkerSelector):
        def select(self, endpoints, overlaps, isl):
            from dynamo_tpu.llm.kv_router.scheduler import SchedulingDecision

            if pinned not in endpoints.metrics:
                return None
            return SchedulingDecision(
                worker_id=pinned, overlap_blocks=0, logit=0.0
            )

    service2 = await RouterService(
        drt_a, "svc.worker.generate", component_name="router2",
        selector=PinSelector(),
    ).start()
    push2 = await PushRouter.create(
        drt_a, service2.endpoint_path, mode=RouterMode.ROUND_ROBIN
    )
    before = cnt_b.count
    for _ in range(3):
        req = PreprocessedRequest(
            token_ids=list(range(32)),
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=2, ignore_eos=True),
        )
        async for _item in push2.generate(Context(req.to_wire())):
            pass
    assert cnt_b.count == before + 3  # every request hit the pinned worker

    await service2.stop()
    await eng_a.stop()
    await eng_b.stop()
    await drt_a.shutdown()

"""Prompt-lookup speculative decoding (engine/runner.py decode_multi_spec):
greedy output must be EXACTLY the sequential greedy output (same model,
same cache — acceptance only keeps drafts the verify pass would have
produced anyway), sampled lanes must degrade to plain decode, and
acceptance must actually exceed 1 token/step on repetitive text.

The reference has no native engine to put this in (it delegates decode to
vLLM, which ships the same technique as "prompt lookup / n-gram
speculation") — here it is a first-class scan on device: drafts come from
a device-resident history buffer, so no host round trip per step.
"""

import asyncio

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.llm.protocols.common import (
    EngineOutput,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.runtime.engine import Context

pytestmark = pytest.mark.anyio

CFG = ModelConfig.tiny_test()
PARAMS = llama.init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)


def _cfg(**kw) -> EngineConfig:
    defaults = dict(
        model=CFG,
        dtype="float32",
        block_size=4,
        num_blocks=128,
        max_num_seqs=4,
        max_model_len=128,
        decode_chunk=4,
        speculative_k=3,
    )
    defaults.update(kw)
    return EngineConfig(**defaults)


async def _generate(engine, prompt, max_tokens=24, temperature=0.0, seed=None):
    pre = PreprocessedRequest(
        token_ids=prompt,
        sampling=SamplingOptions(temperature=temperature, seed=seed),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
    )
    tokens = []
    async for raw in engine.generate(Context(pre.to_wire())):
        tokens.extend(EngineOutput.from_wire(raw).token_ids)
    return tokens


async def _run(cfg, prompt, **kw):
    engine = TpuEngine(cfg, params=PARAMS)
    await engine.start()
    try:
        return await _generate(engine, prompt, **kw), engine
    finally:
        await engine.stop()


async def test_speculative_greedy_equals_sequential():
    """The headline invariant: spec on/off produce IDENTICAL greedy
    tokens. (A deep random model rarely accepts drafts — full-context
    attention makes repeated bigrams continue differently — so this is
    purely the correctness check; acceptance is proven below.)"""
    prompt = [1, 5, 9, 2, 7, 9, 2, 7]
    seq_tokens, _ = await _run(_cfg(speculative_k=0), prompt, max_tokens=32)
    spec_tokens, _ = await _run(_cfg(), prompt, max_tokens=32)
    assert spec_tokens == seq_tokens
    assert len(spec_tokens) == 32


async def test_speculative_accepts_on_cyclic_continuation():
    """Acceptance > 1 token/step where it must happen: a 0-layer model
    predicts from the last token alone, so greedy generation enters a
    cycle and prompt-lookup drafts are exactly what the verifier
    reproduces. Output must still equal the sequential rollout."""
    cfg0 = ModelConfig.tiny_test().scaled(num_layers=0)
    params0 = llama.init_params(jax.random.PRNGKey(0), cfg0, dtype=jnp.float32)

    async def run(spec_k):
        engine = TpuEngine(
            EngineConfig(
                model=cfg0, dtype="float32", block_size=4, num_blocks=128,
                max_num_seqs=2, max_model_len=128, decode_chunk=4,
                speculative_k=spec_k,
            ),
            params=params0,
        )
        await engine.start()
        try:
            toks = await _generate(engine, [1, 5, 9], max_tokens=48)
        finally:
            await engine.stop()
        return toks, engine

    seq_tokens, _ = await run(0)
    spec_tokens, engine = await run(3)
    assert spec_tokens == seq_tokens
    assert engine.spec_tokens_per_step > 1.5, engine.spec_tokens_per_step


async def test_speculative_concurrent_lanes_match_oracle():
    def oracle_greedy(prompt, n):
        toks = list(prompt)
        out = []
        for _ in range(n):
            logits = llama.reference_forward(CFG, PARAMS, jnp.asarray(toks))
            nxt = int(jnp.argmax(logits[-1]))
            toks.append(nxt)
            out.append(nxt)
        return out

    engine = TpuEngine(_cfg(), params=PARAMS)
    await engine.start()
    try:
        prompts = [[3, 1, 4, 1, 5], [2, 7, 1, 8, 2, 7], [9, 9, 8, 2, 6]]
        results = await asyncio.gather(
            *[_generate(engine, p, max_tokens=16) for p in prompts]
        )
        for p, got in zip(prompts, results):
            assert got == oracle_greedy(p, 16), p
    finally:
        await engine.stop()


async def test_speculative_sampled_lane_is_reproducible():
    """Non-greedy lanes accept zero drafts and sample from the same
    logits as plain decode. Chunk partitioning differs between the two
    modes (spec divides its step budget by K+1), so the sampling-key
    stream — and thus the exact tokens — legitimately differ from plain;
    the invariants are reproducibility under a fixed seed and a full-
    length stream."""
    prompt = [1, 5, 9, 2, 7]
    kw = dict(max_tokens=16, temperature=0.8, seed=7)
    a, _ = await _run(_cfg(seed=3), prompt, **kw)
    b, _ = await _run(_cfg(seed=3), prompt, **kw)
    assert a == b
    assert len(a) == 16
    plain, _ = await _run(_cfg(speculative_k=0, seed=3), prompt, **kw)
    assert len(plain) == 16  # same budget either mode


async def test_speculative_respects_stops_and_limits():
    cfg = _cfg(max_model_len=32)
    engine = TpuEngine(cfg, params=PARAMS)
    await engine.start()
    try:
        prompt = [1, 2, 3, 4, 5, 6, 7, 8]
        pre = PreprocessedRequest(
            token_ids=prompt,
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=64, ignore_eos=True),
        )
        tokens = []
        finish = None
        async for raw in engine.generate(Context(pre.to_wire())):
            out = EngineOutput.from_wire(raw)
            tokens.extend(out.token_ids)
            finish = out.finish_reason or finish
        # capped by context, never past it
        assert len(prompt) + len(tokens) <= cfg.max_model_len
        assert finish is not None
    finally:
        await engine.stop()


def test_speculative_config_validation():
    with pytest.raises(ValueError):
        EngineConfig(model=CFG, speculative_k=-1).validate()
    with pytest.raises(ValueError):
        EngineConfig(model=CFG, block_size=4, speculative_k=5).validate()


async def test_speculative_auto_gates_below_break_even_and_reprobes():
    """VERDICT r03 weak #7: sampled lanes accept zero drafts (exactly 1.0
    delivered token/step < break-even 1.4), so the engine must disable
    speculation after a window, serve plain decode correctly, then
    re-probe after speculative_probe_steps plain steps."""
    cfg = _cfg(speculative_window=8, speculative_probe_steps=16)
    engine = TpuEngine(cfg, params=PARAMS)
    await engine.start()
    try:
        prompt = [1, 5, 9, 2]
        assert engine.spec_active
        await _generate(
            engine, prompt, max_tokens=16, temperature=1.0, seed=11
        )
        assert not engine.spec_active, (
            f"gate should disable at {engine.spec_tokens_per_step:.2f} "
            f"tok/step"
        )
        assert engine.spec_tokens_per_step < cfg.speculative_break_even

        # The plain fallback must still produce correct greedy output.
        gated_tokens = await _generate(engine, prompt, max_tokens=8)
        plain_tokens, _ = await _run(
            _cfg(speculative_k=0), prompt, max_tokens=8
        )
        assert gated_tokens == plain_tokens

        # Enough plain steps re-arm the probe. (The probe may measure the
        # greedy stream below break-even and disable AGAIN within the same
        # run — correct behavior — so assert the re-probe EVENT, not the
        # final gate state.)
        await _generate(engine, prompt, max_tokens=16)
        assert engine.spec_probe_count >= 1, (
            "probe should have re-enabled speculation at least once"
        )
    finally:
        await engine.stop()


async def test_spec_flight_records_and_metric_surfaces():
    """Unified spec observability (DT011-clean): accepting-draft
    dispatches leave kind="spec" flight records carrying the
    drafted/accepted split, and the cumulative twins reach the metrics
    callback, the readiness snapshot, and ForwardPassMetrics."""
    from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics
    from dynamo_tpu.mocker import MockerConfig, MockerEngine, det_next_token

    # Position-free deterministic chain on a tiny vocab: an 11-cycle, so
    # a chain prompt's bigrams repeat and prompt-lookup drafts verify
    # (built through the sim's own closed-form helper).
    vocab = 23
    prompt = [3]
    for _ in range(47):
        prompt.append(int(det_next_token(prompt[-1], 0, vocab, positional=False)))
    eng = MockerEngine(
        EngineConfig(
            model=ModelConfig.tiny_test(), num_blocks=128, max_num_seqs=2,
            max_model_len=256, speculative_k=4, unified_token_budget=64,
        ),
        MockerConfig(
            vocab_size=vocab, deterministic_tokens=True, det_positional=False
        ),
    )
    metrics: list[dict] = []
    eng._on_metrics = metrics.append
    await eng.start()
    try:
        toks = await _generate(eng, prompt, max_tokens=32)
        assert len(toks) == 32
        assert eng.spec_tokens_per_step > 1.5  # drafts actually accepted
        recs = [r for r in eng.debug_steps() if r.get("kind") == "spec"]
        assert recs, "no spec flight records"
        assert any(r["drafted"] > 0 for r in recs)
        assert any(r["accepted"] > 0 for r in recs)
        assert sum(r["drafted"] for r in recs) == eng._spec_drafted
        assert sum(r["accepted"] for r in recs) == eng._spec_accepted
        # All three metric surfaces carry the cumulative twins.
        m = metrics[-1]
        assert m["spec_drafted_tokens_total"] == eng._spec_drafted
        assert m["spec_accepted_tokens_total"] == eng._spec_accepted
        r = eng.readiness()
        assert r["spec_drafted_tokens_total"] == eng._spec_drafted
        assert r["spec_accepted_tokens_total"] == eng._spec_accepted
        fpm = ForwardPassMetrics.from_wire(m)
        assert fpm.spec_drafted_tokens_total == eng._spec_drafted
        assert fpm.spec_accepted_tokens_total == eng._spec_accepted
    finally:
        await eng.stop()


async def test_spec_reprobe_recovers_on_accepting_traffic():
    """Regression (review round 3): a re-probe must measure DRAFT-VERIFY
    dispatches, not plain dispatches already in flight when the gate
    flipped — counting those judged every probe at 1.0 tok/step and
    speculation could never re-enable. Drive sampled traffic to disable
    the gate, then accepting greedy chain traffic: the probe must
    recover (spec active again, drafts accepted)."""
    from dynamo_tpu.mocker import MockerConfig, MockerEngine, det_next_token

    vocab = 23
    eng = MockerEngine(
        EngineConfig(
            model=ModelConfig.tiny_test(), num_blocks=256, max_num_seqs=2,
            max_model_len=512, speculative_k=4, unified_token_budget=64,
            speculative_window=8, speculative_probe_window=2,
            speculative_probe_steps=8,
        ),
        MockerConfig(
            vocab_size=vocab, deterministic_tokens=True, det_positional=False
        ),
    )
    await eng.start()
    try:
        # Sampled traffic: accepts nothing → the gate disables.
        await _generate(
            eng, [1, 5, 9, 2], max_tokens=16, temperature=1.0, seed=3
        )
        assert not eng.spec_active
        # Accepting greedy chain traffic: the re-probe must measure real
        # draft-verify dispatches and re-commit to speculation.
        prompt = [3]
        for _ in range(47):
            prompt.append(
                int(det_next_token(prompt[-1], 0, vocab, positional=False))
            )
        await _generate(eng, prompt, max_tokens=96)
        assert eng.spec_probe_count >= 1
        assert eng._spec_drafted > 0, (
            "re-probe never issued a draft-verify dispatch — the probe "
            "window was judged on plain dispatches"
        )
        assert eng.spec_active, (
            f"speculation never recovered on accepting traffic "
            f"({eng.spec_tokens_per_step:.2f} tok/step measured)"
        )
    finally:
        await eng.stop()


async def test_spec_gate_is_free_when_losing_mocker_ab():
    """VERDICT weak #6 (narrow scope): once the gate has disabled
    speculation, plain decode must pay ~0% overhead — each RE-probe runs
    only speculative_probe_window spec steps (not a full measurement
    window), so the steady-state loss is probe_window/probe_steps. The
    mocker's decode_multi_spec never accepts drafts (1.0 tok/step, a
    guaranteed loss) and charges the verify width per step — the exact
    regime the gate must make free. A/B'd against a plain mocker engine
    on the same workload (the BENCH_SPEC_AB path, mocker mode)."""
    from dynamo_tpu.mocker import MockerConfig, MockerEngine

    def mocker_cfg(**kw):
        defaults = dict(
            model=ModelConfig.tiny_test(),
            dtype="float32",
            num_blocks=128,
            max_num_seqs=2,
            max_model_len=512,
            decode_chunk=4,
        )
        defaults.update(kw)
        return EngineConfig(**defaults)

    window, probe_window, probe_steps = 8, 2, 32
    spec = MockerEngine(
        mocker_cfg(
            # decode_chunk == probe_window: a spec chunk is the probe's
            # quantum, so each re-probe costs exactly probe_window steps.
            decode_chunk=2,
            speculative_k=3,
            speculative_window=window,
            speculative_probe_window=probe_window,
            speculative_probe_steps=probe_steps,
        ),
        MockerConfig(seed=5),
    )
    plain = MockerEngine(mocker_cfg(), MockerConfig(seed=5))
    await spec.start()
    await plain.start()
    try:
        prompt = list(range(24))
        n_tokens = 360
        spec_toks = await _generate(spec, prompt, max_tokens=n_tokens)
        plain_toks = await _generate(plain, prompt, max_tokens=n_tokens)
        assert len(spec_toks) == len(plain_toks) == n_tokens
        # The gate disabled after the initial window and every re-probe
        # cost only probe_window steps: total losing (spec) work is
        # bounded by window + probes * probe_window — NOT window per
        # probe (the old ladder, which would be ~4x this bound here).
        assert not spec.spec_active
        assert spec.spec_probe_count >= 1, "re-probe never fired"
        budget = window + spec.spec_probe_count * probe_window
        assert spec._spec_steps <= budget + probe_window, (
            f"{spec._spec_steps} spec steps run; free-when-losing bound "
            f"is {budget}"
        )
        # Steady-state overhead ratio: losing steps over total steps —
        # must be single-digit percent, not the old ~window/probe_steps.
        overhead = spec._spec_steps / n_tokens
        assert overhead < 0.10, f"gated-off overhead {overhead:.1%}"
    finally:
        await spec.stop()
        await plain.stop()

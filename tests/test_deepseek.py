"""DeepSeek-family coverage: MLA paged attention vs the no-cache oracle,
V3 sigmoid routing semantics, shared experts, sharded serving equivalence,
and the HF checkpoint layout round trip (kv_b_proj split into the absorbed
w_uk/w_uv). Reference context: the reference serves DeepSeek-R1 through
vLLM (BASELINE.md stage 5); here MLA is native — the paged cache stores
one [latent ‖ roped k_pe] entry per token and the attention kernels run
as MQA (models/llama.py _qkv_mla)."""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.engine.runner import ModelRunner
from dynamo_tpu.llm.protocols.common import (
    EngineOutput,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.moe import MoeConfig, moe_router
from dynamo_tpu.parallel.mesh import build_mesh
from dynamo_tpu.runtime.engine import Context

pytestmark = pytest.mark.anyio

CFG = ModelConfig.tiny_mla_test()
PARAMS = llama.init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)


def oracle_greedy(prompt: list[int], n: int) -> list[int]:
    tokens = list(prompt)
    out = []
    for _ in range(n):
        logits = llama.reference_forward(CFG, PARAMS, jnp.asarray(tokens))
        nxt = int(jnp.argmax(logits[-1]))
        tokens.append(nxt)
        out.append(nxt)
    return out


def test_mla_cache_geometry():
    assert CFG.is_mla
    assert CFG.num_cache_heads == 1
    assert CFG.kv_cache_head_dim == CFG.kv_lora_rank + CFG.qk_rope_head_dim
    ecfg = EngineConfig(
        model=CFG, dtype="float32", block_size=4, num_blocks=32,
        max_num_seqs=2, max_model_len=64,
    )
    r = ModelRunner(ecfg)
    k_cache, _ = r.kv_caches[0]
    assert k_cache.shape[1] == 1  # one shared latent head
    assert k_cache.shape[2] == r.cache_head_dim


async def test_mla_engine_matches_oracle():
    ecfg = EngineConfig(
        model=CFG, dtype="float32", block_size=4, num_blocks=64,
        max_num_seqs=4, max_model_len=128,
    )
    engine = TpuEngine(ecfg, params=PARAMS)
    await engine.start()
    try:
        prompt = [1, 5, 9, 2, 7]  # crosses a block boundary
        pre = PreprocessedRequest(
            token_ids=prompt,
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=10, ignore_eos=True),
        )
        tokens = []
        async for raw in engine.generate(Context(pre.to_wire())):
            tokens.extend(EngineOutput.from_wire(raw).token_ids)
        assert tokens == oracle_greedy(prompt, 10)
    finally:
        await engine.stop()


def test_mla_sharded_matches_single_chip():
    """tp (q heads) × ep (experts) × dp mesh: MLA's latent cache is
    replicated over tp while q heads shard — greedy tokens must be
    identical to the single-device runner."""
    ecfg = EngineConfig(
        model=CFG, dtype="float32", block_size=16, num_blocks=32,
        max_num_seqs=2, max_model_len=128,
    )
    prompt = list(range(2, 18))
    blocks = [1, 2, 3, 4]
    single = ModelRunner(ecfg, params=PARAMS)
    tok = single.prefill(prompt, blocks, 0, (0.0, 0, 1.0))
    mesh = build_mesh({"tp": 2, "ep": 2, "dp": 2})
    sharded = ModelRunner(ecfg, params=PARAMS, mesh=mesh)
    tok2 = sharded.prefill(prompt, blocks, 0, (0.0, 0, 1.0))
    assert tok == tok2


def test_sigmoid_router_selection_bias_vs_weights():
    """V3 gating: the per-expert bias steers SELECTION only — the mixture
    weights come from the raw sigmoid probs of the selected experts."""
    cfg = MoeConfig(
        hidden_size=8, num_experts=4, num_experts_per_tok=2,
        gating="sigmoid", norm_topk_prob=True, routed_scaling_factor=1.0,
    )
    x = jnp.ones((1, 8), jnp.float32)
    w = jnp.zeros((8, 4), jnp.float32)
    # logits all 0 → probs all 0.5; bias pushes experts 2,3 into the top-k
    bias = jnp.asarray([0.0, 0.0, 1.0, 1.0], jnp.float32)
    gates = moe_router({"w_router": w, "router_bias": bias}, x, cfg)
    assert gates.shape == (1, 4)
    np.testing.assert_allclose(np.asarray(gates[0]), [0, 0, 0.5, 0.5], atol=1e-6)

    # without bias, softmax gating renormalizes over the selection
    cfg_sm = MoeConfig(
        hidden_size=8, num_experts=4, num_experts_per_tok=2, gating="softmax"
    )
    gates = moe_router({"w_router": w}, x, cfg_sm)
    assert float(gates.sum()) == pytest.approx(1.0, abs=1e-5)


def test_first_k_dense_replace_layer_plan():
    """Layer 0 is dense (no router); later layers carry router + shared
    experts (the V3/R1 layer plan)."""
    assert "w_router" not in PARAMS["layers"][0]
    assert "w_gate" in PARAMS["layers"][0]          # dense SwiGLU
    assert PARAMS["layers"][0]["w_gate"].ndim == 2
    layer1 = PARAMS["layers"][1]
    assert "w_router" in layer1
    assert "router_bias" in layer1                   # sigmoid gating
    assert layer1["w_gate"].ndim == 3                # stacked experts
    assert "w_shared_gate" in layer1


def test_deepseek_hf_load_roundtrip(tmp_path):
    """Synthesize a DeepSeek-layout safetensors checkpoint and load it:
    kv_b_proj splits into w_uk/w_uv per head, the router bias loads, and
    the loaded model's forward is finite and matches the layer plan."""
    from safetensors.numpy import save_file

    cfg = CFG
    rng = np.random.default_rng(0)
    H, dn, dr, dc = (
        cfg.num_heads, cfg.qk_nope_head_dim, cfg.qk_rope_head_dim,
        cfg.kv_lora_rank,
    )
    D, dv, E = cfg.hidden_size, cfg.v_head_dim, cfg.num_experts

    def t(*shape):
        return (rng.standard_normal(shape) * 0.02).astype(np.float32)

    tensors = {
        "model.embed_tokens.weight": t(cfg.vocab_size, D),
        "model.norm.weight": np.ones(D, np.float32),
        "lm_head.weight": t(cfg.vocab_size, D),
    }
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}"
        tensors |= {
            f"{p}.self_attn.q_a_proj.weight": t(cfg.q_lora_rank, D),
            f"{p}.self_attn.q_a_layernorm.weight": np.ones(
                cfg.q_lora_rank, np.float32
            ),
            f"{p}.self_attn.q_b_proj.weight": t(H * (dn + dr), cfg.q_lora_rank),
            f"{p}.self_attn.kv_a_proj_with_mqa.weight": t(dc + dr, D),
            f"{p}.self_attn.kv_a_layernorm.weight": np.ones(dc, np.float32),
            f"{p}.self_attn.kv_b_proj.weight": t(H * (dn + dv), dc),
            f"{p}.self_attn.o_proj.weight": t(D, H * dv),
            f"{p}.input_layernorm.weight": np.ones(D, np.float32),
            f"{p}.post_attention_layernorm.weight": np.ones(D, np.float32),
        }
        if cfg.moe_layer(i):
            Im = cfg.moe_intermediate_size
            tensors |= {
                f"{p}.mlp.gate.weight": t(E, D),
                f"{p}.mlp.gate.e_score_correction_bias": t(E),
            }
            for e in range(E):
                tensors |= {
                    f"{p}.mlp.experts.{e}.gate_proj.weight": t(Im, D),
                    f"{p}.mlp.experts.{e}.up_proj.weight": t(Im, D),
                    f"{p}.mlp.experts.{e}.down_proj.weight": t(D, Im),
                }
            Is = Im * cfg.n_shared_experts
            tensors |= {
                f"{p}.mlp.shared_experts.gate_proj.weight": t(Is, D),
                f"{p}.mlp.shared_experts.up_proj.weight": t(Is, D),
                f"{p}.mlp.shared_experts.down_proj.weight": t(D, Is),
            }
        else:
            I = cfg.intermediate_size
            tensors |= {
                f"{p}.mlp.gate_proj.weight": t(I, D),
                f"{p}.mlp.up_proj.weight": t(I, D),
                f"{p}.mlp.down_proj.weight": t(D, I),
            }
    save_file(tensors, str(tmp_path / "model.safetensors"))

    params = llama.load_hf_weights(cfg, str(tmp_path), dtype=jnp.float32)
    layer1 = params["layers"][1]
    assert layer1["w_uk"].shape == (H, dn, dc)
    assert layer1["w_uv"].shape == (H, dv, dc)
    np.testing.assert_allclose(
        np.asarray(layer1["router_bias"]),
        tensors["model.layers.1.mlp.gate.e_score_correction_bias"],
        atol=1e-6,
    )
    # the split must reproduce kv_b_proj exactly
    kvb = tensors["model.layers.1.self_attn.kv_b_proj.weight"].reshape(
        H, dn + dv, dc
    )
    np.testing.assert_allclose(np.asarray(layer1["w_uk"]), kvb[:, :dn], atol=1e-6)
    np.testing.assert_allclose(np.asarray(layer1["w_uv"]), kvb[:, dn:], atol=1e-6)
    out = llama.reference_forward(cfg, params, jnp.arange(2, 18, dtype=jnp.int32))
    assert bool(jnp.all(jnp.isfinite(out)))


def test_yarn_rope_scaling():
    """DeepSeek checkpoints ship rope_scaling type 'yarn' — from_hf must
    parse it, long-position rotations must differ from unscaled, and the
    mscale attention correction must match the formula."""
    from dynamo_tpu.ops.rope import RopeScaling, apply_rope

    s = RopeScaling.from_hf(
        {
            "type": "yarn",
            "factor": 40,
            "original_max_position_embeddings": 4096,
            "beta_fast": 32,
            "beta_slow": 1,
            "mscale": 1.0,
            "mscale_all_dim": 1.0,
        }
    )
    assert s.kind == "yarn" and s.factor == 40
    # yarn_get_mscale(40, 1.0) = 0.1*ln(40)+1
    assert s.attn_mscale() == pytest.approx(0.1 * np.log(40) + 1, abs=1e-6)
    assert s.embed_mscale() == pytest.approx(1.0)

    x = jnp.ones((1, 1, 64), jnp.float32)
    far = jnp.asarray([50000])
    scaled = apply_rope(x, far, 10000.0, s)
    unscaled = apply_rope(x, far, 10000.0, None)
    assert bool(jnp.all(jnp.isfinite(scaled)))
    assert float(jnp.max(jnp.abs(scaled - unscaled))) > 1e-3
    # high-frequency (early) dims extrapolate: identical at short range
    near = jnp.asarray([1])
    s_near = apply_rope(x, near, 10000.0, s)
    u_near = apply_rope(x, near, 10000.0, None)
    assert float(jnp.max(jnp.abs(s_near[..., 0] - u_near[..., 0]))) < 1e-5


def test_group_limited_routing():
    """noaux_tc: only experts in the topk_group best groups are eligible,
    even when a masked group holds the globally best expert-by-prob."""
    cfg = MoeConfig(
        hidden_size=4, num_experts=4, num_experts_per_tok=2,
        gating="sigmoid", n_group=2, topk_group=1,
        routed_scaling_factor=1.0,
    )
    x = jnp.ones((1, 4), jnp.float32)
    w = jnp.zeros((4, 4), jnp.float32)  # probs all 0.5
    # bias makes group 1 (experts 2,3) win the group score
    bias = jnp.asarray([0.0, 0.0, 0.6, 0.6], jnp.float32)
    gates = moe_router({"w_router": w, "router_bias": bias}, x, cfg)
    assert float(gates[0, 0]) == 0.0 and float(gates[0, 1]) == 0.0
    assert float(gates[0, 2]) > 0 and float(gates[0, 3]) > 0


def test_quantized_mla_matches_quantized_oracle():
    """int8 quantization covers the MLA projections and shared experts
    (ops/quant.py QUANT_KEYS incl. per-head w_uk/w_uv with axis-aware
    scales); the paged int8 engine must match the int8 oracle exactly."""
    from dynamo_tpu.ops.quant import is_quantized, quantize_params

    qp = jax.jit(quantize_params)(PARAMS)
    layer1 = qp["layers"][1]
    for k in ("w_dq", "w_uq", "w_dkv", "w_uk", "w_uv", "wo",
              "w_shared_gate", "w_shared_up", "w_shared_down"):
        assert is_quantized(layer1[k]), k
    H, dn, dc = CFG.num_heads, CFG.qk_nope_head_dim, CFG.kv_lora_rank
    assert layer1["w_uk"]["s"].shape == (H, dc)            # contract dn
    assert layer1["w_uv"]["s"].shape == (H, CFG.v_head_dim)  # contract dc

    def q_oracle(prompt, n):
        toks = list(prompt)
        out = []
        for _ in range(n):
            logits = llama.reference_forward(CFG, qp, jnp.asarray(toks))
            nxt = int(jnp.argmax(logits[-1]))
            toks.append(nxt)
            out.append(nxt)
        return out

    ecfg = EngineConfig(
        model=CFG, dtype="float32", block_size=4, num_blocks=64,
        max_num_seqs=2, max_model_len=128, quant="int8",
    )
    r = ModelRunner(ecfg, params=PARAMS)
    prompt = [1, 5, 9, 2, 7]
    tok = r.prefill(prompt, [1, 2, 3, 4], 0, (0.0, 0, 1.0))
    assert tok == q_oracle(prompt, 1)[0]


def test_hf_load_applies_rope_permutation(tmp_path):
    """The HF checkpoint's pair-interleaved rope dims are permuted to
    NeoX halves at load: q_pe column k of head h must land at the
    permuted position."""
    from safetensors.numpy import save_file

    cfg = ModelConfig.tiny_mla_test().scaled(num_layers=1, num_experts=0,
                                             first_k_dense_replace=1)
    H, dn, dr, dc = (
        cfg.num_heads, cfg.qk_nope_head_dim, cfg.qk_rope_head_dim,
        cfg.kv_lora_rank,
    )
    D, dv = cfg.hidden_size, cfg.v_head_dim
    rng = np.random.default_rng(1)

    def t(*shape):
        return (rng.standard_normal(shape) * 0.02).astype(np.float32)

    qb = t(H * (dn + dr), cfg.q_lora_rank)
    dkv = t(dc + dr, D)
    tensors = {
        "model.embed_tokens.weight": t(cfg.vocab_size, D),
        "model.norm.weight": np.ones(D, np.float32),
        "lm_head.weight": t(cfg.vocab_size, D),
        "model.layers.0.self_attn.q_a_proj.weight": t(cfg.q_lora_rank, D),
        "model.layers.0.self_attn.q_a_layernorm.weight": np.ones(
            cfg.q_lora_rank, np.float32
        ),
        "model.layers.0.self_attn.q_b_proj.weight": qb,
        "model.layers.0.self_attn.kv_a_proj_with_mqa.weight": dkv,
        "model.layers.0.self_attn.kv_a_layernorm.weight": np.ones(dc, np.float32),
        "model.layers.0.self_attn.kv_b_proj.weight": t(H * (dn + dv), dc),
        "model.layers.0.self_attn.o_proj.weight": t(D, H * dv),
        "model.layers.0.input_layernorm.weight": np.ones(D, np.float32),
        "model.layers.0.post_attention_layernorm.weight": np.ones(D, np.float32),
        "model.layers.0.mlp.gate_proj.weight": t(cfg.intermediate_size, D),
        "model.layers.0.mlp.up_proj.weight": t(cfg.intermediate_size, D),
        "model.layers.0.mlp.down_proj.weight": t(D, cfg.intermediate_size),
    }
    save_file(tensors, str(tmp_path / "model.safetensors"))
    params = llama.load_hf_weights(cfg, str(tmp_path), dtype=jnp.float32)
    perm = np.concatenate([np.arange(0, dr, 2), np.arange(1, dr, 2)])
    # our w_uq is qb.T [q_lora, H*(dn+dr)]; head 0's pe block permuted
    got = np.asarray(params["layers"][0]["w_uq"]).reshape(
        cfg.q_lora_rank, H, dn + dr
    )[:, 0, dn:]
    want = qb.T.reshape(cfg.q_lora_rank, H, dn + dr)[:, 0, dn:][:, perm]
    np.testing.assert_allclose(got, want, atol=1e-6)
    # k_pe rows of w_dkv permuted the same way
    got_k = np.asarray(params["layers"][0]["w_dkv"])[:, dc:]
    want_k = dkv.T[:, dc:][:, perm]
    np.testing.assert_allclose(got_k, want_k, atol=1e-6)


def test_deepseek_config_from_hf(tmp_path):
    hf = {
        "architectures": ["DeepseekV3ForCausalLM"],
        "model_type": "deepseek_v3",
        "vocab_size": 129280,
        "hidden_size": 7168,
        "intermediate_size": 18432,
        "num_hidden_layers": 61,
        "num_attention_heads": 128,
        "num_key_value_heads": 128,
        "kv_lora_rank": 512,
        "q_lora_rank": 1536,
        "qk_nope_head_dim": 128,
        "qk_rope_head_dim": 64,
        "v_head_dim": 128,
        "n_routed_experts": 256,
        "num_experts_per_tok": 8,
        "n_shared_experts": 1,
        "moe_intermediate_size": 2048,
        "first_k_dense_replace": 3,
        "scoring_func": "sigmoid",
        "norm_topk_prob": True,
        "routed_scaling_factor": 2.5,
        "n_group": 8,
        "topk_group": 4,
        "rms_norm_eps": 1e-6,
        "rope_theta": 10000,
        "max_position_embeddings": 163840,
        # real DeepSeek configs ship yarn scaling — from_hf must accept it
        "rope_scaling": {
            "type": "yarn", "factor": 40, "beta_fast": 32, "beta_slow": 1,
            "mscale": 1.0, "mscale_all_dim": 1.0,
            "original_max_position_embeddings": 4096,
        },
    }
    (tmp_path / "config.json").write_text(json.dumps(hf))
    cfg = ModelConfig.from_hf(str(tmp_path))
    assert cfg.is_mla and cfg.is_moe
    assert cfg.kv_lora_rank == 512 and cfg.q_lora_rank == 1536
    assert cfg.gating == "sigmoid"
    assert cfg.n_group == 8 and cfg.topk_group == 4
    assert cfg.rope_scaling.kind == "yarn"
    assert cfg.num_experts == 256 and cfg.n_shared_experts == 1
    assert cfg.first_k_dense_replace == 3
    assert not cfg.moe_layer(2) and cfg.moe_layer(3)
    # 671B MLA cache entry: 576 dims/token vs 128 heads × 128 dims × 2 —
    # the 57x KV compression that makes R1 servable.
    assert cfg.kv_cache_head_dim == 576


def test_mla_absorbed_matches_standard_formulation():
    """ADVICE r03: independent parity oracle for the absorbed MLA math.

    The engine's MLA path (_qkv_mla) projects queries INTO the latent
    space and runs MQA over [latent ‖ k_pe]; hidden_states() shares that
    code, so an error in the absorption algebra or the
    ((dc+dr)/(dn+dr))^0.5 / mscale^2 score correction would cancel out in
    the engine-vs-oracle tests. Here the NON-absorbed formulation (HF
    DeepseekV2Attention: materialize per-head K/V from w_uk/w_uv, standard
    softmax attention at 1/sqrt(dn+dr)) is implemented from scratch and
    must reproduce reference_forward's logits."""
    from dynamo_tpu.models.llama import (
        _logits,
        _mlp,
        apply_rope,
        embed_lookup,
        rms_norm,
    )
    from dynamo_tpu.ops.quant import qmm

    cfg, params = CFG, PARAMS
    H = cfg.num_heads
    dn, dr, dc = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim, cfg.kv_lora_rank
    token_ids = jnp.asarray([1, 5, 9, 2, 7, 3, 3, 8, 11, 4])
    T = token_ids.shape[0]
    positions = jnp.arange(T)

    def standard_mla_attn(layer, h):
        if cfg.q_lora_rank:
            cq = rms_norm(qmm(h, layer["w_dq"]), layer["ln_q"], cfg.rms_eps)
            q = qmm(cq, layer["w_uq"])
        else:
            q = qmm(h, layer["wq"])
        q = q.reshape(T, H, dn + dr)
        q_nope, q_pe = q[..., :dn], q[..., dn:]
        q_pe = apply_rope(q_pe, positions, cfg.rope_theta, cfg.rope_scaling)
        ckr = qmm(h, layer["w_dkv"])
        c = rms_norm(ckr[:, :dc], layer["ln_kv"], cfg.rms_eps)
        k_pe = apply_rope(
            ckr[:, None, dc:], positions, cfg.rope_theta, cfg.rope_scaling
        )[:, 0]
        # Materialized per-head K/V — kv_b_proj in HF terms.
        k_nope = jnp.einsum("tc,hnc->thn", c, layer["w_uk"])
        v = jnp.einsum("tc,hvc->thv", c, layer["w_uv"])
        k = jnp.concatenate(
            [k_nope, jnp.broadcast_to(k_pe[:, None, :], (T, H, dr))], axis=-1
        )
        qh = jnp.concatenate([q_nope, q_pe], axis=-1)
        scale = (dn + dr) ** -0.5
        if cfg.rope_scaling is not None:
            scale *= cfg.rope_scaling.attn_mscale() ** 2
        scores = jnp.einsum("thd,shd->hts", qh, k) * scale
        causal = jnp.tril(jnp.ones((T, T), bool))
        scores = jnp.where(causal[None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        o = jnp.einsum("hts,shv->thv", probs, v)
        return qmm(o.reshape(T, H * cfg.v_head_dim), layer["wo"])

    x = embed_lookup(params["embed"], token_ids)
    for layer in params["layers"]:
        h = rms_norm(x, layer["ln_attn"], cfg.rms_eps)
        x = x + standard_mla_attn(layer, h)
        h = rms_norm(x, layer["ln_mlp"], cfg.rms_eps)
        x = x + _mlp(layer, h, cfg)
    standard_logits = np.asarray(_logits(params, cfg, x))

    absorbed_logits = np.asarray(
        llama.reference_forward(cfg, params, token_ids)
    )
    np.testing.assert_allclose(
        standard_logits, absorbed_logits, rtol=2e-4, atol=2e-4
    )
    # And greedy argmax agrees everywhere (the serving-visible contract).
    assert list(standard_logits.argmax(-1)) == list(
        absorbed_logits.argmax(-1)
    )

"""KVBM tests: pool lifecycle, tier offload/onboard, and cross-engine
prefix restore through the host tier (reference: lib/llm/tests/
block_manager.rs — two managers in one process exchanging blocks)."""

import asyncio

import numpy as np
import pytest

from dynamo_tpu.block_manager import (
    BlockPool,
    HostStorage,
    KvbmConfig,
    KvBlockManager,
    KvLayoutConfig,
)
from dynamo_tpu.block_manager.offload import OffloadManager
from dynamo_tpu.block_manager.pool import BlockState
from dynamo_tpu.block_manager.storage import DiskStorage
from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.runtime.engine import Context

pytestmark = pytest.mark.anyio

LAYOUT = KvLayoutConfig(
    num_layers=2, page_size=16, num_kv_heads=2, head_dim=16, dtype="float32"
)


def _data(seed: float) -> np.ndarray:
    return np.full((LAYOUT.block_elems,), seed, np.float32)


class TestBlockPool:
    def test_lifecycle(self):
        events = []
        pool = BlockPool(HostStorage(4, LAYOUT), on_event=events.append)
        blocks = pool.allocate_blocks(2)
        assert all(b.state is BlockState.PARTIAL for b in blocks)
        pool.storage.write_block(blocks[0].idx, _data(1.0))
        b0 = pool.register_block(blocks[0], sequence_hash=100, tokens=range(16))
        assert b0.state is BlockState.REGISTERED
        assert events[-1].kind == "stored" and events[-1].block_hashes == [100]

        pool.release(b0)        # registered -> inactive, still discoverable
        assert pool.num_free == 3
        hit = pool.match_sequence_hashes([100])
        assert len(hit) == 1 and hit[0].idx == b0.idx
        assert np.array_equal(pool.storage.read_block(hit[0].idx), _data(1.0))
        pool.release(hit[0])

        pool.release(blocks[1])  # unregistered -> free
        assert pool.num_free == 4

    def test_register_dedup(self):
        pool = BlockPool(HostStorage(4, LAYOUT))
        a, b = pool.allocate_blocks(2)
        a = pool.register_block(a, 7)
        b2 = pool.register_block(b, 7)
        assert b2.idx == a.idx and b2.ref == 2  # duplicate released, canon ref'd

    def test_lru_eviction_emits_removed(self):
        events = []
        pool = BlockPool(HostStorage(2, LAYOUT), on_event=events.append)
        a, b = pool.allocate_blocks(2)
        pool.release(pool.register_block(a, 1))
        pool.release(pool.register_block(b, 2))
        c = pool.allocate_blocks(1)[0]  # evicts LRU (hash 1)
        assert c.idx == a.idx
        removed = [e for e in events if e.kind == "removed"]
        assert removed and removed[-1].block_hashes == [1]
        assert pool.get_by_hash(1) is None and pool.get_by_hash(2) is not None

    def test_allocate_overflow(self):
        pool = BlockPool(HostStorage(2, LAYOUT))
        pool.allocate_blocks(2)
        with pytest.raises(MemoryError):
            pool.allocate_blocks(1)


async def test_offload_onboard_roundtrip(tmp_path):
    host = BlockPool(HostStorage(4, LAYOUT))
    disk = BlockPool(DiskStorage(4, LAYOUT, tmp_path / "kv.bin"))
    mgr = OffloadManager(host, disk)

    blocks = host.allocate_blocks(2)
    host.storage.write_block(blocks[0].idx, _data(3.0))
    host.storage.write_block(blocks[1].idx, _data(4.0))
    b0 = host.register_block(blocks[0], 10, None, range(16))
    b1 = host.register_block(blocks[1], 11, 10, range(16, 32))
    mgr.offload(b0)
    mgr.offload(b1)
    await mgr.drain()
    assert disk.num_registered == 2
    assert np.array_equal(
        disk.storage.read_block(disk.get_by_hash(10).idx).view(np.float32),
        _data(3.0),
    )

    # Evict from host, then onboard back from disk.
    host.release(b0)
    host.release(b1)
    host.allocate_blocks(4)  # forces eviction of both registered blocks
    assert host.num_registered == 0
    # fresh pool to onboard into (host is now full)
    host2 = BlockPool(HostStorage(4, LAYOUT))
    mgr2 = OffloadManager(host2, disk)
    up = await mgr2.onboard([10, 11])
    assert [b.sequence_hash for b in up] == [10, 11]
    assert np.array_equal(
        host2.storage.read_block(up[0].idx).view(np.float32), _data(3.0)
    )


async def _generate(engine, prompt, max_tokens=6):
    req = PreprocessedRequest(
        token_ids=prompt,
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
    )
    toks = []
    async for item in engine.generate(Context(req.to_wire())):
        toks += item["token_ids"]
    return toks


async def test_cross_engine_prefix_restore_via_host_tier():
    """Engine A prefilling a prompt offloads its blocks to the host tier;
    a FRESH engine B (cold HBM, same weights) must onboard them, report a
    prefix hit, and produce the identical greedy continuation."""
    mcfg = ModelConfig.tiny_test()
    ecfg = EngineConfig(
        model=mcfg, num_blocks=32, max_num_seqs=2, max_model_len=128,
        dtype="float32",
    )
    layout = KvLayoutConfig(
        num_layers=mcfg.num_layers,
        page_size=ecfg.block_size,
        num_kv_heads=mcfg.num_kv_heads,
        head_dim=mcfg.head_dim,
        dtype="float32",
    )
    import jax

    params = llama.init_params(jax.random.PRNGKey(0), mcfg, dtype="float32")
    kvbm = await KvBlockManager(
        KvbmConfig(layout=layout, host_blocks=16)
    ).start()

    eng_a = TpuEngine(ecfg, params=params, block_manager=kvbm)
    await eng_a.start()
    prompt = list(range(40))  # 2 full blocks + tail
    cold = await _generate(eng_a, prompt)
    await asyncio.sleep(0.3)  # let the offload pump store the blocks
    assert kvbm.stats()["host_registered"] == 2
    await eng_a.stop()

    eng_b = TpuEngine(ecfg, params=params, block_manager=kvbm)
    await eng_b.start()
    warm = await _generate(eng_b, prompt)
    assert warm == cold
    assert eng_b.prefix_hit_rate > 0.0
    await eng_b.stop()
    await kvbm.stop()


async def test_g4_remote_blockset_export_import():
    """Two workers (own runtimes, shared control plane): worker A exports
    its host-tier blockset; worker B discovers it, fetches the blocks over
    the request plane byte-identically, and lands them in its own host
    tier (reference: block_manager.rs:119-146 blockset export/import)."""
    from dynamo_tpu.block_manager.remote import (
        RemoteBlockClient,
        RemoteBlockServer,
    )
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    main = await DistributedRuntime.in_process()
    drt_a = await DistributedRuntime.in_process(store=main.store, bus=main.bus)
    drt_b = await DistributedRuntime.in_process(store=main.store, bus=main.bus)

    layout = {"head_dim": 16, "dtype": "float32"}
    mgr_a = await KvBlockManager(
        KvbmConfig(layout=LAYOUT, host_blocks=8)
    ).start()
    mgr_b = await KvBlockManager(
        KvbmConfig(layout=LAYOUT, host_blocks=8)
    ).start()

    # A holds a 3-block chain.
    chain = [(100, None), (200, 100), (300, 200)]
    for i, (h, parent) in enumerate(chain):
        mgr_a.offer(h, parent, [i] * 4, _data(float(i + 1)))
    deadline = asyncio.get_running_loop().time() + 5
    while mgr_a.stats()["host_registered"] < 3:
        assert asyncio.get_running_loop().time() < deadline
        await asyncio.sleep(0.02)

    comp_a = drt_a.namespace("kv").component("tpu")
    server = await RemoteBlockServer(
        drt_a, comp_a, mgr_a, layout=layout, refresh_s=0.05
    ).start()
    comp_b = drt_b.namespace("kv").component("tpu")
    client = await RemoteBlockClient(drt_b, comp_b, layout=layout).start()

    hashes = [100, 200, 300, 999]
    deadline = asyncio.get_running_loop().time() + 5
    while client.best_peer(hashes)[1] < 3:
        assert asyncio.get_running_loop().time() < deadline, (
            f"blockset never discovered: {client._blocksets}"
        )
        await asyncio.sleep(0.05)

    # Direct fetch: byte fidelity.
    wid, n = client.best_peer(hashes)
    assert n == 3
    blocks = await client.fetch(wid, hashes[:n])
    assert [b[0] for b in blocks] == [100, 200, 300]
    np.testing.assert_array_equal(blocks[1][3], _data(2.0))

    # Import path: B's host tier gains the prefix; re-import is a no-op.
    assert await client.onboard_into(mgr_b, hashes) == 3
    deadline = asyncio.get_running_loop().time() + 5
    while mgr_b.stats()["host_registered"] < 3:
        assert asyncio.get_running_loop().time() < deadline
        await asyncio.sleep(0.02)
    got = mgr_b.match_host([100, 200, 300])
    assert [g[0] for g in got] == [100, 200, 300]
    np.testing.assert_array_equal(got[2][3], _data(3.0))
    assert await client.onboard_into(mgr_b, [100, 200, 300]) == 0

    # A dead exporter's blockset vanishes with its lease.
    await server.stop()
    await drt_a.shutdown()
    deadline = asyncio.get_running_loop().time() + 10
    while client.best_peer([100])[0] is not None:
        assert asyncio.get_running_loop().time() < deadline, (
            "dead worker's blockset never expired"
        )
        await asyncio.sleep(0.1)

    await client.stop()
    await mgr_a.stop()
    await mgr_b.stop()
    await drt_b.shutdown()
    await main.shutdown()


def test_batched_gather_scatter_matches_per_block():
    """gather_blocks/scatter_blocks (one device program for N blocks) must
    be byte-identical to the per-block ops, with power-of-two padding aimed
    at trash block 0 and every other block untouched."""
    from dynamo_tpu.ops.kv_copy import (
        gather_block,
        gather_blocks,
        scatter_block,
        scatter_blocks,
    )

    rng = np.random.default_rng(0)
    L, blocks, bs, H, D = 2, 8, 4, 2, 8
    caches = [
        (
            np.float32(rng.standard_normal((blocks * bs, H, D))),
            np.float32(rng.standard_normal((blocks * bs, H, D))),
        )
        for _ in range(L)
    ]
    import jax.numpy as jnp

    caches = [(jnp.asarray(k), jnp.asarray(v)) for k, v in caches]

    idxs = [3, 5, 1]  # N=3 pads to bucket 4
    batched = gather_blocks(caches, idxs, bs)
    for i, b in enumerate(idxs):
        np.testing.assert_array_equal(batched[i], gather_block(caches, b, bs))

    data = np.float32(rng.standard_normal((3, L, 2, bs, H, D)))
    after_batch = scatter_blocks(
        [(k.copy(), v.copy()) for k, v in caches], idxs, bs, data
    )
    after_seq = [(k.copy(), v.copy()) for k, v in caches]
    for i, b in enumerate(idxs):
        after_seq = scatter_block(after_seq, b, bs, data[i])
    for li in range(L):
        for j in range(2):
            a = np.asarray(after_batch[li][j])
            s = np.asarray(after_seq[li][j])
            # Trash block 0 absorbs the padding row - exclude it.
            np.testing.assert_array_equal(a[bs:], s[bs:])
    # Un-targeted blocks keep their original bytes.
    keep = [b for b in range(1, blocks) if b not in idxs]
    for b in keep:
        np.testing.assert_array_equal(
            np.asarray(after_batch[0][0])[b * bs : (b + 1) * bs],
            np.asarray(caches[0][0])[b * bs : (b + 1) * bs],
        )


async def test_adaptive_onboard_gate_skips_when_recompute_wins():
    """With a measured-slow onboard link and fast prefill, the engine must
    SKIP host-tier onboarding (treating the hit as a miss) and still
    produce the correct tokens; with the gate off it must onboard."""
    mcfg = ModelConfig.tiny_test()
    ecfg = EngineConfig(
        model=mcfg, num_blocks=32, max_num_seqs=2, max_model_len=128,
        dtype="float32",
    )
    layout = KvLayoutConfig(
        num_layers=mcfg.num_layers,
        page_size=ecfg.block_size,
        num_kv_heads=mcfg.num_kv_heads,
        head_dim=mcfg.head_dim,
        dtype="float32",
    )
    import jax

    params = llama.init_params(jax.random.PRNGKey(0), mcfg, dtype="float32")
    kvbm = await KvBlockManager(
        KvbmConfig(layout=layout, host_blocks=16)
    ).start()

    eng_a = TpuEngine(ecfg, params=params, block_manager=kvbm)
    await eng_a.start()
    prompt = list(range(40))
    cold = await _generate(eng_a, prompt)
    await kvbm.drain_offers()
    await eng_a.stop()

    # Gate sees onboarding at 1 byte/s vs prefill at 1e9 tok/s -> skip.
    eng_b = TpuEngine(ecfg, params=params, block_manager=kvbm)
    await eng_b.start()
    eng_b._onboard_bps = 1.0
    eng_b._prefill_tps = 1e9
    warm = await _generate(eng_b, prompt)
    assert warm == cold
    assert eng_b._onboard_skips == 1
    assert eng_b.prefix_hit_rate == 0.0  # host hit was treated as a miss
    await eng_b.stop()

    # Same rates but gate disabled -> onboards anyway.
    import dataclasses

    eng_c = TpuEngine(
        dataclasses.replace(ecfg, kvbm_adaptive_gate=False),
        params=params, block_manager=kvbm,
    )
    await eng_c.start()
    eng_c._onboard_bps = 1.0
    eng_c._prefill_tps = 1e9
    warm_c = await _generate(eng_c, prompt)
    assert warm_c == cold
    assert eng_c._onboard_skips == 0
    assert eng_c.prefix_hit_rate > 0.0
    await eng_c.stop()
    await kvbm.stop()


async def test_disk_promotion_two_touch(tmp_path):
    """G3→G2: a host-tier miss on a disk-resident prefix promotes it
    asynchronously so the next lookup hits host (two-touch promotion)."""
    layout = KvLayoutConfig(
        num_layers=1, page_size=4, num_kv_heads=1, head_dim=4,
        dtype="float32",
    )
    kvbm = await KvBlockManager(
        KvbmConfig(
            layout=layout, host_blocks=2, disk_blocks=8,
            disk_path=str(tmp_path / "g3"),
        )
    ).start()

    rng = np.random.default_rng(3)
    blocks_a = [np.float32(rng.standard_normal(layout.block_elems)) for _ in range(2)]
    kvbm.offer(101, None, (1,) * 4, blocks_a[0])
    kvbm.offer(102, 101, (2,) * 4, blocks_a[1])
    await kvbm.drain_offers()
    # Host full with A; B's offers evict A from host but A stays on disk.
    kvbm.offer(201, None, (3,) * 4, np.zeros(layout.block_elems, np.float32))
    kvbm.offer(202, 201, (4,) * 4, np.zeros(layout.block_elems, np.float32))
    await kvbm.drain_offers()
    assert kvbm.count_host_match([101, 102]) == 0
    assert kvbm.stats()["disk_registered"] >= 2

    kvbm.request_disk_promotion([101, 102])
    await kvbm.drain_offers()
    assert kvbm.count_host_match([101, 102]) == 2
    got = kvbm.match_host([101, 102])
    for (h, _p, _t, data), want in zip(got, blocks_a):
        np.testing.assert_array_equal(
            np.asarray(data).view(np.float32).reshape(-1), want
        )
    await kvbm.stop()


async def test_engine_host_miss_requests_disk_promotion(monkeypatch):
    """The engine's host-tier lookup must hand the unmatched prefix tail to
    request_disk_promotion (no-op without a disk tier, async with one)."""
    mcfg = ModelConfig.tiny_test()
    ecfg = EngineConfig(
        model=mcfg, num_blocks=32, max_num_seqs=2, max_model_len=128,
        dtype="float32",
    )
    layout = KvLayoutConfig(
        num_layers=mcfg.num_layers,
        page_size=ecfg.block_size,
        num_kv_heads=mcfg.num_kv_heads,
        head_dim=mcfg.head_dim,
        dtype="float32",
    )
    kvbm = await KvBlockManager(
        KvbmConfig(layout=layout, host_blocks=16)
    ).start()
    asked = []
    monkeypatch.setattr(
        kvbm, "request_disk_promotion", lambda hashes: asked.append(list(hashes))
    )
    eng = TpuEngine(ecfg, params=None, block_manager=kvbm)
    await eng.start()
    await _generate(eng, list(range(40)))  # cold: full host miss
    assert asked and len(asked[0]) == 2  # both full prompt blocks missed
    await eng.stop()
    await kvbm.stop()

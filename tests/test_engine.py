"""JAX engine tests: paged-attention correctness against a no-cache oracle,
continuous batching, prefix caching, stop handling.

All on the CPU backend with fp32 so greedy decoding is exactly reproducible.
"""

import asyncio

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.engine.kv_cache import BlockAllocator
from dynamo_tpu.llm.protocols.common import (
    EngineOutput,
    FinishReason,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.runtime.engine import Context

pytestmark = pytest.mark.anyio

CFG = ModelConfig.tiny_test()
PARAMS = llama.init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)


def oracle_greedy(prompt: list[int], n: int) -> list[int]:
    """Full-recompute greedy continuation — the correctness reference."""
    tokens = list(prompt)
    out = []
    for _ in range(n):
        logits = llama.reference_forward(CFG, PARAMS, jnp.asarray(tokens))
        nxt = int(jnp.argmax(logits[-1]))
        tokens.append(nxt)
        out.append(nxt)
    return out


def engine_config(**kw) -> EngineConfig:
    defaults = dict(
        model=CFG,
        dtype="float32",
        block_size=4,
        num_blocks=64,
        max_num_seqs=4,
        max_model_len=128,
    )
    defaults.update(kw)
    return EngineConfig(**defaults)


async def collect(engine, prompt, max_tokens=8, **stop_kw):
    pre = PreprocessedRequest(
        token_ids=prompt,
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True, **stop_kw),
    )
    tokens, finish = [], None
    async for raw in engine.generate(Context(pre.to_wire())):
        out = EngineOutput.from_wire(raw)
        tokens.extend(out.token_ids)
        if out.finish_reason:
            finish = out.finish_reason
    return tokens, finish


async def test_engine_matches_oracle():
    engine = TpuEngine(engine_config(), params=PARAMS)
    await engine.start()
    try:
        prompt = [1, 5, 9, 2, 7]  # crosses a block boundary (bs=4)
        tokens, finish = await collect(engine, prompt, max_tokens=10)
        assert tokens == oracle_greedy(prompt, 10)
        assert finish is FinishReason.LENGTH
    finally:
        await engine.stop()


async def test_concurrent_requests_batch_correctly():
    engine = TpuEngine(engine_config(), params=PARAMS)
    await engine.start()
    try:
        prompts = [[3, 1, 4, 1, 5], [2, 7, 1, 8], [9, 9, 8, 2, 6, 5, 3]]
        results = await asyncio.gather(
            *[collect(engine, p, max_tokens=6) for p in prompts]
        )
        for prompt, (tokens, _) in zip(prompts, results):
            assert tokens == oracle_greedy(prompt, 6), prompt
    finally:
        await engine.stop()


async def test_prefix_cache_reuse_is_exact():
    engine = TpuEngine(engine_config(), params=PARAMS)
    await engine.start()
    try:
        prompt = list(range(1, 18))  # 17 tokens = 4 full blocks + tail
        first, _ = await collect(engine, prompt, max_tokens=5)
        assert engine.prefix_hit_rate == 0.0
        second, _ = await collect(engine, prompt, max_tokens=5)
        assert second == first == oracle_greedy(prompt, 5)
        assert engine.prefix_hit_rate == 0.5  # 1 hit / 2 lookups
    finally:
        await engine.stop()


async def test_stop_token_and_max_tokens():
    engine = TpuEngine(engine_config(), params=PARAMS)
    await engine.start()
    try:
        prompt = [1, 2, 3]
        expected = oracle_greedy(prompt, 8)
        stop_tok = expected[3]
        pre = PreprocessedRequest(
            token_ids=prompt,
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=8, stop_token_ids=[stop_tok]),
        )
        tokens, finish = [], None
        async for raw in engine.generate(Context(pre.to_wire())):
            out = EngineOutput.from_wire(raw)
            tokens.extend(out.token_ids)
            if out.finish_reason:
                finish = out.finish_reason
        assert tokens == expected[: expected.index(stop_tok) + 1]
        assert finish is FinishReason.STOP
    finally:
        await engine.stop()


async def test_oversized_prompt_errors():
    engine = TpuEngine(engine_config(max_model_len=16), params=PARAMS)
    await engine.start()
    try:
        tokens, finish = await collect(engine, list(range(20)), max_tokens=4)
        assert tokens == []
        assert finish is FinishReason.ERROR
    finally:
        await engine.stop()


def test_block_allocator_prefix_lifecycle():
    events = []
    alloc = BlockAllocator(8, 4, on_event=events.append)
    blocks = alloc.allocate_many(3)
    assert alloc.num_free == 4  # 7 usable minus 3
    alloc.register(blocks[0], 111, parent_hash=None, token_ids=[1, 2, 3, 4])
    alloc.register(blocks[1], 222, parent_hash=111)
    assert [e.kind for e in events] == ["stored", "stored"]
    for b in blocks:
        alloc.release(b)
    # Registered blocks stay discoverable; unregistered one went to free list.
    assert alloc.num_free == 7
    matched = alloc.match_prefix([111, 222, 333])
    assert matched == blocks[:2]
    for b in matched:
        alloc.release(b)
    # Pressure evicts LRU reusable blocks and emits removal events.
    _ = alloc.allocate_many(7)
    kinds = [e.kind for e in events]
    assert kinds.count("removed") == 2


async def test_decode_chunk_sizes_agree():
    """Fused multi-step decode must emit exactly the single-step stream
    (greedy), including at the max_model_len boundary."""
    prompt = [3, 1, 4, 1, 5, 9, 2, 6]
    outs = []
    for chunk in (1, 4, 8):
        engine = TpuEngine(
            engine_config(decode_chunk=chunk, max_model_len=24), params=PARAMS
        )
        await engine.start()
        toks, finish = await collect(engine, prompt, max_tokens=64)
        await engine.stop()
        outs.append((toks, finish))
    assert outs[0] == outs[1] == outs[2]
    # 24-token context limit: 8 prompt + 16 generated, finish=length.
    assert len(outs[0][0]) == 16 and outs[0][1] is FinishReason.LENGTH


async def test_chunked_prefill_matches_oracle():
    """A prompt longer than prefill_chunk is fed in chunks; the result must
    be bit-identical to the unchunked computation."""
    prompt = list(range(1, 41))  # 40 tokens, chunk=8 -> 5 chunks
    engine = TpuEngine(
        engine_config(prefill_chunk=8, num_blocks=64), params=PARAMS
    )
    await engine.start()
    try:
        toks, finish = await collect(engine, prompt, max_tokens=6)
        assert toks == oracle_greedy(prompt, 6)
        assert finish is FinishReason.LENGTH
    finally:
        await engine.stop()


async def test_long_prefill_interleaves_with_short_requests():
    """A long prompt must NOT freeze token streaming for others: a short
    request already decoding finishes its whole generation before the
    long prompt's first token arrives — decode lanes fill every unified
    dispatch first and the prefill quantum bounds how much of the budget
    the long prompt can take per step."""
    events = []
    first_token = asyncio.Event()

    async def run(engine, name, prompt, max_tokens):
        async for raw in engine.generate(
            Context(
                PreprocessedRequest(
                    token_ids=prompt,
                    sampling=SamplingOptions(temperature=0.0),
                    stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
                ).to_wire()
            )
        ):
            out = EngineOutput.from_wire(raw)
            for _ in out.token_ids:
                events.append(name)
                first_token.set()

    engine = TpuEngine(
        engine_config(
            num_blocks=80, max_model_len=256, prefill_batch=2,
            unified_token_budget=32, unified_prefill_quantum=16,
        ),
        params=PARAMS,
    )
    await engine.start()
    try:
        long_p = list(range(1, 101))  # 100 tokens >> the 32-token budget
        short_p = [2, 7, 1]
        short_task = asyncio.create_task(run(engine, "short", short_p, 8))
        await first_token.wait()  # short is decoding before long arrives
        await asyncio.gather(
            run(engine, "long", long_p, 4),
            short_task,
        )
        first_long = events.index("long")
        short_done = len(events) - 1 - events[::-1].index("short")
        assert short_done < first_long, events
    finally:
        await engine.stop()


def test_context_limit_seq_excluded_from_decode_batch():
    """Regression: a sequence speculatively at the context limit (cap
    exhausted, chunks still in flight — sched_len = max_model_len + 1)
    must be excluded from decode batches. Growing its block table would
    overflow the [B, max_blocks_per_seq] buffer in _issue_decode and kill
    the engine thread, failing every request. Reachable on real hardware
    whenever one sequence hits the limit while a shorter one keeps
    decoding (chunks retire too fast on CPU to hit it end-to-end)."""
    from dynamo_tpu.engine.scheduler import Scheduler
    from dynamo_tpu.engine.sequence import Sequence

    cfg = engine_config(max_model_len=12, num_blocks=16)  # bs=4 → 3 blk/seq
    sched = Scheduler(cfg, BlockAllocator(cfg.num_blocks, cfg.block_size))

    noop = lambda tok, reason: None  # noqa: E731
    capped = Sequence(
        "capped", list(range(7)), SamplingOptions(), StopConditions(), noop
    )
    short = Sequence(
        "short", [1, 2, 3], SamplingOptions(), StopConditions(), noop
    )
    assert sched.admit(capped) and sched.admit(short)
    # Simulate in-flight fused chunks having advanced past the cap.
    capped.inflight_chunks = 2
    capped.sched_len = cfg.max_model_len + 1

    batch = sched.decode_batch(lookahead=4)
    assert capped not in batch and short in batch
    assert len(capped.block_ids) <= cfg.max_blocks_per_seq


async def test_moe_model_engine_matches_oracle():
    """Mixtral-style MoE model family through the full engine: routed
    expert MLPs in every layer, greedy continuation identical to the
    no-cache oracle forward."""
    moe_cfg = ModelConfig.tiny_moe_test()
    moe_params = llama.init_params(jax.random.PRNGKey(3), moe_cfg, dtype=jnp.float32)
    engine = TpuEngine(engine_config(model=moe_cfg), params=moe_params)
    await engine.start()
    try:
        prompt = [4, 11, 7, 2, 19, 5]

        def oracle(n):
            tokens = list(prompt)
            out = []
            for _ in range(n):
                logits = llama.reference_forward(
                    moe_cfg, moe_params, jnp.asarray(tokens)
                )
                nxt = int(jnp.argmax(logits[-1]))
                tokens.append(nxt)
                out.append(nxt)
            return out

        tokens, finish = await collect(engine, prompt, max_tokens=8)
        assert tokens == oracle(8)
        assert finish is FinishReason.LENGTH
    finally:
        await engine.stop()


def test_block_lifecycle_typestate_violations_are_loud():
    """Illegal lifecycle transitions raise BlockStateError instead of
    silently corrupting the pool (SURVEY §5 race discipline — the Python
    answer to the reference's typestate blocks)."""
    from dynamo_tpu.engine.kv_cache import BlockState, BlockStateError

    alloc = BlockAllocator(8, 4)
    b = alloc.allocate()
    assert alloc.state(b) is BlockState.ACTIVE

    alloc.register(b, sequence_hash=111)
    assert alloc.state(b) is BlockState.REGISTERED

    alloc.release(b)
    assert alloc.state(b) is BlockState.REUSABLE
    with pytest.raises(BlockStateError, match="release"):
        alloc.release(b)  # double free
    with pytest.raises(BlockStateError, match="retain"):
        alloc.retain(b)  # retain without ownership (must go via match)

    [b2] = alloc.match_prefix([111])
    assert b2 == b and alloc.state(b) is BlockState.REGISTERED
    alloc.release(b)

    free_block = alloc.allocate()
    alloc.release(free_block)
    assert alloc.state(free_block) is BlockState.FREE
    with pytest.raises(BlockStateError, match="register"):
        alloc.register(free_block, sequence_hash=222)  # not allocated
    with pytest.raises(BlockStateError, match="retain"):
        alloc.retain(0)  # the trash block is never a legal target


def test_rope_scaling_llama3_formula(tmp_path):
    """Llama-3.1 frequency-dependent rope scaling: high-frequency bands
    untouched, low-frequency divided by `factor`, smooth ramp between —
    validated against an independent numpy rendering of the published
    formula, plus HF config parsing."""
    import json
    import math

    from dynamo_tpu.ops.rope import RopeScaling, _scaled_freqs, apply_rope

    s = RopeScaling(
        factor=8.0, low_freq_factor=1.0, high_freq_factor=4.0,
        original_max_position=8192,
    )
    half = 64
    freqs = np.exp(-np.log(500000.0) * (np.arange(half) / half)).astype(
        np.float32
    )
    got = np.asarray(_scaled_freqs(jnp.asarray(freqs), s))

    # Independent reference implementation.
    want = freqs.copy()
    for i, f in enumerate(freqs):
        wl = 2 * math.pi / f
        if wl < 8192 / 4.0:
            pass  # high-frequency: unchanged
        elif wl > 8192 / 1.0:
            want[i] = f / 8.0
        else:
            sm = (8192 / wl - 1.0) / (4.0 - 1.0)
            want[i] = (1 - sm) * f / 8.0 + sm * f
    np.testing.assert_allclose(got, want, rtol=1e-6)
    assert got[0] == freqs[0]          # fastest component untouched
    assert got[-1] == freqs[-1] / 8.0  # slowest fully stretched

    # scaling=None keeps the original rotation bit-for-bit.
    x = jnp.asarray(np.random.default_rng(0).standard_normal((5, 2, 128)),
                    jnp.float32)
    pos = jnp.arange(5)
    np.testing.assert_array_equal(
        np.asarray(apply_rope(x, pos, 500000.0)),
        np.asarray(apply_rope(x, pos, 500000.0, None)),
    )
    # Scaled rotation differs at large positions (the long-context regime).
    far = jnp.arange(20000, 20005)
    a = np.asarray(apply_rope(x, far, 500000.0))
    b = np.asarray(apply_rope(x, far, 500000.0, s))
    assert np.abs(a - b).max() > 1e-3

    # HF config parsing end-to-end.
    cfg_json = {
        "vocab_size": 128, "hidden_size": 64, "intermediate_size": 128,
        "num_hidden_layers": 2, "num_attention_heads": 4,
        "num_key_value_heads": 2, "rope_theta": 500000.0,
        "max_position_embeddings": 131072,
        "rope_scaling": {
            "factor": 32.0, "low_freq_factor": 1.0,
            "high_freq_factor": 4.0,
            "original_max_position_embeddings": 8192,
            "rope_type": "llama3",
        },
    }
    (tmp_path / "config.json").write_text(json.dumps(cfg_json))
    parsed = ModelConfig.from_hf(str(tmp_path))
    assert parsed.rope_scaling == RopeScaling(
        factor=32.0, low_freq_factor=1.0, high_freq_factor=4.0,
        original_max_position=8192,
    )
    assert ModelConfig.llama31_8b().rope_scaling.factor == 8.0


# -- sampling extras: seed / penalties / logprobs (VERDICT r03 #4) ----------

async def collect_full(engine, prompt, max_tokens=8, sampling=None,
                       logprobs=None):
    """collect() variant returning (tokens, logprob_entries, finish)."""
    pre = PreprocessedRequest(
        token_ids=prompt,
        sampling=sampling or SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        logprobs=logprobs,
    )
    tokens, entries, finish = [], [], None
    async for raw in engine.generate(Context(pre.to_wire())):
        out = EngineOutput.from_wire(raw)
        tokens.extend(out.token_ids)
        if out.logprobs:
            entries.extend(out.logprobs)
        if out.finish_reason:
            finish = out.finish_reason
    return tokens, entries, finish


async def test_seeded_sampling_is_deterministic_across_batching():
    """A seeded request reproduces its tokens regardless of co-scheduled
    traffic or which engine step picked it up (the OpenAI `seed`
    contract)."""
    engine = TpuEngine(engine_config(), params=PARAMS)
    await engine.start()
    try:
        prompt = [3, 1, 4, 1, 5]
        seeded = SamplingOptions(temperature=1.0, seed=42)
        # Run 1: alone.
        t1, _, _ = await collect_full(engine, prompt, 12, sampling=seeded)
        # Run 2: batched with unseeded noise traffic.
        results = await asyncio.gather(
            collect_full(engine, prompt, 12, sampling=seeded),
            collect(engine, [2, 7, 1, 8], max_tokens=12),
            collect(engine, [9, 9, 8], max_tokens=12),
        )
        t2 = results[0][0]
        assert t1 == t2, f"seeded run diverged: {t1} vs {t2}"
        # A different seed gives a different stream (overwhelmingly).
        t3, _, _ = await collect_full(
            engine, prompt, 12,
            sampling=SamplingOptions(temperature=1.0, seed=7),
        )
        assert t3 != t1
    finally:
        await engine.stop()


async def test_frequency_penalty_discourages_repeats():
    engine = TpuEngine(engine_config(), params=PARAMS)
    await engine.start()
    try:
        prompt = [1, 5, 9, 2, 7]
        plain, _, _ = await collect_full(engine, prompt, 16)
        pen, _, _ = await collect_full(
            engine, prompt, 16,
            sampling=SamplingOptions(
                temperature=0.0, frequency_penalty=8.0,
            ),
        )
        assert plain == oracle_greedy(prompt, 16)  # full path == plain greedy
        assert pen != plain
        assert len(set(pen)) > len(set(plain)), (
            f"penalty should widen the token set: {pen} vs {plain}"
        )
    finally:
        await engine.stop()


async def test_logprobs_payload_shape_and_values():
    engine = TpuEngine(engine_config(), params=PARAMS)
    await engine.start()
    try:
        prompt = [1, 5, 9, 2, 7]
        tokens, entries, _ = await collect_full(
            engine, prompt, 6, logprobs=3
        )
        assert tokens == oracle_greedy(prompt, 6)
        assert len(entries) == len(tokens)
        for tok, e in zip(tokens, entries):
            assert e["id"] == tok
            assert e["logprob"] <= 0.0
            assert len(e["top"]) == 3
            lps = [lp for _, lp in e["top"]]
            assert lps == sorted(lps, reverse=True)
            # Greedy: the chosen token IS the top-1 alternative.
            assert e["top"][0][0] == tok
            assert abs(e["top"][0][1] - e["logprob"]) < 1e-5
    finally:
        await engine.stop()


async def test_sampling_extras_rejections():
    # Penalties/logprobs are incompatible with speculative decoding.
    engine = TpuEngine(engine_config(speculative_k=2), params=PARAMS)
    await engine.start()
    try:
        with pytest.raises(ValueError, match="speculative"):
            await collect_full(
                engine, [1, 2, 3], 4,
                sampling=SamplingOptions(presence_penalty=1.0),
            )
        with pytest.raises(ValueError, match="exceeds"):
            await collect_full(engine, [1, 2, 3], 4, logprobs=99)
    finally:
        await engine.stop()


async def test_qwen3_qk_norm_engine_matches_oracle():
    """Qwen3-style per-head q/k RMSNorm (qk_norm): the paged engine must
    match the no-cache oracle, and the norm must actually change the
    function (same weights minus the norm gains gives different logits)."""
    import dataclasses

    q3cfg = dataclasses.replace(
        CFG, name="tiny-qwen3", qk_norm=True, qkv_bias=False
    )
    params = llama.init_params(jax.random.PRNGKey(4), q3cfg, dtype=jnp.float32)
    assert "ln_q_head" in params["layers"][0]

    prompt = [1, 5, 9, 2, 7]

    def oracle(n):
        toks, out = list(prompt), []
        for _ in range(n):
            logits = llama.reference_forward(q3cfg, params, jnp.asarray(toks))
            nxt = int(jnp.argmax(logits[-1]))
            toks.append(nxt)
            out.append(nxt)
        return out

    engine = TpuEngine(engine_config(model=q3cfg), params=params)
    await engine.start()
    try:
        tokens, _ = await collect(engine, prompt, max_tokens=8)
        assert tokens == oracle(8)
    finally:
        await engine.stop()

    # The norm is live: zeroing its gains changes the logits.
    import numpy as np

    zeroed = jax.tree.map(lambda x: x, params)
    zeroed["layers"][0] = dict(zeroed["layers"][0])
    zeroed["layers"][0]["ln_q_head"] = jnp.zeros_like(
        params["layers"][0]["ln_q_head"]
    )
    a = np.asarray(llama.reference_forward(q3cfg, params, jnp.asarray(prompt)))
    b = np.asarray(llama.reference_forward(q3cfg, zeroed, jnp.asarray(prompt)))
    assert np.abs(a - b).max() > 1e-3


async def test_sliding_window_engine_matches_oracle():
    """Mistral-style sliding-window attention: the paged engine (window
    masking in every attention path) must match the no-cache oracle with
    the same window, and the window must be live (different tokens than
    the full-attention model once the context exceeds it)."""
    import dataclasses

    import numpy as np

    wcfg = dataclasses.replace(CFG, name="tiny-swa", sliding_window=8)
    params = llama.init_params(jax.random.PRNGKey(6), wcfg, dtype=jnp.float32)
    prompt = [int(t) for t in
              np.random.default_rng(3).integers(1, CFG.vocab_size, 24)]

    def oracle(cfg, n):
        toks, out = list(prompt), []
        for _ in range(n):
            logits = llama.reference_forward(cfg, params, jnp.asarray(toks))
            nxt = int(jnp.argmax(logits[-1]))
            toks.append(nxt)
            out.append(nxt)
        return out

    engine = TpuEngine(engine_config(model=wcfg), params=params)
    await engine.start()
    try:
        tokens, _ = await collect(engine, prompt, max_tokens=10)
        assert tokens == oracle(wcfg, 10)
    finally:
        await engine.stop()

    # Window is live: the full-attention model diverges (ctx 24 >> 8).
    full = oracle(dataclasses.replace(wcfg, sliding_window=0), 10)
    assert tokens != full


async def test_rolling_buffer_eviction_plateaus_and_is_exact():
    """Rolling-buffer KV eviction (VERDICT r04 weak #4): a fully-windowed
    model's long generation must (a) hold only O(window/bs) live blocks —
    behind-window pages are released as decoding advances — and (b)
    produce tokens identical to the same engine with eviction disabled."""
    import dataclasses

    wcfg = dataclasses.replace(CFG, name="tiny-swa", sliding_window=8)
    params = llama.init_params(jax.random.PRNGKey(6), wcfg, dtype=jnp.float32)
    prompt = [int(t) for t in
              np.random.default_rng(4).integers(1, CFG.vocab_size, 20)]
    OUT = 60  # final length 80 >> window 8
    ecfg = engine_config(model=wcfg, max_model_len=128, decode_chunk=4)

    async def run(evict: bool):
        engine = TpuEngine(ecfg, params=params)
        await engine.start()
        if not evict:
            engine.scheduler.evict_behind_window = lambda *a, **k: 0
        peaks = []
        pre = PreprocessedRequest(
            token_ids=prompt,
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=OUT, ignore_eos=True),
        )
        toks = []
        async for raw in engine.generate(Context(pre.to_wire())):
            toks.extend(EngineOutput.from_wire(raw).token_ids)
            peaks.append(engine.scheduler.metrics()["kv_active_blocks"])
        await engine.stop()
        return toks, peaks

    toks_off, peaks_off = await run(evict=False)
    toks_on, peaks_on = await run(evict=True)
    assert toks_on == toks_off, "eviction changed generated tokens"
    # Without eviction the live block count grows with the context; with
    # it, the tail of the run must sit at O(window/bs): window 8 / bs 4 =
    # 2 in-window pages + the partially-filled growth page + pipeline
    # slack (chunks in flight keep sched_len ahead by 2*decode_chunk).
    bs = ecfg.block_size
    bound = (
        (wcfg.sliding_window + bs - 1) // bs + 1
        + (2 * ecfg.decode_chunk) // bs + 1
    )
    assert max(peaks_off) >= (len(prompt) + OUT - 8) // bs  # grew ~O(ctx)
    assert max(peaks_on[len(peaks_on) // 2 :]) <= bound, (
        peaks_on, bound,
    )

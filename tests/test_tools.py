"""Tool calling (llm/tools.py): matcher semantics (reference:
lib/llm/src/preprocessor/tools.rs ToolCallingMatcher), template-side tool
rendering, and the end-to-end chat path — an echoed tool-call JSON comes
back as OpenAI `tool_calls` with finish_reason "tool_calls"."""

import json

import httpx
import pytest

from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher, register_llm
from dynamo_tpu.llm.engines import EchoEngineCore
from dynamo_tpu.llm.http_service import HttpService
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.protocols.sse import DONE, decode_stream
from dynamo_tpu.llm.tokenizer import _JinjaChatTemplate
from dynamo_tpu.llm.tools import ToolCallMatcher
from dynamo_tpu.runtime.distributed import DistributedRuntime

pytestmark = pytest.mark.anyio


def test_matcher_single_call_parameters_and_arguments():
    m = ToolCallMatcher("auto")
    for key in ("parameters", "arguments"):
        calls = m.match(json.dumps({"name": "get_weather", key: {"city": "SF"}}))
        assert len(calls) == 1
        call = calls[0]
        assert call["id"].startswith("call-")
        assert call["type"] == "function"
        assert call["index"] == 0  # required by strict streaming clients
        assert call["function"]["name"] == "get_weather"
        assert json.loads(call["function"]["arguments"]) == {"city": "SF"}


def test_matcher_list_form_and_fenced():
    m = ToolCallMatcher("auto")
    payload = [
        {"name": "a", "parameters": {"x": 1}},
        {"name": "b", "arguments": {"y": 2}},
    ]
    calls = m.match(json.dumps(payload))
    assert [c["function"]["name"] for c in calls] == ["a", "b"]
    assert [c["index"] for c in calls] == [0, 1]
    fenced = "```json\n" + json.dumps(payload[0]) + "\n```"
    assert m.match(fenced)[0]["function"]["name"] == "a"


def test_matcher_rejects_plain_text_and_none_choice():
    m = ToolCallMatcher("auto")
    assert m.match("The weather is sunny.") == []
    assert m.match(json.dumps({"name": "x"})) == []  # no args
    assert m.match(json.dumps({"name": 3, "parameters": {}})) == []
    # a list with any invalid entry matches nothing (all-or-nothing)
    assert (
        m.match(json.dumps([{"name": "a", "parameters": {}}, {"nope": 1}]))
        == []
    )
    disabled = ToolCallMatcher("none")
    assert disabled.match(json.dumps({"name": "a", "parameters": {}})) == []


def test_chat_template_renders_tools():
    tmpl = _JinjaChatTemplate(
        "{% if tools %}[TOOLS]{% for t in tools %}"
        "{{ t.function.name }};{% endfor %}[/TOOLS]{% endif %}"
        "{% for m in messages %}{{ m.content }}{% endfor %}"
    )
    out = tmpl.render(
        [{"role": "user", "content": "hi"}],
        True,
        tools=[{"type": "function", "function": {"name": "get_time"}}],
    )
    assert out == "[TOOLS]get_time;[/TOOLS]hi"


async def _setup():
    drt = await DistributedRuntime.in_process()
    ep = drt.namespace("dyn").component("tpu").endpoint("generate")
    await ep.serve(EchoEngineCore())
    card = ModelDeploymentCard(name="echo-model", model_path="toy")
    await register_llm(drt, ep, card)
    manager = ModelManager()
    watcher = ModelWatcher(drt, manager)
    await watcher.start()
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    return drt, service


TOOLS = [
    {
        "type": "function",
        "function": {
            "name": "get_weather",
            "parameters": {
                "type": "object",
                "properties": {"city": {"type": "string"}},
            },
        },
    }
]


async def test_http_tool_call_roundtrip():
    """Echo engine + raw prompt: the model 'generates' exactly the
    tool-call JSON it was sent, and the pipeline surfaces OpenAI
    tool_calls in both streamed and aggregated responses."""
    drt, service = await _setup()
    base = f"http://127.0.0.1:{service.port}"
    call_json = json.dumps({"name": "get_weather", "parameters": {"city": "SF"}})
    body = {
        "model": "echo-model",
        "messages": [{"role": "user", "content": call_json}],
        "tools": TOOLS,
        "ext": {"use_raw_prompt": True, "ignore_eos": True},
        "max_tokens": 96,
        "stream": False,
    }
    try:
        async with httpx.AsyncClient() as client:
            r = await client.post(f"{base}/v1/chat/completions", json=body)
            msg = r.json()["choices"][0]["message"]
            assert r.json()["choices"][0]["finish_reason"] == "tool_calls"
            assert msg["content"] is None  # OpenAI tool-call turn shape
            assert msg["tool_calls"][0]["function"]["name"] == "get_weather"
            assert json.loads(msg["tool_calls"][0]["function"]["arguments"]) == {
                "city": "SF"
            }

            body["stream"] = True
            r = await client.post(f"{base}/v1/chat/completions", json=body)
            events = list(decode_stream(r.text))
            assert events[-1].data == DONE
            calls, finish = [], None
            for ev in events[:-1]:
                chunk = json.loads(ev.data)
                for choice in chunk.get("choices", []):
                    calls.extend(choice.get("delta", {}).get("tool_calls") or [])
                    finish = choice.get("finish_reason") or finish
            assert finish == "tool_calls"
            assert calls and calls[0]["function"]["name"] == "get_weather"

            # tool_choice="none" disables matching: content passes through.
            body["stream"] = False
            body["tool_choice"] = "none"
            r = await client.post(f"{base}/v1/chat/completions", json=body)
            msg = r.json()["choices"][0]["message"]
            assert not msg.get("tool_calls")
            assert "get_weather" in (msg["content"] or "")
    finally:
        await service.stop()
        await drt.shutdown()


def test_matcher_forced_and_required_choice():
    """ADVICE r03: forced {'type':'function'} choices filter to the named
    function; 'required' (and forced) report required=True so the
    preprocessor can surface an error instead of plain content."""
    call = json.dumps({"name": "get_weather", "parameters": {"city": "SF"}})
    other = json.dumps({"name": "other_fn", "parameters": {"x": 1}})

    forced = ToolCallMatcher(
        {"type": "function", "function": {"name": "get_weather"}}
    )
    assert forced.required and forced.enabled
    assert forced.match(call)[0]["function"]["name"] == "get_weather"
    assert forced.match(other) == []  # wrong function filtered out

    req = ToolCallMatcher("required")
    assert req.required
    assert req.match(call)  # parses fine
    assert req.match("just some prose") == []

    auto = ToolCallMatcher("auto")
    assert not auto.required


async def test_http_tool_choice_required_and_streaming_content():
    """tool_choice='required' with non-tool output surfaces an error (400
    aggregated; SSE error event streamed), and ordinary prose with tools
    present streams incrementally instead of buffering to the end."""
    drt, service = await _setup()
    base = f"http://127.0.0.1:{service.port}"
    prose = "the weather is nice today, no tools needed"
    body = {
        "model": "echo-model",
        "messages": [{"role": "user", "content": prose}],
        "tools": TOOLS,
        "tool_choice": "required",
        "ext": {"use_raw_prompt": True, "ignore_eos": True},
        "max_tokens": 64,
        "stream": False,
    }
    try:
        async with httpx.AsyncClient() as client:
            r = await client.post(f"{base}/v1/chat/completions", json=body)
            assert r.status_code == 400
            assert "tool_choice" in r.text

            # Streamed: error arrives as a terminal SSE payload.
            body["stream"] = True
            r = await client.post(f"{base}/v1/chat/completions", json=body)
            assert r.status_code == 200
            events = list(decode_stream(r.text))
            assert events[-1].data == DONE
            err = json.loads(events[-2].data)
            assert "tool_choice" in err["error"]["message"]

            # auto + prose: content streams as multiple incremental deltas
            # (ADVICE r03: buffering-only was a regression for agents).
            body["tool_choice"] = "auto"
            r = await client.post(f"{base}/v1/chat/completions", json=body)
            deltas = []
            for ev in decode_stream(r.text):
                if ev.data == DONE:
                    continue
                for choice in json.loads(ev.data).get("choices", []):
                    c = choice.get("delta", {}).get("content")
                    if c:
                        deltas.append(c)
            assert "".join(deltas).strip().endswith("no tools needed")
            assert len(deltas) > 1, f"content should stream: {deltas}"
    finally:
        await service.stop()
        await drt.shutdown()

"""Two-pool fleet planner tests (docs/architecture/planner.md):
independent per-phase scaling, hysteresis, drain-vs-requeue semantics,
state migration across the pool split, and the observability plane."""

import asyncio
import collections
import json
import os

import pytest

from dynamo_tpu.llm.kv_router.publisher import WorkerMetricsPublisher
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.planner import (
    PLANNER_OBS,
    DecodeLaw,
    FleetPlanner,
    FleetPlannerConfig,
    FleetSample,
    PoolConfig,
    PrefillLaw,
    WorkerPool,
)
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.egress import PushRouter, RouterMode
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.utils.faults import FAULTS

pytestmark = pytest.mark.anyio


@pytest.fixture(autouse=True)
def _clean_planner_obs():
    PLANNER_OBS.reset()
    yield
    PLANNER_OBS.reset()
    FAULTS.clear()


class CountingConnector:
    """Minimal deployment backend: workers are opaque tickets."""

    def __init__(self) -> None:
        self.spawned = 0
        self.drained = 0

    async def spawn(self):
        self.spawned += 1
        return object()

    async def drain(self, handle) -> None:
        self.drained += 1


def _req(n_tokens: int = 3):
    return PreprocessedRequest(
        token_ids=list(range(1, n_tokens + 1)),
        sampling=SamplingOptions(),
        stop=StopConditions(max_tokens=n_tokens, ignore_eos=True),
    ).to_wire()


# ---------------------------------------------------------------------------
# pool laws + hysteresis (pure control-law units)
# ---------------------------------------------------------------------------


def test_prefill_law_is_queue_driven_and_per_worker():
    law = PrefillLaw(queue_up_per_worker=1.0, queue_down_per_worker=0.1)
    # 8 queued items: pressure for 2 workers, not for 16.
    assert law.decide(FleetSample(queue_depth=8), 2) == "up"
    assert law.decide(FleetSample(queue_depth=8), 16) == "hold"
    # Age bound is absolute: one ancient item = stalled pool at any size.
    assert law.decide(FleetSample(queue_depth=0.5, queue_age_s=30), 16) == "up"
    assert law.decide(FleetSample(queue_depth=0.0), 4) == "down"
    # KV pressure is NOT a prefill signal.
    assert law.decide(FleetSample(kv_usage=0.99), 1) == "down"


def test_decode_law_is_kv_and_itl_driven():
    law = DecodeLaw(kv_up_threshold=0.8, itl_up_ms=20.0, itl_down_ms=10.0)
    assert law.decide(FleetSample(kv_usage=0.9), 1) == "up"
    assert law.decide(FleetSample(itl_ema_ms=25.0), 1) == "up"
    # Queue depth is NOT a decode signal.
    assert law.decide(FleetSample(queue_depth=50), 1) == "down"
    # Any hot axis holds the pool down from shrinking.
    assert law.decide(FleetSample(kv_usage=0.5), 1) == "hold"
    assert law.decide(FleetSample(itl_ema_ms=15.0), 1) == "hold"
    assert law.decide(FleetSample(kv_usage=0.1, itl_ema_ms=5.0), 1) == "down"


def test_laws_hold_when_telemetry_blind():
    """A dead metrics plane / failing queue probe yields all-zero
    averages — the laws must read zero COVERAGE as 'hold', never as
    'idle, shed capacity' (review regression)."""
    from dynamo_tpu.planner.fleet import _Window

    assert DecodeLaw().decide(
        FleetSample(decode_workers_seen=0), 4
    ) == "hold"
    assert PrefillLaw().decide(FleetSample(queue_samples=0), 4) == "hold"
    # The planner's digest of a window where EVERY sample attempt
    # failed reports zero coverage on both axes.
    s = _Window().digest()
    assert s.queue_samples == 0 and s.decode_workers_seen == 0
    assert DecodeLaw().decide(s, 4) == "hold"
    assert PrefillLaw().decide(s, 4) == "hold"
    # Sighted-and-idle still shrinks (the normal path is unchanged).
    from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics

    w = _Window()
    w.add(0, 0.0, {1: ForwardPassMetrics()})
    s = w.digest()
    assert s.decode_workers_seen == 1 and s.queue_samples == 1
    assert DecodeLaw().decide(s, 4) == "down"
    assert PrefillLaw().decide(s, 4) == "down"
    # The two coverage axes are INDEPENDENT: a failing queue probe
    # must not blind the decode pool's metrics read (review regression
    # — they used to share one try block).
    w = _Window()
    w.add_metrics({1: ForwardPassMetrics(gpu_cache_usage_perc=0.95)})
    s = w.digest()
    assert s.queue_samples == 0 and s.decode_workers_seen == 1
    assert DecodeLaw().decide(s, 1) == "up"      # decode still sees load
    assert PrefillLaw().decide(s, 4) == "hold"   # prefill holds, blind


async def test_pool_hysteresis_down_consecutive_and_up_cooldown():
    conn = CountingConnector()
    pool = WorkerPool(
        PoolConfig(name="decode", min_workers=1, max_workers=4,
                   up_cooldown_s=30.0, down_consecutive=2),
        conn,
        DecodeLaw(),
    )
    await pool.ensure_min()
    hot = FleetSample(kv_usage=0.95)
    idle = FleetSample()
    assert await pool.adjust(hot) == "up"
    # Cooldown vetoes a second up in the same window.
    assert await pool.adjust(hot) == "hold"
    assert pool.size == 2
    # One idle window is not enough to shrink; two consecutive are.
    assert await pool.adjust(idle) == "hold"
    assert await pool.adjust(idle) == "down"
    await pool.wait_drained()
    assert pool.size == 1 and conn.drained == 1
    # A hot window RESETS the idle streak.
    pool.cfg.up_cooldown_s = 0.0
    assert await pool.adjust(idle) == "hold"
    assert await pool.adjust(hot) == "up"
    assert await pool.adjust(idle) == "hold"
    assert await pool.adjust(idle) == "down"
    await pool.wait_drained()


# ---------------------------------------------------------------------------
# independent two-pool scaling (the tentpole contract)
# ---------------------------------------------------------------------------


async def test_pools_scale_independently(tmp_path):
    """Queue spike grows ONLY the prefill pool; KV pressure grows ONLY
    the decode pool; each drains back independently."""
    drt = await DistributedRuntime.in_process()
    pf_conn, dec_conn = CountingConnector(), CountingConnector()
    planner = FleetPlanner(
        drt,
        FleetPlannerConfig(
            metric_interval_s=0.02,
            adjustment_interval_s=0.12,
            decision_log_path=str(tmp_path / "decisions.jsonl"),
        ),
        WorkerPool(
            PoolConfig(name="prefill", min_workers=1, max_workers=3,
                       down_consecutive=1),
            pf_conn,
            PrefillLaw(),
        ),
        WorkerPool(
            PoolConfig(name="decode", min_workers=1, max_workers=3,
                       down_consecutive=1),
            dec_conn,
            DecodeLaw(),
        ),
    )
    await planner.start()
    assert planner.prefill.size == 1 and planner.decode.size == 1

    # Phase 1: queued prefill work. Decode pool must not move.
    queue = drt.bus.work_queue("dynamo.prefill_queue")
    for i in range(8):
        await queue.enqueue(b"job%d" % i)
    deadline = asyncio.get_running_loop().time() + 5
    while planner.prefill.size < 2:
        assert asyncio.get_running_loop().time() < deadline, (
            f"prefill never scaled up ({planner.prefill.decisions})"
        )
        await asyncio.sleep(0.03)
    assert planner.decode.size == 1, "queue spike leaked into decode pool"

    # Drain the queue -> prefill shrinks back; decode still untouched.
    while await queue.dequeue(timeout_s=0.05):
        pass
    deadline = asyncio.get_running_loop().time() + 5
    while planner.prefill.size > 1:
        assert asyncio.get_running_loop().time() < deadline
        await asyncio.sleep(0.03)
    assert planner.decode.size == 1

    # Phase 2: KV pressure on the decode metrics plane. Prefill must
    # not move.
    comp = drt.namespace("dynamo").component("tpu")
    pub = WorkerMetricsPublisher()
    pub.publish({"gpu_cache_usage_perc": 0.95, "num_requests_waiting": 0})
    await pub.create_endpoint(comp)
    deadline = asyncio.get_running_loop().time() + 5
    while planner.decode.size < 2:
        assert asyncio.get_running_loop().time() < deadline, (
            f"decode never scaled up ({planner.decode.decisions})"
        )
        await asyncio.sleep(0.03)
    assert planner.prefill.size == 1, "KV pressure leaked into prefill pool"

    pub.publish({"gpu_cache_usage_perc": 0.05, "num_requests_waiting": 0})
    deadline = asyncio.get_running_loop().time() + 5
    while planner.decode.size > 1:
        assert asyncio.get_running_loop().time() < deadline
        await asyncio.sleep(0.03)

    await planner.stop(drain_workers=True)
    assert planner.prefill.size == 0 and planner.decode.size == 0
    # Every spawn was matched by a graceful drain, never a kill.
    assert pf_conn.drained == pf_conn.spawned
    assert dec_conn.drained == dec_conn.spawned
    await drt.shutdown()


async def test_scale_up_hook_fires_on_up_and_is_contained():
    """The G4 pre-placement seam (docs/architecture/kvbm_g4.md): the
    planner awaits ``on_scale_up(pool_name, new_size)`` exactly on "up"
    decisions — never on hold — and a raising hook is contained (logged;
    the decision still lands and the control loop survives)."""
    drt = await DistributedRuntime.in_process()
    calls = []

    async def hook(pool_name, new_size):
        calls.append((pool_name, new_size))
        if len(calls) == 2:
            raise RuntimeError("preplace push blew up")

    planner = FleetPlanner(
        drt,
        FleetPlannerConfig(),
        WorkerPool(
            PoolConfig(name="prefill", min_workers=1, max_workers=3),
            CountingConnector(),
            PrefillLaw(),
        ),
        WorkerPool(
            PoolConfig(name="decode", min_workers=1, max_workers=3,
                       up_cooldown_s=0.0),
            CountingConnector(),
            DecodeLaw(),
        ),
        on_scale_up=hook,
    )
    for pool in planner.pools:
        await pool.ensure_min()

    hot = FleetSample(kv_usage=0.95)
    await planner._adjust(hot)
    # Only the pool that actually grew reports, with its NEW size.
    assert calls == [("decode", 2)]
    # "hold" windows never fire the hook.
    await planner._adjust(FleetSample(kv_usage=0.5))
    assert calls == [("decode", 2)]
    # The second up makes the hook raise: contained, pool still grew.
    await planner._adjust(hot)
    assert calls == [("decode", 2), ("decode", 3)]
    assert planner.decode.size == 3 and planner.prefill.size == 1
    await drt.shutdown()


# ---------------------------------------------------------------------------
# decode shrink: drain, never kill (in-flight stream finishes)
# ---------------------------------------------------------------------------


class SlowStreamEngine:
    """Streams one token per 10 ms — long enough that a scale-down
    lands mid-stream."""

    def __init__(self) -> None:
        self.active = 0
        self.streams_completed = 0

    async def generate(self, request: Context):
        from dynamo_tpu.llm.protocols.common import EngineOutput, FinishReason

        pre = PreprocessedRequest.from_wire(request.payload)
        self.active += 1
        try:
            n = pre.stop.max_tokens or 8
            for i in range(n):
                await asyncio.sleep(0.01)
                yield EngineOutput(token_ids=[i + 1], cum_tokens=i + 1).to_wire()
            yield EngineOutput(
                token_ids=[], finish_reason=FinishReason.STOP, cum_tokens=n
            ).to_wire()
            self.streams_completed += 1
        finally:
            self.active -= 1


class StreamingConnector:
    """Worker = in-process DRT serving SlowStreamEngine. ``drain``
    deregisters FIRST (routers evict) then waits for in-flight streams
    to finish before shutdown — the PR 4 graceful-drain contract."""

    def __init__(self, main_drt) -> None:
        self.main = main_drt
        self.workers: list[tuple] = []   # (drt, engine)
        self.drained = 0
        self.killed_mid_stream = 0

    async def spawn(self):
        drt = await DistributedRuntime.in_process(
            store=self.main.store, bus=self.main.bus
        )
        comp = drt.namespace("dynamo").component("tpu")
        engine = SlowStreamEngine()
        await comp.endpoint("generate").serve(engine)
        handle = (drt, engine)
        self.workers.append(handle)
        return handle

    async def drain(self, handle) -> None:
        drt, engine = handle
        deadline = asyncio.get_running_loop().time() + 10
        while engine.active > 0:
            assert asyncio.get_running_loop().time() < deadline, (
                "drain timed out waiting for in-flight streams"
            )
            await asyncio.sleep(0.01)
        if engine.active > 0:
            self.killed_mid_stream += 1
        await drt.shutdown()
        self.drained += 1


async def test_decode_scale_down_finishes_in_flight_stream():
    """Acceptance: a decode scale-down with an in-flight stream finishes
    the stream with zero dropped tokens."""
    drt = await DistributedRuntime.in_process()
    conn = StreamingConnector(drt)
    pool = WorkerPool(
        PoolConfig(name="decode", min_workers=1, max_workers=2,
                   down_consecutive=1),
        conn,
        DecodeLaw(),
    )
    await pool.ensure_min()
    assert await pool.adjust(FleetSample(kv_usage=0.95)) == "up"
    assert pool.size == 2

    # Long stream pinned to the worker the next scale-down will pop
    # (pools retire LIFO — handles[-1]).
    victim_drt, victim_engine = pool.handles[-1]
    push = await PushRouter.create(
        drt, "dynamo.tpu.generate", mode=RouterMode.ROUND_ROBIN
    )
    n_tokens = 40
    got: list[int] = []
    first_token = asyncio.Event()

    async def consume():
        async for item in push.direct(
            Context(_req(n_tokens)), victim_drt.primary_lease_id
        ):
            toks = item.get("token_ids") or []
            got.extend(toks)
            if toks:
                first_token.set()

    consumer = asyncio.ensure_future(consume())
    await asyncio.wait_for(first_token.wait(), 5)

    # Scale down mid-stream: the VICTIM worker is retired.
    assert await pool.adjust(FleetSample()) == "down"
    assert pool.size == 1
    await asyncio.wait_for(consumer, 10)
    # Zero dropped tokens: the full stream arrived despite retirement.
    assert got == list(range(1, n_tokens + 1))
    assert victim_engine.streams_completed == 1
    await pool.wait_drained()
    assert conn.drained == 1 and conn.killed_mid_stream == 0
    await pool.drain_all()
    await drt.shutdown()


# ---------------------------------------------------------------------------
# prefill shrink: requeue, never drop (exactly-once consumption)
# ---------------------------------------------------------------------------


class QueueConsumerConnector:
    """Worker = a task draining the shared prefill queue with leased
    dequeues (the real PrefillWorker's shape). ``drain`` = graceful
    stop: finish + ack the current item, take no more."""

    def __init__(self, drt, processed: collections.Counter) -> None:
        from dynamo_tpu.disagg.queue import PrefillQueue

        self.queue = PrefillQueue(drt, "dynamo")
        self.processed = processed
        self.workers: list[dict] = []
        self.drained = 0

    async def spawn(self):
        stop = asyncio.Event()

        async def run():
            while not stop.is_set():
                got = await self.queue.dequeue(timeout_s=0.05)
                if got is None:
                    continue
                item_id, req = got
                await asyncio.sleep(0.02)  # simulated prefill work
                await self.queue.ack(item_id)
                self.processed[req["request_id"]] += 1

        handle = {"stop": stop, "task": asyncio.ensure_future(run())}
        self.workers.append(handle)
        return handle

    async def drain(self, handle) -> None:
        handle["stop"].set()
        await handle["task"]   # finishes (and acks) the in-flight item
        self.drained += 1


async def test_prefill_scale_down_requeues_exactly_once():
    """Acceptance: prefill shrink mid-backlog — every queued entry is
    consumed EXACTLY once (no dup, no drop), with control-plane fault
    delay armed across the scale-down window (chaos seasoning: the
    satellite's control.call seam)."""
    drt = await DistributedRuntime.in_process()
    processed: collections.Counter = collections.Counter()
    conn = QueueConsumerConnector(drt, processed)
    pool = WorkerPool(
        PoolConfig(name="prefill", min_workers=1, max_workers=2,
                   down_consecutive=1),
        conn,
        PrefillLaw(),
    )
    await pool.ensure_min()
    assert await pool.adjust(FleetSample(queue_depth=8)) == "up"
    assert pool.size == 2

    n_items = 14
    for i in range(n_items):
        await conn.queue.enqueue({"request_id": f"req-{i}", "token_ids": [1]})
    # Let both workers grab items, then shrink mid-backlog with the
    # control-plane seam degraded (delays, no losses).
    await asyncio.sleep(0.03)
    FAULTS.arm("control.call", "delay", delay_s=0.005, times=8)
    assert await pool.adjust(FleetSample(queue_depth=0)) == "down"
    await pool.wait_drained()
    assert pool.size == 1 and conn.drained == 1

    deadline = asyncio.get_running_loop().time() + 10
    while sum(processed.values()) < n_items:
        assert asyncio.get_running_loop().time() < deadline, (
            f"backlog not drained: {dict(processed)}"
        )
        await asyncio.sleep(0.02)
    await asyncio.sleep(0.1)   # would surface late duplicates
    # Exactly once: nothing dropped, nothing double-consumed.
    assert sum(processed.values()) == n_items
    assert all(v == 1 for v in processed.values()), dict(processed)
    assert await conn.queue.depth() == 0
    await pool.drain_all()
    await drt.shutdown()


# ---------------------------------------------------------------------------
# state: recycled-PID refusal + v1 migration across the pool split
# ---------------------------------------------------------------------------


def test_adopt_refuses_recycled_pid():
    """Regression (satellite): a checkpointed pid that now belongs to a
    DIFFERENT process (start-ticks mismatch) must not be adopted — the
    planner would otherwise SIGTERM a stranger on scale-down."""
    from dynamo_tpu.planner.planner import (
        SubprocessConnector,
        _proc_start_ticks,
    )

    conn = SubprocessConnector("true")
    me = os.getpid()
    real_start = _proc_start_ticks(me)
    assert real_start is not None
    # Same pid, recycled identity: refuse.
    assert conn.adopt(me, started=real_start + 12345) is None
    # Matching identity: adopt.
    handle = conn.adopt(me, started=real_start)
    assert handle is not None and handle.pid == me
    # Dead pid: refuse regardless.
    assert conn.adopt(2**22 + 1234, started=None) is None


class PidConnector:
    """Fake pid-handing connector (test_planner.py's, pool-aware)."""

    def __init__(self, base: int) -> None:
        self.next_pid = base
        self.adopted: list[int] = []
        self.spawned = 0

    async def spawn(self):
        self.spawned += 1
        self.next_pid += 1
        return type("H", (), {"pid": self.next_pid})()

    async def drain(self, handle):
        pass

    def adopt(self, pid, started=None):
        self.adopted.append(pid)
        return type("H", (), {"pid": pid})()


async def test_v1_single_pool_state_loads_into_decode_pool(tmp_path):
    """Restore across the pool split: an old single-pool state file
    adopts its workers into the DECODE pool (they served `generate`)
    and never crashes the restore."""
    state = tmp_path / "dynamo.json"
    state.write_text(json.dumps({
        "namespace": "dynamo",
        "workers": [{"pid": 101, "started": None}, {"pid": 102,
                                                    "started": None}],
        "connector": {"count": 2},
        "decisions": ["up"],
        "ts": 0.0,
    }))
    drt = await DistributedRuntime.in_process()
    pf, dec = PidConnector(200), PidConnector(300)
    planner = FleetPlanner(
        drt,
        FleetPlannerConfig(
            metric_interval_s=10, adjustment_interval_s=10,
            state_path=str(state),
        ),
        WorkerPool(PoolConfig(name="prefill", min_workers=1), pf,
                   PrefillLaw()),
        WorkerPool(PoolConfig(name="decode", min_workers=1), dec,
                   DecodeLaw()),
    )
    await planner.start()
    # v1 workers landed in decode; prefill spawned fresh.
    assert dec.adopted == [101, 102]
    assert planner.decode.size == 2
    assert pf.adopted == [] and planner.prefill.size == 1
    await planner.stop()
    # Saved state is now v2 with per-pool slices.
    saved = json.loads(state.read_text())
    assert saved["version"] == 2
    assert [w["pid"] for w in saved["pools"]["decode"]["workers"]] == [
        101, 102
    ]
    assert len(saved["pools"]["prefill"]["workers"]) == 1

    # Second life restores per-pool from the v2 file.
    pf2, dec2 = PidConnector(400), PidConnector(500)
    p2 = FleetPlanner(
        drt,
        FleetPlannerConfig(
            metric_interval_s=10, adjustment_interval_s=10,
            state_path=str(state),
        ),
        WorkerPool(PoolConfig(name="prefill", min_workers=1), pf2,
                   PrefillLaw()),
        WorkerPool(PoolConfig(name="decode", min_workers=1), dec2,
                   DecodeLaw()),
    )
    await p2.start()
    assert dec2.adopted == [101, 102] and len(pf2.adopted) == 1
    await p2.stop()
    await drt.shutdown()


async def test_legacy_planner_refuses_v2_fleet_state(tmp_path):
    """Review regression: the single-pool planner must refuse a v2
    fleet checkpoint loudly — silently ignoring it would orphan every
    worker the fleet planner had checkpointed and clobber the file."""
    from dynamo_tpu.planner.planner import Planner, PlannerConfig

    state = tmp_path / "dynamo.json"
    state.write_text(json.dumps({
        "version": 2,
        "pools": {"decode": {"workers": [{"pid": 101, "started": 1.0}],
                             "connector": {"count": 1}}},
        "ts": 0.0,
    }))
    drt = await DistributedRuntime.in_process()
    planner = Planner(
        drt,
        PlannerConfig(metric_interval_s=10, adjustment_interval_s=10,
                      state_path=str(state)),
        connector=CountingConnector(),
    )
    with pytest.raises(RuntimeError, match="two-pool"):
        await planner.start()
    # The v2 file is untouched (not clobbered into v1 format).
    assert json.loads(state.read_text())["version"] == 2
    await drt.shutdown()


async def test_malformed_state_starts_fresh(tmp_path):
    state = tmp_path / "bad.json"
    state.write_text("{not json")
    drt = await DistributedRuntime.in_process()
    planner = FleetPlanner(
        drt,
        FleetPlannerConfig(metric_interval_s=10, adjustment_interval_s=10,
                           state_path=str(state)),
        WorkerPool(PoolConfig(name="prefill"), CountingConnector(),
                   PrefillLaw()),
        WorkerPool(PoolConfig(name="decode"), CountingConnector(),
                   DecodeLaw()),
    )
    await planner.start()
    assert planner.prefill.size == 1 and planner.decode.size == 1
    await planner.stop(drain_workers=True)
    await drt.shutdown()


# ---------------------------------------------------------------------------
# observability: gauges on the surfaces + kind="planner" capture records
# ---------------------------------------------------------------------------


async def test_planner_observability_gauges_and_capture(tmp_path,
                                                        monkeypatch):
    """Satellite: decisions reach the metric surfaces and the trace
    capture, not just the decision JSONL."""
    from dynamo_tpu.utils import tracing

    cap = tmp_path / "cap.jsonl"
    monkeypatch.setenv("DYNTPU_TRACE", str(cap))
    tracing.reset_tracer(str(cap))
    try:
        drt = await DistributedRuntime.in_process()
        decision_log = tmp_path / "decisions.jsonl"
        planner = FleetPlanner(
            drt,
            FleetPlannerConfig(
                metric_interval_s=0.02, adjustment_interval_s=0.08,
                decision_log_path=str(decision_log),
            ),
            WorkerPool(
                PoolConfig(name="prefill", min_workers=1, max_workers=2,
                           down_consecutive=1),
                CountingConnector(), PrefillLaw(),
            ),
            WorkerPool(
                PoolConfig(name="decode", min_workers=1, max_workers=2),
                CountingConnector(), DecodeLaw(),
            ),
        )
        await planner.start()
        queue = drt.bus.work_queue("dynamo.prefill_queue")
        for i in range(6):
            await queue.enqueue(b"j%d" % i)
        deadline = asyncio.get_running_loop().time() + 5
        while planner.prefill.size < 2:
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.03)
        await planner.stop(drain_workers=True)
        await drt.shutdown()
    finally:
        tracer = tracing.tracer()

    # 1) PLANNER_OBS gauges (the /metrics surfaces merge these).
    g = PLANNER_OBS.gauges()
    assert g["planner_scale_up_total"] >= 1
    assert g["planner_prefill_scale_up_total"] >= 1
    assert "planner_pool_size_prefill" in g
    assert "planner_pool_size_decode" in g
    assert g["planner_last_decision_age_s"] >= 0

    # 2) kind="planner" records in the DYNTPU_TRACE capture, joinable
    # by the route-audit/trace tooling.
    tracing.reset_tracer(None)
    lines = []
    for p in cap.parent.glob(cap.name + "*"):
        for line in p.read_text().splitlines():
            if not line:
                continue
            raw = json.loads(line)
            lines.append(raw.get("event", raw))  # Recorder envelope
    planner_recs = [r for r in lines if r.get("kind") == "planner"]
    assert planner_recs, "no planner records reached the capture"
    assert {r["pool"] for r in planner_recs} == {"prefill", "decode"}
    ups = [r for r in planner_recs if r["decision"] == "up"]
    assert ups and all("queue" in r for r in ups
                       if r["pool"] == "prefill")

    # 3) The decision JSONL still works and matches the capture shape.
    logged = [json.loads(line)
              for line in decision_log.read_text().splitlines()]
    assert any(r["decision"] == "up" and r["pool"] == "prefill"
               for r in logged)

    # 3b) The route-audit tooling picks planner records out of the same
    # capture (satellite: joinable by the observability tooling) and
    # trace_merge ignores them without phantom orphans.
    from benchmarks.route_audit import load_records
    from benchmarks.trace_merge import load_captures

    _routes, _actuals, planner_loaded = load_records([str(cap)])
    assert any(r["decision"] == "up" for r in planner_loaded)
    assert load_captures([str(cap)]) == {}   # no timeline records leaked

    # 4) Both HTTP surfaces render the gauges.
    import httpx

    from dynamo_tpu.llm.discovery import ModelManager
    from dynamo_tpu.llm.http_service import HealthServer, HttpService

    service = HttpService(ModelManager(), host="127.0.0.1", port=0)
    await service.start()
    health = HealthServer(lambda: {}, host="127.0.0.1", port=0)
    await health.start()
    try:
        async with httpx.AsyncClient() as client:
            for port in (service.port, health.port):
                r = await client.get(f"http://127.0.0.1:{port}/metrics")
                assert "planner_scale_up_total" in r.text
                assert "planner_pool_size_prefill" in r.text
    finally:
        await service.stop()
        await health.stop()


def test_exporter_renders_planner_gauges():
    """The standalone exporter surface (satellite: all three)."""
    from dynamo_tpu.llm.kv_router.metrics_aggregator import (
        ProcessedEndpoints,
    )
    from dynamo_tpu.llm.metrics_exporter import MetricsExporter

    PLANNER_OBS.note_decision("prefill", "up", 2, {"queue": 4.0})
    exp = MetricsExporter.__new__(MetricsExporter)
    exp._labels = 'namespace="dynamo",component="tpu"'
    exp.aggregator = type(
        "A", (), {"endpoints": ProcessedEndpoints()}
    )()
    text = exp.render()
    assert "dyntpu_planner_scale_up_total" in text
    assert "dyntpu_planner_pool_size_prefill" in text


def test_cli_two_pool_and_network_aware_flags_parse():
    from dynamo_tpu.cli import build_parser

    args = build_parser().parse_args([
        "planner", "--control-plane", "x:1", "--worker-cmd", "dec {index}",
        "--two-pool", "--prefill-worker-cmd", "pf {index}",
        "--decode-itl-up-ms", "25", "--prefill-max-workers", "3",
    ])
    assert args.two_pool and args.prefill_worker_cmd == "pf {index}"
    assert args.decode_itl_up_ms == 25.0 and args.prefill_max_workers == 3

    args = build_parser().parse_args([
        "router", "--control-plane", "x:1",
        "--endpoint", "dyn://ns.c.generate", "--route-network-aware",
    ])
    assert args.route_network_aware


async def test_cli_two_pool_rejects_single_pool_sla_flags():
    """--two-pool must refuse --profile/--*-sla-ms loudly — silently
    ignoring a configured SLA is the exact failure the single-pool
    guard exists to reject (review regression)."""
    from dynamo_tpu.cli import _planner, build_parser

    args = build_parser().parse_args([
        "planner", "--control-plane", "x:1", "--worker-cmd", "w",
        "--two-pool", "--prefill-worker-cmd", "p",
        "--ttft-sla-ms", "100",
    ])
    with pytest.raises(SystemExit, match="two-pool"):
        await _planner(args)


def test_legacy_planner_decisions_reach_observatory():
    """planner/planner.py's single pool reports under pool="worker"."""
    from dynamo_tpu.planner.planner import Planner, PlannerConfig
    from dynamo_tpu.planner.planner import _Window as LegacyWindow

    p = Planner.__new__(Planner)
    p.cfg = PlannerConfig(decision_log_path=None)
    p.decisions = ["up"]
    p._handles = [object()]
    p._log_decision(LegacyWindow())
    g = PLANNER_OBS.gauges()
    assert g["planner_scale_up_total"] == 1
    assert g["planner_pool_size_worker"] == 1

"""End-to-end HTTP slice: worker registers model → watcher builds pipeline →
OpenAI requests stream over SSE (model: reference lib/llm/tests/http-service.rs
+ call stack SURVEY.md §3.2)."""

import json

import httpx
import pytest

from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher, register_llm
from dynamo_tpu.llm.engines import EchoEngineCore
from dynamo_tpu.llm.http_service import HttpService
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.protocols.sse import DONE, decode_stream
from dynamo_tpu.runtime.distributed import DistributedRuntime

pytestmark = pytest.mark.anyio


async def _setup():
    drt = await DistributedRuntime.in_process()
    # Worker side: serve the engine endpoint and register the model.
    ep = drt.namespace("dyn").component("tpu").endpoint("generate")
    await ep.serve(EchoEngineCore())
    card = ModelDeploymentCard(name="echo-model", model_path="toy")
    await register_llm(drt, ep, card)

    # Frontend side: watcher + HTTP service.
    manager = ModelManager()
    watcher = ModelWatcher(drt, manager)
    await watcher.start()
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    return drt, service


async def test_http_chat_stream_and_aggregate():
    drt, service = await _setup()
    base = f"http://127.0.0.1:{service.port}"
    try:
        async with httpx.AsyncClient() as client:
            r = await client.get(f"{base}/v1/models")
            assert [m["id"] for m in r.json()["data"]] == ["echo-model"]

            body = {
                "model": "echo-model",
                "messages": [{"role": "user", "content": "hello tpu"}],
                "stream": True,
            }
            r = await client.post(f"{base}/v1/chat/completions", json=body)
            assert r.status_code == 200
            events = list(decode_stream(r.text))
            assert events[-1].data == DONE
            text = ""
            for ev in events[:-1]:
                chunk = json.loads(ev.data)
                for choice in chunk.get("choices", []):
                    text += choice.get("delta", {}).get("content") or ""
            assert "hello tpu" in text

            body["stream"] = False
            r = await client.post(f"{base}/v1/chat/completions", json=body)
            data = r.json()
            assert "hello tpu" in data["choices"][0]["message"]["content"]
            assert data["usage"]["completion_tokens"] > 0

            r = await client.post(
                f"{base}/v1/chat/completions",
                json={"model": "nope", "messages": [], "stream": False},
            )
            assert r.status_code == 404

            r = await client.get(f"{base}/metrics")
            assert "dyntpu_http_service_requests_total" in r.text
            assert 'status="success"' in r.text
    finally:
        await service.stop()
        await drt.shutdown()


async def test_http_completions_endpoint():
    drt, service = await _setup()
    base = f"http://127.0.0.1:{service.port}"
    try:
        async with httpx.AsyncClient() as client:
            r = await client.post(
                f"{base}/v1/completions",
                json={"model": "echo-model", "prompt": "abc", "stream": False},
            )
            assert r.status_code == 200
            assert r.json()["choices"][0]["text"] == "abc"
    finally:
        await service.stop()
        await drt.shutdown()

"""End-to-end HTTP slice: worker registers model → watcher builds pipeline →
OpenAI requests stream over SSE (model: reference lib/llm/tests/http-service.rs
+ call stack SURVEY.md §3.2)."""

import json

import httpx
import pytest

from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher, register_llm
from dynamo_tpu.llm.engines import EchoEngineCore
from dynamo_tpu.llm.http_service import HttpService
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.protocols.sse import DONE, decode_stream
from dynamo_tpu.runtime.distributed import DistributedRuntime

pytestmark = pytest.mark.anyio


async def _setup():
    drt = await DistributedRuntime.in_process()
    # Worker side: serve the engine endpoint and register the model.
    ep = drt.namespace("dyn").component("tpu").endpoint("generate")
    await ep.serve(EchoEngineCore())
    card = ModelDeploymentCard(name="echo-model", model_path="toy")
    await register_llm(drt, ep, card)

    # Frontend side: watcher + HTTP service.
    manager = ModelManager()
    watcher = ModelWatcher(drt, manager)
    await watcher.start()
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    return drt, service


async def test_http_chat_stream_and_aggregate():
    drt, service = await _setup()
    base = f"http://127.0.0.1:{service.port}"
    try:
        async with httpx.AsyncClient() as client:
            r = await client.get(f"{base}/v1/models")
            assert [m["id"] for m in r.json()["data"]] == ["echo-model"]

            body = {
                "model": "echo-model",
                "messages": [{"role": "user", "content": "hello tpu"}],
                "stream": True,
            }
            r = await client.post(f"{base}/v1/chat/completions", json=body)
            assert r.status_code == 200
            events = list(decode_stream(r.text))
            assert events[-1].data == DONE
            text = ""
            for ev in events[:-1]:
                chunk = json.loads(ev.data)
                for choice in chunk.get("choices", []):
                    text += choice.get("delta", {}).get("content") or ""
            assert "hello tpu" in text

            body["stream"] = False
            r = await client.post(f"{base}/v1/chat/completions", json=body)
            data = r.json()
            assert "hello tpu" in data["choices"][0]["message"]["content"]
            assert data["usage"]["completion_tokens"] > 0

            r = await client.post(
                f"{base}/v1/chat/completions",
                json={"model": "nope", "messages": [], "stream": False},
            )
            assert r.status_code == 404

            r = await client.get(f"{base}/metrics")
            assert "dyntpu_http_service_requests_total" in r.text
            assert 'status="success"' in r.text
            # Per-request latency tracing rides the same scrape.
            assert "dyntpu_trace_total_ms_count" in r.text
    finally:
        await service.stop()
        await drt.shutdown()


async def test_http_annotated_sse_events():
    """Requested annotations ride the SSE stream as typed named events
    ahead of the deltas, and the non-stream aggregator skips them
    (reference: lib/runtime/src/protocols/annotated.rs envelope +
    nvext annotations)."""
    drt, service = await _setup()
    base = f"http://127.0.0.1:{service.port}"
    try:
        async with httpx.AsyncClient() as client:
            body = {
                "model": "echo-model",
                "messages": [{"role": "user", "content": "hi there"}],
                "stream": True,
                "nvext": {"annotations": ["formatted_prompt", "token_ids"]},
            }
            r = await client.post(f"{base}/v1/chat/completions", json=body)
            assert r.status_code == 200
            events = list(decode_stream(r.text))
            named = {ev.event: ev for ev in events if ev.event}
            assert "formatted_prompt" in named
            assert "hi there" in json.loads(named["formatted_prompt"].data)
            toks = json.loads(named["token_ids"].data)
            assert isinstance(toks, list) and toks
            # Annotations precede the first delta chunk.
            first_named = next(i for i, ev in enumerate(events) if ev.event)
            first_delta = next(
                i for i, ev in enumerate(events)
                if ev.event is None and ev.data and ev.data != DONE
            )
            assert first_named < first_delta

            # Aggregated (non-stream) response is unaffected by annotations.
            body["stream"] = False
            r = await client.post(f"{base}/v1/chat/completions", json=body)
            assert r.status_code == 200
            assert "hi there" in r.json()["choices"][0]["message"]["content"]
    finally:
        await service.stop()
        await drt.shutdown()


async def test_http_completions_endpoint():
    drt, service = await _setup()
    base = f"http://127.0.0.1:{service.port}"
    try:
        async with httpx.AsyncClient() as client:
            r = await client.post(
                f"{base}/v1/completions",
                json={"model": "echo-model", "prompt": "abc", "stream": False},
            )
            assert r.status_code == 200
            assert r.json()["choices"][0]["text"] == "abc"
    finally:
        await service.stop()
        await drt.shutdown()


async def test_http_embeddings_end_to_end():
    """/v1/embeddings over the full stack: register an embeddings model,
    watcher builds the tokenize-only pipeline, vectors come back unit-norm
    and deterministic (VERDICT r02 missing #5, closed)."""
    import math

    from dynamo_tpu.llm.embedding import EmbeddingEngine
    from dynamo_tpu.models.config import ModelConfig

    drt = await DistributedRuntime.in_process()
    ep = drt.namespace("dyn").component("embed").endpoint("generate")
    mcfg = ModelConfig.tiny_test()
    await ep.serve(EmbeddingEngine(mcfg, dtype="float32"))
    await register_llm(
        drt,
        ep,
        ModelDeploymentCard(name="tiny-embed", model_path="toy"),
        model_type="embeddings",
    )
    manager = ModelManager()
    await ModelWatcher(drt, manager).start()
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    base = f"http://127.0.0.1:{service.port}"
    try:
        async with httpx.AsyncClient() as client:
            r = await client.post(
                f"{base}/v1/embeddings",
                json={
                    "model": "tiny-embed",
                    "input": ["hello world", "second input"],
                },
                timeout=60,
            )
            assert r.status_code == 200, r.text
            data = r.json()
            assert data["model"] == "tiny-embed"
            assert [d["index"] for d in data["data"]] == [0, 1]
            for d in data["data"]:
                vec = d["embedding"]
                assert len(vec) == mcfg.hidden_size
                assert abs(math.sqrt(sum(x * x for x in vec)) - 1.0) < 1e-3
            assert data["data"][0]["embedding"] != data["data"][1]["embedding"]
            assert data["usage"]["prompt_tokens"] > 0

            # Same input -> same vector (deterministic pooled forward).
            r2 = await client.post(
                f"{base}/v1/embeddings",
                json={"model": "tiny-embed", "input": "hello world"},
                timeout=60,
            )
            assert (
                r2.json()["data"][0]["embedding"]
                == data["data"][0]["embedding"]
            )

            # A chat model rejects nothing here, but an unknown model 404s.
            r3 = await client.post(
                f"{base}/v1/embeddings",
                json={"model": "nope", "input": "x"},
            )
            assert r3.status_code == 404
    finally:
        await service.stop()
        await drt.shutdown()


class LogprobEcho:
    """Echo engine that attaches logprob entries, mimicking TpuEngine's
    payload shape — exercises the rendering path (preprocessor chat/
    completions shapes, HTTP aggregation) without jax."""

    async def generate(self, request):
        from dynamo_tpu.llm.protocols.common import (
            EngineOutput,
            FinishReason,
            PreprocessedRequest,
        )

        pre = PreprocessedRequest.from_wire(request.payload)
        want = pre.logprobs
        for i, tid in enumerate(pre.token_ids):
            out = EngineOutput(token_ids=[tid], cum_tokens=i + 1)
            if want is not None:
                out.logprobs = [{
                    "id": tid,
                    "logprob": -0.5,
                    "top": [[tid, -0.5], [tid + 1, -1.5]][:want],
                }]
            yield out.to_wire()
        yield EngineOutput(finish_reason=FinishReason.STOP).to_wire()


async def _setup_logprob():
    drt = await DistributedRuntime.in_process()
    ep = drt.namespace("dyn").component("lp").endpoint("generate")
    await ep.serve(LogprobEcho())
    await register_llm(
        drt, ep, ModelDeploymentCard(name="lp-model", model_path="toy")
    )
    manager = ModelManager()
    await ModelWatcher(drt, manager).start()
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    return drt, service


async def test_http_logprobs_chat_and_completions():
    """OpenAI logprob payloads end to end: chat logprobs.content entries
    (token/logprob/bytes/top_logprobs) in both streamed chunks and the
    aggregated response; legacy parallel lists on /v1/completions
    (VERDICT r03 weak #3: parsed-but-ignored parameters)."""
    drt, service = await _setup_logprob()
    base = f"http://127.0.0.1:{service.port}"
    try:
        async with httpx.AsyncClient() as client:
            body = {
                "model": "lp-model",
                "messages": [{"role": "user", "content": "hi"}],
                "stream": False,
                "logprobs": True,
                "top_logprobs": 2,
            }
            r = await client.post(f"{base}/v1/chat/completions", json=body)
            assert r.status_code == 200
            choice = r.json()["choices"][0]
            content = choice["logprobs"]["content"]
            assert len(content) == r.json()["usage"]["completion_tokens"]
            e = content[0]
            assert set(e) == {"token", "logprob", "bytes", "top_logprobs"}
            assert e["logprob"] == -0.5
            assert len(e["top_logprobs"]) == 2
            assert bytes(e["bytes"]).decode() == e["token"]

            body["stream"] = True
            r = await client.post(f"{base}/v1/chat/completions", json=body)
            chunks = [
                json.loads(ev.data)
                for ev in decode_stream(r.text)
                if ev.data != DONE
            ]
            streamed = [
                c["choices"][0]["logprobs"]["content"][0]
                for c in chunks
                if c.get("choices") and c["choices"][0].get("logprobs")
            ]
            assert streamed and streamed[0]["logprob"] == -0.5

            r = await client.post(
                f"{base}/v1/completions",
                json={
                    "model": "lp-model", "prompt": "abc",
                    "stream": False, "logprobs": 2,
                },
            )
            lp = r.json()["choices"][0]["logprobs"]
            assert lp["tokens"] and len(lp["tokens"]) == len(
                lp["token_logprobs"]
            ) == len(lp["top_logprobs"]) == len(lp["text_offset"])
            assert lp["token_logprobs"][0] == -0.5
            assert lp["text_offset"][0] == 0
    finally:
        await service.stop()
        await drt.shutdown()


async def test_http_unsupported_params_rejected():
    """Unsupported OpenAI knobs 400 instead of being silently dropped."""
    drt, service = await _setup()
    base = f"http://127.0.0.1:{service.port}"
    msg = [{"role": "user", "content": "x"}]
    try:
        async with httpx.AsyncClient() as client:
            for bad in (
                {"n": 2},
                {"best_of": 4},
                {"logit_bias": {"42": 5.0}},
                {"logprobs": True, "top_logprobs": 99},
            ):
                r = await client.post(
                    f"{base}/v1/chat/completions",
                    json={"model": "echo-model", "messages": msg,
                          "stream": False, **bad},
                )
                assert r.status_code == 400, (bad, r.status_code, r.text)
                assert "not supported" in r.text or "exceeds" in r.text
    finally:
        await service.stop()
        await drt.shutdown()

"""Flight-recorder observability plane tests
(docs/architecture/observability.md).

Covers the three tentpole pieces — span-based cross-process tracing
(wire TraceContext, JSONL capture, trace_merge), the engine step flight
recorder (/debug/steps, fault dump), and the on-demand profiling
surface — plus the satellites: TTL sweep of leaked traces, bucketed
histograms with per-token ITL, and log↔trace correlation.

The centerpiece is the mocker-driven disagg e2e: a request enters over
HTTP, goes frontend → prefill queue → prefill engine → KV transfer →
decode engine, and the merged timeline must be gapless with the
``kv_transfer`` span between ``prefill`` and ``decode_first`` — and a
worker-side error must cross the TCP error plane without orphaning the
trace."""

import asyncio
import json
import logging

import pytest

from dynamo_tpu.utils.recorder import Recorder
from dynamo_tpu.utils.tracing import (
    TraceContext,
    Tracer,
    reset_tracer,
    tracer,
)

pytestmark = pytest.mark.anyio


# ---------------------------------------------------------------------------
# tracer units
# ---------------------------------------------------------------------------


def test_bucket_ladder_matches_llm_metrics():
    """BUCKETS_MS is llm/metrics._BUCKETS inlined (utils must not import
    llm); both Prometheus surfaces must quantize latency identically."""
    from dynamo_tpu.llm.metrics import _BUCKETS
    from dynamo_tpu.utils.tracing import BUCKETS_MS

    assert BUCKETS_MS == tuple(1000.0 * b for b in _BUCKETS)


def test_trace_context_rides_the_preprocessed_request_wire():
    from dynamo_tpu.llm.protocols.common import PreprocessedRequest

    tr = Tracer()
    pre = PreprocessedRequest(token_ids=[1, 2, 3])
    pre.trace = tr.context("req-1", parent_span="tokenize")
    wire = pre.to_wire()
    assert wire["trace"]["trace_id"] == tr.trace_id("req-1")
    assert wire["trace"]["parent_span"] == "tokenize"
    assert wire["trace"]["sent_unix"] > 1e9  # the clock-offset hint

    back = PreprocessedRequest.from_wire(wire)
    assert back.trace.trace_id == tr.trace_id("req-1")
    # Absent context stays absent (legacy peers).
    wire.pop("trace")
    assert PreprocessedRequest.from_wire(wire).trace is None


def test_adopt_binds_remote_trace_id_and_offset_hint():
    tr = Tracer()
    ctx = TraceContext("remote-trace-id", "queue_wait")
    tr.adopt("req-9", ctx)
    assert tr.trace_id("req-9") == "remote-trace-id"
    tr.mark("req-9", "engine_queued")
    rec = tr.finish("req-9")
    assert rec.trace_id == "remote-trace-id"
    assert rec.offset_hint_ms is not None  # recv - sent, ~0 in-process
    # None context is a no-op (local path).
    tr.adopt("req-10", None)
    assert tr.trace_id("req-10") != "remote-trace-id"


def test_tracer_ttl_sweep_reaps_leaked_traces(tmp_path):
    """The _active leak fix: auto-opened traces for requests that never
    finish() are reaped by the TTL sweep and counted."""
    path = tmp_path / "cap.jsonl"
    tr = Tracer(record_path=str(path), ttl_s=0.0)
    tr.mark("leaked-1", "received")
    tr.mark("leaked-2", "engine_queued")
    assert tr.active_count == 2
    assert tr.sweep(0.0) == 2
    assert tr.active_count == 0
    assert tr.abandoned_total == 2
    # Late marks after the sweep re-open (then get reaped again) — the
    # counter keeps growing, the dict does not.
    tr.mark("leaked-1", "first_token")
    assert tr.sweep(0.0) == 1
    assert tr.abandoned_total == 3
    # TTL abandons carry a terminal record so trace_merge can tell a
    # reaped trace from an orphaned capture.
    kinds = [ev["kind"] for _, ev in Recorder.load(path)]
    assert kinds.count("abandon") == 3
    # render() reports the counter on the Prometheus surface.
    assert "dyntpu_trace_abandoned_traces_total 3" in tr.render()


def test_touch_keeps_live_streams_out_of_the_sweep():
    """A long-running stream (decode > ttl_s) must NOT be reaped
    mid-flight: the per-token paths (engine observe_itl, egress frame
    loop) touch the trace, refreshing its TTL; touch never re-opens."""
    tr = Tracer(ttl_s=0.05)
    tr.mark("live", "first_token")
    tr._active["live"].last_touch -= 10.0  # simulate a long-idle record
    tr.observe_itl(3.0, "live")  # a token arrives → TTL refreshed
    assert tr.sweep() == 0
    assert tr.active_count == 1
    assert tr.abandoned_total == 0
    # Without the touch the same trace is stale and gets reaped.
    tr._active["live"].last_touch -= 10.0
    assert tr.sweep() == 1
    # touch() on a reaped/unknown id is a no-op — it never opens.
    tr.touch("live")
    tr.touch("never-seen")
    assert tr.active_count == 0


def test_abandon_with_reason_closes_without_stats(tmp_path):
    """The prefill worker's requeue path closes its local capture via
    abandon(reason="requeued"): the trace must NOT count toward
    abandoned_traces_total (routine engine-full churn is not a leak),
    must leave a terminal record (no orphan if a peer worker completes
    the request), and must leave nothing for the TTL sweep."""
    path = tmp_path / "cap.jsonl"
    tr = Tracer(record_path=str(path))
    tr.mark("r1", "received")
    tr.abandon("r1", reason="requeued")
    assert tr.active_count == 0
    assert tr.abandoned_total == 0
    recs = [ev for _, ev in Recorder.load(path)]
    ab = [e for e in recs if e["kind"] == "abandon"]
    assert ab and ab[0]["reason"] == "requeued"
    assert tr.sweep(0.0) == 0


def test_decode_histogram_counts_each_request_once():
    """'decode' is both a span (begun at first token, flushed at finish)
    and a mark-derived interval (first_token→finished); finish() must
    fold the interval only as a FALLBACK or every streaming request is
    observed twice and rate()-math on the decode panel reads 2x."""
    tr = Tracer()
    tr.mark("r1", "received")
    tr.mark("r1", "first_token")
    tr.span_begin("r1", "decode")  # the engine's streaming shape
    tr.finish("r1")
    assert tr.summary()["decode"]["count"] == 1
    # Mark-only traces (no span form) still get the interval fold.
    tr.mark("r2", "first_token")
    tr.finish("r2")
    assert tr.summary()["decode"]["count"] == 2


def test_tracer_opportunistic_sweep_caps_active_dict():
    tr = Tracer(ttl_s=0.0)
    for i in range(600):  # > the 256-op sweep cadence
        tr.mark(f"r{i}", "received")
    assert tr.active_count < 600  # the mark path itself reaped some
    assert tr.abandoned_total > 0


def test_mark_if_active_never_reopens():
    tr = Tracer()
    assert tr.mark_if_active("gone", "kv_landed") is False
    assert tr.active_count == 0  # the late-frame path cannot leak
    tr.mark("here", "received")
    assert tr.mark_if_active("here", "kv_landed") is True


def test_histograms_and_itl_tail():
    """Bucketed histograms replace the p50/p95 sketch: a single stalled
    ITL gap lands in a high bucket and is visible in the tail."""
    tr = Tracer()
    for _ in range(99):
        tr.observe_itl(2.0)
    tr.observe_itl(5000.0)  # one stall
    s = tr.summary()["itl"]
    assert s["count"] == 100
    assert s["p50_ms"] <= 5.0
    assert s["max_ms"] == 5000.0
    text = tr.render()
    assert 'dyntpu_trace_itl_ms_bucket{le="5"} 99' in text
    assert "dyntpu_trace_itl_ms_count 100" in text


def test_log_records_carry_request_and_trace_ids(capsys):
    """`grep trace_id` reconstructs the story across logs + captures:
    records inside a request scope carry both ids in both formats."""
    from dynamo_tpu.utils.logging import (
        JsonlFormatter,
        _ScopeFilter,
        request_scope,
    )

    logger = logging.getLogger("test.trace.corr")
    records = []
    handler = logging.Handler()
    handler.emit = records.append
    handler.addFilter(_ScopeFilter())
    logger.addHandler(handler)
    logger.setLevel(logging.INFO)
    try:
        with request_scope("req-42", "trace-abc"):
            logger.info("inside scope")
        logger.info("outside scope")
    finally:
        logger.removeHandler(handler)

    inside, outside = records
    assert inside.request_id == "req-42" and inside.trace_id == "trace-abc"
    assert "trace-abc" in inside.scope_suffix
    assert outside.request_id == "" and outside.scope_suffix == ""
    line = json.loads(JsonlFormatter().format(inside))
    assert line["request_id"] == "req-42" and line["trace_id"] == "trace-abc"
    assert "trace_id" not in json.loads(JsonlFormatter().format(outside))


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flight_recorder_ring_and_fault_dump(tmp_path):
    from dynamo_tpu.engine.flight_recorder import FlightRecorder

    fr = FlightRecorder(capacity=8, dump_dir=str(tmp_path))
    for i in range(20):
        fr.note_step(
            "decode", decode_tokens=i, batch_fill_ratio=0.5,
            dispatch_ms=1.0,
        )
    records = fr.snapshot()
    assert len(records) == 8                      # bounded ring
    assert records[-1]["decode_tokens"] == 19     # newest kept
    assert fr.snapshot(3)[0]["decode_tokens"] == 17
    assert fr.total_steps == 20

    path = fr.dump_fault("RuntimeError: boom")
    assert path is not None
    doc = json.loads((tmp_path / path.split("/")[-1]).read_text())
    assert doc["reason"] == "RuntimeError: boom"
    assert doc["records"][-1]["kind"] == "fault"
    # No dump dir configured -> quiet no-op, never a raise.
    assert FlightRecorder(dump_dir=None).dump_fault("x") is None


async def test_engine_fault_dumps_flight_record(tmp_path):
    """The black box survives the crash: an engine-loop fault flushes
    the step ring to disk before the engine dies."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.mocker import MockerConfig, MockerEngine
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.runtime.engine import Context

    cfg = EngineConfig(
        model=ModelConfig.tiny_test(), num_blocks=32, max_num_seqs=2,
        max_model_len=128, dtype="float32",
        flight_record_dir=str(tmp_path),
    )
    engine = MockerEngine(cfg, MockerConfig(vocab_size=100))
    await engine.start()
    engine._step = lambda: (_ := None).missing  # fault on first step
    req = PreprocessedRequest(
        token_ids=[1, 2, 3], sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=2, ignore_eos=True),
    )
    # The dying engine fails the queued sequence LOUDLY but typed: the
    # stream ends with an ERROR finish, it does not hang.
    ctx = Context(req.to_wire())
    outs = [o async for o in engine.generate(ctx)]
    assert outs and outs[-1]["finish_reason"] == "error"
    # The fault is attributed on the trace too: an engine death reaches
    # the consumer as an ERROR finish frame, not an exception — the
    # stream ends NORMALLY, so no downstream except clause ever fires.
    # _stream must mark "error" itself or the capture shows a clean
    # completion for a request that died.
    from dynamo_tpu.utils.tracing import tracer

    done = [t for t in tracer()._done if t.id == ctx.id]
    assert done and "error" in done[-1].marks
    for _ in range(100):
        if engine.flight.dumped_path:
            break
        await asyncio.sleep(0.01)
    assert engine.flight.dumped_path is not None
    doc = json.loads(open(engine.flight.dumped_path).read())
    assert "AttributeError" in doc["reason"]
    await engine.stop()


# ---------------------------------------------------------------------------
# debug endpoints + profiler
# ---------------------------------------------------------------------------


class _StubDebug:
    def debug_steps(self, n=None):
        return [
            {"seq": 1, "kind": "unified", "batch_fill_ratio": 0.75},
            {"seq": 2, "kind": "decode", "batch_fill_ratio": 0.5},
        ][-(n or 2):]


async def test_debug_endpoints(tmp_path, monkeypatch):
    import aiohttp

    from dynamo_tpu.llm.discovery import ModelManager
    from dynamo_tpu.llm.http_service import HttpService
    from dynamo_tpu.utils.profiling import Profiler

    profiler = Profiler(base_dir=str(tmp_path))
    started = []
    monkeypatch.setattr(
        Profiler, "_start", lambda self, out: started.append(out) or True
    )
    monkeypatch.setattr(Profiler, "_stop", lambda self: None)

    service = HttpService(
        ModelManager(), host="127.0.0.1", port=0,
        debug=_StubDebug(), profiler=profiler,
    )
    await service.start()
    base = f"http://127.0.0.1:{service.port}"
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{base}/debug/steps?n=1") as resp:
                assert resp.status == 200
                steps = (await resp.json())["steps"]
                assert steps[-1]["kind"] == "decode"
                assert "batch_fill_ratio" in steps[-1]
            async with s.get(f"{base}/debug/trace") as resp:
                assert resp.status == 200
                snap = await resp.json()
                assert "histograms" in snap
                assert "abandoned_traces_total" in snap
            async with s.get(f"{base}/debug/profile?seconds=0.1") as resp:
                assert resp.status == 200
                body = await resp.json()
                assert body["path"].startswith(str(tmp_path))
                assert started  # the window actually started
            # Bad input is a 400, not a 500.
            async with s.get(f"{base}/debug/steps?n=zebra") as resp:
                assert resp.status == 400
    finally:
        await service.stop()


async def test_profile_endpoint_refuses_unconfigured_and_overlap(tmp_path):
    import aiohttp

    from dynamo_tpu.llm.discovery import ModelManager
    from dynamo_tpu.llm.http_service import HttpService
    from dynamo_tpu.utils.profiling import ProfileError, Profiler

    # Unconfigured: the endpoint is disabled (security note in
    # docs/architecture/observability.md), and single-flight overlap is
    # a typed refusal.
    service = HttpService(ModelManager(), host="127.0.0.1", port=0,
                          profiler=Profiler(base_dir=None))
    await service.start()
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(
                f"http://127.0.0.1:{service.port}/debug/profile"
            ) as resp:
                assert resp.status == 503
    finally:
        await service.stop()

    prof = Profiler(base_dir=str(tmp_path))
    prof._busy = True
    with pytest.raises(ProfileError) as exc:
        await prof.capture(1.0)
    assert exc.value.busy


async def test_control_plane_profile_verb(tmp_path, monkeypatch):
    """runtime/debug.py: the profile verb reaches a subscribed worker
    (targeted by lease or broadcast) and runs one window."""
    from dynamo_tpu.runtime.debug import request_profile, watch_profile
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.utils.profiling import Profiler

    drt = await DistributedRuntime.in_process()
    prof = Profiler(base_dir=str(tmp_path), max_seconds=0.2)
    monkeypatch.setattr(Profiler, "_start", lambda self, out: True)
    monkeypatch.setattr(Profiler, "_stop", lambda self: None)
    watch = await watch_profile(drt, "ns", "tpu", prof)
    await request_profile(drt, "ns", "tpu", seconds=0.05)
    for _ in range(100):
        if prof.captures:
            break
        await asyncio.sleep(0.01)
    assert prof.captures == 1
    # A verb targeting another lease is ignored.
    await request_profile(drt, "ns", "tpu", seconds=0.05, lease_id=0xDEAD)
    await asyncio.sleep(0.1)
    assert prof.captures == 1
    watch.close()
    await drt.shutdown()


# ---------------------------------------------------------------------------
# trace_merge
# ---------------------------------------------------------------------------


def _write_capture(path, events):
    with Recorder(path) as rec:
        for ev in events:
            rec.record(ev)


def test_trace_merge_joins_processes_and_flags_orphans(tmp_path):
    from benchmarks.trace_merge import (
        assert_complete,
        load_captures,
        merge_report,
    )

    t0 = 1_000_000.0
    span = lambda tid, name, start, dur, pid: {  # noqa: E731
        "kind": "span", "id": "r1", "trace": tid, "span": name,
        "start_unix": t0 + start, "dur_ms": dur, "pid": pid,
    }
    # Process A (frontend+decode) and process B (prefill worker) captures
    # for ONE trace, plus an orphan trace in B.
    a = tmp_path / "a.jsonl"
    b = tmp_path / "b.jsonl"
    _write_capture(a, [
        span("T1", "admission", 0.000, 1.0, 1),
        span("T1", "tokenize", 0.001, 1.0, 1),
        span("T1", "route", 0.002, 1.0, 1),
        span("T1", "queue_wait", 0.003, 4.0, 1),
        span("T1", "decode_first", 0.030, 2.0, 1),
        span("T1", "decode", 0.032, 50.0, 1),
        {
            "kind": "finish", "id": "r1", "trace": "T1", "pid": 1,
            "marks": {
                "received": t0, "remote_prefill": t0 + 0.004,
                "first_token": t0 + 0.032, "finished": t0 + 0.082,
            },
            "spans": [],
        },
    ])
    _write_capture(b, [
        span("T1", "queue_wait", 0.004, 6.0, 2),
        span("T1", "prefill", 0.010, 12.0, 2),
        span("T1", "kv_transfer", 0.022, 8.0, 2),
        {"kind": "finish", "id": "r1", "trace": "T1", "pid": 2,
         "marks": {}, "spans": []},
        span("ORPHAN", "prefill", 0.0, 5.0, 2),
    ])
    traces = load_captures([str(a), str(b)])
    assert set(traces) == {"T1", "ORPHAN"}
    t1 = traces["T1"]
    assert t1.completed and not t1.missing_spans()
    assert t1.max_gap_ms() < 1.0  # gapless across BOTH processes
    # kv_transfer sits between prefill and decode_first in the merged
    # timeline.
    order = [s["name"] for s in t1.timeline()]
    assert order.index("prefill") < order.index("kv_transfer")
    assert order.index("kv_transfer") < order.index("decode_first")

    report = merge_report(traces)
    dec = report["ttft_decomposition_ms"]
    for name in ("admission", "queue_wait", "prefill", "kv_transfer",
                 "decode_first"):
        assert dec[name]["count"] == 1, name
    assert dec["queue_wait"]["p50_ms"] == 10.0  # summed across processes
    assert report["ttft_ms"]["p50_ms"] == 32.0

    failures = assert_complete(report)
    assert failures and "orphan" in failures[0]

    # Without the orphan the capture passes.
    del traces["ORPHAN"]
    assert assert_complete(merge_report(traces)) == []


def test_trace_merge_cli_exit_codes(tmp_path, capsys):
    from benchmarks.trace_merge import main

    t0 = 3_000_000.0
    good = tmp_path / "good.jsonl"
    _write_capture(good, [
        {"kind": "span", "id": "r", "trace": "T", "span": n,
         "start_unix": t0 + i * 0.001, "dur_ms": 1.0, "pid": 1}
        for i, n in enumerate(
            ("queue_wait", "prefill", "decode_first", "decode")
        )
    ] + [
        {"kind": "finish", "id": "r", "trace": "T", "pid": 1,
         "marks": {"engine_queued": t0, "first_token": t0 + 0.003,
                   "finished": t0 + 0.005},
         "spans": []},
    ])
    assert main([str(good), "--assert-complete"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["completed_requests"] == 1

    bad = tmp_path / "bad.jsonl"
    _write_capture(bad, [
        {"kind": "span", "id": "o", "trace": "ORPH", "span": "prefill",
         "start_unix": t0, "dur_ms": 1.0, "pid": 1},
    ])
    assert main([str(good), str(bad), "--assert-complete"]) == 1
    assert main([str(tmp_path / "missing.jsonl")]) == 2


def test_trace_merge_flags_missing_kv_transfer_and_gaps(tmp_path):
    from benchmarks.trace_merge import load_captures, merge_report

    t0 = 2_000_000.0
    cap = tmp_path / "c.jsonl"
    _write_capture(cap, [
        {"kind": "span", "id": "r2", "trace": "T2", "span": "queue_wait",
         "start_unix": t0, "dur_ms": 1.0, "pid": 1},
        # 900ms hole before prefill (a stall nothing accounts for).
        {"kind": "span", "id": "r2", "trace": "T2", "span": "prefill",
         "start_unix": t0 + 0.901, "dur_ms": 5.0, "pid": 1},
        {"kind": "span", "id": "r2", "trace": "T2", "span": "decode_first",
         "start_unix": t0 + 0.906, "dur_ms": 1.0, "pid": 1},
        {"kind": "span", "id": "r2", "trace": "T2", "span": "decode",
         "start_unix": t0 + 0.907, "dur_ms": 1.0, "pid": 1},
        {"kind": "finish", "id": "r2", "trace": "T2", "pid": 1,
         "marks": {"received": t0, "remote_prefill": t0,
                   "first_token": t0 + 0.907, "finished": t0 + 0.91},
         "spans": []},
    ])
    traces = load_captures([str(cap)])
    report = merge_report(traces, max_gap_ms=250.0)
    assert len(report["incomplete"]) == 1
    bad = report["incomplete"][0]
    assert "kv_transfer" in bad["missing_spans"]  # remote w/o transfer
    assert bad["max_gap_ms"] > 800


# ---------------------------------------------------------------------------
# the cross-process path, end to end (mocker-driven)
# ---------------------------------------------------------------------------


async def test_disagg_trace_e2e_mocker(tmp_path):
    """Frontend → prefill queue → decode over the REAL wire planes
    (HTTP, bus envelope, TCP response plane, KV tcp transfer) with
    mocker engines: the merged timeline must be gapless, kv_transfer
    must land between prefill and first decode, trace ids must survive
    the TCP error plane, and /debug/steps must serve the step ring."""
    import aiohttp

    from benchmarks.trace_merge import (
        assert_complete,
        load_captures,
        merge_report,
    )
    from dynamo_tpu.disagg import (
        DecodeOperator,
        DisaggConfig,
        DisaggRouter,
        PrefillQueue,
        PrefillWorker,
    )
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.llm.discovery import (
        ModelManager,
        ModelWatcher,
        register_llm,
    )
    from dynamo_tpu.llm.http_service import HttpService
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.mocker import MockerConfig, MockerEngine
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    capture = tmp_path / "trace.jsonl"
    reset_tracer(str(capture))
    try:
        cfg = EngineConfig(
            model=ModelConfig.tiny_test(), num_blocks=64, max_num_seqs=4,
            max_model_len=256, dtype="float32",
        )
        decode = MockerEngine(cfg, MockerConfig(vocab_size=100))
        await decode.start()
        prefill = MockerEngine(cfg, MockerConfig(vocab_size=100))
        await prefill.start()

        drt = await DistributedRuntime.in_process()
        queue = PrefillQueue(drt, "trace-e2e")
        dis = DisaggRouter.__new__(DisaggRouter)
        # Force EVERY prefill remote so the full hop chain is exercised.
        dis.cfg = DisaggConfig(
            max_local_prefill_length=1, max_prefill_queue_size=64,
        )
        op = await DecodeOperator(decode, queue, dis, transport="tcp").start()
        pw = PrefillWorker(prefill, queue).start()

        ep = drt.namespace("trace").component("mock").endpoint("generate")
        await ep.serve(op)
        await register_llm(
            drt, ep, ModelDeploymentCard(name="mock", model_path="toy")
        )
        manager = ModelManager()
        await ModelWatcher(drt, manager).start()
        service = HttpService(
            manager, host="127.0.0.1", port=0, debug=decode,
        )
        await service.start()
        base = f"http://127.0.0.1:{service.port}"
        body = {
            "model": "mock",
            "messages": [{
                "role": "user",
                "content": "trace this request across every process hop",
            }],
            "stream": False,
            "max_tokens": 8,
        }
        async with aiohttp.ClientSession() as s:
            async with s.post(f"{base}/v1/chat/completions", json=body) as r:
                assert r.status == 200, await r.text()
                await r.read()
            assert op.remote_count == 1 and op.local_count == 0

            # /debug/steps: the decode engine's ring has records with
            # kind + batch_fill_ratio (acceptance criterion).
            async with s.get(f"{base}/debug/steps?n=16") as r:
                assert r.status == 200
                steps = (await r.json())["steps"]
                assert steps, "flight ring empty after serving"
                assert all("batch_fill_ratio" in st for st in steps)
                assert {st["kind"] for st in steps} <= {
                    "decode", "prefill", "unified", "spec", "fault",
                }

            # TCP error plane: a draining decode engine sheds the next
            # request; the typed 503 must cross the wire AND the trace
            # must finish (no orphan) under the same trace id.
            decode.begin_drain()
            async with s.post(f"{base}/v1/chat/completions", json=body) as r:
                assert r.status == 503
                assert "Retry-After" in r.headers

        await service.stop()
        await pw.stop()
        await op.stop()
        await decode.stop()
        await prefill.stop()
        await drt.shutdown()
    finally:
        reset_tracer(None)

    traces = load_captures([str(capture)])
    completed = [t for t in traces.values() if t.completed]
    assert len(completed) == 1
    t = completed[0]
    # Full chain incl. admission (frontend) and kv_transfer (remote).
    assert t.missing_spans() == []
    have = {s["name"] for s in t.spans}
    assert {"admission", "tokenize", "route", "queue_wait", "prefill",
            "kv_transfer", "decode_first", "decode"} <= have
    # Gapless timeline (in-process clocks agree exactly).
    assert t.max_gap_ms() < 250.0
    # kv_transfer sits between the prefill and the first decode.
    prefill_spans = [s for s in t.spans if s["name"] == "prefill"]
    kvt = next(s for s in t.spans if s["name"] == "kv_transfer")
    dfirst = next(s for s in t.spans if s["name"] == "decode_first")
    prefill_end = max(
        s["start_unix"] + s["dur_ms"] / 1000.0 for s in prefill_spans
    )
    assert kvt["start_unix"] >= prefill_end - 1e-3
    assert dfirst["start_unix"] >= kvt["start_unix"]

    # The run-level report carries the full TTFT decomposition.
    report = merge_report(traces)
    dec = report["ttft_decomposition_ms"]
    for name in ("admission", "queue_wait", "prefill", "kv_transfer",
                 "decode_first"):
        assert name in dec, f"decomposition missing {name}"
    assert assert_complete(report) == []

    # Error-plane request: finished (worker-side "error" mark under the
    # frontend's trace id), not orphaned.
    shed = [
        t for t in traces.values()
        if t.finishes and "error" in t.marks and not t.completed
    ]
    assert len(shed) == 1
    assert {"admission"} <= {s["name"] for s in shed[0].spans}


async def test_trace_ids_survive_bus_envelope_without_preprocessor():
    """The envelope-level trace (runtime/egress.py) covers payloads that
    are NOT a PreprocessedRequest wire: the worker-side capture adopts
    the caller's trace id."""
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.egress import PushRouter, RouterMode
    from dynamo_tpu.runtime.engine import Context, EngineAdapter

    seen = {}

    async def echo(request):
        seen["worker_trace"] = tracer().trace_id(request.id)
        yield {"ok": True}

    drt = await DistributedRuntime.in_process()
    ep = drt.namespace("tr").component("echo").endpoint("generate")
    await ep.serve(EngineAdapter(echo))
    router = await PushRouter.create(
        drt, "tr.echo.generate", RouterMode.ROUND_ROBIN
    )
    ctx = Context({"payload": 1})
    frontend_trace = tracer().trace_id(ctx.id)
    out = [item async for item in router.generate(ctx)]
    assert out == [{"ok": True}]
    assert seen["worker_trace"] == frontend_trace
    tracer().finish(ctx.id)
    await drt.shutdown()

"""KV observatory tests (docs/architecture/observability.md "KV
observatory"): route-decision auditing, indexer staleness measurement,
sharded-indexer equivalence/determinism, aggregator failure counting +
stale-after-TTL endpoints, KVBM tier telemetry, engine-side actual-reuse
reporting with gauge↔ForwardPassMetrics sync, and the
benchmarks/route_audit.py join tool."""

import asyncio
import time
from types import SimpleNamespace

import numpy as np
import pytest

from dynamo_tpu.block_manager import (
    KvbmConfig,
    KvBlockManager,
    KvLayoutConfig,
)
from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.llm.kv_router.audit import RouteAuditRecord, RouteObservatory
from dynamo_tpu.llm.kv_router.indexer import KvIndexer, KvIndexerSharded
from dynamo_tpu.llm.kv_router.metrics_aggregator import KvMetricsAggregator
from dynamo_tpu.llm.kv_router.protocols import (
    ForwardPassMetrics,
    KvCacheEventData,
    RouterEvent,
)
from dynamo_tpu.llm.kv_router.scheduler import (
    DefaultWorkerSelector,
    KvRouterConfig,
)
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.mocker import MockerConfig, MockerEngine
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.utils.faults import FAULTS

pytestmark = pytest.mark.anyio


def _stored(hashes, parent=None, published=None):
    return RouterEvent(
        worker_id=hashes[0] % 7 + 1,
        event=KvCacheEventData(kind="stored", block_hashes=hashes,
                               parent_hash=parent),
        published_unix=published,
    )


# ---------------------------------------------------------------------------
# selector: full candidate field on the decision
# ---------------------------------------------------------------------------


def test_selector_exposes_all_candidates():
    from dynamo_tpu.llm.kv_router.metrics_aggregator import ProcessedEndpoints

    sel = DefaultWorkerSelector(KvRouterConfig(), seed=0)
    eps = ProcessedEndpoints(
        metrics={
            1: ForwardPassMetrics(kv_active_blocks=10, kv_total_blocks=100),
            2: ForwardPassMetrics(kv_active_blocks=90, kv_total_blocks=100,
                                  num_requests_waiting=3),
        }
    )
    d = sel.select(eps, {1: 4}, isl=64)
    assert d.worker_id == 1
    assert {c["worker"] for c in d.candidates} == {1, 2}
    loser = next(c for c in d.candidates if c["worker"] == 2)
    winner = next(c for c in d.candidates if c["worker"] == 1)
    # The audit record can explain WHY 2 lost: lower logit, higher usage.
    assert loser["logit"] < winner["logit"]
    assert loser["usage"] > winner["usage"]
    assert winner["overlap_blocks"] == 4


# ---------------------------------------------------------------------------
# indexer staleness
# ---------------------------------------------------------------------------


async def test_indexer_staleness_accounting():
    idx = KvIndexer().start()
    now = time.time()
    idx.apply(_stored([1, 2], published=now - 0.06))
    idx.apply(_stored([3], parent=2, published=now - 0.06))
    assert idx.pending_events == 2  # nothing applied until the loop runs
    await idx.find_matches([1, 2, 3])
    st = idx.stats()
    assert st["kv_events_applied_total"] == 2
    assert st["kv_events_pending"] == 0
    assert st["kv_event_lag_count"] == 2
    # Events were published ~60ms before apply — the lag histogram must
    # see it (bucketed: the 50/100ms buckets).
    assert st["kv_event_lag_max_ms"] >= 50.0
    assert st["kv_radix_blocks"] == 3
    wm = idx.watermark()
    assert wm["applied"] == 2 and wm["pending"] == 0
    assert "lag_p99_ms" in wm
    # Radix eviction counter: removing every holder prunes the chain
    # ([1,2] landed under worker 2, [3] under worker 4 — _stored keys
    # the worker off the first hash).
    for wid in (2, 4):
        idx.apply(RouterEvent(wid, KvCacheEventData(kind="cleared")))
    await idx.find_matches([1])
    assert idx.stats()["kv_radix_evicted_blocks_total"] >= 3
    await idx.stop()


async def test_indexer_direct_apply_path_counts_too():
    """The consumer-dead fallback (find_matches drains directly) must use
    the same accounting funnel — counters can't diverge from the tree."""
    idx = KvIndexer()  # never started: no consumer task
    idx.apply(_stored([10, 11], published=time.time()))
    assert await idx.find_matches([10, 11]) != {}
    assert idx.events_applied_total == 2 or idx.events_applied_total == 1
    # (one RouterEvent holding two hashes applies as ONE event)
    assert idx.events_applied_total == 1
    assert idx.stats()["kv_event_lag_count"] == 1


async def test_sharded_equivalence_and_determinism():
    """Same event stream ⇒ a sharded indexer answers find_matches
    identically to the unsharded one, and two sharded replicas build
    identical per-shard states (the ROADMAP #5 fan-out invariant)."""
    events = []
    for w in range(1, 6):
        chain = [w * 100 + i for i in range(4)]
        parent = None
        for h in chain:
            events.append(
                RouterEvent(w, KvCacheEventData(
                    kind="stored", block_hashes=[h], parent_hash=parent
                ), published_unix=time.time())
            )
            parent = h

    flat = KvIndexer().start()
    shard_a = KvIndexerSharded(4).start()
    shard_b = KvIndexerSharded(4).start()
    for ev in events:
        flat.apply(ev)
        shard_a.apply(ev)
        shard_b.apply(ev)

    queries = [[100, 101, 102, 103], [300, 301], [500, 999], [42]]
    for q in queries:
        expect = await flat.find_matches(q)
        assert await shard_a.find_matches(q) == expect
        assert await shard_b.find_matches(q) == expect

    # Deterministic fan-out: both replicas applied the same events to the
    # same shard slots.
    counts_a = [s.events_applied_total for s in shard_a.shards]
    counts_b = [s.events_applied_total for s in shard_b.shards]
    assert counts_a == counts_b
    assert sum(counts_a) == len(events)
    st = shard_a.stats()
    assert st["kv_events_applied_total"] == len(events)
    assert st["kv_indexer_shards"] == 4
    await asyncio.gather(flat.stop(), shard_a.stop(), shard_b.stop())


async def test_sharded_staleness_under_delayed_apply_fault():
    """utils/faults.py `indexer.apply` delay = a replica falling behind
    the bus: pending depth must be visible mid-lag, queries must still
    return the complete answer after the drain, and the lag histogram
    must record the delay."""
    idx = KvIndexerSharded(2).start()
    try:
        FAULTS.arm("indexer.apply", "delay", times=4, delay_s=0.05)
        t0 = time.time()
        for w in (1, 2, 3, 4):
            idx.apply(RouterEvent(w, KvCacheEventData(
                kind="stored", block_hashes=[w * 10]
            ), published_unix=t0))
        await asyncio.sleep(0.02)  # consumers now sleeping in the fault
        assert idx.pending_events > 0
        wm = idx.watermark()
        assert wm["pending"] > 0 and len(wm["per_shard_pending"]) == 2
        # The query drains through the delay and still sees everything.
        got = await idx.find_matches([10])
        assert got == {1: 1}
        st = idx.stats()
        assert st["kv_events_applied_total"] == 4
        assert st["kv_events_pending"] == 0
        assert st["kv_event_lag_count"] == 4
        assert st["kv_event_lag_max_ms"] >= 25.0  # delay showed up as lag
    finally:
        FAULTS.disarm("indexer.apply")
        await idx.stop()


async def test_indexer_apply_drop_fault_counts_dropped():
    idx = KvIndexer().start()
    try:
        FAULTS.arm("indexer.apply", "drop", times=1)
        idx.apply(_stored([77], published=time.time()))
        await asyncio.sleep(0.05)
        assert await idx.find_matches([77]) == {}  # event was dropped
        assert idx.events_dropped_total == 1
        assert idx.events_applied_total == 0
    finally:
        FAULTS.disarm("indexer.apply")
        await idx.stop()


# ---------------------------------------------------------------------------
# aggregator: failure counting + stale-after-TTL
# ---------------------------------------------------------------------------


class _StubRouter:
    def __init__(self, ids):
        self.ids = ids
        self.client = SimpleNamespace(
            instances=lambda: [SimpleNamespace(instance_id=i) for i in self.ids]
        )


async def test_aggregator_counts_failures_and_drops_after_ttl():
    agg = KvMetricsAggregator(None, None, endpoint_ttl_s=0.15)
    agg._router = _StubRouter([1, 2])
    failing: set[int] = set()

    async def scrape_one(iid):
        if iid in failing:
            raise RuntimeError("endpoint down")
        return ForwardPassMetrics(kv_active_blocks=iid)

    agg._scrape_one = scrape_one

    eps = await agg.scrape()
    assert set(eps.metrics) == {1, 2}
    assert agg.scrape_failures_total == 0

    # Transient blip: the failure is COUNTED but the last-known snapshot
    # is retained (routing doesn't flap on one timeout).
    failing.add(2)
    eps = await agg.scrape()
    assert agg.scrape_failures_total == 1
    assert set(eps.metrics) == {1, 2}
    assert eps.metrics[2].kv_active_blocks == 2  # last-known value

    # Past the TTL the dead worker's stale load stops being scoreable.
    await asyncio.sleep(0.2)
    eps = await agg.scrape()
    assert set(eps.metrics) == {1}
    assert agg.stale_endpoint_drops_total >= 1
    assert agg.scrape_failures_total == 2

    # Staleness of the WHOLE snapshot (scrape loop dead): age > TTL.
    assert not agg.stale
    await asyncio.sleep(0.2)
    assert agg.stale


# ---------------------------------------------------------------------------
# route observatory
# ---------------------------------------------------------------------------


def test_route_observatory_ring_and_gauges():
    obs = RouteObservatory(capacity=2)
    for i in range(3):
        obs.record(RouteAuditRecord(
            request_id=f"r{i}", trace_id=f"t{i}", worker_id=i,
            overlap_blocks=i, isl_blocks=4, logit=0.5, decision_ms=1.0,
            indexer={"applied": 7, "pending": 0},
        ))
    snap = obs.snapshot(8)
    assert snap["routes_total"] == 3
    assert snap["predicted_blocks_total"] == 0 + 1 + 2
    assert len(snap["recent"]) == 2  # bounded ring
    rec = snap["recent"][-1]
    assert rec["kind"] == "route" and rec["trace"] == "t2"
    assert rec["indexer"]["applied"] == 7

    obs.register_provider(lambda: {"kv_events_applied_total": 5})
    obs.register_provider(lambda: {"kv_events_applied_total": 3})
    g = obs.gauges()
    assert g["kv_router_routes_total"] == 3.0
    assert g["kv_events_applied_total"] == 8.0  # providers sum
    # A broken provider must not take down the gauge merge.
    obs.register_provider(lambda: 1 / 0)
    assert obs.gauges()["kv_router_routes_total"] == 3.0


# ---------------------------------------------------------------------------
# KVBM tier telemetry
# ---------------------------------------------------------------------------

_LAYOUT8 = KvLayoutConfig(
    num_layers=1, page_size=1, num_kv_heads=1, head_dim=4, dtype="float32"
)  # block_elems == 1*2*1*1*4 == 8: the mocker runner's 8-float block rows


def _row(seed: float) -> np.ndarray:
    return np.full((_LAYOUT8.block_elems,), seed, np.float32)


async def _settle(mgr, n):
    deadline = asyncio.get_running_loop().time() + 5
    while mgr.stats()["host_registered"] < n:
        assert asyncio.get_running_loop().time() < deadline
        await asyncio.sleep(0.02)


async def test_kvbm_stats_counters_and_disk_origin(tmp_path):
    mgr = await KvBlockManager(
        KvbmConfig(
            layout=_LAYOUT8, host_blocks=4, disk_blocks=8,
            disk_path=str(tmp_path / "g3.bin"),
        )
    ).start()
    try:
        mgr.offer(100, None, [1] * 4, _row(1.0))
        mgr.offer(200, 100, [2] * 4, _row(2.0))
        await _settle(mgr, 2)
        if mgr._g2_to_g3 is not None:
            await mgr._g2_to_g3.drain()
        st = mgr.stats()
        assert st["host_stored_blocks_total"] == 2
        assert st["offloaded_blocks_total"] == 2      # chained down-tier
        assert st["link_g1g2_bps"] > 0
        assert st["link_g2g3_bps"] > 0
        assert st["disk_registered"] == 2

        # Host-prefix accounting: 2 hits + 1 miss.
        assert mgr.count_host_match([100, 200, 999]) == 2
        st = mgr.stats()
        assert st["host_hit_blocks_total"] == 2
        assert st["host_miss_blocks_total"] == 1

        # Evict the host tier (LRU pressure), then promote back from disk.
        blocks = mgr.host_pool.allocate_blocks(4)
        for b in blocks:
            mgr.host_pool.release(b)
        assert mgr.stats()["host_evictions_total"] >= 2
        assert mgr.count_host_match([100, 200]) == 0

        n = await mgr.onboard_from_disk([100, 200])
        assert n == 2
        st = mgr.stats()
        assert st["promoted_blocks_total"] == 2
        assert st["link_g3g2_bps"] > 0
        # Disk-origin attribution: both host-resident blocks came via G3.
        assert mgr.count_disk_origin([100, 200]) == 2
        assert mgr.count_disk_origin([999]) == 0

        # Re-store from the DEVICE after another eviction: the G3-origin
        # marker must not survive — this reuse is device-fed, not disk.
        blocks = mgr.host_pool.allocate_blocks(4)
        for b in blocks:
            mgr.host_pool.release(b)
        assert mgr.count_host_match([100]) == 0
        mgr.offer(100, None, [1] * 4, _row(1.0))
        await _settle(mgr, 1)
        assert mgr.count_disk_origin([100]) == 0
    finally:
        await mgr.stop()


# ---------------------------------------------------------------------------
# engine: actual-reuse reporting, tier split, gauge sync
# ---------------------------------------------------------------------------


def _ecfg():
    return EngineConfig(
        model=ModelConfig.tiny_test(),
        num_blocks=64,
        max_num_seqs=4,
        max_model_len=256,
        dtype="float32",
    )


async def _generate(engine, prompt, n=4):
    req = PreprocessedRequest(
        token_ids=list(prompt),
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=n, ignore_eos=True),
    )
    out = []
    async for item in engine.generate(Context(req.to_wire())):
        out += item.get("token_ids", [])
    return out


async def test_engine_reports_actuals_split_by_tier():
    """Engine A computes a prompt cold (actual reuse 0) then warm (device
    tier); a FRESH engine B sharing the host tier reuses via G2 — and
    every path lands a kv_actual record with the right split, cumulative
    counters, readiness gauges, and ForwardPassMetrics fields in sync."""
    kvbm = await KvBlockManager(
        KvbmConfig(layout=_LAYOUT8, host_blocks=16)
    ).start()
    actuals_a: list[dict] = []
    metrics_a: list[dict] = []
    eng_a = MockerEngine(
        _ecfg(), MockerConfig(seed=1), block_manager=kvbm,
        on_kv_actual=actuals_a.append, on_metrics=metrics_a.append,
    )
    await eng_a.start()
    prompt = list(range(40))  # 2 full blocks + tail

    await _generate(eng_a, prompt)
    assert len(actuals_a) == 1
    cold = actuals_a[0]
    assert cold["kind"] == "kv_actual" and cold["isl_blocks"] == 3
    assert (cold["device_blocks"], cold["host_blocks"], cold["disk_blocks"]) \
        == (0, 0, 0)

    # Same prompt again on A: pure G1 (device) reuse.
    await _generate(eng_a, prompt)
    warm = actuals_a[1]
    assert warm["device_blocks"] == 2
    assert warm["host_blocks"] == 0 and warm["disk_blocks"] == 0
    assert eng_a._reused_device_blocks == 2

    # Gauge ↔ ForwardPassMetrics sync (the PR 8 coloc-style assertion):
    # the readiness snapshot, the metrics callback dict, and the wire
    # type must agree on every kv observatory key.
    rd = eng_a.readiness()
    assert rd["kv_reused_device_blocks_total"] == 2
    assert rd["kvbm_host_registered"] == kvbm.stats()["host_registered"]
    assert metrics_a, "metrics callback never fired"
    m = metrics_a[-1]
    fpm = ForwardPassMetrics.from_wire(m)
    for key in (
        "kv_reused_device_blocks_total",
        "kv_reused_host_blocks_total",
        "kv_reused_disk_blocks_total",
        "kvbm_host_registered",
        "kvbm_host_stored_blocks_total",
        "kvbm_host_hit_blocks_total",
    ):
        assert key in m, key
        assert getattr(fpm, key) == m[key] == rd[key], key
    await asyncio.sleep(0.3)  # offload pump: blocks → host tier
    await eng_a.stop()

    actuals_b: list[dict] = []
    eng_b = MockerEngine(
        _ecfg(), MockerConfig(seed=2), block_manager=kvbm,
        on_kv_actual=actuals_b.append,
    )
    await eng_b.start()
    await _generate(eng_b, prompt)
    assert len(actuals_b) == 1
    host = actuals_b[0]
    # Cold HBM, warm host tier: the reuse is G2, not G1.
    assert host["host_blocks"] == 2
    assert host["device_blocks"] == 0
    assert eng_b.readiness()["kv_reused_host_blocks_total"] == 2
    await eng_b.stop()
    await kvbm.stop()


def test_metric_surfaces_carry_kv_observatory_fields():
    """Exporter gauges render via getattr on ForwardPassMetrics — every
    declared gauge must exist there, and the new kv observatory fields
    must survive the wire roundtrip."""
    from dynamo_tpu.llm.metrics_exporter import _GAUGES

    m = ForwardPassMetrics()
    for key, _help in _GAUGES:
        assert hasattr(m, key), key
    wire = m.to_wire()
    wire.update(
        kv_reused_device_blocks_total=11,
        kv_reused_host_blocks_total=7,
        kv_reused_disk_blocks_total=3,
        kvbm_host_usage=0.5,
        kvbm_link_g3g2_bps=123.4,
    )
    back = ForwardPassMetrics.from_wire(wire)
    assert back.kv_reused_device_blocks_total == 11
    assert back.kv_reused_host_blocks_total == 7
    assert back.kv_reused_disk_blocks_total == 3
    assert back.kvbm_host_usage == 0.5
    assert back.kvbm_link_g3g2_bps == 123.4


# ---------------------------------------------------------------------------
# route_audit.py: the join tool
# ---------------------------------------------------------------------------


def _route_rec(trace, overlap, pending=0, worker=1):
    return {
        "kind": "route", "id": f"req-{trace}", "trace": trace,
        "worker_id": worker, "overlap_blocks": overlap, "isl_blocks": 8,
        "logit": 0.1, "decision_ms": 2.0, "candidates": [],
        "indexer": {"applied": 10, "pending": pending, "lag_p99_ms": 4.0},
        "indexer_shards": 1, "metrics_age_ms": 100.0, "unix": time.time(),
    }


def _actual_rec(trace, device=0, host=0, disk=0):
    return {
        "kind": "kv_actual", "id": f"req-{trace}", "trace": trace,
        "isl_blocks": 8, "device_blocks": device, "host_blocks": host,
        "disk_blocks": disk, "unix": time.time(),
    }


def test_route_audit_join_and_gates(tmp_path):
    from benchmarks.route_audit import join_report, main, run_asserts
    from dynamo_tpu.utils.recorder import Recorder

    cap = tmp_path / "cap.jsonl"
    rec = Recorder(cap)
    rec.record(_route_rec("t1", overlap=4))               # exact
    rec.record(_actual_rec("t1", device=4))
    rec.record(_route_rec("t2", overlap=6, pending=3))    # stale mispredict
    rec.record(_actual_rec("t2", device=1, host=1))
    rec.record(_route_rec("t3", overlap=2))               # fresh mispredict
    rec.record(_actual_rec("t3", device=0))
    rec.close()

    from benchmarks.route_audit import load_records

    routes, actuals, planner = load_records([str(cap)])
    assert planner == []
    report = join_report(routes, actuals)
    assert report["joined"] == 3 and report["orphan_routes"] == 0
    assert report["join_rate"] == 1.0
    assert report["overlap_error"]["exact"] == 1
    assert report["overlap_error"]["overpredicted"] == 2
    assert report["staleness"]["mispredicted_while_stale"] == 1
    assert report["staleness"]["mispredicted_while_fresh"] == 1
    assert report["staleness"]["indexer_lag_p99_ms"] == 4.0
    assert report["tier_split"] == {
        "device_blocks": 5, "host_blocks": 1, "disk_blocks": 0,
        "peer_blocks": 0,
    }
    assert run_asserts(report, 0.95) == []
    assert main([str(cap), "--assert", "--json"]) == 0

    # An orphan route (no engine actual) hard-fails the gate.
    cap2 = tmp_path / "cap2.jsonl"
    rec = Recorder(cap2)
    rec.record(_route_rec("t9", overlap=4))
    rec.record(_route_rec("t1", overlap=4))
    rec.record(_actual_rec("t1", device=4))
    rec.close()
    routes, actuals, _planner = load_records([str(cap2)])
    report = join_report(routes, actuals)
    assert report["orphan_routes"] == 1
    assert run_asserts(report, 0.95)
    assert main([str(cap2), "--assert", "--json"]) == 1

    # Zero actual reports is a hard failure even with zero routes joined.
    cap3 = tmp_path / "cap3.jsonl"
    rec = Recorder(cap3)
    rec.record(_route_rec("t1", overlap=4))
    rec.close()
    assert main([str(cap3), "--assert", "--json"]) == 1


# ---------------------------------------------------------------------------
# /debug/routes endpoint
# ---------------------------------------------------------------------------


async def test_debug_routes_endpoint():
    import httpx

    from dynamo_tpu.llm.discovery import ModelManager
    from dynamo_tpu.llm.http_service import HttpService
    from dynamo_tpu.llm.kv_router.audit import ROUTE_OBS

    before = ROUTE_OBS.routes_total
    ROUTE_OBS.record(RouteAuditRecord(
        request_id="r", trace_id="t", worker_id=1, overlap_blocks=2,
        isl_blocks=4, logit=0.0, decision_ms=1.0,
    ))
    service = HttpService(ModelManager(), host="127.0.0.1", port=0)
    await service.start()
    try:
        async with httpx.AsyncClient() as client:
            base = f"http://127.0.0.1:{service.port}"
            r = await client.get(f"{base}/debug/routes?n=4")
            assert r.status_code == 200
            body = r.json()
            assert body["routes_total"] == before + 1
            assert body["recent"][-1]["trace"] == "t"
            assert "kv_router_routes_total" in body["gauges"]
            # The router-plane gauges render on /metrics too.
            r = await client.get(f"{base}/metrics")
            assert "kv_router_routes_total" in r.text
    finally:
        await service.stop()

"""Token block hashing tests (model: reference lib/llm/src/tokens.rs tests)."""

from dynamo_tpu.llm.tokens import (
    TokenBlockSequence,
    block_sequence_hashes,
    compute_block_hash,
    compute_sequence_hash,
)


def test_block_hash_deterministic():
    a = compute_block_hash([1, 2, 3, 4])
    b = compute_block_hash([1, 2, 3, 4])
    assert a == b
    assert a != compute_block_hash([1, 2, 3, 5])


def test_sequence_hash_chains():
    h1 = compute_sequence_hash(0, [1, 2])
    h2 = compute_sequence_hash(h1, [3, 4])
    # Same tokens under a different parent give a different sequence hash.
    assert h2 != compute_sequence_hash(0, [3, 4])


def test_sequence_append_extend():
    seq = TokenBlockSequence(block_size=4)
    completed = seq.extend(range(10))
    assert len(completed) == 2
    assert len(seq.blocks) == 2
    assert seq.partial == [8, 9]
    assert len(seq) == 10
    assert seq.tokens == list(range(10))


def test_prefix_property():
    """Shared prefixes produce identical sequence-hash prefixes."""
    a = block_sequence_hashes(list(range(32)), block_size=4)
    b = block_sequence_hashes(list(range(16)) + [99] * 16, block_size=4)
    assert a[:4] == b[:4]
    assert a[4] != b[4]


def test_salt_changes_hashes():
    a = block_sequence_hashes(list(range(8)), block_size=4, salt=b"tenant-a")
    b = block_sequence_hashes(list(range(8)), block_size=4, salt=b"tenant-b")
    assert a != b


def test_truncate_and_unwind():
    seq = TokenBlockSequence.from_tokens(range(10), block_size=4)
    ref = block_sequence_hashes(range(8), block_size=4)
    assert seq.sequence_hashes() == ref

    seq.truncate(6)
    assert seq.tokens == [0, 1, 2, 3, 4, 5]
    assert len(seq.blocks) == 1

    # Unwind back across a block boundary.
    seq2 = TokenBlockSequence.from_tokens(range(8), block_size=4)
    assert seq2.unwind() == 7
    assert seq2.tokens == list(range(7))
    # Re-appending restores the identical chain.
    seq2.append(7)
    assert seq2.sequence_hashes() == ref

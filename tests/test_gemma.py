"""Gemma-3 family tests.

Two layers of proof:
1. An INDEPENDENT oracle: a tiny random-weight HF-transformers
   Gemma3ForCausalLM is saved to disk and loaded through the production
   path (ModelConfig.from_hf + llama.load_hf_weights); our no-cache
   forward must reproduce HF's logits. This pins every family knob —
   (1+w) norms, sandwich norms, GeGLU, scaled embeddings, QK-norm,
   query_pre_attn_scalar, and the local/global rope + window pattern —
   against an implementation we didn't write.
2. The paged serving engine must match the no-cache oracle greedily once
   the context crosses the sliding window, with the global layers' full
   attention live.

Reference parity: the reference serves Gemma through its delegated
engines (e.g. vLLM — reference: launch/dynamo-run/src/subprocess/
vllm_v1_inc.py); here the family is native (models/llama.py).
"""

import asyncio
import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.runtime.engine import Context

pytestmark = pytest.mark.anyio

GCFG = ModelConfig.tiny_gemma_test()


def test_gemma3_matches_hf_transformers(tmp_path):
    """End-to-end HF parity: save a random HF Gemma-3, load it through
    from_hf + load_hf_weights, compare full-sequence logits."""
    torch = pytest.importorskip("torch")
    from transformers import Gemma3ForCausalLM, Gemma3TextConfig

    hf_cfg = Gemma3TextConfig(
        vocab_size=384,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=4,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,
        rope_theta=1_000_000.0,
        rope_local_base_freq=10_000.0,
        sliding_window=32,
        sliding_window_pattern=2,
        max_position_embeddings=512,
        rms_norm_eps=1e-6,
        query_pre_attn_scalar=32,  # != head_dim: the scale fold must be live
        hidden_activation="gelu_pytorch_tanh",
        tie_word_embeddings=True,
        attention_bias=False,
    )
    torch.manual_seed(0)
    model = Gemma3ForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path)

    cfg = ModelConfig.from_hf(str(tmp_path))
    assert cfg.window_pattern == 2
    assert cfg.post_norms and cfg.norm_offset and cfg.embed_scale
    assert cfg.hidden_act == "gelu_tanh" and cfg.qk_norm
    assert cfg.rope_local_theta == 10_000.0
    assert cfg.layer_window(0) == 32 and cfg.layer_window(1) == 0

    params = llama.load_hf_weights(cfg, str(tmp_path), dtype=jnp.float32)
    # 48 tokens > the 32-token window, so local masking + the global
    # layers' full span + both rope bases all matter.
    toks = np.random.default_rng(5).integers(1, 384, 48)
    with torch.no_grad():
        want = model(torch.tensor(toks)[None]).logits[0].float().numpy()
    got = np.asarray(llama.reference_forward(cfg, params, jnp.asarray(toks)))
    np.testing.assert_allclose(got, want, atol=2e-3, rtol=2e-3)


def test_gemma2_softcapping_rejected(tmp_path):
    import json

    (tmp_path / "config.json").write_text(
        json.dumps(
            {
                "architectures": ["Gemma2ForCausalLM"],
                "model_type": "gemma2",
                "attn_logit_softcapping": 50.0,
            }
        )
    )
    with pytest.raises(NotImplementedError):
        ModelConfig.from_hf(str(tmp_path))


async def _collect(engine, prompt, n):
    req = PreprocessedRequest(
        token_ids=prompt,
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=n, ignore_eos=True),
    )
    out = []
    async for item in engine.generate(Context(req.to_wire())):
        out += item["token_ids"]
    return out


async def test_gemma3_engine_matches_oracle():
    """Paged serving (prefill chunks + fused decode + per-layer windows)
    must reproduce the no-cache oracle, and the window pattern must be
    live: an all-global variant diverges once ctx exceeds the window."""
    params = llama.init_params(jax.random.PRNGKey(7), GCFG, dtype=jnp.float32)
    prompt = [int(t) for t in
              np.random.default_rng(9).integers(1, GCFG.vocab_size, 40)]

    def oracle(cfg, n):
        toks, out = list(prompt), []
        for _ in range(n):
            logits = llama.reference_forward(cfg, params, jnp.asarray(toks))
            nxt = int(jnp.argmax(logits[-1]))
            toks.append(nxt)
            out.append(nxt)
        return out

    engine = TpuEngine(
        EngineConfig(
            model=GCFG, num_blocks=64, max_num_seqs=2, max_model_len=128,
            dtype="float32", prefill_chunk=16,
        ),
        params=params,
    )
    await engine.start()
    try:
        tokens = await _collect(engine, prompt, 10)
    finally:
        await engine.stop()
    assert tokens == oracle(GCFG, 10)
    # The 2-pattern is live: making every layer global changes the tokens
    # (ctx 40 > window 32).
    all_global = dataclasses.replace(GCFG, sliding_window=0, window_pattern=0)
    assert tokens != oracle(all_global, 10)


def test_gemma3_multimodal_sparse_text_config(tmp_path):
    """Published multimodal Gemma-3 configs ship sparse text_configs that
    lean on HF defaults — from_hf must fill them, not crash or silently
    disable the window plan (google/gemma-3-4b-it shape)."""
    import json

    (tmp_path / "config.json").write_text(
        json.dumps(
            {
                "architectures": ["Gemma3ForConditionalGeneration"],
                "model_type": "gemma3",
                "text_config": {
                    "hidden_size": 2560,
                    "intermediate_size": 10240,
                    "model_type": "gemma3_text",
                    "num_hidden_layers": 34,
                    "rope_scaling": {"factor": 8.0, "rope_type": "linear"},
                    "sliding_window": 1024,
                },
            }
        )
    )
    cfg = ModelConfig.from_hf(str(tmp_path))
    assert cfg.hidden_size == 2560 and cfg.num_layers == 34
    # HF Gemma3TextConfig defaults fill the gaps:
    assert cfg.num_heads == 8 and cfg.num_kv_heads == 4
    assert cfg.head_dim == 256 and cfg.vocab_size == 262208
    assert cfg.sliding_window == 1024 and cfg.window_pattern == 6
    assert cfg.rope_local_theta == 10_000.0
    assert cfg.rope_scaling is not None and cfg.rope_scaling.kind == "linear"
    assert cfg.layer_window(4) == 1024 and cfg.layer_window(5) == 0

"""SDK tests: the @service/@endpoint/depends() graph model in-process and
split across runtimes, plus the metrics exporter (reference analogues:
deploy/sdk examples/hello_world 3-stage pipeline; components/metrics)."""

import asyncio

import httpx
import pytest

from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.sdk import depends, endpoint, serve_graph, service

pytestmark = pytest.mark.anyio


@service(namespace="demo")
class Backend:
    @endpoint
    async def generate(self, request):
        for word in request["text"].split():
            yield {"word": word.upper()}


@service(namespace="demo")
class Middle:
    backend = depends(Backend)

    @endpoint
    async def generate(self, request):
        i = 0
        async for item in self.backend.generate(request):
            yield {"word": item["word"], "index": i}
            i += 1


@service(namespace="demo")
class Frontend:
    middle = depends(Middle)

    @endpoint
    async def generate(self, request):
        async for item in self.middle.generate(request):
            yield item


def test_graph_structure():
    assert Frontend.dependencies() == {"middle": Middle}
    assert Middle.dependencies() == {"backend": Backend}
    assert Backend.endpoints() == ["generate"]
    assert Middle.endpoint_path("generate") == "dyn://demo.middle.generate"


async def test_three_stage_graph_in_process():
    """The hello_world analogue: Frontend → Middle → Backend, streaming
    through real endpoints/routers on one runtime."""
    drt = await DistributedRuntime.in_process()
    graph = await serve_graph(Frontend, drt)
    try:
        out = []
        handle = graph.instance(Frontend)
        async for item in handle.middle.generate({"text": "hello tpu world"}):
            out.append(item)
        assert out == [
            {"word": "HELLO", "index": 0},
            {"word": "TPU", "index": 1},
            {"word": "WORLD", "index": 2},
        ]
    finally:
        await graph.stop()
        await drt.shutdown()


async def test_graph_split_across_runtimes():
    """The multi-process shape without processes: each service hosted by its
    own runtime sharing one control plane (only={name}), dependencies
    resolved through discovery — code unchanged."""
    main = await DistributedRuntime.in_process()
    drts = [main]
    graphs = []
    for name in ("backend", "middle", "frontend"):
        drt = await DistributedRuntime.in_process(
            store=main.store, bus=main.bus
        )
        drts.append(drt)
        graphs.append(await serve_graph(Frontend, drt, only={name}))
    try:
        # Drive through a fresh consumer runtime, like an external client.
        from dynamo_tpu.sdk import DependencyHandle

        handle = DependencyHandle(main, Frontend)
        out = [item async for item in handle.generate({"text": "a b"})]
        assert out == [{"word": "A", "index": 0}, {"word": "B", "index": 1}]
    finally:
        for g in graphs:
            await g.stop()
        for drt in drts:
            await drt.shutdown()


async def test_http_api_mount():
    @service(namespace="demo2")
    class ApiSvc:
        from dynamo_tpu.sdk import api as _api

        @_api
        async def shout(self, body):
            return {"text": body["text"].upper()}

    drt = await DistributedRuntime.in_process()
    graph = await serve_graph(ApiSvc, drt, http_port=0)
    try:
        port = graph.http_site.addresses[0][1]
        async with httpx.AsyncClient() as client:
            r = await client.post(
                f"http://127.0.0.1:{port}/apisvc/shout",
                json={"text": "quiet"},
            )
            assert r.json() == {"text": "QUIET"}
    finally:
        await graph.stop()
        await drt.shutdown()


async def test_metrics_exporter_scrapes_workers():
    from dynamo_tpu.llm.kv_router.publisher import WorkerMetricsPublisher
    from dynamo_tpu.llm.metrics_exporter import MetricsExporter

    drt = await DistributedRuntime.in_process()
    comp = drt.namespace("dynamo").component("tpu")
    pub = WorkerMetricsPublisher()
    pub.publish(
        {"kv_active_blocks": 7, "kv_total_blocks": 64,
         "gpu_cache_usage_perc": 0.11}
    )
    await pub.create_endpoint(comp)

    exporter = await MetricsExporter(
        drt, host="127.0.0.1", port=0, interval_s=0.05
    ).start()
    try:
        await exporter.aggregator.wait_updated()
        async with httpx.AsyncClient() as client:
            r = await client.get(
                f"http://127.0.0.1:{exporter.port}/metrics"
            )
            assert "dyntpu_worker_count" in r.text
            assert "dyntpu_kv_active_blocks" in r.text
            assert " 7" in r.text
            r = await client.get(
                f"http://127.0.0.1:{exporter.port}/health"
            )
            assert r.json()["workers"]
    finally:
        await exporter.stop()
        await drt.shutdown()


async def test_metrics_exporter_push_mode():
    """PushGateway-style push (reference components/metrics push mode,
    main.rs:85-89): the exporter periodically POSTs its rendered body to
    {push_url}/metrics/job/{job}; a failing gateway only bumps the error
    counter."""
    from aiohttp import web

    from dynamo_tpu.llm.kv_router.publisher import WorkerMetricsPublisher
    from dynamo_tpu.llm.metrics_exporter import MetricsExporter

    received: list[tuple[str, str]] = []

    async def gateway(request: web.Request) -> web.Response:
        received.append((request.path, (await request.read()).decode()))
        return web.Response()

    app = web.Application()
    app.add_routes([web.post("/metrics/job/{job}", gateway)])
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    gw_port = site._server.sockets[0].getsockname()[1]  # noqa: SLF001

    drt = await DistributedRuntime.in_process()
    comp = drt.namespace("dynamo").component("tpu")
    pub = WorkerMetricsPublisher()
    pub.publish({"kv_active_blocks": 3, "kv_total_blocks": 64})
    await pub.create_endpoint(comp)

    exporter = await MetricsExporter(
        drt, host="127.0.0.1", port=0, interval_s=0.05,
        push_url=f"http://127.0.0.1:{gw_port}", push_interval_s=0.05,
        push_job="testjob",
    ).start()
    try:
        await exporter.aggregator.wait_updated()
        for _ in range(100):
            if exporter.push_count >= 2:
                break
            await asyncio.sleep(0.05)
        assert exporter.push_count >= 2
        path, body = received[-1]
        assert path == "/metrics/job/testjob"
        assert "dyntpu_kv_active_blocks" in body
    finally:
        await exporter.stop()
        await runner.cleanup()
        await drt.shutdown()

    # Unreachable gateway: errors counted, exporter survives.
    drt2 = await DistributedRuntime.in_process()
    exporter2 = await MetricsExporter(
        drt2, host="127.0.0.1", port=0, interval_s=0.05,
        push_url="http://127.0.0.1:1", push_interval_s=0.02,
    ).start()
    try:
        for _ in range(100):
            if exporter2.push_errors >= 1:
                break
            await asyncio.sleep(0.05)
        assert exporter2.push_errors >= 1
    finally:
        await exporter2.stop()
        await drt2.shutdown()


async def test_api_store_deployments_and_artifacts():
    """REST registry for deployment specs + artifacts over the control
    plane's object store, exercised cross-process-style through the remote
    client so the new obj_list/obj_del plane ops are covered (reference:
    deploy/cloud/api-store)."""
    import httpx

    from dynamo_tpu.runtime.transports.control_plane import ControlPlaneServer
    from dynamo_tpu.sdk.api_store import ApiStore

    server = await ControlPlaneServer().start()
    drt = await DistributedRuntime.connect(server.address)
    store = await ApiStore(drt, host="127.0.0.1", port=0).start()
    base = f"http://127.0.0.1:{store.port}"
    try:
        async with httpx.AsyncClient() as client:
            spec = {"services": {"Frontend": {"port": 8080}}}
            r = await client.post(
                f"{base}/v1/deployments", json={"name": "agg", "spec": spec}
            )
            assert r.status_code == 201 and r.json()["revision"] == 1
            # Re-publish bumps the revision.
            r = await client.post(
                f"{base}/v1/deployments", json={"name": "agg", "spec": spec}
            )
            assert r.status_code == 200 and r.json()["revision"] == 2

            r = await client.get(f"{base}/v1/deployments")
            assert r.json()["deployments"] == ["agg"]
            r = await client.get(f"{base}/v1/deployments/agg")
            assert r.json()["spec"] == spec

            blob = b"\x00\x01weights"
            r = await client.put(f"{base}/v1/artifacts/model.bin", content=blob)
            assert r.status_code == 201 and r.json()["bytes"] == len(blob)
            r = await client.get(f"{base}/v1/artifacts/model.bin")
            assert r.content == blob
            r = await client.get(f"{base}/v1/artifacts")
            assert r.json()["artifacts"] == ["model.bin"]

            assert (
                await client.delete(f"{base}/v1/deployments/agg")
            ).json()["deleted"]
            assert (
                await client.get(f"{base}/v1/deployments/agg")
            ).status_code == 404
            assert (
                await client.delete(f"{base}/v1/artifacts/model.bin")
            ).json()["deleted"]
            assert (
                await client.delete(f"{base}/v1/artifacts/model.bin")
            ).status_code == 404

            r = await client.post(f"{base}/v1/deployments", json={"name": "x/y", "spec": {}})
            assert r.status_code == 400
    finally:
        await store.stop()
        await drt.shutdown()
        await server.stop()

"""Runtime-core tests: cancellation, leases, discovery, request plane.

Models the reference's runtime tests (reference: lib/runtime/tests/pipeline.rs
+ tests/common/mock.rs — multi-stage pipelines over an in-process network).
"""

import asyncio

import pytest

from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.egress import PushRouter, RouterMode
from dynamo_tpu.runtime.engine import Context, EngineAdapter
from dynamo_tpu.runtime.pipeline import Operator, Pipeline
from dynamo_tpu.runtime.runtime import Runtime
from dynamo_tpu.runtime.transports.store import EventKind, MemoryStore
from dynamo_tpu.utils.cancellation import CancellationToken
from dynamo_tpu.utils.task import CriticalTask

pytestmark = pytest.mark.anyio


async def test_cancellation_tree():
    root = CancellationToken()
    child = root.child_token()
    grandchild = child.child_token()
    child.cancel()
    assert not root.is_cancelled()
    assert child.is_cancelled() and grandchild.is_cancelled()
    root.cancel()
    assert root.is_cancelled()


async def test_critical_task_escalates():
    root = CancellationToken()

    async def boom(token):
        raise RuntimeError("boom")

    task = CriticalTask(boom, root, name="boom")
    await asyncio.sleep(0.05)
    assert task.done()
    assert root.is_cancelled()


async def test_memory_store_lease_expiry_notifies_watch():
    store = MemoryStore()
    lease = await store.grant_lease(0.15)
    await store.put("instances/a", b"1", lease_id=lease)
    watch = await store.watch_prefix("instances/")
    assert watch.initial == {"instances/a": b"1"}
    # No keepalive → lease expires → key deleted → watcher notified.
    ev = await asyncio.wait_for(watch.__anext__(), timeout=2.0)
    assert ev.kind is EventKind.DELETE
    assert ev.key == "instances/a"


async def test_store_create_exclusive():
    store = MemoryStore()
    assert await store.create("k", b"a")
    assert not await store.create("k", b"b")
    assert await store.get("k") == b"a"


async def _echo_engine(ctx: Context):
    for tok in ctx.payload["tokens"]:
        yield {"token": tok, "worker": "w"}


async def test_endpoint_serve_and_route():
    drt = await DistributedRuntime.in_process()
    try:
        ep = drt.namespace("test").component("echo").endpoint("generate")
        await ep.serve(EngineAdapter(_echo_engine))

        router = await PushRouter.create(drt, ep.id, RouterMode.ROUND_ROBIN)
        out = []
        async for item in router.generate(Context({"tokens": [1, 2, 3]})):
            out.append(item["token"])
        assert out == [1, 2, 3]
    finally:
        await drt.shutdown()


async def test_two_workers_round_robin():
    drt1 = await DistributedRuntime.in_process()
    drt2 = await DistributedRuntime.in_process(
        runtime=drt1.runtime, store=drt1.store, bus=drt1.bus
    )
    try:
        for i, drt in enumerate((drt1, drt2)):
            async def engine(ctx, i=i):
                yield {"worker": i}

            ep = drt.namespace("test").component("multi").endpoint("generate")
            await ep.serve(EngineAdapter(engine))

        router = await PushRouter.create(
            drt1, "dyn://test.multi.generate", RouterMode.ROUND_ROBIN
        )
        assert len(router.client.instances()) == 2
        seen = set()
        for _ in range(4):
            async for item in router.generate(Context({})):
                seen.add(item["worker"])
        assert seen == {0, 1}
    finally:
        await drt1.shutdown()


async def test_worker_death_removes_instance():
    drt1 = await DistributedRuntime.in_process()
    drt2 = await DistributedRuntime.in_process(
        runtime=Runtime(), store=drt1.store, bus=drt1.bus
    )
    try:
        ep = drt2.namespace("test").component("dying").endpoint("generate")
        await ep.serve(EngineAdapter(_echo_engine))

        router = await PushRouter.create(drt1, ep.id)
        assert len(await router.client.wait_for_instances()) == 1

        await drt2.shutdown()  # revokes lease → instance key deleted
        await asyncio.sleep(0.05)
        assert router.client.instances() == []
    finally:
        await drt1.shutdown()


async def test_engine_error_propagates():
    drt = await DistributedRuntime.in_process()
    try:
        async def bad_engine(ctx):
            yield {"ok": 1}
            raise ValueError("engine exploded")

        ep = drt.namespace("test").component("bad").endpoint("generate")
        await ep.serve(EngineAdapter(bad_engine))
        router = await PushRouter.create(drt, ep.id)
        with pytest.raises(RuntimeError, match="engine exploded"):
            async for _ in router.generate(Context({})):
                pass
    finally:
        await drt.shutdown()


class _Doubler(Operator):
    async def generate(self, request, downstream):
        req = request.map({"tokens": [t * 2 for t in request.payload["tokens"]]})
        async for item in downstream.generate(req):
            yield {**item, "doubled": True}


async def test_pipeline_operator_bidirectional():
    pipeline = Pipeline.link(_Doubler(), engine=EngineAdapter(_echo_engine))
    out = [item async for item in pipeline.generate(Context({"tokens": [1, 2]}))]
    assert [o["token"] for o in out] == [2, 4]
    assert all(o["doubled"] for o in out)


async def test_pipeline_graph_segments_switch_tap():
    """Graph mechanics beyond the linear chain (reference: pipeline
    nodes.rs:16-120 link() composition): reusable Segments, request-path
    branching via Switch, and non-transforming Taps on both directions."""
    from dynamo_tpu.runtime.pipeline import Operator, Segment, Switch, Tap

    class Add(Operator):
        def __init__(self, tag):
            self.tag = tag

        async def generate(self, request, downstream):
            async for item in downstream.generate(
                request.map(request.payload + [self.tag])
            ):
                yield item + [self.tag]

    class Terminal:
        def __init__(self, name):
            self.name = name
            self.seen = []

        async def generate(self, request):
            self.seen.append(request.payload)
            yield [self.name]

    # Shared segment linked into two different pipelines.
    common = Segment(Add("a")).link(Add("b"))
    t1, t2 = Terminal("t1"), Terminal("t2")
    p1 = common.into(t1)
    p2 = common.link(Add("c")).into(t2)
    out1 = [x async for x in p1.generate(Context([]))]
    out2 = [x async for x in p2.generate(Context([]))]
    assert out1 == [["t1", "b", "a"]]
    assert t1.seen == [["a", "b"]]
    assert out2 == [["t2", "c", "b", "a"]]
    assert t2.seen == [["a", "b", "c"]]

    # Switch routes by request; Tap observes both directions untouched.
    text, vision = Terminal("text"), Terminal("vision")
    reqs, resps = [], []
    sw = Switch(
        lambda req: "vision" if "img" in req.payload else "text",
        {"text": text, "vision": vision},
        default="text",
    )
    pipe = Segment(
        Tap(lambda r: reqs.append(r.payload),
            lambda r, item: resps.append(item)),
        Add("pre"),
    ).into(sw)
    assert [x async for x in pipe.generate(Context(["img"]))] == [
        ["vision", "pre"]
    ]
    assert [x async for x in pipe.generate(Context(["hello"]))] == [
        ["text", "pre"]
    ]
    assert vision.seen == [["img", "pre"]] and text.seen == [["hello", "pre"]]
    assert reqs == [["img"], ["hello"]]
    assert resps == [["vision", "pre"], ["text", "pre"]]

    # Unknown branch without a default is loud.
    sw2 = Switch(lambda r: "nope", {"only": text})
    with pytest.raises(LookupError):
        async for _ in sw2.generate(Context([])):
            pass

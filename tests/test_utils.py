"""Runtime utility tests: generic rotating recorder (reference:
lib/llm/src/recorder.rs) and the generic object pool (reference:
lib/runtime/src/utils/pool.rs)."""

import asyncio
import json

import pytest

from dynamo_tpu.utils.pool import Pool
from dynamo_tpu.utils.recorder import Recorder

pytestmark = pytest.mark.anyio


def test_recorder_rotation_preserves_order(tmp_path):
    path = tmp_path / "events.jsonl"
    # Each record is ~40 bytes; cap files at ~3 records each.
    with Recorder(path, max_bytes=130, max_files=3) as rec:
        for i in range(10):
            rec.record({"seq": i})
    files = Recorder.files(path)
    assert len(files) == 3  # 1 active + 2 rotated; oldest fell off
    events = [ev["seq"] for _, ev in Recorder.load(path)]
    # Oldest generations dropped; surviving events are in order with no gaps.
    assert events == list(range(events[0], 10))
    assert len(events) < 10  # rotation really dropped something


def test_recorder_max_events(tmp_path):
    path = tmp_path / "capped.jsonl"
    with Recorder(path, max_events=3) as rec:
        for i in range(10):
            rec.record(i)
    assert [ev for _, ev in Recorder.load(path)] == [0, 1, 2]


async def test_recorder_replay_timed(tmp_path):
    path = tmp_path / "replay.jsonl"
    with Recorder(path) as rec:
        rec.record({"a": 1})
        rec.record({"a": 2})
    # Fake timestamps 50ms apart to verify timed replay sleeps.
    lines = [json.loads(line) for line in path.read_text().splitlines()]
    lines[1]["ts"] = lines[0]["ts"] + 0.05
    path.write_text("\n".join(json.dumps(d) for d in lines) + "\n")

    seen = []
    t0 = asyncio.get_running_loop().time()
    n = await Recorder.replay(path, seen.append, timed=True)
    assert n == 2 and seen == [{"a": 1}, {"a": 2}]
    assert asyncio.get_running_loop().time() - t0 >= 0.05


async def test_pool_reuse_and_capacity():
    built = []

    def factory():
        built.append(object())
        return built[-1]

    pool = Pool(factory, capacity=2)
    g1 = await pool.acquire()
    g2 = await pool.acquire()
    assert pool.size == 2 and pool.idle == 0

    # Capacity exhausted: third acquire blocks until a release.
    third = asyncio.ensure_future(pool.acquire())
    await asyncio.sleep(0.01)
    assert not third.done()
    g1.release()
    g3 = await asyncio.wait_for(third, 1.0)
    assert g3.item is g1.item  # reused, not rebuilt
    assert len(built) == 2

    # Guard context manager returns the item.
    g3.release()
    g2.release()
    async with await pool.acquire() as item:
        assert item in built
    assert pool.idle == 2


async def test_pool_detach_frees_slot():
    n = [0]

    def factory():
        n[0] += 1
        return n[0]

    pool = Pool(factory, capacity=1)
    g = await pool.acquire()
    assert g.detach() == 1  # broken object removed from pool
    g2 = await pool.acquire()  # slot freed -> fresh build
    assert g2.item == 2
    g2.release()
    assert pool.idle == 1


async def test_pool_async_factory_and_failure():
    calls = [0]

    async def factory():
        calls[0] += 1
        if calls[0] == 1:
            raise RuntimeError("first build fails")
        return "ok"

    pool = Pool(factory, capacity=1)
    with pytest.raises(RuntimeError):
        await pool.acquire()
    # Failed build released its reserved slot — retry succeeds.
    g = await pool.acquire()
    assert g.item == "ok"
    g.release()


async def test_pool_reset_failure_discards_without_leaking_slot():
    """A reset hook that raises marks the item broken: it's dropped, the
    capacity slot is reclaimed, and acquire proceeds with a fresh build."""
    builds = [0]

    def factory():
        builds[0] += 1
        return builds[0]

    def reset(item):
        if item == 1:
            raise RuntimeError("stale connection")

    pool = Pool(factory, capacity=1, reset=reset)
    g = await pool.acquire()
    g.release()
    g2 = await pool.acquire()  # reset(1) raises -> discard -> rebuild
    assert g2.item == 2
    assert pool.size == 1  # no leaked slot
    g2.release()


async def test_pool_reset_hook():
    resets = []
    pool = Pool(lambda: "x", capacity=1, reset=resets.append)
    g = await pool.acquire()
    g.release()
    g2 = await pool.acquire()
    assert resets == ["x"]  # reset ran on reuse, not first build
    g2.release()


def test_tracer_marks_intervals_render():
    from dynamo_tpu.utils.tracing import Tracer

    tr = Tracer()
    tr.mark("r1", "received")
    tr.mark("r1", "engine_queued")
    tr.mark("r1", "first_token")
    tr.mark("r1", "first_token")  # marks are first-write-wins
    assert tr.finish("r1") is not None
    assert tr.finish("r1") is None  # idempotent

    s = tr.summary()
    assert set(s) == {"ttft", "engine", "decode", "total"}
    assert s["total"]["count"] == 1
    # Exact maxes (bucket-free) preserve the interval containment the
    # old two-point summary asserted: received→finished spans
    # first_token→finished.
    assert s["total"]["max_ms"] >= s["decode"]["max_ms"]
    assert s["total"]["p50_ms"] <= s["total"]["max_ms"]

    # Real bucketed histograms on /metrics, not a two-point summary.
    text = tr.render()
    assert 'dyntpu_trace_ttft_ms_bucket{le="5"}' in text
    assert 'dyntpu_trace_ttft_ms_bucket{le="+Inf"} 1' in text
    assert "dyntpu_trace_total_ms_count 1" in text
    assert "dyntpu_trace_abandoned_traces_total 0" in text

    # A trace missing marks only contributes to intervals it has.
    tr.mark("r2", "received")
    tr.finish("r2")
    assert tr.summary()["total"]["count"] == 2
    assert tr.summary()["ttft"]["count"] == 1


def test_tracer_capture_to_disk(tmp_path):
    from dynamo_tpu.utils.tracing import Tracer

    path = tmp_path / "trace.jsonl"
    tr = Tracer(record_path=str(path))
    tr.mark("a", "received")
    with tr.span("a", "admission"):
        pass
    tr.finish("a")
    rows = [ev for _, ev in Recorder.load(path)]
    kinds = [r["kind"] for r in rows]
    assert kinds == ["span", "finish"]  # spans stream out as they close
    fin = rows[-1]
    assert fin["id"] == "a" and fin["trace"]
    assert "received" in fin["marks"] and "finished" in fin["marks"]
    assert fin["spans"][0]["name"] == "admission"
    # Marks are exported as absolute wall-clock instants (cross-process
    # sortable by trace_merge).
    assert fin["marks"]["received"] > 1e9

"""Beyond-one-chip contexts: the paged KV cache's slot axis sharded over
the sp mesh axis (VERDICT r03 #6; SURVEY §5 long-context row).

The engine mode under test: mesh {"sp": n} + EngineConfig.kv_sp=True puts
1/n of the cache slots on each device and runs attention as per-shard
flash partials merged with a logsumexp combine (ops/attention.py
paged_*_attention_sp) — per-call communication is O(query), never
O(cache). The serving proof: a sequence whose KV provably exceeds ONE
device's cache arrays decodes token-identically to a replicated-cache
oracle engine.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.parallel.mesh import build_mesh
from dynamo_tpu.runtime.engine import Context

pytestmark = pytest.mark.anyio

CFG = ModelConfig.tiny_test()
PARAMS = llama.init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)


def test_sp_attention_matches_replicated_oracle():
    """Unit parity: slot-sharded decode/prefill attention vs the
    replicated-cache reference on a random paged cache."""
    from jax.sharding import PartitionSpec as P

    from dynamo_tpu.utils.jax_compat import shard_map

    from dynamo_tpu.ops.attention import (
        paged_decode_attention,
        paged_decode_attention_sp,
        paged_prefill_attention,
        paged_prefill_attention_sp,
    )

    mesh = build_mesh({"sp": 4, "dp": 2})
    rng = np.random.default_rng(0)
    bs, nblocks, kvH, H, D = 4, 16, 2, 4, 8
    slots = nblocks * bs
    k_cache = jnp.asarray(rng.standard_normal((slots, kvH, D)), jnp.float32)
    v_cache = jnp.asarray(rng.standard_normal((slots, kvH, D)), jnp.float32)
    B = 3
    ctx = np.asarray([13, 30, 0], np.int32)
    tables = np.zeros((B, 8), np.int32)
    tables[0, :4] = [1, 2, 3, 4]
    tables[1, :8] = [5, 6, 7, 8, 9, 10, 11, 12]
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)

    want = paged_decode_attention(
        q, k_cache, v_cache, jnp.asarray(tables), jnp.asarray(ctx), bs
    )
    sp_cache = P("sp", None, None)
    got = shard_map(
        lambda *a: paged_decode_attention_sp(*a, block_size=bs),
        mesh=mesh,
        in_specs=(P(), sp_cache, sp_cache, P(), P()),
        out_specs=P(),
        check_vma=False,
    )(q, k_cache, v_cache, jnp.asarray(tables), jnp.asarray(ctx))
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )

    # Prefill: lane 0 extends a 5-token prefix by 8 new tokens.
    T = 8
    qp = jnp.asarray(rng.standard_normal((1, T, H, D)), jnp.float32)
    bt = jnp.asarray(tables[1][None])
    q_start = jnp.asarray([5])
    total = jnp.asarray([13])
    want_p = jax.vmap(
        lambda qq, b, ps, tl: paged_prefill_attention(
            qq, k_cache, v_cache, b, ps, tl, bs
        )
    )(qp, bt, q_start, total)
    got_p = shard_map(
        lambda *a: paged_prefill_attention_sp(*a, block_size=bs),
        mesh=mesh,
        in_specs=(P(), sp_cache, sp_cache, P(), P(), P()),
        out_specs=P(),
        check_vma=False,
    )(qp, k_cache, v_cache, bt, q_start, total)
    np.testing.assert_allclose(
        np.asarray(got_p), np.asarray(want_p), rtol=2e-5, atol=2e-5
    )


async def _generate(engine, prompt, max_tokens):
    req = PreprocessedRequest(
        token_ids=prompt,
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
    )
    toks = []
    async for item in engine.generate(Context(req.to_wire())):
        toks += item["token_ids"]
    return toks


async def test_engine_serves_context_beyond_one_devices_cache():
    """The gate: with 160 total slots sharded 40/device over sp=4, serve a
    sequence needing 130 slots — more than ANY single device's cache
    arrays hold — and match the replicated-cache oracle exactly."""
    mesh = build_mesh({"sp": 4, "dp": 2})
    sp_cfg = EngineConfig(
        model=CFG, dtype="float32", block_size=4, num_blocks=40,
        max_num_seqs=2, max_model_len=144, kv_sp=True,
    )
    oracle_cfg = EngineConfig(
        model=CFG, dtype="float32", block_size=4, num_blocks=64,
        max_num_seqs=2, max_model_len=144,
    )
    prompt = [int(x) for x in
              np.random.default_rng(7).integers(1, CFG.vocab_size, 100)]
    OUT = 30

    oracle = TpuEngine(oracle_cfg, params=PARAMS)
    await oracle.start()
    expected = await _generate(oracle, prompt, OUT)
    await oracle.stop()

    engine = TpuEngine(sp_cfg, params=PARAMS, mesh=mesh)
    await engine.start()
    try:
        # Proof of the capacity claim: each device holds 1/4 of the slots.
        k0 = engine.runner.kv_caches[0][0]
        shard_slots = {
            s.data.shape[0] for s in k0.addressable_shards
        }
        assert shard_slots == {40 * 4 // 4}, shard_slots  # 40 slots/device
        total_needed = len(prompt) + OUT  # 130 > 40 per-device slots
        assert total_needed > 40

        got = await _generate(engine, prompt, OUT)
        assert got == expected, "sp-sharded serving diverged from oracle"
    finally:
        await engine.stop()


def test_kv_sp_validation():
    with pytest.raises(ValueError, match="sp > 1"):
        from dynamo_tpu.engine.runner import ModelRunner

        ModelRunner(
            EngineConfig(
                model=CFG, dtype="float32", block_size=4, num_blocks=40,
                max_num_seqs=2, max_model_len=64, kv_sp=True,
            ),
            params=PARAMS,
        )


def _striped_tables(rng, sp: int, nblocks: int, lane_pages: list[int], width: int):
    """Block tables satisfying the striped allocator's contract: logical
    page i of a lane drawn from shard (i % sp)'s physical range, each
    physical block used once (block 0 reserved for trash)."""
    bps = nblocks // sp
    pools = [
        list(range(s * bps + (1 if s == 0 else 0), (s + 1) * bps))
        for s in range(sp)
    ]
    for p in pools:
        rng.shuffle(p)
    tables = np.zeros((len(lane_pages), width), np.int32)
    for lane, n in enumerate(lane_pages):
        for i in range(n):
            tables[lane, i] = pools[i % sp].pop()
    return tables


@pytest.mark.parametrize("use_pallas", [False, True])
def test_sp_striped_scan_matches_oracle(use_pallas):
    """The r05 striped scan (each sp shard visits ONLY its own stripe of
    logical pages — FLOPs partition sp-ways) against the replicated
    oracle, with tp head-sharding composed in, on both the jnp and the
    Pallas (interpret) paths. Pallas needs D % 128 == 0, so the oracle
    runs on a lane-padded cache too (the production envelope)."""
    from dynamo_tpu.ops.attention import (
        AttnDispatch,
        paged_decode_attention,
        paged_prefill_attention,
    )

    mesh = build_mesh({"sp": 2, "tp": 2, "dp": 2})
    rng = np.random.default_rng(1)
    bs, nblocks, kvH, H = 4, 16, 2, 4
    D = 128 if use_pallas else 8
    slots = nblocks * bs
    k_cache = jnp.asarray(rng.standard_normal((slots, kvH, D)), jnp.float32)
    v_cache = jnp.asarray(rng.standard_normal((slots, kvH, D)), jnp.float32)
    B = 3
    ctx = np.asarray([13, 30, 0], np.int32)
    tables = _striped_tables(rng, 2, nblocks, [4, 8, 0], width=8)
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)

    want = paged_decode_attention(
        q, k_cache, v_cache, jnp.asarray(tables), jnp.asarray(ctx), bs
    )
    disp = AttnDispatch(use_pallas=use_pallas, mesh=mesh, kv_sp=True)
    got = disp.decode(
        q, k_cache, v_cache, jnp.asarray(tables), jnp.asarray(ctx), bs
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )

    # Prefill: lane extends a 5-token prefix by 8 new tokens.
    T = 8
    qp = jnp.asarray(rng.standard_normal((1, T, H, D)), jnp.float32)
    bt = jnp.asarray(tables[1][None])
    q_start = jnp.asarray([5])
    total = jnp.asarray([13])
    want_p = jax.vmap(
        lambda qq, b, ps, tl: paged_prefill_attention(
            qq, k_cache, v_cache, b, ps, tl, bs
        )
    )(qp, bt, q_start, total)
    got_p = disp.prefill(qp, k_cache, v_cache, bt, q_start, total, bs)
    np.testing.assert_allclose(
        np.asarray(got_p), np.asarray(want_p), rtol=2e-5, atol=2e-5
    )


async def test_engine_kv_sp_composes_with_tp():
    """The r04 VERDICT gate: a {tp: 2, sp: 2} kv_sp engine — heads
    sharded over tp AND slots over sp, striped allocator — serves
    token-identically to the replicated single-chip oracle. This is the
    mode a model too big for one chip needs for beyond-chip contexts."""
    mesh = build_mesh({"sp": 2, "tp": 2, "dp": 2})
    sp_cfg = EngineConfig(
        model=CFG, dtype="float32", block_size=4, num_blocks=40,
        max_num_seqs=2, max_model_len=144, kv_sp=True,
    )
    oracle_cfg = EngineConfig(
        model=CFG, dtype="float32", block_size=4, num_blocks=64,
        max_num_seqs=2, max_model_len=144,
    )
    prompt = [int(x) for x in
              np.random.default_rng(11).integers(1, CFG.vocab_size, 100)]
    OUT = 30

    oracle = TpuEngine(oracle_cfg, params=PARAMS)
    await oracle.start()
    expected = await _generate(oracle, prompt, OUT)
    await oracle.stop()

    engine = TpuEngine(sp_cfg, params=PARAMS, mesh=mesh)
    await engine.start()
    try:
        # Capacity claim: each device holds 1/2 the slots AND 1/2 the
        # kv heads — per-device KV bytes are 1/(sp*tp) of the total.
        k0 = engine.runner.kv_caches[0][0]
        shard_shapes = {s.data.shape for s in k0.addressable_shards}
        assert shard_shapes == {(40 * 4 // 2, 1, CFG.head_dim)}, shard_shapes
        got = await _generate(engine, prompt, OUT)
        assert got == expected, "tp x sp kv_sp serving diverged from oracle"
    finally:
        await engine.stop()


async def test_engine_kv_sp_pallas_path(monkeypatch):
    """kv_sp engine with the Pallas kernels active (interpret mode on
    CPU): per-shard kernel over the compacted stripe + logsumexp merge
    must reproduce the oracle's tokens exactly. block_size=8 so the
    per-shard (bs * local kvH) hits the f32 sublane multiple the compiled
    kernel envelope requires (ops/pallas/attention.py pallas_supported)."""
    mesh = build_mesh({"sp": 2, "tp": 2, "dp": 2})
    sp_cfg = EngineConfig(
        model=CFG, dtype="float32", block_size=8, num_blocks=16,
        max_num_seqs=2, max_model_len=48, kv_sp=True,
    )
    oracle_cfg = EngineConfig(
        model=CFG, dtype="float32", block_size=8, num_blocks=24,
        max_num_seqs=2, max_model_len=48,
    )
    prompt = [int(x) for x in
              np.random.default_rng(3).integers(1, CFG.vocab_size, 20)]
    OUT = 8

    oracle = TpuEngine(oracle_cfg, params=PARAMS)
    await oracle.start()
    expected = await _generate(oracle, prompt, OUT)
    await oracle.stop()

    monkeypatch.setenv("DYNAMO_TPU_PALLAS", "1")
    engine = TpuEngine(sp_cfg, params=PARAMS, mesh=mesh)
    await engine.start()
    try:
        assert engine.runner.attn.use_pallas, "Pallas path not engaged"
        got = await _generate(engine, prompt, OUT)
        assert got == expected, "kv_sp Pallas serving diverged from oracle"
    finally:
        await engine.stop()


async def test_engine_kv_sp_via_mesh_shape():
    """The CLI flow: no mesh object handed to the engine — the runner
    builds it from cfg.mesh_shape. The allocator must still stripe
    (review r05 finding: this path silently got an unstriped allocator
    while the runner ran the striped scan)."""
    sp_cfg = EngineConfig(
        model=CFG, dtype="float32", block_size=4, num_blocks=40,
        max_num_seqs=2, max_model_len=144, kv_sp=True,
        mesh_shape={"sp": 2, "dp": 4},
    )
    oracle_cfg = EngineConfig(
        model=CFG, dtype="float32", block_size=4, num_blocks=64,
        max_num_seqs=2, max_model_len=144,
    )
    prompt = [int(x) for x in
              np.random.default_rng(5).integers(1, CFG.vocab_size, 60)]
    OUT = 12

    oracle = TpuEngine(oracle_cfg, params=PARAMS)
    await oracle.start()
    expected = await _generate(oracle, prompt, OUT)
    await oracle.stop()

    engine = TpuEngine(sp_cfg, params=PARAMS)
    await engine.start()
    try:
        assert engine.allocator.num_shards == 2
        got = await _generate(engine, prompt, OUT)
        assert got == expected, "mesh_shape kv_sp serving diverged"
    finally:
        await engine.stop()


def test_striped_allocator_contract():
    """BlockAllocator(num_shards=n): logical block i lands in shard
    (i % n)'s physical range; exhausting one shard raises even while
    others have space; prefix-matched chains keep the striping."""
    from dynamo_tpu.engine.kv_cache import BlockAllocator

    alloc = BlockAllocator(16, 4, num_shards=4)  # 4 blocks/shard
    seq = alloc.allocate_many(8, first_logical=0)
    for i, b in enumerate(seq):
        assert alloc.shard_of(b) == i % 4, (i, b)
    # Shard 0 has 4 blocks minus trash block 0 = 3; two sequences used 2.
    alloc.allocate(0)  # last shard-0 block
    with pytest.raises(MemoryError, match="shard 0"):
        alloc.allocate(4)  # logical 4 -> shard 0 again: dry
    # Other shards still serve.
    assert alloc.shard_of(alloc.allocate(1)) == 1
    # Logical index is required under striping.
    with pytest.raises(TypeError):
        alloc.allocate()

"""Compile-lifecycle subsystem tests (engine/compile_cache.py):

- shape-manifest roundtrip: record → save → load → warm-plan pruning,
  with fingerprint staleness guarding
- persistent-cache fingerprint namespacing + ledger persistence, and the
  second-cold-start speedup (counting stub — no TPU present)
- readiness gating: warmup_gate="hold" parks admission until the hot set
  is warm; "degraded" serves immediately and flags it
- mid-traffic-compile counter incrementing on an un-warmed shape, and
  staying zero on a warmed engine (real CPU runner)
- /health 503-while-warming + compile gauges on /metrics
"""

import asyncio
import os
import time

import numpy as np
import pytest

from dynamo_tpu.engine.compile_cache import (
    CompileStats,
    PersistentCompileCache,
    ShapeManifest,
    default_shape_grid,
    engine_fingerprint,
    fingerprint_key,
    shape_key,
    split_plan,
)
from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.mocker.engine import MockerConfig, MockerEngine
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.runtime.engine import Context

pytestmark = pytest.mark.anyio


def _cfg(**kw) -> EngineConfig:
    defaults = dict(
        model=ModelConfig.tiny_test(),
        num_blocks=128,
        max_num_seqs=4,
        max_model_len=128,
        prefill_chunk=128,
        decode_chunk=4,
        prefill_batch=4,
    )
    defaults.update(kw)
    return EngineConfig(**defaults)


def _req(n_prompt: int, max_tokens: int = 4) -> dict:
    return PreprocessedRequest(
        token_ids=list(range(1, n_prompt + 1)),
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
    ).to_wire()


async def _collect(engine, n_prompt: int, max_tokens: int = 4) -> int:
    n = 0
    async for out in engine.generate(Context(_req(n_prompt, max_tokens))):
        n += len(out["token_ids"])
    return n


# ---------------------------------------------------------------------------
# manifest
# ---------------------------------------------------------------------------


def test_manifest_roundtrip_and_fingerprint_guard(tmp_path):
    m = ShapeManifest()
    for _ in range(5):
        m.record("unified", t=128)
    m.record("unified", t=64)
    m.record("unified_full", t=128)
    path = str(tmp_path / "manifest.json")
    m.save(path, "fp-a")

    loaded = ShapeManifest.load(path, "fp-a")
    assert loaded is not None
    assert loaded.count_of(shape_key("unified", t=128)) == 5
    assert loaded.count_of(shape_key("unified_full", t=128)) == 1

    # A manifest written under a different engine fingerprint must be
    # ignored (stale shapes would warm the wrong programs).
    assert ShapeManifest.load(path, "fp-b") is None
    assert ShapeManifest.load(str(tmp_path / "missing.json"), "fp-a") is None


def test_split_plan_orders_unified_grid(tmp_path):
    """The unified grid: every budget rung is a decode-criticality shape
    (any running lane can land on any rung), so the WHOLE family stays
    hot under a manifest — its value is ORDERING: observed rungs warm
    first, by observed count."""
    cfg = _cfg()
    specs = default_shape_grid(cfg)
    keys = [shape_key(*s) for s in specs]
    assert all(k.startswith("unified") for k in keys)

    m = ShapeManifest()
    for _ in range(9):
        m.record("unified", t=64)
    m.record("unified", t=16)
    hot, tail = split_plan(specs, m)
    hot_keys = [shape_key(*s) for s in hot]
    # Everything stays hot (unified kinds are all decode-critical)...
    assert not tail
    assert set(hot_keys) == set(keys)
    # ...and the dominant observed rung warms before the rare one, which
    # warms before the never-observed rest of the ladder.
    assert hot_keys.index(shape_key("unified", t=64)) < hot_keys.index(
        shape_key("unified", t=16)
    )
    assert hot_keys.index(shape_key("unified", t=16)) < hot_keys.index(
        shape_key("unified", t=32)
    )


def test_fingerprint_tracks_compile_relevant_config():
    a = fingerprint_key(engine_fingerprint(_cfg()))
    assert a == fingerprint_key(engine_fingerprint(_cfg()))  # stable
    assert a != fingerprint_key(engine_fingerprint(_cfg(quant="int8")))
    assert a != fingerprint_key(engine_fingerprint(_cfg(max_num_seqs=8)))
    assert a != fingerprint_key(
        engine_fingerprint(_cfg(mesh_shape={"tp": 2}))
    )


# ---------------------------------------------------------------------------
# persistent cache
# ---------------------------------------------------------------------------


def test_cache_ledger_persists_per_fingerprint(tmp_path):
    base = str(tmp_path)
    fp_a = engine_fingerprint(_cfg())
    cache = PersistentCompileCache(base, fp_a)
    assert not cache.has("prefill:t64")
    cache.note("prefill:t64")
    cache.flush()
    # A new instance over the same dir (a relaunched process) sees it.
    again = PersistentCompileCache(base, fp_a)
    assert again.has("prefill:t64")
    assert again.num_ledger_entries == 1
    # A different fingerprint namespaces into a different directory.
    other = PersistentCompileCache(base, engine_fingerprint(_cfg(quant="int8")))
    assert other.dir != cache.dir
    assert not other.has("prefill:t64")


class _StubWarmRunner:
    """Counting stub standing in for XLA when no TPU is present: a shape
    whose key is in the persistent-cache ledger 'replays from disk'
    (fast); a fresh one 'compiles' (slow). Drives the real CompileStats /
    ledger machinery end to end."""

    COMPILE_S = 0.02
    REPLAY_S = 0.0005

    def __init__(self, cache: PersistentCompileCache) -> None:
        self.compile_stats = CompileStats(cache=cache)

    def warm(self, keys: list[str]) -> float:
        cs = self.compile_stats
        t0 = time.monotonic()
        cs.warming = True
        try:
            for key in keys:
                with cs.observe("stub", t=int(key)):
                    time.sleep(
                        self.REPLAY_S
                        if cs.cache.has(shape_key("stub", t=int(key)))
                        else self.COMPILE_S
                    )
        finally:
            cs.warming = False
            cs.cache.flush()
        return time.monotonic() - t0


def test_second_cold_start_replays_from_cache(tmp_path):
    """Acceptance: a second cold-start warmup against a populated
    persistent cache completes >= 5x faster than the first."""
    fp = engine_fingerprint(_cfg())
    keys = [str(i) for i in range(16, 32)]

    first = _StubWarmRunner(PersistentCompileCache(str(tmp_path), fp))
    t_first = first.warm(keys)
    assert first.compile_stats.warmed_programs == len(keys)
    assert first.compile_stats.replayed_programs == 0

    # Fresh process: new stats + new cache instance, same directory.
    second = _StubWarmRunner(PersistentCompileCache(str(tmp_path), fp))
    t_second = second.warm(keys)
    assert second.compile_stats.replayed_programs == len(keys)
    assert second.compile_stats.mid_traffic_compiles == 0
    assert t_first / t_second >= 5.0


# ---------------------------------------------------------------------------
# readiness gating + mid-traffic accounting (device-free mocker)
# ---------------------------------------------------------------------------


async def test_hold_gate_parks_admission_until_warm():
    engine = MockerEngine(_cfg(warmup_gate="hold"), MockerConfig())
    await engine.start()
    try:
        assert engine.state == "warming" and not engine.is_ready
        task = asyncio.create_task(_collect(engine, n_prompt=8))
        await asyncio.sleep(0.15)
        # Held: the request is queued, not served (and nothing compiled).
        assert not task.done()
        assert engine.runner.compile_stats.seen == set()
        n = await engine.warmup()
        assert n > 0 and engine.is_ready and engine.state == "ready"
        assert await asyncio.wait_for(task, timeout=10) == 4
        assert not engine.served_unwarmed
    finally:
        await engine.stop()


async def test_degraded_gate_serves_and_flags():
    engine = MockerEngine(_cfg(warmup_gate="degraded"), MockerConfig())
    await engine.start()
    try:
        assert engine.state == "warming"
        assert await _collect(engine, n_prompt=8) == 4
        assert engine.state == "ready" and engine.served_unwarmed
        # Un-warmed serving is exactly what the counter exists to expose.
        assert engine.runner.compile_stats.mid_traffic_compiles > 0
    finally:
        await engine.stop()


async def test_mid_traffic_counter_on_unwarmed_shape():
    engine = MockerEngine(_cfg(), MockerConfig())
    await engine.start()
    try:
        # Warm ONLY the bottom of the budget ladder (16/32); a prompt
        # whose batch snaps to the un-warmed 64 rung then compiles
        # mid-traffic and the counters must say so.
        r = engine.runner
        hot, tail = r.warmup_plan()
        small = [
            (key, op) for key, op in hot + tail
            if key in ("unified:t16", "unified:t32")
        ]
        r.run_warm_ops(small)
        engine._state = "ready"
        cs = r.compile_stats
        assert cs.mid_traffic_compiles == 0
        await _collect(engine, n_prompt=16)
        assert cs.mid_traffic_compiles == 0  # covered rungs: free
        await _collect(engine, n_prompt=50)
        assert cs.mid_traffic_compiles >= 1
        assert any("t64" in k for k in cs.mid_traffic_keys)
        stall_after_first = cs.compile_stall_ms_total
        assert stall_after_first > 0
        await _collect(engine, n_prompt=50)  # same shape again: no compile
        assert cs.compile_stall_ms_total == stall_after_first
        assert engine.readiness()["mid_traffic_compiles_total"] >= 1
    finally:
        await engine.stop()


async def test_manifest_saved_on_stop_and_drives_next_warmup(tmp_path):
    path = str(tmp_path / "manifest.json")
    cfg = _cfg(shape_manifest_path=path)
    engine = MockerEngine(cfg, MockerConfig())
    await engine.start()
    await engine.warmup()
    await _collect(engine, n_prompt=40)
    await engine.stop()
    assert os.path.exists(path)

    relaunch = MockerEngine(_cfg(shape_manifest_path=path), MockerConfig())
    await relaunch.start()
    try:
        n_hot = await relaunch.warmup()
        # Every unified rung is decode-critical, so the whole grid stays
        # hot — the manifest's value is ORDERING (observed rungs first)
        # and the zero-mid-traffic replay below.
        assert n_hot == len(default_shape_grid(cfg))
        assert relaunch.is_ready
        # The 40-token prompt's rung was observed and therefore warmed.
        observed = shape_key("unified", t=64)
        assert observed in relaunch.runner.compile_stats.seen
        for _ in range(100):
            if relaunch.warm_tail_pending == 0:
                break
            await asyncio.sleep(0.05)
        assert relaunch.warm_tail_pending == 0
        # Serving the same workload again compiles nothing mid-traffic.
        await _collect(relaunch, n_prompt=40)
        assert relaunch.runner.compile_stats.mid_traffic_compiles == 0
    finally:
        await relaunch.stop()


# ---------------------------------------------------------------------------
# real CPU runner: warmed engine serves with zero mid-traffic compiles
# ---------------------------------------------------------------------------


async def test_real_runner_warmup_covers_serving_shapes():
    from dynamo_tpu.engine.engine import TpuEngine

    engine = TpuEngine(_cfg(
        model=ModelConfig.tiny_test(),
        max_model_len=64,
        prefill_chunk=32,   # buckets {16, 32}; a 33-token prompt chunks
        decode_chunk=2,     # small ladder — keeps the compile count low
        sampling_extras=False,
        dtype="float32",
    ))
    await engine.start()
    try:
        n = await engine.warmup()
        assert n > 0
        cs = engine.runner.compile_stats
        assert cs.warmed_programs == n
        await asyncio.gather(
            _collect(engine, n_prompt=5),
            _collect(engine, n_prompt=20),
            _collect(engine, n_prompt=33),
        )
        assert cs.mid_traffic_compiles == 0, cs.mid_traffic_keys
    finally:
        await engine.stop()


def test_budget_snapping_covers_every_serving_batch():
    """The lane ladder is GONE — runtime shape snapping is the budget
    ladder alone: every possible unified batch total lands on a warmed
    rung, so the grid covers everything serving can execute (the unified
    successor of the old lane-bucket snapping contract)."""
    from dynamo_tpu.engine.compile_cache import (
        budget_ladder,
        token_budget,
    )

    cap = 256
    ladder = set(budget_ladder(cap))
    for total in (1, 2, 15, 16, 17, 100, 255, 256, 400):
        assert token_budget(total, cap) in ladder
    # And the ladder-deletion is structural: the mixin no longer carries
    # lane-bucket machinery at all.
    from dynamo_tpu.engine.compile_cache import WarmupPlanMixin

    assert not hasattr(WarmupPlanMixin, "lane_bucket")
    assert not hasattr(WarmupPlanMixin, "add_lane_bucket")


# ---------------------------------------------------------------------------
# HTTP surface
# ---------------------------------------------------------------------------


async def test_health_warming_503_and_compile_gauges():
    import aiohttp

    from dynamo_tpu.llm.discovery import ModelManager
    from dynamo_tpu.llm.http_service import HttpService

    state = {"state": "warming", "mid_traffic_compiles_total": 0,
             "warm_tail_pending": 3}
    service = HttpService(
        ModelManager(), host="127.0.0.1", port=0,
        readiness=lambda: dict(state),
    )
    await service.start()
    try:
        base = f"http://127.0.0.1:{service.port}"
        async with aiohttp.ClientSession() as s:
            async with s.get(f"{base}/health") as resp:
                assert resp.status == 503
                body = await resp.json()
                assert body["status"] == "warming"
                assert body["engine"]["warm_tail_pending"] == 3
            async with s.get(f"{base}/live") as resp:
                assert resp.status == 200  # liveness unaffected by warmup
            state["state"] = "ready"
            state["mid_traffic_compiles_total"] = 2
            async with s.get(f"{base}/health") as resp:
                assert resp.status == 200
                assert (await resp.json())["status"] == "healthy"
            async with s.get(f"{base}/metrics") as resp:
                text = await resp.text()
                assert "engine_ready 1.0" in text
                assert "mid_traffic_compiles_total 2" in text
    finally:
        await service.stop()

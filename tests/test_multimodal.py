"""Multimodal serving tests: vision encoder, soft-prompt prefill vs the
no-cache oracle, and the full encode-worker → preprocessor → engine
pipeline (reference: examples/multimodal — encode_worker ahead of the
decode worker, README.md:18-30)."""

import base64
import io

import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.vision import VisionConfig

pytestmark = pytest.mark.anyio


def _npy_data_url(arr: np.ndarray) -> str:
    buf = io.BytesIO()
    np.save(buf, arr)
    return "data:application/x-npy;base64," + base64.b64encode(
        buf.getvalue()
    ).decode()


def _image(seed: float) -> np.ndarray:
    rng = np.random.default_rng(int(seed))
    return rng.random((32, 32, 3), np.float32)


def test_decode_image_npy_and_resize():
    from dynamo_tpu.llm.multimodal import decode_image

    img = _image(1)
    out = decode_image(_npy_data_url(img), 32)
    np.testing.assert_array_equal(out, img)

    # uint8 input normalizes; non-square resizes to the encoder's input.
    big = (np.arange(64 * 48 * 3) % 255).reshape(64, 48, 3).astype(np.uint8)
    out = decode_image(_npy_data_url(big), 32)
    assert out.shape == (32, 32, 3) and 0.0 <= out.min() and out.max() <= 1.0

    with pytest.raises(ValueError, match="data:"):
        decode_image("http://example.com/cat.png", 32)


def test_vision_encoder_shape_and_determinism():
    import jax

    from dynamo_tpu.models.vision import encode_image, init_vision_params

    cfg = VisionConfig.tiny_test(out_dim=64)
    params = init_vision_params(jax.random.PRNGKey(0), cfg)
    img = _image(2)
    a = np.asarray(encode_image(params, cfg, img))
    b = np.asarray(encode_image(params, cfg, img))
    assert a.shape == (cfg.num_patches, 64)
    np.testing.assert_array_equal(a, b)
    c = np.asarray(encode_image(params, cfg, _image(3)))
    assert np.abs(a - c).max() > 1e-3  # different image, different embeds


def test_runner_mm_prefill_matches_oracle():
    """Soft-prompt prefill must agree with the no-cache oracle forward with
    the same embedding rows spliced in (greedy first token identical)."""
    import jax.numpy as jnp

    from dynamo_tpu.engine.runner import ModelRunner
    from dynamo_tpu.models import llama

    mcfg = ModelConfig.tiny_test()
    ecfg = EngineConfig(
        model=mcfg, num_blocks=32, max_num_seqs=2, max_model_len=128,
        dtype="float32",
    )
    runner = ModelRunner(ecfg, rng_seed=0)

    prompt = list(range(10, 40))  # 30 tokens
    rng = np.random.default_rng(0)
    seg = rng.standard_normal((8, mcfg.hidden_size)).astype(np.float32)
    off = 5  # embeds replace prompt positions 5..12

    tok = runner.prefill(
        prompt, [1, 2], 0, (0.0, 0, 1.0), mm_embeds=[(off, seg)]
    )

    embeds = np.zeros((len(prompt), mcfg.hidden_size), np.float32)
    mask = np.zeros(len(prompt), bool)
    embeds[off : off + len(seg)] = seg
    mask[off : off + len(seg)] = True
    logits = llama.reference_forward(
        mcfg, runner.params, jnp.asarray(prompt, jnp.int32),
        embeds=jnp.asarray(embeds), embed_mask=jnp.asarray(mask),
    )
    assert tok == int(np.argmax(np.asarray(logits)[-1]))

    # And differs from the text-only prefill of the same tokens.
    runner2 = ModelRunner(ecfg, rng_seed=0)
    plain = runner2.prefill(prompt, [1, 2], 0, (0.0, 0, 1.0))
    assert plain == int(
        np.argmax(
            np.asarray(
                llama.reference_forward(
                    mcfg, runner2.params, jnp.asarray(prompt, jnp.int32)
                )
            )[-1]
        )
    )


async def test_multimodal_pipeline_end_to_end():
    """Chat request with an image content part: the preprocessor routes the
    image through the encode engine, placeholder tokens carry the patch
    embeddings into the TpuEngine, and greedy decoding is reproducible and
    image-dependent."""
    from dynamo_tpu.engine.engine import TpuEngine
    from dynamo_tpu.llm.backend import Detokenizer
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.multimodal import (
        MultimodalPreprocessor,
        VisionEncodeEngine,
    )
    from dynamo_tpu.llm.protocols.openai import ChatCompletionRequest
    from dynamo_tpu.llm.tokenizer import ToyTokenizer
    from dynamo_tpu.runtime.engine import Context
    from dynamo_tpu.runtime.pipeline import Pipeline

    # Vocab pinned to the ToyTokenizer's single-byte ASCII range: ids
    # >= 256 decode to NOTHING and ids 128..255 are held as partial UTF-8
    # sequences, so random weights whose greedy continuation lands there
    # would make the text assertions below vacuously flaky. With 128 every
    # sampled token renders immediately as one character.
    import dataclasses

    mcfg = dataclasses.replace(ModelConfig.tiny_test(), vocab_size=128)
    ecfg = EngineConfig(
        model=mcfg, num_blocks=64, max_num_seqs=2, max_model_len=256,
        dtype="float32",
    )
    engine = TpuEngine(ecfg)
    await engine.start()
    vcfg = VisionConfig.tiny_test(out_dim=mcfg.hidden_size)
    encoder = VisionEncodeEngine(vcfg, rng_seed=7)
    card = ModelDeploymentCard(name="tiny-mm", model_path="toy")
    pipe = Pipeline.link(
        MultimodalPreprocessor(
            card,
            ToyTokenizer(),
            encoder,
            placeholder_token=1,
        ),
        Detokenizer(ToyTokenizer()),
        engine=engine,
    )

    def req(image_url):
        return ChatCompletionRequest(
            model="tiny-mm",
            messages=[
                {
                    "role": "user",
                    "content": [
                        {"type": "text", "text": "describe "},
                        {
                            "type": "image_url",
                            "image_url": {"url": image_url},
                        },
                    ],
                }
            ],
            stream=True,
            max_tokens=6,
            temperature=0.0,
            ext={"ignore_eos": True},
        )

    async def run(image_url):
        toks = []
        async for chunk in pipe.generate(Context(req(image_url))):
            for choice in getattr(chunk, "choices", []):
                if choice.delta and choice.delta.content:
                    toks.append(choice.delta.content)
        return "".join(toks)

    url_a = _npy_data_url(_image(11))
    out_a = await run(url_a)
    assert out_a  # produced text
    assert await run(url_a) == out_a  # greedy + same image => reproducible
    out_b = await run(_npy_data_url(_image(99)))
    assert out_b != out_a  # a different image changes the continuation

    # Text-only chats still flow through the same preprocessor untouched.
    plain = ChatCompletionRequest(
        model="tiny-mm",
        messages=[{"role": "user", "content": "hello"}],
        stream=True,
        max_tokens=4,
        temperature=0.0,
        ext={"ignore_eos": True},
    )
    got = []
    async for chunk in pipe.generate(Context(plain)):
        for choice in getattr(chunk, "choices", []):
            if choice.delta and choice.delta.content:
                got.append(choice.delta.content)
    assert got

    await engine.stop()


async def test_multimodal_model_discovery_deployment():
    """Full deployment shape: an encode worker and a TPU worker register
    over the runtime; the watcher builds the multimodal pipeline from the
    card (model_type=multimodal + extra.encode_endpoint) and requests flow
    across the request plane with embeddings on the wire."""
    from dynamo_tpu.engine.engine import TpuEngine
    from dynamo_tpu.llm.discovery import (
        ModelManager,
        ModelWatcher,
        register_llm,
    )
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.multimodal import VisionEncodeEngine
    from dynamo_tpu.llm.protocols.openai import ChatCompletionRequest
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.engine import Context

    drt = await DistributedRuntime.in_process()
    mcfg = ModelConfig.tiny_test()
    vcfg = VisionConfig.tiny_test(out_dim=mcfg.hidden_size)

    enc_ep = drt.namespace("mm").component("encoder").endpoint("encode")
    await enc_ep.serve(VisionEncodeEngine(vcfg, rng_seed=7))

    engine = TpuEngine(
        EngineConfig(
            model=mcfg, num_blocks=64, max_num_seqs=2, max_model_len=256,
            dtype="float32",
        )
    )
    await engine.start()
    gen_ep = drt.namespace("mm").component("tpu").endpoint("generate")
    await gen_ep.serve(engine)
    card = ModelDeploymentCard(
        name="tiny-mm",
        model_path="toy",
        extra={
            "encode_endpoint": "mm.encoder.encode",
            "placeholder_token": 1,
        },
    )
    await register_llm(drt, gen_ep, card, model_type="multimodal")

    manager = ModelManager()
    await ModelWatcher(drt, manager).start()
    for _ in range(50):
        if manager.get("tiny-mm") is not None:
            break
        import asyncio

        await asyncio.sleep(0.05)
    pipe = manager.get("tiny-mm")
    assert pipe is not None

    body = {
        "model": "tiny-mm",
        "messages": [
            {
                "role": "user",
                "content": [
                    {"type": "text", "text": "look: "},
                    {
                        "type": "image_url",
                        "image_url": {"url": _npy_data_url(_image(42))},
                    },
                ],
            }
        ],
        "stream": True,
        "max_tokens": 4,
        "temperature": 0.0,
        "ext": {"ignore_eos": True},
    }
    chunks = []
    usage = None
    async for chunk in pipe.generate(
        Context(ChatCompletionRequest.model_validate(body))
    ):
        chunks.append(chunk)
        if getattr(chunk, "usage", None) is not None:
            usage = chunk.usage
    # The tiny model's greedy tokens may fall outside the byte-level
    # tokenizer's printable range, so assert on the stream itself: deltas
    # arrived and the final usage counts the generated tokens.
    assert chunks
    assert usage is not None and usage.completion_tokens == 4

    await engine.stop()
    await drt.shutdown()

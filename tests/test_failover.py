"""Self-healing fleet tests (docs/architecture/failure_model.md
"Mid-stream failover").

Covers the failover plane end to end: the mark-dead fast path (a
dispatch-time connection error evicts the instance from the router AND
drops it from the metrics aggregator in one step), the mid-stream
worker-kill replay (byte-identical greedy streams, proven against the
mocker's deterministic-token closed form), the error taxonomy (Shed /
Deadline / Request errors are provably NEVER retried), bounded attempts
ending in the clean typed 502, the last-dispatch heartbeat, the planner
crash path (dead workers replaced immediately with no drain
accounting), the failover trace chain, the metric surfaces, and the
docs↔code fault-point drift gate."""

import asyncio
import re
from pathlib import Path

import pytest

from dynamo_tpu.llm.protocols.common import (
    DeadlineError,
    FailoverExhausted,
    PreprocessedRequest,
    RequestError,
    SamplingOptions,
    ShedError,
    StopConditions,
    WorkerDiedError,
)
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.failover import (
    FAILOVER,
    FailoverEngine,
    failover_eligible,
)
from dynamo_tpu.utils.faults import FAULTS, KNOWN_FAULT_POINTS

pytestmark = pytest.mark.anyio

REPO = Path(__file__).resolve().parents[1]


@pytest.fixture(autouse=True)
def _clear_faults():
    yield
    FAULTS.clear()


def _wire(prompt, osl=16):
    return PreprocessedRequest(
        token_ids=list(prompt),
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=osl, ignore_eos=True),
    ).to_wire()


async def _mocker_fleet(drt, n, *, decode_us=8000.0, vocab=100):
    """n deterministic-token mocker workers served on one endpoint."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.mocker import MockerConfig, MockerEngine
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    handles = []
    for i in range(n):
        cfg = EngineConfig(
            model=ModelConfig.tiny_test(), num_blocks=128, max_num_seqs=4,
            max_model_len=256, dtype="float32",
        )
        eng = MockerEngine(cfg, MockerConfig(
            vocab_size=vocab, seed=i, deterministic_tokens=True,
            decode_time_per_step_us=decode_us,
        ))
        await eng.start()
        sub = (
            await DistributedRuntime.in_process(
                store=drt.store, bus=drt.bus, runtime=drt.runtime
            )
            if i else drt
        )
        inst = await sub.namespace("fo").component("w").endpoint(
            "gen"
        ).serve(eng)
        handles.append((inst, eng))
    return handles


async def _teardown(handles, drt):
    for inst, eng in handles:
        try:
            await inst.stop()
        except Exception:  # noqa: BLE001 — may already be killed
            pass
        await eng.stop()
    await drt.shutdown()


# ---------------------------------------------------------------------------
# error taxonomy
# ---------------------------------------------------------------------------


def test_failover_eligibility_is_structural():
    """ONLY the transport/engine-death class fails over: ShedError,
    DeadlineError, RequestError, and plain server bugs never do."""
    assert failover_eligible(WorkerDiedError("gone"))
    assert failover_eligible(ConnectionRefusedError("refused"))
    assert failover_eligible(asyncio.IncompleteReadError(b"", 4))
    from dynamo_tpu.runtime.transports.bus import NoSubscriberError
    from dynamo_tpu.utils.faults import FaultError

    assert failover_eligible(NoSubscriberError("dead subject"))
    assert failover_eligible(FaultError("injected"))
    assert not failover_eligible(ShedError("overloaded"))
    assert not failover_eligible(DeadlineError("expired"))
    assert not failover_eligible(RequestError("bad param"))
    assert not failover_eligible(RuntimeError("server bug"))
    assert not failover_eligible(FailoverExhausted("done retrying"))


class _ScriptedEngine:
    """Downstream whose generate() runs a scripted stream per call."""

    def __init__(self, scripts):
        self.scripts = list(scripts)
        self.calls = 0
        self.payloads = []

    async def generate(self, request):
        self.calls += 1
        self.payloads.append(request.payload)
        script = self.scripts[min(self.calls, len(self.scripts)) - 1]
        for step in script:
            if isinstance(step, BaseException):
                raise step
            yield step


async def test_shed_deadline_request_errors_are_never_retried():
    """The negative proof: a Shed/Deadline/Request failure propagates on
    the FIRST attempt — zero re-dispatches, zero failover counters."""
    for exc_type, exc in (
        (ShedError, ShedError("queue full")),
        (DeadlineError, DeadlineError("expired")),
        (RequestError, RequestError("bad")),
    ):
        before = FAILOVER.total
        down = _ScriptedEngine([[{"token_ids": [1]}, exc]])
        fo = FailoverEngine(down)
        got = []
        with pytest.raises(exc_type):
            async for item in fo.generate(Context(_wire([5, 6]))):
                got.append(item)
        assert down.calls == 1, f"{exc_type.__name__} was retried"
        assert FAILOVER.total == before
        assert got == [{"token_ids": [1]}]


async def test_failover_replays_prompt_plus_emitted_and_shrinks_budgets():
    """The replay wire: token_ids = prompt + emitted, max_tokens shrunk
    by K, the SAME trace id, and the stream stitched without skip or
    repeat."""
    down = _ScriptedEngine([
        [{"token_ids": [10], "cum_tokens": 1},
         {"token_ids": [11], "cum_tokens": 2},
         WorkerDiedError("killed")],
        [{"token_ids": [12], "cum_tokens": 1},
         {"token_ids": [13], "cum_tokens": 2},
         {"token_ids": [], "cum_tokens": 2, "finish_reason": "length"}],
    ])
    fo = FailoverEngine(down)
    got = []
    async for item in fo.generate(Context(_wire([5, 6], osl=4))):
        got.append(item)
    toks = [t for i in got for t in i.get("token_ids", [])]
    assert toks == [10, 11, 12, 13]
    # Replay payload: prompt + the 2 delivered tokens, budget 4 - 2.
    replay = down.payloads[1]
    assert replay["token_ids"] == [5, 6, 10, 11]
    assert replay["stop"]["max_tokens"] == 2
    # Client-visible cumulative count keeps climbing across the seam —
    # INCLUDING the tokenless terminal frame, whose replay-local count
    # must not regress it (review regression).
    assert [i.get("cum_tokens") for i in got] == [1, 2, 3, 4, 4]
    assert FAILOVER.success_by_reason.get("WorkerDiedError", 0) >= 1


async def test_engine_error_finish_frame_triggers_failover():
    """An engine fault ends the stream NORMALLY with an ERROR finish
    frame — the wrapper must re-typify it as death, mark the faulted
    worker dead (the transport was healthy, so egress never did), and
    replay — never deliver the corpse marker."""

    class _Marked(_ScriptedEngine):
        def __init__(self, scripts):
            super().__init__(scripts)
            self.marked = []

        def mark_dead(self, instance_id, reason):
            self.marked.append((instance_id, reason))

    down = _Marked([
        [{"token_ids": [7], "cum_tokens": 1},
         {"token_ids": [], "finish_reason": "error"}],
        [{"token_ids": [8], "cum_tokens": 1,
          "finish_reason": "stop"}],
    ])
    fo = FailoverEngine(down)
    ctx = Context(_wire([1, 2], osl=8))
    ctx.annotations["worker_id"] = 0xBEEF
    got = []
    async for item in fo.generate(ctx):
        got.append(item)
    assert down.calls == 2
    toks = [t for i in got for t in i.get("token_ids", [])]
    assert toks == [7, 8]
    assert all(i.get("finish_reason") != "error" for i in got)
    # The ERROR-frame path marks the corpse dead so the replay cannot
    # route straight back to it (review regression).
    assert down.marked == [(0xBEEF, "engine_fault")]


async def test_bounded_attempts_end_in_typed_failover_exhausted():
    """Every attempt dies ⇒ FailoverExhausted (the clean typed 502) —
    which is NOT ConnectionError, so nothing upstream re-retries it."""
    down = _ScriptedEngine([[WorkerDiedError("dead")]] * 10)
    fo = FailoverEngine(down, max_attempts=3)
    with pytest.raises(FailoverExhausted) as ei:
        async for _ in fo.generate(Context(_wire([1, 2]))):
            pass
    assert ei.value.attempts == 3
    assert down.calls == 4  # original + 3 bounded failovers
    assert not isinstance(ei.value, ConnectionError)


async def test_death_after_final_token_synthesizes_length_finish():
    """The worker died BETWEEN its max_tokens-th token frame and the
    tokenless terminal frame: everything owed was delivered, so the
    wrapper synthesizes the LENGTH finish instead of replaying (a
    replay would hand the client a max_tokens+1st token — review
    regression)."""
    down = _ScriptedEngine([
        [{"token_ids": [10], "cum_tokens": 1},
         {"token_ids": [11], "cum_tokens": 2},
         WorkerDiedError("died before the terminal frame")],
        [{"token_ids": [99], "cum_tokens": 1,
          "finish_reason": "length"}],  # must never run
    ])
    fo = FailoverEngine(down)
    got = []
    async for item in fo.generate(Context(_wire([5, 6], osl=2))):
        got.append(item)
    assert down.calls == 1  # no replay dispatched
    toks = [t for i in got for t in i.get("token_ids", [])]
    assert toks == [10, 11]  # exactly max_tokens, not one more
    assert got[-1]["finish_reason"] == "length"
    assert got[-1]["cum_tokens"] == 2


async def test_death_after_stop_token_synthesizes_stop_finish():
    """Same terminal gap, STOP flavor: the last delivered token IS a
    stop id — the stream already ended semantically, so the wrapper
    synthesizes the STOP finish instead of replaying past it."""
    wire = PreprocessedRequest(
        token_ids=[5, 6],
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=16, stop_token_ids=[11]),
    ).to_wire()
    down = _ScriptedEngine([
        [{"token_ids": [10], "cum_tokens": 1},
         {"token_ids": [11], "cum_tokens": 2},
         WorkerDiedError("died before the terminal frame")],
        [{"token_ids": [99], "cum_tokens": 1,
          "finish_reason": "stop"}],  # must never run
    ])
    fo = FailoverEngine(down)
    got = []
    async for item in fo.generate(Context(wire)):
        got.append(item)
    assert down.calls == 1  # no replay past the stop id
    toks = [t for i in got for t in i.get("token_ids", [])]
    assert toks == [10, 11]
    assert got[-1]["finish_reason"] == "stop"


async def test_error_frame_worker_died_fails_over_without_eviction():
    """A WorkerDiedError that crossed as an error FRAME was delivered
    by a live worker (worker-local transient): it must fail over, but
    NOT take the mark-dead fast path — evicting the reporter and
    pruning its KV state would punish the fleet for nothing. Only
    transport evidence (no terminal frame / refused dispatch) evicts."""
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.egress import PushRouter

    drt = await DistributedRuntime.in_process()
    handles = await _mocker_fleet(drt, 2, decode_us=100.0)
    try:
        push = await PushRouter.create(drt, "fo.w.gen", connect_timeout_s=2.0)
        before = FAILOVER.marked_dead_total
        FAULTS.arm("tcp.respond", "raise", times=1)
        out = []
        async for item in FailoverEngine(push).generate(
            Context(_wire([3, 4], osl=4))
        ):
            out += item.get("token_ids", [])
        assert len(out) == 4  # failed over and completed
        # Both workers still live in the routing view — no eviction for
        # a worker-reported transient.
        assert len(push.client._instances) == 2
        assert FAILOVER.marked_dead_total == before
        assert FAILOVER.success_by_reason.get("WorkerDiedError", 0) >= 1
    finally:
        await _teardown(handles, drt)


async def test_expired_deadline_blocks_failover():
    """A replay must run under the REMAINING deadline; an expired one
    raises DeadlineError instead of redispatching."""
    from dynamo_tpu.utils.deadline import Deadline

    wire = _wire([1, 2])
    wire["deadline_ms"] = Deadline.after_ms(0.0).to_wire()
    down = _ScriptedEngine([[{"token_ids": [3]}, WorkerDiedError("x")]])
    fo = FailoverEngine(down)
    with pytest.raises(DeadlineError):
        async for _ in fo.generate(Context(wire)):
            pass
    assert down.calls == 1  # the death was NOT replayed


# ---------------------------------------------------------------------------
# the mark-dead fast path (satellite: one-step eviction)
# ---------------------------------------------------------------------------


async def test_dispatch_error_evicts_router_and_aggregator_in_one_step():
    """Regression (the ghost bug): a dispatch-time connection error must
    drop the corpse from the router's live view AND the metrics
    aggregator (and radix index) in the SAME step — previously its
    last-known load stayed scoreable until endpoint_ttl_s."""
    from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics
    from dynamo_tpu.llm.kv_router.router import KvRouter
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.egress import PushRouter

    drt = await DistributedRuntime.in_process()
    handles = await _mocker_fleet(drt, 2, decode_us=100.0)
    try:
        comp = drt.namespace("fo").component("w")
        kvr = KvRouter(drt, comp)  # not started: the hook needs no pumps
        wids = [i.instance.instance_id for i, _ in handles]
        for wid in wids:
            kvr.aggregator.endpoints.metrics[wid] = ForwardPassMetrics()

        push = await PushRouter.create(drt, "fo.w.gen", connect_timeout_s=2.0)
        push.on_dead.append(kvr.note_worker_dead)

        # The one-step contract, synchronously: mark_dead evicts from
        # the router's live view AND fires the aggregator/indexer hook
        # in the same call — nothing waits for a TTL.
        push.mark_dead(wids[0], "test:unit")
        assert wids[0] not in push.client._instances
        assert wids[0] not in kvr.aggregator.endpoints.metrics
        # The store still holds the (actually alive) worker: the
        # background refresh heals the false eviction on a later pick —
        # re-seed the aggregator to observe the e2e drop below.
        kvr.aggregator.endpoints.metrics[wids[0]] = ForwardPassMetrics()

        FAULTS.arm("fleet.worker_kill", "raise", times=1)
        before = FAILOVER.marked_dead_total
        ctx = Context(_wire([3, 4], osl=4))
        out = []
        async for item in FailoverEngine(push).generate(ctx):
            out += item.get("token_ids", [])
        assert len(out) == 4  # the request still completed elsewhere
        # ONE step: the dispatch-time connection error dropped exactly
        # one worker from the aggregator (the router view may already
        # have been re-resolved from the store — the victim is alive,
        # the fault was injected — which is the designed false-eviction
        # recovery, not a TTL).
        dead = [w for w in wids if w not in kvr.aggregator.endpoints.metrics]
        assert len(dead) == 1
        assert FAILOVER.marked_dead_total >= before + 1
    finally:
        await _teardown(handles, drt)


async def test_selector_owner_auto_wired_to_on_dead():
    """A KV selector's owning router is wired into on_dead without any
    per-deployment glue (selector_fn.__self__ sniffing)."""
    from dynamo_tpu.llm.kv_router.router import KvRouter
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.egress import PushRouter, RouterMode

    drt = await DistributedRuntime.in_process()
    try:
        comp = drt.namespace("fo").component("w")
        kvr = KvRouter(drt, comp)
        push = await PushRouter.create(
            drt, "fo.w.gen", mode=RouterMode.KV, selector=kvr.selector_fn
        )
        assert kvr.note_worker_dead in push.on_dead
    finally:
        await drt.shutdown()


async def test_aggregator_mark_dead_drops_snapshot():
    from dynamo_tpu.llm.kv_router.metrics_aggregator import (
        KvMetricsAggregator,
    )
    from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics

    agg = KvMetricsAggregator.__new__(KvMetricsAggregator)
    from dynamo_tpu.llm.kv_router.metrics_aggregator import (
        ProcessedEndpoints,
    )

    agg.endpoints = ProcessedEndpoints(
        metrics={7: ForwardPassMetrics(), 9: ForwardPassMetrics()}
    )
    agg._last_seen = {7: 1.0, 9: 1.0}
    agg.stale_endpoint_drops_total = 0
    agg.mark_dead(7)
    assert 7 not in agg.endpoints.metrics
    assert 7 not in agg._last_seen
    assert 9 in agg.endpoints.metrics
    assert agg.stale_endpoint_drops_total == 1
    agg.mark_dead(7)  # idempotent
    assert agg.stale_endpoint_drops_total == 1


def test_stream_closed_without_terminal_frame_is_worker_death():
    """Transport-level detection: the receiver distinguishes a clean end
    frame from the socket dying mid-stream."""
    from dynamo_tpu.runtime.transports.tcp import ResponseStreamReceiver

    async def run():
        r = ResponseStreamReceiver()
        r._push("data", b"x")
        r._close()  # connection died: NO end/err frame
        assert await r.__anext__() == b"x"
        with pytest.raises(WorkerDiedError):
            await r.__anext__()

        clean = ResponseStreamReceiver()
        clean._push("end", b"")
        clean._close()
        with pytest.raises(StopAsyncIteration):
            await clean.__anext__()

    asyncio.run(run())


async def test_no_subscriber_publish_is_typed_and_optional():
    from dynamo_tpu.runtime.transports.bus import InProcBus, NoSubscriberError

    bus = InProcBus()
    # Fire-and-forget publishes keep silent-drop semantics.
    await bus.publish("nobody.home", b"x")
    with pytest.raises(NoSubscriberError):
        await bus.publish("nobody.home", b"x", require_subscriber=True)
    sub = await bus.subscribe("somebody")
    await bus.publish("somebody", b"y", require_subscriber=True)
    assert await asyncio.wait_for(sub.__anext__(), 1.0) == b"y"
    sub.close()


# ---------------------------------------------------------------------------
# the acceptance e2e: byte-identical greedy stream across a mid-stream kill
# ---------------------------------------------------------------------------


async def test_mid_stream_kill_yields_byte_identical_greedy_stream(tmp_path):
    """THE acceptance criterion: kill the serving worker mid-decode; the
    client token stream must equal the uninterrupted single-worker
    reference byte for byte (deterministic-token mocker — the stream is
    a pure function of the prompt), the failover span must land in the
    trace capture, and trace_merge must honor the chain."""
    from benchmarks.trace_merge import (
        assert_complete,
        load_captures,
        merge_report,
    )
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.egress import PushRouter
    from dynamo_tpu.utils.tracing import reset_tracer, tracer

    prompt, osl = [5, 6, 7, 8], 30

    # Reference: one worker, uninterrupted.
    drt = await DistributedRuntime.in_process()
    handles = await _mocker_fleet(drt, 1)
    push = await PushRouter.create(drt, "fo.w.gen")
    ref = []
    async for item in FailoverEngine(push).generate(Context(_wire(prompt, osl))):
        ref += item.get("token_ids", [])
    await _teardown(handles, drt)
    assert len(ref) == osl

    capture = tmp_path / "failover_trace.jsonl"
    reset_tracer(str(capture))
    try:
        drt = await DistributedRuntime.in_process()
        handles = await _mocker_fleet(drt, 2)
        push = await PushRouter.create(drt, "fo.w.gen", connect_timeout_s=2.0)
        ctx = Context(_wire(prompt, osl))
        got, killed = [], False
        async for item in FailoverEngine(push).generate(ctx):
            got += item.get("token_ids", [])
            if len(got) >= 5 and not killed:
                killed = True
                wid = ctx.annotations["worker_id"]
                victim = next(
                    h for h in handles
                    if h[0].instance.instance_id == wid
                )
                await victim[0].kill()
        tracer().finish(ctx.id)
        assert killed
        assert got == ref, (
            f"stream NOT byte-identical across the kill:\n"
            f"ref={ref}\ngot={got}"
        )
        assert FAILOVER.success_by_reason.get("WorkerDiedError", 0) >= 1
        await _teardown(handles, drt)
    finally:
        reset_tracer(None)

    # The trace catalog: a kind="failover" record with reason/attempt/
    # old/new worker, and --assert-complete honoring the chain.
    from dynamo_tpu.utils.recorder import Recorder

    records = [ev for _ts, ev in Recorder.load(str(capture))]
    fo_recs = [r for r in records if r.get("kind") == "failover"]
    assert len(fo_recs) == 1
    rec = fo_recs[0]
    assert rec["reason"] == "WorkerDiedError"
    assert rec["attempt"] == 1
    assert rec["old_worker"] and rec["new_worker"]
    assert rec["old_worker"] != rec["new_worker"]
    assert rec["resumed_at_token"] >= 5

    traces = load_captures([str(capture)])
    report = merge_report(traces)
    assert assert_complete(report) == []
    t = next(t for t in traces.values() if t.failed_over)
    assert "failover" in {s["name"] for s in t.spans}


# ---------------------------------------------------------------------------
# heartbeat + planner crash path
# ---------------------------------------------------------------------------


async def test_readiness_exports_last_dispatch_heartbeat():
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.mocker import MockerConfig, MockerEngine
    from dynamo_tpu.models.config import ModelConfig

    eng = MockerEngine(
        EngineConfig(
            model=ModelConfig.tiny_test(), num_blocks=32, max_num_seqs=2,
            max_model_len=128, dtype="float32",
        ),
        MockerConfig(vocab_size=50),
    )
    await eng.start()
    try:
        await asyncio.sleep(0.05)
        r = eng.readiness()
        assert "last_dispatch_age_s" in r
        # A live engine loop heartbeats every pass (idle poll included).
        assert 0.0 <= r["last_dispatch_age_s"] < 5.0
        for key in (
            "failover_total", "failover_success_total",
            "workers_marked_dead_total",
        ):
            assert key in r
    finally:
        await eng.stop()


async def test_worker_pool_replaces_dead_immediately_without_drain():
    """Crash ≠ drain: a dead worker is removed and replaced at target
    size with NO drain task; a live worker scaling down still drains."""
    from dynamo_tpu.planner.obs import PLANNER_OBS
    from dynamo_tpu.planner.pools import PoolConfig, WorkerPool

    class Handle:
        def __init__(self, n):
            self.n = n
            self.alive = True

    class Conn:
        def __init__(self):
            self.spawned = 0
            self.drained = []

        async def spawn(self):
            self.spawned += 1
            return Handle(self.spawned)

        def alive(self, h):
            return h.alive

        async def drain(self, h):
            self.drained.append(h.n)

    conn = Conn()
    pool = WorkerPool(
        PoolConfig(name="decode", min_workers=3, max_workers=4), conn,
        law=None,
    )
    await pool.ensure_min()
    assert pool.size == 3
    before = PLANNER_OBS.replaced_dead_total

    pool.handles[1].alive = False
    replaced = await pool.reap_dead()
    assert replaced == 1
    assert pool.size == 3                 # healed to target immediately
    assert pool.draining == 0             # crash path: NO drain task
    assert conn.drained == []             # dead worker never "drained"
    assert conn.spawned == 4
    assert all(h.alive for h in pool.handles)
    assert PLANNER_OBS.replaced_dead_total == before + 1
    assert await pool.reap_dead() == 0    # idempotent when all alive


async def test_both_pools_chaos_heal_under_kill_storm():
    """Chaos across BOTH pools: repeated kills while the heal loop runs;
    both pools end at target with every handle alive."""
    import random

    from dynamo_tpu.planner.pools import PoolConfig, WorkerPool

    class Handle:
        def __init__(self, n):
            self.n = n
            self.alive = True

    class Conn:
        def __init__(self):
            self.spawned = 0

        async def spawn(self):
            self.spawned += 1
            await asyncio.sleep(0.001)
            return Handle(self.spawned)

        def alive(self, h):
            return h.alive

        async def drain(self, h):
            pass

    rng = random.Random(3)
    pools = [
        WorkerPool(PoolConfig(name="prefill", min_workers=2), Conn(), None),
        WorkerPool(PoolConfig(name="decode", min_workers=4), Conn(), None),
    ]
    for p in pools:
        await p.ensure_min()
    for _ in range(6):
        victim_pool = rng.choice(pools)
        if victim_pool.handles:
            rng.choice(victim_pool.handles).alive = False
        for p in pools:
            await p.reap_dead()
    assert pools[0].size == 2 and pools[1].size == 4
    assert all(h.alive for p in pools for h in p.handles)
    assert all(p.draining == 0 for p in pools)


def test_subprocess_connector_alive_detects_exit():
    import subprocess

    from dynamo_tpu.planner.planner import SubprocessConnector

    conn = SubprocessConnector("true")
    live = subprocess.Popen(["sleep", "5"])
    dead = subprocess.Popen(["true"])
    dead.wait()
    try:
        assert conn.alive(live)
        assert not conn.alive(dead)
    finally:
        live.kill()
        live.wait()


# ---------------------------------------------------------------------------
# drift gate + metric surfaces
# ---------------------------------------------------------------------------


def test_fault_point_docs_code_drift_gate():
    """Every seam named in failure_model.md's instrumented-points list
    must be registered in KNOWN_FAULT_POINTS, and vice versa — AND each
    registered point must have a real ``maybe_fail`` call site (docs↔
    code parity, the DT011 spirit pointed at the failure model)."""
    doc = (REPO / "docs/architecture/failure_model.md").read_text()
    m = re.search(r"Instrumented points:(.*?)\n\n", doc, re.S)
    assert m, "failure_model.md lost its 'Instrumented points:' list"
    documented = set(re.findall(r"`([a-z_]+\.[a-z_]+)`", m.group(1)))
    assert documented == set(KNOWN_FAULT_POINTS), (
        f"docs↔code drift:\n  documented-not-registered: "
        f"{sorted(documented - set(KNOWN_FAULT_POINTS))}\n  "
        f"registered-not-documented: "
        f"{sorted(set(KNOWN_FAULT_POINTS) - documented)}"
    )
    # Each registered point is armed at a REAL call site somewhere.
    sources = ""
    for py in (REPO / "dynamo_tpu").rglob("*.py"):
        sources += py.read_text()
    for point in KNOWN_FAULT_POINTS:
        assert f'"{point}"' in sources, (
            f"fault point {point!r} is registered but has no call site"
        )


def test_failover_counters_on_every_metric_surface():
    """DT011-adjacent: the failover counters + heartbeat exist on
    ForwardPassMetrics (the exporter scrapes attributes) and in the
    exporter's _GAUGES table; the labeled per-reason/per-seam render is
    well-formed Prometheus text."""
    from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics
    from dynamo_tpu.llm.metrics_exporter import _GAUGES

    names = {n for n, _ in _GAUGES}
    fpm = ForwardPassMetrics()
    for key in (
        "failover_total", "failover_success_total",
        "workers_marked_dead_total", "last_dispatch_age_s",
    ):
        assert key in names, f"{key} missing from exporter _GAUGES"
        assert hasattr(fpm, key), f"{key} missing from ForwardPassMetrics"

    from dynamo_tpu.runtime.failover import FailoverStats

    st = FailoverStats()
    st.note_attempt("WorkerDiedError")
    st.note_success("WorkerDiedError")
    st.note_marked_dead("dispatch:NoSubscriberError")
    text = st.render_labeled("dyntpu")
    assert (
        'dyntpu_failover_total_by_reason{reason="WorkerDiedError"} 1'
        in text
    )
    assert (
        'dyntpu_workers_marked_dead_total_by_reason'
        '{reason="dispatch:NoSubscriberError"} 1' in text
    )
    assert st.total == 1 and st.success_total == 1
    assert st.marked_dead_total == 1

"""End-to-end KV-block integrity (docs/architecture/integrity.md): the
envelope is stamped ONCE at the G1→G2 store and verified at every later
trust-boundary crossing; failures quarantine the block and degrade the
request to recompute, never to an error or to wrong bytes.

Covered here: checksum primitives, host-onboard verify + quarantine +
re-admission, quantized packed rows, G3 promotion verify, the background
scrubber (detection + injectable pacing), crash-consistent sidecar
recovery, a kill -9 mid-offload restart drill (subprocess), the
mixed-fleet refusals (G4 blockset + disagg layout handshake), and
metric-surface parity for the integrity gauges (DT011 posture).
"""

import asyncio
import dataclasses
import logging
import os
import sys
import time
from types import SimpleNamespace

import msgpack
import numpy as np
import pytest

from dynamo_tpu.block_manager import (
    BlockPool,
    DiskStorage,
    HostStorage,
    KvbmConfig,
    KvBlockManager,
    KvLayoutConfig,
)
from dynamo_tpu.block_manager.integrity import (
    CHECKSUM_ALGO,
    INTEGRITY,
    block_checksum,
    verify_block,
)
from dynamo_tpu.block_manager.offload import OffloadManager

pytestmark = pytest.mark.anyio

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TORN_WORKER = os.path.join(REPO, "tests", "procs", "torn_offload_worker.py")

LAYOUT = KvLayoutConfig(
    num_layers=2, page_size=16, num_kv_heads=2, head_dim=16, dtype="float32"
)
QLAYOUT = KvLayoutConfig(
    num_layers=2, page_size=16, num_kv_heads=2, head_dim=16,
    dtype="float32", quant="int8",
)
# Mirror of tests/procs/torn_offload_worker.py LAYOUT — the drill reopens
# the child's disk file under this geometry.
TORN_LAYOUT = KvLayoutConfig(
    num_layers=1, page_size=4, num_kv_heads=1, head_dim=4, dtype="float32"
)


def _data(seed: float) -> np.ndarray:
    return np.full((LAYOUT.block_elems,), seed, np.float32)


@pytest.fixture(autouse=True)
def _reset_integrity():
    """The integrity ledger is process-global; counter assertions here
    must not see residue from other tests (or leave any behind)."""
    INTEGRITY.reset()
    yield
    INTEGRITY.reset()


def test_checksum_primitives():
    arr = np.arange(64, dtype=np.float32)
    crc = block_checksum(arr)
    # Array and raw-bytes forms agree: senders checksum tobytes() wire
    # payloads, receivers verify ndarray views — same envelope.
    assert crc == block_checksum(arr.tobytes())
    assert verify_block(arr, crc)
    assert verify_block(arr.tobytes(), crc)
    # None = legacy/unstamped: trusted, old behavior preserved.
    assert verify_block(arr, None)
    rotten = arr.copy()
    rotten.view(np.uint8)[17] ^= 0x01
    assert not verify_block(rotten, crc)
    assert CHECKSUM_ALGO == "crc32-v1"


async def test_store_stamps_once_and_match_host_quarantines():
    kvbm = await KvBlockManager(
        KvbmConfig(layout=LAYOUT, host_blocks=8)
    ).start()
    try:
        d = _data(3.0)
        kvbm.offer(42, None, tuple(range(16)), d)
        await kvbm.drain_offers(10.0)
        blk = kvbm.host_pool.get_by_hash(42)
        assert blk is not None
        # The envelope was stamped at the store, over the stored bytes.
        assert blk.checksum == block_checksum(d)
        got = kvbm.match_host([42])
        assert len(got) == 1 and np.array_equal(got[0][3], d)
        assert INTEGRITY.snapshot()["integrity_failures_total"] == 0

        # Bit-rot the host arena behind the envelope's back: the G2→G1
        # crossing must refuse the block, not serve it.
        row = kvbm.host_pool.storage.read_block(blk.idx)
        row.view(np.uint8)[7] ^= 0x01
        assert kvbm.match_host([42]) == []
        snap = INTEGRITY.snapshot()
        assert snap["integrity_failures_host"] == 1
        assert snap["integrity_failures_total"] == 1
        # Quarantined: evicted, and barred from every export surface.
        assert kvbm.host_pool.get_by_hash(42) is None
        assert 42 not in kvbm.registered_hashes()
        assert all(h != 42 for h, _, _ in kvbm.host_entries())

        # A fresh store re-stamps the envelope and lifts the bar.
        kvbm.offer(42, None, tuple(range(16)), d)
        await kvbm.drain_offers(10.0)
        assert 42 in kvbm.registered_hashes()
        got = kvbm.match_host([42])
        assert len(got) == 1 and np.array_equal(got[0][3], d)
    finally:
        await kvbm.stop()


async def test_quantized_packed_row_envelope():
    """quant="int8" tiers stamp the CRC over the PACKED row (int8 data ‖
    float32 scales); rot anywhere in it — scales included — is caught."""
    kvbm = await KvBlockManager(
        KvbmConfig(layout=QLAYOUT, host_blocks=4)
    ).start()
    try:
        d = np.linspace(-2.0, 2.0, QLAYOUT.block_elems, dtype=np.float32)
        kvbm.offer(7, None, tuple(range(16)), d)
        await kvbm.drain_offers(10.0)
        blk = kvbm.host_pool.get_by_hash(7)
        stored = np.asarray(kvbm.host_pool.storage.read_block(blk.idx))
        assert stored.dtype == np.uint8
        assert stored.nbytes == QLAYOUT.block_bytes
        assert blk.checksum == block_checksum(stored)
        got = kvbm.match_host([7])
        assert len(got) == 1 and np.array_equal(got[0][3], stored)

        # Flip a byte in the scale sidecar (the packed row's tail): the
        # envelope covers it, so the onboard must still refuse.
        kvbm.host_pool.storage.read_block(blk.idx)[-1] ^= 0x01
        assert kvbm.match_host([7]) == []
        assert INTEGRITY.snapshot()["integrity_failures_host"] == 1
    finally:
        await kvbm.stop()


async def test_disk_promotion_verifies_envelope(tmp_path):
    host = BlockPool(HostStorage(4, LAYOUT))
    disk = BlockPool(DiskStorage(4, LAYOUT, tmp_path / "kv.bin"))
    mgr = OffloadManager(host, disk)
    for i, h in enumerate((10, 11)):
        b = host.allocate_blocks(1)[0]
        host.storage.write_block(b.idx, _data(float(i + 1)))
        b = host.register_block(
            b, h, 10 if i else None, tuple(range(16)),
            checksum=block_checksum(_data(float(i + 1))),
        )
        mgr.offload(b)
        host.release(b)
    await mgr.drain()

    # The envelope rode down-tier unchanged (carried, never re-stamped).
    assert disk.get_by_hash(10).checksum == block_checksum(_data(1.0))

    # Silent SSD rot under block 11: flip one byte in the mmap.
    stor = disk.storage
    off = disk.get_by_hash(11).idx * LAYOUT.block_bytes + 13
    stor._map[off] = stor._map[off] ^ 0x01
    up = await mgr.onboard([10, 11])
    try:
        # Promotion stops AT the corrupt block: the clean prefix lands,
        # the rotten tail is quarantined for the engine to recompute.
        assert [b.sequence_hash for b in up] == [10]
        assert np.array_equal(
            np.asarray(host.storage.read_block(up[0].idx)), _data(1.0)
        )
    finally:
        for b in up:
            host.release(b)
    snap = INTEGRITY.snapshot()
    assert snap["integrity_failures_disk"] == 1
    assert disk.get_by_hash(11) is None
    assert disk.get_by_hash(10) is not None


async def test_scrub_loop_detects_and_paces(tmp_path):
    cfg = KvbmConfig(
        layout=LAYOUT,
        host_blocks=8,
        disk_blocks=8,
        disk_path=str(tmp_path / "kv.bin"),
        scrub_blocks_per_tick=4,
        scrub_interval_s=0.075,
    )
    kvbm = KvBlockManager(cfg)
    sleeps: list[float] = []

    async def pace(interval: float) -> None:
        # Injectable pacing clock: record what the loop asked for, tick
        # fast so the test doesn't wait out real intervals.
        sleeps.append(interval)
        await asyncio.sleep(0.005)

    kvbm._scrub_sleep = pace
    await kvbm.start()
    try:
        parent = None
        for i in range(3):
            kvbm.offer(100 + i, parent, tuple(range(16)), _data(float(i + 1)))
            parent = 100 + i
        await kvbm.drain_offers(10.0)
        await kvbm._g2_to_g3.drain()

        blk = kvbm.disk_pool.get_by_hash(101)
        stor = kvbm.disk_pool.storage
        off = blk.idx * LAYOUT.block_bytes + 11
        stor._map[off] = stor._map[off] ^ 0x01

        deadline = time.monotonic() + 10.0
        while INTEGRITY.snapshot()["scrub_detected_total"] < 1:
            assert time.monotonic() < deadline, \
                "scrubber never caught the planted rot"
            await asyncio.sleep(0.01)
        snap = INTEGRITY.snapshot()
        assert snap["integrity_failures_disk"] == 1
        assert snap["scrub_scanned_total"] >= 1
        # Quarantined out of the tier before any request could meet it;
        # the clean neighbors survive the sweep.
        assert kvbm.disk_pool.get_by_hash(101) is None
        assert kvbm.disk_pool.get_by_hash(100) is not None
        assert kvbm.disk_pool.get_by_hash(102) is not None
        # Every tick slept exactly the configured interval.
        assert sleeps and set(sleeps) == {cfg.scrub_interval_s}
    finally:
        await kvbm.stop()


def test_sidecar_recovery_drops_torn_tail(tmp_path):
    path = tmp_path / "g3.kv"
    stor = DiskStorage(4, LAYOUT, path, persist=True)
    for i in range(3):
        d = _data(float(i + 1))
        stor.write_block(i, d)
        stor.record_block(
            i, 100 + i, (99 + i) if i else None, tuple(range(16)),
            block_checksum(d),
        )
    stor.close()

    # Rot block 2's bytes behind the sidecar's back (the crash window
    # where the data region lost a write the index already named).
    with open(path, "r+b") as fh:
        fh.seek(2 * LAYOUT.block_bytes + 5)
        byte = fh.read(1)[0]
        fh.seek(-1, 1)
        fh.write(bytes([byte ^ 0x01]))

    INTEGRITY.reset()
    stor2 = DiskStorage(4, LAYOUT, path, persist=True)
    try:
        entries = stor2.recovered_entries()
        assert {h for _, h, *_ in entries} == {100, 101}
        for idx, h, _parent, _tokens, crc in entries:
            assert block_checksum(stor2.read_block(idx)) == crc
        snap = INTEGRITY.snapshot()
        assert snap["integrity_failures_disk"] == 1
        assert snap["scrub_detected_total"] == 1
    finally:
        stor2.close()


def test_torn_write_fault_truncates_block_recovery_drops_it(tmp_path):
    """Armed ``kvbm.torn_write`` at the G3 write seam: only half the
    row's bytes land, but the sidecar names the block with its full
    upstream checksum — restart recovery must drop exactly that block
    and keep the intact neighbors."""
    from dynamo_tpu.utils.faults import FAULTS

    path = tmp_path / "g3.kv"
    stor = DiskStorage(4, LAYOUT, path, persist=True)
    try:
        for i in range(2):
            d = _data(float(i + 1))
            stor.write_block(i, d)
            stor.record_block(i, 100 + i, None, tuple(range(16)),
                              block_checksum(d))
        torn = _data(9.0)
        before = FAULTS.injected.get("kvbm.torn_write", 0)
        FAULTS.arm("kvbm.torn_write", "truncate", times=1)
        stor.write_block(2, torn)  # torn: only the first half lands
        stor.record_block(2, 102, None, tuple(range(16)),
                          block_checksum(torn))
        assert FAULTS.injected["kvbm.torn_write"] == before + 1
        stor.close()

        INTEGRITY.reset()
        stor2 = DiskStorage(4, LAYOUT, path, persist=True)
        try:
            assert {h for _, h, *_ in stor2.recovered_entries()} == {100, 101}
            assert INTEGRITY.snapshot()["integrity_failures_disk"] == 1
        finally:
            stor2.close()
    finally:
        FAULTS.clear()


def test_torn_write_fault_tears_sidecar_recovery_starts_fresh(tmp_path):
    """Armed ``kvbm.torn_write`` at the sidecar flush: the index JSON is
    cut mid-document (a crash on a non-atomic fs). Recovery must degrade
    to an empty tier — never adopt half-parsed junk."""
    from dynamo_tpu.utils.faults import FAULTS

    path = tmp_path / "g3.kv"
    stor = DiskStorage(4, LAYOUT, path, persist=True)
    try:
        d = _data(1.0)
        stor.write_block(0, d)
        stor.record_block(0, 100, None, tuple(range(16)), block_checksum(d))
        d2 = _data(2.0)
        stor.write_block(1, d2)
        # The flush for THIS record gets torn. write_block spends no
        # budget first because corrupt() only fires at mutate sites and
        # the truncate is armed after the bytes landed.
        before = FAULTS.injected.get("kvbm.torn_write", 0)
        FAULTS.arm("kvbm.torn_write", "truncate", times=1)
        stor.record_block(1, 101, None, tuple(range(16)),
                          block_checksum(d2))
        assert FAULTS.injected["kvbm.torn_write"] == before + 1
        stor.close()

        stor2 = DiskStorage(4, LAYOUT, path, persist=True)
        try:
            assert stor2.recovered_entries() == []
        finally:
            stor2.close()
    finally:
        FAULTS.clear()


async def test_torn_write_crash_drill(tmp_path):
    """kill -9 mid-offload, then restart: the sidecar's ordering contract
    (bytes msync'd before the index names them) means the reopened tier
    serves a contiguous, byte-identical prefix of the chain — at least
    everything the child acknowledged before dying, never a torn block."""
    path = str(tmp_path / "g3.kv")
    proc = await asyncio.create_subprocess_exec(
        sys.executable, TORN_WORKER, "--path", path, "--blocks", "8",
        stdout=asyncio.subprocess.PIPE,
        stderr=asyncio.subprocess.STDOUT,
        cwd=REPO,
    )
    stored = -1
    try:
        while stored < 2:
            line = await asyncio.wait_for(proc.stdout.readline(), 60)
            assert line, "offload child died before storing 3 blocks"
            text = line.decode().strip()
            if text.startswith("STORED "):
                stored = int(text.split()[1])
        proc.kill()  # SIGKILL: no atexit, no flush, mid-offload
    finally:
        if proc.returncode is None:
            proc.kill()
        await proc.wait()

    kvbm = await KvBlockManager(
        KvbmConfig(
            layout=TORN_LAYOUT,
            host_blocks=12,
            disk_blocks=12,
            disk_path=path,
            disk_persist=True,
        )
    ).start()
    try:
        adopted = sorted(kvbm.disk_pool.registered_hashes())
        k = len(adopted)
        # Everything acknowledged before the kill survived...
        assert k >= stored + 1
        # ...and what survived is a contiguous prefix — no holes, no
        # torn tail block resurrected as valid.
        assert adopted == [1000 + j for j in range(k)]

        chain = [1000 + j for j in range(8)]
        assert await kvbm.onboard_from_disk(chain) == k
        got = kvbm.match_host(chain)
        assert len(got) == k
        for j, (h, _parent, _tokens, data) in enumerate(got):
            assert h == 1000 + j
            want = np.full(
                (TORN_LAYOUT.block_elems,), float(j + 1), np.float32
            )
            assert np.array_equal(np.asarray(data), want)
        assert INTEGRITY.snapshot()["integrity_failures_total"] == 0
    finally:
        await kvbm.stop()


def test_legacy_peer_blockset_refused(caplog):
    """Satellite regression: a checksumming worker REFUSES a legacy
    peer's blockset loudly — its rows are unverifiable here."""
    from dynamo_tpu.block_manager.peer import layout_fingerprint
    from dynamo_tpu.block_manager.remote import RemoteBlockClient

    ours = layout_fingerprint(LAYOUT)
    assert ours["checksum"] == CHECKSUM_ALGO
    comp = SimpleNamespace(name="tpu", namespace=SimpleNamespace(name="kv"))
    client = RemoteBlockClient(None, comp, layout=ours)

    legacy = dict(ours)
    del legacy["checksum"]  # a pre-envelope build's fingerprint
    with caplog.at_level(
        logging.WARNING, logger="dynamo_tpu.block_manager.remote"
    ):
        client._apply(
            client._prefix + "beef",
            msgpack.packb({"hashes": [1, 2, 3], "layout": legacy}),
        )
    assert "beef" not in client._blocksets
    assert "REFUSED: checksum algorithm" in caplog.text

    # Same-algorithm peer: accepted.
    client._apply(
        client._prefix + "cafe",
        msgpack.packb({"hashes": [1, 2], "layout": dict(ours)}),
    )
    assert client._blocksets["cafe"] == {1, 2}


def test_disagg_layout_checksum_handshake(caplog):
    from dynamo_tpu.disagg.worker import PrefillWorker

    pw = PrefillWorker.__new__(PrefillWorker)
    pw.engine = SimpleNamespace(
        cfg=SimpleNamespace(
            model=SimpleNamespace(num_layers=2, num_cache_heads=2),
            block_size=16,
            dtype="float32",
            kv_quant=None,
        ),
        runner=None,
    )
    base = {
        "num_layers": 2,
        "num_kv_heads": 2,
        "block_size": 16,
        "dtype": "float32",
        "kv_quant": None,
    }
    # Legacy peer (no checksum field): accepted, frames ride unchecksummed.
    assert pw._check_layout({"layout": dict(base)})
    assert pw._check_layout({"layout": {**base, "checksum": CHECKSUM_ALGO}})
    # Algorithm split: rejected loudly — the decode side would quarantine
    # every frame this worker ships.
    with caplog.at_level(logging.ERROR, logger="dynamo_tpu.disagg.worker"):
        ok = pw._check_layout(
            {"request_id": "r1", "layout": {**base, "checksum": "crc32-v0"}}
        )
    assert not ok
    assert "mixed integrity fleet" in caplog.text


def test_integrity_metric_surface_parity():
    """DT011 posture: every integrity ledger key is surfaced — as a
    ForwardPassMetrics field AND a standalone-exporter gauge — under the
    kvbm_ prefix; drift in any direction fails here."""
    from dynamo_tpu.llm import metrics_exporter
    from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics

    snap_keys = set(INTEGRITY.snapshot())
    assert snap_keys == {
        "integrity_failures_total",
        "integrity_failures_host",
        "integrity_failures_disk",
        "integrity_failures_peer",
        "integrity_failures_frame",
        "scrub_scanned_total",
        "scrub_detected_total",
    }
    gauge_names = {name for name, _ in metrics_exporter._GAUGES}
    fpm_fields = {f.name for f in dataclasses.fields(ForwardPassMetrics)}
    for key in snap_keys:
        assert f"kvbm_{key}" in gauge_names
        assert f"kvbm_{key}" in fpm_fields

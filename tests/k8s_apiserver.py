"""In-repo Kubernetes API-server emulator for operator e2e tests.

This build environment has no kubectl, kind, or network egress, so a real
apiserver cannot run here. This emulator speaks the actual wire protocol
the operator's REST client (operator/restkube.py) uses in production —
bearer-token auth, typed REST paths, server-side-apply PATCH, label-
selector lists, streaming ``?watch=1`` event lines, CRD registration that
GATES custom-resource paths (a GraphDeployment request 404s until the CRD
is installed, like a real cluster) — over a real HTTP socket. It is the
envtest role of the reference's operator suite
(reference: deploy/cloud/operator — controller tests against envtest's
apiserver binary).
"""

from __future__ import annotations

import asyncio
import json
import re
from typing import Any

from aiohttp import web

Manifest = dict[str, Any]

TOKEN = "test-sa-token"

#: plural -> kind for the built-in types; custom plurals come from CRDs.
BUILTINS = {"deployments": "Deployment", "services": "Service"}


def _match(labels: dict, selector: str) -> bool:
    if not selector:
        return True
    for part in selector.split(","):
        k, _, v = part.partition("=")
        if labels.get(k) != v:
            return False
    return True


class ApiServerEmulator:
    def __init__(self) -> None:
        #: (plural, namespace, name) -> object
        self.objects: dict[tuple[str, str, str], Manifest] = {}
        self.crds: dict[str, Manifest] = {}   # plural -> CRD
        self._rv = 0
        self._watchers: list[tuple[str, str, asyncio.Queue]] = []
        self._runner: web.AppRunner | None = None
        self.port = 0
        self.patch_count = 0

    # -- lifecycle ----------------------------------------------------------
    async def start(self) -> "ApiServerEmulator":
        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._dispatch)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]  # noqa: SLF001
        return self

    async def stop(self) -> None:
        for _, _, q in self._watchers:
            q.put_nowait(None)
        if self._runner:
            await self._runner.cleanup()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    # -- test helpers (kubelet / out-of-band actor) -------------------------
    def mark_ready(self, namespace: str, name: str) -> None:
        obj = self.objects[("deployments", namespace, name)]
        obj["status"] = {
            "readyReplicas": obj.get("spec", {}).get("replicas", 0)
        }
        self._notify("deployments", obj)

    def external_delete(self, plural: str, namespace: str, name: str) -> None:
        obj = self.objects.pop((plural, namespace, name))
        self._notify(plural, obj, kind="DELETED")

    # -- internals ----------------------------------------------------------
    def _notify(self, plural: str, obj: Manifest, kind: str = "MODIFIED"):
        labels = obj.get("metadata", {}).get("labels", {})
        for wplural, selector, q in list(self._watchers):
            if wplural == plural and _match(labels, selector):
                q.put_nowait({"type": kind, "object": obj})

    _PATHS = [
        # /api/v1/... (core) and /apis/{group}/{version}/...
        re.compile(
            r"^/(?:api/v1|apis/[^/]+/[^/]+)"
            r"(?:/namespaces/(?P<ns>[^/]+))?/(?P<plural>[^/]+)"
            r"(?:/(?P<name>[^/]+))?$"
        ),
    ]

    async def _dispatch(self, request: web.Request) -> web.StreamResponse:
        if request.headers.get("Authorization") != f"Bearer {TOKEN}":
            return web.json_response({"message": "Unauthorized"}, status=401)
        m = self._PATHS[0].match(request.path)
        if not m:
            return web.json_response({"message": "not found"}, status=404)
        ns = m.group("ns") or ""
        plural, name = m.group("plural"), m.group("name")

        if plural == "customresourcedefinitions":
            return await self._crd(request, name)
        if plural not in BUILTINS and not any(
            c["spec"]["names"]["plural"] == plural for c in self.crds.values()
        ):
            # A real apiserver 404s unknown resources until a CRD
            # registers them — ensure_crd ordering is load-bearing.
            return web.json_response(
                {"message": f"no resource {plural!r}"}, status=404
            )

        if request.method == "GET" and name is None:
            if request.query.get("watch") == "1":
                return await self._watch(request, plural)
            sel = request.query.get("labelSelector", "")
            items = [
                o
                for (p, ons, _), o in self.objects.items()
                if p == plural
                and (not ns or ons == ns)
                and _match(o.get("metadata", {}).get("labels", {}), sel)
            ]
            return web.json_response({"items": items})
        if request.method == "GET":
            obj = self.objects.get((plural, ns, name))
            if obj is None:
                return web.json_response({"message": "NotFound"}, status=404)
            return web.json_response(obj)
        if request.method == "PATCH":
            if request.content_type != "application/apply-patch+yaml":
                return web.json_response(
                    {"message": "bad patch type"}, status=415
                )
            if not request.query.get("fieldManager"):
                return web.json_response(
                    {"message": "fieldManager required"}, status=400
                )
            self.patch_count += 1
            obj = json.loads(await request.read())
            self._rv += 1
            obj.setdefault("metadata", {})["resourceVersion"] = str(self._rv)
            obj["metadata"].setdefault("namespace", ns)
            prior = self.objects.get((plural, ns, name))
            if prior and "status" in prior and "status" not in obj:
                obj["status"] = prior["status"]  # apply doesn't clear status
            self.objects[(plural, ns, name)] = obj
            self._notify(plural, obj, "MODIFIED" if prior else "ADDED")
            return web.json_response(obj)
        if request.method == "DELETE":
            obj = self.objects.pop((plural, ns, name), None)
            if obj is None:
                return web.json_response({"message": "NotFound"}, status=404)
            self._notify(plural, obj, "DELETED")
            return web.json_response({"status": "Success"})
        return web.json_response({"message": "method"}, status=405)

    async def _crd(self, request: web.Request, name: str | None):
        if request.method == "POST":
            crd = await request.json()
            cname = crd["metadata"]["name"]
            if cname in self.crds:
                return web.json_response(
                    {"message": "AlreadyExists"}, status=409
                )
            self.crds[cname] = crd
            return web.json_response(crd, status=201)
        if request.method == "GET" and name:
            crd = self.crds.get(name)
            if crd is None:
                return web.json_response({"message": "NotFound"}, status=404)
            return web.json_response(crd)
        return web.json_response({"items": list(self.crds.values())})

    async def _watch(self, request: web.Request, plural: str):
        resp = web.StreamResponse()
        resp.content_type = "application/json"
        await resp.prepare(request)
        q: asyncio.Queue = asyncio.Queue()
        entry = (plural, request.query.get("labelSelector", ""), q)
        self._watchers.append(entry)
        try:
            while True:
                ev = await q.get()
                if ev is None:
                    break
                await resp.write(json.dumps(ev).encode() + b"\n")
        finally:
            if entry in self._watchers:
                self._watchers.remove(entry)
        return resp

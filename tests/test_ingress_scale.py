"""Million-user ingress tests (docs/architecture/ingress_scale.md):
SLO classes through the admission/scheduler chain, mark-dead broadcast
across router replicas, sharded-indexer churn convergence, replica
kill/failover/rejoin with measured staleness, and the replay-harness
smoke with its full gate set.
"""

import asyncio
import time

import msgpack
import pytest

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.kv_cache import BlockAllocator
from dynamo_tpu.engine.scheduler import Scheduler
from dynamo_tpu.engine.sequence import Sequence, SeqStatus
from dynamo_tpu.llm import slo
from dynamo_tpu.llm.admission import (
    AdmissionConfig,
    AdmissionController,
    AdmissionRejected,
)
from dynamo_tpu.llm.kv_router.indexer import KvIndexer, KvIndexerSharded
from dynamo_tpu.llm.kv_router.metrics_aggregator import ProcessedEndpoints
from dynamo_tpu.llm.kv_router.protocols import (
    ForwardPassMetrics,
    KvCacheEventData,
    RouterEvent,
)
from dynamo_tpu.llm.kv_router.router import KvRouter
from dynamo_tpu.llm.protocols.common import (
    FinishReason,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.runtime.distributed import DistributedRuntime

pytestmark = pytest.mark.anyio


# ---------------------------------------------------------------------------
# SLO taxonomy + admission
# ---------------------------------------------------------------------------


def test_slo_normalization_and_default():
    assert slo.normalize_class("batch") == "batch"
    assert slo.normalize_class(" Batch ") == "batch"
    assert slo.normalize_class("INTERACTIVE") == "interactive"
    assert slo.normalize_class(None) == "interactive"
    assert slo.normalize_class("premium") == "interactive"
    assert slo.normalize_class(None, default="batch") == "batch"
    assert slo.normalize_class("junk", default="junk") == "interactive"
    assert slo.is_batch("batch") and not slo.is_batch("interactive")


def test_admission_class_weighted_inflight_cap():
    """Batch refuses at HALF the inflight cap while interactive still
    admits — cheapest-first degradation at the gate."""
    c = AdmissionController(AdmissionConfig(max_inflight=8))
    permits = [c.admit("batch"), c.admit("interactive"),
               c.admit("batch"), c.admit("batch")]
    with pytest.raises(AdmissionRejected) as exc:
        c.admit("batch")          # 4 inflight >= 8 * 0.5
    assert exc.value.reason == "inflight_cap"
    # Interactive keeps its full headroom.
    for _ in range(4):
        permits.append(c.admit("interactive"))
    with pytest.raises(AdmissionRejected):
        c.admit("interactive")    # now at the real cap
    snap = c.snapshot()
    assert snap["rejected_by_class"] == {"batch": 1, "interactive": 1}
    assert snap["inflight_by_class"]["interactive"] == 5
    for p in permits:
        p.release()
    assert c.inflight == 0
    assert c.snapshot()["inflight_by_class"] == {
        "interactive": 0, "batch": 0,
    }


def test_admission_class_weighted_engine_watermark():
    stats = {"num_requests_waiting": 30}
    c = AdmissionController(
        AdmissionConfig(max_engine_waiting=50),
        engine_stats=lambda: stats,
    )
    # 30 waiting: over batch's effective watermark (25), under
    # interactive's (50).
    with pytest.raises(AdmissionRejected) as exc:
        c.admit("batch")
    assert exc.value.reason == "engine_waiting"
    c.admit("interactive").release()


def test_retry_after_is_load_proportional_and_capped():
    stats = {"num_requests_waiting": 0}
    c = AdmissionController(
        AdmissionConfig(
            max_engine_waiting=10, retry_after_s=1.0, retry_after_max_s=6.0
        ),
        engine_stats=lambda: stats,
    )
    stats["num_requests_waiting"] = 20
    with pytest.raises(AdmissionRejected) as exc:
        c.admit()
    assert exc.value.retry_after_s == pytest.approx(2.0)   # 20/10 * base
    stats["num_requests_waiting"] = 500
    with pytest.raises(AdmissionRejected) as exc:
        c.admit()
    assert exc.value.retry_after_s == pytest.approx(6.0)   # capped
    # Per-reason hints surfaced for operators (and the 429 body).
    assert c.snapshot()["retry_after_by_reason"]["engine_waiting"] == 6.0


def test_retry_after_inflight_cap_scales_with_overshoot():
    c = AdmissionController(AdmissionConfig(
        max_inflight=4, retry_after_s=1.0, retry_after_max_s=30.0,
    ))
    held = [c.admit("interactive") for _ in range(3)]
    with pytest.raises(AdmissionRejected) as exc:
        c.admit("batch")          # batch cap is 2; 3 inflight = 1.5x
    assert exc.value.retry_after_s == pytest.approx(1.5)
    for p in held:
        p.release()


# ---------------------------------------------------------------------------
# Scheduler shed/preempt order
# ---------------------------------------------------------------------------


def _cfg(**kw) -> EngineConfig:
    base = dict(
        model=ModelConfig.tiny_test(), num_blocks=64, max_num_seqs=4,
        max_model_len=128, dtype="float32",
    )
    base.update(kw)
    return EngineConfig(**base)


def _seq(rid: str, cls: str = "interactive", arrival: float = 0.0,
         tokens: int = 8) -> Sequence:
    emitted = []
    s = Sequence(
        request_id=rid,
        prompt_tokens=list(range(1, tokens + 1)),
        sampling=SamplingOptions(),
        stop=StopConditions(max_tokens=4),
        emit=lambda t, f, lp=None: emitted.append((t, f)),
        slo_class=cls,
    )
    s.arrival_s = arrival
    s._emitted = emitted  # test hook
    return s


def test_shed_order_batch_before_interactive_at_equal_age():
    """The ISSUE's shed-order proof: with an over-bound waiting list,
    the victim is the oldest BATCH entry even when an interactive entry
    is equally old (or older)."""
    cfg = _cfg(max_waiting=2)
    sched = Scheduler(cfg, BlockAllocator(cfg.num_blocks, cfg.block_size))
    t0 = time.monotonic() - 10.0
    old_interactive = _seq("i-old", "interactive", arrival=t0)
    old_batch = _seq("b-old", "batch", arrival=t0)      # equal age
    sched.add(old_interactive)
    sched.add(old_batch)
    sched.add(_seq("i-new", "interactive", arrival=t0 + 5))
    # Over the bound: the batch entry is shed, not the (equally old,
    # queue-head) interactive one.
    assert old_batch.status is SeqStatus.FINISHED
    assert old_batch._emitted[-1][1] is not None
    assert old_interactive.status is SeqStatus.WAITING
    assert [s.request_id for s in sched.waiting] == ["i-old", "i-new"]


def test_shed_order_falls_back_to_oldest_without_batch():
    cfg = _cfg(max_waiting=2)
    sched = Scheduler(cfg, BlockAllocator(cfg.num_blocks, cfg.block_size))
    t0 = time.monotonic() - 10.0
    a = _seq("i-a", "interactive", arrival=t0)
    b = _seq("i-b", "interactive", arrival=t0 + 1)
    sched.add(a)
    sched.add(b)
    sched.add(_seq("i-c", "interactive", arrival=t0 + 2))
    assert a.status is SeqStatus.FINISHED       # oldest-first (legacy)
    assert [s.request_id for s in sched.waiting] == ["i-b", "i-c"]


def test_preempt_victim_prefers_batch():
    cfg = _cfg()
    sched = Scheduler(cfg, BlockAllocator(cfg.num_blocks, cfg.block_size))
    t0 = time.monotonic() - 10.0
    batch_old = _seq("b", "batch", arrival=t0)
    inter_new = _seq("i", "interactive", arrival=t0 + 5)
    for s in (batch_old, inter_new):
        assert sched.admit(s)
    # The newest-arrival rule would pick the interactive sequence; the
    # class rule overrides: batch pays first, even when older.
    victim = sched._pick_victim(exclude=None)
    assert victim is batch_old
    sched.finish(batch_old, FinishReason.STOP)
    assert sched._pick_victim(exclude=None) is inter_new


def test_waiting_by_class_split():
    cfg = _cfg()
    sched = Scheduler(cfg, BlockAllocator(cfg.num_blocks, cfg.block_size))
    sched.add(_seq("a", "interactive"))
    sched.add(_seq("b", "batch"))
    sched.add(_seq("c", "batch"))
    assert sched.waiting_by_class() == {"interactive": 1, "batch": 2}


def test_request_class_rides_the_wire_to_the_sequence():
    from dynamo_tpu.engine.engine import _request_class

    pre = PreprocessedRequest(
        token_ids=[1, 2, 3],
        annotations={slo.ANNOTATION_KEY: "batch"},
    )
    wire = PreprocessedRequest.from_wire(pre.to_wire())
    assert _request_class(wire) == "batch"
    assert _request_class(PreprocessedRequest(token_ids=[1])) == (
        "interactive"
    )
    # Unknown labels degrade to interactive — the class system can
    # never worsen legacy traffic.
    pre.annotations[slo.ANNOTATION_KEY] = "vip"
    assert _request_class(pre) == "interactive"


def test_per_class_metric_names_on_every_surface():
    """DT011's runtime twin for the new per-class gauges: the exporter
    table and ForwardPassMetrics must carry every name the engine
    registers (a scrape must never AttributeError)."""
    from dynamo_tpu.llm.metrics_exporter import _GAUGES

    names = {
        "num_waiting_interactive", "num_waiting_batch",
        "shed_interactive_total", "shed_batch_total",
    }
    exported = {name for name, _ in _GAUGES}
    assert names <= exported
    m = ForwardPassMetrics()
    for n in names:
        assert hasattr(m, n)
    wire = ForwardPassMetrics(num_waiting_batch=3, shed_batch_total=2)
    back = ForwardPassMetrics.from_wire(wire.to_wire())
    assert back.num_waiting_batch == 3 and back.shed_batch_total == 2


def test_decode_law_class_weighted_pressure():
    from dynamo_tpu.planner.pools import DecodeLaw, FleetSample

    law = DecodeLaw(waiting_up_per_worker=2.0, batch_weight=0.5)
    # 3 batch waiters alone (weighted 1.5) are NOT an emergency...
    s = FleetSample(waiting=3.0, waiting_interactive=0.0,
                    waiting_batch=3.0)
    assert law.decide(s, n=1) == "hold"
    # ...but 3 interactive waiters are.
    s = FleetSample(waiting=3.0, waiting_interactive=3.0,
                    waiting_batch=0.0)
    assert law.decide(s, n=1) == "up"
    # Class-blind samples fall back to the unsplit axis unchanged.
    s = FleetSample(waiting=3.0)
    assert law.decide(s, n=1) == "up"


def test_prefill_queue_entry_is_class_tagged():
    """The disagg queue entry carries the class, and the consumer
    threads it into its prefill sequences (llm/slo.py ANNOTATION_KEY
    through the PreprocessedRequest annotations)."""
    pre = PreprocessedRequest(
        token_ids=[1, 2], annotations={"request_class": "batch"},
    )
    # The tag the decode operator writes into the queue entry:
    assert (pre.annotations or {}).get(
        "request_class", "interactive"
    ) == "batch"
    from dynamo_tpu.engine.engine import _request_class

    consumer_pre = PreprocessedRequest(
        token_ids=[1, 2],
        annotations={"request_class": "batch"},
    )
    assert _request_class(consumer_pre) == "batch"


# ---------------------------------------------------------------------------
# Mark-dead propagation + sharded-indexer churn
# ---------------------------------------------------------------------------


async def test_mark_dead_broadcast_reaches_sibling_replicas():
    """Regression (ISSUE 14 satellite): PR 13's one-step eviction pruned
    only the OBSERVING replica's view; the worker_dead broadcast must
    clear the corpse from every sibling's radix index AND metrics
    snapshot within one apply."""
    drt = await DistributedRuntime.in_process()
    comp = drt.namespace("t").component("w")
    a = await KvRouter(drt, comp, replica_id=0).start()
    b = await KvRouter(drt, comp, replica_id=1).start()
    try:
        ev = RouterEvent(
            0xAB, KvCacheEventData(kind="stored", block_hashes=[1, 2, 3]),
            published_unix=time.time(),
        )
        await drt.bus.broadcast(
            comp.event_subject("kv_events"), msgpack.packb(ev.to_wire())
        )
        await asyncio.sleep(0.05)
        b.aggregator.endpoints = ProcessedEndpoints(
            metrics={0xAB: ForwardPassMetrics()}, stamp=time.monotonic()
        )
        assert await a.indexer.find_matches([1, 2, 3]) == {0xAB: 3}
        assert await b.indexer.find_matches([1, 2, 3]) == {0xAB: 3}
        a.note_worker_dead(0xAB)
        # One broadcast + one apply later, the SIBLING stopped scoring.
        for _ in range(50):
            await asyncio.sleep(0.01)
            if not await b.indexer.find_matches([1, 2, 3]):
                break
        assert await b.indexer.find_matches([1, 2, 3]) == {}
        assert 0xAB not in b.aggregator.endpoints.metrics
        assert await a.indexer.find_matches([1, 2, 3]) == {}
    finally:
        await a.stop()
        await b.stop()
        await drt.shutdown()


async def test_sharded_indexer_churn_converges_to_oracle():
    """ISSUE 14 satellite: concurrent apply + worker removal + rejoin
    must converge to the unsharded oracle's matches, with publish→apply
    staleness measured through the churn window."""
    import random

    rng = random.Random(7)
    sharded = KvIndexerSharded(4).start()
    oracle = KvIndexer().start()
    workers = list(range(1, 9))
    chains = {
        w: [w * 1000 + i for i in range(8)] for w in workers
    }

    def feed(ev: RouterEvent) -> None:
        sharded.apply(ev)
        oracle.apply(ev)

    async def churn(w: int) -> None:
        for round_ in range(3):
            parent = None
            for h in chains[w]:
                feed(RouterEvent(
                    w,
                    KvCacheEventData(
                        kind="stored", block_hashes=[h], parent_hash=parent
                    ),
                    published_unix=time.time(),
                ))
                parent = h
                if rng.random() < 0.3:
                    await asyncio.sleep(0)
            if round_ < 2 and w % 2 == 0:
                # Removal (death) then rejoin with a fresh store pass.
                feed(RouterEvent(w, KvCacheEventData(kind="cleared")))
                await asyncio.sleep(0)

    await asyncio.gather(*[churn(w) for w in workers])
    for w in workers:
        probe = chains[w] + [w * 1000 + 99]
        assert await sharded.find_matches(probe) == (
            await oracle.find_matches(probe)
        )
    # Staleness stayed measured through the churn window.
    stats = sharded.stats()
    assert stats["kv_event_lag_count"] > 0
    assert stats["kv_events_applied_total"] == (
        oracle.stats()["kv_events_applied_total"]
    )
    await sharded.stop()
    await oracle.stop()


async def test_worker_dead_event_kind_prunes_like_cleared():
    idx = KvIndexer().start()
    idx.apply(RouterEvent(
        5, KvCacheEventData(kind="stored", block_hashes=[50, 51]),
        published_unix=time.time(),
    ))
    assert await idx.find_matches([50, 51]) == {5: 2}
    idx.apply(RouterEvent(5, KvCacheEventData(kind="worker_dead")))
    assert await idx.find_matches([50, 51]) == {}
    await idx.stop()


# ---------------------------------------------------------------------------
# Replica fleet: kill / failover / rejoin / staleness
# ---------------------------------------------------------------------------


async def test_replica_kill_fails_over_and_rejoin_staleness_measured():
    """The replica-death story symmetric to PR 13's worker story: a
    killed replica's in-flight requests fail over to the survivor via
    the frontend FailoverEngine (byte-identical streams under the
    deterministic mocker), and the rejoined replica's missed-event lag
    is MEASURED.

    The rejoin leg also proves the re-announce repair end to end
    (docs/architecture/kvbm_g4.md): each worker runs a ``Reannouncer``
    on the KV event plane, and the rejoined replica's fresh radix view
    must re-cover a prefix stored BEFORE its downtime — events its
    subscription can never replay — before any post-rejoin traffic
    could have re-published it."""
    from benchmarks.chaos_bench import expected_stream
    from dynamo_tpu.block_manager.config import KvbmConfig, KvLayoutConfig
    from dynamo_tpu.block_manager.manager import KvBlockManager
    from dynamo_tpu.block_manager.peer import Reannouncer
    from dynamo_tpu.llm.kv_router.publisher import (
        KvEventPublisher,
        WorkerMetricsPublisher,
    )
    from dynamo_tpu.llm.kv_router.replicas import RouterReplicaSet
    from dynamo_tpu.llm.tokens import TokenBlockSequence
    from dynamo_tpu.mocker import MockerConfig, MockerEngine
    from dynamo_tpu.runtime.egress import PushRouter
    from dynamo_tpu.runtime.engine import Context
    from dynamo_tpu.runtime.failover import FailoverEngine
    from dynamo_tpu.utils.tracing import tracer

    vocab = 997
    drt0 = await DistributedRuntime.in_process()

    async def sub_drt():
        return await DistributedRuntime.in_process(
            store=drt0.store, bus=drt0.bus, runtime=drt0.runtime
        )

    layout = KvLayoutConfig(
        num_layers=1, page_size=1, num_kv_heads=1, head_dim=4,
        dtype="float32",
    )  # 8-float rows: the mocker runner's simulated KV geometry
    workers = []
    for i in range(2):
        drt = await sub_drt()
        comp = drt.namespace("rt").component("w")
        kvbm = await KvBlockManager(
            KvbmConfig(layout=layout, host_blocks=64)
        ).start()
        eng = MockerEngine(
            _cfg(num_blocks=256, enable_prefix_caching=True),
            MockerConfig(
                vocab_size=vocab, seed=i, deterministic_tokens=True,
                decode_time_per_step_us=4000.0,
            ),
            block_manager=kvbm,
        )
        pub = KvEventPublisher(drt, comp, drt.primary_lease_id)
        wm = WorkerMetricsPublisher()
        eng._external_kv_event = pub.publish_engine_event
        eng._on_metrics = wm.publish
        await eng.start()
        inst = await comp.endpoint("generate").serve(eng)
        await wm.create_endpoint(comp)
        # interval_s way out: only the rejoin-triggered broadcast may
        # drive the announce, so convergence below proves the trigger
        # path and not a lucky periodic tick.
        ann = await Reannouncer(
            drt, comp, pub, kvbm.host_entries, interval_s=3600.0
        ).start()
        workers.append((inst, eng, ann, kvbm))

    rs = await RouterReplicaSet(sub_drt, "rt.w.generate").start(2)
    push = await PushRouter.create(
        drt0, "rt.router.generate", connect_timeout_s=2.0
    )
    front = FailoverEngine(push)

    async def one(i: int, osl: int = 10):
        prompt = [(i * 7 + j) % (vocab - 1) + 1 for j in range(24)]
        req = PreprocessedRequest(
            token_ids=prompt,
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=osl, ignore_eos=True),
        )
        ctx = Context(req.to_wire())
        out = []
        async for item in front.generate(ctx):
            out += item.get("token_ids", [])
        tracer().finish(ctx.id)
        assert out == expected_stream(prompt, osl, vocab)

    # The probe prefix: request 0's prompt, served (and its KV events
    # published) strictly BEFORE the kill. Its one full block is what
    # the rejoined replica must re-learn from re-announce alone.
    probe = [(0 * 7 + j) % (vocab - 1) + 1 for j in range(24)]
    probe_hashes = TokenBlockSequence.from_tokens(
        probe, block_size=16
    ).sequence_hashes()
    assert probe_hashes  # 24 tokens -> at least one full block

    async def _wait(pred, timeout_s: float, what: str):
        deadline = asyncio.get_running_loop().time() + timeout_s
        while not pred():
            assert (
                asyncio.get_running_loop().time() < deadline
            ), f"timed out waiting for {what}"
            await asyncio.sleep(0.02)

    try:
        await asyncio.gather(*[one(i) for i in range(4)])
        # The probe block must reach some worker's host tier (the
        # re-announce payload source) before the replica dies.
        await _wait(
            lambda: any(
                probe_hashes[0] in {e[0] for e in kvbm.host_entries()}
                for _, _, _, kvbm in workers
            ),
            5.0, "probe block host offload",
        )

        async def killer():
            await asyncio.sleep(0.02)
            await rs.kill(rs.replicas[0])

        # Mid-stream kill: every request still completes byte-identical.
        await asyncio.gather(
            asyncio.gather(*[one(10 + i) for i in range(6)]), killer()
        )
        # Traffic while replica 0 is down builds the lag it will rejoin
        # with (KV events it can never see).
        await asyncio.gather(*[one(30 + i) for i in range(4)])
        announces_before = sum(a.announces_total for _, _, a, _ in workers)
        await rs.rejoin(rs.replicas[0])

        # Re-announce e2e, BEFORE any post-rejoin traffic: rejoin's
        # broadcast must reach the worker Reannouncers, and their
        # republished stored events must rebuild the probe prefix in
        # the rejoined replica's fresh radix view.
        rejoined = rs.replicas[0]

        async def _probe_depth() -> int:
            m = await rejoined.service.kv_router.indexer.find_matches(
                probe_hashes
            )
            return max(m.values(), default=0)

        depth = 0

        async def _converged() -> bool:
            nonlocal depth
            depth = await _probe_depth()
            return depth >= 1

        deadline = asyncio.get_running_loop().time() + 5.0
        while not await _converged():
            assert (
                asyncio.get_running_loop().time() < deadline
            ), "rejoined radix view never re-covered the pre-kill prefix"
            await asyncio.sleep(0.02)
        assert sum(
            a.announces_total for _, _, a, _ in workers
        ) > announces_before

        # And the prediction quality converges with it: re-request the
        # probe prompt until the REJOINED replica decides one — its
        # predicted overlap must be back to the actual (>= the probe's
        # full block), not pinned at the stale zero a rejoin without
        # re-announce would carry forever (|predicted-actual| for
        # pre-downtime prefixes collapses back under the fleet bound;
        # the capture-wide p95 version of this claim runs in
        # benchmarks/route_audit.py via the ingress bench).
        from dynamo_tpu.llm.kv_router.audit import ROUTE_OBS

        routes_before = ROUTE_OBS.routes_total
        rejoined_overlap = None
        for _ in range(12):
            await one(0)
            snap = ROUTE_OBS.snapshot(64)
            fresh = snap["recent"][-(snap["routes_total"] - routes_before):]
            probe_recs = [
                r for r in fresh
                if r["replica_id"] == 0
                and r["isl_blocks"] == (len(probe) + 15) // 16
            ]
            if probe_recs:
                rejoined_overlap = max(
                    r["overlap_blocks"] for r in probe_recs
                )
                break
        assert rejoined_overlap is not None, (
            "rejoined replica never decided a probe request"
        )
        assert rejoined_overlap >= len(probe_hashes)

        await asyncio.gather(*[one(50 + i) for i in range(4)])
        await asyncio.sleep(0.1)
        st = rs.staleness()
        rec = st["replicas"][0]
        assert rec["rejoined"] is True
        # Missed-history divergence is MEASURED, not assumed away.
        assert rec["applied_lag"] > 0
        assert st["applied_max"] > 0
    finally:
        await rs.stop()
        for inst, eng, ann, kvbm in workers:
            await ann.stop()
            await inst.stop()
            await eng.stop()
            await kvbm.stop()
        await drt0.shutdown()


# ---------------------------------------------------------------------------
# The replay harness, end to end (small scale)
# ---------------------------------------------------------------------------


async def test_ingress_bench_smoke_gates(tmp_path, monkeypatch):
    """A small-scale run of the 100k harness with its FULL gate set:
    replica kill + rejoin, overload burst shedding batch-first, per-
    class TTFT SLOs, and the multi-replica route-audit bound over the
    merged capture."""
    from benchmarks.ingress_bench import run_gates, run_ingress
    from dynamo_tpu.utils.tracing import reset_tracer

    capture = tmp_path / "ingress.jsonl"
    monkeypatch.setenv("DYNTPU_TRACE", str(capture))
    reset_tracer(str(capture))
    try:
        report = await run_ingress(
            requests=400, workers=2, replicas=2, concurrency=64,
            max_inflight=220, burst_extra=90, burst_attempts=300,
            watchdog_s=120.0,
        )
        # At 400 requests the rejoined replica's post-rejoin (stale)
        # window dominates its route sample, so its error bound is
        # looser here than the full-scale leg's default: staleness
        # decays as live traffic re-stores the hot prefix blocks, which
        # a 400-request tail can't amortize the way 100k do.
        failures = run_gates(report, max_abs_p95=8.0)
        assert not failures, failures
        assert report["by_status"].get("hang", 0) == 0
        assert report["burst"]["batch_shed"] > 0
        assert report["chaos"]["rejoined_lag_max"] > 0
        assert report["route_audit"]["per_replica"]
    finally:
        reset_tracer(None)

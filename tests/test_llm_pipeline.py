"""LLM library tests: tokenizer, preprocessor, detokenizer/stop jailing."""

import os

import pytest

from dynamo_tpu.llm.backend import Detokenizer, StopStringJail
from dynamo_tpu.llm.engines import EchoEngineCore
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
from dynamo_tpu.llm.protocols.common import EngineOutput, FinishReason
from dynamo_tpu.llm.protocols.openai import ChatCompletionRequest
from dynamo_tpu.llm.tokenizer import HfTokenizer, ToyTokenizer
from dynamo_tpu.runtime.engine import Context, EngineAdapter
from dynamo_tpu.runtime.pipeline import Pipeline

pytestmark = pytest.mark.anyio

TINYLLAMA_DIR = "/root/reference/lib/llm/tests/data/sample-models/TinyLlama_v1.1"


def test_toy_tokenizer_roundtrip():
    tok = ToyTokenizer()
    text = "héllo wörld ✓"
    assert tok.decode(tok.encode(text)) == text


def test_toy_incremental_decode_multibyte():
    tok = ToyTokenizer()
    ids = tok.encode("a✓b")
    stream = tok.decode_stream()
    out = []
    for tid in ids:
        piece = stream.step(tid)
        if piece is not None:
            out.append(piece)
    assert "".join(out) == "a✓b"
    # The 3-byte ✓ must have been held until complete.
    assert out == ["a", "✓", "b"]


@pytest.mark.skipif(not os.path.isdir(TINYLLAMA_DIR), reason="fixture missing")
def test_hf_tokenizer_fixture():
    tok = HfTokenizer(TINYLLAMA_DIR)
    ids = tok.encode("Hello, TPU world!")
    assert ids
    assert "TPU" in tok.decode(ids)
    stream = tok.decode_stream()
    text = "".join(p for p in (stream.step(t) for t in ids) if p)
    assert "TPU world" in text


def _chat_request(**kwargs) -> ChatCompletionRequest:
    return ChatCompletionRequest.model_validate(
        {
            "model": "test",
            "messages": [{"role": "user", "content": "hi there"}],
            **kwargs,
        }
    )


def test_preprocessor_templates_and_limits():
    card = ModelDeploymentCard(name="test", context_length=64)
    pre_op = OpenAIPreprocessor(card, ToyTokenizer())
    pre = pre_op.preprocess(_chat_request(max_tokens=1000))
    prompt = pre.annotations["formatted_prompt"]
    assert "<|user|>hi there" in prompt
    assert "<|assistant|>" in prompt
    # max_tokens clamped to remaining context budget.
    assert pre.stop.max_tokens == 64 - len(pre.token_ids)
    # eos token ids folded into stop ids
    assert ToyTokenizer.EOS in pre.stop.stop_token_ids


def test_preprocessor_rejects_oversized_prompt():
    card = ModelDeploymentCard(name="test", context_length=4)
    pre_op = OpenAIPreprocessor(card, ToyTokenizer())
    with pytest.raises(ValueError, match="exceeds context length"):
        pre_op.preprocess(_chat_request())


def test_stop_string_jail():
    jail = StopStringJail(["STOP"])
    emit, hit = jail.push("hello S")
    assert emit == "hello " and not hit
    emit, hit = jail.push("T")
    assert emit == "" and not hit
    emit, hit = jail.push("OP ignored tail")
    assert emit == "" and hit

    # Prefix that fails to complete is released.
    jail2 = StopStringJail(["STOP"])
    emit, _ = jail2.push("ST")
    assert emit == ""
    emit, hit = jail2.push("ART")
    assert emit == "START" and not hit


async def test_detokenizer_stop_string_ends_stream():
    tok = ToyTokenizer()

    async def engine(ctx):
        for tid in tok.encode("hello STOP never"):
            yield EngineOutput(token_ids=[tid]).to_wire()

    pre = OpenAIPreprocessor(ModelDeploymentCard(name="t"), tok).preprocess(
        _chat_request(stop=["STOP"])
    )
    pipeline = Pipeline.link(Detokenizer(tok), engine=EngineAdapter(engine))
    outs = [
        EngineOutput.from_wire(o)
        async for o in pipeline.generate(Context(pre.to_wire()))
    ]
    text = "".join(o.text or "" for o in outs)
    assert text == "hello "
    assert outs[-1].finish_reason is FinishReason.STOP


async def test_echo_pipeline_end_to_end():
    tok = ToyTokenizer()
    card = ModelDeploymentCard(name="echo")
    pipeline = Pipeline.link(
        OpenAIPreprocessor(card, tok),
        Detokenizer(tok),
        engine=EchoEngineCore(),
    )
    chunks = [c async for c in pipeline.generate(Context(_chat_request()))]
    text = "".join(
        ch.choices[0].delta.content or ""
        for ch in chunks
        if ch.choices and ch.choices[0].delta.content
    )
    # Echo returns the templated prompt text.
    assert "hi there" in text
    usage = chunks[-1].usage
    assert usage is not None and usage.completion_tokens > 0


@pytest.mark.skipif(not os.path.isdir(TINYLLAMA_DIR), reason="fixture missing")
async def test_mdc_artifact_shipping_roundtrip(tmp_path):
    """Prompt-formatter artifacts (tokenizer files + chat template) ship
    through the object store so a frontend on another host materializes a
    working tokenizer without a shared filesystem (reference:
    model_card/model.rs:232-328 move_to_nats/move_from_nats)."""
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.tokenizer import load_tokenizer
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    drt = await DistributedRuntime.in_process()
    card = ModelDeploymentCard(name="tiny-ship", model_path=TINYLLAMA_DIR)
    await card.publish(drt.bus)
    assert "tokenizer.json" in card.extra["artifacts"]

    fetched = await ModelDeploymentCard.fetch(drt.bus, "tiny-ship")
    fetched.model_path = "/nonexistent/worker/path"  # other-host view
    assert await fetched.materialize(drt.bus, tmp_path)
    assert str(tmp_path) in fetched.model_path

    text = "hello tpu world"
    assert load_tokenizer(fetched.model_path).encode(text) == load_tokenizer(
        TINYLLAMA_DIR
    ).encode(text)
    await drt.shutdown()

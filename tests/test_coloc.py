"""SLO-aware prefill/decode co-location (engine/coloc.py; ROADMAP #3):
controller convergence from both sides, floor + deadband behavior,
per-phase admission, compose_unified deferral fairness, the phase-aware
HTTP admission watermark, and the mocker e2e where a prefill burst
arrives mid-decode and ITL stays within the SLO."""

import asyncio
import dataclasses
import time

import numpy as np
import pytest

from dynamo_tpu.engine.coloc import ColocController
from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.scheduler import compose_unified
from dynamo_tpu.llm.admission import (
    AdmissionConfig,
    AdmissionController,
    AdmissionRejected,
)
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.runtime.engine import Context

pytestmark = pytest.mark.anyio

SLO = 10.0


def _cfg(**kw) -> EngineConfig:
    base = dict(
        model=ModelConfig.tiny_test(), num_blocks=64, max_model_len=256,
        unified=True, unified_token_budget=1024,
        unified_prefill_quantum=64, coloc="adaptive", itl_slo_ms=SLO,
        coloc_min_quantum=16,
    )
    base.update(kw)
    return EngineConfig(**base)


def _drive(ctrl: ColocController, cost_ms, steps: int) -> None:
    """Closed loop: each observed sample is the cost of the quantum the
    controller chose for that step (cost_ms: quantum -> ms)."""
    for _ in range(steps):
        ctrl.observe(cost_ms(ctrl.quantum), decode_lanes=8,
                     prefill_tokens=ctrl.quantum)


# ---------------------------------------------------------------------------
# controller convergence
# ---------------------------------------------------------------------------


def test_oversized_quantum_shrinks_until_itl_meets_slo():
    """From a way-oversized hand-tuned quantum, the loop must converge
    to dispatches within the SLO without collapsing to the floor (the
    cost model leaves plenty of feasible quantum above it)."""
    ctrl = ColocController(_cfg(unified_prefill_quantum=1024))
    cost = lambda q: 2.0 + 0.01 * q  # noqa: E731 — 1024 -> 12.2 ms > SLO
    _drive(ctrl, cost, 200)
    assert cost(ctrl.quantum) <= SLO
    assert ctrl.itl_ema_ms <= SLO
    assert ctrl.quantum < 1024
    assert ctrl.quantum > ctrl.floor  # feasible region is far above 16
    assert ctrl.itl_slo_violations_total >= 1  # the oversized start


def test_undersized_quantum_grows_until_budget_limited():
    """With negligible per-token cost, nothing stops growth before the
    token budget cap — the controller must find it."""
    ctrl = ColocController(_cfg(unified_prefill_quantum=16))
    _drive(ctrl, lambda q: 2.0 + 0.0001 * q, 200)
    assert ctrl.quantum == ctrl.cap == 1024
    assert ctrl.itl_slo_violations_total == 0


def test_undersized_quantum_grows_into_deadband_and_holds():
    """Growth stops inside [headroom_frac * SLO, SLO] — the deadband —
    and stays there: no persistent oscillation under steady load."""
    ctrl = ColocController(_cfg(unified_prefill_quantum=16))
    cost = lambda q: 2.0 + 0.01 * q  # noqa: E731
    _drive(ctrl, cost, 300)
    band = (ctrl.headroom_frac * SLO, SLO)
    assert band[0] <= cost(ctrl.quantum) <= band[1]
    trace = []
    for _ in range(100):
        _drive(ctrl, cost, 1)
        trace.append(ctrl.quantum)
    # Steady state: the quantum must not keep sawing (AIMD converged
    # into the deadband; at most one grow step of residual motion).
    assert max(trace) - min(trace) <= ctrl.grow_tokens


def test_floor_respected_under_sustained_slo_pressure():
    """When even zero prefill can't meet the SLO (decode alone is over),
    the quantum pins at the floor — prefill never fully starves — and
    every dispatch counts a violation."""
    ctrl = ColocController(_cfg(unified_prefill_quantum=512))
    _drive(ctrl, lambda q: 2 * SLO, 100)
    assert ctrl.quantum == ctrl.floor
    assert ctrl.itl_slo_violations_total == 100
    _drive(ctrl, lambda q: 2 * SLO, 50)
    assert ctrl.quantum == ctrl.floor  # stays pinned, never below


def test_prefill_only_dispatches_are_not_itl_evidence():
    ctrl = ColocController(_cfg())
    ctrl.observe(500.0, decode_lanes=0, prefill_tokens=256)
    assert ctrl.steps_observed == 0
    assert ctrl.itl_ema_ms == 0.0
    assert ctrl.quantum == 64  # no adaptation off non-evidence


def test_static_mode_measures_but_never_adapts():
    """coloc='static' with an SLO set is monitoring-only: violations
    and EMA are tracked, the quantum stays hand-tuned, and per-phase
    admission never defers (legacy behavior, the A/B control)."""
    ctrl = ColocController(_cfg(coloc="static", unified_prefill_quantum=96))
    _drive(ctrl, lambda q: 2 * SLO, 50)
    assert ctrl.quantum == 96
    assert ctrl.itl_slo_violations_total == 50
    assert ctrl.itl_ema_ms > SLO
    assert ctrl.admit_prefill() is True
    assert ctrl.prefill_deferrals_total == 0


# ---------------------------------------------------------------------------
# per-phase admission
# ---------------------------------------------------------------------------


def test_admit_prefill_defers_under_pressure_with_bounded_streak():
    ctrl = ColocController(_cfg(), max_defer_steps=5)
    _drive(ctrl, lambda q: 2 * SLO, 10)  # in violation
    assert ctrl.under_pressure
    decisions = [ctrl.admit_prefill() for _ in range(6)]
    # 5 consecutive deferrals, then the anti-starvation valve admits.
    assert decisions == [False] * 5 + [True]
    assert ctrl.prefill_deferrals_total == 5
    # Pressure relieved -> admission resumes immediately.
    _drive(ctrl, lambda q: 1.0, 50)
    assert not ctrl.under_pressure
    assert ctrl.admit_prefill() is True
    assert ctrl.prefill_deferrals_total == 5


def test_config_validation_rejects_bad_coloc_combos():
    with pytest.raises(ValueError, match="coloc="):
        _cfg(coloc="magic").validate()
    with pytest.raises(ValueError, match="requires unified"):
        _cfg(unified=False).validate()
    with pytest.raises(ValueError, match="itl_slo_ms"):
        _cfg(itl_slo_ms=0.0).validate()
    with pytest.raises(ValueError, match="coloc_min_quantum"):
        _cfg(coloc_min_quantum=4096).validate()
    _cfg().validate()  # the good combo
    # Static + SLO-less stays valid (the historical default).
    _cfg(coloc="static", itl_slo_ms=0.0).validate()


# ---------------------------------------------------------------------------
# compose_unified deferral fairness (rotation)
# ---------------------------------------------------------------------------


def test_compose_rotation_round_robins_deferral():
    lanes = [f"d{i}" for i in range(8)]
    served: dict[str, int] = {l: 0 for l in lanes}
    rotation = 0
    steps = 16
    for _ in range(steps):
        take, _ = compose_unified(lanes, [], budget=4, quantum=2,
                                  rotation=rotation)
        assert len(take) == 4
        rotation += len(take)
        for l in take:
            served[l] += 1
    # Half the population fits per step; over 16 steps every lane is
    # served exactly half the time — round-robin, not head-first.
    assert set(served.values()) == {steps // 2}


def test_compose_rotation_bounds_lane_itl_vs_population_median():
    """No lane's deferral gap may be unboundedly worse than the
    population median: with N lanes and M slots the worst wait between
    services is bounded by ceil(N/M) steps for EVERY lane."""
    n_lanes, budget = 10, 3
    lanes = list(range(n_lanes))
    last_served = {l: 0 for l in lanes}
    worst_gap = {l: 0 for l in lanes}
    rotation = 0
    for step in range(1, 61):
        take, _ = compose_unified(lanes, [], budget=budget, quantum=budget,
                                  rotation=rotation)
        rotation += len(take)
        for l in take:
            worst_gap[l] = max(worst_gap[l], step - last_served[l])
            last_served[l] = step
    gaps = sorted(worst_gap.values())
    median = gaps[len(gaps) // 2]
    bound = -(-n_lanes // budget) + 1  # ceil + slack for the first lap
    assert max(gaps) <= bound
    assert max(gaps) <= 2 * median  # nobody unboundedly worse


def test_compose_rotation_default_keeps_legacy_order():
    take, _ = compose_unified(["a", "b", "c"], [], budget=2, quantum=1)
    assert take == ["a", "b"]  # rotation=0: byte-compatible with PR 6


# ---------------------------------------------------------------------------
# phase-aware HTTP admission watermark
# ---------------------------------------------------------------------------


def test_admission_prefill_backlog_watermark():
    stats = {"prefill_backlog_tokens": 0, "num_requests_waiting": 50}
    gate = AdmissionController(
        AdmissionConfig(max_prefill_backlog_tokens=4096),
        engine_stats=lambda: stats,
    )
    # Deep queue of decode-bound (tiny-backlog) work: NOT shed — the
    # request-count watermark is off and the token watermark sees the
    # real prefill pressure, which is none.
    with gate.admit():
        pass
    # A prompt-token flood trips it with its own typed reason.
    stats["prefill_backlog_tokens"] = 5000
    with pytest.raises(AdmissionRejected) as exc:
        gate.admit()
    assert exc.value.reason == "prefill_backlog"
    assert gate.rejected["prefill_backlog"] == 1


def test_metric_surfaces_carry_coloc_fields():
    """Exporter gauges are rendered via getattr on ForwardPassMetrics —
    every declared gauge must exist there, including the new coloc set,
    and survive the wire roundtrip."""
    from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics
    from dynamo_tpu.llm.metrics_exporter import _GAUGES

    m = ForwardPassMetrics()
    for key, _help in _GAUGES:
        assert hasattr(m, key), key
    wire = m.to_wire()
    wire.update(
        coloc_quantum=640, itl_ema_ms=7.5, itl_slo_violations_total=3,
        coloc_prefill_deferrals_total=2, prefill_backlog_tokens=9000,
    )
    back = ForwardPassMetrics.from_wire(wire)
    assert back.coloc_quantum == 640
    assert back.itl_slo_violations_total == 3
    assert back.prefill_backlog_tokens == 9000


# ---------------------------------------------------------------------------
# mocker e2e: burst mid-decode
# ---------------------------------------------------------------------------


async def test_mocker_prefill_burst_mid_decode_holds_itl_slo():
    """The bench leg in miniature: a decode population is mid-stream
    when a long-prompt burst arrives; the adaptive controller must keep
    the engine-side dispatch-interval p95 within the SLO while the
    burst still completes, and the full coloc surface must show up on
    readiness, the metrics callback, and the flight recorder."""
    from dynamo_tpu.mocker import MockerConfig, MockerEngine

    slo = 15.0
    cfg = EngineConfig(
        model=ModelConfig.tiny_test(), num_blocks=512, block_size=16,
        max_num_seqs=6, max_model_len=1024, prefill_batch=2,
        dtype="float32", sampling_extras=False,
        unified=True, unified_token_budget=512,
        unified_prefill_quantum=32, coloc="adaptive", itl_slo_ms=slo,
        coloc_min_quantum=16,
    )
    sim = MockerConfig(
        prefill_time_per_token_us=10.0, prefill_quadratic_us=0.0,
        decode_time_per_step_us=1000.0, decode_time_per_lane_us=100.0,
        prefill_dispatch_base_us=2000.0,
        vocab_size=cfg.model.vocab_size,
    )
    eng = MockerEngine(cfg, sim)
    metrics: list[dict] = []
    eng._on_metrics = metrics.append
    await eng.start()
    await eng.warmup()
    rng = np.random.default_rng(3)

    async def run(isl, osl):
        req = PreprocessedRequest(
            token_ids=rng.integers(0, 1000, isl).tolist(),
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=osl, ignore_eos=True),
        )
        n = 0
        async for out in eng.generate(Context(req.to_wire())):
            n += len(out["token_ids"])
        return n

    decode_tasks = [
        asyncio.create_task(run(16, 120)) for _ in range(4)
    ]
    await asyncio.sleep(0.05)  # decode mid-stream
    q_before_burst = eng.coloc.quantum
    burst = await asyncio.gather(*[run(600, 2) for _ in range(2)])
    snap = dict(eng.coloc.snapshot())
    assert burst == [2, 2]  # the burst completed (no starvation)
    assert snap["itl_p95_ms"] <= slo, snap
    assert snap["itl_slo_violations_total"] <= max(
        1, int(0.05 * eng.coloc.steps_observed)
    ), snap
    await asyncio.gather(*decode_tasks)
    # Adaptation actually happened: the quantum moved off its
    # hand-tuned start (headroom existed, so it grew).
    assert eng.coloc.quantum != 32 or q_before_burst != 32
    # Metric surfaces: readiness + engine metrics callback.
    r = eng.readiness()
    for key in (
        "coloc_quantum", "itl_ema_ms", "itl_slo_violations_total",
        "coloc_prefill_deferrals_total", "prefill_backlog_tokens",
    ):
        assert key in r, key
    m = metrics[-1]
    assert "coloc_quantum" in m and "itl_slo_violations_total" in m
    assert "prefill_backlog_tokens" in m
    # Flight recorder: unified records carry the quantum decision the
    # trace timeline attributes ITL spikes to.
    unified_recs = [
        rec for rec in eng.debug_steps() if rec.get("kind") == "unified"
    ]
    assert unified_recs
    assert all("quantum" in rec and "itl_ema_ms" in rec
               and "headroom_ms" in rec for rec in unified_recs)
    assert any(rec["quantum"] > 0 for rec in unified_recs)
    cs = eng.runner.compile_stats
    assert cs.mid_traffic_compiles == 0, cs.mid_traffic_keys
    await eng.stop()


async def test_mocker_static_vs_adaptive_quantum_moves_simulated_itl():
    """The per-phase cost model satellite: with prefill priced per
    token, a bigger static quantum must produce measurably longer
    dispatch intervals while prompts are in flight — the observable
    the controller steers. Device-free, deterministic cost model."""
    from dynamo_tpu.mocker import MockerConfig, MockerEngine

    async def measured_ema(quantum: int) -> float:
        cfg = EngineConfig(
            model=ModelConfig.tiny_test(), num_blocks=512, block_size=16,
            max_num_seqs=4, max_model_len=1024, prefill_batch=2,
            dtype="float32", sampling_extras=False,
            unified=True, unified_token_budget=512,
            unified_prefill_quantum=quantum,
            coloc="static", itl_slo_ms=1e9,  # measure, never adapt
        )
        sim = MockerConfig(
            prefill_time_per_token_us=20.0, prefill_quadratic_us=0.0,
            decode_time_per_step_us=500.0,
            vocab_size=cfg.model.vocab_size,
        )
        eng = MockerEngine(cfg, sim)
        await eng.start()
        await eng.warmup()
        rng = np.random.default_rng(5)

        async def run(isl, osl):
            req = PreprocessedRequest(
                token_ids=rng.integers(0, 1000, isl).tolist(),
                sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=osl, ignore_eos=True),
            )
            async for _ in eng.generate(Context(req.to_wire())):
                pass

        decode = asyncio.create_task(run(16, 60))
        await asyncio.sleep(0.02)
        await asyncio.gather(run(400, 2), run(400, 2))
        ema = eng.coloc.itl_ema_ms
        await decode
        await eng.stop()
        return ema

    small = await measured_ema(16)
    large = await measured_ema(256)
    # 256-token quanta cost ~5 ms of prefill per dispatch vs ~0.3 ms:
    # the simulated ITL must visibly follow the quantum.
    assert large > small * 1.5, (small, large)

"""Operator tests (dynamo_tpu/operator/): spec → manifests rendering,
create/update/GC reconciliation against the FakeKube double, and status
write-back — the envtest-style coverage of the reference's Go operator
(reference: deploy/cloud/operator/test/e2e) without a cluster."""

import asyncio
import json

import pytest

from dynamo_tpu.operator import (
    FakeKube,
    GraphDeployment,
    GraphOperator,
    STATUS_BUCKET,
    render,
)
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.sdk.api_store import DEPLOYMENT_BUCKET

pytestmark = pytest.mark.anyio


SPEC = {
    "namespace": "dynamo",
    "services": {
        "ControlPlane": {"role": "control-plane"},
        "Frontend": {"role": "frontend", "port": 8080},
        "Worker": {
            "role": "worker",
            "replicas": 2,
            "chips": 4,
            "args": {"model_path": "/models/llama", "mesh": "tp=4"},
        },
    },
}


def test_render_manifests():
    dep = GraphDeployment.from_record({"name": "graph", "spec": SPEC})
    manifests = render(dep)
    kinds = [(m["kind"], m["metadata"]["name"]) for m in manifests]
    assert ("Deployment", "graph-worker") in kinds
    assert ("Deployment", "graph-frontend") in kinds
    assert ("Service", "graph-frontend") in kinds
    assert ("Service", "graph-controlplane") in kinds
    worker = next(
        m for m in manifests if m["metadata"]["name"] == "graph-worker"
    )
    assert worker["spec"]["replicas"] == 2
    container = worker["spec"]["template"]["spec"]["containers"][0]
    assert container["resources"]["limits"]["google.com/tpu"] == "4"
    assert "--model-path=/models/llama" in container["command"]
    # the dialed control-plane DNS name must be exactly the rendered
    # control-plane Service's name (spec names it "ControlPlane")
    assert "--control-plane=graph-controlplane:6380" in container["command"]


def test_render_rejects_unknown_role():
    with pytest.raises(ValueError):
        GraphDeployment.from_record(
            {"name": "x", "spec": {"services": {"Z": {"role": "gpu"}}}}
        )


async def _put_spec(drt, name, spec):
    await drt.bus.put_object(
        DEPLOYMENT_BUCKET, name,
        json.dumps({"name": name, "spec": spec, "revision": 1}).encode(),
    )


async def test_reconcile_create_update_gc_status():
    drt = await DistributedRuntime.in_process()
    kube = FakeKube()
    op = GraphOperator(drt, kube)
    try:
        await _put_spec(drt, "graph", SPEC)
        status = await op.reconcile_once()
        assert kube.get("Deployment", "dynamo", "graph-worker") is not None
        assert kube.get("Service", "dynamo", "graph-frontend") is not None
        assert status["graph"]["ready"] is False  # nothing ready yet
        assert status["graph"]["services"]["Worker"]["desired"] == 2

        # Unchanged spec → no re-apply (spec-hash short-circuits).
        applies = kube.apply_count
        await op.reconcile_once()
        assert kube.apply_count == applies

        # Replica bump patches the child Deployment.
        spec2 = json.loads(json.dumps(SPEC))
        spec2["services"]["Worker"]["replicas"] = 3
        await _put_spec(drt, "graph", spec2)
        await op.reconcile_once()
        worker = kube.get("Deployment", "dynamo", "graph-worker")
        assert worker["spec"]["replicas"] == 3

        # Readiness reaches the status bucket once replicas come up.
        for name in ("graph-controlplane", "graph-frontend", "graph-worker"):
            kube.mark_ready("Deployment", "dynamo", name)
        status = await op.reconcile_once()
        assert status["graph"]["ready"] is True
        raw = await drt.bus.get_object(STATUS_BUCKET, "graph")
        assert json.loads(raw)["ready"] is True

        # Removing a service garbage-collects its children; deleting the
        # spec garbage-collects everything + the status entry.
        spec3 = json.loads(json.dumps(spec2))
        del spec3["services"]["Frontend"]
        await _put_spec(drt, "graph", spec3)
        await op.reconcile_once()
        assert kube.get("Deployment", "dynamo", "graph-frontend") is None
        assert kube.get("Service", "dynamo", "graph-frontend") is None

        await drt.bus.delete_object(DEPLOYMENT_BUCKET, "graph")
        await op.reconcile_once()
        assert kube.get("Deployment", "dynamo", "graph-worker") is None
        assert await drt.bus.get_object(STATUS_BUCKET, "graph") is None
    finally:
        await drt.shutdown()


async def test_broken_spec_update_protects_running_children():
    """Updating a live deployment with an unparseable spec must hold
    state, not garbage-collect the running pods."""
    drt = await DistributedRuntime.in_process()
    kube = FakeKube()
    op = GraphOperator(drt, kube)
    try:
        await _put_spec(drt, "graph", SPEC)
        await op.reconcile_once()
        assert kube.get("Deployment", "dynamo", "graph-worker") is not None
        # typo'd role in an update
        bad = json.loads(json.dumps(SPEC))
        bad["services"]["Worker"]["role"] = "gpu"
        await _put_spec(drt, "graph", bad)
        status = await op.reconcile_once()
        assert "error" in status["graph"]
        assert kube.get("Deployment", "dynamo", "graph-worker") is not None
        # fixing the spec resumes reconciliation
        await _put_spec(drt, "graph", SPEC)
        status = await op.reconcile_once()
        assert "error" not in status["graph"]
    finally:
        await drt.shutdown()


async def test_service_port_change_reapplies_service():
    drt = await DistributedRuntime.in_process()
    kube = FakeKube()
    op = GraphOperator(drt, kube)
    try:
        await _put_spec(drt, "graph", SPEC)
        await op.reconcile_once()
        svc = kube.get("Service", "dynamo", "graph-frontend")
        assert svc["spec"]["ports"][0]["port"] == 8080
        spec2 = json.loads(json.dumps(SPEC))
        spec2["services"]["Frontend"]["port"] = 9090
        await _put_spec(drt, "graph", spec2)
        await op.reconcile_once()
        svc = kube.get("Service", "dynamo", "graph-frontend")
        assert svc["spec"]["ports"][0]["port"] == 9090
    finally:
        await drt.shutdown()


async def test_gc_covers_non_default_namespace():
    """Children rendered into a spec's own namespace are garbage-collected
    after the spec is deleted (the namespace rides the status record)."""
    drt = await DistributedRuntime.in_process()
    kube = FakeKube()
    op = GraphOperator(drt, kube)  # operator namespace stays "dynamo"
    try:
        spec = json.loads(json.dumps(SPEC))
        spec["namespace"] = "prod"
        await _put_spec(drt, "graph", spec)
        await op.reconcile_once()
        assert kube.get("Deployment", "prod", "graph-worker") is not None
        await drt.bus.delete_object(DEPLOYMENT_BUCKET, "graph")
        await op.reconcile_once()
        assert kube.get("Deployment", "prod", "graph-worker") is None
        assert kube.get("Service", "prod", "graph-frontend") is None
    finally:
        await drt.shutdown()


async def test_reconcile_survives_bad_spec():
    drt = await DistributedRuntime.in_process()
    kube = FakeKube()
    op = GraphOperator(drt, kube)
    try:
        await _put_spec(drt, "bad", {"services": {"X": {"role": "gpu"}}})
        await _put_spec(drt, "good", SPEC)
        status = await op.reconcile_once()
        assert "error" in status["bad"]
        # the good deployment still reconciles
        assert kube.get("Deployment", "dynamo", "good-worker") is not None
    finally:
        await drt.shutdown()


async def test_watch_driven_reconcile_reacts_without_resync():
    """VERDICT r03 #10: the loop is watch-driven, not a fixed-interval
    poll. With a resync interval of ONE HOUR, (a) a spec PUT through the
    api-store's notification subject and (b) an out-of-band child
    deletion seen by the cluster watch must each trigger a reconcile
    within milliseconds."""
    from dynamo_tpu.operator.operator import SPEC_EVENTS_SUBJECT

    drt = await DistributedRuntime.in_process()
    kube = FakeKube()
    op = GraphOperator(drt, kube, interval_s=3600.0)
    try:
        await op.start()
        await asyncio.sleep(0.05)  # first (startup) pass
        base = op.reconcile_count
        assert base >= 1

        # (a) Spec event: put the spec, then publish the api-store kick.
        await _put_spec(drt, "graph", SPEC)
        await drt.bus.publish(SPEC_EVENTS_SUBJECT, b"graph")
        for _ in range(100):
            await asyncio.sleep(0.01)
            if kube.get("Deployment", "dynamo", "graph-worker"):
                break
        assert kube.get("Deployment", "dynamo", "graph-worker") is not None
        assert op.reconcile_count > base

        # (b) Cluster event: an out-of-band deletion fires the watch; the
        # reconciler must restore the child with no resync wait.
        count = op.reconcile_count
        kube.external_delete("Deployment", "dynamo", "graph-worker")
        for _ in range(100):
            await asyncio.sleep(0.01)
            if kube.get("Deployment", "dynamo", "graph-worker"):
                break
        assert kube.get("Deployment", "dynamo", "graph-worker") is not None
        assert op.reconcile_count > count
    finally:
        await op.stop()
        await drt.shutdown()


async def test_api_store_put_kicks_operator():
    """End-to-end: a deployment created through the api-store REST surface
    reconciles immediately (the store publishes SPEC_EVENTS_SUBJECT)."""
    import httpx

    from dynamo_tpu.sdk.api_store import ApiStore

    drt = await DistributedRuntime.in_process()
    kube = FakeKube()
    op = GraphOperator(drt, kube, interval_s=3600.0)
    store = await ApiStore(drt, host="127.0.0.1", port=0).start()
    try:
        await op.start()
        await asyncio.sleep(0.05)
        async with httpx.AsyncClient() as client:
            r = await client.post(
                f"http://127.0.0.1:{store.port}/v1/deployments",
                json={"name": "graph", "spec": SPEC},
            )
            assert r.status_code == 201
        for _ in range(100):
            await asyncio.sleep(0.01)
            if kube.get("Deployment", "dynamo", "graph-worker"):
                break
        assert kube.get("Deployment", "dynamo", "graph-worker") is not None
    finally:
        await op.stop()
        await store.stop()
        await drt.shutdown()


def test_crd_style_validation_messages():
    """The schema rejects malformed specs with precise, field-scoped
    messages (the kubebuilder validation-marker role)."""
    from dynamo_tpu.operator.resources import validate_record

    assert validate_record({"name": "ok", "spec": {
        "services": {"worker": {"role": "worker", "replicas": 1}}
    }}) == []

    errs = validate_record({"name": "Bad_Name", "spec": {
        "namespace": "ALSO BAD",
        "services": {
            "w": {"role": "worker", "replicas": -1, "chips": True,
                  "port": 99999, "args": []},
            "cp1": {"role": "control-plane"},
            "cp2": {"role": "control-plane"},
        },
    }})
    text = "\n".join(errs)
    assert "DNS-1123" in text
    assert "replicas" in text and "chips" in text
    assert "port" in text and "args" in text
    assert "at most one control-plane" in text
    assert validate_record({"name": "x", "spec": {"services": {}}}) == [
        "spec.services must be a non-empty object"
    ]

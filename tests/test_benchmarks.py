"""Benchmark harness tests: synthesizer structure + sweep/agg-vs-disagg
drivers on the mocker (device-free)."""

import pytest

from benchmarks.synthesizer import WorkloadConfig, generate, prefix_stats

pytestmark = pytest.mark.anyio


def test_synthesizer_prefix_structure():
    cfg = WorkloadConfig(num_requests=64, isl_mean=100, reuse=0.6, seed=3)
    reqs = generate(cfg)
    assert len(reqs) == 64
    stats = prefix_stats(reqs)
    # Prefix sharing exists and is material (the radix structure the
    # reference synthesizer preserves, synthesizer.py:48-75).
    assert stats["shared_prefix_fraction"] > 0.2
    # Shared prefixes really are shared: at least two requests start with
    # the same depth-1 run.
    firsts = {}
    for r in reqs:
        key = tuple(r.token_ids[:10])
        firsts[key] = firsts.get(key, 0) + 1
    assert max(firsts.values()) >= 2
    # Determinism: same seed, same workload.
    again = generate(WorkloadConfig(num_requests=64, isl_mean=100, reuse=0.6, seed=3))
    assert [r.token_ids for r in again] == [r.token_ids for r in reqs]


def test_synthesizer_no_reuse_is_unique():
    reqs = generate(WorkloadConfig(num_requests=16, reuse=0.0, seed=1))
    assert len({tuple(r.token_ids) for r in reqs}) == 16


def test_synthesizer_poisson_arrivals():
    reqs = generate(WorkloadConfig(num_requests=32, arrival_rate=100.0, seed=2))
    times = [r.arrival_s for r in reqs]
    assert times == sorted(times)
    assert times[-1] > 0


async def test_sweep_and_agg_vs_disagg_on_mocker():
    from benchmarks.sweep import _agg_vs_disagg, _mock_engine, sweep

    engine = _mock_engine()
    await engine.start()
    levels = await sweep(
        engine,
        levels=(1, 8),
        requests_per_level=6,
        workload=WorkloadConfig(num_requests=6, isl_mean=64, osl_mean=8),
    )
    await engine.stop()
    assert [lv["concurrency"] for lv in levels] == [1, 8]
    for lv in levels:
        assert lv["tok_per_s"] > 0
        assert lv["p50_ttft_ms"] is not None
        assert lv["p50_itl_ms"] is not None

    reqs = generate(WorkloadConfig(num_requests=8, isl_mean=64, osl_mean=8))
    cmp = await _agg_vs_disagg(reqs)
    assert cmp["agg"]["tok_per_s"] > 0
    assert cmp["disagg"]["tok_per_s"] > 0
    assert cmp["remote_prefills"] > 0  # long prompts actually went remote

"""Benchmark harness tests: synthesizer structure + sweep/agg-vs-disagg
drivers on the mocker (device-free)."""

import pytest

from benchmarks.synthesizer import WorkloadConfig, generate, prefix_stats

pytestmark = pytest.mark.anyio


def test_synthesizer_prefix_structure():
    cfg = WorkloadConfig(num_requests=64, isl_mean=100, reuse=0.6, seed=3)
    reqs = generate(cfg)
    assert len(reqs) == 64
    stats = prefix_stats(reqs)
    # Prefix sharing exists and is material (the radix structure the
    # reference synthesizer preserves, synthesizer.py:48-75).
    assert stats["shared_prefix_fraction"] > 0.2
    # Shared prefixes really are shared: at least two requests start with
    # the same depth-1 run.
    firsts = {}
    for r in reqs:
        key = tuple(r.token_ids[:10])
        firsts[key] = firsts.get(key, 0) + 1
    assert max(firsts.values()) >= 2
    # Determinism: same seed, same workload.
    again = generate(WorkloadConfig(num_requests=64, isl_mean=100, reuse=0.6, seed=3))
    assert [r.token_ids for r in again] == [r.token_ids for r in reqs]


def test_synthesizer_no_reuse_is_unique():
    reqs = generate(WorkloadConfig(num_requests=16, reuse=0.0, seed=1))
    assert len({tuple(r.token_ids) for r in reqs}) == 16


def test_synthesizer_poisson_arrivals():
    reqs = generate(WorkloadConfig(num_requests=32, arrival_rate=100.0, seed=2))
    times = [r.arrival_s for r in reqs]
    assert times == sorted(times)
    assert times[-1] > 0


async def test_sweep_and_agg_vs_disagg_on_mocker():
    from benchmarks.sweep import _agg_vs_disagg, _mock_engine, sweep

    engine = _mock_engine()
    await engine.start()
    levels = await sweep(
        engine,
        levels=(1, 8),
        requests_per_level=6,
        workload=WorkloadConfig(num_requests=6, isl_mean=64, osl_mean=8),
    )
    await engine.stop()
    assert [lv["concurrency"] for lv in levels] == [1, 8]
    for lv in levels:
        assert lv["tok_per_s"] > 0
        assert lv["p50_ttft_ms"] is not None
        assert lv["p50_itl_ms"] is not None

    reqs = generate(WorkloadConfig(num_requests=8, isl_mean=64, osl_mean=8))
    cmp = await _agg_vs_disagg(reqs)
    assert cmp["agg"]["tok_per_s"] > 0
    assert cmp["disagg"]["tok_per_s"] > 0
    assert cmp["remote_prefills"] > 0  # long prompts actually went remote


def test_mooncake_trace_replay_preserves_structure(tmp_path):
    """VERDICT r03 missing #5: Mooncake-format traces drive the workload
    generator — shared hash_ids become shared token prefixes (the trace's
    radix structure), arrivals scale by speedup_ratio, and loading is
    deterministic."""
    import json

    from benchmarks.synthesizer import from_mooncake_trace

    trace = tmp_path / "mooncake.jsonl"
    recs = [
        # Requests 0 and 1 share their first two 512-token blocks (hash
        # ids 7, 8); request 2 is unique; request 3 shares only block 7.
        {"timestamp": 0, "input_length": 1100, "output_length": 12,
         "hash_ids": [7, 8, 9]},
        {"timestamp": 1000, "input_length": 1200, "output_length": 8,
         "hash_ids": [7, 8, 11]},
        {"timestamp": 2000, "input_length": 600, "output_length": 4,
         "hash_ids": [20, 21]},
        {"timestamp": 4000, "input_length": 800, "output_length": 6,
         "hash_ids": [7, 30]},
    ]
    trace.write_text("\n".join(json.dumps(r) for r in recs))

    reqs = from_mooncake_trace(trace, speedup_ratio=2.0)
    assert [len(r.token_ids) for r in reqs] == [1100, 1200, 600, 800]
    assert [r.max_tokens for r in reqs] == [12, 8, 4, 6]
    # speedup 2x: 0s, 0.5s, 1s, 2s
    assert [round(r.arrival_s, 3) for r in reqs] == [0.0, 0.5, 1.0, 2.0]
    # Shared hash ids -> IDENTICAL token prefixes (1024 = two full blocks).
    assert reqs[0].token_ids[:1024] == reqs[1].token_ids[:1024]
    assert reqs[0].token_ids[:512] == reqs[3].token_ids[:512]
    # ...and divergence after the shared part.
    assert reqs[0].token_ids[1024:1100] != reqs[1].token_ids[1024:1100]
    assert reqs[2].token_ids[:512] != reqs[0].token_ids[:512]
    # prefix_len marks the LEADING shared blocks only.
    assert [r.prefix_len for r in reqs] == [1024, 1024, 0, 512]
    # Deterministic reload.
    again = from_mooncake_trace(trace, speedup_ratio=2.0)
    assert [r.token_ids for r in again] == [r.token_ids for r in reqs]


def test_request_jsonl_roundtrip(tmp_path):
    from benchmarks.synthesizer import (
        WorkloadConfig,
        generate,
        load_request_jsonl,
        save_request_jsonl,
    )

    reqs = generate(WorkloadConfig(num_requests=8, isl_mean=32, seed=5))
    p = tmp_path / "capture.jsonl"
    save_request_jsonl(reqs, p)
    back = load_request_jsonl(p)
    assert [r.token_ids for r in back] == [r.token_ids for r in reqs]
    assert [r.max_tokens for r in back] == [r.max_tokens for r in reqs]
    assert [r.prefix_len for r in back] == [r.prefix_len for r in reqs]
    assert [r.request_id for r in back] == [r.request_id for r in reqs]
    # arrival_s is what makes a capture replayable with recorded timing.
    assert [r.arrival_s for r in back] == [r.arrival_s for r in reqs]


async def test_trace_replay_hits_prefix_cache_on_mocker(tmp_path):
    """Replaying a reuse-heavy trace through the engine exercises the
    prefix cache the way production traffic would: the trace's shared
    blocks turn into real G1 prefix hits."""
    import json

    from benchmarks.sweep import _mock_engine, run_level
    from benchmarks.synthesizer import from_mooncake_trace

    trace = tmp_path / "mooncake.jsonl"
    base = {"timestamp": 0, "input_length": 96, "output_length": 4}
    recs = [dict(base, hash_ids=[1], timestamp=i * 10) for i in range(6)]
    recs += [
        dict(base, hash_ids=[50 + i], timestamp=100 + i * 10)
        for i in range(2)
    ]
    trace.write_text("\n".join(json.dumps(r) for r in recs))
    reqs = from_mooncake_trace(trace, block_size=64, vocab_size=900)

    engine = _mock_engine()
    await engine.start()
    try:
        level = await run_level(engine, reqs, concurrency=1)
        assert level["tok_per_s"] > 0
        # 6 requests share their first 64-token block: after the first
        # computes it, the other 5 hit the prefix cache.
        assert engine.prefix_hit_rate > 0.5
    finally:
        await engine.stop()


def test_prefix_analyzer_over_capture_jsonl(tmp_path):
    """benchmarks/prefix_analyzer.py (VERDICT missing #4): prefix-sharing
    stats + the theoretical hit-rate-vs-cache-size curve over the repo's
    capture/replay JSONL, in the engine's own block-hash identity."""
    import json

    from benchmarks.prefix_analyzer import analyze, load_trace, main
    from benchmarks.synthesizer import save_request_jsonl

    reqs = generate(
        WorkloadConfig(num_requests=48, isl_mean=96, reuse=0.6, seed=7)
    )
    path = tmp_path / "capture.jsonl"
    save_request_jsonl(reqs, path)

    loaded = load_trace(path)  # auto-sniffs the request format
    assert len(loaded) == 48
    report = analyze(loaded, block_size=16)
    assert report["requests"] == 48
    assert report["total_prompt_blocks"] > report["unique_prompt_blocks"]
    # The synthesizer's radix structure must be visible as real sharing.
    assert report["ideal_hit_rate"] > 0.1
    assert report["shared_prefix_block_fraction"] > 0.1
    assert report["requests_with_shared_prefix"] >= 2
    # The LRU curve: monotone non-decreasing in capacity, and a cache big
    # enough for every unique block reaches the ideal ceiling exactly.
    curve = report["curve"]
    rates = [pt["hit_rate"] for pt in curve]
    assert rates == sorted(rates)
    assert curve[-1]["cache_blocks"] >= report["unique_prompt_blocks"]
    assert abs(rates[-1] - report["ideal_hit_rate"]) < 1e-6
    # A tiny cache does strictly worse than the full one (eviction bites).
    assert rates[0] < rates[-1]

    # Zero-reuse workload: ~no sharing, ideal hit rate ~0.
    unique = generate(WorkloadConfig(num_requests=16, reuse=0.0, seed=1))
    r2 = analyze(unique, block_size=16)
    assert r2["ideal_hit_rate"] < 0.05
    assert r2["shared_prefix_block_fraction"] < 0.05

    # CLI entry: prints one JSON report; explicit cache sizes respected.
    report_cli = main([str(path), "--block-size", "16",
                       "--cache-sizes", "32,64"])
    assert [pt["cache_blocks"] for pt in report_cli["curve"]] == [32, 64]
    assert json.dumps(report_cli)  # JSON-serializable end to end


def test_prefix_analyzer_mooncake_format(tmp_path):
    """The analyzer reads Mooncake-format traces through the same loader
    the replay path uses, preserving hash-id sharing structure."""
    import json

    from benchmarks.prefix_analyzer import analyze, load_trace

    path = tmp_path / "trace.jsonl"
    records = [
        {"timestamp": i * 100, "input_length": 1024,
         "output_length": 8, "hash_ids": [0, 1, i + 10]}
        for i in range(8)
    ]
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    reqs = load_trace(path)  # auto-sniffs mooncake
    assert len(reqs) == 8
    report = analyze(reqs, block_size=16)
    # Blocks 0/1 are shared by all 8 requests -> strong sharing signal.
    assert report["ideal_hit_rate"] > 0.3
    assert report["requests_with_shared_prefix"] == 7

"""Ragged unified attention: the Pallas kernel (interpret mode) and the
jnp twin (ops/attention.py ragged_paged_attention) against the phase-split
oracles, over mixed prefill+decode batches, GQA, bf16, sliding windows,
prefix hits, and idle metadata rows. The same kernel compiles under
Mosaic on real TPU; interpret mode runs the identical code path on CPU."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.ops.attention import (
    paged_decode_attention,
    paged_prefill_attention,
    ragged_paged_attention,
)
from dynamo_tpu.ops.pallas.ragged_attention import (
    ragged_paged_attention_pallas,
)

BS = 16  # block size


def _caches(rng, num_blocks, kvH, D, dtype=jnp.float32):
    shape = (num_blocks * BS, kvH, D)
    k = jnp.asarray(rng.standard_normal(shape), dtype)
    v = jnp.asarray(rng.standard_normal(shape), dtype)
    return k, v


def _tables(rng, S, max_blocks, num_blocks):
    """Disjoint block tables (block 0 is the trash block, never used)."""
    ids = rng.permutation(np.arange(1, num_blocks))[: S * max_blocks]
    return jnp.asarray(ids.reshape(S, max_blocks), jnp.int32)


def _flat_batch(rng, spans, T, H, D, dtype=jnp.float32):
    """Build (q, span arrays, token arrays) for spans =
    [(q_start, q_len), ...] packed back to back from row 0."""
    S = len(spans)
    q_start = np.zeros(S, np.int32)
    q_len = np.zeros(S, np.int32)
    row_start = np.zeros(S, np.int32)
    token_seq = np.zeros(T, np.int32)
    token_pos = np.full(T, -1, np.int32)
    cursor = 0
    for s, (qs, ql) in enumerate(spans):
        q_start[s], q_len[s], row_start[s] = qs, ql, cursor
        token_seq[cursor : cursor + ql] = s
        token_pos[cursor : cursor + ql] = np.arange(qs, qs + ql)
        cursor += ql
    assert cursor <= T
    q = jnp.asarray(rng.standard_normal((T, H, D)), dtype)
    return (
        q,
        jnp.asarray(q_start),
        jnp.asarray(q_len),
        jnp.asarray(q_start + q_len),
        jnp.asarray(row_start),
        jnp.asarray(token_seq),
        jnp.asarray(token_pos),
    )


def _both(q, k, v, tables, qs, ql, kv, rs, tseq, tpos, window=0, q_tile=8):
    want = ragged_paged_attention(q, k, v, tables, tseq, tpos, BS, window)
    got = ragged_paged_attention_pallas(
        q, k, v, tables, qs, ql, kv, rs, BS, q_tile=q_tile, window=window
    )
    return np.asarray(want), np.asarray(got)


@pytest.mark.parametrize("H,kvH,D", [(8, 8, 128), (8, 2, 128), (4, 1, 128)])
def test_mixed_batch_matches_twin(H, kvH, D):
    """Decode spans + prefill quanta + a prefix-hit chunk + an idle row
    in ONE flat batch: kernel == jnp twin (incl. zeroed padding rows)."""
    rng = np.random.default_rng(0)
    k, v = _caches(rng, 64, kvH, D)
    tables = _tables(rng, 5, 4, 64)
    # decode@ctx37, decode@ctx1, prefill 20 from 0, chunk 13 @ prefix 16,
    # idle row; padding rows after.
    spans = [(36, 1), (0, 1), (0, 20), (16, 13), (0, 0)]
    q, qs, ql, kv_len, rs, tseq, tpos = _flat_batch(rng, spans, 40, H, D)
    want, got = _both(q, k, v, tables, qs, ql, kv_len, rs, tseq, tpos)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
    assert not got[35:].any()  # padding rows stay zero


def test_decode_only_matches_decode_oracle():
    """A decode-only unified batch must equal batched decode attention."""
    rng = np.random.default_rng(1)
    H, kvH, D = 8, 2, 128
    k, v = _caches(rng, 64, kvH, D)
    tables = _tables(rng, 4, 4, 64)
    ctx = np.asarray([64, 37, 1, 16], np.int32)
    spans = [(c - 1, 1) for c in ctx]
    q, qs, ql, kv_len, rs, tseq, tpos = _flat_batch(rng, spans, 16, H, D)
    want, got = _both(q, k, v, tables, qs, ql, kv_len, rs, tseq, tpos)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
    oracle = paged_decode_attention(
        q[:4], k, v, tables, jnp.asarray(ctx), BS
    )
    np.testing.assert_allclose(
        got[:4], np.asarray(oracle), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("q_tile", [8, 32])
def test_prefill_only_matches_prefill_oracle(q_tile):
    """Prefill-only unified batches (incl. a prefix hit) against the
    per-lane prefill oracle, across tile widths (full tiles + ragged
    tails)."""
    rng = np.random.default_rng(2)
    H, kvH, D = 8, 2, 128
    k, v = _caches(rng, 64, kvH, D)
    tables = _tables(rng, 2, 4, 64)
    spans = [(0, 24), (16, 13)]  # span 1 extends a 16-token prefix
    q, qs, ql, kv_len, rs, tseq, tpos = _flat_batch(rng, spans, 40, H, D)
    want, got = _both(
        q, k, v, tables, qs, ql, kv_len, rs, tseq, tpos, q_tile=q_tile
    )
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
    o0 = paged_prefill_attention(
        q[:24], k, v, tables[0], jnp.int32(0), jnp.int32(24), BS
    )
    o1 = paged_prefill_attention(
        q[24:37], k, v, tables[1], jnp.int32(16), jnp.int32(29), BS
    )
    np.testing.assert_allclose(got[:24], np.asarray(o0), rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(got[24:37], np.asarray(o1), rtol=2e-5, atol=2e-5)


def test_bf16_mixed_batch():
    rng = np.random.default_rng(3)
    H, kvH, D = 8, 4, 128
    k, v = _caches(rng, 32, kvH, D, jnp.bfloat16)
    tables = _tables(rng, 3, 3, 32)
    spans = [(19, 1), (0, 12), (8, 5)]
    q, qs, ql, kv_len, rs, tseq, tpos = _flat_batch(
        rng, spans, 24, H, D, jnp.bfloat16
    )
    want, got = _both(q, k, v, tables, qs, ql, kv_len, rs, tseq, tpos)
    np.testing.assert_allclose(
        got.astype(np.float32), want.astype(np.float32), rtol=2e-2, atol=2e-2
    )


def test_sliding_window_mixed_batch():
    """Windowed attention (Mistral-style) over a mixed batch: kernel ==
    twin, and a long-context decode span sees only the window."""
    rng = np.random.default_rng(4)
    H, kvH, D = 4, 2, 128
    k, v = _caches(rng, 64, kvH, D)
    tables = _tables(rng, 3, 4, 64)
    spans = [(63, 1), (0, 20), (30, 9)]
    q, qs, ql, kv_len, rs, tseq, tpos = _flat_batch(rng, spans, 32, H, D)
    for window in (8, 24):
        want, got = _both(
            q, k, v, tables, qs, ql, kv_len, rs, tseq, tpos, window=window
        )
        np.testing.assert_allclose(
            got, want, rtol=2e-5, atol=2e-5, err_msg=f"window={window}"
        )
    # Decode span vs the windowed decode oracle.
    want_d = paged_decode_attention(
        q[:1], k, v, tables[:1], jnp.asarray([64], jnp.int32), BS, window=8
    )
    got_w = ragged_paged_attention_pallas(
        q, k, v, tables, qs, ql, kv_len, rs, BS, window=8
    )
    np.testing.assert_allclose(
        np.asarray(got_w)[:1], np.asarray(want_d), rtol=2e-5, atol=2e-5
    )


def test_twin_is_pure_decode_reformulation():
    """The jnp twin's mixed-batch output equals running each phase
    through its own oracle — the contract that makes it a valid oracle
    for the kernel."""
    rng = np.random.default_rng(5)
    H, kvH, D = 8, 2, 64  # twin has no lane constraint; D=64 fine
    k, v = _caches(rng, 64, kvH, D)
    tables = _tables(rng, 2, 4, 64)
    spans = [(47, 1), (0, 10)]
    q, qs, ql, kv_len, rs, tseq, tpos = _flat_batch(rng, spans, 16, H, D)
    out = np.asarray(
        ragged_paged_attention(q, k, v, tables, tseq, tpos, BS)
    )
    dec = paged_decode_attention(
        q[:1], k, v, tables[:1], jnp.asarray([48], jnp.int32), BS
    )
    pre = paged_prefill_attention(
        q[1:11], k, v, tables[1], jnp.int32(0), jnp.int32(10), BS
    )
    np.testing.assert_allclose(out[:1], np.asarray(dec), rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(out[1:11], np.asarray(pre), rtol=2e-5, atol=2e-5)
    assert not out[11:].any()


def test_gqa_grouping_matches_full_heads():
    """GQA (kvH < H) kernel output equals a full-head run on a cache with
    each kv head repeated over its query group."""
    rng = np.random.default_rng(6)
    H, kvH, D = 8, 2, 128
    k, v = _caches(rng, 32, kvH, D)
    tables = _tables(rng, 2, 3, 32)
    spans = [(21, 1), (0, 9)]
    q, qs, ql, kv_len, rs, tseq, tpos = _flat_batch(rng, spans, 16, H, D)
    got = np.asarray(
        ragged_paged_attention_pallas(
            q, k, v, tables, qs, ql, kv_len, rs, BS
        )
    )
    G = H // kvH
    k_full = jnp.repeat(k, G, axis=1)
    v_full = jnp.repeat(v, G, axis=1)
    want = np.asarray(
        ragged_paged_attention_pallas(
            q, k_full, v_full, tables, qs, ql, kv_len, rs, BS
        )
    )
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("H,kvH", [(8, 8), (8, 2)])
def test_spec_verify_spans_match_twin(H, kvH):
    """Speculative draft-verify spans (q_len = k+1 rows at
    q_start = ctx-1) mixed with plain decode and prefill quanta in ONE
    flat batch: kernel == jnp twin, GQA included. A verify span's
    attention math is identical to a short prefill over the draft
    positions — this pins the contract the unified spec port rides."""
    rng = np.random.default_rng(7)
    D = 128
    k, v = _caches(rng, 64, kvH, D)
    tables = _tables(rng, 4, 4, 64)
    # verify span: ctx 36, fed token + 3 drafts (rows 35..38);
    # verify span at the context floor: ctx 1, fed + 2 drafts;
    # a plain decode span and a prefill quantum ride along.
    spans = [(35, 4), (0, 3), (21, 1), (0, 10)]
    q, qs, ql, kv_len, rs, tseq, tpos = _flat_batch(rng, spans, 32, H, D)
    want, got = _both(q, k, v, tables, qs, ql, kv_len, rs, tseq, tpos)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
    # Each verify ROW equals the prefill oracle over the same span —
    # verification IS a short prefill over the draft positions.
    o0 = paged_prefill_attention(
        q[:4], k, v, tables[0], jnp.int32(35), jnp.int32(39), BS
    )
    np.testing.assert_allclose(got[:4], np.asarray(o0), rtol=2e-5, atol=2e-5)


def test_spec_verify_spans_match_twin_windowed():
    """Draft-verify spans under a sliding window: kernel == twin, and
    the verify rows see only the window."""
    rng = np.random.default_rng(8)
    H, kvH, D = 4, 2, 128
    k, v = _caches(rng, 64, kvH, D)
    tables = _tables(rng, 2, 4, 64)
    spans = [(50, 5), (0, 8)]  # ctx-51 verify span (4 drafts) + prefill
    q, qs, ql, kv_len, rs, tseq, tpos = _flat_batch(rng, spans, 16, H, D)
    for window in (8, 16):
        want, got = _both(
            q, k, v, tables, qs, ql, kv_len, rs, tseq, tpos, window=window
        )
        np.testing.assert_allclose(
            got, want, rtol=2e-5, atol=2e-5, err_msg=f"window={window}"
        )


def test_unified_verify_rows_match_reference_forward():
    """llama.unified verify_rows > 1: every verify row's logits equal
    the no-cache reference forward at the same position — the law the
    in-dispatch accept-prefix check scores drafts against."""
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import ModelConfig

    cfg = ModelConfig.tiny_test()
    params = llama.init_params(jax.random.PRNGKey(1), cfg, jnp.float32)
    prompt = [1, 5, 9, 2, 7, 3]
    P = len(prompt)
    drafts = [11, 12, 4]
    num_slots = 8 * BS
    kv_caches = [
        (
            jnp.zeros((num_slots, cfg.num_kv_heads, cfg.head_dim)),
            jnp.zeros((num_slots, cfg.num_kv_heads, cfg.head_dim)),
        )
        for _ in range(cfg.num_layers)
    ]

    def build(toks, prefix, S=2):
        T = 16
        token_ids = np.zeros(T, np.int32)
        token_ids[: len(toks)] = toks
        token_pos = np.full(T, -1, np.int32)
        token_pos[: len(toks)] = np.arange(prefix, prefix + len(toks))
        slot_mapping = np.zeros(T, np.int32)
        slot_mapping[: len(toks)] = np.arange(
            BS + prefix, BS + prefix + len(toks)
        )  # block 1
        token_seq = np.zeros(T, np.int32)
        tables = np.zeros((S, 4), np.int32)
        tables[0, 0] = 1
        n = len(toks)
        return (
            jnp.asarray(token_ids), jnp.asarray(token_pos),
            jnp.asarray(slot_mapping), jnp.asarray(token_seq),
            jnp.asarray(tables),
            jnp.asarray([prefix, 0], jnp.int32),
            jnp.asarray([n, 0], jnp.int32),
            jnp.asarray([prefix + n, 0], jnp.int32),
            jnp.asarray([0, 0], jnp.int32),
        )

    # Prefill the prompt (all but the last token is "fed history"; the
    # verify span feeds the last prompt token + the drafts).
    _, kv_caches = llama.unified(
        cfg, params, kv_caches, *build(prompt[:-1], 0), BS
    )
    verify = [prompt[-1]] + drafts
    K = len(drafts)
    logits, _ = llama.unified(
        cfg, params, kv_caches, *build(verify, P - 1), BS,
        draft_len=jnp.asarray([K, 0], jnp.int32), verify_rows=K + 1,
    )
    assert logits.shape[:2] == (2, K + 1)
    full = prompt + drafts
    ref = llama.reference_forward(cfg, params, jnp.asarray(full))
    for j in range(K + 1):
        np.testing.assert_allclose(
            np.asarray(logits[0, j]), np.asarray(ref[P - 1 + j]),
            rtol=2e-4, atol=2e-4, err_msg=f"verify row {j}",
        )


def test_unified_model_forward_matches_no_cache_oracle():
    """llama.unified end-to-end (tiny model, XLA twin path): a full-prompt
    span's logits must match the no-cache greedy oracle's last-token
    logits."""
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import ModelConfig

    cfg = ModelConfig.tiny_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg, jnp.float32)
    prompt = [5, 9, 2, 7, 11, 3]
    P = len(prompt)
    num_slots = 8 * BS
    kv_caches = [
        (
            jnp.zeros((num_slots, cfg.num_kv_heads, cfg.head_dim)),
            jnp.zeros((num_slots, cfg.num_kv_heads, cfg.head_dim)),
        )
        for _ in range(cfg.num_layers)
    ]
    T, S = 16, 2
    token_ids = np.zeros(T, np.int32)
    token_ids[:P] = prompt
    token_pos = np.full(T, -1, np.int32)
    token_pos[:P] = np.arange(P)
    slot_mapping = np.zeros(T, np.int32)
    slot_mapping[:P] = np.arange(BS, BS + P)  # block 1
    token_seq = np.zeros(T, np.int32)
    tables = np.zeros((S, 4), np.int32)
    tables[0, 0] = 1
    logits, _ = llama.unified(
        cfg, params, kv_caches,
        jnp.asarray(token_ids), jnp.asarray(token_pos),
        jnp.asarray(slot_mapping), jnp.asarray(token_seq),
        jnp.asarray(tables),
        jnp.asarray([0, 0], jnp.int32), jnp.asarray([P, 0], jnp.int32),
        jnp.asarray([P, 0], jnp.int32), jnp.asarray([0, 0], jnp.int32),
        BS,
    )
    want = llama.reference_forward(cfg, params, jnp.asarray(prompt))[-1]
    np.testing.assert_allclose(
        np.asarray(logits[0]), np.asarray(want), rtol=2e-4, atol=2e-4
    )

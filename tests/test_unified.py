"""Unified single-dispatch serving (docs/architecture/unified_step.md):
token-budget batch composition, the budget-ladder warmup contract, the
runner's device feed, and end-to-end token parity against the
phase-alternating path."""

import asyncio

import numpy as np
import pytest

from dynamo_tpu.engine.compile_cache import (
    budget_ladder,
    default_shape_grid,
    token_budget,
)
from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.scheduler import compose_unified
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    RequestError,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.runtime.engine import Context

pytestmark = pytest.mark.anyio


# ---------------------------------------------------------------------------
# token budget + shape grid
# ---------------------------------------------------------------------------


def test_token_budget_snaps_to_ladder():
    assert token_budget(1, 256) == 16
    assert token_budget(16, 256) == 16
    assert token_budget(17, 256) == 32
    assert token_budget(100, 256) == 128
    assert token_budget(300, 256) == 256  # capped at the ladder top
    assert budget_ladder(256) == [16, 32, 64, 128, 256]


def test_unified_shape_grid_is_budget_ladder_only():
    """The unified grid IS the ladder (plus ONE top-rung program per
    configured variant) — no prefill buckets, no lane axis, no
    decode-chunk ladder. This is the delete-the-grid contract, and it
    holds with speculation enabled: the spec program IS the ladder."""
    cfg = EngineConfig(
        model=ModelConfig.tiny_test(), num_blocks=64, max_model_len=256,
        unified=True, unified_token_budget=256, sampling_extras=False,
    )
    specs = default_shape_grid(cfg)
    assert specs == [("unified", b, 0, 0, 0) for b in (16, 32, 64, 128, 256)]
    assert len(specs) <= 8
    # Speculation adds ZERO programs — same ladder, spec-aware program.
    import dataclasses

    spec_cfg = dataclasses.replace(cfg, speculative_k=4)
    assert default_shape_grid(spec_cfg) == specs
    # Extras requests are rejected on spec engines, so the unified_full
    # program would be unreachable dead warmup weight there.
    spec_extras = dataclasses.replace(
        cfg, speculative_k=4, sampling_extras=True
    )
    assert default_shape_grid(spec_extras) == specs
    # Extras and multimodal each add exactly ONE top-rung program.
    full_cfg = dataclasses.replace(cfg, sampling_extras=True, multimodal=True)
    full = default_shape_grid(full_cfg)
    assert full == specs + [
        ("unified_full", 256, 0, 0, 0), ("unified_mm", 256, 0, 0, 0)
    ]
    assert len(full) <= 8


def test_config_validation_one_path():
    base = dict(model=ModelConfig.tiny_test(), num_blocks=64,
                max_model_len=256, unified=True)
    for bad in (
        dict(unified_token_budget=8),
        dict(unified_prefill_quantum=0),
        dict(unified=False),          # the phased path is GONE
        dict(speculative_k=16, unified_token_budget=16),  # span > half
    ):
        with pytest.raises(ValueError):
            cfg = dict(base)
            cfg.update(bad)
            EngineConfig(**cfg).validate()
    EngineConfig(**base).validate()  # the plain combo is fine
    # Speculation and multimodal are FIRST-CLASS on the unified path now.
    EngineConfig(**base, speculative_k=4).validate()
    EngineConfig(**base, multimodal=True).validate()


def test_config_budget_clamps_to_reachable_rung():
    """A budget past the largest fillable batch CLAMPS down to the
    biggest reachable rung (with the quantum snapped inside it) instead
    of rejecting — the default budget must stay valid on tiny engines."""
    cfg = EngineConfig(
        model=ModelConfig.tiny_test(), num_blocks=64, max_num_seqs=2,
        max_model_len=32, prefill_batch=2, unified_token_budget=256,
        unified_prefill_quantum=200,
    )
    cfg.validate()
    assert cfg.unified_token_budget == 64  # (2+2)*31 = 124 → rung 64
    assert cfg.unified_prefill_quantum == 64


# ---------------------------------------------------------------------------
# batch composition (pure policy, no engine)
# ---------------------------------------------------------------------------


def test_compose_decode_first_fill():
    """Decode lanes admit first; remaining budget packs prefill quanta."""
    dec = [f"d{i}" for i in range(6)]
    pre = [("p0", 100), ("p1", 30)]
    decode_take, prefill_take = compose_unified(dec, pre, 64, 16)
    assert decode_take == dec  # all decode lanes fit
    assert prefill_take == [("p0", 16), ("p1", 16)]  # one quantum each


def test_compose_prefill_quantum_cap_lifts_when_alone():
    """A prefill-only batch may spend the whole budget on one prompt
    (pure TTFT); under co-location each prompt is quantum-capped."""
    _, alone = compose_unified([], [("p0", 500)], 64, 16)
    assert alone == [("p0", 64)]
    _, shared = compose_unified(["d0"], [("p0", 500)], 64, 16)
    assert shared == [("p0", 16)]


def test_compose_starvation_bounds():
    """A full decode population cannot starve prefill below one quantum,
    and prefill can never displace a decode lane that fits."""
    dec = [f"d{i}" for i in range(64)]
    decode_take, prefill_take = compose_unified(dec, [("p0", 100)], 64, 16)
    assert len(decode_take) == 48  # 64 - 16 reserved
    assert prefill_take == [("p0", 16)]  # prefill always progresses
    # no prefill work -> decode takes the whole budget
    decode_take, prefill_take = compose_unified(dec, [], 64, 16)
    assert len(decode_take) == 64 and prefill_take == []
    # reserve never exceeds the actual prefill demand
    decode_take, prefill_take = compose_unified(dec, [("p0", 3)], 64, 16)
    assert len(decode_take) == 61 and prefill_take == [("p0", 3)]
    # quantum == budget must NOT zero decode out: the reserve is capped
    # so decode keeps at least half the budget (or all it needs).
    decode_take, prefill_take = compose_unified(dec, [("p0", 500)], 64, 64)
    assert len(decode_take) == 32
    assert prefill_take == [("p0", 32)]
    decode_take, prefill_take = compose_unified(
        dec[:2], [("p0", 500)], 64, 64
    )
    assert len(decode_take) == 2  # small decode population fully fits
    assert prefill_take == [("p0", 62)]


def test_compose_budget_exhaustion_stops_packing():
    dec = ["d0", "d1"]
    pre = [("p0", 40), ("p1", 40), ("p2", 40)]
    decode_take, prefill_take = compose_unified(dec, pre, 32, 16)
    assert decode_take == dec
    # 30 tokens left: one full quantum + a truncated one; p2 waits.
    assert prefill_take == [("p0", 16), ("p1", 14)]


# ---------------------------------------------------------------------------
# engine end-to-end (mocker: warmup contract; real engine: token parity)
# ---------------------------------------------------------------------------


def _engine_cfg(unified: bool, **kw) -> EngineConfig:
    return EngineConfig(
        model=ModelConfig.tiny_test(), num_blocks=64, max_num_seqs=4,
        max_model_len=96, prefill_chunk=32, dtype="float32",
        unified=unified, unified_token_budget=64,
        unified_prefill_quantum=32, sampling_extras=False, **kw,
    )


async def test_mocker_unified_warmup_and_zero_midtraffic_compiles():
    """Unified mocker engine: warmup compiles exactly the budget ladder
    (≤ 8 programs), mixed traffic runs with ZERO mid-traffic compiles,
    and the unified metrics surface on the engine snapshot."""
    from dynamo_tpu.mocker import MockerConfig, MockerEngine

    cfg = EngineConfig(
        model=ModelConfig.tiny_test(), num_blocks=64, max_num_seqs=4,
        max_model_len=128, prefill_chunk=64, unified=True,
        unified_token_budget=64, unified_prefill_quantum=16,
    )
    eng = MockerEngine(cfg, MockerConfig())
    metrics: list[dict] = []
    eng._on_metrics = metrics.append
    await eng.start()
    warmed = await eng.warmup()
    assert warmed <= 8
    # The ladder plus the single extras top-rung program
    # (sampling_extras defaults True).
    assert warmed == len(budget_ladder(cfg.unified_token_budget)) + 1
    rng = np.random.default_rng(0)

    async def run_one():
        req = PreprocessedRequest(
            token_ids=rng.integers(0, 1000, 40).tolist(),
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=8, ignore_eos=True),
        )
        n = 0
        async for out in eng.generate(Context(req.to_wire())):
            n += len(out["token_ids"])
        return n

    counts = await asyncio.gather(*[run_one() for _ in range(6)])
    assert counts == [8] * 6
    cs = eng.runner.compile_stats
    assert cs.mid_traffic_compiles == 0, cs.mid_traffic_keys
    assert cs.snapshot()["warmup_programs_total"] == warmed
    # Observability satellite: the split + fill ratio reach the metrics
    # callback and the readiness snapshot.
    assert eng._unified_prefill_tokens == 6 * 40
    assert eng._unified_decode_tokens > 0
    m = metrics[-1]
    assert "unified_step_tokens_decode_total" in m
    assert "batch_fill_ratio" in m
    r = eng.readiness()
    assert r["unified_step_tokens_prefill_total"] == 6 * 40
    await eng.stop()


async def test_unified_remote_prefill_uses_budget_programs_only():
    """A unified disagg PREFILL worker must serve remote-prefill batches
    through unified_step spans — never the phase-path prefill programs
    its warmup no longer compiles (that would be a mid-traffic compile
    per bucket, the r05 stall class)."""
    from dynamo_tpu.mocker import MockerConfig, MockerEngine

    cfg = EngineConfig(
        model=ModelConfig.tiny_test(), num_blocks=64, max_num_seqs=4,
        max_model_len=128, prefill_chunk=64, unified=True,
        unified_token_budget=64, unified_prefill_quantum=16,
    )
    eng = MockerEngine(cfg, MockerConfig())
    await eng.start()
    await eng.warmup()
    rng = np.random.default_rng(2)
    items = [
        (
            PreprocessedRequest(
                token_ids=rng.integers(0, 1000, n).tolist(),
                sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=4, ignore_eos=True),
            ),
            f"rp-{i}",
            False,
        )
        for i, n in enumerate((90, 40))
    ]
    results = await asyncio.gather(*eng.prefill_only_batch(items))
    for (pre, _rid, _dev), res in zip(items, results):
        assert res is not None
        token, blocks = res
        assert isinstance(token, int)
        assert len(blocks) == -(-len(pre.token_ids) // cfg.block_size)
    cs = eng.runner.compile_stats
    assert cs.mid_traffic_compiles == 0, cs.mid_traffic_keys
    assert all(k.startswith("unified") for k in cs.seen), cs.seen
    await eng.stop()


async def test_unified_rejects_extras_only_when_disabled():
    """sampling_extras=False still 400-rejects penalties/logprobs; the
    default unified engine serves them (the extras port)."""
    from dynamo_tpu.mocker import MockerConfig, MockerEngine

    cfg = EngineConfig(
        model=ModelConfig.tiny_test(), num_blocks=64, max_num_seqs=4,
        max_model_len=128, unified=True, sampling_extras=False,
    )
    eng = MockerEngine(cfg, MockerConfig())
    await eng.start()
    req = PreprocessedRequest(
        token_ids=[1, 2, 3],
        sampling=SamplingOptions(temperature=0.0, frequency_penalty=0.5),
        stop=StopConditions(max_tokens=4, ignore_eos=True),
    )
    with pytest.raises(RequestError):
        async for _ in eng.generate(Context(req.to_wire())):
            pass
    await eng.stop()


async def test_engine_spec_greedy_streams_byte_identical():
    """The tentpole regression gate (pre/post-port byte identity, REAL
    engine): greedy token streams through the unified step are
    byte-identical with speculative decoding ON and OFF — verification
    only ever keeps drafts the plain rollout would have produced, and
    gated-off spec traffic reduces to the exact plain program."""
    from dynamo_tpu.engine.engine import TpuEngine

    async def run(spec_k: int) -> list[list[int]]:
        eng = TpuEngine(_engine_cfg(True, speculative_k=spec_k))
        await eng.start()
        rng = np.random.default_rng(0)
        prompts = [
            rng.integers(0, 500, n).tolist() for n in (7, 19, 40, 12, 33)
        ]
        out = []
        for p in prompts:
            req = PreprocessedRequest(
                token_ids=p,
                sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=8, ignore_eos=True),
            )
            toks = []
            async for o in eng.generate(Context(req.to_wire())):
                toks.extend(o["token_ids"])
            out.append(toks)
        assert eng.runner.compile_stats.manifest.count_of("unified:t16")
        await eng.stop()
        return out

    plain = await run(0)
    spec = await run(3)
    assert spec == plain
    assert all(len(t) == 8 for t in plain)


async def test_engine_unified_mixed_concurrency_and_prefix_cache():
    """Concurrent mixed-length prompts (prefill quanta + decode lanes
    co-resident in single dispatches) all complete, and a repeated prompt
    takes the prefix-cache hit path through the unified step."""
    from dynamo_tpu.engine.engine import TpuEngine

    eng = TpuEngine(_engine_cfg(True))
    await eng.start()
    rng = np.random.default_rng(1)
    base = rng.integers(0, 500, 48).tolist()

    async def run_one(p, n=6):
        req = PreprocessedRequest(
            token_ids=p,
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=n, ignore_eos=True),
        )
        toks = []
        async for o in eng.generate(Context(req.to_wire())):
            toks.extend(o["token_ids"])
        return toks

    prompts = [base, rng.integers(0, 500, 9).tolist(),
               rng.integers(0, 500, 21).tolist()]
    first = await asyncio.gather(*[run_one(p) for p in prompts])
    assert all(len(t) == 6 for t in first)
    # Same prompt again: blocks registered by the first pass give a
    # prefix hit; the continuation must still decode identical tokens.
    again = await run_one(base)
    assert again == first[0]
    assert eng.prefix_hit_rate > 0
    await eng.stop()

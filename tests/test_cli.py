"""CLI tests: the shell entrypoints actually serve (reference analogue:
launch/dynamo-run's in/out matrix, opt.rs:22-188).

Subprocess-driven like a user would run them; CPU backend, tiny preset.
"""

import asyncio
import os
import re
import signal
import sys

import httpx
import pytest

from dynamo_tpu.cli import _parse_mesh, build_parser

pytestmark = pytest.mark.anyio

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_parse_mesh():
    assert _parse_mesh(None) == {}
    assert _parse_mesh("tp=4") == {"tp": 4}
    assert _parse_mesh("tp=2,dp=2") == {"tp": 2, "dp": 2}
    with pytest.raises(SystemExit):
        _parse_mesh("bogus=3")


def test_parser_defaults():
    args = build_parser().parse_args(["run"])
    assert args.input == "http" and args.output == "tpu"
    args = build_parser().parse_args(
        ["run", "--in", "batch:f.txt", "--out", "echo_core"]
    )
    assert args.input == "batch:f.txt"


async def _spawn_cli(*args: str, ready_pattern: str, timeout: float = 120):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = await asyncio.create_subprocess_exec(
        sys.executable, "-m", "dynamo_tpu", *args,
        stdout=asyncio.subprocess.PIPE,
        stderr=asyncio.subprocess.STDOUT,
        env=env,
        cwd=REPO,
    )
    lines = []
    pat = re.compile(ready_pattern)
    while True:
        line = await asyncio.wait_for(proc.stdout.readline(), timeout)
        if not line:
            raise RuntimeError(
                "CLI died before ready:\n" + "".join(lines)
            )
        text = line.decode()
        lines.append(text)
        m = pat.search(text)
        if m:
            return proc, m


async def _stop(proc) -> None:
    if proc.returncode is None:
        proc.send_signal(signal.SIGINT)
        try:
            await asyncio.wait_for(proc.wait(), 15)
        except asyncio.TimeoutError:
            proc.kill()
            await proc.wait()


async def test_cli_batch_echo(tmp_path):
    prompts = tmp_path / "prompts.txt"
    prompts.write_text("hello world\nsecond prompt\n")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = await asyncio.create_subprocess_exec(
        sys.executable, "-m", "dynamo_tpu", "run",
        "--in", f"batch:{prompts}", "--out", "echo_core",
        stdout=asyncio.subprocess.PIPE,
        stderr=asyncio.subprocess.STDOUT,
        env=env,
        cwd=REPO,
    )
    out, _ = await asyncio.wait_for(proc.communicate(), 120)
    text = out.decode()
    assert proc.returncode == 0, text
    import json

    json_lines = [
        ln for ln in text.strip().splitlines() if ln.startswith("{")
    ]
    assert json_lines, f"no JSON report in output:\n{text}"
    report = json.loads(json_lines[-1])
    assert report["requests"] == 2
    assert report["tokens_out_per_s"] > 0
    assert report["p50_ttft_ms"] is not None


async def test_cli_http_serves_tpu_preset():
    """One shell command serves OpenAI-compatible chat on the real engine
    (tiny preset, CPU): the VERDICT r02 'can't be launched from a shell'
    gap, closed."""
    proc, m = await _spawn_cli(
        "run", "--in", "http", "--out", "tpu",
        "--model-path", "preset:tiny-test",
        "--http-host", "127.0.0.1", "--http-port", "0",
        "--max-model-len", "64", "--num-blocks", "32",
        "--max-num-seqs", "4", "--no-warmup",
        ready_pattern=r"OpenAI server on http://127\.0\.0\.1:(\d+)",
    )
    try:
        port = int(m.group(1))
        async with httpx.AsyncClient() as client:
            r = await client.get(f"http://127.0.0.1:{port}/v1/models")
            assert [x["id"] for x in r.json()["data"]] == ["tiny-test"]
            r = await client.post(
                f"http://127.0.0.1:{port}/v1/chat/completions",
                json={
                    "model": "tiny-test",
                    "messages": [{"role": "user", "content": "hi"}],
                    "stream": False,
                    "max_tokens": 4,
                },
                timeout=120,
            )
            assert r.status_code == 200, r.text
            data = r.json()
            assert data["usage"]["completion_tokens"] > 0
    finally:
        await _stop(proc)


async def test_cli_worker_joins_frontend():
    """Two shell commands: a frontend hosting the control plane + HTTP, and
    a separate worker process joining it — the reference's
    `in=http out=dyn` + `in=dyn://... out=...` split (lib.rs:207-240)."""
    front, m = await _spawn_cli(
        "run", "--in", "http", "--out", "dyn",
        "--spawn-control-plane", "0",
        "--http-host", "127.0.0.1", "--http-port", "0",
        ready_pattern=r"control plane on ([0-9.]+:\d+)",
    )
    worker = None
    try:
        cp_addr = m.group(1)
        # The frontend prints its HTTP line next.
        pat = re.compile(r"OpenAI server on http://127\.0\.0\.1:(\d+)")
        while True:
            line = (await asyncio.wait_for(front.stdout.readline(), 60)).decode()
            assert line, "frontend died"
            hit = pat.search(line)
            if hit:
                port = int(hit.group(1))
                break

        worker, _ = await _spawn_cli(
            "run", "--in", "dyn://dynamo.tpu.generate", "--out", "echo_core",
            "--control-plane", cp_addr, "--model-name", "joined-echo",
            ready_pattern=r"worker serving dyn://dynamo\.tpu\.generate",
        )
        async with httpx.AsyncClient() as client:
            deadline = asyncio.get_running_loop().time() + 30
            while True:
                r = await client.get(f"http://127.0.0.1:{port}/v1/models")
                if [x["id"] for x in r.json()["data"]] == ["joined-echo"]:
                    break
                assert asyncio.get_running_loop().time() < deadline, (
                    "worker never discovered"
                )
                await asyncio.sleep(0.2)
            r = await client.post(
                f"http://127.0.0.1:{port}/v1/chat/completions",
                json={
                    "model": "joined-echo",
                    "messages": [{"role": "user", "content": "ping pong"}],
                    "stream": False,
                },
                timeout=60,
            )
            assert r.status_code == 200, r.text
            assert "ping pong" in r.json()["choices"][0]["message"]["content"]
    finally:
        await _stop(front)
        if worker is not None:
            await _stop(worker)

"""Chaos suite: every instrumented fault point armed, every recovery
invariant asserted (no hang — every wait is bounded; no token corruption;
counters incremented; disarmed behavior identical).

The recovery semantics under test are documented in
docs/architecture/failure_model.md; fault points live in
dynamo_tpu/utils/faults.py, the shared backoff policy in
dynamo_tpu/utils/retry.py.
"""

import asyncio
import time

import numpy as np
import pytest

from dynamo_tpu.utils.faults import FAULTS, FaultError, FaultRegistry, _arm_from_env
from dynamo_tpu.utils.retry import RETRIES, RetryPolicy, retry_async, retry_sync

pytestmark = pytest.mark.anyio


@pytest.fixture(autouse=True)
def _disarm_everything():
    FAULTS.clear()
    yield
    FAULTS.clear()


# ---------------------------------------------------------------------------
# Registry + policy primitives
# ---------------------------------------------------------------------------


def test_fault_registry_actions():
    reg = FaultRegistry()
    # Disarmed: free pass, nothing counted.
    assert reg.maybe_fail("p") is True
    assert reg.total_injected == 0

    # raise: fires `times` times then auto-disarms.
    reg.arm("p", "raise", times=2)
    with pytest.raises(FaultError):
        reg.maybe_fail("p")
    with pytest.raises(FaultError):
        reg.maybe_fail("p")
    assert reg.maybe_fail("p") is True  # budget spent
    assert reg.injected["p"] == 2

    # drop: returns False (caller skips the side effect) at drop-capable
    # call sites.
    reg.arm("q", "drop", times=1)
    assert reg.maybe_fail("q", can_drop=True) is False
    assert reg.maybe_fail("q", can_drop=True) is True

    # partition: raises until explicitly disarmed.
    reg.arm("r", "partition")
    for _ in range(5):
        with pytest.raises(FaultError):
            reg.maybe_fail("r")
    reg.disarm("r")
    assert reg.maybe_fail("r") is True

    # delay: proceeds after sleeping.
    reg.arm("s", "delay", delay_s=0.01, times=1)
    t0 = time.monotonic()
    assert reg.maybe_fail("s") is True
    assert time.monotonic() - t0 >= 0.009

    # drop at a seam that cannot skip (can_drop=False, the default) is
    # inert AND uncounted — the counter must never claim a loss that
    # didn't happen.
    reg.arm("t", "drop", times=1)
    assert reg.maybe_fail("t") is True
    assert "t" not in reg.injected
    assert reg.maybe_fail("t", can_drop=True) is False  # still armed
    assert reg.injected["t"] == 1

    # FaultError is transport-shaped: retry filters treat it as loss.
    assert issubclass(FaultError, ConnectionError)
    assert reg.total_injected == sum(reg.injected.values()) > 0


def test_fault_env_arming():
    reg = FaultRegistry()
    _arm_from_env(reg, "a.b:raise:2, c.d:drop , e.f:delay:0.25, ,bad:zap:9")
    assert reg.armed("a.b") and reg.armed("c.d") and reg.armed("e.f")
    assert not reg.armed("bad")  # bad entries are ignored loudly, not fatal
    with pytest.raises(FaultError):
        reg.maybe_fail("a.b")


async def test_retry_async_recovers_and_counts():
    calls = []
    base = RETRIES.total

    async def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("transient")
        return "ok"

    policy = RetryPolicy(attempts=3, base_delay_s=0.001, jitter=0.0)
    assert await retry_async(flaky, policy, seam="test.flaky") == "ok"
    assert len(calls) == 3
    assert RETRIES.total - base == 2
    assert RETRIES.snapshot().get("test.flaky", 0) >= 2

    # Budget exhaustion re-raises the LAST failure.
    with pytest.raises(ConnectionError):
        await retry_async(
            lambda: (_ for _ in ()).throw(ConnectionError("down")) and None,
            RetryPolicy(attempts=2, base_delay_s=0.001, jitter=0.0),
            seam="test.down",
        )


def test_retry_sync_non_retryable_propagates():
    calls = []

    def bad():
        calls.append(1)
        raise ValueError("logic bug, not transport")

    with pytest.raises(ValueError):
        retry_sync(bad, RetryPolicy(attempts=5, base_delay_s=0.001))
    assert len(calls) == 1  # no blind retry of a non-transport error


def test_retry_deadline_bounds_wall_clock():
    def always_down():
        raise TimeoutError("down")

    policy = RetryPolicy(
        attempts=1000, base_delay_s=0.05, multiplier=1.0, jitter=0.0,
        deadline_s=0.2,
    )
    t0 = time.monotonic()
    with pytest.raises(TimeoutError):
        retry_sync(always_down, policy, seam="test.deadline")
    assert time.monotonic() - t0 < 1.0  # deadline, not 1000 attempts


# ---------------------------------------------------------------------------
# Stepcast typed wire (pickle replacement)
# ---------------------------------------------------------------------------


def test_stepcast_codec_roundtrip():
    from dynamo_tpu.parallel.stepcast import decode_step, encode_step

    toks = np.arange(7, dtype=np.int32)
    tables = np.zeros((2, 4), np.int32)
    args = (
        toks, tables, 5, 2.5, "name", None, True,
        (0.0, 40, 1.0),                      # sampling tuple
        [1, 2, [3, (4, 5)]],                 # nested list/tuple
        {"k": np.float32(1.5), "n": None},   # str-keyed dict
    )
    kwargs = {"mm_embeds": np.ones((2, 3), np.float32), "flag": False}
    seq, name, out_args, out_kwargs = decode_step(
        encode_step(3, "prefill", args, kwargs)
    )
    assert (seq, name) == (3, "prefill")
    np.testing.assert_array_equal(out_args[0], toks)
    assert out_args[0].dtype == np.int32
    np.testing.assert_array_equal(out_args[1], tables)
    assert out_args[2:7] == (5, 2.5, "name", None, True)
    assert out_args[7] == (0.0, 40, 1.0) and isinstance(out_args[7], tuple)
    assert out_args[8] == [1, 2, [3, (4, 5)]]
    assert out_args[9] == {"k": 1.5, "n": None}
    np.testing.assert_array_equal(out_kwargs["mm_embeds"], np.ones((2, 3)))
    assert out_kwargs["flag"] is False


def test_stepcast_rejects_malformed():
    import msgpack

    from dynamo_tpu.parallel.stepcast import (
        StepWireError,
        decode_step,
        encode_step,
    )

    # Unknown method name.
    with pytest.raises(StepWireError, match="unexpected replayed call"):
        decode_step(encode_step(0, "eval_evil_code", (), {}))
    # Unknown wire version.
    with pytest.raises(StepWireError, match="version"):
        decode_step(msgpack.packb(
            {"v": 99, "seq": 0, "name": "prefill", "args": [], "kwargs": {}}
        ))
    # Extra field smuggled in.
    with pytest.raises(StepWireError, match="fields"):
        decode_step(msgpack.packb(
            {"v": 1, "seq": 0, "name": "prefill", "args": [], "kwargs": {},
             "__reduce__": "rm -rf"}
        ))
    # Unknown value tag.
    with pytest.raises(StepWireError, match="unknown wire tag"):
        decode_step(msgpack.packb(
            {"v": 1, "seq": 0, "name": "prefill",
             "args": [{"__obj__": "x"}], "kwargs": {}}
        ))
    # Forbidden ndarray dtype (object arrays were pickle's attack surface).
    with pytest.raises(StepWireError, match="dtype"):
        decode_step(msgpack.packb(
            {"v": 1, "seq": 0, "name": "prefill",
             "args": [{"__nd__": ["|O", [1], b"x"]}], "kwargs": {}}
        ))
    # Malformed ndarray payloads wrap into StepWireError too (reshape /
    # frombuffer / arity errors must not escape as raw ValueError).
    for bad in (
        {"__nd__": ["<f8", ["x"], b""]},          # non-int shape
        {"__nd__": ["<f8", [100], b"\x00" * 8]},  # shape/buffer mismatch
        {"__nd__": ["<f8", [1]]},                 # wrong arity
        {"__nd__": ["not-a-dtype", [1], b"\x00" * 8]},
    ):
        with pytest.raises(StepWireError):
            decode_step(msgpack.packb(
                {"v": 1, "seq": 0, "name": "prefill", "args": [bad],
                 "kwargs": {}}
            ))
    # Not even msgpack.
    with pytest.raises(StepWireError):
        decode_step(b"\x80\x04\x95pickle-bytes")
    # Leader side refuses unshippable values instead of pickling them.
    with pytest.raises(TypeError):
        encode_step(0, "prefill", (object(),), {})


def test_stepcast_has_no_pickle():
    """Acceptance tripwire: `grep -rn pickle parallel/stepcast.py` must
    stay empty — the step plane must never regress to object
    deserialization."""
    import dynamo_tpu.parallel.stepcast as sc

    source = open(sc.__file__.rstrip("c")).read()
    assert "pickle" not in source


class _RecordingRunner:
    """Follower-side runner stub: records replayed calls."""

    def __init__(self):
        self.calls = []

    def __getattr__(self, name):
        def call(*args, **kwargs):
            self.calls.append((name, args, kwargs))
            return None

        return call


async def test_stepcast_leader_follower_typed_wire():
    from dynamo_tpu.parallel.stepcast import StepLeader, follower_serve
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    drt = await DistributedRuntime.in_process()
    try:
        runner = _RecordingRunner()
        leader_runner = _RecordingRunner()
        follower = asyncio.ensure_future(
            follower_serve(runner, drt, namespace="t", rank=1,
                           heartbeat_s=0.05)
        )
        leader = await asyncio.wait_for(
            StepLeader(
                leader_runner, drt, namespace="t", num_followers=1,
                heartbeat_s=0.05, liveness_timeout_s=5.0,
            ).start(),
            timeout=5.0,
        )
        toks = np.arange(5, dtype=np.int32)
        leader.prefill(toks, [1, 2], 0, (0.0, 0, 1.0))
        leader.decode_multi(toks, toks, np.zeros((1, 2), np.int32), 4)
        leader.attn = "passthrough-not-replayed"  # attribute proxying
        await asyncio.sleep(0.2)
        await leader.stop()
        assert await asyncio.wait_for(follower, 5.0) == 2
        assert [c[0] for c in runner.calls] == ["prefill", "decode_multi"]
        np.testing.assert_array_equal(runner.calls[0][1][0], toks)
        assert runner.calls[0][1][3] == (0.0, 0, 1.0)
        # Leader executed locally too, and non-replayed attrs passed through.
        assert [c[0] for c in leader_runner.calls] == [
            "prefill", "decode_multi"
        ]
        assert leader_runner.attn == "passthrough-not-replayed"
    finally:
        await drt.shutdown()


async def test_stepcast_unified_feed_ships_sentinel_not_device_array():
    """unified_step's feed tokens are the previous dispatch's DEVICE
    array — the wire must carry the FEED_PREV sentinel instead (a
    per-dispatch device→host sync would defeat the pipelined feed), and
    the follower must substitute ITS OWN previous unified output."""
    from dynamo_tpu.engine.runner import UnifiedOut
    from dynamo_tpu.parallel.stepcast import (
        FEED_PREV,
        StepLeader,
        follower_serve,
    )
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    class _NeverEncoded:
        """Stand-in for a device array: the wire encoder would force it
        via __array__ — the test fails loudly if that ever happens."""

        def __array__(self, *a, **k):  # pragma: no cover - failure path
            raise AssertionError("device feed array reached the wire")

    class _UnifiedRunner:
        def __init__(self):
            self.calls = []

        def unified_step(self, lanes, feed=None, **kw):
            self.calls.append((lanes, feed, kw))
            return UnifiedOut(
                last=np.full(4, 7 + len(self.calls), np.int32)
            )

    drt = await DistributedRuntime.in_process()
    try:
        runner = _UnifiedRunner()
        leader_runner = _UnifiedRunner()
        follower = asyncio.ensure_future(
            follower_serve(runner, drt, namespace="u", rank=1,
                           heartbeat_s=0.05)
        )
        leader = await asyncio.wait_for(
            StepLeader(
                leader_runner, drt, namespace="u", num_followers=1,
                heartbeat_s=0.05, liveness_timeout_s=5.0,
            ).start(),
            timeout=5.0,
        )
        lanes = [([3], [1], 0, (0.0, 0, 1.0))]
        # First dispatch: no lane feeds (use_prev all False).
        leader.unified_step(
            lanes,
            feed=(_NeverEncoded(), np.zeros(4, np.int32),
                  np.zeros(4, bool)),
        )
        # Second dispatch: a feeding lane — the follower must substitute
        # its own previous output, never see the leader's device array.
        leader.unified_step(
            lanes,
            feed=(_NeverEncoded(), np.zeros(4, np.int32),
                  np.array([True, False, False, False])),
        )
        await asyncio.sleep(0.2)
        await leader.stop()
        assert await asyncio.wait_for(follower, 5.0) == 2
        assert len(runner.calls) == 2
        for _lanes, feed, _kw in runner.calls:
            assert not isinstance(feed[0], str) or feed[0] != FEED_PREV
        # The follower's second call fed ITS OWN first output.
        np.testing.assert_array_equal(
            np.asarray(runner.calls[1][1][0]), np.full(4, 8, np.int32)
        )
        # The leader's local calls kept the REAL feed object.
        assert isinstance(leader_runner.calls[0][1][0], _NeverEncoded)
    finally:
        await drt.shutdown()


async def test_stepcast_dropped_step_fails_loudly():
    """An injected broadcast drop leaves a seq gap: the follower must fail
    LOUDLY (collectives would deadlock silently otherwise)."""
    from dynamo_tpu.parallel.stepcast import StepLeader, follower_serve
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    drt = await DistributedRuntime.in_process()
    try:
        escalations: list = []
        follower = asyncio.ensure_future(
            follower_serve(_RecordingRunner(), drt, namespace="d", rank=1,
                           heartbeat_s=0.05)
        )
        leader = await asyncio.wait_for(
            StepLeader(
                _RecordingRunner(), drt, namespace="d", num_followers=1,
                heartbeat_s=0.05, liveness_timeout_s=10.0,
                on_follower_lost=escalations.append,
            ).start(),
            timeout=5.0,
        )
        leader.prefill([1], [], 0, (0.0, 0, 1.0))
        FAULTS.arm("stepcast.broadcast", "drop", times=1)
        leader.decode([1], [0], [[0]], [1], [0], 0.0, 0, 1.0)  # dropped
        leader.gather_block(3)  # arrives with seq 2 — gap!
        # Prong 1: the follower's gap check fires on the next frame.
        with pytest.raises(RuntimeError, match="lost step"):
            await asyncio.wait_for(follower, 5.0)
        # Prong 2: the leader's watchdog escalates the drop itself —
        # vital on a real mesh, where the engine thread wedges in the
        # dropped step's collective and never sends a next frame.
        t0 = time.monotonic()
        while not escalations and time.monotonic() - t0 < 3.0:
            await asyncio.sleep(0.02)
        assert escalations, "watchdog never escalated the dropped step"
        assert leader._dropped_steps == [1]
        assert FAULTS.injected["stepcast.broadcast"] == 1
        await leader.stop()
    finally:
        await drt.shutdown()


async def test_stepcast_replay_fault_kills_follower_loudly():
    """An injected fault at the follower's replay seam (the step frame
    failing to apply — the SPMD twin diverging) must kill follower_serve
    LOUDLY: a follower that swallows a replay error and keeps acking
    heartbeats would desync the mesh while looking alive."""
    from dynamo_tpu.parallel.stepcast import StepLeader, follower_serve
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    drt = await DistributedRuntime.in_process()
    try:
        follower = asyncio.ensure_future(
            follower_serve(_RecordingRunner(), drt, namespace="r", rank=1,
                           heartbeat_s=0.05)
        )
        leader = await asyncio.wait_for(
            StepLeader(
                _RecordingRunner(), drt, namespace="r", num_followers=1,
                heartbeat_s=0.05, liveness_timeout_s=10.0,
            ).start(),
            timeout=5.0,
        )
        FAULTS.arm("stepcast.replay", "raise", times=1)
        leader.prefill([1], [], 0, (0.0, 0, 1.0))
        with pytest.raises(FaultError):
            await asyncio.wait_for(follower, 5.0)
        assert FAULTS.injected["stepcast.replay"] == 1
        await leader.stop()
    finally:
        await drt.shutdown()


async def test_stepcast_leader_detects_dead_follower():
    """Follower death mid-serve: the leader's watchdog must flag it within
    the liveness timeout — never hang waiting for a heartbeat."""
    from dynamo_tpu.parallel.stepcast import StepLeader, follower_serve
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    drt = await DistributedRuntime.in_process()
    try:
        lost: list = []
        follower = asyncio.ensure_future(
            follower_serve(_RecordingRunner(), drt, namespace="w", rank=1,
                           heartbeat_s=0.05)
        )
        leader = await asyncio.wait_for(
            StepLeader(
                _RecordingRunner(), drt, namespace="w", num_followers=1,
                heartbeat_s=0.05, liveness_timeout_s=0.3,
                on_follower_lost=lost.append,
            ).start(),
            timeout=5.0,
        )
        leader.prefill([1], [], 0, (0.0, 0, 1.0))
        await asyncio.sleep(0.2)
        assert not lost  # heartbeats flowing — no false positive
        follower.cancel()  # the "process died" moment
        try:
            await follower
        except asyncio.CancelledError:
            pass
        t0 = time.monotonic()
        while not lost and time.monotonic() - t0 < 3.0:
            await asyncio.sleep(0.02)
        assert lost == [["1"]], "watchdog never flagged the dead follower"
        assert leader.followers_lost == ["1"]
        await leader.stop()
    finally:
        await drt.shutdown()


# ---------------------------------------------------------------------------
# Bus / control plane / response plane
# ---------------------------------------------------------------------------


async def test_bus_publish_drop_counted_no_hang():
    from dynamo_tpu.runtime.transports.bus import InProcBus

    bus = InProcBus()
    sub = await bus.subscribe("subj")
    FAULTS.arm("bus.publish", "drop", times=1)
    await bus.publish("subj", b"lost")
    await bus.publish("subj", b"kept")
    got = await asyncio.wait_for(sub.__anext__(), 2.0)
    assert got == b"kept"
    assert FAULTS.injected["bus.publish"] == 1
    sub.close()


async def test_bus_broadcast_drop_loses_whole_fanout_counted():
    """An injected broadcast drop is one lost EVENT, not one lost
    delivery: no subscriber sees the dropped frame (the events plane is
    fire-and-forget — KV events / metrics — so consumers must tolerate
    gaps), and the loss is counted exactly once."""
    from dynamo_tpu.runtime.transports.bus import InProcBus

    bus = InProcBus()
    sub_a = await bus.subscribe("events")
    sub_b = await bus.subscribe("events")
    FAULTS.arm("bus.broadcast", "drop", times=1)
    await bus.broadcast("events", b"lost")
    await bus.broadcast("events", b"kept")
    for sub in (sub_a, sub_b):
        got = await asyncio.wait_for(sub.__anext__(), 2.0)
        assert got == b"kept"
        sub.close()
    assert FAULTS.injected["bus.broadcast"] == 1


async def test_control_keepalive_partition_escalates_to_shutdown():
    """Injected keepalive partition ⇒ the lease cannot renew ⇒ the
    CriticalTask escalates to runtime shutdown (the lease-death ⇒
    shutdown coupling) — within a bounded wait, not a silent wedge."""
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.transports.control_plane import ControlPlaneServer

    server = await ControlPlaneServer().start()
    drt = await DistributedRuntime.connect(server.address, lease_ttl_s=0.3)
    try:
        assert not drt.runtime.is_shutdown
        FAULTS.arm("control.keepalive", "partition")
        t0 = time.monotonic()
        while not drt.runtime.is_shutdown and time.monotonic() - t0 < 5.0:
            await asyncio.sleep(0.05)
        assert drt.runtime.is_shutdown, "keepalive death never escalated"
        assert FAULTS.injected["control.keepalive"] >= 1
    finally:
        FAULTS.clear()
        await drt.shutdown()
        await server.stop()


async def test_control_connect_retries_through_refusal():
    """The first dial hitting an injected connection fault must retry
    under the shared policy, not kill the worker (k8s rollout ordering)."""
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.transports.control_plane import ControlPlaneServer

    server = await ControlPlaneServer().start()
    base = RETRIES.snapshot().get("control.connect", 0)
    # First RPC (the client's auth-free first _call is grant_lease; the
    # connect seam wraps socket open + first calls) — inject one failure
    # at the control.call seam via partition-then-clear is racy; instead
    # arm a single raise on the call seam and rely on connect's retry.
    FAULTS.arm("control.call", "raise", times=1)
    drt = await DistributedRuntime.connect(server.address, lease_ttl_s=5.0)
    try:
        assert RETRIES.snapshot().get("control.connect", 0) > base
        assert await drt.store.get("nope") is None  # plane usable after
    finally:
        await drt.shutdown()
        await server.stop()


async def test_tcp_respond_fault_bounded_and_recovers():
    """A response-plane failure mid-stream surfaces as a bounded TYPED
    transport error (WorkerDiedError — the failover-eligible class,
    never a hang, never an untyped RuntimeError); the NEXT request
    succeeds on a fresh stream even though the mark-dead fast path
    evicted the instance (the store refresh re-resolves it)."""
    from dynamo_tpu.llm.protocols.common import WorkerDiedError
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.egress import PushRouter
    from dynamo_tpu.runtime.engine import Context, EngineAdapter

    async def engine(ctx):
        for tok in ctx.payload["tokens"]:
            yield {"token": tok}

    drt = await DistributedRuntime.in_process()
    try:
        ep = drt.namespace("chaos").component("tcp").endpoint("generate")
        await ep.serve(EngineAdapter(engine))
        router = await PushRouter.create(drt, ep.id)

        injected_before = FAULTS.injected.get("tcp.respond", 0)
        FAULTS.arm("tcp.respond", "raise", times=1)

        async def collect():
            out = []
            async for item in router.generate(Context({"tokens": [1, 2]})):
                out.append(item["token"])
            return out

        with pytest.raises(WorkerDiedError, match="injected fault"):
            await asyncio.wait_for(collect(), 5.0)
        assert await asyncio.wait_for(collect(), 5.0) == [1, 2]
        assert FAULTS.injected["tcp.respond"] == injected_before + 1
    finally:
        await drt.shutdown()


# ---------------------------------------------------------------------------
# KVBM offload pump
# ---------------------------------------------------------------------------


async def test_kvbm_pump_fault_drops_offer_then_recovers():
    from dynamo_tpu.block_manager import (
        KvbmConfig,
        KvBlockManager,
        KvLayoutConfig,
    )

    layout = KvLayoutConfig(
        num_layers=2, page_size=16, num_kv_heads=2, head_dim=16,
        dtype="float32",
    )
    mgr = await KvBlockManager(
        KvbmConfig(host_blocks=4, layout=layout)
    ).start()
    try:
        data = np.full((layout.block_elems,), 3.0, np.float32)
        FAULTS.arm("kvbm.pump", "raise", times=1)
        mgr.offer(0xA1, None, tuple(range(16)), data)
        await asyncio.wait_for(mgr.drain_offers(5.0), 6.0)
        # The faulted batch was dropped (offer is opportunistic cache
        # population — recovery is recompute, never request loss)...
        assert mgr.host_pool.get_by_hash(0xA1) is None
        assert FAULTS.injected["kvbm.pump"] == 1
        # ...and the hash was un-marked, so a re-offer lands cleanly.
        mgr.offer(0xA1, None, tuple(range(16)), data)
        await asyncio.wait_for(mgr.drain_offers(5.0), 6.0)
        assert mgr.host_pool.get_by_hash(0xA1) is not None
        # drop action: the batch is silently lost but un-marked too.
        FAULTS.arm("kvbm.pump", "drop", times=1)
        mgr.offer(0xA2, None, tuple(range(16, 32)), data)
        await asyncio.wait_for(mgr.drain_offers(5.0), 6.0)
        assert mgr.host_pool.get_by_hash(0xA2) is None
        mgr.offer(0xA2, None, tuple(range(16, 32)), data)
        await asyncio.wait_for(mgr.drain_offers(5.0), 6.0)
        assert mgr.host_pool.get_by_hash(0xA2) is not None
    finally:
        await mgr.stop()


async def test_kvbm_pump_materializes_only_kept_rows():
    """Satellite (ADVICE r05): a mostly-duplicate offer batch must
    row-select BEFORE host materialization — only dedup-kept rows pay."""
    from dynamo_tpu.block_manager import (
        KvbmConfig,
        KvBlockManager,
        KvLayoutConfig,
    )

    layout = KvLayoutConfig(
        num_layers=2, page_size=16, num_kv_heads=2, head_dim=16,
        dtype="float32",
    )
    mgr = await KvBlockManager(
        KvbmConfig(host_blocks=8, layout=layout)
    ).start()
    try:
        batch = np.stack(
            [np.full((layout.block_elems,), float(i)) for i in range(4)]
        ).astype(np.float32)

        class SpyArray(np.ndarray):
            """ndarray subclass recording the row-select index, proving
            the host path gathers kept rows BEFORE any full-batch copy."""

            selected = None

            def __getitem__(self, idx):
                if isinstance(idx, np.ndarray):
                    SpyArray.selected = np.asarray(idx)
                return super().__getitem__(idx)

        entries = [
            (0xB0, None, tuple(range(16))),
            (0xB1, 0xB0, tuple(range(16, 32))),
            (0xB2, 0xB1, tuple(range(32, 48))),
            (0xB3, 0xB2, tuple(range(48, 64))),
        ]
        # Pre-store rows 0 and 2 so the batch dedups down to rows 1, 3.
        mgr.offer_batch(entries[:1], batch[:1])
        await asyncio.wait_for(mgr.drain_offers(5.0), 6.0)
        mgr.offer_batch(entries[2:3], batch[2:3])
        await asyncio.wait_for(mgr.drain_offers(5.0), 6.0)

        spy = batch.view(SpyArray)
        mgr.offer_batch(entries, spy)
        await asyncio.wait_for(mgr.drain_offers(5.0), 6.0)
        assert SpyArray.selected is not None, "full batch materialized"
        assert list(SpyArray.selected) == [1, 3]
        for h in (0xB0, 0xB1, 0xB2, 0xB3):
            assert mgr.host_pool.get_by_hash(h) is not None
        # Byte fidelity for the row-selected stores.
        b3 = mgr.host_pool.get_by_hash(0xB3)
        got = mgr.host_pool.storage.read_block(b3.idx)
        np.testing.assert_array_equal(np.asarray(got), batch[3])
    finally:
        await mgr.stop()


# ---------------------------------------------------------------------------
# Disagg transfer plane
# ---------------------------------------------------------------------------


async def test_disagg_transfer_fault_retries_and_lands():
    """One injected send failure: the shared retry policy resends on a
    fresh connection and the blocks land byte-identical."""
    from dynamo_tpu.disagg.transfer import KvReceiver, KvSender

    landed = {}
    finished = []
    recv = await KvReceiver(
        on_block=lambda r, i, d: landed.setdefault((r, i), np.array(d)),
        on_finish=lambda r, t: finished.append((r, t)),
    ).start()
    sender = KvSender()
    base = RETRIES.snapshot().get("disagg.send", 0)
    block = np.arange(8, dtype=np.float32).reshape(2, 4)
    FAULTS.arm("disagg.send", "raise", times=1)
    await asyncio.wait_for(
        sender.send_blocks(recv.address, "r1", [block], 42, auth=recv.auth),
        5.0,
    )
    assert finished == [("r1", 42)]
    np.testing.assert_array_equal(landed[("r1", 0)], block)
    assert RETRIES.snapshot().get("disagg.send", 0) == base + 1
    assert FAULTS.injected["disagg.send"] == 1
    await sender.close()
    await recv.stop()


async def test_disagg_transfer_receiver_death_exhausts_retries():
    """The receiver dying mid-transfer (injected at the landing seam,
    partition) must exhaust the bounded retry budget and raise — the
    caller's requeue/degradation path takes over; never an infinite loop."""
    from dynamo_tpu.disagg.transfer import KvReceiver, KvSender

    recv = await KvReceiver(
        on_block=lambda r, i, d: None, on_finish=lambda r, t: None
    ).start()
    sender = KvSender()
    FAULTS.arm("disagg.recv", "partition")
    block = np.ones((2, 4), np.float32)
    with pytest.raises((ConnectionError, asyncio.IncompleteReadError, OSError)):
        await asyncio.wait_for(
            sender.send_blocks(
                recv.address, "r2", [block], 7, auth=recv.auth
            ),
            10.0,
        )
    assert FAULTS.injected["disagg.recv"] >= 1
    await sender.close()
    FAULTS.clear()
    await recv.stop()


async def test_remote_prefill_transfer_death_degrades_to_local():
    """THE disagg degradation invariant (reference: disagg_serving.md
    degradation-to-local-prefill): the KV push plane dies entirely ⇒ the
    decode side times out the remote wait and completes the request by
    LOCAL recompute — no request loss, degraded counter incremented."""
    from dynamo_tpu.disagg import (
        DecodeOperator,
        DisaggConfig,
        DisaggRouter,
        PrefillQueue,
        PrefillWorker,
    )
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.mocker import MockerConfig, MockerEngine
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.engine import Context

    def ecfg():
        return EngineConfig(
            model=ModelConfig.tiny_test(),
            num_blocks=32,
            max_num_seqs=2,
            max_model_len=128,
            dtype="float32",
            remote_kv_timeout_s=0.5,  # fast chaos loop; default is 30 s
        )

    drt = await DistributedRuntime.in_process()
    queue = PrefillQueue(drt, "chaos")
    dis = DisaggRouter.__new__(DisaggRouter)
    dis.cfg = DisaggConfig(max_local_prefill_length=16, max_prefill_queue_size=8)

    decode = MockerEngine(ecfg(), MockerConfig(seed=7))
    await decode.start()
    prefill = MockerEngine(ecfg(), MockerConfig(seed=7))
    await prefill.start()
    op = await DecodeOperator(decode, queue, dis, transport="tcp").start()
    pw = PrefillWorker(prefill, queue).start()
    try:
        # The entire KV push plane is down (partition at the send seam).
        FAULTS.arm("disagg.send", "partition")
        req = PreprocessedRequest(
            token_ids=list(range(40)),  # long ⇒ routed remote
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=6, ignore_eos=True),
        )
        toks = []

        async def run():
            async for item in op.generate(Context(req.to_wire())):
                toks.extend(item["token_ids"])

        await asyncio.wait_for(run(), 30.0)  # bounded: no hang
        assert len(toks) == 6, "request lost under transfer death"
        assert op.remote_count == 1  # it WAS routed remote...
        assert decode.degraded_requests == 1  # ...and degraded to local
        assert decode.readiness()["degraded_requests_total"] == 1
        assert FAULTS.injected["disagg.send"] >= 1

        # Second scenario: ONE silently lost block frame (drop at the
        # landing seam). The finish notification arrives over a hole —
        # activation's completeness check must refuse to decode over
        # stale KV and degrade to recompute instead (no token
        # corruption, no hang, still no request loss).
        FAULTS.clear()
        # Run 1's bounded requeue attempts may still be in flight; once
        # the partition clears, a late attempt SUCCEEDS and its frames
        # would consume the drop budget below. Wait for the queue AND the
        # worker to go quiet (depth 0, served count stable over a window
        # longer than the retry backoff) before arming.
        stable, t0 = 0, time.monotonic()
        while stable < 2 and time.monotonic() - t0 < 15.0:
            before = pw.served
            await asyncio.sleep(0.4)
            if await queue.depth() == 0 and pw.served == before:
                stable += 1
            else:
                stable = 0
        recv_base = FAULTS.snapshot().get("disagg.recv", 0)
        FAULTS.arm("disagg.recv", "drop", times=1)
        req2 = PreprocessedRequest(
            token_ids=list(range(100, 140)),  # fresh prompt: no prefix
            sampling=SamplingOptions(temperature=0.0),  # hit keeps it
            stop=StopConditions(max_tokens=6, ignore_eos=True),  # remote
        )
        toks2: list = []

        async def run2():
            async for item in op.generate(Context(req2.to_wire())):
                toks2.extend(item["token_ids"])

        await asyncio.wait_for(run2(), 30.0)
        assert len(toks2) == 6, "request lost under single-frame loss"
        assert op.remote_count == 2
        assert decode.degraded_requests == 2
        assert FAULTS.snapshot().get("disagg.recv", 0) == recv_base + 1
    finally:
        FAULTS.clear()
        await pw.stop()
        await op.stop()
        await decode.stop()
        await prefill.stop()
        await drt.shutdown()


# ---------------------------------------------------------------------------
# Lease expiry end-to-end (satellite)
# ---------------------------------------------------------------------------


async def test_lease_expiry_end_to_end():
    """Worker lease lapses ⇒ store deregisters ⇒ router stops routing to
    it ⇒ the request already streaming COMPLETES (the response plane is a
    direct TCP stream, independent of discovery)."""
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.egress import PushRouter
    from dynamo_tpu.runtime.engine import Context, EngineAdapter
    from dynamo_tpu.runtime.runtime import Runtime

    drt_front = await DistributedRuntime.in_process()
    drt_worker = await DistributedRuntime.in_process(
        runtime=Runtime(), store=drt_front.store, bus=drt_front.bus
    )
    try:
        async def slow_engine(ctx):
            for i in range(5):
                yield {"i": i}
                await asyncio.sleep(0.15)

        ep = drt_worker.namespace("chaos").component("lease").endpoint("gen")
        await ep.serve(EngineAdapter(slow_engine))
        router = await PushRouter.create(drt_front, ep.id)
        assert len(await router.client.wait_for_instances()) == 1

        got = []

        async def consume():
            async for item in router.generate(Context({})):
                got.append(item["i"])

        stream = asyncio.ensure_future(consume())
        await asyncio.sleep(0.2)  # stream in flight

        # The lease lapses: keepalive dies and the TTL runs out.
        drt_worker._keepalive.cancel()
        lease = drt_front.store._leases[drt_worker.primary_lease_id]
        lease.ttl_s = 0.1
        lease.expires_at = time.monotonic() + 0.1

        t0 = time.monotonic()
        while router.client.instances() and time.monotonic() - t0 < 3.0:
            await asyncio.sleep(0.02)
        assert router.client.instances() == [], "router kept a dead worker"

        # New requests have nowhere to go...
        with pytest.raises(asyncio.TimeoutError):
            await router.client.wait_for_instances(timeout_s=0.2)
        # ...but the in-flight stream completes untouched.
        await asyncio.wait_for(stream, 5.0)
        assert got == [0, 1, 2, 3, 4]
    finally:
        await drt_worker.shutdown()
        await drt_front.shutdown()


# ---------------------------------------------------------------------------
# Lease keepalive flap hardening (satellite)
# ---------------------------------------------------------------------------


async def test_keepalive_flap_does_not_deregister():
    """A TRANSIENT control-plane blip shorter than the lease TTL must NOT
    take a healthy worker down: the keepalive retries in place (within
    the TTL budget) and the lease-bound instance key survives. Regression
    for the old behavior where ONE raised keepalive escalated straight to
    runtime shutdown even though the lease had 2/3 of its TTL left."""
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.transports.control_plane import ControlPlaneServer

    server = await ControlPlaneServer().start()
    drt = await DistributedRuntime.connect(server.address, lease_ttl_s=1.0)
    try:
        await drt.store.put(
            "flap/instance", b"alive", lease_id=drt.primary_lease_id
        )
        base = RETRIES.snapshot().get("control.keepalive", 0)
        injected_base = FAULTS.snapshot().get("control.keepalive", 0)
        # Two consecutive keepalive failures — a partition far shorter
        # than the TTL (each retried within ~TTL/30 of backoff).
        FAULTS.arm("control.keepalive", "raise", times=2)
        await asyncio.sleep(2.2)  # several keepalive periods
        assert not drt.runtime.is_shutdown, (
            "transient keepalive flap deregistered a healthy worker"
        )
        assert await drt.store.get("flap/instance") == b"alive"
        assert (
            FAULTS.snapshot().get("control.keepalive", 0) == injected_base + 2
        )
        assert RETRIES.snapshot().get("control.keepalive", 0) > base
    finally:
        FAULTS.clear()
        await drt.shutdown()
        await server.stop()


# ---------------------------------------------------------------------------
# Graceful drain (tentpole e2e) + deadline/queue-full chaos
# ---------------------------------------------------------------------------


async def test_drain_verb_end_to_end():
    """Control-plane drain verb on a worker with an in-flight request:
    the in-flight stream COMPLETES, readiness flips to draining, new
    requests are refused with a typed ShedError, the instance key is
    deleted (router eviction), and the engine fully drains."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        ShedError,
        StopConditions,
    )
    from dynamo_tpu.mocker import MockerConfig, MockerEngine
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.drain import request_drain, watch_drain
    from dynamo_tpu.runtime.egress import PushRouter
    from dynamo_tpu.runtime.engine import Context
    from dynamo_tpu.runtime.runtime import Runtime
    from dynamo_tpu.utils.task import spawn_tracked

    drt_front = await DistributedRuntime.in_process()
    drt_worker = await DistributedRuntime.in_process(
        runtime=Runtime(), store=drt_front.store, bus=drt_front.bus
    )
    engine = MockerEngine(
        EngineConfig(
            model=ModelConfig.tiny_test(), num_blocks=64, max_num_seqs=4,
            max_model_len=256, dtype="float32",
        ),
        MockerConfig(decode_time_per_step_us=15000.0),
    )
    await engine.start()
    try:
        ep = drt_worker.namespace("chaos").component("drain").endpoint("gen")
        served = await ep.serve(engine)
        drain_done = asyncio.Event()

        def on_drain():
            async def run():
                # Canonical order (cli._graceful_drain): refuse new work,
                # deregister FIRST for immediate eviction, then drain.
                engine.begin_drain()
                assert await served.drain(30.0)
                assert await engine.wait_drained(10.0)
                drain_done.set()

            spawn_tracked(run(), name="test-drain")

        await watch_drain(drt_worker, "chaos", "drain", on_drain)
        router = await PushRouter.create(drt_front, ep.id)
        assert len(await router.client.wait_for_instances()) == 1

        req = PreprocessedRequest(
            token_ids=list(range(16)),
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=24, ignore_eos=True),
        )
        got: list = []

        async def consume():
            async for item in router.generate(Context(req.to_wire())):
                got.extend(item.get("token_ids") or [])

        stream = asyncio.ensure_future(consume())
        await asyncio.sleep(0.3)  # request genuinely in flight
        assert got and len(got) < 24

        # The control-plane verb (fired from the FRONTEND runtime).
        await request_drain(drt_front, "chaos", "drain")
        await asyncio.wait_for(drain_done.wait(), 30.0)

        # In-flight stream completed in full — nothing dropped.
        await asyncio.wait_for(stream, 10.0)
        assert len(got) == 24

        # Readiness flipped and new work is refused with a typed error.
        assert engine.readiness()["state"] == "draining"
        with pytest.raises(ShedError):
            async for _ in engine.generate(Context(req.to_wire())):
                pass

        # Router evicted the instance (store key deleted by drain).
        t0 = time.monotonic()
        while router.client.instances() and time.monotonic() - t0 < 3.0:
            await asyncio.sleep(0.02)
        assert router.client.instances() == []
    finally:
        await engine.stop()
        await drt_worker.shutdown()
        await drt_front.shutdown()


async def test_sigterm_drain_end_to_end():
    """SIGTERM on a worker PROCESS with an in-flight request: the stream
    completes, the instance deregisters, and the process exits cleanly
    after printing its drain verdict — the loss-free rolling restart."""
    import os
    import signal as _signal
    import sys

    from dynamo_tpu.runtime.component import EndpointId
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.egress import PushRouter
    from dynamo_tpu.runtime.engine import Context
    from dynamo_tpu.runtime.transports.control_plane import ControlPlaneServer
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker_py = os.path.join(repo, "tests", "procs", "drain_worker.py")
    server = await ControlPlaneServer().start()
    proc = await asyncio.create_subprocess_exec(
        sys.executable, worker_py, "--addr", server.address,
        stdout=asyncio.subprocess.PIPE,
        stderr=asyncio.subprocess.STDOUT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    try:
        # Wait for READY.
        while True:
            line = await asyncio.wait_for(proc.stdout.readline(), 60.0)
            assert line, "worker died before READY"
            if line.startswith(b"READY"):
                break
        drt = await DistributedRuntime.connect(server.address)
        try:
            router = await PushRouter.create(
                drt, EndpointId("chaos", "drainw", "generate")
            )
            assert len(await router.client.wait_for_instances(10.0)) == 1
            req = PreprocessedRequest(
                token_ids=list(range(16)),
                sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=24, ignore_eos=True),
            )
            got: list = []

            async def consume():
                async for item in router.generate(Context(req.to_wire())):
                    got.extend(item.get("token_ids") or [])

            stream = asyncio.ensure_future(consume())
            await asyncio.sleep(0.4)
            assert got and len(got) < 24  # mid-flight

            proc.send_signal(_signal.SIGTERM)
            await asyncio.wait_for(stream, 30.0)
            assert len(got) == 24, "SIGTERM dropped an in-flight request"

            # Instance deregistered (drain deletes the key; lease revoke
            # backs it up), so the router has nowhere to send new work.
            t0 = time.monotonic()
            while (
                router.client.instances() and time.monotonic() - t0 < 10.0
            ):
                await asyncio.sleep(0.05)
            assert router.client.instances() == []
        finally:
            await drt.shutdown()
        out, _ = await asyncio.wait_for(proc.communicate(), 30.0)
        assert b"DRAINED True" in out, out
        assert proc.returncode == 0
    finally:
        if proc.returncode is None:
            proc.kill()
            await proc.wait()
        await server.stop()


async def test_deadline_expiry_under_injected_transfer_delay():
    """Chaos: the disagg KV push plane is slow (injected delay past the
    request's deadline). The decode side's remote-wait sweep cancels the
    request with a typed DEADLINE finish — bounded, counted, no hang and
    no decode over late-arriving KV."""
    from dynamo_tpu.disagg import (
        DecodeOperator,
        DisaggConfig,
        DisaggRouter,
        PrefillQueue,
        PrefillWorker,
    )
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.llm.protocols.common import (
        FinishReason,
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.mocker import MockerConfig, MockerEngine
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.engine import Context
    from dynamo_tpu.utils.deadline import OVERLOAD, Deadline

    def ecfg():
        return EngineConfig(
            model=ModelConfig.tiny_test(), num_blocks=32, max_num_seqs=2,
            max_model_len=128, dtype="float32", remote_kv_timeout_s=30.0,
        )

    drt = await DistributedRuntime.in_process()
    queue = PrefillQueue(drt, "chaos-deadline")
    dis = DisaggRouter.__new__(DisaggRouter)
    dis.cfg = DisaggConfig(
        max_local_prefill_length=16, max_prefill_queue_size=8
    )
    decode = MockerEngine(ecfg(), MockerConfig(seed=7))
    await decode.start()
    prefill = MockerEngine(ecfg(), MockerConfig(seed=7))
    await prefill.start()
    op = await DecodeOperator(decode, queue, dis, transport="tcp").start()
    pw = PrefillWorker(prefill, queue).start()
    try:
        base = OVERLOAD.deadline_total
        # Every KV send stalls 1.2 s — well past the 0.4 s deadline.
        FAULTS.arm("disagg.send", "delay", delay_s=1.2, times=None)
        req = PreprocessedRequest(
            token_ids=list(range(40)),  # long => routed remote
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=6, ignore_eos=True),
            deadline=Deadline.after(0.4),
        )
        toks: list = []
        finish = None

        async def run():
            nonlocal finish
            async for item in op.generate(Context(req.to_wire())):
                toks.extend(item["token_ids"])
                if item.get("finish_reason"):
                    finish = item["finish_reason"]

        await asyncio.wait_for(run(), 30.0)  # bounded — never a hang
        assert op.remote_count == 1
        assert toks == []
        assert finish == FinishReason.DEADLINE.value
        assert OVERLOAD.deadline_total > base
    finally:
        FAULTS.clear()
        await pw.stop()
        await op.stop()
        await decode.stop()
        await prefill.stop()
        await drt.shutdown()


async def test_queue_full_sheds_remote_to_local():
    """Chaos: the prefill queue sits at its depth bound with NO live
    consumer (a stalled pool — the same end state an armed
    ``disagg.send`` partition leaves after the workers' bounded requeues
    give up). New remote-eligible requests must fall back to LOCAL
    prefill — they complete, the shed is counted, nothing queues behind
    the stall."""
    from dynamo_tpu.disagg import (
        DecodeOperator,
        DisaggConfig,
        DisaggRouter,
        PrefillQueue,
    )
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.mocker import MockerConfig, MockerEngine
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.engine import Context
    from dynamo_tpu.utils.deadline import OVERLOAD

    drt = await DistributedRuntime.in_process()
    # Hard depth bound of 2; no live consumer (the stalled-pool shape the
    # age/depth bounds exist for).
    queue = PrefillQueue(drt, "chaos-full", max_depth=2)
    dis = DisaggRouter.__new__(DisaggRouter)
    dis.cfg = DisaggConfig(
        max_local_prefill_length=16,
        max_prefill_queue_size=10**6,  # router soft bound out of the way
        max_prefill_queue_age_s=1e9,
    )
    decode = MockerEngine(
        EngineConfig(
            model=ModelConfig.tiny_test(), num_blocks=64, max_num_seqs=4,
            max_model_len=256, dtype="float32",
        ),
        MockerConfig(seed=3),
    )
    await decode.start()
    op = await DecodeOperator(decode, queue, dis, transport="tcp").start()
    try:
        # Fill the queue to its bound (a stalled pool never drains these).
        await queue.enqueue({"request_id": "stuck-1"})
        await queue.enqueue({"request_id": "stuck-2"})
        base = OVERLOAD.shed_total
        req = PreprocessedRequest(
            token_ids=list(range(40)),
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=6, ignore_eos=True),
        )
        toks: list = []

        async def run():
            async for item in op.generate(Context(req.to_wire())):
                toks.extend(item["token_ids"])

        await asyncio.wait_for(run(), 30.0)
        assert len(toks) == 6, "request lost under queue-full shed"
        assert op.remote_count == 0 and op.local_count == 1
        assert OVERLOAD.shed_total > base
        assert await queue.depth() == 2  # nothing new queued behind it
    finally:
        await op.stop()
        await decode.stop()
        await drt.shutdown()


# ---------------------------------------------------------------------------
# Adaptive onboard gate (satellite)
# ---------------------------------------------------------------------------


class _FakeClock:
    """Deterministic monotonic clock: each call advances a fixed step."""

    def __init__(self, step_s: float):
        self.t = 0.0
        self.step = step_s

    def __call__(self) -> float:
        self.t += self.step
        return self.t


def _gate_engine(adaptive=True):
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.mocker import MockerConfig, MockerEngine
    from dynamo_tpu.models.config import ModelConfig

    return MockerEngine(
        EngineConfig(
            model=ModelConfig.tiny_test(),
            num_blocks=64,
            max_num_seqs=2,
            max_model_len=512,
            dtype="float32",
            kvbm_adaptive_gate=adaptive,
        ),
        MockerConfig(),
    )


class _FakeKvbm:
    """count/match stub: every requested hash 'exists' on the host tier,
    with per-call recording of how much the engine actually pulled."""

    def __init__(self, block_bytes):
        self.block_bytes = block_bytes
        self.match_lens = []

    def count_host_match(self, hashes):
        return len(hashes)

    def request_disk_promotion(self, hashes):
        pass

    def match_host(self, hashes):
        self.match_lens.append(len(hashes))
        row = np.zeros(self.block_bytes // 4, np.float32)
        return [(h, None, tuple(range(16)), row) for h in hashes]


async def test_adaptive_gate_first_probe_byte_capped(monkeypatch):
    """VERDICT weak #3: the FIRST gate measurement must move at most
    PROBE_BLOCKS blocks — the unbounded first onboard was a 6+ s engine
    stall (14x p95 TTFT) on exactly the slow link the gate exists for."""
    from dynamo_tpu.engine.sequence import Sequence

    eng = _gate_engine()
    await eng.start()
    try:
        cfg = eng.cfg
        block_bytes = (
            cfg.model.num_layers * 2 * cfg.block_size
            * cfg.model.num_cache_heads * eng.runner.cache_head_dim
            * np.dtype(cfg.dtype).itemsize
        )
        fake = _FakeKvbm(block_bytes)
        eng.kvbm = fake
        monkeypatch.setattr(
            eng.allocator, "register", lambda *a, **k: None
        )
        eng._clock = _FakeClock(0.01)

        def seq_for(n_tokens):
            s = Sequence(
                request_id="probe",
                prompt_tokens=list(range(n_tokens)),
                sampling=None,
                stop=None,
                emit=lambda *a: None,
            )
            assert eng.scheduler.admit(s)
            return s

        seq = seq_for(16 * cfg.block_size + 1)  # 16 full prompt blocks
        eng._onboard_host_prefix(seq)
        assert fake.match_lens == [eng.PROBE_BLOCKS], (
            f"first probe pulled {fake.match_lens} blocks, not the cap"
        )
        assert eng._onboard_probes == 1
        # The injected clock advanced one step across the probe window, so
        # the extrapolated rate is exactly probe_bytes / step.
        expected_bps = eng.PROBE_BLOCKS * block_bytes / 0.01
        assert eng._onboard_bps == pytest.approx(expected_bps, rel=1e-6)
    finally:
        await eng.stop()


async def test_adaptive_gate_ema_convergence():
    """EMA convergence under an injected clock: repeated byte-capped
    probes at a stable link rate converge the estimate to that rate."""
    eng = _gate_engine()
    true_bps = 80e6
    probe_bytes = 4 * 2**20
    dt = probe_bytes / true_bps
    # Contaminated first sample (e.g. a compile in the window): 100x slow.
    eng._note_onboard_rate(probe_bytes, dt * 100)
    assert eng._onboard_bps < true_bps / 50
    for _ in range(20):
        eng._note_onboard_rate(probe_bytes, dt)
    assert abs(eng._onboard_bps - true_bps) / true_bps < 0.01, (
        "EMA failed to converge to the true link rate"
    )
    # Prefill-side EMA mirrors it.
    for _ in range(20):
        eng._note_prefill_rate(1000, 0.5)
    assert abs(eng._prefill_tps - 2000.0) < 20.0


# ---------------------------------------------------------------------------
# Disarmed == identical (acceptance)
# ---------------------------------------------------------------------------


async def _mocker_tokens(seed=3):
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.mocker import MockerConfig, MockerEngine
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.runtime.engine import Context

    eng = MockerEngine(
        EngineConfig(
            model=ModelConfig.tiny_test(), num_blocks=32, max_num_seqs=2,
            max_model_len=128, dtype="float32",
        ),
        MockerConfig(seed=seed),
    )
    await eng.start()
    req = PreprocessedRequest(
        token_ids=list(range(24)),
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=8, ignore_eos=True),
    )
    toks = []
    async for item in eng.generate(Context(req.to_wire())):
        toks += item["token_ids"]
    await eng.stop()
    return toks


async def test_disarmed_faults_behavior_identical():
    """With nothing armed the instrumented seams must be pass-through:
    the same seeded serving run produces identical tokens before fault
    arming, after arm+clear, and with a fault armed on an unused point."""
    baseline = await _mocker_tokens()
    FAULTS.arm("some.unused.point", "partition")
    with_unused_fault = await _mocker_tokens()
    FAULTS.clear()
    after_clear = await _mocker_tokens()
    assert baseline == with_unused_fault == after_clear
    assert len(baseline) == 8


# ---------------------------------------------------------------------------
# dynarace runtime checker drills (docs/development/static_analysis.md
# "Concurrency discipline"): a tier-1 subset runs REAL seams with
# DYNTPU_CHECK_THREADS=1 — tracked locks on the block-manager pool, the
# recorder, the tracer and the flight ring, affinity-bound threads — and
# must come out clean. ci.sh re-runs this module plus
# tests/test_concurrency.py with the env var set for the import-time
# enablement path.
# ---------------------------------------------------------------------------


@pytest.fixture
def _checker_on(monkeypatch):
    import os

    from dynamo_tpu.utils import concurrency as ck

    # Restore the OUTER env value on teardown (the ci.sh dynarace leg
    # sets DYNTPU_CHECK_THREADS=1 for the whole session) and refresh
    # AFTER the restore — delenv+refresh would leave the checker
    # silently disarmed for every test that runs after this one.
    prev = os.environ.get("DYNTPU_CHECK_THREADS")
    monkeypatch.setenv("DYNTPU_CHECK_THREADS", "1")
    ck.refresh_enabled()
    ck.reset_tracking()
    yield ck
    if prev is None:
        monkeypatch.delenv("DYNTPU_CHECK_THREADS", raising=False)
    else:
        monkeypatch.setenv("DYNTPU_CHECK_THREADS", prev)
    ck.refresh_enabled()
    ck.reset_tracking()


async def test_kvbm_offload_pipeline_clean_under_checker(_checker_on, tmp_path):
    """The PR 9 seam under the runtime checker: engine-thread-shaped
    stores and loop-side onboard/stats share the tracked pool lock with
    no lock-order inversion and no affinity violation."""
    import numpy as np

    from dynamo_tpu.block_manager.config import KvLayoutConfig
    from dynamo_tpu.block_manager.offload import OffloadManager
    from dynamo_tpu.block_manager.pool import BlockPool
    from dynamo_tpu.block_manager.storage import DiskStorage, HostStorage
    from dynamo_tpu.utils import concurrency as ck

    layout = KvLayoutConfig(
        num_layers=2, page_size=16, num_kv_heads=2, head_dim=16,
        dtype="float32",
    )
    lock = ck.make_lock("kvbm.pool")
    assert isinstance(lock, ck.TrackedLock)
    host = BlockPool(HostStorage(4, layout))
    disk = BlockPool(DiskStorage(4, layout, tmp_path / "kv.bin"))
    mgr = OffloadManager(host, disk, lock=lock)

    data = np.zeros(layout.block_elems, np.float32)
    blocks = host.allocate_blocks(2)
    for i, b in enumerate(blocks):
        host.storage.write_block(b.idx, data)
    regs = [
        host.release(host.register_block(b, 10 + i, None, range(16)))
        or host.get_by_hash(10 + i)
        for i, b in enumerate(blocks)
    ]
    for b in regs:
        mgr.offload(b)
    await mgr.drain()
    assert disk.num_registered == 2
    # Loop-side onboard (to_thread workers bind "worker" via bound()).
    host2 = BlockPool(HostStorage(4, layout))
    mgr2 = OffloadManager(host2, disk, lock=lock)
    up = await mgr2.onboard([10, 11])
    assert [b.sequence_hash for b in up] == [10, 11]
    assert mgr.stats()["offloaded_blocks_total"] == 2


async def test_tracer_and_flight_ring_clean_under_checker(_checker_on, tmp_path):
    """Span storm across engine/loop-bound threads through the tracked
    tracer + recorder + flight-ring locks: no inversion observed."""
    import threading

    from dynamo_tpu.engine.flight_recorder import FlightRecorder
    from dynamo_tpu.utils import concurrency as ck
    from dynamo_tpu.utils.tracing import Tracer

    tr = Tracer(record_path=str(tmp_path / "spans.jsonl"))
    fr = FlightRecorder(capacity=64)
    assert isinstance(tr._lock, ck.TrackedLock)
    assert isinstance(fr._lock, ck.TrackedLock)

    def engine_side():
        ck.bind_thread("engine")
        for i in range(100):
            rid = f"r{i}"
            tr.mark(rid, "received")
            with tr.span(rid, "dispatch"):
                fr.note_step("unified", decode_tokens=1)
            tr.finish(rid)

    def loop_side():
        ck.bind_thread("loop")
        for _ in range(100):
            fr.snapshot(8)
            tr.snapshot(4)
            tr.render()

    threads = [
        threading.Thread(target=engine_side),
        threading.Thread(target=loop_side),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
        assert not t.is_alive()
    assert fr.total_steps == 100

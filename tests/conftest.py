"""Test harness config: force an 8-device virtual CPU mesh.

Multi-chip hardware is not available in CI; sharding/mesh tests run against
`--xla_force_host_platform_device_count=8` CPU devices, mirroring how the
reference tests distributed behavior without a cluster (reference:
lib/runtime/tests/common/mock.rs — in-process mock network).

Note: the environment's sitecustomize imports jax at interpreter startup and
registers a remote TPU platform (JAX_PLATFORMS=axon), so env vars are too
late — we must flip the platform via jax.config before any backend
initializes.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
if "xla_backend_optimization_level" not in _flags:
    # Tier-1 is compile-bound: the suite compiles thousands of tiny-model
    # XLA programs and runs each a handful of times, so LLVM optimization
    # passes dominate wall clock (measured ~35% of test_engine.py).
    # Correctness is opt-level-independent; tests comparing two runs do
    # so under the same flags. Production paths never see this.
    _flags = (_flags + " --xla_backend_optimization_level=0").strip()
os.environ["XLA_FLAGS"] = _flags

# Disable the persistent XLA compile cache's auto-resolution unless a test
# opts in (explicit EngineConfig.compile_cache_dir / monkeypatch): one
# CLI-path engine activating it would flip the process-global
# jax_compilation_cache_dir (entry-size/compile-time floors at 0) and every
# later compile in the suite would pay disk serialization for nothing.
# Unconditional assignment — an ambient value (the shipped container
# exports this var) must not leak into the suite either.
os.environ["DYNAMO_TPU_COMPILE_CACHE_DIR"] = "none"

import jax

jax.config.update("jax_platforms", "cpu")

import pytest


@pytest.fixture
def anyio_backend():
    return "asyncio"

"""Test harness config: force an 8-device virtual CPU mesh before JAX loads.

Multi-chip hardware is not available in CI; sharding/mesh tests run against
`--xla_force_host_platform_device_count=8` CPU devices, mirroring how the
reference tests distributed behavior without a cluster (reference:
lib/runtime/tests/common/mock.rs — in-process mock network).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest


@pytest.fixture
def anyio_backend():
    return "asyncio"

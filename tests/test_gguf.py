"""GGUF container tests: reader/writer round trip, config + tokenizer from
metadata, unquantized weight loading feeding the real engine (reference
parity: lib/llm/src/gguf/gguf_tokenizer.rs:1-587, gguf_metadata.rs)."""

import numpy as np
import pytest

from dynamo_tpu.llm.gguf import (
    GgufTokenizer,
    load_gguf_weights,
    model_config_from_gguf,
    read_gguf,
    write_gguf,
)
from dynamo_tpu.models.config import ModelConfig

pytestmark = pytest.mark.anyio

VOCAB = (
    ["<unk>", "<s>", "</s>"]
    + [f"<0x{b:02X}>" for b in range(256)]
    + ["▁hello", "▁world", "▁he", "llo", "▁", "hel", "lo"]
)


def _tiny_gguf(path, cfg: ModelConfig, params=None) -> None:
    meta = {
        "general.architecture": "llama",
        "general.name": "tiny-gguf",
        "llama.embedding_length": cfg.hidden_size,
        "llama.feed_forward_length": cfg.intermediate_size,
        "llama.block_count": cfg.num_layers,
        "llama.attention.head_count": cfg.num_heads,
        "llama.attention.head_count_kv": cfg.num_kv_heads,
        "llama.attention.key_length": cfg.head_dim,
        "llama.attention.layer_norm_rms_epsilon": cfg.rms_eps,
        "llama.rope.freq_base": cfg.rope_theta,
        "llama.context_length": cfg.max_position,
        "llama.vocab_size": cfg.vocab_size,
        "tokenizer.ggml.tokens": VOCAB,
        "tokenizer.ggml.bos_token_id": 1,
        "tokenizer.ggml.eos_token_id": 2,
    }
    tensors = {}
    if params is not None:
        from dynamo_tpu.llm.gguf import _LAYER_MAP

        tensors["token_embd.weight"] = np.asarray(params["embed"], np.float32)
        tensors["output_norm.weight"] = np.asarray(params["ln_f"], np.float32)
        if "lm_head" in params:
            tensors["output.weight"] = np.asarray(params["lm_head"], np.float32).T
        for i, layer in enumerate(params["layers"]):
            for our, theirs in _LAYER_MAP.items():
                tensors[f"blk.{i}.{theirs}.weight"] = np.asarray(
                    layer[our], np.float32
                ).T  # back to ggml [out, in]
            tensors[f"blk.{i}.attn_norm.weight"] = np.asarray(
                layer["ln_attn"], np.float32
            )
            tensors[f"blk.{i}.ffn_norm.weight"] = np.asarray(
                layer["ln_mlp"], np.float32
            )
    write_gguf(path, meta, tensors)


def test_gguf_metadata_and_config(tmp_path):
    cfg = ModelConfig.tiny_test(vocab_size=len(VOCAB))
    path = tmp_path / "tiny.gguf"
    _tiny_gguf(path, cfg)
    gf = read_gguf(path)
    assert gf.metadata["general.architecture"] == "llama"
    got = model_config_from_gguf(gf)
    for attr in (
        "vocab_size", "hidden_size", "intermediate_size", "num_layers",
        "num_heads", "num_kv_heads", "head_dim", "max_position",
    ):
        assert getattr(got, attr) == getattr(cfg, attr), attr
    assert got.rope_theta == cfg.rope_theta


def test_gguf_tokenizer_roundtrip(tmp_path):
    cfg = ModelConfig.tiny_test(vocab_size=len(VOCAB))
    path = tmp_path / "tok.gguf"
    _tiny_gguf(path, cfg)
    tok = GgufTokenizer(read_gguf(path, load_tensors_index=False))
    assert tok.eos_token_ids == [2]
    ids = tok.encode("hello world")
    assert ids and tok.decode(ids) == "hello world"
    # byte fallback: a char not in the vocab round-trips via <0xNN> tokens
    ids = tok.encode("hello Zx")
    assert tok.decode(ids) == "hello Zx"
    # incremental decode matches batch decode
    stream = tok.decode_stream()
    text = "".join(p for p in (stream.step(t) for t in ids) if p)
    assert text == "hello Zx"


async def test_gguf_weights_serve_identically(tmp_path):
    """Weights loaded from GGUF must generate the SAME tokens as the source
    params — the loader is lossless for unquantized files."""
    import jax

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import TpuEngine
    from dynamo_tpu.llm.local_model import LocalModel
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.models import llama
    from dynamo_tpu.runtime.engine import Context

    cfg = ModelConfig.tiny_test(vocab_size=len(VOCAB))
    src = llama.init_params(jax.random.PRNGKey(7), cfg, dtype="float32")
    path = tmp_path / "model.gguf"
    _tiny_gguf(path, cfg, params=src)

    local = LocalModel.prepare(str(path))
    assert local.name == "model"
    assert local.config.num_layers == cfg.num_layers
    loaded = local.load_params(dtype="float32")
    np.testing.assert_array_equal(
        np.asarray(loaded["layers"][0]["wq"]),
        np.asarray(src["layers"][0]["wq"]),
    )

    async def gen(params):
        eng = TpuEngine(
            EngineConfig(
                model=cfg, num_blocks=32, max_num_seqs=2, max_model_len=64,
                dtype="float32",
            ),
            params=params,
        )
        await eng.start()
        req = PreprocessedRequest(
            token_ids=[1, 260, 261, 262],
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=5, ignore_eos=True),
        )
        toks = []
        async for item in eng.generate(Context(req.to_wire())):
            toks += item["token_ids"]
        await eng.stop()
        return toks

    assert await gen(loaded) == await gen(src)


def test_gguf_quantized_tensor_rejected(tmp_path):
    path = tmp_path / "q.gguf"
    _tiny_gguf(path, ModelConfig.tiny_test(vocab_size=len(VOCAB)))
    gf = read_gguf(path)
    # Force a fake quantized type on a tensor index entry.
    write_gguf(path, gf.metadata, {"token_embd.weight": np.zeros((4, 4))})
    gf = read_gguf(path)
    gf.tensors["token_embd.weight"].ggml_type = 2  # Q4_0
    with pytest.raises(NotImplementedError, match="quantized"):
        gf.load_tensor("token_embd.weight")


def test_gguf_bpe_tokenizer_roundtrip(tmp_path):
    """llama3/qwen2-style byte-level BPE vocab ('Ġ' mapped space,
    tokenizer.ggml.model == 'gpt2') must round-trip exactly — the SPM
    assumptions must not leak in."""
    from dynamo_tpu.llm.gguf import _bytes_to_unicode

    b2u = _bytes_to_unicode()

    def mapped(s: str) -> str:
        return "".join(b2u[b] for b in s.encode("utf-8"))

    vocab = (
        ["<|end|>"]
        + [b2u[b] for b in range(256)]  # all single mapped bytes
        + [mapped(" hello"), mapped(" world"), mapped("hel"), mapped("lo")]
    )
    write_gguf(
        tmp_path / "bpe.gguf",
        {
            "general.architecture": "qwen2",
            "tokenizer.ggml.model": "gpt2",
            "tokenizer.ggml.tokens": vocab,
            "tokenizer.ggml.eos_token_id": 0,
        },
    )
    tok = GgufTokenizer(read_gguf(tmp_path / "bpe.gguf"))
    assert tok.is_bpe
    ids = tok.encode("hello world")
    assert tok.decode(ids) == "hello world"
    # multi-word + unicode round-trips through single-byte tokens
    ids = tok.encode(" hello Zx ✓")
    assert tok.decode(ids) == " hello Zx ✓"
    stream = tok.decode_stream()
    text = "".join(p for p in (stream.step(t) for t in ids) if p)
    assert text == " hello Zx ✓"


def test_gguf_qwen3_maps_qk_norm(tmp_path):
    """Qwen3 GGUFs must carry qk_norm into the ModelConfig — without it
    the per-head q/k RMSNorm is silently skipped and logits are garbage."""
    from dynamo_tpu.llm.gguf import model_config_from_gguf

    write_gguf(
        tmp_path / "q3.gguf",
        {
            "general.architecture": "qwen3",
            "qwen3.attention.head_count": 16,
            "qwen3.attention.head_count_kv": 8,
            "qwen3.embedding_length": 1024,
            "qwen3.block_count": 2,
            "qwen3.feed_forward_length": 3072,
            "tokenizer.ggml.tokens": ["a"] * 128,
        },
    )
    cfg = model_config_from_gguf(read_gguf(tmp_path / "q3.gguf"))
    assert cfg.qk_norm is True
    assert cfg.qkv_bias is False

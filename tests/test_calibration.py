"""Drift guards for the single-sourced transfer calibration.

The 21.7 GB/s batched-KV handoff rate (BENCHMARKS.md "Batched KV block
IO") is recorded in exactly ONE symbol —
``planner.calibration.HANDOFF_GBPS`` — and every consumer (the router's
network-aware selector, the G4 peer pricing law) must read it from
there. A re-calibration run edits one line; these tests fail if a copy
of the number has crept back in anywhere or a consumer stopped
following the symbol.
"""

from __future__ import annotations

import re
from pathlib import Path
from types import SimpleNamespace

import dynamo_tpu
from dynamo_tpu.planner import calibration as cal


def test_router_default_link_is_the_calibrated_channel():
    from dynamo_tpu.llm.kv_router.scheduler import KvRouterConfig

    assert KvRouterConfig().default_link_gbps == cal.HANDOFF_GBPS


def test_peer_pricing_fallback_is_the_calibrated_channel():
    from dynamo_tpu.block_manager.peer import PeerBlockClient

    drt = SimpleNamespace(primary_lease_id=0xAA)
    comp = SimpleNamespace(
        namespace=SimpleNamespace(name="kv"), name="tpu"
    )
    client = PeerBlockClient(drt, comp, None)
    # No measured pull EMA, no peer advertisement: the pricing law must
    # fall back to the recorded channel, byte-for-byte.
    assert client.effective_bps("nobody") == cal.HANDOFF_GBPS * 1e9


def test_handoff_rate_has_exactly_one_source():
    """No module other than planner/calibration.py may carry the
    literal — a second copy silently diverges on re-calibration."""
    root = Path(dynamo_tpu.__file__).parent
    literal = re.compile(r"(?<![\d.])21\.7(?![\d])")
    offenders = [
        str(p.relative_to(root.parent))
        for p in sorted(root.rglob("*.py"))
        if p.name != "calibration.py" and literal.search(p.read_text())
    ]
    assert offenders == [], (
        f"hardcoded 21.7 GB/s copies found (use "
        f"planner.calibration.HANDOFF_GBPS): {offenders}"
    )


def test_transfer_cost_model_uses_the_symbol():
    """calibration.handoff_seconds matches the closed form built from
    the two published symbols — the contract every pricing consumer
    (router selector, G4 peer client) replicates."""
    isl = 3000
    base = cal.handoff_seconds(isl)
    expected = cal.HANDOFF_FIXED_US / 1e6 + (
        isl * cal.kv_bytes_per_token(None)
    ) / (cal.HANDOFF_GBPS * 1e9)
    assert abs(base - expected) < 1e-12

"""Disaggregated prefill/decode tests: the full remote-prefill round trip
with REAL engines (tiny model on the virtual CPU mesh) — decode admits,
prefill computes, KV streams over the transfer plane into decode's blocks,
and the greedy continuation must be bit-identical to a local-only run
(the transferred-KV correctness oracle)."""

import asyncio

import jax
import pytest

from dynamo_tpu.disagg import (
    DecodeOperator,
    DisaggConfig,
    DisaggRouter,
    PrefillQueue,
    PrefillWorker,
)
from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import Context

pytestmark = pytest.mark.anyio


def _ecfg():
    return EngineConfig(
        model=ModelConfig.tiny_test(),
        num_blocks=32,
        max_num_seqs=2,
        max_model_len=128,
        dtype="float32",
    )


async def _generate(engine, prompt, max_tokens=6):
    req = PreprocessedRequest(
        token_ids=prompt,
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
    )
    toks = []
    async for item in engine.generate(Context(req.to_wire())):
        toks += item["token_ids"]
    return toks


def test_disagg_decision():
    r = DisaggRouter.__new__(DisaggRouter)
    r.cfg = DisaggConfig(max_local_prefill_length=100, max_prefill_queue_size=4)
    assert r.prefill_remote(500, 0.0, 0)
    assert not r.prefill_remote(50, 0.0, 0)          # short prompt
    assert not r.prefill_remote(500, 0.9, 0)         # high prefix hit rate
    assert not r.prefill_remote(500, 0.0, 10)        # queue backed up


async def test_disagg_config_watch():
    drt = await DistributedRuntime.in_process()
    router = await DisaggRouter(drt, "ns").start()
    assert router.cfg.max_local_prefill_length == 512
    await router.publish_config(DisaggConfig(max_local_prefill_length=64))
    # A second router on the same store sees the live update.
    router2 = await DisaggRouter(drt, "ns").start()
    assert router2.cfg.max_local_prefill_length == 64
    await router.publish_config(DisaggConfig(max_local_prefill_length=32))
    await asyncio.sleep(0.05)
    assert router2.cfg.max_local_prefill_length == 32
    await drt.shutdown()


@pytest.mark.parametrize("transport", ["tcp", "native", "device"])
async def test_remote_prefill_roundtrip_matches_local(transport):
    params = llama.init_params(
        jax.random.PRNGKey(0), ModelConfig.tiny_test(), dtype="float32"
    )
    prompt = list(range(40))  # 3 blocks (2 full + partial)

    # Oracle: plain local engine.
    local = TpuEngine(_ecfg(), params=params)
    await local.start()
    expected = await _generate(local, prompt)
    await local.stop()

    # Disagg: decode + prefill engines wired through queue + transfer plane.
    drt = await DistributedRuntime.in_process()
    queue = PrefillQueue(drt, "test")
    dis = DisaggRouter.__new__(DisaggRouter)
    dis.cfg = DisaggConfig(max_local_prefill_length=16, max_prefill_queue_size=8)

    decode = TpuEngine(_ecfg(), params=params)
    await decode.start()
    prefill = TpuEngine(_ecfg(), params=params)
    await prefill.start()

    op = await DecodeOperator(decode, queue, dis, transport=transport).start()
    if transport == "device":
        # Same-process pair ⇒ HBM→HBM channel advertised; the wire path
        # (whatever resolved) is only the cross-process fallback.
        assert op.device_receiver is not None
    else:
        assert op.transport == transport
        assert op.device_receiver is None  # pinned wire path
    pw = PrefillWorker(prefill, queue).start()

    req = PreprocessedRequest(
        token_ids=prompt,
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=6, ignore_eos=True),
    )
    toks = []
    async for item in op.generate(Context(req.to_wire())):
        toks += item["token_ids"]

    assert toks == expected
    assert op.remote_count == 1 and op.local_count == 0
    assert pw.served == 1
    if transport == "device":
        assert op.device_receiver.blocks_received > 0  # device path used

    # Short prompt stays local.
    short = await _generate(op, list(range(8)))
    assert op.local_count == 1
    assert len(short) == 6

    await pw.stop()
    await op.stop()
    await decode.stop()
    await prefill.stop()
    await drt.shutdown()


async def test_staging_pressure_degrades_to_tcp_not_local():
    """r05 regression: a transfer the native staging arena can't fund
    must stay REMOTE over the staging-free tcp wire, not silently shed to
    local prefill (which turned the ISL-3000 disagg bench into
    aggregated serving). Tokens still match the local oracle."""
    params = llama.init_params(
        jax.random.PRNGKey(0), ModelConfig.tiny_test(), dtype="float32"
    )
    prompt = list(range(40))  # 3 blocks > the 2-slot arena below

    local = TpuEngine(_ecfg(), params=params)
    await local.start()
    expected = await _generate(local, prompt)
    await local.stop()

    drt = await DistributedRuntime.in_process()
    queue = PrefillQueue(drt, "test")
    dis = DisaggRouter.__new__(DisaggRouter)
    dis.cfg = DisaggConfig(max_local_prefill_length=16, max_prefill_queue_size=8)

    decode = TpuEngine(_ecfg(), params=params)
    await decode.start()
    prefill = TpuEngine(_ecfg(), params=params)
    await prefill.start()
    # Wire pinned to "auto"-resolved native with a 2-slot arena; no
    # same-process device shortcut, so the tcp fallback is what carries it.
    op = await DecodeOperator(
        decode, queue, dis, transport="auto", staging_slots=2
    ).start()
    await op.device_receiver.stop()  # force the wire path (and don't
    op.device_receiver = None        # leak the registry entry)
    assert op.transport == "native" and op.tcp_receiver is not None
    pw = PrefillWorker(prefill, queue).start()

    toks = await _generate(op, prompt)
    assert toks == expected
    assert op.remote_count == 1 and op.local_count == 0
    assert pw.served == 1

    await pw.stop()
    await op.stop()
    await decode.stop()
    await prefill.stop()
    await drt.shutdown()


async def test_tcp_receiver_rejects_unauthenticated_peer():
    """The transfer plane is raw memory writes — a peer without the shared
    secret (carried by the queue entry) must not land a single block."""
    from dynamo_tpu.disagg.transfer import KvReceiver, KvSender

    landed = []
    recv = await KvReceiver(
        on_block=lambda r, i, d: landed.append((r, i)),
        on_finish=lambda r, t: landed.append(("finish", r)),
    ).start()
    import numpy as np

    block = np.ones((2, 4), np.float32)
    bad = KvSender()
    with pytest.raises((ConnectionError, asyncio.IncompleteReadError, OSError)):
        await bad.send_blocks(recv.address, "r1", [block], 7, auth="00" * 16)
    await bad.close()
    assert landed == []

    good = KvSender()
    await good.send_blocks(recv.address, "r1", [block], 7, auth=recv.auth)
    await good.close()
    assert ("finish", "r1") in landed
    await recv.stop()


async def test_native_receiver_rejects_unauthenticated_peer():
    from dynamo_tpu.native import transfer as nt

    if not nt.available():
        pytest.skip("native agent unavailable")
    import numpy as np

    server = nt.TransferServer()
    arena = np.zeros(64, np.uint8)
    server.register(7, arena)

    bad = nt.TransferClient("127.0.0.1", server.port, b"\x00" * 16)
    # The server closes the connection on bad auth; the write may buffer
    # locally, but nothing must land and notify must never complete.
    try:
        bad.write(7, 0, np.full(8, 0xAB, np.uint8))
        bad.notify(1, b"x")
    except ConnectionError:
        pass
    bad.close()
    await asyncio.sleep(0.05)
    assert server.poll() is None
    assert not arena.any()

    good = nt.TransferClient("127.0.0.1", server.port, server.token)
    good.write(7, 0, np.full(8, 0xCD, np.uint8))
    good.notify(2, b"ok")
    for _ in range(100):
        ev = server.poll()
        if ev is not None:
            break
        await asyncio.sleep(0.01)
    assert ev == (2, b"ok")
    assert (arena[:8] == 0xCD).all()
    good.close()
    server.close()


async def test_queue_age_sla_signal():
    """Oldest-item age rides the queue (surviving redelivery) and flips
    the disagg decision to local when the pool is stalled — the per-item
    SLA signal depth alone can't give (VERDICT r02 weak #7)."""
    from dynamo_tpu.disagg.router import DisaggConfig, DisaggRouter
    from dynamo_tpu.runtime.transports.bus import InProcQueue

    q = InProcQueue()
    assert await q.oldest_age_s() == 0.0
    await q.enqueue(b"stuck")
    await asyncio.sleep(0.15)
    age = await q.oldest_age_s()
    assert age >= 0.15

    # A stuck consumer holding the only item must not hide the stall:
    # in-flight items count toward the age even at depth 0.
    item_id, _ = await q.dequeue_leased(lease_s=30.0)
    assert await q.depth() == 0
    assert await q.oldest_age_s() >= age
    # Redelivery preserves the ORIGINAL enqueue time (the work's wait, not
    # the last lease's).
    await q.nack(item_id)
    assert await q.oldest_age_s() >= age
    assert (await q.stats())[0] == 1

    router = DisaggRouter.__new__(DisaggRouter)
    router.cfg = DisaggConfig(
        max_local_prefill_length=10,
        max_prefill_queue_size=16,
        max_prefill_queue_age_s=0.5,
    )
    # Long prompt, empty-ish queue: remote while the queue is fresh...
    assert router.prefill_remote(1000, 0.0, queue_size=1, queue_age_s=0.1)
    # ...but a stalled queue (old item) keeps prefill local even at depth 1.
    assert not router.prefill_remote(1000, 0.0, queue_size=1, queue_age_s=0.9)


@pytest.mark.parametrize("tp_pair,transport", [
    ((2, 1), "tcp"),
    ((1, 2), "tcp"),
    # Same-process device channel advertised but tp differs: the sender
    # must fall back to the wire (device snapshots carry the sender's
    # sharding) — tokens still correct, zero device blocks.
    ((2, 1), "device"),
])
async def test_heterogeneous_tp_prefill_decode_roundtrip(tp_pair, transport):
    """xPyD with DIFFERENT tensor-parallel degrees per pool (VERDICT r03
    #5; reference: docs/architecture/disagg_serving.md:100-109): a
    tp-sharded prefill engine feeds a decode engine of another tp over
    the wire path, and greedy tokens must match the plain local engine.
    The wire carries blocks in the LOGICAL [L, 2, bs, H_total, D] layout,
    so the head-axis reshard is the gather on one side and the scatter
    slice on the other."""
    from dynamo_tpu.parallel.mesh import build_mesh

    prefill_tp, decode_tp = tp_pair
    params = llama.init_params(
        jax.random.PRNGKey(0), ModelConfig.tiny_test(), dtype="float32"
    )
    prompt = list(range(40))

    local = TpuEngine(_ecfg(), params=params)
    await local.start()
    expected = await _generate(local, prompt)
    await local.stop()

    drt = await DistributedRuntime.in_process()
    queue = PrefillQueue(drt, "tp-mix")
    dis = DisaggRouter.__new__(DisaggRouter)
    dis.cfg = DisaggConfig(max_local_prefill_length=16, max_prefill_queue_size=8)

    def mesh_for(tp):
        return build_mesh({"tp": tp}, devices=jax.devices()[:tp]) if tp > 1 else None

    decode = TpuEngine(_ecfg(), params=params, mesh=mesh_for(decode_tp))
    await decode.start()
    prefill = TpuEngine(_ecfg(), params=params, mesh=mesh_for(prefill_tp))
    await prefill.start()

    op = await DecodeOperator(decode, queue, dis, transport=transport).start()
    pw = PrefillWorker(prefill, queue).start()

    # The queue entry advertises the decode pool's tp.
    assert op._layout()["tp"] == decode_tp

    req = PreprocessedRequest(
        token_ids=prompt,
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=6, ignore_eos=True),
    )
    toks = []
    async for item in op.generate(Context(req.to_wire())):
        toks += item["token_ids"]

    assert toks == expected, (
        f"tp={prefill_tp} prefill -> tp={decode_tp} decode diverged"
    )
    assert op.remote_count == 1 and pw.served == 1
    if transport == "device":
        # The guard routed around the device channel.
        assert op.device_receiver is not None
        assert op.device_receiver.blocks_received == 0

    await pw.stop()
    await op.stop()
    await decode.stop()
    await prefill.stop()
    await drt.shutdown()

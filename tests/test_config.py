"""Layered config tests (reference: SDK YAML configs with Common +
common-configs inheritance and --Component.key=value overrides,
examples/llm/configs/disagg.yaml:15-52; figment DYN_* env config)."""

import pytest

from dynamo_tpu.utils.config import load_config


def _write(tmp_path, text):
    p = tmp_path / "deploy.yaml"
    p.write_text(text)
    return p


YAML = """
Common:
  model-path: /models/llama
  block-size: 32

Frontend:
  port: 9000

Engine:
  common-configs: [model-path, block-size]
  max-num-seqs: 16
"""


def test_yaml_sections_and_common_inheritance(tmp_path):
    cfg = load_config(_write(tmp_path, YAML))
    eng = cfg.component("Engine")
    # common-configs pulls listed keys from Common; lookup is
    # dash/underscore-insensitive.
    assert eng.get("model_path") == "/models/llama"
    assert eng.get("block-size") == 32
    assert eng.get("max_num_seqs") == 16
    # Frontend did not opt into Common.
    fe = cfg.component("Frontend")
    assert fe.get("port") == 9000
    assert fe.get("model_path") is None
    assert "Common" not in cfg.sections()


def test_common_reference_to_missing_key_rejected(tmp_path):
    bad = "Common:\n  a: 1\nEngine:\n  common-configs: [missing]\n"
    with pytest.raises(KeyError, match="missing"):
        load_config(_write(tmp_path, bad))


def test_env_layer_refines_known_sections_only(tmp_path):
    cfg = load_config(
        _write(tmp_path, YAML),
        env={
            "DYNTPU_ENGINE_MAX_NUM_SEQS": "64",  # typed via yaml parse
            "DYNTPU_ENGINE_DTYPE": "float32",
            "DYNTPU_LOG": "debug",  # logging subsystem var: ignored
            "DYNTPU_NOSUCH_KEY": "1",  # unknown section: ignored
        },
    )
    eng = cfg.component("Engine")
    assert eng.get("max_num_seqs") == 64
    assert eng.get("dtype") == "float32"
    assert cfg.sections() == ["Engine", "Frontend"]


def test_overrides_beat_file_and_env(tmp_path):
    cfg = load_config(
        _write(tmp_path, YAML),
        overrides=["Engine.max-num-seqs=8", "Router.mode=kv"],
        env={"DYNTPU_ENGINE_MAX_NUM_SEQS": "64"},
    )
    assert cfg.component("Engine").get("max_num_seqs") == 8
    assert cfg.component("Router").get("mode") == "kv"  # new section ok


def test_bad_override_shape():
    with pytest.raises(ValueError, match="Component.key=value"):
        load_config(overrides=["noequals"])
    with pytest.raises(ValueError, match="Component.key=value"):
        load_config(overrides=["nodot=1"])


def test_component_config_helpers():
    cfg = load_config(overrides=["Engine.num-blocks=128"])
    eng = cfg.component("Engine")
    assert "num_blocks" in eng and "nope" not in eng
    assert eng.require("num_blocks") == 128
    with pytest.raises(KeyError):
        eng.require("nope")

    class Obj:
        num_blocks = 0
        other = "keep"

    obj = eng.apply_to(Obj())
    assert obj.num_blocks == 128 and obj.other == "keep"


def test_cli_apply_config(tmp_path):
    from dynamo_tpu.cli import _apply_config, build_parser

    path = _write(
        tmp_path,
        """
Run:
  out: echo_core
Frontend:
  port: 18080
Engine:
  max-num-seqs: 4
  warmup: false
""",
    )
    args = build_parser().parse_args(
        ["run", "--config", str(path), "--set", "Engine.max-num-seqs=2"]
    )
    _apply_config(args)
    assert args.output == "echo_core"
    assert args.http_port == 18080
    assert args.max_num_seqs == 2  # --set beats the file
    assert args.no_warmup is True  # Engine.warmup: false

    # Explicit CLI flags beat the file; --set beats even explicit flags.
    args = build_parser().parse_args(
        ["run", "--config", str(path), "--http-port", "9000"]
    )
    _apply_config(args)
    assert args.http_port == 9000  # file's 18080 loses to the flag
    args = build_parser().parse_args(
        [
            "run", "--config", str(path),
            "--max-num-seqs", "64", "--set", "Engine.max-num-seqs=2",
        ]
    )
    _apply_config(args)
    assert args.max_num_seqs == 2

    # A typo'd warmup outside the Engine section is rejected, not applied.
    args = build_parser().parse_args(["run", "--set", "Frontend.warmup=false"])
    with pytest.raises(SystemExit, match="warmup"):
        _apply_config(args)

    args = build_parser().parse_args(
        ["run", "--set", "Engine.no-such-knob=1"]
    )
    with pytest.raises(SystemExit, match="no-such-knob"):
        _apply_config(args)

"""Multi-host serving bootstrap (parallel/multihost.py).

Spawns TWO real OS processes, each owning two virtual CPU devices, joined
through jax.distributed (coordination service + gloo collectives) into one
4-device mesh serving the tiny model — then asserts the greedy tokens are
identical across the processes AND identical to a single-process run of
the same mesh shape. This is the code path a v5p pod slice takes
(reference analogue: MultiNodeConfig multi-node engine bootstrap,
lib/llm/src/engines.rs:42-60, launch/dynamo-run/src/lib.rs:176-258); only
the transport is simulated.
"""

from dynamo_tpu.parallel.multihost import (
    _default_shape,
    run_multihost_check,
    run_serve_harness,
)

STEPS = 16
TOTAL = 4


def test_two_process_mesh_token_identical():
    import jax

    multi_tokens = run_multihost_check(
        total_devices=TOTAL, num_procs=2, steps=STEPS
    )
    # Single-PROCESS baseline over the same mesh shape (4 of the 8 virtual
    # devices the test harness provides).
    single_tokens = run_serve_harness(
        _default_shape(TOTAL), steps=STEPS, devices=jax.devices()[:TOTAL]
    )
    assert multi_tokens == single_tokens, (
        f"2-process serving diverged from single-process:\n"
        f"  multi:  {multi_tokens}\n  single: {single_tokens}"
    )


# ---------------------------------------------------------------------------
# Full-stack multi-host serving (VERDICT r04 weak #7): control plane +
# HTTP frontend here, a 2-process × 4-device mesh worker joined via the
# CLI's --coordinator path (rank 0 = step leader serving the endpoint,
# rank 1 = stepcast follower), one REAL HTTP completion — token-identical
# to a single-process worker of the same mesh shape.
# ---------------------------------------------------------------------------

import asyncio
import os
import socket
import sys

import pytest

pytestmark_async = pytest.mark.anyio

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


async def _spawn_worker(cp_addr: str, rank: int, num_nodes: int,
                        coordinator: str, devices: int):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    env["XLA_FLAGS"] = " ".join(
        flags + [f"--xla_force_host_platform_device_count={devices}"]
    )
    args = [
        sys.executable, "-m", "dynamo_tpu", "run",
        "--in", "dyn://dynamo.tpu.generate", "--out", "tpu",
        "--model-path", "preset:tiny-test",
        "--control-plane", cp_addr,
        "--mesh", "tp=2,dp=2",
        "--dtype", "float32",
        "--max-model-len", "64",
        "--num-blocks", "64",
        "--max-num-seqs", "4",
        "--kv-cache-block-size", "4",
        "--no-warmup",
    ]
    if num_nodes > 1:
        args += [
            "--coordinator", coordinator,
            "--num-nodes", str(num_nodes),
            "--node-rank", str(rank),
        ]
    proc = await asyncio.create_subprocess_exec(
        *args,
        stdout=asyncio.subprocess.PIPE,
        stderr=asyncio.subprocess.STDOUT,
        env=env,
        cwd=REPO,
    )
    return proc, []


async def _wait_ready(proc, log: list, rank: int) -> None:
    ready = "registered at" if rank == 0 else "follower rank"
    while True:
        line = await proc.stdout.readline()
        if not line:
            raise RuntimeError(
                f"worker rank {rank} died:\n" + "".join(log[-60:])
            )
        text = line.decode(errors="replace")
        log.append(text)
        if ready in text:
            return


async def _complete_via_http(cp_addr: str) -> list[int]:
    """Frontend half of the CLI stack, in-process: watcher + HTTP service
    against the shared control plane; returns the completion's tokens."""
    import httpx

    from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher
    from dynamo_tpu.llm.http_service import HttpService
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    drt = await DistributedRuntime.connect(cp_addr)
    manager = ModelManager()
    watcher = ModelWatcher(drt, manager)
    await watcher.start()
    for _ in range(100):
        if manager.models():
            break
        await asyncio.sleep(0.1)
    assert manager.models(), "worker model never appeared in discovery"
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    try:
        async with httpx.AsyncClient(timeout=240.0) as client:
            r = await client.post(
                f"http://127.0.0.1:{service.port}/v1/completions",
                json={
                    "model": "tiny-test",
                    "prompt": "hello tpu",
                    "max_tokens": 8,
                    "temperature": 0,
                    "nvext": {"ignore_eos": True},
                },
            )
            assert r.status_code == 200, r.text
            text = r.json()["choices"][0]["text"]
    finally:
        await service.stop()
        await drt.shutdown()
    # Byte-level toy tokenizer: the text is the token identity.
    return list(text.encode())


async def _serve_once(num_nodes: int) -> list[int]:
    from dynamo_tpu.runtime.transports.control_plane import (
        ControlPlaneServer,
    )

    server = await ControlPlaneServer().start()
    procs = []
    try:
        coordinator = f"127.0.0.1:{_free_port()}"
        per = 4 // num_nodes
        # Spawn every rank BEFORE waiting: rank 0's sharded runner build
        # blocks on cross-process collectives until rank 1 is up.
        for rank in range(num_nodes):
            procs.append(
                await _spawn_worker(
                    server.address, rank, num_nodes, coordinator, per
                )
            )
        await asyncio.wait_for(
            asyncio.gather(*[
                _wait_ready(proc, log, rank)
                for rank, (proc, log) in enumerate(procs)
            ]),
            300,
        )
        return await _complete_via_http(server.address)
    finally:
        for proc, log in procs:
            if proc.returncode is None:
                proc.terminate()
                try:
                    await asyncio.wait_for(proc.wait(), 20)
                except asyncio.TimeoutError:
                    proc.kill()
        await server.stop()


@pytest.mark.anyio
async def test_full_stack_multihost_http_matches_single_process():
    multi = await _serve_once(num_nodes=2)
    single = await _serve_once(num_nodes=1)
    assert multi, "empty completion"
    assert multi == single, (
        f"multihost HTTP completion diverged:\n"
        f"  multi:  {multi}\n  single: {single}"
    )

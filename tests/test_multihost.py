"""Multi-host serving bootstrap (parallel/multihost.py).

Spawns TWO real OS processes, each owning two virtual CPU devices, joined
through jax.distributed (coordination service + gloo collectives) into one
4-device mesh serving the tiny model — then asserts the greedy tokens are
identical across the processes AND identical to a single-process run of
the same mesh shape. This is the code path a v5p pod slice takes
(reference analogue: MultiNodeConfig multi-node engine bootstrap,
lib/llm/src/engines.rs:42-60, launch/dynamo-run/src/lib.rs:176-258); only
the transport is simulated.
"""

from dynamo_tpu.parallel.multihost import (
    _default_shape,
    run_multihost_check,
    run_serve_harness,
)

STEPS = 16
TOTAL = 4


def test_two_process_mesh_token_identical():
    import jax

    multi_tokens = run_multihost_check(
        total_devices=TOTAL, num_procs=2, steps=STEPS
    )
    # Single-PROCESS baseline over the same mesh shape (4 of the 8 virtual
    # devices the test harness provides).
    single_tokens = run_serve_harness(
        _default_shape(TOTAL), steps=STEPS, devices=jax.devices()[:TOTAL]
    )
    assert multi_tokens == single_tokens, (
        f"2-process serving diverged from single-process:\n"
        f"  multi:  {multi_tokens}\n  single: {single_tokens}"
    )

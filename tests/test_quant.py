"""int8 weight-only quantization (ops/quant.py): reconstruction accuracy,
paged-engine-vs-oracle exactness under quant, sharded/single-chip token
equality, and spec-tree mirroring.

The reference reaches quantized serving through its backend engines (its
headline disagg numbers are FP8-70B via vLLM, reference:
docs/architecture/architecture.md:75-79); our engine is native, so the
quantized path is first-class and tested like any other model path.
"""

import asyncio

import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.engine.runner import ModelRunner
from dynamo_tpu.llm.protocols.common import (
    EngineOutput,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.ops.quant import (
    dequantize_weight,
    is_quantized,
    qmm,
    quantize_param_specs,
    quantize_params,
    quantize_weight,
)
from dynamo_tpu.parallel.mesh import build_mesh
from dynamo_tpu.parallel.sharding import llama_param_specs
from dynamo_tpu.runtime.engine import Context

pytestmark = pytest.mark.anyio

CFG = ModelConfig.tiny_test()
PARAMS = llama.init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)
QPARAMS = jax.jit(quantize_params)(PARAMS)


def test_weight_roundtrip_error_small():
    w = jax.random.normal(jax.random.PRNGKey(1), (96, 160), jnp.float32) * 0.2
    qw = quantize_weight(w)
    assert qw["q"].dtype == jnp.int8
    assert qw["s"].shape == (160,)
    rel = float(
        jnp.max(jnp.abs(dequantize_weight(qw) - w)) / jnp.max(jnp.abs(w))
    )
    assert rel < 0.01, rel
    # qmm agrees with the dequantized matmul
    x = jax.random.normal(jax.random.PRNGKey(2), (8, 96), jnp.float32)
    got = qmm(x, qw)
    want = x @ dequantize_weight(qw)
    assert float(jnp.max(jnp.abs(got - want))) < 1e-3


def test_quantized_logits_close_to_fp():
    toks = jnp.arange(2, 34, dtype=jnp.int32)
    ref = llama.reference_forward(CFG, PARAMS, toks)
    qref = llama.reference_forward(CFG, QPARAMS, toks)
    cos = float(
        jnp.sum(ref * qref) / (jnp.linalg.norm(ref) * jnp.linalg.norm(qref))
    )
    assert cos > 0.995, cos


def test_quantize_params_structure_and_specs_mirror():
    layer = QPARAMS["layers"][0]
    for k in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"):
        assert is_quantized(layer[k]), k
    assert not is_quantized(QPARAMS["embed"])
    assert not is_quantized(layer["ln_attn"])
    assert is_quantized(QPARAMS["lm_head"])
    # spec tree mirrors the quantized params tree exactly
    specs = quantize_param_specs(llama_param_specs(CFG))
    jax.tree.map(lambda p, s: None, QPARAMS, specs)  # raises on mismatch
    # s-spec drops the contraction axis: wq (None, tp) -> s (tp,)
    assert tuple(specs["layers"][0]["wq"]["s"]) == ("tp",)
    assert tuple(specs["layers"][0]["wo"]["s"]) in ((), (None,))  # replicated


def oracle_greedy_quant(prompt: list[int], n: int) -> list[int]:
    """Greedy continuation through the QUANTIZED no-cache oracle — the
    paged int8 engine must match it exactly (same math, fp32 accum)."""
    tokens = list(prompt)
    out = []
    for _ in range(n):
        logits = llama.reference_forward(CFG, QPARAMS, jnp.asarray(tokens))
        nxt = int(jnp.argmax(logits[-1]))
        tokens.append(nxt)
        out.append(nxt)
    return out


async def _collect(engine, prompt, max_tokens=8):
    pre = PreprocessedRequest(
        token_ids=prompt,
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
    )
    tokens = []
    async for raw in engine.generate(Context(pre.to_wire())):
        tokens.extend(EngineOutput.from_wire(raw).token_ids)
    return tokens


async def test_quantized_engine_matches_quantized_oracle():
    cfg = EngineConfig(
        model=CFG, dtype="float32", block_size=4, num_blocks=64,
        max_num_seqs=4, max_model_len=128, quant="int8",
    )
    engine = TpuEngine(cfg, params=PARAMS)
    await engine.start()
    try:
        prompt = [1, 5, 9, 2, 7]
        tokens = await _collect(engine, prompt, max_tokens=10)
        assert tokens == oracle_greedy_quant(prompt, 10)
    finally:
        await engine.stop()


def test_sharded_quantized_prefill_matches_single_chip():
    ecfg = EngineConfig(
        model=CFG, dtype="float32", block_size=16, num_blocks=32,
        max_num_seqs=2, max_model_len=128, quant="int8",
    )
    blocks = [1, 2, 3, 4]
    prompt = list(range(2, 18))
    single = ModelRunner(ecfg)
    tok_single = single.prefill(prompt, blocks, 0, (0.0, 0, 1.0))
    mesh = build_mesh({"tp": 2, "dp": 4})
    sharded = ModelRunner(ecfg, mesh=mesh)
    tok_sharded = sharded.prefill(prompt, blocks, 0, (0.0, 0, 1.0))
    assert tok_single == tok_sharded


def test_quantized_moe_forward_finite():
    mcfg = ModelConfig.tiny_moe_test()
    mparams = llama.init_params(jax.random.PRNGKey(3), mcfg, dtype=jnp.float32)
    mq = jax.jit(quantize_params)(mparams)
    out = llama.reference_forward(mcfg, mq, jnp.arange(2, 18, dtype=jnp.int32))
    assert bool(jnp.all(jnp.isfinite(out)))
    ref = llama.reference_forward(mcfg, mparams, jnp.arange(2, 18, dtype=jnp.int32))
    cos = float(
        jnp.sum(ref * out) / (jnp.linalg.norm(ref) * jnp.linalg.norm(out))
    )
    assert cos > 0.99, cos


def test_tied_embed_quantization_roundtrip():
    """tie_word_embeddings models quantize the embed table per-row so the
    tied lm_head matmul streams int8 (ops/quant.py tied_head_mm); greedy
    tokens still match the same-quantized oracle exactly."""
    tcfg = ModelConfig.tiny_test().scaled(tie_word_embeddings=True)
    tparams = llama.init_params(jax.random.PRNGKey(5), tcfg, dtype=jnp.float32)
    from functools import partial

    tq = jax.jit(partial(quantize_params, tie_embed=True))(tparams)
    assert is_quantized(tq["embed"])
    assert tq["embed"]["s"].shape == (tcfg.vocab_size,)
    ref = llama.reference_forward(tcfg, tparams, jnp.arange(2, 34, dtype=jnp.int32))
    qref = llama.reference_forward(tcfg, tq, jnp.arange(2, 34, dtype=jnp.int32))
    cos = float(
        jnp.sum(ref * qref) / (jnp.linalg.norm(ref) * jnp.linalg.norm(qref))
    )
    assert cos > 0.99, cos

    # sharded (tp over the embed feature dim) matches single-chip
    ecfg = EngineConfig(
        model=tcfg, dtype="float32", block_size=16, num_blocks=32,
        max_num_seqs=2, max_model_len=128, quant="int8",
    )
    prompt = list(range(2, 18))
    tok_single = ModelRunner(ecfg, params=tparams).prefill(
        prompt, [1, 2, 3, 4], 0, (0.0, 0, 1.0)
    )
    mesh = build_mesh({"tp": 2, "dp": 4})
    tok_sharded = ModelRunner(ecfg, params=tparams, mesh=mesh).prefill(
        prompt, [1, 2, 3, 4], 0, (0.0, 0, 1.0)
    )
    assert tok_single == tok_sharded


def test_engine_config_rejects_unknown_quant():
    cfg = EngineConfig(model=CFG, quant="fp4")
    with pytest.raises(ValueError):
        cfg.validate()

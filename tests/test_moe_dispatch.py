"""Capacity-dispatch MoE (models/moe.py _moe_mlp_capacity): must agree
with the dense gate-masked formulation when capacity is ample, degrade by
the standard overflow-drop rule when it isn't, stay exact end-to-end
through the engine, and shard over ep like the dense path."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.moe import MoeConfig, init_moe_params, moe_mlp
from dynamo_tpu.parallel.mesh import build_mesh

pytestmark = pytest.mark.anyio


def _cfgs(**kw):
    base = dict(
        hidden_size=32, intermediate_size=48, num_experts=4,
        num_experts_per_tok=2,
    )
    base.update(kw)
    dense = MoeConfig(**base, dispatch="dense")
    cap = MoeConfig(**base, dispatch="capacity", capacity_factor=4.0)
    return dense, cap


def test_capacity_matches_dense_when_ample():
    dense, cap = _cfgs()
    params = init_moe_params(jax.random.PRNGKey(0), dense)
    x = jax.random.normal(jax.random.PRNGKey(1), (16, 32), jnp.float32)
    out_d = moe_mlp(params, x, dense)
    out_c = moe_mlp(params, x, cap)
    np.testing.assert_allclose(np.asarray(out_d), np.asarray(out_c), atol=2e-5)


def test_capacity_matches_dense_sigmoid_grouped():
    dense, cap = _cfgs(
        gating="sigmoid", n_group=2, topk_group=1, routed_scaling_factor=2.5
    )
    params = init_moe_params(jax.random.PRNGKey(2), dense)
    params["router_bias"] = jnp.asarray([0.1, 0.0, 0.4, 0.0], jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(3), (12, 32), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(moe_mlp(params, x, dense)),
        np.asarray(moe_mlp(params, x, cap)),
        atol=2e-5,
    )


def test_capacity_overflow_drops_tokens():
    """With capacity_factor shrunk below fair share, some (token, expert)
    assignments drop — output differs from dense but stays finite and
    earlier tokens (which claim slots first) keep their dense value."""
    dense, _ = _cfgs()
    tight = MoeConfig(
        hidden_size=32, intermediate_size=48, num_experts=4,
        num_experts_per_tok=2, dispatch="capacity", capacity_factor=0.25,
    )
    params = init_moe_params(jax.random.PRNGKey(0), dense)
    x = jnp.tile(
        jax.random.normal(jax.random.PRNGKey(4), (1, 32), jnp.float32), (16, 1)
    )  # identical tokens → identical routing → guaranteed overflow
    out_d = moe_mlp(params, x, dense)
    out_t = moe_mlp(params, x, tight)
    assert bool(jnp.all(jnp.isfinite(out_t)))
    # first token gets both its slots; dense value preserved
    np.testing.assert_allclose(
        np.asarray(out_d[0]), np.asarray(out_t[0]), atol=2e-5
    )
    # the last token lost at least one expert
    assert float(jnp.max(jnp.abs(out_d[-1] - out_t[-1]))) > 1e-6


async def test_capacity_dispatch_engine_end_to_end():
    """A MoE model served with capacity dispatch produces the same greedy
    tokens as its own oracle (reference_forward shares the dispatch via
    ModelConfig), proving the paged serving path composes with it."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import TpuEngine
    from dynamo_tpu.llm.protocols.common import (
        EngineOutput,
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    cfg = ModelConfig.tiny_moe_test().scaled(
        moe_dispatch="capacity", moe_capacity_factor=4.0
    )
    params = llama.init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)

    def oracle(prompt, n):
        toks = list(prompt)
        out = []
        for _ in range(n):
            logits = llama.reference_forward(cfg, params, jnp.asarray(toks))
            nxt = int(jnp.argmax(logits[-1]))
            toks.append(nxt)
            out.append(nxt)
        return out

    engine = TpuEngine(
        EngineConfig(
            model=cfg, dtype="float32", block_size=4, num_blocks=64,
            max_num_seqs=2, max_model_len=128,
        ),
        params=params,
    )
    await engine.start()
    try:
        prompt = [1, 5, 9, 2, 7]
        pre = PreprocessedRequest(
            token_ids=prompt,
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=8, ignore_eos=True),
        )
        tokens = []
        async for raw in engine.generate(Context(pre.to_wire())):
            tokens.extend(EngineOutput.from_wire(raw).token_ids)
        assert tokens == oracle(prompt, 8)
    finally:
        await engine.stop()


def test_capacity_dispatch_sharded_matches_single():
    """ep×tp-sharded capacity dispatch = single-device capacity dispatch
    (the scatter/gather cross ep shards; GSPMD inserts the collectives)."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.runner import ModelRunner

    cfg = ModelConfig.tiny_moe_test().scaled(
        moe_dispatch="capacity", moe_capacity_factor=4.0
    )
    params = llama.init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    ecfg = EngineConfig(
        model=cfg, dtype="float32", block_size=16, num_blocks=32,
        max_num_seqs=2, max_model_len=128,
    )
    prompt = list(range(2, 18))
    tok = ModelRunner(ecfg, params=params).prefill(
        prompt, [1, 2, 3, 4], 0, (0.0, 0, 1.0)
    )
    mesh = build_mesh({"ep": 2, "tp": 2, "dp": 2})
    tok2 = ModelRunner(ecfg, params=params, mesh=mesh).prefill(
        prompt, [1, 2, 3, 4], 0, (0.0, 0, 1.0)
    )
    assert tok == tok2


def test_auto_dispatch_crossover():
    """"auto" (the default) resolves by expert count: dense below 16
    experts (dense's E/topk FLOP waste is cheaper than dispatch), capacity
    at 16+ (measured crossover — benchmarks/moe_bench.py; on an ep mesh
    capacity wins ~3.9x at E=128)."""
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.models.moe import MoeConfig

    assert MoeConfig(num_experts=8).resolved_dispatch == "dense"
    assert MoeConfig(num_experts=16).resolved_dispatch == "capacity"
    assert MoeConfig(num_experts=256).resolved_dispatch == "capacity"
    assert MoeConfig(num_experts=256, dispatch="dense").resolved_dispatch == "dense"
    assert ModelConfig.tiny_moe_test().moe_dispatch == "auto"


def test_auto_capacity_ep_mesh_matches_dense(monkeypatch):
    """A 16-expert model under an ep mesh takes the capacity path via
    "auto" with ep-pinned buffers and must produce the same output as the
    dense formulation (ample capacity)."""
    import numpy as np

    from dynamo_tpu.models.moe import (
        MoeConfig,
        init_moe_params,
        moe_mlp,
        shard_moe_params,
    )
    from dynamo_tpu.parallel.mesh import build_mesh

    mesh = build_mesh({"ep": 4, "dp": 2})
    kw = dict(
        hidden_size=32, intermediate_size=16, num_experts=16,
        num_experts_per_tok=4,
    )
    params = init_moe_params(jax.random.PRNGKey(0), MoeConfig(**kw))
    x = jnp.asarray(
        np.random.default_rng(1).standard_normal((24, 32)), jnp.float32
    )
    auto_cfg = MoeConfig(**kw, capacity_factor=4.0)  # auto -> capacity
    assert auto_cfg.resolved_dispatch == "capacity"
    sharded = shard_moe_params(params, mesh)
    got = jax.jit(lambda p, xx: moe_mlp(p, xx, auto_cfg, mesh=mesh))(
        sharded, x
    )
    want = moe_mlp(params, x, MoeConfig(**kw, dispatch="dense"))
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_auto_falls_back_to_dense_at_decode_token_counts():
    """At decode-size T, capacity C collapses toward 1 and collisions DROP
    routed contributions — "auto" must run dense there and stay exact."""
    import numpy as np

    from dynamo_tpu.models.moe import MoeConfig, init_moe_params, moe_mlp

    kw = dict(
        hidden_size=32, intermediate_size=16, num_experts=32,
        num_experts_per_tok=2,
    )
    cfg = MoeConfig(**kw)  # auto; E=32 >= 16 but T is tiny
    assert cfg.resolved_dispatch == "capacity"
    assert not cfg.auto_capacity_ok(8)   # 8*2 < 2*32
    assert cfg.auto_capacity_ok(64)      # 64*2 >= 2*32
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jnp.asarray(
        np.random.default_rng(2).standard_normal((8, 32)), jnp.float32
    )
    got = moe_mlp(params, x, cfg)
    want = moe_mlp(params, x, MoeConfig(**kw, dispatch="dense"))
    # Bit-exact: auto at T=8 must have taken the dense path (capacity with
    # C=1 would drop colliding tokens and diverge).
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

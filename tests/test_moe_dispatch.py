"""Capacity-dispatch MoE (models/moe.py _moe_mlp_capacity): must agree
with the dense gate-masked formulation when capacity is ample, degrade by
the standard overflow-drop rule when it isn't, stay exact end-to-end
through the engine, and shard over ep like the dense path."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.moe import MoeConfig, init_moe_params, moe_mlp
from dynamo_tpu.parallel.mesh import build_mesh

pytestmark = pytest.mark.anyio


def _cfgs(**kw):
    base = dict(
        hidden_size=32, intermediate_size=48, num_experts=4,
        num_experts_per_tok=2,
    )
    base.update(kw)
    dense = MoeConfig(**base, dispatch="dense")
    cap = MoeConfig(**base, dispatch="capacity", capacity_factor=4.0)
    return dense, cap


def test_capacity_matches_dense_when_ample():
    dense, cap = _cfgs()
    params = init_moe_params(jax.random.PRNGKey(0), dense)
    x = jax.random.normal(jax.random.PRNGKey(1), (16, 32), jnp.float32)
    out_d = moe_mlp(params, x, dense)
    out_c = moe_mlp(params, x, cap)
    np.testing.assert_allclose(np.asarray(out_d), np.asarray(out_c), atol=2e-5)


def test_capacity_matches_dense_sigmoid_grouped():
    dense, cap = _cfgs(
        gating="sigmoid", n_group=2, topk_group=1, routed_scaling_factor=2.5
    )
    params = init_moe_params(jax.random.PRNGKey(2), dense)
    params["router_bias"] = jnp.asarray([0.1, 0.0, 0.4, 0.0], jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(3), (12, 32), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(moe_mlp(params, x, dense)),
        np.asarray(moe_mlp(params, x, cap)),
        atol=2e-5,
    )


def test_capacity_overflow_drops_tokens():
    """With capacity_factor shrunk below fair share, some (token, expert)
    assignments drop — output differs from dense but stays finite and
    earlier tokens (which claim slots first) keep their dense value."""
    dense, _ = _cfgs()
    tight = MoeConfig(
        hidden_size=32, intermediate_size=48, num_experts=4,
        num_experts_per_tok=2, dispatch="capacity", capacity_factor=0.25,
    )
    params = init_moe_params(jax.random.PRNGKey(0), dense)
    x = jnp.tile(
        jax.random.normal(jax.random.PRNGKey(4), (1, 32), jnp.float32), (16, 1)
    )  # identical tokens → identical routing → guaranteed overflow
    out_d = moe_mlp(params, x, dense)
    out_t = moe_mlp(params, x, tight)
    assert bool(jnp.all(jnp.isfinite(out_t)))
    # first token gets both its slots; dense value preserved
    np.testing.assert_allclose(
        np.asarray(out_d[0]), np.asarray(out_t[0]), atol=2e-5
    )
    # the last token lost at least one expert
    assert float(jnp.max(jnp.abs(out_d[-1] - out_t[-1]))) > 1e-6


async def test_capacity_dispatch_engine_end_to_end():
    """A MoE model served with capacity dispatch produces the same greedy
    tokens as its own oracle (reference_forward shares the dispatch via
    ModelConfig), proving the paged serving path composes with it."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import TpuEngine
    from dynamo_tpu.llm.protocols.common import (
        EngineOutput,
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    cfg = ModelConfig.tiny_moe_test().scaled(
        moe_dispatch="capacity", moe_capacity_factor=4.0
    )
    params = llama.init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)

    def oracle(prompt, n):
        toks = list(prompt)
        out = []
        for _ in range(n):
            logits = llama.reference_forward(cfg, params, jnp.asarray(toks))
            nxt = int(jnp.argmax(logits[-1]))
            toks.append(nxt)
            out.append(nxt)
        return out

    engine = TpuEngine(
        EngineConfig(
            model=cfg, dtype="float32", block_size=4, num_blocks=64,
            max_num_seqs=2, max_model_len=128,
        ),
        params=params,
    )
    await engine.start()
    try:
        prompt = [1, 5, 9, 2, 7]
        pre = PreprocessedRequest(
            token_ids=prompt,
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=8, ignore_eos=True),
        )
        tokens = []
        async for raw in engine.generate(Context(pre.to_wire())):
            tokens.extend(EngineOutput.from_wire(raw).token_ids)
        assert tokens == oracle(prompt, 8)
    finally:
        await engine.stop()


def test_capacity_dispatch_sharded_matches_single():
    """ep×tp-sharded capacity dispatch = single-device capacity dispatch
    (the scatter/gather cross ep shards; GSPMD inserts the collectives)."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.runner import ModelRunner

    cfg = ModelConfig.tiny_moe_test().scaled(
        moe_dispatch="capacity", moe_capacity_factor=4.0
    )
    params = llama.init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    ecfg = EngineConfig(
        model=cfg, dtype="float32", block_size=16, num_blocks=32,
        max_num_seqs=2, max_model_len=128,
    )
    prompt = list(range(2, 18))
    tok = ModelRunner(ecfg, params=params).prefill(
        prompt, [1, 2, 3, 4], 0, (0.0, 0, 1.0)
    )
    mesh = build_mesh({"ep": 2, "tp": 2, "dp": 2})
    tok2 = ModelRunner(ecfg, params=params, mesh=mesh).prefill(
        prompt, [1, 2, 3, 4], 0, (0.0, 0, 1.0)
    )
    assert tok == tok2

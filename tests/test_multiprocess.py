"""TRUE multi-process tests: worker processes join a control-plane server
over TCP; the driver (this process) routes requests to them, observes KV
affinity across the process boundary, and verifies that killing a worker
expires its lease, deregisters its instances, and drains routing to the
survivor with zero failed requests (reference behavior:
docs/architecture/disagg_serving.md:111-194 runtime-reconfigurable xPyD;
transports/etcd.rs:100-131 lease-death deregistration).
"""

import asyncio
import os
import signal
import subprocess
import sys

import pytest

from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.llm.tokens import TokenBlockSequence
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.egress import PushRouter, RouterMode
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.transports.control_plane import ControlPlaneServer

pytestmark = pytest.mark.anyio

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "procs", "mocker_worker.py")
PREFILL = os.path.join(REPO, "tests", "procs", "prefill_worker.py")
SHARDED = os.path.join(REPO, "tests", "procs", "sharded_worker.py")


async def _spawn_proc(script: str, *args: str):
    """Start a worker subprocess; wait for READY; return (proc, worker_id)."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # let the script pick cpu itself
    proc = await asyncio.create_subprocess_exec(
        sys.executable,
        script,
        *args,
        stdout=asyncio.subprocess.PIPE,
        stderr=asyncio.subprocess.STDOUT,
        env=env,
        cwd=REPO,
    )
    while True:
        line = await asyncio.wait_for(proc.stdout.readline(), 120)
        if not line:
            raise RuntimeError("worker died before READY")
        text = line.decode().strip()
        if text.startswith("READY "):
            return proc, int(text.split()[1])


def _req(prompt, max_tokens=4):
    return PreprocessedRequest(
        token_ids=prompt,
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
    ).to_wire()


async def _send(push, prompt, **kw):
    """Returns (tokens, serving worker id)."""
    toks, wid = [], None
    async for item in push.generate(Context(_req(prompt)), **kw):
        toks += item.get("token_ids") or []
        wid = item.get("worker_id", wid)
    return toks, wid


@pytest.fixture
async def plane():
    server = await ControlPlaneServer().start()
    frontend = await DistributedRuntime.connect(server.address)
    procs = []

    async def spawn(seed, ttl=1.0, script=WORKER, extra=()):
        args = ["--addr", server.address, "--ttl", str(ttl), *extra]
        if script == WORKER:
            args += ["--seed", str(seed)]
        proc, wid = await _spawn_proc(script, *args)
        procs.append(proc)
        return proc, wid

    yield server, frontend, spawn
    for proc in procs:
        if proc.returncode is None:
            proc.kill()
        await proc.wait()
    await frontend.shutdown()
    await server.stop()


async def test_cross_process_round_robin_and_worker_death(plane):
    server, frontend, spawn = plane
    proc_a, wid_a = await spawn(seed=1)
    proc_b, wid_b = await spawn(seed=2)
    assert wid_a != wid_b

    push = await PushRouter.create(
        frontend, "test.worker.generate", mode=RouterMode.ROUND_ROBIN
    )
    served = set()
    for i in range(4):
        toks, wid = await _send(push, list(range(16)))
        assert toks, "no tokens streamed back across the process boundary"
        served.add(wid)
    assert served == {wid_a, wid_b}

    # Kill worker A hard (no graceful deregistration): its lease (ttl=1s)
    # must expire, the instance key must vanish, and every subsequent
    # request must land on B without a single failure.
    proc_a.kill()
    await proc_a.wait()
    deadline = asyncio.get_running_loop().time() + 10
    while wid_a in push.client.instance_ids():
        assert asyncio.get_running_loop().time() < deadline, (
            "dead worker instance never deregistered"
        )
        await asyncio.sleep(0.1)

    for _ in range(4):
        toks, wid = await _send(push, list(range(16)))
        assert toks and wid == wid_b


async def test_cross_process_kv_affinity(plane):
    """The round-1 in-process affinity test (tests/test_kv_router.py),
    now with the two mocker workers in separate OS processes: KV events
    and load metrics flow over the wire into the driver's KvRouter."""
    from dynamo_tpu.llm.kv_router.router import KvRouter

    server, frontend, spawn = plane
    _, wid_a = await spawn(seed=1)
    _, wid_b = await spawn(seed=2)

    comp = frontend.namespace("test").component("worker")
    router = await KvRouter(frontend, comp).start()
    push = await PushRouter.create(
        frontend,
        "test.worker.generate",
        mode=RouterMode.KV,
        selector=router.selector_fn,
    )

    prompt = list(range(64))  # 4 full blocks
    toks, first_wid = await _send(push, prompt)
    assert toks and first_wid in (wid_a, wid_b)

    # KV events from the worker process must reach this process's indexer.
    hashes = TokenBlockSequence.from_tokens(prompt, block_size=16).sequence_hashes()
    deadline = asyncio.get_running_loop().time() + 5
    while True:
        overlaps = await router.indexer.find_matches(hashes)
        if overlaps:
            break
        assert asyncio.get_running_loop().time() < deadline, (
            "KV events never crossed the process boundary"
        )
        await asyncio.sleep(0.05)
    assert list(overlaps) == [first_wid]

    # Affinity: identical prompts stick to the block-holding worker.
    for _ in range(3):
        _, wid = await _send(push, prompt)
        assert wid == first_wid

    await router.stop()


@pytest.mark.parametrize("transport", ["tcp", "native"])
async def test_cross_process_disagg_roundtrip(plane, transport):
    """Remote prefill in a REAL separate process: the decode engine (this
    process) routes a long prompt through the shared queue; the prefill
    process computes KV and pushes it over the transfer plane; the greedy
    continuation must be bit-identical to a local-only run."""
    import jax

    from dynamo_tpu.disagg import (
        DecodeOperator,
        DisaggConfig,
        DisaggRouter,
        PrefillQueue,
    )
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import TpuEngine
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import ModelConfig

    server, frontend, spawn = plane
    mcfg = ModelConfig.tiny_test()
    params = llama.init_params(jax.random.PRNGKey(0), mcfg, dtype="float32")
    ecfg = EngineConfig(
        model=mcfg, num_blocks=32, max_num_seqs=2, max_model_len=128,
        dtype="float32",
    )
    prompt = list(range(40))

    # Local oracle.
    local = TpuEngine(ecfg, params=params)
    await local.start()
    expected, _ = [], None
    async for item in local.generate(Context(_req(prompt, max_tokens=6))):
        expected += item.get("token_ids") or []
    await local.stop()
    assert expected

    await spawn(seed=0, ttl=2.0, script=PREFILL)

    decode = TpuEngine(ecfg, params=params)
    await decode.start()
    dis = DisaggRouter.__new__(DisaggRouter)
    dis.cfg = DisaggConfig(max_local_prefill_length=16, max_prefill_queue_size=8)
    op = await DecodeOperator(
        decode, PrefillQueue(frontend, "test"), dis, transport=transport
    ).start()
    assert op.transport == transport

    toks = []
    async for item in op.generate(Context(_req(prompt, max_tokens=6))):
        toks += item.get("token_ids") or []
    assert toks == expected
    assert op.remote_count == 1 and op.local_count == 0

    await op.stop()
    await decode.stop()


async def test_prefill_worker_death_after_dequeue_redelivers(plane):
    """VERDICT r02 'done' gate for the durable queue: a prefill worker that
    crashes AFTER dequeuing (before pushing KV) must not lose the request —
    its connection death nacks the leased item, a later worker picks it up,
    and the decode stream still completes bit-identical to a local run."""
    import jax

    from dynamo_tpu.disagg import (
        DecodeOperator,
        DisaggConfig,
        DisaggRouter,
        PrefillQueue,
    )
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import TpuEngine
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import ModelConfig

    server, frontend, spawn = plane
    mcfg = ModelConfig.tiny_test()
    params = llama.init_params(jax.random.PRNGKey(0), mcfg, dtype="float32")
    ecfg = EngineConfig(
        model=mcfg, num_blocks=32, max_num_seqs=2, max_model_len=128,
        dtype="float32",
    )
    prompt = list(range(40))

    local = TpuEngine(ecfg, params=params)
    await local.start()
    expected = []
    async for item in local.generate(Context(_req(prompt, max_tokens=6))):
        expected += item.get("token_ids") or []
    await local.stop()

    # Only the crashing worker is up when the request is enqueued.
    dying, _ = await spawn(
        seed=0, ttl=2.0, script=PREFILL, extra=("--die-after-dequeue",)
    )

    decode = TpuEngine(ecfg, params=params)
    await decode.start()
    dis = DisaggRouter.__new__(DisaggRouter)
    dis.cfg = DisaggConfig(max_local_prefill_length=16, max_prefill_queue_size=8)
    op = await DecodeOperator(
        decode, PrefillQueue(frontend, "test"), dis, transport="tcp"
    ).start()

    async def consume():
        toks = []
        async for item in op.generate(Context(_req(prompt, max_tokens=6))):
            toks += item.get("token_ids") or []
        return toks

    stream = asyncio.ensure_future(consume())
    await asyncio.wait_for(dying.wait(), 30)  # crashed holding the lease
    assert dying.returncode == 17
    assert not stream.done(), "stream must still be pending, not failed"

    # A healthy worker arrives later and must receive the redelivery.
    await spawn(seed=0, ttl=2.0, script=PREFILL)
    toks = await asyncio.wait_for(stream, 60)
    assert toks == expected
    assert op.remote_count == 1 and op.local_count == 0

    await op.stop()
    await decode.stop()


async def test_cross_process_sharded_worker_matches_local(plane):
    """Cross-host × multi-chip serving: a worker PROCESS running a REAL
    TpuEngine over a tp=2 virtual mesh serves requests routed from this
    process, and its greedy tokens are identical to a local single-device
    engine with the same weights (the determinism contract both sides
    build from PRNGKey(0) fp32). This is the multi-process × multi-device
    shape VERDICT r02 asked for (reference: one engine process per host,
    TP inside — lib/llm/src/engines.rs:42-60 MultiNodeConfig)."""
    import jax

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import TpuEngine
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import ModelConfig

    server, frontend, spawn = plane
    # Long TTL: mesh-sharded jit TRACING is Python-side and holds the GIL
    # for seconds inside the engine thread, starving the keepalive
    # coroutine — a real deployment sizes lease TTLs above its worst
    # compile stall for exactly this reason.
    await spawn(seed=0, ttl=30.0, script=SHARDED)

    mcfg = ModelConfig.tiny_test()
    params = llama.init_params(jax.random.PRNGKey(0), mcfg, dtype="float32")
    local = TpuEngine(
        EngineConfig(
            model=mcfg, num_blocks=32, max_num_seqs=2, max_model_len=128,
            dtype="float32",
        ),
        params=params,
    )
    await local.start()
    try:
        push = await PushRouter.create(
            frontend, "test.worker.generate", mode=RouterMode.ROUND_ROBIN
        )
        prompt = [1, 5, 9, 2, 7, 3, 8]
        remote_toks, _ = await _send(push, prompt)

        local_toks = []
        async for item in local.generate(Context(_req(prompt))):
            local_toks += item.get("token_ids") or []
        assert remote_toks == local_toks, (remote_toks, local_toks)
        assert len(remote_toks) == 4
    finally:
        await local.stop()

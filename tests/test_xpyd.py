"""xPyD calibration + projection + network-aware routing tests
(ROADMAP #4; docs/architecture/planner.md).

The calibration fixture is the drift gate: the checked-in constants
(planner/calibration.py) must keep reproducing the RECORDED BENCH_r04
headline within 10 % — a mocker cost-model edit that silently skews the
xPyD projections fails here, not in a later postmortem."""

import pytest

from dynamo_tpu.llm.kv_router.metrics_aggregator import ProcessedEndpoints
from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics
from dynamo_tpu.llm.kv_router.scheduler import (
    DefaultWorkerSelector,
    KvRouterConfig,
)
from dynamo_tpu.planner import calibration as cal
from dynamo_tpu.planner import simulate as sim

# ---------------------------------------------------------------------------
# calibration fixture (<10% vs the recorded r04 run)
# ---------------------------------------------------------------------------


def test_calibration_constants_match_recorded_artifact():
    """The decode-dispatch constants are DERIVED from BENCH_r04.json's
    two measured step times; re-derive from the artifact and compare so
    the constants and the recording can't drift apart."""
    rec = cal.recorded_r04()
    per_lane_us = (rec["decode_step_ms"] - rec["decode_step_ms_b32"]) \
        * 1000.0 / 32.0
    base_us = rec["decode_step_ms_b32"] * 1000.0 - 32.0 * per_lane_us
    assert per_lane_us == pytest.approx(cal.DECODE_TIME_PER_LANE_US,
                                        rel=0.02)
    assert base_us == pytest.approx(cal.DECODE_TIME_PER_STEP_US, rel=0.02)
    assert rec["tok_s"] == cal.R04_HEADLINE_TOK_S
    assert rec["p50_ttft_ms"] == cal.R04_P50_TTFT_MS


def test_calibrated_sim_reproduces_r04_headline_within_10pct():
    """Acceptance: mocker cost model reproduces recorded BENCH_r04
    aggregated tok/s and p50 TTFT within 10%."""
    cfg = sim.SimConfig()
    wl = sim.synth_workload(cal.R04_NUM_REQUESTS, cal.R04_ISL, cal.R04_OSL)
    r = sim.simulate_aggregated(cfg, wl, 1)
    assert r.tok_s == pytest.approx(cal.R04_HEADLINE_TOK_S, rel=0.10)
    assert r.p50_ttft_ms == pytest.approx(cal.R04_P50_TTFT_MS, rel=0.10)
    # The current fit is far tighter than the gate; if it degrades past
    # 5% someone changed the cost model — re-derive before loosening.
    assert r.tok_s == pytest.approx(cal.R04_HEADLINE_TOK_S, rel=0.05)
    assert r.p50_ttft_ms == pytest.approx(cal.R04_P50_TTFT_MS, rel=0.05)


def test_calibrated_mocker_config_carries_constants():
    m = cal.calibrated_mocker_config()
    assert m.decode_time_per_lane_us == cal.DECODE_TIME_PER_LANE_US
    assert m.prefill_dispatch_base_us == cal.PREFILL_DISPATCH_BASE_US
    assert m.decode_time_per_step_us == cal.DECODE_TIME_PER_STEP_US
    over = cal.calibrated_mocker_config(decode_time_per_lane_us=1.0)
    assert over.decode_time_per_lane_us == 1.0


def test_handoff_transfer_term_matches_measured_channel():
    """ISL-3000 over the measured 21.7 GB/s device channel lands in
    ~9 ms (BENCHMARKS.md 'Batched KV block IO') — the fixed 2-dispatch
    cost plus bytes/rate."""
    s = cal.handoff_seconds(3000)
    assert 0.004 < s < 0.012
    # A wire-rate link (0.005 GB/s) makes the same prompt ~20 s — the
    # asymmetry network-aware selection exists to route around.
    assert cal.handoff_seconds(3000, link_gbps=0.005) > 15.0


# ---------------------------------------------------------------------------
# projection gates (the BENCH_XPYD=1 table)
# ---------------------------------------------------------------------------


def test_xpyd_projection_gates():
    from benchmarks.xpyd_bench import calibration_check, projection, run_gates

    assert calibration_check()["ok"]
    # run_gates is THE gate pipeline bench.py's BENCH_XPYD leg and the
    # --assert CLI both call (single source of truth).
    report = run_gates()
    assert all(report["gates"].values()), report["gates"]
    assert report["headline_ratio"] > 1.30
    proj = projection()
    by_top = {r["topology"]: r for r in proj["rows"]}
    assert set(by_top) >= {"1xAGG", "1xcoloc", "3xcoloc", "1P1D", "2P1D",
                           "2P2D"}
    # The ci.sh gate: 2P1D beats the 1-worker aggregated baseline on
    # the prefill-heavy replay...
    assert by_top["2P1D"]["tok_s"] > by_top["1xAGG"]["tok_s"]
    # ...and beats the SLO-holding co-located fleet at EQUAL chips —
    # the honest form of the "+30% disagg" pillar claim (the dedicated
    # prefill pool runs fused batches; co-located prefill pays the
    # quantum tax to hold decode ITL).
    assert by_top["2P1D"]["tok_s"] > 1.30 * by_top["3xcoloc"]["tok_s"]
    # Disagg decode ITL never sees a prefill stall: max gap ≈ one step.
    assert by_top["2P1D"]["itl_max_ms"] < 25.0
    # The throughput-max aggregated baseline DOES stall decode for whole
    # fused prefill batches (the SLO failure disagg removes).
    assert by_top["1xAGG"]["itl_max_ms"] > 1000.0
    # Nothing dropped anywhere.
    assert all(r["dropped"] == 0 for r in proj["rows"])


def test_scale_down_mid_run_drops_nothing():
    """Acceptance: decode scale-down mid-run — zero dropped requests,
    traffic shifts to the survivor."""
    from benchmarks.xpyd_bench import drain_leg

    d = drain_leg()
    assert d["ok"]
    assert d["row"]["dropped"] == 0
    assert d["survivor_served"] > d["drained_worker_served"] > 0
    # The drain COMPLETED before the run ended (drain ≠ hang).
    assert d["row"]["decode_drained_at_s"] is not None
    assert d["row"]["decode_drained_at_s"] <= d["row"]["elapsed_s"]


def test_sim_drain_with_no_survivor_drops_late_arrivals():
    """Counter-case: draining the ONLY decode worker leaves late
    arrivals unroutable — the simulator reports them as dropped rather
    than hanging (the gate above proves the planner never does this:
    min_workers floors the pool)."""
    cfg = sim.SimConfig()
    wl = sim.synth_workload(8, 128, 16, rate_rps=2.0)
    r = sim.simulate_xpyd(cfg, wl, 1, 1, drain_decode_at=(1.0, 0))
    assert r.dropped > 0
    assert r.completed + r.dropped == 8


def test_sim_netaware_selection_avoids_slow_link():
    """Simulator twin of the router A/B: equal-load decode workers on a
    21.7 vs 0.012 GB/s link split under plain selection but shift to
    the fast link under netaware selection."""
    cfg = sim.SimConfig()

    def run(selector):
        wl = sim.synth_workload(16, 3000, 20)
        return sim.simulate_xpyd(
            cfg, wl, 2, 2, decode_links_gbps=[21.7, 0.012],
            selector=selector,
        )

    plain = run("plain")
    net = run("netaware")
    assert plain.per_decode_worker[1] >= 6       # blind split
    assert net.per_decode_worker[0] >= 14        # fast link wins
    assert net.per_decode_worker[1] <= 2
    # Routing around the slow link pays off end-to-end.
    assert net.p95_ttft_ms < plain.p95_ttft_ms


# ---------------------------------------------------------------------------
# network-aware selector (production scheduler path)
# ---------------------------------------------------------------------------


def _eps(fast_bps=21.7e9, slow_bps=0.012e9, overlap_total=4096):
    return ProcessedEndpoints(
        metrics={
            1: ForwardPassMetrics(kv_total_blocks=overlap_total,
                                  kvbm_link_g2g1_bps=fast_bps),
            2: ForwardPassMetrics(kv_total_blocks=overlap_total,
                                  kvbm_link_g2g1_bps=slow_bps),
        },
        stamp=1.0,
    )


def test_selector_network_aware_shifts_off_slow_link():
    plain = DefaultWorkerSelector(KvRouterConfig(), seed=0)
    net = DefaultWorkerSelector(
        KvRouterConfig(network_aware=True), seed=0
    )
    plain_picks = {1: 0, 2: 0}
    net_picks = {1: 0, 2: 0}
    for _ in range(100):
        plain_picks[plain.select(_eps(), {}, isl=128).worker_id] += 1
        net_picks[net.select(_eps(), {}, isl=128).worker_id] += 1
    # Plain mode: identical candidates -> the predicted-load bump
    # alternates the tie -> a split. No link preference.
    assert 30 <= plain_picks[1] <= 70
    # Network-aware: the slow link pays the full transfer term.
    assert net_picks[1] >= 90


def test_selector_audits_transfer_cost_in_candidates():
    """Acceptance: the decision is visible in the audit records — every
    candidate carries its priced transfer_ms + the applied term."""
    net = DefaultWorkerSelector(KvRouterConfig(network_aware=True), seed=0)
    d = net.select(_eps(), {}, isl=128)
    by_worker = {c["worker"]: c for c in d.candidates}
    assert by_worker[1]["transfer_ms"] < by_worker[2]["transfer_ms"]
    assert by_worker[2]["transfer_term"] == pytest.approx(1.0)
    # (both fields are rounded for the audit record — compare loosely)
    assert by_worker[1]["transfer_term"] == pytest.approx(
        by_worker[1]["transfer_ms"] / by_worker[2]["transfer_ms"], abs=1e-3
    )
    # Plain mode emits no transfer fields (the flag is honest).
    plain = DefaultWorkerSelector(KvRouterConfig(), seed=0)
    d = plain.select(_eps(), {}, isl=128)
    assert all("transfer_ms" not in c for c in d.candidates)


def test_selector_overlap_reduces_transfer_cost():
    """Predicted-overlap blocks don't travel: a full-overlap candidate
    pays zero transfer even on a slow link."""
    net = DefaultWorkerSelector(KvRouterConfig(network_aware=True), seed=0)
    isl = 128
    blocks = (isl + 15) // 16
    # Worker 2 (slow link) holds the whole prefix; worker 1 holds none.
    d = net.select(_eps(), {2: blocks}, isl=isl)
    by_worker = {c["worker"]: c for c in d.candidates}
    assert by_worker[2]["transfer_ms"] == 0.0
    assert d.worker_id == 2   # overlap + zero transfer beats fast link


def test_selector_uniform_links_do_not_distort_selection():
    """Uniform fleet: the normalized term shifts every logit equally,
    so network-aware mode picks exactly what plain mode picks."""
    eps = ProcessedEndpoints(
        metrics={
            1: ForwardPassMetrics(kv_active_blocks=10, kv_total_blocks=100,
                                  kvbm_link_g2g1_bps=21.7e9),
            2: ForwardPassMetrics(kv_active_blocks=90, kv_total_blocks=100,
                                  kvbm_link_g2g1_bps=21.7e9,
                                  num_requests_waiting=3),
        },
        stamp=1.0,
    )
    plain = DefaultWorkerSelector(KvRouterConfig(), seed=0)
    net = DefaultWorkerSelector(KvRouterConfig(network_aware=True), seed=0)
    assert plain.select(eps, {1: 4}, isl=64).worker_id == \
        net.select(eps, {1: 4}, isl=64).worker_id == 1


def test_selector_missing_link_ema_falls_back_to_default():
    """A fresh worker with no EMA yet is priced at the default link,
    not at infinity/zero."""
    eps = ProcessedEndpoints(
        metrics={
            1: ForwardPassMetrics(kv_total_blocks=100),   # no EMA
            2: ForwardPassMetrics(kv_total_blocks=100,
                                  kvbm_link_g2g1_bps=0.012e9),
        },
        stamp=1.0,
    )
    net = DefaultWorkerSelector(KvRouterConfig(network_aware=True), seed=0)
    d = net.select(eps, {}, isl=128)
    assert d.worker_id == 1   # default 21.7 GB/s beats the slow EMA
    by_worker = {c["worker"]: c for c in d.candidates}
    assert 0 < by_worker[1]["transfer_ms"] < by_worker[2]["transfer_ms"]


def test_router_ab_harness():
    """The ci.sh router A/B leg end-to-end (benchmarks/xpyd_bench.py)."""
    from benchmarks.xpyd_bench import router_ab

    ab = router_ab(trials=60)
    assert ab["ok"]
    assert ab["netaware"]["fast_link_share"] >= 0.9
    assert ab["netaware"]["transfer_audited"]
    assert not ab["plain"]["transfer_audited"]


@pytest.mark.anyio
async def test_netaware_decision_visible_in_debug_routes():
    """Acceptance: the transfer-cost decision shows up in /debug/routes
    audit records (candidates carry transfer_ms/transfer_term)."""
    import httpx

    from dynamo_tpu.llm.discovery import ModelManager
    from dynamo_tpu.llm.http_service import HttpService
    from dynamo_tpu.llm.kv_router.audit import ROUTE_OBS, RouteAuditRecord

    net = DefaultWorkerSelector(KvRouterConfig(network_aware=True), seed=0)
    d = net.select(_eps(), {}, isl=128)
    ROUTE_OBS.record(RouteAuditRecord(
        request_id="req-net", trace_id="", worker_id=d.worker_id,
        overlap_blocks=d.overlap_blocks, isl_blocks=8, logit=d.logit,
        decision_ms=0.5, candidates=d.candidates,
    ))
    service = HttpService(ModelManager(), host="127.0.0.1", port=0)
    await service.start()
    try:
        async with httpx.AsyncClient() as client:
            r = await client.get(
                f"http://127.0.0.1:{service.port}/debug/routes?n=4"
            )
            rec = next(x for x in r.json()["recent"]
                       if x["id"] == "req-net")
            assert any("transfer_ms" in c for c in rec["candidates"])
            assert any("transfer_term" in c for c in rec["candidates"])
    finally:
        await service.stop()


# ---------------------------------------------------------------------------
# simulator internals
# ---------------------------------------------------------------------------


def test_sim_decode_worker_balance_and_cap():
    cfg = sim.SimConfig(max_num_seqs=8)
    wl = sim.synth_workload(32, 128, 8)
    r = sim.simulate_xpyd(cfg, wl, 1, 2)
    assert r.completed == 32 and r.dropped == 0
    assert r.per_decode_worker == [16, 16]   # least-loaded split


def test_sim_tok_s_accounting():
    cfg = sim.SimConfig()
    wl = sim.synth_workload(4, 64, 8)
    r = sim.simulate_aggregated(cfg, wl, 1)
    assert r.completed == 4
    assert r.tok_s == pytest.approx(4 * 8 / r.elapsed_s, rel=1e-6)


def test_sim_coloc_mode_holds_itl_while_batch_mode_stalls():
    cfg = sim.SimConfig()
    wl_b = sim.synth_workload(32, 3000, 150)
    wl_c = sim.synth_workload(32, 3000, 150)
    batch = sim.simulate_aggregated(cfg, wl_b, 1, mode="batch")
    coloc = sim.simulate_aggregated(cfg, wl_c, 1, mode="coloc")
    # Co-location: no dispatch ever exceeds ~step+quantum cost.
    assert coloc.itl_max_ms < 40.0
    # Batch mode: a fused ISL-3000x16 prefill stalls decode for seconds.
    assert batch.itl_max_ms > 1000.0
    # The price of holding ITL: prefill efficiency (the tax the
    # dedicated prefill pool removes).
    assert coloc.tok_s < batch.tok_s

"""Subprocess worker running a REAL TpuEngine over a tp=2 virtual device
mesh — the cross-host × multi-chip serving fixture: requests arrive over
the control plane from another OS process while the engine itself is
mesh-sharded (GSPMD TP + shard_map attention), exactly the shape of a
multi-host TPU deployment scaled down to CI (reference analogue: the
reference's multi-node engines bootstrap via MultiNodeConfig,
lib/llm/src/engines.rs:42-60 — one worker process per host, TP inside).

Run: python tests/procs/sharded_worker.py --addr HOST:PORT [--mesh tp=2]
Prints "READY <lease_id>" once serving.
"""

import argparse
import asyncio
import os
import sys

# Force EXACTLY two virtual devices (override any inherited device-count
# flag — pytest's conftest exports 8, and the tp=2 mesh must equal the
# device count).
_flags = [
    f
    for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f
]
os.environ["XLA_FLAGS"] = " ".join(
    _flags + ["--xla_force_host_platform_device_count=2"]
)
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from dynamo_tpu.engine.config import EngineConfig  # noqa: E402
from dynamo_tpu.engine.engine import TpuEngine  # noqa: E402
from dynamo_tpu.models import llama  # noqa: E402
from dynamo_tpu.models.config import ModelConfig  # noqa: E402
from dynamo_tpu.runtime.distributed import DistributedRuntime  # noqa: E402


async def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--addr", required=True)
    ap.add_argument("--ns", default="test")
    ap.add_argument("--component", default="worker")
    ap.add_argument("--ttl", type=float, default=2.0)
    ap.add_argument("--tp", type=int, default=2)
    args = ap.parse_args()

    drt = await DistributedRuntime.connect(args.addr, lease_ttl_s=args.ttl)
    comp = drt.namespace(args.ns).component(args.component)
    mcfg = ModelConfig.tiny_test()
    # Determinism contract with the driver test: PRNGKey(0) fp32 weights,
    # so the sharded serve must reproduce the driver's local greedy run.
    params = llama.init_params(jax.random.PRNGKey(0), mcfg, dtype="float32")
    engine = TpuEngine(
        EngineConfig(
            model=mcfg,
            num_blocks=32,
            max_num_seqs=2,
            max_model_len=128,
            dtype="float32",
            mesh_shape={"tp": args.tp},
        ),
        params=params,
    )
    await engine.start()
    await comp.endpoint("generate").serve(engine)
    print(f"READY {drt.primary_lease_id}", flush=True)
    try:
        await drt.runtime.token.cancelled()
    finally:
        await engine.stop()


if __name__ == "__main__":
    asyncio.run(main())

"""Crash-drill child for tests/test_integrity.py: pushes a chain of KV
blocks through the real offer → G2 host → G3 disk(persist) path, printing
"STORED <i>" only after block i's bytes AND sidecar entry are durable
(drain_offers + the G2→G3 edge drained). The parent SIGKILLs this process
mid-chain and asserts the restarted tier serves exactly a valid prefix of
the chain — never a torn block (docs/architecture/integrity.md).

Run: python tests/procs/torn_offload_worker.py --path /tmp/g3.kv --blocks 8
"""

import argparse
import asyncio
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np  # noqa: E402

from dynamo_tpu.block_manager import (  # noqa: E402
    KvbmConfig,
    KvBlockManager,
    KvLayoutConfig,
)

# Must match tests/test_integrity.py TORN_LAYOUT exactly — the parent
# reopens the same disk file and verifies byte-identity per block.
LAYOUT = KvLayoutConfig(
    num_layers=1, page_size=4, num_kv_heads=1, head_dim=4, dtype="float32"
)


def _row(i: int) -> np.ndarray:
    return np.full((LAYOUT.block_elems,), float(i + 1), np.float32)


async def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--path", required=True)
    ap.add_argument("--blocks", type=int, default=8)
    args = ap.parse_args()

    kvbm = await KvBlockManager(
        KvbmConfig(
            layout=LAYOUT,
            host_blocks=args.blocks + 4,
            disk_blocks=args.blocks + 4,
            disk_path=args.path,
            disk_persist=True,
            # Serialized transfers keep the sidecar's record order equal
            # to chain order, so "STORED i" implies blocks 0..i durable.
            offload_concurrency=1,
        )
    ).start()
    parent = None
    for i in range(args.blocks):
        h = 1000 + i
        kvbm.offer(h, parent, [i] * LAYOUT.page_size, _row(i))
        await kvbm.drain_offers(10.0)
        await kvbm._g2_to_g3.drain()
        parent = h
        print(f"STORED {i}", flush=True)
        # A real offload stream has inter-block gaps; the pause is where
        # the parent's SIGKILL lands, mid-chain rather than post-DONE.
        await asyncio.sleep(0.05)
    print("DONE", flush=True)


if __name__ == "__main__":
    asyncio.run(main())

"""Subprocess prefill worker for multi-process disagg tests: a REAL tiny
TpuEngine draining the shared prefill queue over the control plane, pushing
computed KV into the decode process's transfer receiver (reference:
examples/llm/components/prefill_worker.py:139-211, as a real OS process).

Determinism contract with the driver test: both sides init the tiny model
with PRNGKey(0) fp32 on the CPU backend, so weights are identical and the
disagg continuation must be bit-identical to a local run.
"""

import argparse
import asyncio
import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=2")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from dynamo_tpu.disagg import PrefillQueue, PrefillWorker  # noqa: E402
from dynamo_tpu.engine.config import EngineConfig  # noqa: E402
from dynamo_tpu.engine.engine import TpuEngine  # noqa: E402
from dynamo_tpu.models import llama  # noqa: E402
from dynamo_tpu.models.config import ModelConfig  # noqa: E402
from dynamo_tpu.runtime.distributed import DistributedRuntime  # noqa: E402


class _DyingWorker(PrefillWorker):
    """Crashes hard after dequeuing (before serving) — the durable-queue
    redelivery fixture: its un-acked item must reach another worker.
    (_serve_batch is the drain entrypoint since the r05 batched worker.)"""

    async def _serve_batch(self, reqs: list) -> None:
        print(f"DEQUEUED {reqs[0].get('request_id')}", flush=True)
        os._exit(17)


async def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--addr", required=True)
    ap.add_argument("--ns", default="test")
    ap.add_argument("--ttl", type=float, default=2.0)
    ap.add_argument("--die-after-dequeue", action="store_true")
    args = ap.parse_args()

    drt = await DistributedRuntime.connect(args.addr, lease_ttl_s=args.ttl)
    mcfg = ModelConfig.tiny_test()
    params = llama.init_params(jax.random.PRNGKey(0), mcfg, dtype="float32")
    engine = TpuEngine(
        EngineConfig(
            model=mcfg,
            num_blocks=32,
            max_num_seqs=2,
            max_model_len=128,
            dtype="float32",
        ),
        params=params,
    )
    await engine.start()
    cls = _DyingWorker if args.die_after_dequeue else PrefillWorker
    pw = cls(engine, PrefillQueue(drt, args.ns)).start()
    print(f"READY {drt.primary_lease_id}", flush=True)
    try:
        await drt.runtime.token.cancelled()
    finally:
        await pw.stop()
        await engine.stop()


if __name__ == "__main__":
    asyncio.run(main())

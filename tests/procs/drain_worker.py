"""Subprocess worker for the graceful-drain e2e test: a slow mocker
engine served over the control plane that drains on SIGTERM OR the
control-plane drain verb — the same state machine cli.py runs
(docs/architecture/overload_and_drain.md).

Run: python tests/procs/drain_worker.py --addr HOST:PORT
Prints "READY <lease_id>" once serving; on SIGTERM/drain-verb it stops
admitting, finishes in-flight sequences, deregisters, and prints
"DRAINED <ok>" before exiting cleanly.
"""

import argparse
import asyncio
import os
import signal
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from dynamo_tpu.engine.config import EngineConfig  # noqa: E402
from dynamo_tpu.mocker import MockerConfig, MockerEngine  # noqa: E402
from dynamo_tpu.models.config import ModelConfig  # noqa: E402
from dynamo_tpu.runtime.distributed import DistributedRuntime  # noqa: E402
from dynamo_tpu.runtime.drain import watch_drain  # noqa: E402


async def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--addr", required=True)
    ap.add_argument("--ns", default="chaos")
    ap.add_argument("--component", default="drainw")
    args = ap.parse_args()

    drt = await DistributedRuntime.connect(args.addr, lease_ttl_s=2.0)
    engine = MockerEngine(
        EngineConfig(
            model=ModelConfig.tiny_test(),
            num_blocks=64,
            max_num_seqs=4,
            max_model_len=256,
            dtype="float32",
        ),
        # Slow decode so requests are genuinely in flight when the drain
        # signal lands.
        MockerConfig(decode_time_per_step_us=20000.0),
    )
    await engine.start()
    comp = drt.namespace(args.ns).component(args.component)
    served = await comp.endpoint("generate").serve(engine)

    loop = asyncio.get_running_loop()
    stop = asyncio.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    watch = await watch_drain(drt, args.ns, args.component, stop.set)
    print(f"READY {drt.primary_lease_id}", flush=True)

    await stop.wait()
    watch.close()
    # Same order as cli._graceful_drain: stop admitting, deregister FIRST
    # (immediate router eviction), then finish in-flight work.
    engine.begin_drain()
    ok = await served.drain(20.0)
    ok = await engine.wait_drained(10.0) and ok
    await engine.stop()
    await drt.shutdown()
    print(f"DRAINED {ok}", flush=True)


if __name__ == "__main__":
    asyncio.run(main())

"""Subprocess worker for multi-process tests: a mocker engine served over
the control plane, publishing KV events + load metrics like a real worker.

Run: python tests/procs/mocker_worker.py --addr HOST:PORT [--seed N]
Prints "READY <lease_id>" once serving; runs until killed. The driver test
asserts cross-process routing, KV affinity, and lease-death deregistration
against these processes (reference: the reference proves this path with
real etcd+NATS in lib/bindings/python/tests/; we prove it against our own
control plane).
"""

import argparse
import asyncio
import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=2")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from dynamo_tpu.engine.config import EngineConfig  # noqa: E402
from dynamo_tpu.llm.kv_router.publisher import (  # noqa: E402
    KvEventPublisher,
    WorkerMetricsPublisher,
)
from dynamo_tpu.mocker import MockerConfig, MockerEngine  # noqa: E402
from dynamo_tpu.models.config import ModelConfig  # noqa: E402
from dynamo_tpu.runtime.distributed import DistributedRuntime  # noqa: E402


async def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--addr", required=True)
    ap.add_argument("--ns", default="test")
    ap.add_argument("--component", default="worker")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--ttl", type=float, default=1.0)
    args = ap.parse_args()

    drt = await DistributedRuntime.connect(args.addr, lease_ttl_s=args.ttl)
    comp = drt.namespace(args.ns).component(args.component)
    cfg = EngineConfig(
        model=ModelConfig.tiny_test(),
        num_blocks=64,
        max_num_seqs=4,
        max_model_len=256,
    )
    engine = MockerEngine(cfg, MockerConfig(seed=args.seed))
    wm = WorkerMetricsPublisher()
    pub = KvEventPublisher(drt, comp, drt.primary_lease_id)
    engine._external_kv_event = pub.publish_engine_event
    engine._on_metrics = wm.publish
    await engine.start()

    worker_id = drt.primary_lease_id

    class Tagged:
        """Stamp each response item with this worker's id so the driver
        test can assert which process served it."""

        async def generate(self, ctx):
            async for item in engine.generate(ctx):
                item["worker_id"] = worker_id
                yield item

    await comp.endpoint("generate").serve(Tagged())
    await wm.create_endpoint(comp)
    print(f"READY {worker_id}", flush=True)
    await drt.runtime.token.cancelled()


if __name__ == "__main__":
    asyncio.run(main())

"""Examples stay runnable: every script byte-compiles, and the fast ones
run end-to-end (an example with a broken import path is a broken quickstart
— exactly what reviewers and new users hit first)."""

import pathlib
import py_compile
import subprocess
import sys

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent


def test_all_example_scripts_compile():
    scripts = sorted((ROOT / "examples").rglob("*.py"))
    assert scripts, "no example scripts found"
    for script in scripts:
        py_compile.compile(str(script), doraise=True)


@pytest.mark.parametrize("script", ["examples/hello_world/graph.py"])
def test_fast_examples_run(script):
    proc = subprocess.run(
        [sys.executable, str(ROOT / script)],
        capture_output=True,
        text=True,
        timeout=120,
        cwd=ROOT,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]

"""Planner tests: load spike scales up, idle scales down, zero failed
requests throughout (reference behavior: docs/architecture/planner.md:39-49,
local_connector.py:105-304)."""

import asyncio

import pytest

from dynamo_tpu.llm.engines import EchoEngineCore
from dynamo_tpu.llm.kv_router.publisher import WorkerMetricsPublisher
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.planner import Planner, PlannerConfig
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.egress import PushRouter, RouterMode
from dynamo_tpu.runtime.engine import Context

pytestmark = pytest.mark.anyio


class InProcConnector:
    """Test deployment backend: a 'worker' is an in-process DRT (own lease)
    serving an echo engine + metrics endpoint on the shared control plane."""

    def __init__(self, main_drt) -> None:
        self.main = main_drt
        self.spawned = 0
        self.drained = 0

    async def spawn(self):
        drt = await DistributedRuntime.in_process(
            store=self.main.store, bus=self.main.bus
        )
        comp = drt.namespace("dynamo").component("tpu")
        await comp.endpoint("generate").serve(EchoEngineCore())
        pub = WorkerMetricsPublisher()
        pub.publish({"gpu_cache_usage_perc": 0.0, "num_requests_waiting": 0})
        await pub.create_endpoint(comp)
        self.spawned += 1
        return drt

    async def drain(self, drt) -> None:
        # Lease revoke -> instance keys vanish -> routers drop the worker
        # (the multiprocess suite proves in-flight streams still finish).
        await drt.shutdown()
        self.drained += 1


def _req():
    return PreprocessedRequest(
        token_ids=[1, 2, 3],
        sampling=SamplingOptions(),
        stop=StopConditions(max_tokens=3, ignore_eos=True),
    ).to_wire()


async def test_planner_scales_up_on_load_and_down_when_idle(tmp_path):
    drt = await DistributedRuntime.in_process()
    connector = InProcConnector(drt)
    decision_log = tmp_path / "decisions.jsonl"
    planner = Planner(
        drt,
        PlannerConfig(
            min_workers=1,
            max_workers=2,
            metric_interval_s=0.02,
            adjustment_interval_s=0.15,
            queue_up_threshold=0.5,
            queue_down_threshold=0.1,
            decision_log_path=str(decision_log),
        ),
        connector=connector,
    )
    await planner.start()
    assert planner.num_workers == 1

    # Continuous traffic through the router; count failures end-to-end.
    push = await PushRouter.create(
        drt, "dynamo.tpu.generate", mode=RouterMode.ROUND_ROBIN
    )
    failures = 0
    requests = 0
    stop_traffic = asyncio.Event()

    async def traffic():
        nonlocal failures, requests
        while not stop_traffic.is_set():
            requests += 1
            try:
                async for _ in push.generate(Context(_req())):
                    pass
            except Exception:
                failures += 1
            await asyncio.sleep(0.01)

    traffic_task = asyncio.ensure_future(traffic())

    # Load spike: queued prefill work the planner watches.
    queue = drt.bus.work_queue("dynamo.prefill_queue")
    for i in range(8):
        await queue.enqueue(b"job%d" % i)

    deadline = asyncio.get_running_loop().time() + 5
    while planner.num_workers < 2:
        assert asyncio.get_running_loop().time() < deadline, (
            f"never scaled up (decisions={planner.decisions})"
        )
        await asyncio.sleep(0.05)
    assert connector.spawned == 2

    # Queue drains -> idle -> scale back down to min_workers.
    while await queue.dequeue(timeout_s=0.1):
        pass
    deadline = asyncio.get_running_loop().time() + 5
    while planner.num_workers > 1:
        assert asyncio.get_running_loop().time() < deadline, (
            f"never scaled down (decisions={planner.decisions})"
        )
        await asyncio.sleep(0.05)
    assert connector.drained == 1

    # Budget respected: pressure again but max_workers=2.
    for i in range(8):
        await queue.enqueue(b"again%d" % i)
    await asyncio.sleep(0.4)
    assert planner.num_workers <= 2

    await asyncio.sleep(0.1)
    stop_traffic.set()
    await traffic_task
    assert requests > 10
    assert failures == 0, f"{failures}/{requests} requests failed"

    await planner.stop(drain_workers=True)
    assert planner.num_workers == 0
    await drt.shutdown()

    # Decision time series (reference planner's TensorBoard analogue):
    # one JSONL line per adjustment tick with the inputs that drove it.
    import json as _json

    lines = [
        _json.loads(l)
        for l in decision_log.read_text().splitlines()
    ]
    kinds = {l["decision"] for l in lines}
    assert {"up", "down"} <= kinds, kinds
    assert all(
        {"ts", "decision", "workers", "queue", "kv", "waiting"} <= set(l)
        for l in lines
    )


async def test_planner_state_checkpoint_resume(tmp_path):
    """Planner persists its worker set and re-adopts still-alive workers on
    restart (reference: local connector state ~/.dynamo/state/{ns}.json)."""
    import json

    state = tmp_path / "dynamo.json"

    class PidConnector:
        """Workers are fake pids; adopt() re-attaches the even ones."""

        def __init__(self):
            self.next_pid = 100
            self.adopted = []
            self.spawned = 0

        async def spawn(self):
            self.spawned += 1
            self.next_pid += 1
            return type("H", (), {"pid": self.next_pid})()

        async def drain(self, handle):
            pass

        def adopt(self, pid):
            if pid % 2:  # odd pids "died" between lives
                return None
            self.adopted.append(pid)
            return type("H", (), {"pid": pid})()

    drt = await DistributedRuntime.in_process()
    conn = PidConnector()
    cfg = PlannerConfig(
        min_workers=2, metric_interval_s=10, adjustment_interval_s=10,
        state_path=str(state),
    )
    p1 = Planner(drt, cfg, connector=conn)
    await p1.start()
    assert p1.num_workers == 2
    await p1.stop()
    saved = json.loads(state.read_text())
    assert [w["pid"] for w in saved["workers"]] == [101, 102]

    # Second life: pid 102 survives and is adopted; 101 is gone, so one
    # fresh spawn tops back up to min_workers.
    conn2 = PidConnector()
    conn2.next_pid = 200
    p2 = Planner(drt, cfg, connector=conn2)
    await p2.start()
    assert conn2.adopted == [102]
    assert conn2.spawned == 1
    assert p2.num_workers == 2
    await p2.stop()
    assert [
        w["pid"] for w in json.loads(state.read_text())["workers"]
    ] == [102, 201]
    await drt.shutdown()


def test_perf_profile_interpolation_and_targets(tmp_path):
    """TTFT/ITL interpolation and SLA capacity math (reference:
    planner.md:53-90 profiled scaling; SURVEY §7 hard part #5)."""
    import json

    from dynamo_tpu.planner.profiles import PerfPoint, PerfProfile

    prof = PerfProfile(
        [
            PerfPoint(1, ttft_ms=100, itl_ms=15),
            PerfPoint(8, ttft_ms=200, itl_ms=16),
            PerfPoint(32, ttft_ms=800, itl_ms=20),
        ]
    )
    assert prof.ttft_ms(1) == 100
    assert prof.ttft_ms(4.5) == 150  # midpoint of the 1..8 segment
    assert prof.ttft_ms(0.5) == 100  # clamped below
    assert prof.ttft_ms(40) > 800  # extrapolates upward past the data

    # TTFT SLA of 200ms supports concurrency 8; 500ms lands mid-segment.
    assert abs(prof.max_concurrency_within(ttft_sla_ms=200) - 8) < 0.01
    c = prof.max_concurrency_within(ttft_sla_ms=500)
    assert 8 < c < 32 and abs(prof.ttft_ms(c) - 500) < 1.0
    # Both bounds: the tighter one wins.
    both = prof.max_concurrency_within(ttft_sla_ms=500, itl_sla_ms=16)
    assert both <= 8.01
    # Unmeetable SLA still allows one request per worker.
    assert prof.max_concurrency_within(ttft_sla_ms=1) == 1.0

    assert prof.target_workers(64, ttft_sla_ms=200) == 8
    assert prof.target_workers(0, ttft_sla_ms=200) == 1

    # Round-trips from a bench.py output line.
    bench = {
        "metric": "x", "value": 1.0,
        "extras": {"sweep": [
            {"concurrency": 1, "p50_ttft_ms": 100, "p50_itl_ms": 15},
            {"concurrency": 16, "p50_ttft_ms": 400, "p50_itl_ms": 18},
        ]},
    }
    p = tmp_path / "bench.json"
    p.write_text(json.dumps(bench))
    loaded = PerfProfile.from_bench_json(p)
    assert loaded.ttft_ms(16) == 400


async def test_planner_sla_mode_scales_to_profile_target():
    """With a profile + TTFT SLA, the planner steps toward
    load/capacity instead of watermarks."""
    from dynamo_tpu.planner.profiles import PerfPoint, PerfProfile

    drt = await DistributedRuntime.in_process()
    connector = InProcConnector(drt)
    prof = PerfProfile(
        [PerfPoint(1, 100, 15), PerfPoint(8, 200, 16), PerfPoint(32, 800, 20)]
    )
    planner = Planner(
        drt,
        PlannerConfig(
            min_workers=1, max_workers=3,
            metric_interval_s=0.02, adjustment_interval_s=0.1,
            ttft_sla_ms=200.0,  # per-worker capacity = 8 concurrent
        ),
        connector=connector,
        profile=prof,
    )
    await planner.start()
    assert planner.num_workers == 1

    # Load of ~20 concurrent -> target ceil(20/8)=3 workers.
    queue = drt.bus.work_queue("dynamo.prefill_queue")
    for i in range(20):
        await queue.enqueue(b"job%d" % i)
    deadline = asyncio.get_running_loop().time() + 5
    while planner.num_workers < 3:
        assert asyncio.get_running_loop().time() < deadline, (
            f"never reached SLA target (decisions={planner.decisions})"
        )
        await asyncio.sleep(0.05)

    # Load drains -> back down to min_workers.
    while await queue.dequeue(timeout_s=0.05):
        pass
    deadline = asyncio.get_running_loop().time() + 5
    while planner.num_workers > 1:
        assert asyncio.get_running_loop().time() < deadline, (
            f"never scaled down (decisions={planner.decisions})"
        )
        await asyncio.sleep(0.05)

    await planner.stop(drain_workers=True)
    await drt.shutdown()

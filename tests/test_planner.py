"""Planner tests: load spike scales up, idle scales down, zero failed
requests throughout (reference behavior: docs/architecture/planner.md:39-49,
local_connector.py:105-304)."""

import asyncio

import pytest

from dynamo_tpu.llm.engines import EchoEngineCore
from dynamo_tpu.llm.kv_router.publisher import WorkerMetricsPublisher
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.planner import Planner, PlannerConfig
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.egress import PushRouter, RouterMode
from dynamo_tpu.runtime.engine import Context

pytestmark = pytest.mark.anyio


class InProcConnector:
    """Test deployment backend: a 'worker' is an in-process DRT (own lease)
    serving an echo engine + metrics endpoint on the shared control plane."""

    def __init__(self, main_drt) -> None:
        self.main = main_drt
        self.spawned = 0
        self.drained = 0

    async def spawn(self):
        drt = await DistributedRuntime.in_process(
            store=self.main.store, bus=self.main.bus
        )
        comp = drt.namespace("dynamo").component("tpu")
        await comp.endpoint("generate").serve(EchoEngineCore())
        pub = WorkerMetricsPublisher()
        pub.publish({"gpu_cache_usage_perc": 0.0, "num_requests_waiting": 0})
        await pub.create_endpoint(comp)
        self.spawned += 1
        return drt

    async def drain(self, drt) -> None:
        # Lease revoke -> instance keys vanish -> routers drop the worker
        # (the multiprocess suite proves in-flight streams still finish).
        await drt.shutdown()
        self.drained += 1


def _req():
    return PreprocessedRequest(
        token_ids=[1, 2, 3],
        sampling=SamplingOptions(),
        stop=StopConditions(max_tokens=3, ignore_eos=True),
    ).to_wire()


async def test_planner_scales_up_on_load_and_down_when_idle():
    drt = await DistributedRuntime.in_process()
    connector = InProcConnector(drt)
    planner = Planner(
        drt,
        PlannerConfig(
            min_workers=1,
            max_workers=2,
            metric_interval_s=0.02,
            adjustment_interval_s=0.15,
            queue_up_threshold=0.5,
            queue_down_threshold=0.1,
        ),
        connector=connector,
    )
    await planner.start()
    assert planner.num_workers == 1

    # Continuous traffic through the router; count failures end-to-end.
    push = await PushRouter.create(
        drt, "dynamo.tpu.generate", mode=RouterMode.ROUND_ROBIN
    )
    failures = 0
    requests = 0
    stop_traffic = asyncio.Event()

    async def traffic():
        nonlocal failures, requests
        while not stop_traffic.is_set():
            requests += 1
            try:
                async for _ in push.generate(Context(_req())):
                    pass
            except Exception:
                failures += 1
            await asyncio.sleep(0.01)

    traffic_task = asyncio.ensure_future(traffic())

    # Load spike: queued prefill work the planner watches.
    queue = drt.bus.work_queue("dynamo.prefill_queue")
    for i in range(8):
        await queue.enqueue(b"job%d" % i)

    deadline = asyncio.get_running_loop().time() + 5
    while planner.num_workers < 2:
        assert asyncio.get_running_loop().time() < deadline, (
            f"never scaled up (decisions={planner.decisions})"
        )
        await asyncio.sleep(0.05)
    assert connector.spawned == 2

    # Queue drains -> idle -> scale back down to min_workers.
    while await queue.dequeue(timeout_s=0.1):
        pass
    deadline = asyncio.get_running_loop().time() + 5
    while planner.num_workers > 1:
        assert asyncio.get_running_loop().time() < deadline, (
            f"never scaled down (decisions={planner.decisions})"
        )
        await asyncio.sleep(0.05)
    assert connector.drained == 1

    # Budget respected: pressure again but max_workers=2.
    for i in range(8):
        await queue.enqueue(b"again%d" % i)
    await asyncio.sleep(0.4)
    assert planner.num_workers <= 2

    await asyncio.sleep(0.1)
    stop_traffic.set()
    await traffic_task
    assert requests > 10
    assert failures == 0, f"{failures}/{requests} requests failed"

    await planner.stop(drain_workers=True)
    assert planner.num_workers == 0
    await drt.shutdown()


async def test_planner_state_checkpoint_resume(tmp_path):
    """Planner persists its worker set and re-adopts still-alive workers on
    restart (reference: local connector state ~/.dynamo/state/{ns}.json)."""
    import json

    state = tmp_path / "dynamo.json"

    class PidConnector:
        """Workers are fake pids; adopt() re-attaches the even ones."""

        def __init__(self):
            self.next_pid = 100
            self.adopted = []
            self.spawned = 0

        async def spawn(self):
            self.spawned += 1
            self.next_pid += 1
            return type("H", (), {"pid": self.next_pid})()

        async def drain(self, handle):
            pass

        def adopt(self, pid):
            if pid % 2:  # odd pids "died" between lives
                return None
            self.adopted.append(pid)
            return type("H", (), {"pid": pid})()

    drt = await DistributedRuntime.in_process()
    conn = PidConnector()
    cfg = PlannerConfig(
        min_workers=2, metric_interval_s=10, adjustment_interval_s=10,
        state_path=str(state),
    )
    p1 = Planner(drt, cfg, connector=conn)
    await p1.start()
    assert p1.num_workers == 2
    await p1.stop()
    saved = json.loads(state.read_text())
    assert [w["pid"] for w in saved["workers"]] == [101, 102]

    # Second life: pid 102 survives and is adopted; 101 is gone, so one
    # fresh spawn tops back up to min_workers.
    conn2 = PidConnector()
    conn2.next_pid = 200
    p2 = Planner(drt, cfg, connector=conn2)
    await p2.start()
    assert conn2.adopted == [102]
    assert conn2.spawned == 1
    assert p2.num_workers == 2
    await p2.stop()
    assert [
        w["pid"] for w in json.loads(state.read_text())["workers"]
    ] == [102, 201]
    await drt.shutdown()

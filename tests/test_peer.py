"""G4 peer tier tests (docs/architecture/kvbm_g4.md): pull-vs-recompute
pricing, packed-row byte identity across the pull chain, the mixed-
precision layout refusal, peer-death degrade (never hang), the
re-announce protocol, prefix-heat pre-placement, and the engine's
park/resume admission hook."""

import asyncio
from types import SimpleNamespace

import numpy as np
import pytest

from dynamo_tpu.block_manager import (
    KvbmConfig,
    KvBlockManager,
    KvLayoutConfig,
)
from dynamo_tpu.block_manager.peer import (
    PeerBlockClient,
    PeerBlockServer,
    PrefixHeat,
    Reannouncer,
    _parents_first,
    layout_fingerprint,
    preplace,
    request_reannounce,
)
from dynamo_tpu.block_manager.quant import pack_block
from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.llm.kv_router.protocols import KvCacheEventData
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.mocker.engine import MockerConfig, MockerEngine
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.planner.calibration import HANDOFF_GBPS
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.utils.faults import FAULTS

pytestmark = pytest.mark.anyio

LAYOUT_F32 = KvLayoutConfig(
    num_layers=2, page_size=16, num_kv_heads=2, head_dim=16, dtype="float32"
)
LAYOUT_INT8 = KvLayoutConfig(
    num_layers=2, page_size=16, num_kv_heads=2, head_dim=16,
    dtype="bfloat16", quant="int8",
)


def _row_f32(seed: float) -> np.ndarray:
    return np.full((LAYOUT_F32.block_elems,), seed, np.float32)


def _packed_row(seed: int) -> np.ndarray:
    rng = np.random.default_rng(seed)
    q = rng.integers(
        -127, 128,
        (LAYOUT_INT8.num_layers, 2, LAYOUT_INT8.page_size,
         LAYOUT_INT8.num_kv_heads, LAYOUT_INT8.head_dim),
        dtype=np.int8,
    )
    scales = np.float32(rng.uniform(
        0.01, 1.0, (LAYOUT_INT8.num_layers, 2, LAYOUT_INT8.num_kv_heads)
    ))
    return pack_block(q, scales, LAYOUT_INT8)


async def _settle(mgr, n, timeout=5.0):
    deadline = asyncio.get_running_loop().time() + timeout
    while mgr.stats()["host_registered"] < n:
        assert asyncio.get_running_loop().time() < deadline, (
            f"host tier never reached {n} blocks: {mgr.stats()}"
        )
        await asyncio.sleep(0.02)


def _stub_client(layout=LAYOUT_F32):
    """A PeerBlockClient with stubbed transports — enough for the
    pricing law, which only reads _blocksets/_peer_bps."""
    drt = SimpleNamespace(primary_lease_id=0xAA)
    comp = SimpleNamespace(namespace=SimpleNamespace(name="kv"), name="tpu")
    return PeerBlockClient(drt, comp, layout, layout_cfg=layout)


# ---------------------------------------------------------------------------
# pricing law
# ---------------------------------------------------------------------------


def test_slow_link_loses_to_recompute():
    """A peer behind a slow advertised link must LOSE the pricing race:
    plan() returns None and the request recomputes locally."""
    client = _stub_client()
    hashes = [1, 2, 3, 4]
    client._blocksets["bb"] = set(hashes)

    # Calibrated-channel default: the pull wins easily (ms of transfer
    # vs tens of ms of prefill for 4 blocks).
    pull_s, recompute_s = client.price(4, "bb")
    assert pull_s < recompute_s
    assert client.plan(hashes) == ("bb", 4)

    # The same peer advertising a crawling 1 MB/s link reprices every
    # pull above local recompute.
    client._peer_bps["bb"] = 1e6
    pull_s, recompute_s = client.price(4, "bb")
    assert pull_s > recompute_s
    assert client.plan(hashes) is None

    # A measured pull EMA (ground truth) overrides the advertisement.
    client._pull_rate.note(int(20e9), 1.0)
    assert client.effective_bps("bb") > 1e9
    assert client.plan(hashes) == ("bb", 4)


def test_price_fallback_is_the_calibrated_channel():
    """With no measured EMA and no advertisement, pricing must use the
    single-sourced calibration constant — not a stray literal."""
    client = _stub_client()
    assert client.effective_bps("nobody") == HANDOFF_GBPS * 1e9


def test_prefill_tps_moves_the_recompute_side():
    """A very fast live prefill EMA flips the decision to recompute even
    over the calibrated link."""
    client = _stub_client()
    client._blocksets["bb"] = {1, 2}
    assert client.plan([1, 2]) is not None
    assert client.plan([1, 2], prefill_tps=1e9) is None


# ---------------------------------------------------------------------------
# pull chain: byte identity + layout refusal
# ---------------------------------------------------------------------------


async def _peer_pair(main, layout, rows):
    """Worker A (seeded with `rows`) serving worker B; returns
    (mgr_a, mgr_b, server, client, drts)."""
    drt_a = await DistributedRuntime.in_process(store=main.store, bus=main.bus)
    drt_b = await DistributedRuntime.in_process(store=main.store, bus=main.bus)
    mgr_a = await KvBlockManager(
        KvbmConfig(layout=layout, host_blocks=16)
    ).start()
    mgr_b = await KvBlockManager(
        KvbmConfig(layout=layout, host_blocks=16)
    ).start()
    parent = None
    for i, (h, data) in enumerate(rows):
        mgr_a.offer(h, parent, [i] * 4, data)
        parent = h
    await _settle(mgr_a, len(rows))
    comp_a = drt_a.namespace("kv").component("tpu")
    server = await PeerBlockServer(
        drt_a, comp_a, mgr_a, layout=layout, refresh_s=0.05
    ).start()
    comp_b = drt_b.namespace("kv").component("tpu")
    client = await PeerBlockClient(
        drt_b, comp_b, layout, layout_cfg=layout
    ).start()
    return mgr_a, mgr_b, server, client, (drt_a, drt_b)


async def _await_discovery(client, hashes, n, timeout=5.0):
    deadline = asyncio.get_running_loop().time() + timeout
    while client.best_peer(hashes)[1] < n:
        assert asyncio.get_running_loop().time() < deadline, (
            f"blockset never discovered: {client._blocksets}"
        )
        await asyncio.sleep(0.05)


async def test_pull_chain_byte_identity_int8_packed():
    """Packed int8 rows must arrive in B's host tier BIT-EXACT — the
    pull chain is a byte mover, never a repack."""
    main = await DistributedRuntime.in_process()
    rows = [(100, _packed_row(1)), (200, _packed_row(2)),
            (300, _packed_row(3))]
    mgr_a, mgr_b, server, client, drts = await _peer_pair(
        main, LAYOUT_INT8, rows
    )
    try:
        hashes = [100, 200, 300]
        await _await_discovery(client, hashes, 3)
        n = await client.pull_into(mgr_b, hashes)
        assert n == 3
        got = {h: d for h, _p, _t, d in mgr_b.match_host(hashes)}
        for h, want in rows:
            np.testing.assert_array_equal(
                np.asarray(got[h]).view(np.uint8),
                np.asarray(want).view(np.uint8),
            )
        # G4-origin attribution + telemetry.
        assert mgr_b.count_peer_origin(hashes) == 3
        st = client.stats()
        assert st["g4_pulls_total"] == 1
        assert st["g4_pull_bytes_total"] == 3 * LAYOUT_INT8.block_bytes
        assert st["link_peer_bps"] > 0
        # Re-pull is a no-op (already host-resident).
        assert await client.pull_into(mgr_b, hashes) == 0
    finally:
        await client.stop()
        await server.stop()
        await mgr_a.stop()
        await mgr_b.stop()
        for d in drts:
            await d.shutdown()
        await main.shutdown()


async def test_pull_chain_byte_identity_f32():
    """Full-precision rows transfer raw and land byte-identical."""
    main = await DistributedRuntime.in_process()
    rows = [(10, _row_f32(1.5)), (20, _row_f32(2.5))]
    mgr_a, mgr_b, server, client, drts = await _peer_pair(
        main, LAYOUT_F32, rows
    )
    try:
        await _await_discovery(client, [10, 20], 2)
        assert await client.pull_into(mgr_b, [10, 20]) == 2
        got = {h: d for h, _p, _t, d in mgr_b.match_host([10, 20])}
        np.testing.assert_array_equal(
            np.asarray(got[10]).view(np.float32), _row_f32(1.5)
        )
        np.testing.assert_array_equal(
            np.asarray(got[20]).view(np.float32), _row_f32(2.5)
        )
    finally:
        await client.stop()
        await server.stop()
        await mgr_a.stop()
        await mgr_b.stop()
        for d in drts:
            await d.shutdown()
        await main.shutdown()


async def test_mixed_precision_peer_refused():
    """An int8-packing peer must be REFUSED by a bf16 client (and vice
    versa) — blocks are never silently reinterpreted across quant."""
    main = await DistributedRuntime.in_process()
    drt_a = await DistributedRuntime.in_process(store=main.store, bus=main.bus)
    drt_b = await DistributedRuntime.in_process(store=main.store, bus=main.bus)
    mgr_a = await KvBlockManager(
        KvbmConfig(layout=LAYOUT_INT8, host_blocks=8)
    ).start()
    mgr_a.offer(100, None, [0] * 4, _packed_row(1))
    await _settle(mgr_a, 1)
    comp_a = drt_a.namespace("kv").component("tpu")
    server = await PeerBlockServer(
        drt_a, comp_a, mgr_a, layout=LAYOUT_INT8, refresh_s=0.05
    ).start()
    comp_b = drt_b.namespace("kv").component("tpu")
    client = await PeerBlockClient(
        drt_b, comp_b, LAYOUT_F32, layout_cfg=LAYOUT_F32
    ).start()
    try:
        assert layout_fingerprint(LAYOUT_INT8) != layout_fingerprint(
            LAYOUT_F32
        )
        # Give the watch time to deliver the (refused) blockset.
        await asyncio.sleep(0.3)
        assert client.best_peer([100]) == (None, 0)
        assert client.plan([100]) is None
        # A refused peer must not linger in the pricing table either.
        assert client._peer_bps == {}
    finally:
        await client.stop()
        await server.stop()
        await mgr_a.stop()
        for d in (drt_a, drt_b):
            await d.shutdown()
        await main.shutdown()


async def test_peer_death_mid_pull_degrades_to_recompute():
    """An armed kvbm.peer_pull partition (the peer dying mid-transfer,
    past the retry budget) must cost the pull — counted in
    g4_pull_fallbacks_total — and return 0, never hang or raise."""
    main = await DistributedRuntime.in_process()
    rows = [(100, _row_f32(1.0)), (200, _row_f32(2.0))]
    mgr_a, mgr_b, server, client, drts = await _peer_pair(
        main, LAYOUT_F32, rows
    )
    try:
        await _await_discovery(client, [100, 200], 2)
        FAULTS.arm("kvbm.peer_pull", "partition")
        try:
            n = await asyncio.wait_for(
                client.pull_into(mgr_b, [100, 200]), timeout=30
            )
        finally:
            FAULTS.disarm("kvbm.peer_pull")
        assert n == 0
        assert client.stats()["g4_pull_fallbacks_total"] == 1
        assert mgr_b.stats()["host_registered"] == 0
        # The tier heals: with the fault gone the same pull lands.
        assert await client.pull_into(mgr_b, [100, 200]) == 2
    finally:
        await client.stop()
        await server.stop()
        await mgr_a.stop()
        await mgr_b.stop()
        for d in drts:
            await d.shutdown()
        await main.shutdown()


# ---------------------------------------------------------------------------
# re-announce protocol
# ---------------------------------------------------------------------------


def test_parents_first_orders_chains():
    entries = [(300, 200, (3,)), (100, None, (1,)), (200, 100, (2,)),
               (500, 999, (5,))]  # 500's parent was evicted -> root
    out = _parents_first(entries)
    assert len(out) == 4
    pos = {h: i for i, (h, _p, _t) in enumerate(out)}
    assert pos[100] < pos[200] < pos[300]
    assert 500 in pos


async def test_reannounce_trigger_and_event_order():
    """A broadcast on the re-announce plane makes the worker republish
    every resident block as idempotent stored events, parents first."""
    main = await DistributedRuntime.in_process()
    comp = main.namespace("kv").component("tpu")
    published: list[KvCacheEventData] = []
    publisher = SimpleNamespace(publish=published.append)
    entries = [(300, 200, (3,)), (100, None, (1,)), (200, 100, (2,))]
    ann = await Reannouncer(
        main, comp, publisher, lambda: list(entries), interval_s=3600
    ).start()
    try:
        await request_reannounce(main, comp)
        deadline = asyncio.get_running_loop().time() + 5
        while ann.announces_total < 1:
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.02)
        assert [e.block_hashes[0] for e in published] == [100, 200, 300]
        assert all(e.kind == "stored" for e in published)
        assert published[1].parent_hash == 100
    finally:
        await ann.stop()
        await main.shutdown()


async def test_reannounce_rebuilds_rejoined_radix_view():
    """The PR 14 gap, closed: a radix view that missed the original
    stored events (replica rejoined after the fact) converges after one
    announce round — per-block events in parents-first order link the
    whole chain under the worker."""
    from dynamo_tpu.llm.kv_router.indexer import RadixTree

    tree = RadixTree()
    published: list[KvCacheEventData] = []
    publisher = SimpleNamespace(publish=published.append)
    entries = [(300, 200, (3,)), (100, None, (1,)), (200, 100, (2,))]
    ann = Reannouncer(
        SimpleNamespace(), SimpleNamespace(event_subject=lambda s: s),
        publisher, lambda: list(entries),
    )
    ann.announce()
    for ev in published:
        tree.apply_event(7, ev)
    assert tree.find_matches([100, 200, 300]).get(7) == 3
    # Idempotent: a second full announce changes nothing.
    published.clear()
    ann.announce()
    for ev in published:
        tree.apply_event(7, ev)
    assert tree.find_matches([100, 200, 300]).get(7) == 3


# ---------------------------------------------------------------------------
# prefix heat + pre-placement
# ---------------------------------------------------------------------------


def test_prefix_heat_ranks_and_decays():
    heat = PrefixHeat(max_prefixes=4, decay=0.9)
    for _ in range(5):
        heat.note([1, 2, 3])
    heat.note([9])
    top = heat.hottest(2)
    assert top[0] == [1, 2, 3]
    # Longest chain per prefix wins; heat accumulates on the leading hash.
    heat.note([1, 2, 3, 4])
    assert heat.hottest(1)[0] == [1, 2, 3, 4]
    # Bounded: coldest prefixes evict once the table is full.
    for h in (20, 30, 40, 50):
        heat.note([h], weight=10.0)
    assert len(heat.hottest(10)) <= 4


async def test_preplace_pushes_hottest_chains():
    """Pre-placement force-pulls the hottest chains into a joining
    worker's host tier BEFORE it takes traffic — no pricing gate."""
    main = await DistributedRuntime.in_process()
    rows = [(100, _row_f32(1.0)), (200, _row_f32(2.0)),
            (300, _row_f32(3.0))]
    mgr_a, mgr_b, server, client, drts = await _peer_pair(
        main, LAYOUT_F32, rows
    )
    try:
        await _await_discovery(client, [100, 200, 300], 3)
        heat = PrefixHeat()
        heat.note([100, 200, 300])
        heat.note([100, 200, 300])
        heat.note([777])  # nobody holds this one; preplace skips it
        landed = await preplace(client, mgr_b, heat)
        assert landed == 3
        assert mgr_b.count_peer_origin([100, 200, 300]) == 3
    finally:
        await client.stop()
        await server.stop()
        await mgr_a.stop()
        await mgr_b.stop()
        for d in drts:
            await d.shutdown()
        await main.shutdown()


# ---------------------------------------------------------------------------
# engine park/resume: the admission hook
# ---------------------------------------------------------------------------

_LAYOUT8 = KvLayoutConfig(
    num_layers=1, page_size=1, num_kv_heads=1, head_dim=4, dtype="float32"
)  # block_elems == 8: the mocker runner's 8-float block rows


def _ecfg(**kw):
    return EngineConfig(
        model=ModelConfig.tiny_test(),
        num_blocks=64,
        max_num_seqs=4,
        max_model_len=256,
        dtype="float32",
        **kw,
    )


async def _generate(engine, prompt, n=4):
    req = PreprocessedRequest(
        token_ids=list(prompt),
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=n, ignore_eos=True),
    )
    out = []
    async for item in engine.generate(Context(req.to_wire())):
        out += item.get("token_ids", [])
    return out


async def _warm_worker(main, prompt, seed=1):
    """A mocker worker that computed `prompt` and offloaded its blocks
    to the host tier, exported as a G4 peer."""
    drt = await DistributedRuntime.in_process(store=main.store, bus=main.bus)
    kvbm = await KvBlockManager(
        KvbmConfig(layout=_LAYOUT8, host_blocks=32)
    ).start()
    eng = MockerEngine(_ecfg(), MockerConfig(seed=seed, deterministic_tokens=True), block_manager=kvbm)
    await eng.start()
    toks = await _generate(eng, prompt)
    deadline = asyncio.get_running_loop().time() + 5
    while kvbm.stats()["host_registered"] < 2:
        assert asyncio.get_running_loop().time() < deadline
        await asyncio.sleep(0.05)
    comp = drt.namespace("kv").component("tpu")
    server = await PeerBlockServer(
        drt, comp, kvbm, layout=_LAYOUT8, refresh_s=0.05
    ).start()
    return drt, kvbm, eng, server, toks


async def test_engine_parks_for_peer_pull_and_reuses_g4():
    """Cold engine B misses G1/G2/G3 but a fleet peer announced the
    prompt's blocks: admission parks the request on the pull, the rows
    land in G2, and the actual-reuse split attributes them to the PEER
    tier on every metric surface."""
    main = await DistributedRuntime.in_process()
    prompt = list(range(40))  # 2 full blocks + tail
    drt_a, kvbm_a, eng_a, server, cold_toks = await _warm_worker(
        main, prompt
    )

    drt_b = await DistributedRuntime.in_process(store=main.store, bus=main.bus)
    kvbm_b = await KvBlockManager(
        KvbmConfig(layout=_LAYOUT8, host_blocks=32)
    ).start()
    comp_b = drt_b.namespace("kv").component("tpu")
    # Handshake on the mocker layout, but price with the calibrated
    # default geometry (layout_cfg=None): the 8-float sim rows are not
    # real KV bytes, and _LAYOUT8's page_size=1 would make every pull
    # lose to recomputing "one token" — a simulation artifact, not the
    # law under test.
    client = await PeerBlockClient(
        drt_b, comp_b, layout_fingerprint(_LAYOUT8)
    ).start()
    await _await_discovery(client, [h for h in kvbm_a.registered_hashes()], 1)
    kvbm_b.attach_peer_client(client)

    actuals: list[dict] = []
    eng_b = MockerEngine(
        _ecfg(), MockerConfig(seed=2, deterministic_tokens=True),
        block_manager=kvbm_b,
        on_kv_actual=actuals.append,
    )
    await eng_b.start()
    try:
        warm_toks = await _generate(eng_b, prompt)
        # Determinism across the tier: same greedy stream either way.
        assert warm_toks == cold_toks
        assert len(actuals) == 1
        rec = actuals[0]
        assert rec["peer_blocks"] == 2, rec
        assert rec["host_blocks"] == 0 and rec["disk_blocks"] == 0
        rd = eng_b.readiness()
        assert rd["kv_reused_peer_blocks_total"] == 2
        assert rd["kvbm_g4_pulls_total"] == 1
        assert rd["kvbm_g4_pull_bytes_total"] > 0
        assert rd["kvbm_g4_pull_fallbacks_total"] == 0
        assert rd["kvbm_link_peer_bps"] > 0
        assert eng_b.degraded_requests == 0
    finally:
        await eng_b.stop()
        await client.stop()
        await kvbm_b.stop()
        await server.stop()
        await eng_a.stop()
        await kvbm_a.stop()
        for d in (drt_a, drt_b):
            await d.shutdown()
        await main.shutdown()


async def test_engine_peer_timeout_degrades_not_hangs():
    """A pull stuck past kvbm_peer_timeout_s (delay-armed peer seam)
    must NOT stall the request: it resumes via local recompute, counted
    degraded, with the fallback on the G4 counters."""
    main = await DistributedRuntime.in_process()
    prompt = list(range(40))
    drt_a, kvbm_a, eng_a, server, cold_toks = await _warm_worker(
        main, prompt
    )

    drt_b = await DistributedRuntime.in_process(store=main.store, bus=main.bus)
    kvbm_b = await KvBlockManager(
        KvbmConfig(layout=_LAYOUT8, host_blocks=32)
    ).start()
    comp_b = drt_b.namespace("kv").component("tpu")
    # Handshake on the mocker layout, but price with the calibrated
    # default geometry (layout_cfg=None): the 8-float sim rows are not
    # real KV bytes, and _LAYOUT8's page_size=1 would make every pull
    # lose to recomputing "one token" — a simulation artifact, not the
    # law under test.
    client = await PeerBlockClient(
        drt_b, comp_b, layout_fingerprint(_LAYOUT8)
    ).start()
    await _await_discovery(client, [h for h in kvbm_a.registered_hashes()], 1)
    kvbm_b.attach_peer_client(client)

    eng_b = MockerEngine(
        _ecfg(kvbm_peer_timeout_s=0.2),
        MockerConfig(seed=2, deterministic_tokens=True),
        block_manager=kvbm_b,
    )
    await eng_b.start()
    FAULTS.arm("kvbm.peer_pull", "delay", times=None, delay_s=2.0)
    try:
        toks = await asyncio.wait_for(_generate(eng_b, prompt), timeout=30)
        assert toks == cold_toks  # recompute produced the same stream
        assert eng_b.degraded_requests == 1
        rd = eng_b.readiness()
        assert rd["kvbm_g4_pull_fallbacks_total"] >= 1
        assert rd["kv_reused_peer_blocks_total"] == 0
    finally:
        FAULTS.disarm("kvbm.peer_pull")
        await eng_b.stop()
        try:
            await kvbm_b.drain_pulls(timeout_s=10)
        except TimeoutError:
            pass
        await client.stop()
        await kvbm_b.stop()
        await server.stop()
        await eng_a.stop()
        await kvbm_a.stop()
        for d in (drt_a, drt_b):
            await d.shutdown()
        await main.shutdown()

"""Operator e2e over the REAL Kubernetes REST protocol (VERDICT r04 weak
#5): GraphOperator + operator/restkube.py against tests/k8s_apiserver.py
— bearer auth, server-side-apply PATCH, label-selector lists, streaming
watches, and CRD-gated GraphDeployment mirroring, all over an actual HTTP
socket. (No kubectl/kind/egress exists in this environment — see the
emulator's docstring for exactly what is and isn't real here; the same
RestKube client pointed at a genuine apiserver needs only
RestKube.in_cluster().)

Ports the FakeKube suite's happy path + drift repair; the drift-repair
leg goes through the REAL watch stream (HTTP chunked events → reader
thread → reconcile kick), not a test callback.
"""

import asyncio
import json

import pytest

from dynamo_tpu.operator import GraphOperator, STATUS_BUCKET
from dynamo_tpu.operator.restkube import RestKube
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.sdk.api_store import DEPLOYMENT_BUCKET

from k8s_apiserver import TOKEN, ApiServerEmulator

pytestmark = pytest.mark.anyio

SPEC = {
    "namespace": "dynamo",
    "services": {
        "ControlPlane": {"role": "control-plane"},
        "Frontend": {"role": "frontend", "port": 8080},
        "Worker": {"role": "worker", "replicas": 2, "chips": 4},
    },
}


async def _put_spec(drt, name, spec):
    await drt.bus.put_object(
        DEPLOYMENT_BUCKET, name,
        json.dumps({"name": name, "spec": spec, "revision": 1}).encode(),
    )


async def test_rest_operator_happy_path_and_drift_repair():
    api = await ApiServerEmulator().start()
    drt = await DistributedRuntime.in_process()
    kube = RestKube(api.url, token=TOKEN)
    # Short resync only as a safety net — drift repair below must arrive
    # via the watch stream well before it.
    op = GraphOperator(drt, kube, interval_s=5.0)
    try:
        await _put_spec(drt, "graph", SPEC)
        await op.start()
        status = await op.reconcile_once()

        # CRD installed over POST; custom-resource paths now serve.
        assert "graphdeployments.dynamo.tpu" in api.crds
        # Children exist in the emulator's store via server-side apply.
        assert ("deployments", "dynamo", "graph-worker") in api.objects
        assert ("services", "dynamo", "graph-frontend") in api.objects
        # GraphDeployment mirror carries spec + status.
        gd = api.objects[("graphdeployments", "dynamo", "graph")]
        assert gd["spec"]["services"]["Worker"]["replicas"] == 2
        assert gd["status"]["ready"] is False
        assert status["graph"]["ready"] is False

        # Steady state: a second pass applies nothing (spec-hash +
        # mirror-diff short-circuits).
        patches = api.patch_count
        await op.reconcile_once()
        assert api.patch_count == patches

        # Kubelet brings replicas up -> ready in status bucket AND mirror.
        for svc in ("controlplane", "frontend", "worker"):
            api.mark_ready("dynamo", f"graph-{svc}")
        status = await op.reconcile_once()
        assert status["graph"]["ready"] is True
        gd = api.objects[("graphdeployments", "dynamo", "graph")]
        assert gd["status"]["ready"] is True

        # Drift repair via the REAL watch: delete a child out-of-band;
        # the streamed DELETED event must kick a reconcile that restores
        # it, with no manual reconcile_once here.
        api.external_delete("deployments", "dynamo", "graph-worker")
        async def _restored():
            while ("deployments", "dynamo", "graph-worker") not in api.objects:
                await asyncio.sleep(0.05)
        await asyncio.wait_for(_restored(), 30)

        # Spec deletion garbage-collects children AND the mirror.
        await drt.bus.delete_object(DEPLOYMENT_BUCKET, "graph")
        await op.reconcile_once()
        assert not any(p == "deployments" for p, _, _ in api.objects)
        assert not any(
            p == "graphdeployments" for p, _, _ in api.objects
        )
        assert await drt.bus.list_objects(STATUS_BUCKET) == []
    finally:
        await op.stop()
        await drt.shutdown()
        await api.stop()


def test_crd_yaml_matches_packaged_constant():
    """deploy/k8s/crd-graphdeployment.yaml (manual installs) must stay in
    sync with resources.GRAPHDEPLOYMENT_CRD (what the operator actually
    installs — packaged trees have no deploy/ directory)."""
    import yaml
    from pathlib import Path

    from dynamo_tpu.operator.resources import GRAPHDEPLOYMENT_CRD

    on_disk = yaml.safe_load(
        (Path(__file__).resolve().parent.parent / "deploy" / "k8s"
         / "crd-graphdeployment.yaml").read_text()
    )
    assert on_disk == GRAPHDEPLOYMENT_CRD


async def test_rest_client_wire_discipline():
    """Protocol details a kubectl shim would hide: bearer auth is
    enforced, apply uses server-side-apply semantics, unknown custom
    resources 404 until their CRD lands. (Every client call runs in a
    worker thread — the emulator serves on this test's event loop, and
    blocking it would deadlock; the operator does the same via
    asyncio.to_thread.)"""
    import httpx

    def call(fn, *a):
        return asyncio.to_thread(fn, *a)

    api = await ApiServerEmulator().start()
    try:
        # Wrong token -> 401 surfaces as an HTTP error, not silence.
        bad = RestKube(api.url, token="wrong")
        with pytest.raises(httpx.HTTPStatusError):
            await call(bad.apply, {
                "apiVersion": "apps/v1", "kind": "Deployment",
                "metadata": {"name": "x", "namespace": "d"},
            })

        kube = RestKube(api.url, token=TOKEN)
        # Custom resource before CRD: 404, like a real cluster.
        with pytest.raises(httpx.HTTPStatusError):
            await call(kube.apply, {
                "apiVersion": "dynamo.tpu/v1alpha1",
                "kind": "GraphDeployment",
                "metadata": {"name": "g", "namespace": "d"},
            })
        import yaml
        from pathlib import Path

        crd = yaml.safe_load(
            (Path(__file__).resolve().parent.parent / "deploy" / "k8s"
             / "crd-graphdeployment.yaml").read_text()
        )
        await call(kube.ensure_crd, crd)
        await call(kube.ensure_crd, crd)  # idempotent (409 swallowed)
        await call(kube.apply, {
            "apiVersion": "dynamo.tpu/v1alpha1",
            "kind": "GraphDeployment",
            "metadata": {"name": "g", "namespace": "d",
                         "labels": {"app": "dynamo-tpu"}},
            "spec": {"services": {}},
        })
        assert await call(kube.get, "GraphDeployment", "d", "g") is not None
        assert len(await call(
            kube.list, "GraphDeployment", "d", {"app": "dynamo-tpu"}
        )) == 1
        assert await call(kube.delete, "GraphDeployment", "d", "g") is True
        assert await call(kube.delete, "GraphDeployment", "d", "g") is False
    finally:
        await api.stop()

"""Pallas kernel numerics: interpret-mode kernels vs the jnp oracles
(ops/attention.py) over ragged batches, GQA, prefix hits, idle lanes.
The same kernels compile under Mosaic on real TPU; interpret mode runs the
identical kernel code path on the CPU backend."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.ops.attention import (
    paged_decode_attention,
    paged_prefill_attention,
)
from dynamo_tpu.ops.pallas import (
    paged_decode_attention_pallas,
    paged_prefill_attention_pallas,
)

BS = 16  # block size


def _caches(rng, num_blocks, kvH, D, dtype=jnp.float32):
    shape = (num_blocks * BS, kvH, D)
    k = jnp.asarray(rng.standard_normal(shape), dtype)
    v = jnp.asarray(rng.standard_normal(shape), dtype)
    return k, v


def _tables(rng, B, max_blocks, num_blocks):
    """Disjoint block tables (block 0 is the trash block, never used)."""
    ids = rng.permutation(np.arange(1, num_blocks))[: B * max_blocks]
    return jnp.asarray(ids.reshape(B, max_blocks), jnp.int32)


@pytest.mark.parametrize("H,kvH,D", [(8, 8, 64), (8, 2, 64), (4, 1, 128)])
def test_decode_kernel_matches_oracle(H, kvH, D):
    rng = np.random.default_rng(0)
    B, max_blocks, num_blocks = 5, 4, 64
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    k_cache, v_cache = _caches(rng, num_blocks, kvH, D)
    tables = _tables(rng, B, max_blocks, num_blocks)
    # Ragged: full blocks, partial block, single token, inactive slot.
    ctx = jnp.asarray([64, 37, 1, 16, 0], jnp.int32)

    want = paged_decode_attention(q, k_cache, v_cache, tables, ctx, BS)
    got = paged_decode_attention_pallas(q, k_cache, v_cache, tables, ctx, BS)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
    assert not np.asarray(got[-1]).any()  # inactive slot stays zero


def test_decode_kernel_bf16():
    rng = np.random.default_rng(1)
    B, H, kvH, D, max_blocks, num_blocks = 3, 8, 4, 64, 3, 32
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.bfloat16)
    k_cache, v_cache = _caches(rng, num_blocks, kvH, D, jnp.bfloat16)
    tables = _tables(rng, B, max_blocks, num_blocks)
    ctx = jnp.asarray([48, 20, 5], jnp.int32)

    want = paged_decode_attention(q, k_cache, v_cache, tables, ctx, BS)
    got = paged_decode_attention_pallas(q, k_cache, v_cache, tables, ctx, BS)
    np.testing.assert_allclose(
        got.astype(jnp.float32), want.astype(jnp.float32), rtol=2e-2, atol=2e-2
    )


@pytest.mark.parametrize("H,kvH,D", [(8, 8, 64), (8, 2, 64)])
@pytest.mark.parametrize("q_tile", [8, 128])
def test_prefill_kernel_matches_oracle(H, kvH, D, q_tile):
    """Lanes with: no prefix, a prefix hit, padding (T > real tokens), and
    an idle lane — against the vmapped jnp oracle."""
    rng = np.random.default_rng(2)
    N, T, max_blocks, num_blocks = 4, 24, 4, 64
    q = jnp.asarray(rng.standard_normal((N, T, H, D)), jnp.float32)
    k_cache, v_cache = _caches(rng, num_blocks, kvH, D)
    tables = _tables(rng, N, max_blocks, num_blocks)
    q_start = jnp.asarray([0, 16, 0, 0], jnp.int32)   # lane 1: prefix hit
    total = jnp.asarray([24, 40, 10, 0], jnp.int32)   # lane 2 padded, 3 idle

    want = jax.vmap(
        lambda qq, bt, ps, tl: paged_prefill_attention(
            qq, k_cache, v_cache, bt, ps, tl, BS
        )
    )(q, tables, q_start, total)
    got = paged_prefill_attention_pallas(
        q, k_cache, v_cache, tables, q_start, total, BS, q_tile=q_tile
    )
    # Compare only REAL token rows: the oracle zeroes fully-masked padded
    # rows, the kernel lets them attend to valid keys (both are discarded
    # by the engine — only `last` real row feeds logits).
    for n in range(N):
        real = int(total[n]) - int(q_start[n])
        np.testing.assert_allclose(
            got[n, :real], want[n, :real], rtol=2e-5, atol=2e-5,
            err_msg=f"lane {n}",
        )


@pytest.mark.anyio
async def test_engine_end_to_end_pallas_interpret(monkeypatch):
    """Full engine (scheduler → padded cache → Pallas interpret kernels)
    must match the no-cache greedy oracle — covers the lane-padding path
    (tiny model D=32 → cache 128) exactly as the TPU runs it."""
    import asyncio

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import TpuEngine
    from dynamo_tpu.llm.protocols.common import (
        EngineOutput,
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.runtime.engine import Context

    monkeypatch.setenv("DYNAMO_TPU_PALLAS", "1")
    cfg = ModelConfig.tiny_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    engine = TpuEngine(
        EngineConfig(
            model=cfg, dtype="float32", block_size=8, num_blocks=32,
            max_num_seqs=2, max_model_len=64,
        ),
        params=params,
    )
    await engine.start()
    try:
        assert engine.runner.cache_head_dim == 128  # padded for the kernel
        prompts = [[3, 1, 4, 1, 5, 9, 2, 6, 5], [2, 7, 1]]

        async def run(prompt):
            req = PreprocessedRequest(
                token_ids=prompt,
                sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=5, ignore_eos=True),
            )
            toks = []
            async for raw in engine.generate(Context(req.to_wire())):
                toks += EngineOutput.from_wire(raw).token_ids
            return toks

        results = await asyncio.gather(*[run(p) for p in prompts])
        for prompt, toks in zip(prompts, results):
            want = []
            tokens = list(prompt)
            for _ in range(5):
                logits = llama.reference_forward(cfg, params, jnp.asarray(tokens))
                nxt = int(jnp.argmax(logits[-1]))
                tokens.append(nxt)
                want.append(nxt)
            assert toks == want, prompt
    finally:
        await engine.stop()


def test_prefill_kernel_matches_full_attention_end_to_end():
    """Scatter K/V into the cache then compare against plain causal
    attention — the full no-cache oracle."""
    from dynamo_tpu.ops.attention import full_causal_attention

    rng = np.random.default_rng(3)
    T, H, kvH, D, num_blocks = 40, 4, 2, 64, 16
    q = jnp.asarray(rng.standard_normal((T, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((T, kvH, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((T, kvH, D)), jnp.float32)

    k_cache = jnp.zeros((num_blocks * BS, kvH, D), jnp.float32)
    v_cache = jnp.zeros_like(k_cache)
    blocks = [1, 2, 3]  # 3 blocks cover 40 tokens
    slots = jnp.asarray(
        [blocks[t // BS] * BS + t % BS for t in range(T)], jnp.int32
    )
    k_cache = k_cache.at[slots].set(k)
    v_cache = v_cache.at[slots].set(v)
    table = jnp.asarray([blocks + [0]], jnp.int32)

    want = full_causal_attention(q, k, v)
    got = paged_prefill_attention_pallas(
        q[None], k_cache, v_cache, table,
        jnp.asarray([0], jnp.int32), jnp.asarray([T], jnp.int32), BS,
    )[0]
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_kernels_sliding_window_matches_oracle():
    """window-masked decode + prefill kernels vs the jnp reference."""
    rng = np.random.default_rng(9)
    B, H, kvH, D, max_blocks, num_blocks, W = 3, 8, 2, 128, 4, 64, 10
    q = jnp.asarray(rng.standard_normal((B, H, D)), jnp.float32)
    k_cache, v_cache = _caches(rng, num_blocks, kvH, D)
    tables = _tables(rng, B, max_blocks, num_blocks)
    ctx = jnp.asarray([64, 23, 0], jnp.int32)

    want = paged_decode_attention(
        q, k_cache, v_cache, tables, ctx, BS, window=W
    )
    got = paged_decode_attention_pallas(
        q, k_cache, v_cache, tables, ctx, BS, window=W
    )
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
    # And the window changed the answer vs full attention.
    full = paged_decode_attention_pallas(
        q, k_cache, v_cache, tables, ctx, BS
    )
    assert np.abs(np.asarray(got[0]) - np.asarray(full[0])).max() > 1e-4

    N, T = 2, 24
    qp = jnp.asarray(rng.standard_normal((N, T, H, D)), jnp.float32)
    ptables = _tables(rng, N, max_blocks, num_blocks)
    q_start = jnp.asarray([0, 16], jnp.int32)
    total = jnp.asarray([24, 40], jnp.int32)
    want_p = jax.vmap(
        lambda qq, bt, ps, tl: paged_prefill_attention(
            qq, k_cache, v_cache, bt, ps, tl, BS, window=W
        )
    )(qp, ptables, q_start, total)
    got_p = paged_prefill_attention_pallas(
        qp, k_cache, v_cache, ptables, q_start, total, BS, window=W
    )
    np.testing.assert_allclose(got_p, want_p, rtol=2e-5, atol=2e-5)

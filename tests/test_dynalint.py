"""dynalint: per-rule fixtures, suppression parsing, baseline semantics,
and the repo-wide no-new-findings gate that keeps CI honest.

Each rule gets a positive fixture (the bug shape it exists for — proves
the rule FIRES) and negative fixtures (the idiomatic fix — proves it
stays quiet). The repo-wide test at the bottom is the enforcement: it
fails the suite if anyone introduces a finding that is not in
tools/dynalint/baseline.json, and asserts the burn-down invariant that
DT001/DT002/DT003 have no grandfathered debt at all.
"""

from __future__ import annotations

import asyncio
import json
import textwrap
from pathlib import Path

import pytest

from tools.dynalint import (
    Baseline,
    all_rules,
    diff_against,
    lint_paths,
    lint_source,
)
from tools.dynalint.baseline import DEFAULT_BASELINE
from tools.dynalint.core import DEFAULT_TARGETS, parse_suppressions

REPO_ROOT = Path(__file__).resolve().parent.parent

# Paths that put fixtures in/out of the scoped rules' blast radius.
SEAM = "dynamo_tpu/engine/whatever.py"          # DT003 critical seam
STEP = "dynamo_tpu/engine/runner.py"            # DT005/DT006 step path
EDGE = "dynamo_tpu/llm/http_service.py"         # neither


def findings_for(src: str, path: str = "dynamo_tpu/x.py") -> list:
    return lint_source(textwrap.dedent(src), path)


def rules_of(findings) -> set[str]:
    return {f.rule for f in findings}


# -- registry ----------------------------------------------------------------

def test_registry_has_all_rules():
    ids = {r.id for r in all_rules()}
    assert {
        "DT001", "DT002", "DT003", "DT004", "DT005", "DT006",
        "DT007", "DT008", "DT009", "DT010", "DT011",
        "DT012", "DT013", "DT014", "DT015", "DT016",
    } <= ids


def test_dynaflow_rules_declare_requires_program():
    flags = {r.id: r.requires_program for r in all_rules()}
    # DT013 is deliberately per-file (a raw write is a local fact); the
    # other dynaflow laws need the whole program.
    assert flags["DT012"] and flags["DT014"]
    assert flags["DT015"] and flags["DT016"]
    assert not flags["DT013"]


def test_rule_metadata_complete():
    for r in all_rules():
        assert r.id and r.name and r.summary


# -- DT001: blocking call in async def ---------------------------------------

def test_dt001_fires_on_time_sleep():
    fs = findings_for("""
        import time
        async def handler():
            time.sleep(1)
    """)
    assert rules_of(fs) == {"DT001"}
    assert fs[0].line == 4


def test_dt001_fires_on_aliased_and_from_imports():
    fs = findings_for("""
        import time as _time
        from subprocess import run
        async def a():
            _time.sleep(1)
        async def b():
            run(["ls"])
    """)
    assert [f.rule for f in fs] == ["DT001", "DT001"]


def test_dt001_fires_on_result_open_and_pathlib_io():
    fs = findings_for("""
        async def f(fut, p):
            x = fut.result()
            with open("f") as fh:
                pass
            p.write_text("data")
    """)
    # The write_text line draws DT013 too (raw durable write in
    # dynamo_tpu/ scope) — two laws, one line, both real.
    dt001 = [f for f in fs if f.rule == "DT001"]
    assert len(dt001) == 3 and rules_of(fs) == {"DT001", "DT013"}


def test_dt001_quiet_outside_async_and_on_async_sleep():
    fs = findings_for("""
        import time, asyncio
        def sync():
            time.sleep(1)
        async def ok(fut):
            await asyncio.sleep(1)
            fut.result(timeout=5)
    """)
    assert fs == []


def test_dt001_skips_nested_sync_def():
    # The nested def is a definition, not an execution, in the coroutine.
    fs = findings_for("""
        import time
        async def outer():
            def inner():
                time.sleep(1)
            return inner
    """)
    assert fs == []


# -- DT002: discarded task ----------------------------------------------------

def test_dt002_fires_on_discarded_spawn():
    fs = findings_for("""
        import asyncio
        async def go(coro):
            asyncio.create_task(coro)
            asyncio.ensure_future(coro)
            _ = asyncio.create_task(coro)
    """)
    assert [f.rule for f in fs] == ["DT002"] * 3


def test_dt002_fires_on_loop_create_task_and_lambda():
    fs = findings_for("""
        import asyncio
        def go(loop, coro):
            loop.create_task(coro)
            loop.call_soon(lambda: asyncio.ensure_future(coro))
    """)
    assert [f.rule for f in fs] == ["DT002"] * 2


def test_dt002_quiet_when_retained():
    fs = findings_for("""
        import asyncio
        from dynamo_tpu.utils.task import spawn_tracked
        async def go(self, coro, tasks):
            t = asyncio.create_task(coro)
            self._task = asyncio.ensure_future(coro)
            tasks.append(asyncio.create_task(coro))
            spawn_tracked(coro)
            return t
    """)
    assert fs == []


# -- DT003: broad except swallows in critical seam ----------------------------

BROAD = """
    import logging
    def pump():
        try:
            work()
        except Exception:
            logging.exception("boom")
"""


def test_dt003_fires_in_seam_only():
    assert rules_of(findings_for(BROAD, SEAM)) == {"DT003"}
    assert findings_for(BROAD, EDGE) == []


def test_dt003_fires_on_bare_and_tuple_except():
    fs = findings_for("""
        def pump():
            try:
                work()
            except (ValueError, Exception):
                pass
            try:
                work()
            except:
                pass
    """, SEAM)
    assert [f.rule for f in fs] == ["DT003"] * 2


def test_dt003_quiet_on_reraise_or_narrow():
    fs = findings_for("""
        import logging
        def pump():
            try:
                work()
            except Exception:
                logging.exception("boom")
                raise
            try:
                work()
            except ValueError:
                pass
    """, SEAM)
    assert fs == []


# -- DT004: lock held across await --------------------------------------------

def test_dt004_fires_on_sync_lock_over_await():
    fs = findings_for("""
        async def f(self):
            with self._lock:
                await self.flush()
    """)
    assert rules_of(fs) == {"DT004"}


def test_dt004_quiet_on_async_lock_or_no_await():
    fs = findings_for("""
        async def f(self):
            async with self._lock:
                await self.flush()
            with self._lock:
                self.n += 1
    """)
    assert fs == []


# -- DT005: host sync on the step path ----------------------------------------

def test_dt005_fires_on_step_path_only():
    src = """
        import numpy as np
        def step(toks):
            out = np.asarray(toks)
            out.block_until_ready()
            return out.item()
    """
    assert [f.rule for f in findings_for(src, STEP)] == ["DT005"] * 3
    assert findings_for(src, EDGE) == []


def test_dt005_fires_on_device_get():
    fs = findings_for("""
        import jax
        def step(x):
            return jax.device_get(x)
    """, STEP)
    assert rules_of(fs) == {"DT005"}


# -- DT006: unbucketed shape --------------------------------------------------

def test_dt006_fires_on_raw_len_shape():
    fs = findings_for("""
        import numpy as np
        def build(tokens):
            return np.zeros((len(tokens), 4), np.int32)
    """, STEP)
    assert "DT006" in rules_of(fs)


def test_dt006_fires_on_len_arithmetic():
    fs = findings_for("""
        import jax.numpy as jnp
        def build(tokens):
            return jnp.zeros(2 * len(tokens) + 1)
    """, STEP)
    assert "DT006" in rules_of(fs)


def test_dt006_quiet_when_bucketed_or_static():
    fs = findings_for("""
        import numpy as np
        from dynamo_tpu.engine.compile_cache import _bucket
        def build(tokens, B):
            a = np.zeros(_bucket(len(tokens)), np.int32)
            b = np.zeros((B, 4), np.int32)
            return a, b
    """, STEP)
    assert "DT006" not in rules_of(fs)


def test_dt006_quiet_off_step_path():
    fs = findings_for("""
        import numpy as np
        def build(tokens):
            return np.zeros((len(tokens), 4))
    """, EDGE)
    assert fs == []


# -- dynarace thread-context model (DT007-DT010 substrate) --------------------

# A path with NO seed-registry entries: contexts come only from the
# annotations / async defs / spawn inference in the fixture itself.
RACE = "dynamo_tpu/somewhere/shared.py"


def test_context_model_annotation_seed_async_and_spawn():
    import ast as _ast

    from tools.dynalint.contexts import build_context_model
    from tools.dynalint.core import FileContext

    src = textwrap.dedent("""
        import asyncio, threading

        def annotated(self):  # dynarace: context[engine]
            pass

        # dynarace: context[control]
        def above(self):
            pass

        async def handler(self):
            pass

        def spawned():
            pass

        def start():
            threading.Thread(target=spawned, name="pump").start()

        def offloaded():
            pass

        async def go():
            await asyncio.to_thread(offloaded)
    """)
    ctx = FileContext(RACE, src, _ast.parse(src))
    model = build_context_model(ctx)
    assert model.of("annotated") == {"engine"}
    assert model.of("above") == {"control"}
    assert "loop" in model.of("handler")
    assert model.of("spawned") == {"thread:pump"}
    assert model.of("offloaded") == {"worker"}


def test_context_model_propagates_through_sync_calls_not_into_async():
    import ast as _ast

    from tools.dynalint.contexts import build_context_model
    from tools.dynalint.core import FileContext

    src = textwrap.dedent("""
        class Engine:
            def loop(self):  # dynarace: context[engine]
                self.helper()

            def helper(self):
                self.deeper()

            def deeper(self):
                pass

            async def coro(self):
                self.helper()

            async def other(self):
                pass
    """)
    ctx = FileContext(RACE, src, _ast.parse(src))
    model = build_context_model(ctx)
    # Transitive: engine flows loop -> helper -> deeper; the async caller
    # adds "loop" to helper/deeper too — a genuinely shared helper.
    assert model.of("Engine.helper") == {"engine", "loop"}
    assert model.of("Engine.deeper") == {"engine", "loop"}
    # Calling a coroutine function from a sync context is not execution:
    # async defs keep exactly their own loop context.
    assert model.of("Engine.other") == {"loop"}


# -- DT007: cross-context unlocked mutation -----------------------------------

DT007_POSITIVE = """
    class Stats:
        def bump(self):  # dynarace: context[engine]
            self.total += 1

        async def scrape_reset(self):
            self.total = 0
"""


def test_dt007_fires_on_cross_context_unlocked_write():
    fs = findings_for(DT007_POSITIVE, RACE)
    assert rules_of(fs) == {"DT007"}
    assert "Stats.total" in fs[0].message
    assert "engine" in fs[0].message and "loop" in fs[0].message


def test_dt007_quiet_when_locked_single_context_or_init():
    fs = findings_for("""
        class Stats:
            def __init__(self):  # dynarace: context[engine]
                self.total = 0          # constructors are exempt

            def bump(self):  # dynarace: context[engine]
                with self._lock:
                    self.total += 1

            async def reset(self):
                with self._lock:
                    self.total = 0

            def engine_only(self):  # dynarace: context[engine]
                self.steps += 1         # one context: fine
    """, RACE)
    assert "DT007" not in rules_of(fs)


def test_dt007_honors_locked_suffix_convention_and_module_globals():
    fs = findings_for("""
        TOTAL = 0

        class S:
            def _bump_locked(self):  # dynarace: context[engine]
                self.n += 1

            async def _also_locked(self):
                self._bump_locked()

        def w1():  # dynarace: context[engine]
            global TOTAL
            TOTAL += 1

        async def w2():
            global TOTAL
            TOTAL = 0
    """, RACE)
    # `_locked` helpers are reviewed as called-with-lock-held; the module
    # global written from two contexts still fires.
    msgs = [f.message for f in fs if f.rule == "DT007"]
    assert len(msgs) == 1 and "<module>.TOTAL" in msgs[0]


def test_dt007_ignores_files_without_annotations_or_seams():
    # Same mutation shape, but no seam path and no annotation: no model,
    # no finding — precision over recall.
    fs = findings_for(DT007_POSITIVE.replace(
        "  # dynarace: context[engine]", ""
    ), "dynamo_tpu/llm/protocols/openai.py")
    assert "DT007" not in rules_of(fs)


# -- DT008: lock-order inversion ----------------------------------------------

def test_dt008_fires_on_two_path_inversion():
    fs = findings_for("""
        class M:
            def a_then_b(self):
                with self._alock:
                    with self._block:
                        pass

            def b_then_a(self):
                with self._block:
                    with self._alock:
                        pass
    """, RACE)
    assert rules_of(fs) == {"DT008"}
    assert len(fs) == 1  # one finding per inverted pair, not per edge
    assert "M._alock" in fs[0].message and "M._block" in fs[0].message


def test_dt008_fires_on_nested_reacquisition_and_multi_item_with():
    fs = findings_for("""
        class M:
            def oops(self):
                with self._lock:
                    with self._lock:
                        pass

            def ok(self, other):
                with self._lock, other.pool_lock:
                    pass
    """, RACE)
    msgs = [f.message for f in fs if f.rule == "DT008"]
    assert len(msgs) == 1 and "reacquisition" in msgs[0]


def test_dt008_quiet_on_consistent_order_and_nested_defs():
    fs = findings_for("""
        class M:
            def one(self):
                with self._alock:
                    with self._block:
                        pass

            def two(self):
                with self._alock:
                    with self._block:
                        pass

            def three(self):
                with self._block:
                    def later():
                        # runs on another frame, not under _block
                        with self._alock:
                            pass
                    return later
    """, RACE)
    assert "DT008" not in rules_of(fs)


def test_dt008_distinguishes_same_attr_on_different_classes():
    fs = findings_for("""
        class A:
            def f(self):
                with self._lock:
                    with self.other._lock:
                        pass

        class B:
            def g(self):
                with self._lock:
                    with self.other._lock:
                        pass
    """, RACE)
    # A._lock -> self.other._lock and B._lock -> self.other._lock are
    # consistent edges, not an inversion.
    assert "DT008" not in rules_of(fs)


# -- DT009: loop-affinity violation -------------------------------------------

def test_dt009_fires_from_engine_context():
    fs = findings_for("""
        def deliver(self, fut, loop):  # dynarace: context[engine]
            loop.call_soon(fut.cancel)
            fut.set_result(1)
    """, RACE)
    assert [f.rule for f in fs] == ["DT009", "DT009"]


def test_dt009_quiet_on_threadsafe_crossings_loop_context_and_unknown():
    fs = findings_for("""
        import asyncio

        def deliver(self, fut, loop, coro):  # dynarace: context[engine]
            loop.call_soon_threadsafe(fut.set_result, 1)
            asyncio.run_coroutine_threadsafe(coro, loop)
            loop.call_soon_threadsafe(lambda: fut.set_result(2))

        async def on_loop(self, fut):
            fut.set_result(3)

        def unknown_context(fut):
            fut.set_result(4)
    """, RACE)
    assert "DT009" not in rules_of(fs)


# -- DT010: blocking work under a loop-shared lock ----------------------------

def test_dt010_fires_on_io_under_loop_shared_lock():
    fs = findings_for("""
        class Pool:
            async def probe(self):
                with self._lock:
                    n = self.count

            def transfer(self, storage, idx, data):  # dynarace: context[worker]
                with self._lock:
                    storage.write_block(idx, data)
    """, RACE)
    assert rules_of(fs) == {"DT010"}
    assert "write_block" in fs[0].message


def test_dt010_quiet_when_lock_never_touches_loop_or_io_outside():
    fs = findings_for("""
        class Pool:
            def transfer(self, storage, idx, data):  # dynarace: context[worker]
                with self._lock:
                    storage.write_block(idx, data)  # lock is worker-only

        class Tracer:
            async def snap(self):
                with self._lock:
                    pending = list(self._pending)
                self._recorder.flush()  # IO AFTER the lock released
    """, RACE)
    assert "DT010" not in rules_of(fs)


def test_dt010_awaited_calls_are_not_blocking():
    fs = findings_for("""
        class S:
            async def f(self):
                with self._lock:
                    await self.flush()
    """, RACE)
    # DT004's finding (lock across await), not DT010's.
    assert rules_of(fs) == {"DT004"}


# -- DT011: metric-surface parity ---------------------------------------------

ENGINE_SRC = """
    class TpuEngine:
        def _flush_side_channels(self):
            m = self.scheduler.metrics()
            m["engine_ready"] = 1
            m["special_total"] = self.special
            m.update(self._kvbm_gauges())

        def _kvbm_gauges(self):
            return {"kvbm_host_usage": 0.5}
"""

HTTP_SRC = """
class HttpService:
    async def _metrics(self, _request):
        for key in ("engine_ready",):
            self.metrics.set_gauge(key, 1.0)
        for key, val in eng.items():
            if key.startswith(("kvbm_",)):
                self.metrics.set_gauge(key, float(val))
"""

EXPORTER_SRC = """
_GAUGES = (
    ("engine_ready", "Ready"),
    ("special_total", "The special counter"),
    ("kvbm_host_usage", "Host usage"),
)
"""


def _parity(engine_src, http_src=HTTP_SRC, exporter_src=EXPORTER_SRC):
    import ast as _ast

    from tools.dynalint.core import FileContext
    from tools.dynalint.rules.dt011_metric_parity import parity_findings

    src = textwrap.dedent(engine_src)
    ctx = FileContext(
        "dynamo_tpu/engine/engine.py", src, _ast.parse(src)
    )
    return parity_findings(ctx, http_src, exporter_src)


def test_dt011_fires_on_each_missing_surface():
    fs = _parity(ENGINE_SRC)
    # special_total is not on the HTTP surface (no literal, no prefix).
    assert len(fs) == 1 and "special_total" in fs[0].message
    assert "http_service" in fs[0].message
    # Drop it from the exporter too: the message names both surfaces.
    fs2 = _parity(
        ENGINE_SRC,
        exporter_src="_GAUGES = ((\"engine_ready\", \"Ready\"),)",
    )
    missing = {f.message.split("`")[1] for f in fs2}
    assert missing == {"special_total", "kvbm_host_usage"}


def test_dt011_prefix_wildcards_and_full_parity_are_clean():
    clean = """
        class TpuEngine:
            def _flush_side_channels(self):
                m = {}
                m["engine_ready"] = 1
                m["kvbm_onboard_skips"] = 2   # covered by kvbm_ prefix
    """
    fs = _parity(
        clean,
        exporter_src="_GAUGES = ((\"engine_ready\", \"R\"),"
                     " (\"kvbm_onboard_skips\", \"S\"),)",
    )
    assert fs == []


def test_dt011_real_surfaces_have_parity():
    """The satellite's burn-down contract: today's tree has zero drift
    between the engine callback, HTTP /metrics, and the exporter."""
    import ast as _ast

    from tools.dynalint.core import FileContext
    from tools.dynalint.rules.dt011_metric_parity import parity_findings

    engine_p = REPO_ROOT / "dynamo_tpu/engine/engine.py"
    src = engine_p.read_text()
    ctx = FileContext(
        "dynamo_tpu/engine/engine.py", src, _ast.parse(src)
    )
    fs = parity_findings(
        ctx,
        (REPO_ROOT / "dynamo_tpu/llm/http_service.py").read_text(),
        (REPO_ROOT / "dynamo_tpu/llm/metrics_exporter.py").read_text(),
    )
    assert fs == [], "\n".join(f.render() for f in fs)


def test_dt011_exporter_names_all_exist_on_forward_pass_metrics():
    """The exporter reads every _GAUGES name off ForwardPassMetrics via
    getattr — a name missing there renders a scrape-time AttributeError,
    which is exactly the drift class DT011 exists to kill."""
    import ast as _ast

    from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics
    from tools.dynalint.rules.dt011_metric_parity import (
        exporter_metric_names,
    )

    tree = _ast.parse(
        (REPO_ROOT / "dynamo_tpu/llm/metrics_exporter.py").read_text()
    )
    m = ForwardPassMetrics()
    missing = [n for n in sorted(exporter_metric_names(tree))
               if not hasattr(m, n)]
    assert missing == []


# -- dynaflow: program model --------------------------------------------------

from tools.dynalint.callgraph import CallGraph  # noqa: E402
from tools.dynalint.program import (  # noqa: E402
    ProgramContext,
    module_name,
)

CG_SOURCES = {
    "pkg/util.py": "def helper():\n    return 1\n",
    "pkg/core.py": """\
from pkg.util import helper

class Engine:
    def step(self):
        self._advance()
        return helper()

    def _advance(self):
        pass

def schedule(cb):
    cb()

def job():
    pass

def kick():
    schedule(job)
""",
    "pkg/noise.py": """\
def distinctive_leaf():
    pass

def clear():
    pass

def touch(x):
    x.clear()
    x.distinctive_leaf()
""",
}


def test_module_name_mapping():
    assert module_name("a/b/c.py") == "a.b.c"
    assert module_name("a/b/__init__.py") == "a.b"
    assert module_name("bench.py") == "bench"


def test_program_symbol_table_and_indexes():
    prog = ProgramContext.from_sources(CG_SOURCES)
    assert "pkg/core.py::Engine.step" in prog.functions
    info = prog.functions["pkg/core.py::Engine.step"]
    assert info.terminal == "step" and info.class_name == "Engine"
    assert info.dotted == "pkg.core.Engine.step"
    assert prog.by_terminal["helper"] == ["pkg/util.py::helper"]
    assert prog.resolve_dotted("pkg.util.helper") == "pkg/util.py::helper"
    assert prog.find_method("Engine.step") == ["pkg/core.py::Engine.step"]
    assert set(prog.methods_of_class("Engine")) == {
        "pkg/core.py::Engine.step", "pkg/core.py::Engine._advance",
    }


def test_program_import_graph():
    prog = ProgramContext.from_sources(CG_SOURCES)
    # `from pkg.util import helper` resolves through the longest module
    # prefix: the symbol import still yields a file-level edge.
    assert prog.imports_of("pkg/core.py") == {"pkg/util.py"}
    assert prog.imports_of("pkg/util.py") == set()


def test_program_skips_unparseable_fixture_files():
    prog = ProgramContext.from_sources({
        "ok.py": "def f():\n    pass\n",
        "broken.py": "def f(:\n",
    })
    assert "ok.py" in prog.files and "broken.py" not in prog.files


# -- dynaflow: call graph -----------------------------------------------------

def test_callgraph_resolved_edges_self_samefile_and_import():
    graph = CallGraph.of(ProgramContext.from_sources(CG_SOURCES))
    assert graph.callees("pkg/core.py::Engine.step") == {
        "pkg/core.py::Engine._advance",  # self.method, same class
        "pkg/util.py::helper",           # import-resolved name
    }


def test_callgraph_callback_args_are_loose_only():
    graph = CallGraph.of(ProgramContext.from_sources(CG_SOURCES))
    kick = "pkg/core.py::kick"
    assert graph.callees(kick) == {"pkg/core.py::schedule"}
    # Being passed as an argument is "may be invoked": loose tier only.
    assert "pkg/core.py::job" in graph.callees(kick, loose=True)
    assert graph.reachable([kick]) == {kick, "pkg/core.py::schedule"}
    assert "pkg/core.py::job" in graph.reachable([kick], loose=True)


def test_callgraph_noise_terminals_create_no_edges():
    graph = CallGraph.of(ProgramContext.from_sources(CG_SOURCES))
    touch = "pkg/noise.py::touch"
    assert graph.callees(touch) == set()
    loose = graph.callees(touch, loose=True)
    # `x.distinctive_leaf()` gets the terminal-name over-approximation;
    # `x.clear()` is too generic to connect anything.
    assert loose == {"pkg/noise.py::distinctive_leaf"}


def test_callgraph_reaches_and_callers_closure():
    graph = CallGraph.of(ProgramContext.from_sources(CG_SOURCES))
    assert graph.reaches("pkg/core.py::Engine.step", ["pkg/util.py::helper"])
    assert not graph.reaches("pkg/core.py::kick", ["pkg/util.py::helper"])
    callers = graph.callers_closure(["pkg/util.py::helper"])
    assert "pkg/core.py::Engine.step" in callers
    assert "pkg/core.py::kick" not in callers


def test_callgraph_memoized_in_program_cache():
    prog = ProgramContext.from_sources(CG_SOURCES)
    assert CallGraph.of(prog) is CallGraph.of(prog)


# -- dynaflow rule harness ----------------------------------------------------

def program_findings(prog, path: str, rule_id: str) -> list:
    """Run one program rule over one fixture file with the fixture
    program attached — the shape lint_paths drives for real files."""
    rules = [r for r in all_rules() if r.id == rule_id]
    ctx = prog.files[path]
    return lint_source(ctx.source, path, rules, program=prog, ctx=ctx)


# -- DT012: integrity-envelope completeness -----------------------------------

INTEG_PATH = "dynamo_tpu/block_manager/integrity.py"

ENVELOPE_DOC = """\
The per-block CRC is computed exactly once, at the G1→G2 store law
(`Manager._store_host`), and verified at every trust boundary.

## Verification matrix

| Seam | Verify site | Counter split |
|------|-------------|---------------|
| host onboard | `Manager.verify_host` | `host` |

## Elsewhere
"""

ENVELOPE_SOURCES = {
    INTEG_PATH: (
        "def block_checksum(data):\n    return 1\n\n"
        "def verify_block(data, crc):\n    return True\n"
    ),
    "dynamo_tpu/block_manager/manager.py": """\
from dynamo_tpu.block_manager.integrity import block_checksum, verify_block
from dynamo_tpu.utils.faults import FAULTS

class Manager:
    def _store_host(self, data):
        crc = block_checksum(data)
        self.write_rows(data)
        return crc

    def write_rows(self, data):
        FAULTS.corrupt("kvbm.host", data)

    def verify_host(self, data, crc):
        return verify_block(data, crc)

class Rogue:
    def leak(self, data):
        FAULTS.corrupt("kvbm.rogue", data)
""",
}


def _envelope_program(tmp_path, doc: str | None = ENVELOPE_DOC):
    if doc is not None:
        d = tmp_path / "docs" / "architecture"
        d.mkdir(parents=True, exist_ok=True)
        (d / "integrity.md").write_text(doc)
    return ProgramContext.from_sources(ENVELOPE_SOURCES, root=tmp_path)


def test_dt012_parses_envelope_doc():
    from tools.dynalint.rules.dt012_integrity_envelope import (
        parse_envelope_doc,
    )

    stamp, rows = parse_envelope_doc(ENVELOPE_DOC)
    assert stamp == "Manager._store_host"
    assert rows == [("Manager.verify_host", "host")]


def test_dt012_fires_on_uncovered_corrupt_seam(tmp_path):
    prog = _envelope_program(tmp_path)
    fs = program_findings(
        prog, "dynamo_tpu/block_manager/manager.py", "DT012"
    )
    # write_rows sits under the stamping caller (_store_host) — covered;
    # Rogue.leak has no path to the envelope — injectable-but-
    # undetectable corruption, exactly one finding.
    assert len(fs) == 1 and "kvbm.rogue" in fs[0].message


def test_dt012_doc_row_naming_missing_function_fires(tmp_path):
    doc = ENVELOPE_DOC.replace("Manager.verify_host", "Manager.gone")
    prog = _envelope_program(tmp_path, doc)
    fs = program_findings(prog, INTEG_PATH, "DT012")
    assert any("Manager.gone" in f.message and "no such function"
               in f.message for f in fs)


def test_dt012_stamp_site_must_call_checksum_directly(tmp_path):
    srcs = dict(ENVELOPE_SOURCES)
    srcs["dynamo_tpu/block_manager/manager.py"] = srcs[
        "dynamo_tpu/block_manager/manager.py"
    ].replace("crc = block_checksum(data)", "crc = 0")
    d = tmp_path / "docs" / "architecture"
    d.mkdir(parents=True)
    (d / "integrity.md").write_text(ENVELOPE_DOC)
    prog = ProgramContext.from_sources(srcs, root=tmp_path)
    fs = program_findings(prog, INTEG_PATH, "DT012")
    assert any("does not call" in f.message for f in fs)


def test_dt012_quiet_without_doc(tmp_path):
    prog = _envelope_program(tmp_path, doc=None)
    assert program_findings(
        prog, "dynamo_tpu/block_manager/manager.py", "DT012"
    ) == []


def test_dt012_suppression(tmp_path):
    srcs = dict(ENVELOPE_SOURCES)
    srcs["dynamo_tpu/block_manager/manager.py"] = srcs[
        "dynamo_tpu/block_manager/manager.py"
    ].replace(
        'FAULTS.corrupt("kvbm.rogue", data)',
        'FAULTS.corrupt("kvbm.rogue", data)'
        "  # dynalint: allow[DT012] covered by an external scrubber",
    )
    d = tmp_path / "docs" / "architecture"
    d.mkdir(parents=True)
    (d / "integrity.md").write_text(ENVELOPE_DOC)
    prog = ProgramContext.from_sources(srcs, root=tmp_path)
    assert program_findings(
        prog, "dynamo_tpu/block_manager/manager.py", "DT012"
    ) == []


# -- DT013: atomic durability -------------------------------------------------

def test_dt013_fires_on_each_raw_write_shape():
    fs = findings_for("""
        import json, os
        def persist(path, doc, p):
            with open(path, "w") as fh:
                json.dump(doc, fh)
            os.replace(path + ".tmp", path)
            p.write_bytes(b"x")
            p.open("wb")
    """)
    dt013 = [f for f in fs if f.rule == "DT013"]
    assert len(dt013) == 5
    blob = " ".join(f.message for f in dt013)
    assert "open('w')" in blob and "json.dump" in blob
    assert "os.replace" in blob and "write_bytes" in blob


def test_dt013_quiet_on_reads_appends_and_blessed_module():
    src = """
        def ok(path, p, mode):
            open(path)
            open(path, "rb")
            open(path, "a")
            open(path, "r+b")
            open(path, mode)  # dynamic mode: not provable, not flagged
    """
    assert "DT013" not in rules_of(findings_for(src))
    raw = """
        import os
        def swap(a, b):
            os.replace(a, b)
    """
    # The blessed implementation itself, and out-of-scope paths, pass.
    assert findings_for(raw, "dynamo_tpu/utils/atomic_io.py") == []
    assert findings_for(raw, "tools/gen.py") == []


def test_dt013_suppression():
    fs = findings_for("""
        def prealloc(path, size):
            # dynalint: allow[DT013] arena pre-size, not durable state
            with open(path, "wb") as fh:
                fh.truncate(size)
    """)
    assert fs == []


# -- DT014: fault-point parity ------------------------------------------------

FAULT_SOURCES = {
    "dynamo_tpu/utils/faults.py": """\
KNOWN_FAULT_POINTS = (
    "seam.good",
    "seam.dead",
    "seam.unproven",
)
""",
    "dynamo_tpu/pipe.py": """\
from dynamo_tpu.utils.faults import FAULTS

def push(data):
    FAULTS.maybe_fail("seam.good")
    FAULTS.maybe_fail("seam.unproven")
    FAULTS.corrupt("seam.unregistered", data)
""",
    "tests/test_pipe.py": """\
from dynamo_tpu.utils.faults import FAULTS

def test_push():
    FAULTS.arm("seam.good", "raise", times=1)
""",
}


def test_dt014_flags_all_three_parity_breaks():
    prog = ProgramContext.from_sources(FAULT_SOURCES)
    site_fs = program_findings(prog, "dynamo_tpu/pipe.py", "DT014")
    assert len(site_fs) == 1
    assert "seam.unregistered" in site_fs[0].message
    reg_fs = program_findings(
        prog, "dynamo_tpu/utils/faults.py", "DT014"
    )
    msgs = {f.message.split("'")[1]: f.message for f in reg_fs}
    assert set(msgs) == {"seam.dead", "seam.unproven"}
    assert "no FAULTS.maybe_fail" in msgs["seam.dead"]
    assert "never armed" in msgs["seam.unproven"]


def test_dt014_quiet_when_three_legs_align():
    srcs = {
        "dynamo_tpu/utils/faults.py":
            'KNOWN_FAULT_POINTS = (\n    "seam.good",\n)\n',
        "dynamo_tpu/pipe.py": FAULT_SOURCES["dynamo_tpu/pipe.py"].split(
            "    FAULTS.maybe_fail(\"seam.unproven\")"
        )[0],
        "tests/test_pipe.py": FAULT_SOURCES["tests/test_pipe.py"],
    }
    prog = ProgramContext.from_sources(srcs)
    assert program_findings(prog, "dynamo_tpu/pipe.py", "DT014") == []
    assert program_findings(
        prog, "dynamo_tpu/utils/faults.py", "DT014"
    ) == []


def test_dt014_quiet_without_registry():
    prog = ProgramContext.from_sources({
        "dynamo_tpu/pipe.py": FAULT_SOURCES["dynamo_tpu/pipe.py"],
    })
    assert program_findings(prog, "dynamo_tpu/pipe.py", "DT014") == []


def test_dt014_suppression():
    srcs = dict(FAULT_SOURCES)
    srcs["dynamo_tpu/pipe.py"] = srcs["dynamo_tpu/pipe.py"].replace(
        'FAULTS.corrupt("seam.unregistered", data)',
        'FAULTS.corrupt("seam.unregistered", data)'
        "  # dynalint: allow[DT014] staging seam, registered next PR",
    )
    prog = ProgramContext.from_sources(srcs)
    assert program_findings(prog, "dynamo_tpu/pipe.py", "DT014") == []


# -- DT015: calibration single-source -----------------------------------------

CAL_SOURCES = {
    "dynamo_tpu/planner/calibration.py": """\
HANDOFF_GBPS = 21.7
KV_BYTES_PER_TOKEN = 32768
R04_ISL = 128
""",
    "dynamo_tpu/planner/thing.py": """\
rate = 21.7
kv = 32768
link_bps = 21.7e9
isl = 128
small_scaled = 21700.0
unrelated = 12345
""",
}


def test_dt015_flags_direct_and_scaled_shadows():
    prog = ProgramContext.from_sources(CAL_SOURCES)
    fs = program_findings(prog, "dynamo_tpu/planner/thing.py", "DT015")
    by_line = {f.line: f.message for f in fs}
    assert set(by_line) == {1, 2, 3}
    assert "HANDOFF_GBPS" in by_line[1]
    assert "KV_BYTES_PER_TOKEN" in by_line[2]
    assert "HANDOFF_GBPS (×1e+09)" in by_line[3]
    # 128 is under the int floor (R04_ISL was never collected); 21700.0
    # is a scaled match but below the 1e6 magnitude bar; 12345 matches
    # nothing — all three stay quiet.


def test_dt015_quiet_out_of_scope_and_without_calibration():
    prog = ProgramContext.from_sources({
        "dynamo_tpu/planner/thing.py":
            CAL_SOURCES["dynamo_tpu/planner/thing.py"],
    })
    # No calibration.py in the program: nothing to police.
    assert program_findings(
        prog, "dynamo_tpu/planner/thing.py", "DT015"
    ) == []
    rule = next(r for r in all_rules() if r.id == "DT015")
    assert not rule.applies_to("dynamo_tpu/llm/http_service.py")
    assert not rule.applies_to("dynamo_tpu/planner/calibration.py")


def test_dt015_suppression():
    srcs = dict(CAL_SOURCES)
    srcs["dynamo_tpu/planner/thing.py"] = (
        "rate = 21.7  # dynalint: allow[DT015] SI prefix table, not GB/s\n"
    )
    prog = ProgramContext.from_sources(srcs)
    assert program_findings(
        prog, "dynamo_tpu/planner/thing.py", "DT015"
    ) == []


# -- DT016: recompile hazards -------------------------------------------------

JIT_SOURCES = {
    "dynamo_tpu/llm/side.py": """\
import jax

def helper(x):
    if x.any():
        return 0
    return 1

def fwd(x):
    return helper(x)

_f = jax.jit(fwd)
""",
}


def test_dt016_flags_out_of_budget_site_and_traced_branch():
    prog = ProgramContext.from_sources(JIT_SOURCES)
    fs = program_findings(prog, "dynamo_tpu/llm/side.py", "DT016")
    msgs = [f.message for f in fs]
    assert len(fs) == 2
    assert any("budget ladder" in m for m in msgs)
    # helper is jit-reachable through fwd on the RESOLVED tier only —
    # a hazard claim must be defensible.
    assert any("branches on .any()" in m for m in msgs)


def test_dt016_budget_ladder_files_may_jit():
    prog = ProgramContext.from_sources({
        "dynamo_tpu/ops/fused.py":
            "import jax\n\ndef k(x):\n    return x\n\n_f = jax.jit(k)\n",
    })
    assert program_findings(prog, "dynamo_tpu/ops/fused.py", "DT016") == []


def test_dt016_flags_unhashable_static_default_partial_decorator():
    prog = ProgramContext.from_sources({
        "dynamo_tpu/llm/deco.py": """\
import jax
from functools import partial

@partial(jax.jit, static_argnames=("cfg",))
def fwd(x, cfg=[]):
    return x
""",
    })
    fs = program_findings(prog, "dynamo_tpu/llm/deco.py", "DT016")
    assert any("unhashable" in f.message and "`cfg`" in f.message
               for f in fs)


def test_dt016_flags_unhashable_static_argnums_call_shape():
    prog = ProgramContext.from_sources({
        "dynamo_tpu/llm/call.py": """\
import jax

def fn(x, opts={}):
    return x

_f = jax.jit(fn, static_argnums=(1,))
""",
    })
    fs = program_findings(prog, "dynamo_tpu/llm/call.py", "DT016")
    assert any("unhashable" in f.message and "`opts`" in f.message
               for f in fs)


def test_dt016_suppression():
    prog = ProgramContext.from_sources({
        "dynamo_tpu/llm/side.py": """\
import jax

def fwd(x):
    return x

# dynalint: allow[DT016] offline sidecar, one program per process
_f = jax.jit(fwd)
""",
    })
    assert program_findings(prog, "dynamo_tpu/llm/side.py", "DT016") == []


# -- dynaflow: lint_source / driver integration -------------------------------

def test_program_rules_skip_without_program():
    # DT015 would flag this literal, but a lone lint_source call has no
    # program: the rule (and its suppressions' hygiene) must stay out.
    fs = lint_source("rate = 21.7\n", "dynamo_tpu/planner/thing.py")
    assert fs == []
    fs = lint_source(
        "x = 1  # dynalint: allow[DT015] pinned for a reason\n",
        "dynamo_tpu/planner/thing.py",
    )
    assert fs == []  # unused-ness is undecidable without a program


def test_dynaflow_zero_findings_on_target_modules():
    """The acceptance gate: DT012–DT016 hold at zero findings (no
    baseline allowance) on the law's target modules, plus the linter's
    own tree and the bench drivers (self-lint satellite)."""
    rules = [r for r in all_rules()
             if r.id in {"DT012", "DT013", "DT014", "DT015", "DT016"}]
    fs = lint_paths(
        ["dynamo_tpu/block_manager", "dynamo_tpu/disagg",
         "dynamo_tpu/planner", "dynamo_tpu/engine",
         "tools", "benchmarks", "bench.py"],
        REPO_ROOT, rules,
    )
    assert fs == [], "\n".join(f.render() for f in fs)


# -- suppressions -------------------------------------------------------------

def test_suppression_inline_and_standalone():
    fs = findings_for("""
        import time
        async def f():
            time.sleep(1)  # dynalint: allow[DT001] admin path, loop idle here
            # dynalint: allow[DT001] second one, also justified
            time.sleep(2)
    """)
    assert fs == []


def test_suppression_requires_matching_rule():
    fs = findings_for("""
        import time
        async def f():
            time.sleep(1)  # dynalint: allow[DT005] wrong rule id
    """)
    # The DT001 finding survives AND the suppression reports unused.
    assert sorted(rules_of(fs)) == ["DT000", "DT001"]


def test_suppression_empty_reason_rejected():
    fs = findings_for("""
        import time
        async def f():
            time.sleep(1)  # dynalint: allow[DT001]
    """)
    # No free pass without a justification: original finding + DT000.
    assert sorted(rules_of(fs)) == ["DT000", "DT001"]


def test_suppression_unused_is_flagged():
    fs = findings_for("""
        def fine():
            return 1  # dynalint: allow[DT001] nothing actually fires here
    """)
    assert rules_of(fs) == {"DT000"}


def test_suppression_ignores_strings_and_multi_ids():
    src = textwrap.dedent("""
        DOC = "example: # dynalint: allow[DT001] not a real comment"
        import time
        async def f(fut):
            time.sleep(1); fut.result()  # dynalint: allow[DT001, DT001] both on this line
    """)
    assert lint_source(src, "dynamo_tpu/x.py") == []
    sups, problems = parse_suppressions(src)
    assert len(sups) == 1 and problems == []


def test_suppression_malformed_marker_reported():
    fs = findings_for("""
        x = 1  # dynalint: allow me everything
    """)
    assert rules_of(fs) == {"DT000"}


# -- baseline semantics -------------------------------------------------------

def _mkfindings(src: str, path: str):
    return lint_source(textwrap.dedent(src), path)


def test_baseline_grandfathers_then_catches_new(tmp_path):
    old = _mkfindings("""
        import time
        async def f():
            time.sleep(1)
    """, "m.py")
    base = Baseline.from_findings(old)
    # Same debt: clean.
    d = diff_against(old, base)
    assert d.new == [] and len(d.known) == 1 and d.stale == {}
    # A SECOND identical finding in the same file is new debt (counted keys).
    more = _mkfindings("""
        import time
        async def f():
            time.sleep(1)
        async def g():
            time.sleep(1)
    """, "m.py")
    d2 = diff_against(more, base)
    assert len(d2.new) == 1 and len(d2.known) == 1


def test_baseline_expires_fixed_findings(tmp_path):
    old = _mkfindings("""
        import time
        async def f():
            time.sleep(1)
    """, "m.py")
    base = Baseline.from_findings(old)
    d = diff_against([], base)
    assert d.new == [] and len(d.stale) == 1
    # --update-baseline semantics: rebuilt from current findings, debt gone.
    assert Baseline.from_findings([]).entries == {}


def test_baseline_save_load_roundtrip_and_version(tmp_path):
    f = _mkfindings("""
        import time
        async def f():
            time.sleep(1)
    """, "m.py")
    p = tmp_path / "b.json"
    Baseline.from_findings(f).save(p)
    assert Baseline.load(p).entries == Baseline.from_findings(f).entries
    data = json.loads(p.read_text())
    data["version"] = 99
    p.write_text(json.dumps(data))
    with pytest.raises(ValueError):
        Baseline.load(p)


def test_baseline_keys_are_line_insensitive():
    a = _mkfindings("import time\nasync def f():\n    time.sleep(1)\n", "m.py")
    b = _mkfindings(
        "import time\n\n\n\nasync def f():\n    time.sleep(1)\n", "m.py"
    )
    assert [x.key() for x in a] == [x.key() for x in b]
    assert a[0].line != b[0].line


# -- CLI ----------------------------------------------------------------------

def test_cli_list_rules_and_bad_select(capsys):
    from tools.dynalint.__main__ import main

    assert main(["--list-rules"]) == 0
    assert "DT003" in capsys.readouterr().out
    assert main(["--select", "DT999"]) == 2


def test_cli_flags_synthetic_violation(tmp_path, capsys):
    """The ci.sh contract: a new violation anywhere in the tree fails the
    run even with the baseline in place."""
    from tools.dynalint.__main__ import main

    bad = tmp_path / "bad.py"
    bad.write_text(
        "import time\nasync def f():\n    time.sleep(1)\n"
    )
    rc = main([str(bad), "--baseline", DEFAULT_BASELINE])
    out = capsys.readouterr().out
    assert rc == 1 and "DT001" in out


def test_select_does_not_flag_unselected_suppressions_unused():
    """`--select DT001` must not report every allow[DT003] as dead: a
    suppression's usage is only decidable when its rules actually ran."""
    src = textwrap.dedent("""
        import logging
        def pump():
            try:
                work()
            # dynalint: allow[DT003] degrade path, see ledger
            except Exception:
                logging.exception("boom")
    """)
    dt001 = [r for r in all_rules() if r.id == "DT001"]
    assert lint_source(src, SEAM, dt001) == []
    # Full run: the suppression is used — still clean.
    assert lint_source(src, SEAM) == []


def test_cli_update_baseline_refuses_narrowed_scope(capsys):
    from tools.dynalint.__main__ import main

    assert main(["--select", "DT001", "--update-baseline"]) == 2
    assert main(["dynamo_tpu/engine", "--update-baseline"]) == 2
    assert "default scope" in capsys.readouterr().err


def test_spawn_tracked_prunes_tasks_from_closed_loops():
    from dynamo_tpu.utils.task import spawn_tracked, tracked_tasks

    async def hang(evt):
        await evt.wait()

    loop = asyncio.new_event_loop()
    try:
        evt = asyncio.Event()
        t = loop.run_until_complete(
            asyncio.wait_for(_spawn_pending(spawn_tracked, hang, evt), 5)
        )
        assert not t.done()
    finally:
        loop.close()
    # The loop died with the task still pending: the strong ref must not
    # outlive it. tracked_tasks() (and the next spawn) prunes it.
    assert t not in tracked_tasks()


async def _spawn_pending(spawn_tracked, hang, evt):
    task = spawn_tracked(hang(evt), name="pending-forever")
    await asyncio.sleep(0)
    return task


# -- repo-wide gate -----------------------------------------------------------

def test_repo_has_no_new_findings_vs_baseline():
    findings = lint_paths(list(DEFAULT_TARGETS), REPO_ROOT)
    baseline = Baseline.load(REPO_ROOT / DEFAULT_BASELINE)
    d = diff_against(findings, baseline)
    msg = "\n".join(f.render() for f in d.new)
    assert d.new == [], f"new dynalint findings (fix or justify):\n{msg}"
    assert d.stale == {}, (
        "stale baseline entries — run `python -m tools.dynalint "
        f"--update-baseline`: {sorted(d.stale)}"
    )


def test_baseline_burned_down_for_critical_rules():
    """The burn-down invariant: no grandfathered blocking-call,
    discarded-task, or swallowed-exception debt — and since the dynarace
    PR emptied the last DT005 entries, no grandfathered debt AT ALL. New
    findings cannot enter (previous test); every deliberate exception in
    the tree is a reasoned in-file suppression, not a baseline row."""
    baseline = Baseline.load(REPO_ROOT / DEFAULT_BASELINE)
    critical = [
        k for k in baseline.entries
        if k.split("::")[1] in {"DT000", "DT001", "DT002", "DT003", "DT005"}
    ]
    assert critical == []
    assert baseline.entries == {}, (
        "the baseline was emptied in the dynarace PR and must stay empty "
        "— fix new findings or suppress them in-file with a reason: "
        f"{sorted(baseline.entries)}"
    )

"""Mesh/sharding tests on the 8-device virtual CPU mesh (conftest.py).

Mirrors how the reference tests distributed behavior without hardware
(reference: lib/runtime/tests/common/mock.rs mock network); here the mock
is XLA's host-platform device override.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.runner import ModelRunner
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.parallel.mesh import MESH_AXES, build_mesh
from dynamo_tpu.parallel.sharding import shard_params
from dynamo_tpu.parallel.train import make_train_step


def test_build_mesh_defaults_to_tp():
    mesh = build_mesh()
    assert mesh.shape["tp"] == len(jax.devices())
    assert mesh.axis_names == MESH_AXES


def test_build_mesh_explicit_shape():
    mesh = build_mesh({"dp": 2, "tp": 2, "sp": 2})
    assert mesh.shape == {"dp": 2, "sp": 2, "ep": 1, "tp": 2}


def test_build_mesh_bad_shape():
    with pytest.raises(ValueError):
        build_mesh({"dp": 3, "tp": 3})


def test_sharded_decode_matches_single_device():
    """TP-sharded engine step must produce identical tokens to unsharded."""
    cfg = ModelConfig.tiny_test()
    ecfg = EngineConfig(
        model=cfg, num_blocks=32, max_num_seqs=4, max_model_len=64,
        dtype="float32",
    )
    prompt = [5, 9, 2, 7, 11, 3]

    def run(mesh):
        runner = ModelRunner(ecfg, mesh=mesh, rng_seed=0)
        toks = [runner.prefill(prompt, [1], 0, (0.0, 0, 1.0))]
        n = len(prompt)
        for _ in range(4):
            B = ecfg.max_num_seqs
            table = np.zeros((B, ecfg.max_blocks_per_seq), np.int32)
            table[0, 0] = 1
            out = runner.decode(
                np.array([toks[-1]] + [0] * (B - 1), np.int32),
                np.array([n] + [0] * (B - 1), np.int32),
                table,
                np.array([n + 1] + [0] * (B - 1), np.int32),
                np.array([16 + n] + [0] * (B - 1), np.int32),
                np.zeros(B, np.float32),
                np.zeros(B, np.int32),
                np.ones(B, np.float32),
            )
            toks.append(int(out[0]))
            n += 1
        return toks

    single = run(None)
    sharded = run(build_mesh({"dp": 2, "tp": 2, "sp": 2}))
    assert single == sharded


def test_sharded_pallas_decode_matches_single_device_jnp(monkeypatch):
    """The Pallas kernels under shard_map over tp (interpret mode on CPU)
    must produce the same tokens as the single-chip jnp path — the gate
    VERDICT r02 asked for before trusting TP-sharded serving perf."""
    cfg = ModelConfig.tiny_test()
    ecfg = EngineConfig(
        model=cfg, num_blocks=32, max_num_seqs=4, max_model_len=64,
        dtype="float32",
    )
    prompt = [5, 9, 2, 7, 11, 3]

    def run(mesh, pallas: bool):
        monkeypatch.setenv("DYNAMO_TPU_PALLAS", "1" if pallas else "0")
        runner = ModelRunner(ecfg, mesh=mesh, rng_seed=0)
        assert runner.attn.use_pallas is pallas
        if pallas and mesh is not None:
            assert runner.attn.mesh is mesh  # shard_map path, not fallback
        toks = [runner.prefill(prompt, [1], 0, (0.0, 0, 1.0))]
        n = len(prompt)
        B = ecfg.max_num_seqs
        table = np.zeros((B, ecfg.max_blocks_per_seq), np.int32)
        table[0, :4] = [1, 2, 3, 4]
        out = runner.decode_multi(
            np.array([toks[-1]] + [0] * (B - 1), np.int32),
            np.array([n] + [0] * (B - 1), np.int32),
            table,
            np.array([n + 1] + [0] * (B - 1), np.int32),
            np.zeros(B, np.float32),
            np.zeros(B, np.int32),
            np.ones(B, np.float32),
            4,
        )
        return toks + [int(t) for t in out[:, 0]]

    baseline = run(None, pallas=False)
    assert run(build_mesh({"dp": 4, "tp": 2}), pallas=True) == baseline
    assert run(build_mesh({"dp": 2, "tp": 2, "sp": 2}), pallas=True) == baseline


def test_train_step_runs_and_learns():
    mesh = build_mesh({"dp": 2, "tp": 2, "sp": 2})
    cfg = ModelConfig.tiny_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg, dtype=jnp.float32)
    params = shard_params(params, mesh, cfg=cfg)
    step = make_train_step(cfg, mesh, lr=1e-2)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size)
    params, loss0 = step(params, tokens)
    for _ in range(5):
        params, loss = step(params, tokens)
    assert float(loss) < float(loss0)


def test_moe_ep_sharded_matches_replicated():
    """The ep-sharded MoE layer (models/moe.py) must match an unsharded
    run bit-for-time: GSPMD turns the expert-dim contractions into psums
    over ep, never changing the math (stage-5 prerequisite, BASELINE.md)."""
    import numpy as np

    from dynamo_tpu.models.moe import (
        MoeConfig,
        init_moe_params,
        moe_mlp,
        shard_moe_params,
    )

    cfg = MoeConfig(num_experts=8, num_experts_per_tok=2)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (16, cfg.hidden_size))

    ref = moe_mlp(params, x, cfg)
    assert np.isfinite(np.asarray(ref)).all()

    mesh = build_mesh({"dp": 2, "ep": 2, "tp": 2})
    sharded = jax.jit(lambda p, x: moe_mlp(p, x, cfg))(
        shard_moe_params(params, mesh), x
    )
    np.testing.assert_allclose(
        np.asarray(sharded), np.asarray(ref), rtol=2e-5, atol=2e-5
    )
    assert ref.shape == x.shape
    # Router sparsity: exactly top-k experts carry gate mass per token and
    # the renormalized softmax sums to 1.
    from dynamo_tpu.models.moe import moe_router

    gates = np.asarray(moe_router(params, x, cfg))
    assert ((gates > 0).sum(axis=-1) == cfg.num_experts_per_tok).all()
    np.testing.assert_allclose(gates.sum(axis=-1), 1.0, rtol=1e-5)


def test_sequence_parallel_prefill_matches_single_device(monkeypatch):
    """sp-sharded prefill (each shard's query tile vs full KV, Pallas under
    shard_map with per-shard q_start offsets) must produce the same first
    token and decode continuation as a single chip — the long-context
    sequence-parallel path SURVEY §5 requires natively."""
    cfg = ModelConfig.tiny_test()
    ecfg = EngineConfig(
        model=cfg, num_blocks=64, max_num_seqs=4, max_model_len=128,
        dtype="float32",
    )
    prompt = list(range(1, 49))  # 48 tokens -> bucket 64, sp=4 divides

    def run(mesh, pallas: bool):
        monkeypatch.setenv("DYNAMO_TPU_PALLAS", "1" if pallas else "0")
        runner = ModelRunner(ecfg, mesh=mesh, rng_seed=0)
        blocks = [1, 2, 3, 4]
        first = runner.prefill(prompt, blocks, 0, (0.0, 0, 1.0))
        B = ecfg.max_num_seqs
        table = np.zeros((B, ecfg.max_blocks_per_seq), np.int32)
        table[0, : len(blocks)] = blocks
        n = len(prompt)
        out = runner.decode_multi(
            np.array([first] + [0] * (B - 1), np.int32),
            np.array([n] + [0] * (B - 1), np.int32),
            table,
            np.array([n + 1] + [0] * (B - 1), np.int32),
            np.zeros(B, np.float32),
            np.zeros(B, np.int32),
            np.ones(B, np.float32),
            4,
        )
        return [first] + [int(t) for t in out[:, 0]]

    baseline = run(None, pallas=False)
    assert run(build_mesh({"sp": 4, "tp": 2}), pallas=True) == baseline
    # batched-prefill lanes under sp too
    monkeypatch.setenv("DYNAMO_TPU_PALLAS", "1")
    runner = ModelRunner(ecfg, mesh=build_mesh({"sp": 4, "tp": 2}), rng_seed=0)
    lanes = [
        (prompt, [1, 2, 3, 4], 0, (0.0, 0, 1.0)),
        (prompt[:20], [5, 6], 0, (0.0, 0, 1.0)),
    ]
    toks = runner.prefill_batch(lanes)
    assert toks[0] == baseline[0]


def test_moe_model_ep_sharded_serving_matches_single_device(monkeypatch):
    """Mixtral-style MoE model under an ep×tp mesh: expert-parallel routed
    MLPs in the serving prefill/decode path must produce tokens identical
    to the single-device runner (the DeepSeek-R1/Mixtral stage-5 serving
    prerequisite — BASELINE.md stage 5)."""
    cfg = ModelConfig.tiny_moe_test()
    ecfg = EngineConfig(
        model=cfg, num_blocks=64, max_num_seqs=4, max_model_len=128,
        dtype="float32",
    )
    prompt = list(range(3, 35))  # 32 tokens

    def run(mesh):
        runner = ModelRunner(ecfg, mesh=mesh, rng_seed=1)
        blocks = [1, 2, 3]
        first = runner.prefill(prompt, blocks, 0, (0.0, 0, 1.0))
        B = ecfg.max_num_seqs
        table = np.zeros((B, ecfg.max_blocks_per_seq), np.int32)
        table[0, : len(blocks)] = blocks
        n = len(prompt)
        out = runner.decode_multi(
            np.array([first] + [0] * (B - 1), np.int32),
            np.array([n] + [0] * (B - 1), np.int32),
            table,
            np.array([n + 1] + [0] * (B - 1), np.int32),
            np.zeros(B, np.float32),
            np.zeros(B, np.int32),
            np.ones(B, np.float32),
            8,
        )
        return [first] + [int(t) for t in out[:, 0]]

    baseline = run(None)
    assert run(build_mesh({"ep": 2, "tp": 2, "dp": 2})) == baseline
    assert run(build_mesh({"ep": 4, "tp": 2})) == baseline


def test_ring_attention_matches_full_causal():
    """Ring attention (K/V sharded over sp, blocks rotating via ppermute
    with an online-softmax fold) must match plain causal attention — the
    long-context primitive whose per-chip memory is O(T/n)."""
    from dynamo_tpu.ops.attention import full_causal_attention
    from dynamo_tpu.ops.ring_attention import ring_attention_sharded

    T, H, kvH, D = 64, 4, 2, 16
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (T, H, D), jnp.float32)
    k = jax.random.normal(kk, (T, kvH, D), jnp.float32)
    v = jax.random.normal(kv, (T, kvH, D), jnp.float32)

    ref = full_causal_attention(q, k, v)
    for sp in (2, 4, 8):
        mesh = build_mesh({"sp": sp, "tp": 1, "dp": 8 // sp})
        got = ring_attention_sharded(mesh, q, k, v)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5,
            err_msg=f"sp={sp}",
        )


def test_llama70b_kv_sp_tp_sharded_step_lowers():
    """Scale proof at the compile-shape level (BASELINE.md steps 4-5):
    the REAL Llama-3-70B config's decode step traces and lowers under a
    {tp: 4, sp: 2} mesh with the kv_sp slot+head-sharded cache —
    abstract params only (280 GB of weights never materialize), so this
    validates shape/divisibility/sharding-spec consistency for the
    beyond-chip target that cannot run in this environment."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from dynamo_tpu.models import llama
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.ops.attention import AttnDispatch
    from dynamo_tpu.parallel.sharding import kv_cache_spec, llama_param_specs

    cfg = ModelConfig.llama3_70b()
    mesh = build_mesh({"tp": 4, "sp": 2})
    bs, num_blocks, B, max_blocks = 16, 64, 4, 16

    params_avals = jax.eval_shape(
        lambda k: llama.init_params(k, cfg, dtype=jnp.bfloat16),
        jax.random.PRNGKey(0),
    )
    params_avals = jax.tree.map(
        lambda a, s: jax.ShapeDtypeStruct(
            a.shape, a.dtype, sharding=NamedSharding(mesh, s)
        ),
        params_avals,
        llama_param_specs(cfg),
        is_leaf=lambda x: isinstance(x, P),
    )
    kv_sh = NamedSharding(mesh, kv_cache_spec(cfg.is_mla, sp=True))
    kv_shape = (num_blocks * bs, cfg.num_cache_heads, cfg.kv_cache_head_dim)
    kv_avals = [
        (
            jax.ShapeDtypeStruct(kv_shape, jnp.bfloat16, sharding=kv_sh),
            jax.ShapeDtypeStruct(kv_shape, jnp.bfloat16, sharding=kv_sh),
        )
        for _ in range(cfg.num_layers)
    ]
    attn = AttnDispatch(use_pallas=False, mesh=mesh, kv_sp=True)

    def step(params, kv, toks, pos, tables, ctx, slots):
        return llama.decode(
            cfg, params, kv, toks, pos, tables, ctx, slots, bs, attn=attn
        )

    lowered = jax.jit(step).lower(
        params_avals,
        kv_avals,
        jax.ShapeDtypeStruct((B,), jnp.int32),
        jax.ShapeDtypeStruct((B,), jnp.int32),
        jax.ShapeDtypeStruct((B, max_blocks), jnp.int32),
        jax.ShapeDtypeStruct((B,), jnp.int32),
        jax.ShapeDtypeStruct((B,), jnp.int32),
    )
    # The lowered module exists and carries the mesh's axes.
    assert lowered.as_text()  # non-empty StableHLO


def test_stepcast_replays_every_block_io_form():
    """Multi-host lockstep invariant: every runner method that issues a
    device program over the sharded caches must be in REPLAYED, or rank 0
    issues SPMD programs followers never see and the mesh deadlocks
    (parallel/stepcast.py docstring). Block IO has per-block AND batched
    forms; all of them must replay."""
    from dynamo_tpu.parallel.stepcast import REPLAYED

    for name in (
        "prefill", "prefill_batch", "decode_multi",
        "gather_block", "scatter_block",
        "gather_many", "gather_many_device",
        "scatter_many", "scatter_many_device",
    ):
        assert name in REPLAYED, name

"""Per-matmul weight-quantization policy (docs/architecture/
weight_quant.md): qdot's exact XLA-twin contract per matmul site,
quantize-on-load parity, engine-vs-oracle exactness, TP-sharded token
equality, the REAL-engine greedy quality gate, config validation, the
calibration weight-bytes term, mocker pricing, the BENCH_WQUANT
equal-budget math, and DT011 gauge-surface parity.

The reference reaches quantized serving through its backend engines
(its headline disagg numbers are FP8-70B via vLLM, reference:
docs/architecture/architecture.md:75-79); our engine is native, so the
per-site weight policy is first-class and tested like any other model
path.
"""

import asyncio
import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.engine.runner import ModelRunner
from dynamo_tpu.llm.protocols.common import (
    EngineOutput,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.ops.quant import (
    ATTN_KEYS,
    FP8_DTYPE,
    MLP_KEYS,
    dequantize_weight,
    is_quantized,
    qdot,
    quantize_param_specs_policy,
    quantize_params_policy,
    quantize_weight,
    quant_tree_stats,
)
from dynamo_tpu.parallel.mesh import build_mesh
from dynamo_tpu.parallel.sharding import llama_param_specs
from dynamo_tpu.runtime.engine import Context

pytestmark = pytest.mark.anyio

CFG = ModelConfig.tiny_test()
PARAMS = llama.init_params(jax.random.PRNGKey(0), CFG, dtype=jnp.float32)

SITES = ("embedding", "attn", "mlp", "unembed")


def _policy(spec: str) -> llama.WeightQuantPolicy:
    return llama.WeightQuantPolicy.from_string(spec)


# ---------------------------------------------------------------------------
# Policy grammar
# ---------------------------------------------------------------------------


def test_policy_parsing_and_describe():
    p = _policy("int8")
    assert [getattr(p, s) for s in SITES] == ["int8"] * 4
    assert p.active
    assert p.describe() == "embedding=int8,attn=int8,mlp=int8,unembed=int8"
    p = _policy("attn=int8,mlp=fp8")
    assert p.embedding is None and p.unembed is None
    assert p.attn == "int8" and p.mlp == "fp8"
    assert p.describe() == "attn=int8,mlp=fp8"
    assert not llama.WeightQuantPolicy().active
    assert llama.WeightQuantPolicy().describe() == "off"
    with pytest.raises(ValueError, match="site"):
        _policy("router=int8")
    with pytest.raises(ValueError, match="format"):
        _policy("attn=int4")


# ---------------------------------------------------------------------------
# qdot: the one arithmetic contract, exact per site
# ---------------------------------------------------------------------------


def test_qdot_exact_contract():
    """qdot on a quantized operand must be BIT-IDENTICAL to its XLA twin
    (x @ q.astype * s, same association) — the parity the unified
    programs rely on to stay byte-stable under the policy — and the
    identity x @ w on a plain operand."""
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 96), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(2), (96, 160), jnp.float32) * 0.2
    qw = quantize_weight(w)
    twin = (x @ qw["q"].astype(x.dtype)) * qw["s"].astype(x.dtype)
    assert jnp.array_equal(qdot(x, qw), twin)
    assert jnp.array_equal(qdot(x, w), x @ w)
    # and under jit (the form every engine program compiles)
    assert jnp.array_equal(jax.jit(qdot)(x, qw), twin)


def test_qdot_reconstruction_close():
    w = jax.random.normal(jax.random.PRNGKey(3), (96, 160), jnp.float32) * 0.2
    qw = quantize_weight(w)
    rel = float(
        jnp.max(jnp.abs(dequantize_weight(qw) - w)) / jnp.max(jnp.abs(w))
    )
    assert rel < 0.01, rel


def test_fp8_weight_roundtrip():
    if FP8_DTYPE is None:
        pytest.skip("no float8_e4m3fn in this jax")
    w = jax.random.normal(jax.random.PRNGKey(4), (64, 96), jnp.float32) * 0.3
    qw = quantize_weight(w, fmt="fp8")
    assert qw["q"].dtype == FP8_DTYPE
    assert qw["s"].shape == (96,)
    rel = float(
        jnp.max(jnp.abs(dequantize_weight(qw) - w)) / jnp.max(jnp.abs(w))
    )
    assert rel < 0.1, rel  # e4m3: 3 mantissa bits
    x = jax.random.normal(jax.random.PRNGKey(5), (4, 64), jnp.float32)
    twin = (x @ qw["q"].astype(x.dtype)) * qw["s"].astype(x.dtype)
    assert jnp.array_equal(qdot(x, qw), twin)


def test_unknown_format_rejected():
    w = jnp.ones((8, 8), jnp.float32)
    with pytest.raises(ValueError):
        quantize_weight(w, fmt="int4")


# ---------------------------------------------------------------------------
# Per-site engine-vs-oracle exactness (kernel parity per matmul site)
# ---------------------------------------------------------------------------


def _oracle_greedy(qparams, prompt: list[int], n: int) -> list[int]:
    """Greedy continuation through the no-cache oracle over the SAME
    quantized tree — the paged unified engine must match it exactly
    (qdot is exact-contract, so site precision cannot drift between
    the oracle and the budget-ladder programs)."""
    tokens = list(prompt)
    out = []
    for _ in range(n):
        logits = llama.reference_forward(CFG, qparams, jnp.asarray(tokens))
        nxt = int(jnp.argmax(logits[-1]))
        tokens.append(nxt)
        out.append(nxt)
    return out


async def _collect(engine, prompt, max_tokens=8):
    pre = PreprocessedRequest(
        token_ids=prompt,
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
    )
    tokens = []
    async for raw in engine.generate(Context(pre.to_wire())):
        tokens.extend(EngineOutput.from_wire(raw).token_ids)
    return tokens


@pytest.mark.parametrize(
    "spec", ["embedding=int8", "attn=int8", "mlp=int8", "unembed=int8", "int8"]
)
async def test_unified_engine_matches_policy_oracle(spec):
    """Each site selected ALONE (then all together) through the real
    unified engine: greedy tokens must equal the same-policy no-cache
    oracle exactly — per-matmul parity of the serving kernels."""
    qparams = quantize_params_policy(
        jax.tree.map(jnp.copy, PARAMS), _policy(spec),
        tie_embed=CFG.tie_word_embeddings,
    )
    cfg = EngineConfig(
        model=CFG, dtype="float32", block_size=4, num_blocks=64,
        max_num_seqs=4, max_model_len=128, weight_quant=spec,
        unified=True, unified_token_budget=64, unified_prefill_quantum=16,
        sampling_extras=False,
    )
    engine = TpuEngine(cfg, params=jax.tree.map(jnp.copy, PARAMS))
    await engine.start()
    try:
        prompt = [1, 5, 9, 2, 7]
        tokens = await _collect(engine, prompt, max_tokens=10)
        assert tokens == _oracle_greedy(qparams, prompt, 10)
    finally:
        await engine.stop()


def test_policy_tree_structure_and_specs_mirror():
    p = _policy("attn=int8,mlp=int8,unembed=int8")
    q = quantize_params_policy(
        jax.tree.map(jnp.copy, PARAMS), p, tie_embed=CFG.tie_word_embeddings
    )
    layer = q["layers"][0]
    for k in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"):
        assert is_quantized(layer[k]), k
    assert not is_quantized(q["embed"])       # embedding site off
    assert not is_quantized(layer["ln_attn"])
    assert is_quantized(q["lm_head"])
    # spec tree mirrors the quantized params tree exactly, and the
    # scale spec drops the contracted axis: wq (None, tp) -> s (tp,)
    specs = quantize_param_specs_policy(
        llama_param_specs(CFG), p, tie_embed=CFG.tie_word_embeddings
    )
    jax.tree.map(lambda a, b: None, q, specs)  # raises on mismatch
    assert tuple(specs["layers"][0]["wq"]["s"]) == ("tp",)
    # partial policy: untouched sites keep their plain specs
    p2 = _policy("attn=int8")
    specs2 = quantize_param_specs_policy(
        llama_param_specs(CFG), p2, tie_embed=CFG.tie_word_embeddings
    )
    q2 = quantize_params_policy(
        jax.tree.map(jnp.copy, PARAMS), p2, tie_embed=CFG.tie_word_embeddings
    )
    jax.tree.map(lambda a, b: None, q2, specs2)


def test_site_key_groups_cover_known_matrices():
    assert set(ATTN_KEYS) >= {"wq", "wk", "wv", "wo"}
    assert set(MLP_KEYS) >= {"w_gate", "w_up", "w_down"}
    assert "w_router" not in ATTN_KEYS + MLP_KEYS  # router stays full


def test_tied_embed_policy_quantizes_table_per_row():
    tcfg = ModelConfig.tiny_test().scaled(tie_word_embeddings=True)
    tparams = llama.init_params(jax.random.PRNGKey(5), tcfg, dtype=jnp.float32)
    q = quantize_params_policy(
        jax.tree.map(jnp.copy, tparams), _policy("unembed=int8"),
        tie_embed=True,
    )
    assert is_quantized(q["embed"])
    assert q["embed"]["s"].shape == (tcfg.vocab_size,)
    ref = llama.reference_forward(
        tcfg, tparams, jnp.arange(2, 34, dtype=jnp.int32)
    )
    qref = llama.reference_forward(
        tcfg, q, jnp.arange(2, 34, dtype=jnp.int32)
    )
    cos = float(
        jnp.sum(ref * qref) / (jnp.linalg.norm(ref) * jnp.linalg.norm(qref))
    )
    assert cos > 0.99, cos


def test_sharded_policy_engine_matches_single_chip():
    ecfg = EngineConfig(
        model=CFG, dtype="float32", block_size=16, num_blocks=32,
        max_num_seqs=2, max_model_len=128, weight_quant="int8",
    )
    blocks = [1, 2, 3, 4]
    prompt = list(range(2, 18))
    single = ModelRunner(ecfg)
    tok_single = single.prefill(prompt, blocks, 0, (0.0, 0, 1.0))
    mesh = build_mesh({"tp": 2, "dp": 4})
    sharded = ModelRunner(ecfg, mesh=mesh)
    tok_sharded = sharded.prefill(prompt, blocks, 0, (0.0, 0, 1.0))
    assert tok_single == tok_sharded


# ---------------------------------------------------------------------------
# Quantize-on-load (HF checkpoint path)
# ---------------------------------------------------------------------------


def _write_hf_checkpoint(tmp_path, cfg, seed=7):
    """A tiny random llama-layout safetensors shard (HF [out, in])."""
    from safetensors.numpy import save_file

    rng = np.random.default_rng(seed)
    h, inter, v = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    qd = cfg.num_heads * cfg.head_dim
    kvd = cfg.num_kv_heads * cfg.head_dim

    def mat(out_dim, in_dim):
        return (rng.standard_normal((out_dim, in_dim)) * 0.05).astype(
            np.float32
        )

    t = {
        "model.embed_tokens.weight": mat(v, h),
        "model.norm.weight": np.ones((h,), np.float32),
        "lm_head.weight": mat(v, h),
    }
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}"
        t[f"{p}.self_attn.q_proj.weight"] = mat(qd, h)
        t[f"{p}.self_attn.k_proj.weight"] = mat(kvd, h)
        t[f"{p}.self_attn.v_proj.weight"] = mat(kvd, h)
        t[f"{p}.self_attn.o_proj.weight"] = mat(h, qd)
        t[f"{p}.input_layernorm.weight"] = np.ones((h,), np.float32)
        t[f"{p}.post_attention_layernorm.weight"] = np.ones((h,), np.float32)
        t[f"{p}.mlp.gate_proj.weight"] = mat(inter, h)
        t[f"{p}.mlp.up_proj.weight"] = mat(inter, h)
        t[f"{p}.mlp.down_proj.weight"] = mat(h, inter)
    save_file(t, str(tmp_path / "model.safetensors"))


def test_load_hf_weights_quantizes_on_load(tmp_path):
    """load_hf_weights(policy=...) must equal quantize-after-load
    EXACTLY (same eager quantize_weight calls on the same arrays) and
    feed a working reference forward — the bf16 tree never needs to
    exist resident for the quantized load to be correct."""
    pytest.importorskip("safetensors")
    _write_hf_checkpoint(tmp_path, CFG)
    p = _policy("int8")
    plain = llama.load_hf_weights(CFG, str(tmp_path), dtype=jnp.float32)
    fused = llama.load_hf_weights(
        CFG, str(tmp_path), dtype=jnp.float32, policy=p
    )
    want = quantize_params_policy(
        plain, p, tie_embed=CFG.tie_word_embeddings
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        fused,
        want,
    )
    toks = jnp.arange(2, 34, dtype=jnp.int32)
    out = llama.reference_forward(CFG, fused, toks)
    assert bool(jnp.all(jnp.isfinite(out)))


# ---------------------------------------------------------------------------
# REAL-engine greedy quality gate (int8 weights vs full precision)
# ---------------------------------------------------------------------------


def test_greedy_stream_quality_gate():
    """Greedy token streams on the REAL tiny model: int8 weights must
    match the full-precision stream at >= the threshold rate
    (tier-1-sized: 2 prompts, short OSL)."""
    _greedy_quality(n_prompts=2, osl=10, threshold=0.7)


def _greedy_quality(n_prompts, osl, threshold):
    async def run(weight_quant):
        cfg = EngineConfig(
            model=ModelConfig.tiny_test(), dtype="float32", num_blocks=64,
            max_num_seqs=4, max_model_len=128, prefill_batch=2,
            unified=True, unified_token_budget=64,
            unified_prefill_quantum=16, sampling_extras=False,
            weight_quant=weight_quant,
        )
        eng = TpuEngine(cfg)
        await eng.start()

        async def one(seed):
            rng = np.random.default_rng(seed)
            req = PreprocessedRequest(
                token_ids=rng.integers(0, 384, 24).tolist(),
                sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=osl, ignore_eos=True),
            )
            toks = []
            async for out in eng.generate(Context(req.to_wire())):
                toks += out["token_ids"]
            return toks

        streams = await asyncio.gather(*[one(s) for s in range(n_prompts)])
        ready = eng.readiness()
        gauges = {
            k: ready[k]
            for k in (
                "weight_quant_active",
                "weight_quant_bytes_saved",
                "weight_quant_density",
            )
        }
        await eng.stop()
        return streams, gauges

    base, g_b = asyncio.run(run(None))
    quant, g_q = asyncio.run(run("int8"))
    assert g_b["weight_quant_active"] == 0.0
    assert g_b["weight_quant_bytes_saved"] == 0.0
    assert g_q["weight_quant_active"] == 1.0
    assert g_q["weight_quant_bytes_saved"] > 0
    assert 0.9 < g_q["weight_quant_density"] <= 1.0
    match = sum(
        x == y for s1, s2 in zip(base, quant) for x, y in zip(s1, s2)
    )
    total = sum(len(s) for s in base)
    assert total == n_prompts * osl
    rate = match / total
    assert rate >= threshold, (
        f"greedy token-match rate {rate:.2f} below {threshold} "
        f"({match}/{total}) — int8 weights degraded the stream too far"
    )


def test_weight_quant_composes_with_kv_quant():
    """Both quant axes at once through the real engine: a finite greedy
    stream and both gauge families live on readiness."""
    async def run():
        cfg = EngineConfig(
            model=ModelConfig.tiny_test(), dtype="float32", num_blocks=64,
            max_num_seqs=2, max_model_len=128, unified=True,
            unified_token_budget=64, unified_prefill_quantum=16,
            sampling_extras=False, weight_quant="int8", kv_quant="int8",
        )
        eng = TpuEngine(cfg)
        await eng.start()
        try:
            toks = await _collect(eng, [1, 5, 9, 2, 7], max_tokens=6)
            ready = eng.readiness()
        finally:
            await eng.stop()
        return toks, ready

    toks, ready = asyncio.run(run())
    assert len(toks) == 6
    assert ready["weight_quant_active"] == 1.0
    assert 0.2 < ready["kvbm_kv_quant_ratio"] < 0.3  # int8 KV over f32


# ---------------------------------------------------------------------------
# Config validation
# ---------------------------------------------------------------------------


def test_weight_quant_config_validation():
    # Unified is the default path, so a bare policy validates...
    EngineConfig(model=CFG, weight_quant="int8").validate()
    EngineConfig(model=CFG, weight_quant="attn=int8,mlp=fp8").validate()
    # ...composes with kv_quant...
    EngineConfig(model=CFG, weight_quant="int8", kv_quant="int8").validate()
    # ...rejects the phased engine, naming the conflicting pair...
    with pytest.raises(ValueError, match="--weight-quant \\+ unified"):
        EngineConfig(model=CFG, weight_quant="int8", unified=False).validate()
    # ...rejects stacking on the legacy whole-tree quant...
    with pytest.raises(ValueError, match="--quant \\+ --weight-quant"):
        EngineConfig(model=CFG, weight_quant="int8", quant="int8").validate()
    # ...and parse errors surface at validate time.
    with pytest.raises(ValueError, match="format"):
        EngineConfig(model=CFG, weight_quant="int4").validate()
    with pytest.raises(ValueError, match="site"):
        EngineConfig(model=CFG, weight_quant="router=int8").validate()


def test_kv_quant_conflict_messages_name_flag_pairs():
    with pytest.raises(ValueError, match="--kv-quant \\+ unified"):
        EngineConfig(model=CFG, kv_quant="int8", unified=False).validate()
    with pytest.raises(ValueError, match="--kv-quant \\+ --kv-sp"):
        EngineConfig(
            model=CFG, kv_quant="int8", kv_sp=True,
            mesh_shape={"tp": 1, "sp": 2},
        ).validate()


def test_compile_cache_fingerprint_covers_quant_family():
    from dynamo_tpu.engine.compile_cache import (
        engine_fingerprint,
        fingerprint_key,
    )

    base = EngineConfig(model=CFG)
    keys = {
        fingerprint_key(engine_fingerprint(c))
        for c in (
            base,
            dataclasses.replace(base, weight_quant="int8"),
            dataclasses.replace(base, weight_quant="attn=int8"),
            dataclasses.replace(base, kv_quant="int8"),
        )
    }
    assert len(keys) == 4  # each quant choice lands in its own namespace


# ---------------------------------------------------------------------------
# Calibration: the weight-bytes term and its artifact contract
# ---------------------------------------------------------------------------


def test_weight_bytes_per_step_rederives_from_artifact():
    """WEIGHT_BYTES_PER_STEP is the r04 decode step priced at the r04
    bandwidth — and the standalone-prefill dispatch base must ROUND-TRIP
    through it exactly (bytes / rate = the measured flat base), so the
    two pricing laws can never drift apart (same contract as the PR 10
    decode constants)."""
    from dynamo_tpu.planner import calibration as cal

    rec = cal.recorded_r04()
    # The artifact's two-point fit (test_xpyd re-derives the published
    # constant the same way): base = b32 step minus 32 lane slopes.
    per_lane_us = (
        (rec["decode_step_ms"] - rec["decode_step_ms_b32"]) * 1000.0 / 32.0
    )
    base_us = rec["decode_step_ms_b32"] * 1000.0 - 32.0 * per_lane_us
    want = base_us * 1e-6 * rec["effective_hbm_gbps"] * 1e9
    assert cal.WEIGHT_BYTES_PER_STEP == pytest.approx(want, rel=0.02)
    assert cal.DECODE_HBM_GBPS == rec["effective_hbm_gbps"]
    # Exact closed forms over the published symbols: the bytes term IS
    # base·rate, and the standalone-prefill base round-trips through it
    # to the SAME flat microseconds — the two pricing laws cannot drift.
    assert cal.WEIGHT_BYTES_PER_STEP == (
        cal.DECODE_TIME_PER_STEP_US * 1e-6 * cal.DECODE_HBM_GBPS * 1e9
    )
    assert (
        cal.PREFILL_DISPATCH_BASE_US
        == cal.WEIGHT_BYTES_PER_STEP / (cal.DECODE_HBM_GBPS * 1e9) * 1e6
        == cal.DECODE_TIME_PER_STEP_US
    )


def test_weight_quant_bytes_ratio_math():
    from dynamo_tpu.planner import calibration as cal

    # int8 data + one f32 scale per output channel over bf16 rows.
    assert cal.weight_quant_bytes_ratio(2048, 2) == (2048 + 4) / 4096
    assert 0.5 < cal.weight_quant_bytes_ratio() < 0.51
    assert cal.weight_bytes_per_step(None) == cal.WEIGHT_BYTES_PER_STEP
    assert (
        cal.weight_bytes_per_step("int8")
        == cal.WEIGHT_BYTES_PER_STEP * cal.weight_quant_bytes_ratio()
    )


def test_mocker_weight_pass_pricing():
    """_weight_pass_us REPLACES the flat base with bytes/rate when both
    terms are armed, scales with the ratio, falls back to base*ratio
    when the bandwidth term is off, and is the identity at defaults —
    every pre-existing scenario stays byte-identical."""
    from dynamo_tpu.mocker.engine import MockerConfig, _SimRunner

    cfg = EngineConfig(model=CFG)
    sim = _SimRunner(cfg, MockerConfig())
    assert sim._weight_pass_us(123.0) == 123.0  # defaults: identity
    sim.sim = MockerConfig(
        weight_bytes_per_step=2e9, decode_hbm_gbps=100.0,
        weight_bytes_ratio=1.0,
    )
    assert abs(sim._weight_pass_us(123.0) - 2e9 / (100e9) * 1e6) < 1e-9
    sim.sim = MockerConfig(
        weight_bytes_per_step=2e9, decode_hbm_gbps=100.0,
        weight_bytes_ratio=0.5,
    )
    assert abs(sim._weight_pass_us(123.0) - 1e9 / (100e9) * 1e6) < 1e-9
    sim.sim = MockerConfig(weight_bytes_ratio=0.5)  # no bandwidth term
    assert sim._weight_pass_us(100.0) == 50.0


def test_calibrated_mocker_config_weight_term_is_inert():
    """calibrated_mocker_config arms weight_bytes_per_step but NOT the
    bandwidth term — the xPyD calibration gate's pricing must stay the
    recorded flat base."""
    from dynamo_tpu.mocker.engine import _SimRunner
    from dynamo_tpu.planner import calibration as cal

    sim_cfg = cal.calibrated_mocker_config()
    assert sim_cfg.weight_bytes_per_step == cal.WEIGHT_BYTES_PER_STEP
    assert sim_cfg.decode_hbm_gbps == 0.0
    sim = _SimRunner(EngineConfig(model=CFG), sim_cfg)
    assert (
        sim._weight_pass_us(cal.DECODE_TIME_PER_STEP_US)
        == cal.DECODE_TIME_PER_STEP_US
    )


def test_simulate_prices_weight_quant():
    """SimConfig.weight_quant scales the decode step's weight pass by
    the calibration ratio (and only that term)."""
    from dynamo_tpu.planner import calibration as cal
    from dynamo_tpu.planner.simulate import SimConfig

    base = SimConfig()
    q = SimConfig(weight_quant="int8")
    lanes = 16
    m = base.mocker
    full = base.decode_step_cost_s(lanes)
    packed = q.decode_step_cost_s(lanes)
    ratio = cal.weight_quant_bytes_ratio()
    shared = (
        base.host_overhead_us + m.decode_time_per_lane_us * lanes
    ) / 1e6
    assert abs(
        (packed - shared) / (full - shared) - ratio
    ) < 1e-9
    # standalone prefill's weight-pass base scales the same way
    pf = base.prefill_batch_cost_s([512])
    pq = q.prefill_batch_cost_s([512])
    assert pf > pq
    assert abs(
        (pf - pq) - m.prefill_dispatch_base_us * (1 - ratio) / 1e6
    ) < 1e-9


def test_wquant_equal_budget_math():
    """The BENCH_WQUANT lane law: freed weight bytes convert to KV
    blocks; lanes scale with blocks but never oversubscribe them."""
    import bench
    from dynamo_tpu.planner import calibration as cal

    wratio = cal.weight_quant_bytes_ratio()
    blocks, lanes = bench.wquant_equal_budget(
        3328, 24, wratio, tokens_per_lane=2048 + 150
    )
    kv_block_bytes = cal.KV_BYTES_PER_TOKEN * 16
    freed = cal.WEIGHT_BYTES_PER_STEP * (1 - wratio)
    assert blocks == 3328 + int(freed // kv_block_bytes)
    per_lane = -(-(2048 + 150) // 16)  # ceil
    assert lanes * per_lane <= blocks
    assert lanes > 24  # the freed HBM actually buys lanes
    # identity leg: ratio 1.0 changes nothing
    b1, l1 = bench.wquant_equal_budget(3328, 24, 1.0, tokens_per_lane=2198)
    assert (b1, l1) == (3328, 24)


# ---------------------------------------------------------------------------
# Gauges: tree stats + DT011 surfaces
# ---------------------------------------------------------------------------


def test_quant_tree_stats_counts_bytes():
    p = _policy("int8")
    q = quantize_params_policy(
        jax.tree.map(jnp.copy, PARAMS), p, tie_embed=CFG.tie_word_embeddings
    )
    saved, density = quant_tree_stats(q, dtype_bytes=4)  # f32 tree
    # int8 + f32 row vs f32: saves just under 3/4 of covered bytes
    assert saved > 0
    assert 0.9 < density <= 1.0
    s0, d0 = quant_tree_stats(PARAMS, dtype_bytes=4)
    assert (s0, d0) == (0.0, 0.0)


def test_weight_quant_gauges_on_wire_and_exporter_surfaces():
    """The weight_quant_* gauges survive the ForwardPassMetrics wire
    roundtrip and are registered on the standalone exporter (DT011's
    dynamic complement)."""
    from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics
    from dynamo_tpu.llm.metrics_exporter import _GAUGES

    names = {n for n, _ in _GAUGES}
    for g in (
        "weight_quant_active",
        "weight_quant_bytes_saved",
        "weight_quant_density",
    ):
        assert g in names
        assert hasattr(ForwardPassMetrics(), g)
    m = ForwardPassMetrics.from_wire(
        {"weight_quant_active": 1.0, "weight_quant_bytes_saved": 42.0}
    )
    assert m.weight_quant_active == 1.0
    assert m.weight_quant_bytes_saved == 42.0


def test_mocker_exposes_weight_quant_gauges():
    from dynamo_tpu.mocker.engine import MockerConfig, _SimRunner

    cfg = EngineConfig(model=CFG, weight_quant="int8")
    sim = _SimRunner(
        cfg,
        MockerConfig(weight_bytes_per_step=2e9, weight_bytes_ratio=0.5),
    )
    assert sim.weight_quant_density == 1.0
    assert sim.weight_quant_bytes_saved == 1e9
    sim_off = _SimRunner(EngineConfig(model=CFG), MockerConfig())
    assert sim_off.weight_quant_density == 0.0
    assert sim_off.weight_quant_bytes_saved == 0.0

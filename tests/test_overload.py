"""Overload-safe serving (docs/architecture/overload_and_drain.md):
admission control at the HTTP boundary, deadline propagation with per-hop
expiry, bounded queues with oldest-first shedding, and graceful drain.

Invariants under test: excess load is refused with typed retryable errors
(429/503 + Retry-After) instead of queueing unboundedly; expired work is
cancelled at every hop, never executed; shed work is ALWAYS visible
(counters + typed finishes), never silently dropped; a draining service
finishes what it admitted.
"""

import asyncio

import httpx
import pytest

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.llm.admission import (
    AdmissionConfig,
    AdmissionController,
    AdmissionRejected,
)
from dynamo_tpu.llm.protocols.common import (
    DeadlineError,
    FinishReason,
    PreprocessedRequest,
    SamplingOptions,
    ShedError,
    StopConditions,
)
from dynamo_tpu.mocker import MockerConfig, MockerEngine
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.utils.deadline import (
    OVERLOAD,
    Deadline,
    parse_timeout_ms,
)

pytestmark = pytest.mark.anyio


# ---------------------------------------------------------------------------
# Deadline primitive
# ---------------------------------------------------------------------------


def test_deadline_basics():
    d = Deadline.after(10.0)
    assert not d.expired
    assert 9.0 < d.remaining_s() <= 10.0
    assert Deadline.after(-1.0).expired
    assert Deadline.after_ms(0.0).expired

    # Wire round trip: remaining budget, re-anchored on receipt.
    d2 = Deadline.from_wire(d.to_wire())
    assert abs(d2.remaining_s() - d.remaining_s()) < 0.5
    assert Deadline.from_wire(None) is None
    # An expired deadline stays expired across the hop (clamped at 0).
    assert Deadline.from_wire(Deadline.after(-5).to_wire()).expired

    # Unix (wall-clock) form for cross-process queue entries.
    d3 = Deadline.from_unix(d.to_unix())
    assert abs(d3.remaining_s() - d.remaining_s()) < 0.5
    assert Deadline.from_unix(None) is None

    assert parse_timeout_ms("1500") == 1500.0
    assert parse_timeout_ms("nope") is None
    assert parse_timeout_ms("-5") is None
    assert parse_timeout_ms(None) is None


def test_preprocessed_request_deadline_wire():
    pre = PreprocessedRequest(token_ids=[1, 2, 3], deadline=Deadline.after(5))
    wire = pre.to_wire()
    assert 0 < wire["deadline_ms"] <= 5000
    back = PreprocessedRequest.from_wire(wire)
    assert back.deadline is not None and not back.deadline.expired
    # No deadline -> no wire field, None on the far side.
    wire2 = PreprocessedRequest(token_ids=[1]).to_wire()
    assert "deadline_ms" not in wire2
    assert PreprocessedRequest.from_wire(wire2).deadline is None


# ---------------------------------------------------------------------------
# Admission controller
# ---------------------------------------------------------------------------


def test_admission_inflight_cap_and_release():
    c = AdmissionController(AdmissionConfig(max_inflight=2))
    p1 = c.admit()
    p2 = c.admit()
    with pytest.raises(AdmissionRejected) as exc:
        c.admit()
    assert exc.value.reason == "inflight_cap"
    assert not exc.value.draining
    assert exc.value.retry_after_s > 0
    p1.release()
    p3 = c.admit()  # slot freed
    # Double release must not underflow the gauge.
    p1.release()
    assert c.inflight == 2
    p2.release()
    p3.release()
    assert c.inflight == 0
    assert c.admitted_total == 3
    assert c.rejected == {"inflight_cap": 1}


def test_admission_engine_watermarks():
    stats = {"num_requests_waiting": 0, "gpu_cache_usage_perc": 0.2}
    c = AdmissionController(
        AdmissionConfig(max_inflight=99, max_engine_waiting=4, max_kv_usage=0.9),
        engine_stats=lambda: stats,
    )
    c.admit().release()
    stats["num_requests_waiting"] = 4
    with pytest.raises(AdmissionRejected) as exc:
        c.admit()
    assert exc.value.reason == "engine_waiting"
    stats["num_requests_waiting"] = 0
    stats["gpu_cache_usage_perc"] = 0.95
    with pytest.raises(AdmissionRejected) as exc:
        c.admit()
    assert exc.value.reason == "kv_watermark"
    # A BROKEN stats probe fails open on watermarks (the inflight cap and
    # drain latch still protect) — admission must never 500 on a probe.
    c2 = AdmissionController(
        AdmissionConfig(max_inflight=1, max_engine_waiting=1),
        engine_stats=lambda: (_ for _ in ()).throw(RuntimeError("probe")),
    )
    c2.admit()


def test_admission_draining():
    c = AdmissionController(AdmissionConfig(max_inflight=8))
    c.admit()
    c.begin_drain()
    with pytest.raises(AdmissionRejected) as exc:
        c.admit()
    assert exc.value.draining
    snap = c.snapshot()
    assert snap["draining"] and snap["inflight"] == 1


# ---------------------------------------------------------------------------
# Engine: bounded waiting list + deadline hops
# ---------------------------------------------------------------------------


def _cfg(**kw) -> EngineConfig:
    defaults = dict(
        model=ModelConfig.tiny_test(),
        num_blocks=64,
        max_num_seqs=2,
        max_model_len=128,
        dtype="float32",
    )
    defaults.update(kw)
    return EngineConfig(**defaults)


def _req(n=24, max_tokens=4, deadline=None):
    return PreprocessedRequest(
        token_ids=list(range(n)),
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        deadline=deadline,
    )


async def _collect(engine, req):
    out = []
    finish = None
    async for item in engine.generate(Context(req.to_wire())):
        out.extend(item["token_ids"])
        if item.get("finish_reason"):
            finish = item["finish_reason"]
    return out, finish


async def test_engine_expired_arrival_raises_deadline_error():
    eng = MockerEngine(_cfg(), MockerConfig())
    await eng.start()
    try:
        base = OVERLOAD.deadline_total
        with pytest.raises(DeadlineError):
            await _collect(eng, _req(deadline=Deadline.after(-1)))
        assert OVERLOAD.deadline_total > base
    finally:
        await eng.stop()


async def test_engine_queued_past_deadline_is_shed_not_executed():
    """A queued prefill whose deadline expires while it waits is cancelled
    with a typed DEADLINE finish — the engine never runs it. Slots are
    pinned by two long-running requests so the victim genuinely queues."""
    eng = MockerEngine(
        _cfg(max_num_seqs=2),
        MockerConfig(decode_time_per_step_us=20000.0),  # slow decode
    )
    await eng.start()
    try:
        hogs = [
            asyncio.ensure_future(_collect(eng, _req(max_tokens=48)))
            for _ in range(2)
        ]
        await asyncio.sleep(0.05)  # hogs admitted, slots full
        out, finish = await asyncio.wait_for(
            _collect(eng, _req(deadline=Deadline.after(0.05))), 30.0
        )
        assert out == []
        assert finish == FinishReason.DEADLINE.value
        for h in hogs:
            toks, fin = await asyncio.wait_for(h, 60.0)
            assert len(toks) == 48 and fin == FinishReason.LENGTH.value
    finally:
        await eng.stop()


async def test_engine_waiting_depth_bound_sheds_oldest():
    """max_waiting=1: with slots full and two more requests queued, the
    OLDEST waiter is shed with FinishReason.SHED; the newest keeps its
    place and completes."""
    eng = MockerEngine(
        _cfg(max_num_seqs=1, max_waiting=1),
        MockerConfig(decode_time_per_step_us=20000.0),
    )
    await eng.start()
    try:
        base = OVERLOAD.shed_total
        hog = asyncio.ensure_future(_collect(eng, _req(max_tokens=32)))
        await asyncio.sleep(0.05)
        first = asyncio.ensure_future(_collect(eng, _req(max_tokens=2)))
        await asyncio.sleep(0.05)  # first is now the oldest waiter
        second = asyncio.ensure_future(_collect(eng, _req(max_tokens=2)))
        out1, fin1 = await asyncio.wait_for(first, 30.0)
        assert (out1, fin1) == ([], FinishReason.SHED.value)
        assert OVERLOAD.shed_total > base
        out2, fin2 = await asyncio.wait_for(second, 60.0)
        assert len(out2) == 2 and fin2 == FinishReason.LENGTH.value
        await asyncio.wait_for(hog, 60.0)
    finally:
        await eng.stop()


async def test_engine_mid_generation_deadline_finishes_stream():
    """A deadline that expires mid-generation ends the stream with a
    DEADLINE finish and the partial output — bounded, no hang."""
    eng = MockerEngine(
        _cfg(),
        MockerConfig(decode_time_per_step_us=30000.0),
    )
    await eng.start()
    try:
        out, finish = await asyncio.wait_for(
            _collect(eng, _req(max_tokens=64, deadline=Deadline.after(0.4))),
            30.0,
        )
        assert finish == FinishReason.DEADLINE.value
        assert 0 < len(out) < 64
    finally:
        await eng.stop()


async def test_engine_drain_refuses_new_finishes_inflight():
    eng = MockerEngine(
        _cfg(), MockerConfig(decode_time_per_step_us=5000.0)
    )
    await eng.start()
    try:
        inflight = asyncio.ensure_future(_collect(eng, _req(max_tokens=16)))
        await asyncio.sleep(0.05)
        eng.begin_drain()
        assert eng.readiness()["state"] == "draining"
        assert eng.readiness()["draining"] is True
        with pytest.raises(ShedError):
            await _collect(eng, _req())
        toks, fin = await asyncio.wait_for(inflight, 30.0)
        assert len(toks) == 16 and fin == FinishReason.LENGTH.value
        assert await eng.wait_drained(10.0)
        assert eng.drained
    finally:
        await eng.stop()


# ---------------------------------------------------------------------------
# HTTP boundary: 429/503/504 + Retry-After + deadline header + drain
# ---------------------------------------------------------------------------


class _SlowEcho:
    """Engine stub: sleeps, then echoes — enough to hold admission slots
    and to observe deadline wire fields."""

    def __init__(self, delay_s=0.0):
        self.delay_s = delay_s
        self.seen_deadlines: list = []

    async def generate(self, ctx):
        from dynamo_tpu.llm.protocols.openai import ChatCompletionChunk, StreamChoice, ChatDelta

        self.seen_deadlines.append(ctx.annotations.get("deadline"))
        if self.delay_s:
            await asyncio.sleep(self.delay_s)
        yield ChatCompletionChunk(
            id="c0", model="m",
            choices=[StreamChoice(
                delta=ChatDelta(role="assistant", content="ok"),
                finish_reason="stop",
            )],
        )


async def _http_service(engine, admission=None):
    from dynamo_tpu.llm.discovery import ModelManager
    from dynamo_tpu.llm.http_service import HttpService

    manager = ModelManager()
    manager.add_model("m", engine)
    service = HttpService(
        manager, host="127.0.0.1", port=0, admission=admission
    )
    await service.start()
    return service


BODY = {
    "model": "m",
    "messages": [{"role": "user", "content": "x"}],
    "stream": False,
}


async def test_http_admission_429_with_retry_after_and_drain_503():
    engine = _SlowEcho(delay_s=0.5)
    admission = AdmissionController(AdmissionConfig(max_inflight=1))
    service = await _http_service(engine, admission)
    base = f"http://127.0.0.1:{service.port}"
    try:
        async with httpx.AsyncClient() as client:
            slow = asyncio.ensure_future(
                client.post(f"{base}/v1/chat/completions", json=BODY)
            )
            await asyncio.sleep(0.1)  # slow request holds the one slot
            r = await client.post(f"{base}/v1/chat/completions", json=BODY)
            assert r.status_code == 429
            assert "Retry-After" in r.headers
            assert r.json()["error"]["type"] == "overloaded_error"
            assert (await slow).status_code == 200

            # Drain: health flips 503 first, new requests get 503 +
            # Retry-After, the drain completes once idle.
            drain = asyncio.ensure_future(service.drain(grace_s=10.0))
            await asyncio.sleep(0.05)
            h = await client.get(f"{base}/health")
            assert h.status_code == 503
            assert h.json()["status"] == "draining"
            r = await client.post(f"{base}/v1/chat/completions", json=BODY)
            assert r.status_code == 503
            assert "Retry-After" in r.headers
            assert await asyncio.wait_for(drain, 15.0)

            m = await client.get(f"{base}/metrics")
            assert "shed_requests_total" in m.text
            assert "deadline_exceeded_total" in m.text
            assert "_draining 1.0" in m.text
    finally:
        await service.stop()


async def test_http_deadline_header_reaches_engine_and_expired_maps_504():
    engine = _SlowEcho()
    admission = AdmissionController(
        AdmissionConfig(default_deadline_s=7.0)
    )
    service = await _http_service(engine, admission)
    base = f"http://127.0.0.1:{service.port}"
    try:
        async with httpx.AsyncClient() as client:
            # Header budget wins over the default.
            r = await client.post(
                f"{base}/v1/chat/completions", json=BODY,
                headers={"X-Request-Timeout-Ms": "2000"},
            )
            assert r.status_code == 200
            d = engine.seen_deadlines[-1]
            assert d is not None and 0 < d.remaining_s() <= 2.0
            # No header -> configured default.
            r = await client.post(f"{base}/v1/chat/completions", json=BODY)
            assert r.status_code == 200
            d = engine.seen_deadlines[-1]
            assert d is not None and 2.0 < d.remaining_s() <= 7.0

            # An engine-raised DeadlineError maps to 504.
            class Expired:
                async def generate(self, ctx):
                    raise DeadlineError("expired in queue")
                    yield  # pragma: no cover

            service.manager.add_model("dead", Expired())
            r = await client.post(
                f"{base}/v1/chat/completions",
                json={**BODY, "model": "dead"},
            )
            assert r.status_code == 504
            assert r.json()["error"]["type"] == "deadline_exceeded"

            # A downstream ShedError maps to 429 + Retry-After.
            class Shedding:
                async def generate(self, ctx):
                    raise ShedError("bounded queue full", retry_after_s=3.0)
                    yield  # pragma: no cover

            service.manager.add_model("shed", Shedding())
            r = await client.post(
                f"{base}/v1/chat/completions", json={**BODY, "model": "shed"}
            )
            assert r.status_code == 429
            assert r.headers.get("Retry-After") == "3"
    finally:
        await service.stop()


# ---------------------------------------------------------------------------
# Preprocessor: SHED / DEADLINE zero-token finishes become typed errors
# ---------------------------------------------------------------------------


async def test_preprocessor_maps_shed_finish_to_typed_error():
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
    from dynamo_tpu.llm.protocols.common import EngineOutput
    from dynamo_tpu.llm.protocols.openai import ChatCompletionRequest
    from dynamo_tpu.llm.tokenizer import load_tokenizer
    from dynamo_tpu.runtime.engine import EngineAdapter

    card = ModelDeploymentCard(name="m", model_path="toy")
    pre = OpenAIPreprocessor(card, load_tokenizer("toy"))
    oai = ChatCompletionRequest.model_validate(
        {"model": "m", "messages": [{"role": "user", "content": "hi"}]}
    )

    async def shed_engine(ctx):
        yield EngineOutput(finish_reason=FinishReason.SHED).to_wire()

    async def deadline_engine(ctx):
        yield EngineOutput(finish_reason=FinishReason.DEADLINE).to_wire()

    with pytest.raises(ShedError):
        async for _ in pre.generate(Context(oai), EngineAdapter(shed_engine)):
            pass
    with pytest.raises(DeadlineError):
        async for _ in pre.generate(
            Context(oai), EngineAdapter(deadline_engine)
        ):
            pass


# ---------------------------------------------------------------------------
# Disagg queue bounds
# ---------------------------------------------------------------------------


async def test_prefill_queue_try_enqueue_bounds():
    from dynamo_tpu.disagg.queue import PrefillQueue
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    drt = await DistributedRuntime.in_process()
    try:
        q = PrefillQueue(drt, "bounds", max_depth=2)
        base = OVERLOAD.shed_total
        assert await q.try_enqueue({"request_id": "a"})
        assert await q.try_enqueue({"request_id": "b"})
        assert not await q.try_enqueue({"request_id": "c"})  # over depth
        assert OVERLOAD.shed_total == base + 1
        assert await q.depth() == 2

        # Age bound: a stalled consumer pool (old head item) refuses new
        # remote work even at low depth.
        q2 = PrefillQueue(drt, "age", max_depth=0, max_age_s=0.05)
        assert await q2.try_enqueue({"request_id": "old"})
        await asyncio.sleep(0.15)
        assert not await q2.try_enqueue({"request_id": "new"})
    finally:
        await drt.shutdown()


# ---------------------------------------------------------------------------
# Egress: all instances evicted -> typed retryable error
# ---------------------------------------------------------------------------


async def test_egress_no_instances_is_typed_shed_error():
    from dynamo_tpu.runtime.component import EndpointId
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.egress import Client, PushRouter

    drt = await DistributedRuntime.in_process()
    try:
        client = await Client.create(drt, EndpointId("ns", "comp", "gen"))
        client.wait_for_instances = lambda timeout_s=0.1: asyncio.wait_for(
            asyncio.Event().wait(), 0.05
        )
        router = PushRouter(drt, client)
        with pytest.raises(ShedError, match="no live instances"):
            async for _ in router.generate(Context({})):
                pass
    finally:
        await drt.shutdown()

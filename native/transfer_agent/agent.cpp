// transfer_agent: RDMA-style one-sided block transfer over TCP (DCN path).
//
// The role NIXL (external C++ repo, consumed via nixl-sys FFI in the
// reference's block_manager) plays for GPU clusters: a peer registers
// memory regions; remote peers WRITE bytes straight into those regions
// (kernel->memcpy into the registered arena, no Python in the data path)
// and post a NOTIFY carrying opaque metadata; the owning process drains a
// completion queue. READ provides the symmetric one-sided fetch.
//
// Wire protocol (little-endian), framed per message:
//   WRITE : u8 op=1 | u64 region | u64 offset | u64 len | payload[len]
//   NOTIFY: u8 op=2 | u64 tag    | u32 mlen   | meta[mlen]
//   READ  : u8 op=3 | u64 region | u64 offset | u64 len
//        -> u8 ok   | u64 len    | payload[len]
//   AUTH  : u8 op=4 | token[16]
//   (WRITE and NOTIFY are one-way; only READ has a response, so a stream
//    of writes pipelines without round trips.)
//
// When the server is created with a 16-byte token, a connection must AUTH
// before any other op is accepted (wrong token or premature op closes the
// connection). The token is distributed out of band via the trusted
// control plane, so an arbitrary network peer that can reach the port
// cannot write into registered arenas.
//
// Exposed as a C ABI for ctypes (no pybind11 in this image).

#include <algorithm>
#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace {

struct Region {
  uint8_t *base;
  uint64_t len;
  // Server-assigned registration epoch: in-flight chunked transfers
  // capture it at the first chunk and bounce if the id is unregistered
  // and re-registered (same id, new epoch) mid-transfer.
  uint64_t gen;
};

struct Completion {
  uint64_t tag;
  std::vector<uint8_t> meta;
};

struct Server {
  int listen_fd = -1;
  uint16_t port = 0;
  std::thread loop;
  std::mutex mu;
  std::unordered_map<uint64_t, Region> regions;
  uint64_t next_gen = 1;
  std::deque<Completion> completions;
  bool stopping = false;
  int wake_pipe[2] = {-1, -1};
  bool require_auth = false;
  uint8_t token[16] = {0};
};

// Constant-time compare — a timing oracle must not leak the token.
bool token_eq(const uint8_t *a, const uint8_t *b) {
  uint8_t d = 0;
  for (int i = 0; i < 16; ++i) d |= a[i] ^ b[i];
  return d == 0;
}

bool read_full(int fd, void *buf, size_t n) {
  uint8_t *p = static_cast<uint8_t *>(buf);
  while (n) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) {
      if (r < 0 && (errno == EINTR)) continue;
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void *buf, size_t n) {
  const uint8_t *p = static_cast<const uint8_t *>(buf);
  while (n) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return false;
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

constexpr uint64_t kMaxTransfer = 1ull << 32;  // 4 GiB sanity bound
constexpr size_t kChunk = 4u << 20;  // streaming chunk: bounds scratch
                                     // memory and mutex hold per transfer

// Serve one message from a connected peer. Returns false on EOF/error.
bool serve_one(Server *s, int fd, bool &authed) {
  uint8_t op;
  if (!read_full(fd, &op, 1)) return false;
  if (op == 4) {  // AUTH
    uint8_t tok[16];
    if (!read_full(fd, tok, 16)) return false;
    if (s->require_auth && !token_eq(tok, s->token)) return false;
    authed = true;
    return true;
  }
  if (s->require_auth && !authed) return false;  // auth-first, or drop
  if (op == 1) {  // WRITE
    uint64_t region, offset, len;
    if (!read_full(fd, &region, 8) || !read_full(fd, &offset, 8) ||
        !read_full(fd, &len, 8))
      return false;
    if (len > kMaxTransfer) return false;
    // Stream in bounded chunks; each chunk commits under the lock after
    // RE-validating the region AND its registration epoch — a region
    // unregistered (even if the same id is immediately re-registered for
    // a new owner) mid-transfer bounces the remaining chunks instead of
    // scribbling over the slot's next user. An invalid region from the
    // start just drains the payload to keep the stream sane. Chunking
    // keeps scratch memory and mutex hold O(kChunk), not O(len).
    std::vector<uint8_t> buf(
        len < kChunk ? static_cast<size_t>(len) : kChunk);
    uint64_t pos = 0;
    uint64_t gen = 0;  // captured at first committed chunk
    while (pos < len) {
      size_t chunk = static_cast<size_t>(
          len - pos < buf.size() ? len - pos : buf.size());
      if (!read_full(fd, buf.data(), chunk)) return false;
      std::lock_guard<std::mutex> g(s->mu);
      auto it = s->regions.find(region);
      // Overflow-safe bounds check: offset + len can wrap in u64.
      if (it != s->regions.end() && offset <= it->second.len &&
          len <= it->second.len - offset &&
          (gen == 0 || gen == it->second.gen)) {
        gen = it->second.gen;
        std::memcpy(it->second.base + offset + pos, buf.data(), chunk);
      } else {
        gen = UINT64_MAX;  // poisoned: never commit again, keep draining
      }
      pos += chunk;
    }
    return true;
  }
  if (op == 2) {  // NOTIFY
    uint64_t tag;
    uint32_t mlen;
    if (!read_full(fd, &tag, 8) || !read_full(fd, &mlen, 4)) return false;
    if (mlen > (1u << 24)) return false;
    Completion c;
    c.tag = tag;
    c.meta.resize(mlen);
    if (mlen && !read_full(fd, c.meta.data(), mlen)) return false;
    std::lock_guard<std::mutex> g(s->mu);
    s->completions.push_back(std::move(c));
    return true;
  }
  if (op == 3) {  // READ
    uint64_t region, offset, len;
    if (!read_full(fd, &region, 8) || !read_full(fd, &offset, 8) ||
        !read_full(fd, &len, 8))
      return false;
    if (len > kMaxTransfer) return false;
    uint8_t ok = 0;
    uint64_t gen = 0;
    {
      std::lock_guard<std::mutex> g(s->mu);
      auto it = s->regions.find(region);
      // Overflow-safe bounds check: offset + len can wrap in u64.
      if (it != s->regions.end() && offset <= it->second.len &&
          len <= it->second.len - offset) {
        ok = 1;
        gen = it->second.gen;
      }
    }
    if (!write_full(fd, &ok, 1)) return false;
    uint64_t out_len = ok ? len : 0;
    if (!write_full(fd, &out_len, 8)) return false;
    if (!ok) return true;
    // Copy out in bounded chunks, re-validating region + epoch per chunk
    // (symmetric to WRITE: the region may be unregistered, or its id
    // recycled, while a slow peer drains the response). Once the length
    // is promised a vanished region can't be retracted in-band, so FAIL
    // HARD — drop the connection and let the client's short read surface
    // the race as an error rather than silently landing half-stale
    // bytes. Bounds scratch memory and mutex hold at O(kChunk).
    std::vector<uint8_t> buf(
        len < kChunk ? static_cast<size_t>(len) : kChunk);
    uint64_t pos = 0;
    while (pos < len) {
      size_t chunk = static_cast<size_t>(
          len - pos < buf.size() ? len - pos : buf.size());
      {
        std::lock_guard<std::mutex> g(s->mu);
        auto it = s->regions.find(region);
        if (it == s->regions.end() || it->second.gen != gen ||
            offset > it->second.len || len > it->second.len - offset)
          return false;
        std::memcpy(buf.data(), it->second.base + offset + pos, chunk);
      }
      if (!write_full(fd, buf.data(), chunk)) return false;
      pos += chunk;
    }
    return true;
  }
  return false;
}

void server_loop(Server *s) {
  std::unordered_map<int, bool> clients;  // fd -> authed
  while (true) {
    std::vector<pollfd> fds;
    fds.push_back({s->listen_fd, POLLIN, 0});
    fds.push_back({s->wake_pipe[0], POLLIN, 0});
    for (auto &c : clients) fds.push_back({c.first, POLLIN, 0});
    if (::poll(fds.data(), fds.size(), -1) < 0) {
      if (errno == EINTR) continue;
      break;
    }
    {
      std::lock_guard<std::mutex> g(s->mu);
      if (s->stopping) break;
    }
    if (fds[0].revents & POLLIN) {
      int c = ::accept(s->listen_fd, nullptr, nullptr);
      if (c >= 0) {
        int one = 1;
        ::setsockopt(c, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        clients.emplace(c, false);
      }
    }
    for (size_t i = 2; i < fds.size(); ++i) {
      if (!(fds[i].revents & (POLLIN | POLLHUP | POLLERR))) continue;
      int fd = fds[i].fd;
      // Serve messages until the socket would block (level-triggered poll
      // re-arms us; serve_one blocks only mid-message, which is fine).
      if (!serve_one(s, fd, clients[fd])) {
        ::close(fd);
        clients.erase(fd);
      }
    }
  }
  for (auto &c : clients) ::close(c.first);
}

}  // namespace

extern "C" {

// bind_host: dotted-quad address to bind ("0.0.0.0" to accept cross-host
// peers — the reference's NIXL plane is explicitly multi-node). NULL or ""
// binds loopback only. token: 16-byte shared secret peers must AUTH with
// before any other op, or NULL to disable (loopback-only test setups).
void *ta_create(const char *bind_host, uint16_t port, const uint8_t *token) {
  auto *s = new Server();
  if (token) {
    s->require_auth = true;
    std::memcpy(s->token, token, 16);
  }
  s->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) {
    delete s;
    return nullptr;
  }
  int one = 1;
  ::setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  if (bind_host && bind_host[0] &&
      ::inet_pton(AF_INET, bind_host, &addr.sin_addr) != 1) {
    ::close(s->listen_fd);
    delete s;
    return nullptr;
  }
  addr.sin_port = htons(port);
  if (::bind(s->listen_fd, reinterpret_cast<sockaddr *>(&addr), sizeof(addr)) <
          0 ||
      ::listen(s->listen_fd, 64) < 0) {
    ::close(s->listen_fd);
    delete s;
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(s->listen_fd, reinterpret_cast<sockaddr *>(&addr), &alen);
  s->port = ntohs(addr.sin_port);
  if (::pipe(s->wake_pipe) != 0) {
    ::close(s->listen_fd);
    delete s;
    return nullptr;
  }
  s->loop = std::thread(server_loop, s);
  return s;
}

uint16_t ta_port(void *h) { return static_cast<Server *>(h)->port; }

int ta_register(void *h, uint64_t region_id, void *base, uint64_t len) {
  auto *s = static_cast<Server *>(h);
  std::lock_guard<std::mutex> g(s->mu);
  s->regions[region_id] = {static_cast<uint8_t *>(base), len, s->next_gen++};
  return 0;
}

int ta_unregister(void *h, uint64_t region_id) {
  auto *s = static_cast<Server *>(h);
  std::lock_guard<std::mutex> g(s->mu);
  return s->regions.erase(region_id) ? 0 : -1;
}

// Drain one completion. Returns meta length >= 0, or -1 if queue empty,
// or -2 if meta_cap too small (completion left queued).
int64_t ta_poll(void *h, uint64_t *tag_out, uint8_t *meta_out,
                uint32_t meta_cap) {
  auto *s = static_cast<Server *>(h);
  std::lock_guard<std::mutex> g(s->mu);
  if (s->completions.empty()) return -1;
  Completion &c = s->completions.front();
  if (c.meta.size() > meta_cap) return -2;
  *tag_out = c.tag;
  if (!c.meta.empty()) std::memcpy(meta_out, c.meta.data(), c.meta.size());
  int64_t n = static_cast<int64_t>(c.meta.size());
  s->completions.pop_front();
  return n;
}

void ta_destroy(void *h) {
  auto *s = static_cast<Server *>(h);
  {
    std::lock_guard<std::mutex> g(s->mu);
    s->stopping = true;
  }
  char b = 1;
  (void)!::write(s->wake_pipe[1], &b, 1);
  s->loop.join();
  ::close(s->listen_fd);
  ::close(s->wake_pipe[0]);
  ::close(s->wake_pipe[1]);
  delete s;
}

// ---- client side ----------------------------------------------------------

struct Conn {
  int fd;
  std::mutex mu;
};

// token: 16-byte shared secret to AUTH with right after connecting, or
// NULL to skip (server must have auth disabled).
void *ta_connect(const char *host, uint16_t port, const uint8_t *token) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1 ||
      ::connect(fd, reinterpret_cast<sockaddr *>(&addr), sizeof(addr)) < 0) {
    ::close(fd);
    return nullptr;
  }
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  if (token) {
    uint8_t op = 4;
    if (!write_full(fd, &op, 1) || !write_full(fd, token, 16)) {
      ::close(fd);
      return nullptr;
    }
  }
  auto *c = new Conn();
  c->fd = fd;
  return c;
}

int ta_write(void *conn, uint64_t region, uint64_t offset, const void *data,
             uint64_t len) {
  auto *c = static_cast<Conn *>(conn);
  std::lock_guard<std::mutex> g(c->mu);
  uint8_t op = 1;
  if (!write_full(c->fd, &op, 1) || !write_full(c->fd, &region, 8) ||
      !write_full(c->fd, &offset, 8) || !write_full(c->fd, &len, 8) ||
      !write_full(c->fd, data, len))
    return -1;
  return 0;
}

int ta_notify(void *conn, uint64_t tag, const void *meta, uint32_t mlen) {
  auto *c = static_cast<Conn *>(conn);
  std::lock_guard<std::mutex> g(c->mu);
  uint8_t op = 2;
  if (!write_full(c->fd, &op, 1) || !write_full(c->fd, &tag, 8) ||
      !write_full(c->fd, &mlen, 4) ||
      (mlen && !write_full(c->fd, meta, mlen)))
    return -1;
  return 0;
}

int64_t ta_read(void *conn, uint64_t region, uint64_t offset, void *out,
                uint64_t len) {
  auto *c = static_cast<Conn *>(conn);
  std::lock_guard<std::mutex> g(c->mu);
  uint8_t op = 3;
  if (!write_full(c->fd, &op, 1) || !write_full(c->fd, &region, 8) ||
      !write_full(c->fd, &offset, 8) || !write_full(c->fd, &len, 8))
    return -1;
  uint8_t ok;
  uint64_t rlen;
  if (!read_full(c->fd, &ok, 1) || !read_full(c->fd, &rlen, 8)) return -1;
  if (!ok) return -2;
  if (rlen && !read_full(c->fd, out, rlen)) return -1;
  return static_cast<int64_t>(rlen);
}

void ta_close(void *conn) {
  auto *c = static_cast<Conn *>(conn);
  ::close(c->fd);
  delete c;
}

}  // extern "C"

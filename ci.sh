#!/usr/bin/env bash
# CI entrypoint — the exact checks .github/workflows/ci.yml runs, kept in
# one script so "CI is green" is reproducible locally with `./ci.sh`.
#
# Stages (each skippable via SKIP_<STAGE>=1 while iterating):
#   lint      byte-compile every Python file (syntax gate; uses ruff when
#             one is installed, which CI images may add — rule set pinned
#             in pyproject.toml [tool.ruff])
#   dynalint  project-native AST analysis (tools/dynalint): async/TPU
#             serving invariants, the dynarace concurrency rules, and
#             the dynaflow whole-program laws (DT012-DT016), all at
#             zero debt — any NEW finding fails
#             (docs/development/static_analysis.md).
#             LINT_ONLY=1 runs just the lint stages and exits — the
#             dedicated ci.yml lint job, red in seconds.
#   tests     the tier-1 CPU suite (ROADMAP.md invocation)
#   dynarace  the chaos subset re-run with DYNTPU_CHECK_THREADS=1: the
#             runtime thread-affinity + lock-order checker armed on the
#             real serving seams
#   helm    chart render check: `helm template` when the binary exists,
#           else the restricted-subset renderer in tests/test_deploy.py
#           (same substitution semantics; see its docstring)
#   bench   mocker-mode bench.py smoke — full serving stack, no device,
#           fails on mid-traffic compiles or the compile-stall TTFT
#           signature
set -euo pipefail
cd "$(dirname "$0")"

export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}

say() { printf '\n== %s ==\n' "$*"; }

chaos_leg() {
  say "mocker chaos fleet"
  # Self-healing-fleet leg (docs/architecture/failure_model.md
  # "Mid-stream failover"): a SEEDED randomized chaos schedule — mid-
  # stream worker kills, a bus partition, dropped KV frames — over a
  # 4-decode-worker mocker fleet with the trace capture on. HARD-FAILS
  # unless every request resolves with zero hangs, failover succeeds
  # whenever healthy capacity remains, greedy streams stay byte-
  # identical across kills, and the planner crash path heals the fleet
  # to target size; trace_merge then proves failover chains join the
  # request timelines instead of red-barring them. Toggles:
  # CHAOS_ONLY=1 runs just this leg (the ci.yml red check);
  # SKIP_CHAOS=1 skips it (when it already ran standalone).
  CHAOS_CAP=$(mktemp -t dyntpu_chaos_ci.XXXXXX.jsonl)
  rm -f "$CHAOS_CAP"
  BENCH_CHAOS=1 BENCH_CHAOS_SEED=1234 DYNTPU_TRACE="$CHAOS_CAP" \
    python bench.py
  python benchmarks/trace_merge.py "$CHAOS_CAP" --assert-complete >/dev/null
  rm -f "$CHAOS_CAP"*
}

ingress_leg() {
  say "mocker 100k ingress replay"
  # Million-user-ingress leg (docs/architecture/ingress_scale.md;
  # ROADMAP #4): a seeded Mooncake-style trace — 100k requests, 8
  # mocker workers, 2 router replicas — replayed through the FULL
  # replicated ingress (class-weighted admission → failover frontend →
  # router replicas → workers) with a mid-replay replica KILL + rejoin
  # and an overload burst. HARD-FAILS unless zero requests are lost or
  # hung through the kill, per-class p99 TTFT holds its SLO with zero
  # cross-class inversions, the burst's 429s land on batch (not
  # interactive) with load-proportional Retry-After, rejoin staleness
  # is measured, and the route-audit predicted-vs-actual error bound
  # holds across ALL replicas over the merged capture. Toggles:
  # INGRESS_ONLY=1 runs just this leg (the ci.yml red check);
  # SKIP_INGRESS=1 skips it (when it already ran standalone).
  INGRESS_CAP=$(mktemp -t dyntpu_ingress_ci.XXXXXX.jsonl)
  rm -f "$INGRESS_CAP"
  # Generous capture rotation: the 100k replay writes hundreds of MB of
  # route/kv_actual records and the route-audit join is gated over ALL
  # of them — the default 4x64 MB set would drop the oldest.
  BENCH_INGRESS=1 BENCH_INGRESS_SEED=20260805 DYNTPU_TRACE="$INGRESS_CAP" \
    DYNTPU_TRACE_MAX_MB=128 DYNTPU_TRACE_MAX_FILES=8 \
    python bench.py
  rm -f "$INGRESS_CAP"*
}

g4_leg() {
  say "mocker G4 peer tier"
  # G4 peer-tier leg (docs/architecture/kvbm_g4.md; BENCHMARKS.md "G4
  # peer tier"): a cold worker PULLS a fleet peer's packed KV rows
  # instead of recomputing them, pre-placement warms a joining worker
  # before traffic reaches it, and a peer killed mid-pull degrades to
  # local recompute. HARD-FAILS unless the pulled TTFT beats recompute
  # >=2x at the calibrated link rate (planner/calibration.HANDOFF_GBPS),
  # the pre-placed join reaches steady-state warm-hit rate >=2x faster
  # than the cold join, and the mid-pull kill completes byte-
  # identically with zero hangs. Toggles: G4_ONLY=1 runs just this leg
  # (the ci.yml red check); SKIP_G4=1 skips it (when it already ran
  # standalone).
  BENCH_G4=1 BENCH_G4_SEED=20260806 python bench.py
}

wquant_leg() {
  say "mocker wquant A/B"
  # Quantized-weights leg (docs/architecture/weight_quant.md): int8
  # weights at the SAME simulated HBM byte budget (weight bytes + KV
  # bytes) vs the bf16 baseline, priced by the r04-calibrated
  # weight-bytes term — the freed weight HBM converts to KV lanes.
  # HARD-FAILS unless the int8-weights leg delivers >= 1.3x decode
  # tok/s/chip at equal ITL SLO with zero mid-traffic compiles and the
  # unchanged <= 8-program budget ladder (BENCHMARKS.md "Weight quant
  # A/B"). Toggles: WQUANT_ONLY=1 runs just this leg (the ci.yml red
  # check); SKIP_WQUANT=1 skips it (when it already ran standalone).
  BENCH_WQUANT=1 python bench.py
}

integrity_leg() {
  say "mocker KV integrity"
  # Integrity-envelope leg (docs/architecture/integrity.md): randomized
  # corruption injected at ALL FIVE tier-crossing seams — G2 host
  # onboard, G3 scrub, G4 peer pull, disagg tcp frames, disagg native
  # frames — across 3 seeds on the deterministic mocker. HARD-FAILS
  # unless every injected corruption is detected and attributed to
  # exactly one per-tier counter split, zero streams deviate from the
  # closed form (corruption degrades to recompute, byte-identical), the
  # wire legs complete via the degrade path, and verification overhead
  # stays < 2% of decode wall-clock. The unit suite then covers the
  # stamp/verify/quarantine laws, the scrubber, sidecar recovery, the
  # kill -9 restart drill, and the mixed-fleet refusals. Toggles:
  # INTEGRITY_ONLY=1 runs just this leg (the ci.yml red check);
  # SKIP_INTEGRITY=1 skips it (when it already ran standalone).
  BENCH_INTEGRITY=1 python bench.py
  timeout -k 10 300 python -m pytest tests/test_integrity.py -q \
    -p no:cacheprovider
}

spec_leg() {
  say "mocker spec A/B"
  # Speculative-decode leg (docs/architecture/unified_step.md
  # "Speculative decode on the ragged step"; ROADMAP #2's last leg):
  # draft-verify spans on the unified budget ladder — HARD-FAILS unless
  # accepting-draft spec throughput beats both the unified non-spec leg
  # and the recorded phased-spec baseline, warmup stays within the
  # budget ladder (spec adds ZERO programs), every leg pays zero
  # mid-traffic compiles, and the auto-gate's free-when-losing
  # probe-window bound holds (BENCHMARKS.md "Speculative decode A/B").
  # Toggles: SPEC_ONLY=1 runs just this leg (the ci.yml red check);
  # SKIP_SPEC=1 skips it (when it already ran standalone).
  BENCH_SPEC=1 python bench.py
}

if [[ -n "${SPEC_ONLY:-}" ]]; then
  spec_leg
  say "ci.sh: spec leg green"
  exit 0
fi

if [[ -n "${CHAOS_ONLY:-}" ]]; then
  chaos_leg
  say "ci.sh: chaos leg green"
  exit 0
fi

if [[ -n "${INGRESS_ONLY:-}" ]]; then
  ingress_leg
  say "ci.sh: ingress leg green"
  exit 0
fi

if [[ -n "${G4_ONLY:-}" ]]; then
  g4_leg
  say "ci.sh: G4 leg green"
  exit 0
fi

if [[ -n "${INTEGRITY_ONLY:-}" ]]; then
  integrity_leg
  say "ci.sh: integrity leg green"
  exit 0
fi

if [[ -n "${WQUANT_ONLY:-}" ]]; then
  wquant_leg
  say "ci.sh: wquant leg green"
  exit 0
fi

if [[ -z "${SKIP_LINT:-}" ]]; then
  say "lint"
  if command -v ruff >/dev/null 2>&1; then
    ruff check dynamo_tpu tests bench.py
  else
    python -m compileall -q dynamo_tpu tests bench.py benchmarks
  fi
fi

dynalint_leg() {
  say "lint-dynalint"
  python -m tools.dynalint --stats
  # dynarace concurrency rules (DT007-DT011) launched at ZERO debt and
  # must stay there repo-wide — no baseline allowance at all; every
  # deliberate exception is a reasoned in-file suppression
  # (docs/development/static_analysis.md "Concurrency discipline").
  python -m tools.dynalint --no-baseline \
    --select DT007,DT008,DT009,DT010,DT011
  # Observability-plane modules are dynalint-clean with NO baseline
  # allowance — new instrumentation must not regress the invariants it
  # exists to observe (docs/architecture/observability.md). The KV
  # observatory extends the set to the routing plane and the block
  # manager tiers it instruments.
  # The fleet-planner subsystem (ROADMAP #4) is dynalint-clean with NO
  # baseline allowance too — its control loops share the asyncio
  # process with the metrics plane (docs/architecture/planner.md).
  python -m tools.dynalint --no-baseline \
    dynamo_tpu/planner/obs.py \
    dynamo_tpu/planner/pools.py \
    dynamo_tpu/planner/fleet.py \
    dynamo_tpu/planner/calibration.py \
    dynamo_tpu/planner/simulate.py \
    dynamo_tpu/planner/planner.py \
    dynamo_tpu/planner/profiles.py \
    benchmarks/xpyd_bench.py \
    dynamo_tpu/utils/tracing.py \
    dynamo_tpu/utils/profiling.py \
    dynamo_tpu/engine/flight_recorder.py \
    dynamo_tpu/engine/coloc.py \
    dynamo_tpu/runtime/debug.py \
    benchmarks/trace_merge.py \
    benchmarks/route_audit.py \
    dynamo_tpu/llm/kv_router/audit.py \
    dynamo_tpu/llm/kv_router/indexer.py \
    dynamo_tpu/llm/kv_router/router.py \
    dynamo_tpu/llm/kv_router/scheduler.py \
    dynamo_tpu/llm/kv_router/metrics_aggregator.py \
    dynamo_tpu/llm/kv_router/publisher.py \
    dynamo_tpu/llm/kv_router/protocols.py \
    dynamo_tpu/block_manager/manager.py \
    dynamo_tpu/block_manager/peer.py \
    dynamo_tpu/block_manager/remote.py \
    benchmarks/g4_bench.py \
    dynamo_tpu/block_manager/offload.py \
    dynamo_tpu/block_manager/pool.py \
    dynamo_tpu/block_manager/quant.py \
    dynamo_tpu/block_manager/storage.py \
    dynamo_tpu/block_manager/config.py \
    dynamo_tpu/block_manager/integrity.py \
    dynamo_tpu/utils/atomic_io.py \
    dynamo_tpu/utils/faults.py \
    dynamo_tpu/disagg/transfer.py \
    dynamo_tpu/disagg/native_transfer.py \
    dynamo_tpu/runtime/failover.py \
    benchmarks/chaos_bench.py \
    dynamo_tpu/llm/slo.py \
    dynamo_tpu/llm/admission.py \
    dynamo_tpu/llm/kv_router/replicas.py \
    dynamo_tpu/llm/router_service.py \
    benchmarks/ingress_bench.py \
    dynamo_tpu/engine/engine.py \
    dynamo_tpu/engine/runner.py \
    dynamo_tpu/engine/scheduler.py \
    dynamo_tpu/engine/compile_cache.py \
    dynamo_tpu/mocker/engine.py \
    dynamo_tpu/ops/quant.py \
    dynamo_tpu/models/llama.py \
    dynamo_tpu/llm/metrics_exporter.py \
    dynamo_tpu/llm/http_service.py \
    dynamo_tpu/engine/config.py
  # The dynaflow laws (DT012-DT016) launched at ZERO debt on their
  # target modules — envelope completeness, atomic durability, fault
  # parity, calibration single-source, and the program-budget ladder
  # are interprocedural facts a baseline must never grandfather
  # (docs/development/static_analysis.md "Whole-program laws").
  python -m tools.dynalint --no-baseline \
    --select DT012,DT013,DT014,DT015,DT016 \
    dynamo_tpu/block_manager \
    dynamo_tpu/disagg \
    dynamo_tpu/planner \
    dynamo_tpu/engine \
    tools \
    benchmarks \
    bench.py
}

if [[ -n "${LINT_ONLY:-}" ]]; then
  # Fast red check: the full dynalint sweep (DT001-DT016, whole-program
  # context included) without the test matrix — ci.yml runs this as its
  # own job so lint failures surface in seconds, independently.
  dynalint_leg
  say "ci.sh: dynalint green"
  exit 0
fi

if [[ -z "${SKIP_DYNALINT:-}" ]]; then
  dynalint_leg
fi

if [[ -z "${SKIP_TESTS:-}" ]]; then
  say "tier-1 tests (CPU)"
  timeout -k 10 870 python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider
fi

if [[ -z "${SKIP_DYNARACE:-}" ]]; then
  say "dynarace chaos subset (DYNTPU_CHECK_THREADS=1)"
  # The runtime concurrency checker armed for real: tracked locks feed
  # the lock-order graph and affinity-bound threads are asserted across
  # the chaos drills — an inversion or cross-context touch anywhere in
  # these seams fails CI deterministically instead of deadlocking a
  # production run (dynamo_tpu/utils/concurrency.py).
  DYNTPU_CHECK_THREADS=1 timeout -k 10 300 python -m pytest \
    tests/test_chaos.py tests/test_concurrency.py -q -p no:cacheprovider
fi

if [[ -z "${SKIP_HELM:-}" ]]; then
  say "helm render"
  if command -v helm >/dev/null 2>&1; then
    helm template test-rel deploy/helm/dynamo-tpu >/dev/null
    echo "helm template: OK"
  else
    python -m pytest tests/test_deploy.py -q -p no:cacheprovider
  fi
fi

if [[ -z "${SKIP_BENCH:-}" ]]; then
  say "mocker bench smoke"
  BENCH_SMOKE=1 BENCH_MOCKER=1 python bench.py
  say "mocker overload smoke"
  # Overload-safety leg (docs/architecture/overload_and_drain.md):
  # offered load >> capacity must shed with 429 + Retry-After, hang
  # nothing, keep admitted TTFT bounded; the low-load leg sheds nothing.
  BENCH_SMOKE=1 BENCH_MOCKER=1 BENCH_OVERLOAD=1 python bench.py
  say "mocker unified smoke"
  # Unified-path leg (docs/architecture/unified_step.md): the full
  # serving stack on the unified scheduler — HARD-FAILS unless
  # mid_traffic_compiles == 0 and the warmup plan stays within the
  # budget ladder (≤ 8 programs vs the lane×bucket grid's dozens).
  BENCH_SMOKE=1 BENCH_MOCKER=1 BENCH_UNIFIED=1 python bench.py
  if [[ -z "${SKIP_SPEC:-}" ]]; then
    spec_leg
  fi
  say "mocker coloc A/B"
  # Co-location leg (engine/coloc.py; ROADMAP #3): SLO-aware ADAPTIVE
  # co-located serving vs the static-quantum baseline under an
  # ISL3000-style mixed load — HARD-FAILS unless the adaptive leg's
  # decode ITL p95 holds within the SLO, its prefill throughput meets
  # or exceeds the static baseline's, and it pays zero mid-traffic
  # compiles (BENCHMARKS.md "Co-location A/B").
  BENCH_SMOKE=1 BENCH_MOCKER=1 BENCH_COLOC=1 python bench.py
  say "mocker quant A/B"
  # Quantized-KV leg (docs/architecture/kv_quant.md): int8 KV at the
  # SAME simulated HBM byte budget vs the bf16 baseline, priced by the
  # r04-calibrated decode HBM-bytes term — HARD-FAILS unless int8
  # delivers >= 1.5x decode tok/s/chip at equal ITL SLO with zero
  # mid-traffic compiles and the unchanged <= 8-program budget ladder
  # (BENCHMARKS.md "Quantized KV A/B").
  BENCH_QUANT=1 python bench.py
  if [[ -z "${SKIP_WQUANT:-}" ]]; then
    wquant_leg
  fi
  say "mocker trace smoke"
  # Observability leg (docs/architecture/observability.md): the same
  # mocker run with the span capture on; trace_merge --assert-complete
  # HARD-FAILS unless every completed request has a full, gapless span
  # chain and no trace is orphaned — a seam that stops propagating
  # trace context breaks the build, not the next postmortem.
  TRACE_CAP=$(mktemp -t dyntpu_trace_ci.XXXXXX.jsonl)
  rm -f "$TRACE_CAP"
  BENCH_SMOKE=1 BENCH_MOCKER=1 BENCH_TRACE=1 DYNTPU_TRACE="$TRACE_CAP" \
    python bench.py
  python benchmarks/trace_merge.py "$TRACE_CAP" --assert-complete >/dev/null
  rm -f "$TRACE_CAP"*
  say "mocker route audit"
  # KV-observatory leg (docs/architecture/observability.md "KV
  # observatory"): a multi-worker mocker run behind the KV-aware router
  # with the span capture on, then route_audit.py closes the
  # predicted-vs-actual loop — HARD-FAILS unless ≥95% of requests join
  # predicted↔actual by trace id, no route record is orphaned, and the
  # engine reported at least one actual-reuse record.
  ROUTE_CAP=$(mktemp -t dyntpu_route_ci.XXXXXX.jsonl)
  rm -f "$ROUTE_CAP"
  BENCH_SMOKE=1 BENCH_MOCKER=1 BENCH_ROUTE_AUDIT=1 DYNTPU_TRACE="$ROUTE_CAP" \
    python bench.py
  python benchmarks/route_audit.py "$ROUTE_CAP" --assert >/dev/null
  rm -f "$ROUTE_CAP"*
  if [[ -z "${SKIP_CHAOS:-}" ]]; then
    chaos_leg
  fi
  if [[ -z "${SKIP_INGRESS:-}" ]]; then
    ingress_leg
  fi
  if [[ -z "${SKIP_G4:-}" ]]; then
    g4_leg
  fi
  if [[ -z "${SKIP_INTEGRITY:-}" ]]; then
    integrity_leg
  fi
  say "xPyD fleet projection"
  # Fleet-planner leg (ROADMAP #4; docs/architecture/planner.md): the
  # calibrated-mocker xPyD simulation — HARD-FAILS unless the mocker
  # cost model reproduces the recorded BENCH_r04 headline within 10%,
  # the 2P1D topology beats the 1-worker aggregated baseline on the
  # prefill-heavy replay, and a decode scale-down mid-run drops zero
  # requests (BENCHMARKS.md "xPyD projection").
  BENCH_XPYD=1 python bench.py
  say "network-aware router A/B"
  # NetKV-style decode selection on heterogeneous simulated links: the
  # transfer-cost term must shift selection off the slow link while
  # plain mode splits (the term stays honest: off by default).
  python benchmarks/xpyd_bench.py --router-ab >/dev/null
fi

say "ci.sh: all stages green"

"""Trace prefix-sharing analyzer (VERDICT missing #4).

Role of the reference's ``benchmarks/data_generator/prefix_analyzer.py``:
before sizing a prefix cache or enabling KV-aware routing, an operator
wants to know — from a real trace — how much prefix sharing the workload
actually has and what hit rate a cache of N blocks could theoretically
reach. This tool answers both over the repo's capture/replay JSONL
formats (benchmarks/synthesizer.py):

- our request JSONL (``{"token_ids": [...], "max_tokens": N, ...}`` per
  line — ``save_request_jsonl`` writes it from any served workload), and
- Mooncake-format traces (``{"input_length", "output_length",
  "hash_ids", "timestamp"}`` — reconstructed via ``from_mooncake_trace``).

Block identity is the framework's own chained sequence hash
(llm/tokens.py TokenBlockSequence) — the exact identity the engine's
prefix cache and the KV router index by, so the predicted hit rates are
in the same currency as ``gpu_prefix_cache_hit_rate`` on /metrics.

Two curves come out:

- ``ideal`` hit rate: an infinite cache replaying requests in arrival
  order — the workload's intrinsic reuse ceiling;
- ``curve``: LRU caches of increasing block capacity — where the knee is
  tells you how many blocks (HBM, or G2 host tier) buy most of the
  ceiling.

Run: ``python -m benchmarks.prefix_analyzer TRACE.jsonl [--block-size N]
[--format auto|requests|mooncake] [--cache-sizes 256,1024,...]`` —
prints one JSON report.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import OrderedDict

from dynamo_tpu.llm.tokens import TokenBlockSequence


def _sniff_format(path) -> str:
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if "token_ids" in rec:
                return "requests"
            if "input_length" in rec or "hash_ids" in rec:
                return "mooncake"
            break
    raise ValueError(f"{path}: neither request JSONL nor a Mooncake trace")


def load_trace(path, fmt: str = "auto", block_size: int = 16):
    """Load either capture/replay format into synthesizer Requests."""
    from benchmarks.synthesizer import from_mooncake_trace, load_request_jsonl

    if fmt == "auto":
        fmt = _sniff_format(path)
    if fmt == "requests":
        return load_request_jsonl(path)
    if fmt == "mooncake":
        return from_mooncake_trace(path, block_size=max(block_size, 16) * 32)
    raise ValueError(f"unknown trace format {fmt!r}")


def _request_hashes(reqs, block_size: int) -> list[list[int]]:
    """Per request: the chained hashes of its FULL prompt blocks — the
    prefix-cache identity of each cacheable unit."""
    out = []
    for r in reqs:
        n_full = len(r.token_ids) // block_size
        if n_full == 0:
            out.append([])
            continue
        seq = TokenBlockSequence.from_tokens(
            list(r.token_ids[: n_full * block_size]), block_size=block_size
        )
        out.append(list(seq.sequence_hashes()[:n_full]))
    return out


def _lru_replay(hash_lists: list[list[int]], capacity: int) -> float:
    """Theoretical hit rate of an LRU block cache of `capacity` blocks
    over the trace in arrival order. Each request touches its prompt
    blocks front to back; a hit refreshes recency, a miss inserts (and
    evicts the coldest). Matches the engine's registration model: every
    computed block becomes cacheable."""
    lru: OrderedDict[int, None] = OrderedDict()
    hits = 0
    total = 0
    for hashes in hash_lists:
        for h in hashes:
            total += 1
            if h in lru:
                hits += 1
                lru.move_to_end(h)
            else:
                lru[h] = None
                if len(lru) > capacity:
                    lru.popitem(last=False)
    return hits / total if total else 0.0


def _shared_prefix_blocks(hash_lists: list[list[int]]) -> list[int]:
    """Per request: how many of its leading blocks were already produced
    by ANY earlier request (the streaming shared-prefix measure — what a
    warm, infinite cache would have hit)."""
    seen: set[int] = set()
    shared = []
    for hashes in hash_lists:
        n = 0
        for h in hashes:
            if h in seen:
                n += 1
            else:
                break  # chained hashes: a miss breaks the shared prefix
        shared.append(n)
        seen.update(hashes)
    return shared


def _default_cache_sizes(unique_blocks: int) -> list[int]:
    sizes = []
    n = 16
    while n < unique_blocks:
        sizes.append(n)
        n *= 4
    sizes.append(max(unique_blocks, 16))
    return sizes


def analyze(
    reqs,
    block_size: int = 16,
    cache_sizes: list[int] | None = None,
) -> dict:
    hash_lists = _request_hashes(reqs, block_size)
    total_blocks = sum(len(h) for h in hash_lists)
    unique_blocks = len({h for hl in hash_lists for h in hl})
    shared = _shared_prefix_blocks(hash_lists)
    total_tokens = sum(len(r.token_ids) for r in reqs)
    sizes = cache_sizes or _default_cache_sizes(unique_blocks)
    curve = [
        {
            "cache_blocks": c,
            "hit_rate": round(_lru_replay(hash_lists, c), 4),
        }
        for c in sorted(set(sizes))
    ]
    ideal = (
        (total_blocks - unique_blocks) / total_blocks if total_blocks else 0.0
    )
    return {
        "requests": len(reqs),
        "block_size": block_size,
        "total_tokens": total_tokens,
        "mean_isl": round(total_tokens / max(len(reqs), 1), 1),
        "mean_osl": round(
            sum(r.max_tokens for r in reqs) / max(len(reqs), 1), 1
        ),
        "total_prompt_blocks": total_blocks,
        "unique_prompt_blocks": unique_blocks,
        # Fraction of prompt blocks a warm infinite cache would hit — the
        # reuse ceiling no cache size can beat.
        "ideal_hit_rate": round(ideal, 4),
        # Streaming view: blocks already produced by an earlier request.
        "shared_prefix_block_fraction": round(
            sum(shared) / total_blocks, 4
        ) if total_blocks else 0.0,
        "requests_with_shared_prefix": sum(1 for s in shared if s > 0),
        # Hit rate vs LRU cache capacity — size the arena at the knee.
        "curve": curve,
    }


def main(argv: list[str] | None = None) -> dict:
    ap = argparse.ArgumentParser(prog="prefix_analyzer")
    ap.add_argument("trace", help="capture/replay JSONL (see module doc)")
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument(
        "--format", default="auto", choices=["auto", "requests", "mooncake"]
    )
    ap.add_argument(
        "--cache-sizes", default=None,
        help="comma-separated block capacities for the LRU curve",
    )
    args = ap.parse_args(argv)
    sizes = (
        [int(s) for s in args.cache_sizes.split(",") if s.strip()]
        if args.cache_sizes
        else None
    )
    reqs = load_trace(args.trace, fmt=args.format, block_size=args.block_size)
    report = analyze(reqs, block_size=args.block_size, cache_sizes=sizes)
    print(json.dumps(report))
    return report


if __name__ == "__main__":
    main(sys.argv[1:])

"""Host-DRAM KV offload A/B: follow-up-turn TTFT with and without the G2 tier.

The reference's claim: KV offload to host DRAM improves TTFT ~40% over
GPU-only prefix caching on a multi-turn workload (10 conversations x 80
users; reference: docs/architecture/architecture.md:95-99). This bench is
the one-chip analogue: U users each hold a long distinct prefix; the HBM
arena is sized so a user's G1 prefix blocks are LRU-evicted by the other
users' traffic between their turns. On the follow-up turn the offload
engine onboards the prefix from host DRAM (one batched scatter); the
baseline engine recomputes the whole prefill.

Run via `BENCH_OFFLOAD=1 python bench.py`. Knobs: BENCH_OFFLOAD_USERS,
BENCH_OFFLOAD_PREFIX (tokens), BENCH_MODEL.
"""

from __future__ import annotations

import asyncio
import os
import time

import numpy as np

from dynamo_tpu.block_manager import KvbmConfig, KvBlockManager, KvLayoutConfig
from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.runtime.engine import Context

USERS = int(os.environ.get("BENCH_OFFLOAD_USERS", 8))
PREFIX = int(os.environ.get("BENCH_OFFLOAD_PREFIX", 1024))
TURN1_OSL = 16
DELTA = 32  # new user tokens on the follow-up turn
TURN2_OSL = 16


def _cfg() -> EngineConfig:
    model = getattr(
        ModelConfig, os.environ.get("BENCH_MODEL", "llama32_1b")
    )()
    blocks_per_prefix = PREFIX // 16
    # Arena holds ~70% of the users' combined prefixes: enough working set
    # for one active sequence, small enough that every user's turn-1 blocks
    # face eviction pressure before their turn 2.
    num_blocks = max(256, int(USERS * blocks_per_prefix * 0.7))
    return EngineConfig(
        model=model,
        num_blocks=num_blocks,
        block_size=16,
        max_num_seqs=4,
        max_model_len=1 << (PREFIX + TURN1_OSL + DELTA + TURN2_OSL).bit_length(),
        decode_chunk=8,
        prefill_batch=4,
        enable_prefix_caching=True,
        quant=os.environ.get("DYNAMO_TPU_QUANT") or None,
    )


def _kvbm_layout(cfg: EngineConfig, engine: TpuEngine) -> KvLayoutConfig:
    m = cfg.model
    return KvLayoutConfig(
        num_layers=m.num_layers,
        page_size=cfg.block_size,
        num_kv_heads=m.num_cache_heads,
        head_dim=engine.runner.cache_head_dim,
        dtype=cfg.dtype,
    )


async def _turn(engine, tokens: list[int], osl: int):
    req = PreprocessedRequest(
        token_ids=tokens,
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=osl, ignore_eos=True),
    )
    t0 = time.monotonic()
    ttft = None
    out: list[int] = []
    async for item in engine.generate(Context(req.to_wire())):
        if item["token_ids"] and ttft is None:
            ttft = time.monotonic() - t0
        out += item["token_ids"]
    return ttft, out


async def _run_case(mode: str, prompts: list[list[int]]) -> dict:
    """mode: 'baseline' (no host tier), 'adaptive' (host tier + live
    onboard-vs-recompute gate, the production default), or 'raw' (host tier
    with the gate forced off — measures the unconditional onboard path)."""
    import dataclasses

    with_offload = mode != "baseline"
    cfg = _cfg()
    if mode == "raw":
        cfg = dataclasses.replace(cfg, kvbm_adaptive_gate=False)
    kvbm = None
    engine = TpuEngine(cfg)
    await engine.start()
    if with_offload:
        # The layout needs the live runner's (lane-padded) cache head dim;
        # attaching the manager post-start is safe — the serving path reads
        # engine.kvbm per request.
        kvbm = await KvBlockManager(
            KvbmConfig(
                layout=_kvbm_layout(cfg, engine),
                host_blocks=2 * USERS * (PREFIX // cfg.block_size + 8),
            )
        ).start()
        engine.kvbm = kvbm

    # Throwaway session compiles every serving shape (prefill buckets,
    # decode, and - offload case - the gather/scatter block buckets) off
    # the clock.
    rng = np.random.default_rng(1234)
    warm = rng.integers(0, cfg.model.vocab_size, PREFIX).tolist()
    _, w_out = await _turn(engine, warm, TURN1_OSL)
    await _turn(engine, warm + w_out + warm[:DELTA], TURN2_OSL)
    if with_offload:
        # The warm turn-2 hits G1 (no eviction yet), so the batched onboard
        # scatter never compiled — warm its bucket directly against trash
        # block 0 (engine is idle between requests; nothing races the
        # donated cache update).
        n = PREFIX // cfg.block_size
        m = cfg.model
        zeros = np.zeros(
            (
                n, m.num_layers, 2, cfg.block_size, m.num_cache_heads,
                engine.runner.cache_head_dim,
            ),
            np.float32,
        )
        engine.runner.scatter_many([0] * n, zeros)

    # Turn 1, every user in order: builds each prefix once; the arena
    # evicts the oldest users' blocks as later users arrive.
    turn1_out: list[list[int]] = []
    for p in prompts:
        _, out = await _turn(engine, p, TURN1_OSL)
        turn1_out.append(out)
    if kvbm is not None:
        await kvbm.drain_offers()

    # Turn 2, same order: user i's follow-up shares the full turn-1
    # history plus DELTA fresh tokens.
    ttfts, latencies, outs = [], [], []
    hits0 = engine._prefix_hits
    for p, o1 in zip(prompts, turn1_out):
        t0 = time.monotonic()
        ttft, out = await _turn(engine, p + o1 + p[:DELTA], TURN2_OSL)
        latencies.append(time.monotonic() - t0)
        ttfts.append(ttft)
        outs.append(out)

    stats = {
        "p50_ttft_ms": round(1000 * float(np.median(ttfts)), 1),
        "p95_ttft_ms": round(1000 * float(np.percentile(ttfts, 95)), 1),
        "mean_latency_ms": round(1000 * float(np.mean(latencies)), 1),
        "turn2_prefix_hits": engine._prefix_hits - hits0,
        "turn2_requests": len(prompts),
    }
    if kvbm is not None:
        stats["host_tier"] = kvbm.stats()
        stats["onboard_skips"] = engine._onboard_skips
        if engine._onboard_bps is not None:
            stats["onboard_mbps"] = round(engine._onboard_bps / 1e6, 1)
        if engine._prefill_tps is not None:
            stats["prefill_tok_per_s_wall"] = round(engine._prefill_tps, 1)
    await engine.stop()
    if kvbm is not None:
        await kvbm.stop()
    return stats, outs


def main() -> dict:
    rng = np.random.default_rng(7)
    cfg = _cfg()
    prompts = [
        rng.integers(0, cfg.model.vocab_size, PREFIX).tolist()
        for _ in range(USERS)
    ]

    async def run() -> dict:
        base, base_outs = await _run_case("baseline", prompts)
        raw, raw_outs = await _run_case("raw", prompts)
        adapt, adapt_outs = await _run_case("adaptive", prompts)
        return {
            "metric": f"offload_ttft_gain_prefix{PREFIX}_users{USERS}",
            # TTFT improvement of the production (adaptive) host tier over
            # full recompute (reference bar: +40%, architecture.md:95-99).
            "value": round(
                (base["p50_ttft_ms"] - adapt["p50_ttft_ms"])
                / max(base["p50_ttft_ms"], 1e-9),
                3,
            ),
            "unit": "fractional p50 TTFT reduction (ref bar 0.40)",
            "vs_baseline": round(
                base["p50_ttft_ms"] / max(adapt["p50_ttft_ms"], 1e-9), 3
            ),
            "extras": {
                "baseline_recompute": base,
                "host_offload_raw": raw,
                "host_offload_adaptive": adapt,
                "turn2_tokens_identical": base_outs == raw_outs
                and base_outs == adapt_outs,
                "users": USERS,
                "prefix_tokens": PREFIX,
            },
        }

    return asyncio.run(run())


if __name__ == "__main__":
    import json

    print(json.dumps(main()))

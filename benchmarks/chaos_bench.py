"""Randomized chaos-schedule harness: the self-healing-fleet proof.

``BENCH_CHAOS=1 python bench.py`` (ci.sh "mocker chaos fleet" leg)
replays a request trace over a ≥4-decode-worker mocker fleet — the FULL
production planes: bus dispatch, TCP response streams, shared prefill
queue with remote KV transfer, the ingress failover plane
(runtime/failover.py), and two planner worker pools
(planner/pools.py) — while a SEEDED randomized schedule:

- **kills workers** mid-stream (``ServedInstance.kill()``: the pump and
  every in-flight handler die abruptly, response sockets abort with no
  terminal frame, discovery keys linger — exactly a crashed process);
- **partitions the bus** (``bus.publish`` armed ``partition`` for a
  window: every dispatch fails, the mark-dead fast path evicts the
  whole fleet, the store refresh re-resolves it after heal);
- **drops KV frames** (``disagg.recv`` armed ``drop``: lost transfer
  frames degrade remote prefill to local recompute — the PR 2 ledger).

Hard gates (docs/architecture/failure_model.md "Mid-stream failover"):

1. **Every request resolves** — success or a clean typed error — with
   ZERO hangs under a per-request watchdog.
2. **Failover succeeds whenever healthy capacity remains**: a request
   may fail ONLY while (or right after) a bus partition had the whole
   fleet unreachable; worker kills alone never fail a request.
3. **Streams stay byte-identical**: deterministic-token mode makes
   every greedy stream a pure function of the prompt, so each
   successful request's tokens are checked against the closed-form
   expectation — a failover that skipped or repeated a token fails.
4. **The fleet heals to target size**: dead workers are replaced
   immediately by the pools' crash path (``reap_dead`` — no drain
   accounting) and the run ends at target with every worker alive.

The schedule is ``random.Random(seed)``-driven (``BENCH_CHAOS_SEED``):
reruns with one seed replay one schedule.
"""

# dynarace: context[loop]

from __future__ import annotations

import asyncio
import logging
import os
import random
import sys
import time

if __package__ in (None, ""):  # `python benchmarks/chaos_bench.py`
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )

logger = logging.getLogger(__name__)

#: Mirrors mocker _SimRunner._det_next — the closed-form greedy stream.
_A, _C, _D = 1103515245, 12345, 7


def expected_stream(prompt: list[int], osl: int, vocab: int) -> list[int]:
    """The deterministic tokens ANY healthy serving path must produce."""
    out: list[int] = []
    prev, pos = prompt[-1], len(prompt)
    for _ in range(osl):
        prev = (prev * _A + pos * _C + _D) % vocab
        out.append(prev)
        pos += 1
    return out


class _WorkerHandle:
    """One live mocker worker: served instance + engine (+ operator)."""

    def __init__(self, instance, engine, operator=None, prefill=None):
        self.instance = instance
        self.engine = engine
        self.operator = operator
        self.prefill = prefill
        self.alive = True

    @property
    def worker_id(self) -> int:
        return self.instance.instance.instance_id


class _DecodeConnector:
    """Planner connector spawning in-process mocker decode workers —
    ``alive()`` opts the pool into crash healing (pools.reap_dead)."""

    def __init__(self, spawn_fn):
        self._spawn_fn = spawn_fn
        self.spawned = 0

    async def spawn(self) -> _WorkerHandle:
        self.spawned += 1
        return await self._spawn_fn(self.spawned)

    def alive(self, handle: _WorkerHandle) -> bool:
        return handle.alive

    async def drain(self, handle: _WorkerHandle) -> None:
        if handle.alive:
            await handle.instance.drain(grace_s=10.0)
            await handle.engine.stop()


async def run_chaos(
    seed: int = 1234,
    decode_workers: int = 4,
    prefill_workers: int = 2,
    requests: int = 24,
    osl: int = 24,
    vocab: int = 997,
    watchdog_s: float = 60.0,
) -> dict:
    from dynamo_tpu.disagg import (
        DisaggConfig,
        DisaggRouter,
        DecodeOperator,
        PrefillQueue,
        PrefillWorker,
    )
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.llm.protocols.common import (
        DeadlineError,
        FailoverExhausted,
        PreprocessedRequest,
        SamplingOptions,
        ShedError,
        StopConditions,
        WorkerDiedError,
    )
    from dynamo_tpu.mocker import MockerConfig, MockerEngine
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.planner.pools import PoolConfig, PrefillLaw, WorkerPool
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.egress import PushRouter
    from dynamo_tpu.runtime.engine import Context
    from dynamo_tpu.runtime.failover import FAILOVER, FailoverEngine
    from dynamo_tpu.utils.faults import FAULTS
    from dynamo_tpu.utils.tracing import tracer

    rng = random.Random(seed)
    t_start = time.monotonic()
    drt0 = await DistributedRuntime.in_process()
    queue = PrefillQueue(drt0, "chaos")
    dis = DisaggRouter.__new__(DisaggRouter)
    dis.cfg = DisaggConfig(
        max_local_prefill_length=24, max_prefill_queue_size=256,
    )

    def engine_cfg() -> EngineConfig:
        return EngineConfig(
            model=ModelConfig.tiny_test(), num_blocks=512, max_num_seqs=4,
            max_model_len=512, dtype="float32",
        )

    def sim_cfg(i: int) -> MockerConfig:
        # ~20 ms per fused decode step: streams last ~0.5 s, so the
        # kill schedule reliably lands mid-decode; the whole run stays
        # well under a minute.
        return MockerConfig(
            vocab_size=vocab, seed=i, deterministic_tokens=True,
            decode_time_per_step_us=20000.0,
        )

    async def sub_drt():
        return await DistributedRuntime.in_process(
            store=drt0.store, bus=drt0.bus, runtime=drt0.runtime
        )

    async def spawn_decode(i: int) -> _WorkerHandle:
        eng = MockerEngine(engine_cfg(), sim_cfg(i))
        await eng.start()
        op = await DecodeOperator(eng, queue, dis, transport="tcp").start()
        drt = await sub_drt()
        inst = await drt.namespace("chaos").component("w").endpoint(
            "generate"
        ).serve(op)
        return _WorkerHandle(inst, eng, operator=op)

    async def spawn_prefill(i: int) -> _WorkerHandle:
        eng = MockerEngine(engine_cfg(), sim_cfg(1000 + i))
        await eng.start()
        pw = PrefillWorker(eng, queue).start()
        # Prefill workers are queue consumers, not served endpoints —
        # the handle's "instance" is the worker itself.
        h = _WorkerHandle(_NoInstance(), eng, prefill=pw)
        return h

    class _NoInstance:
        async def kill(self):
            pass

        async def drain(self, grace_s: float = 10.0):
            pass

        class instance:
            instance_id = 0

    class _PrefillConnector(_DecodeConnector):
        async def drain(self, handle: _WorkerHandle) -> None:
            if handle.alive:
                await handle.prefill.stop()
                await handle.engine.stop()

    decode_pool = WorkerPool(
        PoolConfig(
            name="decode", min_workers=decode_workers,
            max_workers=decode_workers + 2,
        ),
        _DecodeConnector(spawn_decode),
        law=None,
    )
    prefill_pool = WorkerPool(
        PoolConfig(
            name="prefill", min_workers=prefill_workers,
            max_workers=prefill_workers + 1,
        ),
        _PrefillConnector(spawn_prefill),
        law=PrefillLaw(),
    )
    await decode_pool.ensure_min()
    await prefill_pool.ensure_min()

    push = await PushRouter.create(
        drt0, "chaos.w.generate", connect_timeout_s=2.0
    )
    engine = FailoverEngine(push)

    # -- the healing loop (planner crash path, every 150 ms) -------------
    replaced = {"n": 0}

    async def heal_loop():
        while True:
            for pool in (decode_pool, prefill_pool):
                replaced["n"] += await pool.reap_dead()
            await asyncio.sleep(0.15)

    healer = asyncio.ensure_future(heal_loop())

    # -- the seeded chaos schedule ---------------------------------------
    kills = {"decode": 0, "prefill": 0}
    partitions: list[tuple[float, float]] = []
    graveyard: list[_WorkerHandle] = []  # killed handles, for teardown

    async def kill_decode():
        live = [h for h in decode_pool.handles if h.alive]
        if len(live) <= 1:
            return  # never kill the last healthy worker
        # Prefer a worker with streams in flight: killing an idle corpse
        # proves only the dispatch fast path — the mid-stream replay is
        # the seam this harness exists to drill.
        busy = [h for h in live if h.instance.inflight > 0]
        victim = rng.choice(busy or live)
        victim.alive = False
        kills["decode"] += 1
        graveyard.append(victim)
        logger.warning("CHAOS: killing decode worker %#x", victim.worker_id)
        await victim.instance.kill()

    async def kill_prefill():
        live = [h for h in prefill_pool.handles if h.alive]
        if len(live) <= 1:
            return
        victim = rng.choice(live)
        victim.alive = False
        kills["prefill"] += 1
        graveyard.append(victim)
        logger.warning("CHAOS: killing a prefill worker")
        await victim.prefill.stop()

    async def partition_bus(window_s: float):
        t0 = time.monotonic() - t_start
        logger.warning("CHAOS: partitioning the bus for %.2fs", window_s)
        FAULTS.arm("bus.publish", "partition")
        await asyncio.sleep(window_s)
        FAULTS.disarm("bus.publish")
        partitions.append((t0, time.monotonic() - t_start))

    async def drop_kv_frames():
        logger.warning("CHAOS: dropping the next 2 KV transfer frames")
        FAULTS.arm("disagg.recv", "drop", times=2)

    events = [
        (1.0 + rng.random() * 0.8, kill_decode),
        (2.2 + rng.random() * 0.8, kill_decode),
        (1.6 + rng.random() * 0.6, kill_prefill),
        (1.2 + rng.random() * 0.5, drop_kv_frames),
        (2.8 + rng.random() * 0.5, drop_kv_frames),
        (4.2 + rng.random() * 0.5, lambda: partition_bus(0.4)),
    ]

    async def run_schedule():
        for delay, fn in sorted(events, key=lambda e: e[0]):
            await asyncio.sleep(
                max(0.0, delay - (time.monotonic() - t_start))
            )
            await fn()

    schedule = asyncio.ensure_future(run_schedule())

    # -- the load ---------------------------------------------------------
    prompts = [
        [rng.randrange(1, vocab) for _ in range(rng.choice((16, 48, 64)))]
        for _ in range(requests)
    ]

    async def one(idx: int, prompt: list[int]):
        await asyncio.sleep(idx * (4.0 / max(requests, 1)))
        req = PreprocessedRequest(
            token_ids=list(prompt),
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=osl, ignore_eos=True),
        )
        ctx = Context(req.to_wire())
        out: list[int] = []
        try:
            async for item in engine.generate(ctx):
                out += item.get("token_ids", [])
            want = expected_stream(prompt, osl, vocab)
            if out != want:
                return ("corrupt", time.monotonic() - t_start,
                        f"req {idx}: got {len(out)} tokens, "
                        f"mismatch vs closed form")
            return ("ok", time.monotonic() - t_start, "")
        except (
            ShedError, DeadlineError, FailoverExhausted, WorkerDiedError,
        ) as exc:
            return ("typed_error", time.monotonic() - t_start,
                    f"req {idx}: {type(exc).__name__}: {exc}")
        except Exception as exc:  # noqa: BLE001 — untyped = gate failure
            return ("untyped_error", time.monotonic() - t_start,
                    f"req {idx}: {type(exc).__name__}: {exc}")
        finally:
            tracer().finish(ctx.id)

    async def guarded(idx, prompt):
        try:
            return await asyncio.wait_for(one(idx, prompt), watchdog_s)
        except asyncio.TimeoutError:
            return ("hang", time.monotonic() - t_start, f"req {idx}: WATCHDOG")

    results = await asyncio.gather(
        *[guarded(i, p) for i, p in enumerate(prompts)]
    )
    await schedule
    # Let the healer finish replacing the last kills, then freeze it.
    for _ in range(60):
        live_d = sum(1 for h in decode_pool.handles if h.alive)
        live_p = sum(1 for h in prefill_pool.handles if h.alive)
        if (
            live_d >= decode_workers and live_p >= prefill_workers
            and replaced["n"] >= kills["decode"] + kills["prefill"]
        ):
            break
        await asyncio.sleep(0.15)
    healer.cancel()
    try:
        await healer
    except asyncio.CancelledError:
        pass
    FAULTS.clear()

    # -- gates -------------------------------------------------------------
    counts: dict[str, int] = {}
    for status, _, _ in results:
        counts[status] = counts.get(status, 0) + 1
    failures: list[str] = []
    if counts.get("hang"):
        failures.append(f"{counts['hang']} request(s) HUNG past the watchdog")
    if counts.get("untyped_error"):
        bad = [d for s, _, d in results if s == "untyped_error"]
        failures.append(f"untyped errors (must be typed): {bad[:3]}")
    if counts.get("corrupt"):
        bad = [d for s, _, d in results if s == "corrupt"]
        failures.append(f"corrupted streams across failover: {bad[:3]}")
    # Gate 2: typed errors are legitimate ONLY while a partition had the
    # fleet unreachable (plus settle slack) — kills alone never fail a
    # request when healthy capacity remains.
    pad = 3.0
    for status, t_done, detail in results:
        if status != "typed_error":
            continue
        if not any(w0 <= t_done <= w1 + pad for w0, w1 in partitions):
            failures.append(
                f"request failed OUTSIDE any partition window (healthy "
                f"capacity remained): {detail} at t={t_done:.2f}s "
                f"windows={partitions}"
            )
    live_decode = sum(1 for h in decode_pool.handles if h.alive)
    live_prefill = sum(1 for h in prefill_pool.handles if h.alive)
    if live_decode < decode_workers:
        failures.append(
            f"decode pool did not heal: {live_decode}/{decode_workers} alive"
        )
    if live_prefill < prefill_workers:
        failures.append(
            f"prefill pool did not heal: "
            f"{live_prefill}/{prefill_workers} alive"
        )
    total_kills = kills["decode"] + kills["prefill"]
    if replaced["n"] < total_kills:
        failures.append(
            f"crash path replaced {replaced['n']} < {total_kills} kills"
        )
    if kills["decode"] and FAILOVER.success_total < 1:
        failures.append(
            "decode workers were killed but no failover completed a "
            "request"
        )

    # -- teardown ----------------------------------------------------------
    for h in list(decode_pool.handles):
        try:
            if h.alive:
                await h.instance.stop()
            await h.engine.stop()
        except Exception:  # noqa: BLE001 — teardown
            pass
    for h in list(prefill_pool.handles):
        try:
            if h.alive and h.prefill is not None:
                await h.prefill.stop()
            await h.engine.stop()
        except Exception:  # noqa: BLE001 — teardown
            pass
    for h in graveyard:
        try:
            await h.engine.stop()
        except Exception:  # noqa: BLE001 — teardown
            pass
    await drt0.shutdown()

    degraded = FAILOVER.snapshot()
    report = {
        "seed": seed,
        "requests": requests,
        "resolved": sum(counts.values()),
        "ok": counts.get("ok", 0),
        "typed_errors": counts.get("typed_error", 0),
        "hangs": counts.get("hang", 0),
        "corrupt": counts.get("corrupt", 0),
        "kills": dict(kills),
        "replaced_dead": replaced["n"],
        "partitions": [
            (round(a, 2), round(b, 2)) for a, b in partitions
        ],
        "failover": degraded,
        "failover_success_total": FAILOVER.success_total,
        "workers_marked_dead_total": FAILOVER.marked_dead_total,
        "decode_pool_final": live_decode,
        "prefill_pool_final": live_prefill,
        "duration_s": round(time.monotonic() - t_start, 2),
        "failures": failures,
    }
    return report


def run_gates(report: dict) -> None:
    """Hard-fail on any gate violation (ci.sh leg + BENCH_CHAOS)."""
    if report["failures"]:
        raise RuntimeError(
            "CHAOS GATES FAILED:\n  " + "\n  ".join(report["failures"])
        )
    if report["resolved"] != report["requests"]:
        raise RuntimeError(
            f"only {report['resolved']}/{report['requests']} requests "
            f"resolved"
        )


# ---------------------------------------------------------------------------
# KV-block integrity: the randomized corruption schedule
# (``BENCH_INTEGRITY=1 python bench.py`` — ci.sh "integrity" leg)
#
# Five trust-boundary seams, each corrupted by a seeded schedule (flip or
# truncate, chosen per leg), each gated on the same invariant: the
# corruption is DETECTED (checksum refusal, counted on the right tier),
# the block is quarantined, and the request rides degrade-to-recompute
# to a stream byte-identical to the deterministic closed form. A nonzero
# failure counter with a zero-deviation stream is the system WORKING.
#
#   1. G2 onboard   — a byte rots in the host DRAM arena; match_host
#                     refuses the block at the G2→G1 crossing.
#   2. G3 scrub     — disk writes corrupted in flight; the paced
#                     scrubber finds every rotten block before a reader.
#   3. G4 pull      — a peer-served frame corrupts on the DCN; the
#                     importer refuses the record mid-pull.
#   4. disagg tcp   — a prefill→decode KV frame corrupts on the wire;
#                     the receiver drops it and the ledger degrades the
#                     request to local recompute.
#   5. disagg native— same seam over the native transfer agent
#                     (checksums ride the notify metadata).
#
# Plus an overhead leg: the envelope's CRC cost per crossing, measured
# directly, must stay under 2% of serve wall time.
# ---------------------------------------------------------------------------

_INT_OSL = 8


def _int_layout():
    from dynamo_tpu.block_manager import KvLayoutConfig

    # block_elems == 8: the mocker runner's 8-float block rows.
    return KvLayoutConfig(
        num_layers=1, page_size=1, num_kv_heads=1, head_dim=4,
        dtype="float32",
    )


def _int_ecfg(**kw):
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.models.config import ModelConfig

    kw.setdefault("num_blocks", 192)
    kw.setdefault("max_num_seqs", 4)
    kw.setdefault("max_model_len", 2048)
    # Tier placement, not the adaptive-gate ramp, is under test.
    kw.setdefault("kvbm_adaptive_gate", False)
    return EngineConfig(model=ModelConfig.tiny_test(), dtype="float32", **kw)


async def _int_worker(main, *, kvbm_cfg=None, ecfg=None, sim_seed=1,
                      link_gbps=0.0):
    """One KVBM-attached mocker worker on the shared fleet planes.
    Returns (drt, kvbm, engine)."""
    from dynamo_tpu.block_manager import KvBlockManager, KvbmConfig
    from dynamo_tpu.mocker.engine import MockerConfig, MockerEngine
    from dynamo_tpu.planner import calibration as cal
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    drt = await DistributedRuntime.in_process(store=main.store, bus=main.bus)
    kvbm = await KvBlockManager(
        kvbm_cfg or KvbmConfig(layout=_int_layout(), host_blocks=128)
    ).start()
    eng = MockerEngine(
        ecfg or _int_ecfg(),
        MockerConfig(
            seed=sim_seed, deterministic_tokens=True,
            peer_link_gbps=link_gbps,
            prefill_time_per_token_us=cal.PREFILL_TIME_PER_TOKEN_US,
        ),
        block_manager=kvbm,
    )
    await eng.start()
    return drt, kvbm, eng


async def _int_generate(engine, prompt, n=_INT_OSL, watchdog_s=60.0):
    """One greedy request; a hang past the watchdog raises (loud gate
    failure), it never wedges the schedule."""
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    req = PreprocessedRequest(
        token_ids=list(prompt),
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=n, ignore_eos=True),
    )

    async def _drain() -> list[int]:
        out: list[int] = []
        async for item in engine.generate(Context(req.to_wire())):
            out += item.get("token_ids", [])
        return out

    return await asyncio.wait_for(_drain(), watchdog_s)


def _int_chain(tokens, block_size=16):
    from dynamo_tpu.llm.tokens import TokenBlockSequence

    return TokenBlockSequence.from_tokens(
        tokens, block_size=block_size
    ).sequence_hashes()


async def _int_wait_host(kvbm, n, timeout=15.0):
    deadline = asyncio.get_running_loop().time() + timeout
    while kvbm.stats()["host_registered"] < n:
        if asyncio.get_running_loop().time() >= deadline:
            raise TimeoutError(
                f"host tier never reached {n} registered blocks "
                f"(at {kvbm.stats()['host_registered']})"
            )
        await asyncio.sleep(0.02)


def _int_prompt(rng, tokens=130):
    return [rng.randrange(1, 31991) for _ in range(tokens)]


async def _ileg_host_onboard(main, rng) -> dict:
    """Seam 1 — G2→G1: rot one byte in the host arena (no code seam to
    arm: DRAM rot happens between writes), then force a cold onboard."""
    import numpy as np

    from dynamo_tpu.block_manager.integrity import INTEGRITY
    from dynamo_tpu.mocker.engine import MockerConfig, MockerEngine

    INTEGRITY.reset()
    prompt = _int_prompt(rng)
    nblocks = (len(prompt) - 1) // 16
    drt, kvbm, eng_a = await _int_worker(main, sim_seed=1)
    eng_b = None
    try:
        vocab = eng_a.runner.sim.vocab_size
        want = expected_stream(prompt, _INT_OSL, vocab)
        base = await _int_generate(eng_a, prompt)
        await kvbm.drain_offers(20.0)
        await _int_wait_host(kvbm, nblocks)
        regs = set(kvbm.host_pool.registered_hashes())
        victims = [h for h in _int_chain(prompt)[:nblocks] if h in regs]
        blk = kvbm.host_pool.get_by_hash(rng.choice(victims))
        # HostStorage.read_block returns the arena row VIEW — flip one
        # byte in place, exactly silent DRAM rot under the envelope.
        row = kvbm.host_pool.storage.read_block(blk.idx)
        flat = row.view(np.uint8)
        flat[rng.randrange(len(flat))] ^= 0x01
        # A second engine on the SAME kvbm: its cold G1 forces the host
        # onboard, where match_host verifies every matched block.
        eng_b = MockerEngine(
            _int_ecfg(),
            MockerConfig(seed=2, deterministic_tokens=True),
            block_manager=kvbm,
        )
        await eng_b.start()
        toks = await _int_generate(eng_b, prompt)
        snap = INTEGRITY.snapshot()
        return {
            "injected": 1,
            "detected": snap["integrity_failures_host"],
            "tier_split_clean": (
                snap["integrity_failures_total"]
                == snap["integrity_failures_host"]
            ),
            "stream_identical": toks == want and base == want,
        }
    finally:
        for eng in (eng_b, eng_a):
            if eng is not None:
                await eng.stop()
        await kvbm.stop()
        await drt.shutdown()


async def _ileg_disk_scrub(main, rng, tmp) -> dict:
    """Seam 2 — G3: corrupt disk writes in flight (flip or truncate);
    one full scrubber sweep must find and quarantine every rotten block
    BEFORE any reader, and the host-intact re-serve stays identical."""
    from dynamo_tpu.block_manager import KvbmConfig
    from dynamo_tpu.block_manager.integrity import INTEGRITY
    from dynamo_tpu.mocker.engine import MockerConfig, MockerEngine
    from dynamo_tpu.utils.faults import FAULTS

    INTEGRITY.reset()
    action = rng.choice(("flip", "truncate"))
    times = rng.randint(1, 3)
    prompt = _int_prompt(rng)
    nblocks = (len(prompt) - 1) // 16
    cfg = KvbmConfig(
        layout=_int_layout(), host_blocks=64, disk_blocks=64,
        disk_path=os.path.join(tmp, "g3.kv"), disk_persist=True,
    )
    drt, kvbm, eng_a = await _int_worker(main, kvbm_cfg=cfg)
    eng_b = None
    before = FAULTS.snapshot().get("kvbm.corrupt_disk", 0)
    FAULTS.arm("kvbm.corrupt_disk", action, times=times)
    try:
        vocab = eng_a.runner.sim.vocab_size
        want = expected_stream(prompt, _INT_OSL, vocab)
        base = await _int_generate(eng_a, prompt)
        await kvbm.drain_offers(20.0)
        await _int_wait_host(kvbm, nblocks)
        await kvbm._g2_to_g3.drain()
        FAULTS.disarm("kvbm.corrupt_disk")
        injected = FAULTS.snapshot().get("kvbm.corrupt_disk", 0) - before
        scanned, detected = kvbm.scrub_tick(max_blocks=cfg.disk_blocks)
        # Cold-G1 re-serve: intact HOST copies feed the onboard; the
        # rotten disk blocks are already quarantined and un-named.
        eng_b = MockerEngine(
            _int_ecfg(),
            MockerConfig(seed=2, deterministic_tokens=True),
            block_manager=kvbm,
        )
        await eng_b.start()
        toks = await _int_generate(eng_b, prompt)
        snap = INTEGRITY.snapshot()
        return {
            "action": action,
            "injected": injected,
            "scrub_scanned": scanned,
            "scrub_detected": detected,
            "detected": snap["integrity_failures_disk"],
            "tier_split_clean": (
                snap["integrity_failures_total"]
                == snap["integrity_failures_disk"]
            ),
            "stream_identical": toks == want and base == want,
        }
    finally:
        FAULTS.disarm("kvbm.corrupt_disk")
        for eng in (eng_b, eng_a):
            if eng is not None:
                await eng.stop()
        await kvbm.stop()
        await drt.shutdown()


async def _ileg_peer_pull(main, rng) -> dict:
    """Seam 3 — G4: corrupt one peer-served frame mid-pull; the importer
    refuses the record, the parked request resumes on the shortened
    prefix and recomputes the rest, byte-identical."""
    from dynamo_tpu.block_manager.integrity import INTEGRITY
    from dynamo_tpu.block_manager.peer import (
        PeerBlockClient,
        PeerBlockServer,
        layout_fingerprint,
    )
    from dynamo_tpu.planner import calibration as cal
    from dynamo_tpu.utils.faults import FAULTS

    INTEGRITY.reset()
    action = rng.choice(("flip", "truncate"))
    # The pull-win shape (g4_bench leg 1): a long prompt priced against
    # the calibrated link, so the pull is actually planned.
    prompt = [(7 * i + 3) % 31991 for i in range(1600)]
    nblocks = (len(prompt) - 1) // 16
    drt_a, kvbm_a, eng_a = await _int_worker(
        main, link_gbps=cal.HANDOFF_GBPS
    )
    server = None
    drt_b = kvbm_b = eng_b = client = None
    before = FAULTS.snapshot().get("kvbm.corrupt_frame", 0)
    try:
        vocab = eng_a.runner.sim.vocab_size
        want = expected_stream(prompt, 4, vocab)
        base = await _int_generate(eng_a, prompt, n=4)
        await _int_wait_host(kvbm_a, nblocks)
        comp = drt_a.namespace("kv").component("tpu")
        server = await PeerBlockServer(
            drt_a, comp, kvbm_a, layout=_int_layout(), refresh_s=0.05,
            serve_link_gbps=eng_a.runner.sim.peer_link_gbps,
        ).start()

        drt_b, kvbm_b, eng_b = await _int_worker(main, sim_seed=2)
        comp_b = drt_b.namespace("kv").component("tpu")
        client = await PeerBlockClient(
            drt_b, comp_b, layout_fingerprint(_int_layout())
        ).start()
        chain = _int_chain(prompt)
        deadline = asyncio.get_running_loop().time() + 10
        while client.best_peer(chain)[1] < nblocks:
            if asyncio.get_running_loop().time() >= deadline:
                raise TimeoutError("G4 peer discovery never converged")
            await asyncio.sleep(0.02)
        kvbm_b.attach_peer_client(client)

        FAULTS.arm("kvbm.corrupt_frame", action, times=1)
        toks = await _int_generate(eng_b, prompt, n=4)
        FAULTS.disarm("kvbm.corrupt_frame")
        await kvbm_b.drain_pulls(timeout_s=20)
        injected = FAULTS.snapshot().get("kvbm.corrupt_frame", 0) - before
        snap = INTEGRITY.snapshot()
        return {
            "action": action,
            "injected": injected,
            "detected": snap["integrity_failures_peer"],
            "tier_split_clean": (
                snap["integrity_failures_total"]
                == snap["integrity_failures_peer"]
            ),
            "stream_identical": toks == want and base == want,
        }
    finally:
        FAULTS.disarm("kvbm.corrupt_frame")
        for eng in (eng_b, eng_a):
            if eng is not None:
                await eng.stop()
        if client is not None:
            await client.stop()
        if server is not None:
            await server.stop()
        for kvbm in (kvbm_b, kvbm_a):
            if kvbm is not None:
                await kvbm.stop()
        for drt in (drt_b, drt_a):
            if drt is not None:
                await drt.shutdown()


async def _ileg_disagg(main, rng, transport: str) -> dict:
    """Seams 4/5 — prefill→decode KV frames (tcp / native transfer
    agent): the receiver's checksum drops a corrupted frame like a lost
    one, the completeness ledger refuses to activate over the hole, and
    the request degrades to local recompute."""
    from dynamo_tpu.block_manager.integrity import INTEGRITY
    from dynamo_tpu.disagg import (
        DisaggConfig,
        DisaggRouter,
        DecodeOperator,
        PrefillQueue,
        PrefillWorker,
    )
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.mocker import MockerConfig, MockerEngine
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.egress import PushRouter
    from dynamo_tpu.runtime.failover import FailoverEngine
    from dynamo_tpu.utils.faults import FAULTS
    from dynamo_tpu.utils.tracing import tracer

    INTEGRITY.reset()
    action = rng.choice(("flip", "truncate"))
    times = rng.randint(1, 2)
    vocab, osl, ns = 997, _INT_OSL, f"integ-{transport}"
    queue = PrefillQueue(main, ns)
    dis = DisaggRouter.__new__(DisaggRouter)
    dis.cfg = DisaggConfig(
        max_local_prefill_length=24, max_prefill_queue_size=64,
    )

    def ecfg(**kw) -> EngineConfig:
        return EngineConfig(
            model=ModelConfig.tiny_test(), num_blocks=256, max_num_seqs=4,
            max_model_len=512, dtype="float32", **kw,
        )

    # A dropped frame must degrade within the leg, not after a 30 s wait.
    eng_d = MockerEngine(
        ecfg(remote_kv_timeout_s=2.0),
        MockerConfig(vocab_size=vocab, seed=1, deterministic_tokens=True),
    )
    await eng_d.start()
    op = await DecodeOperator(eng_d, queue, dis, transport=transport).start()
    drt_d = await DistributedRuntime.in_process(
        store=main.store, bus=main.bus
    )
    inst = await drt_d.namespace(ns).component("w").endpoint(
        "generate"
    ).serve(op)
    eng_p = MockerEngine(
        ecfg(),
        MockerConfig(vocab_size=vocab, seed=2, deterministic_tokens=True),
    )
    await eng_p.start()
    pw = PrefillWorker(eng_p, queue).start()
    push = await PushRouter.create(
        main, f"{ns}.w.generate", connect_timeout_s=2.0
    )
    engine = FailoverEngine(push)

    before = FAULTS.snapshot().get("kvbm.corrupt_frame", 0)
    FAULTS.arm("kvbm.corrupt_frame", action, times=times)
    try:
        from dynamo_tpu.llm.protocols.common import (
            PreprocessedRequest,
            SamplingOptions,
            StopConditions,
        )
        from dynamo_tpu.runtime.engine import Context

        streams_ok = True
        # >max_local_prefill_length, so every request prefills REMOTELY
        # and its KV rides the corrupted wire back.
        for _ in range(4):
            prompt = [rng.randrange(1, vocab) for _ in range(48)]
            req = PreprocessedRequest(
                token_ids=list(prompt),
                sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=osl, ignore_eos=True),
            )
            ctx = Context(req.to_wire())
            out: list[int] = []

            async def _drain() -> None:
                async for item in engine.generate(ctx):
                    out.extend(item.get("token_ids", []))

            try:
                await asyncio.wait_for(_drain(), 30.0)
            finally:
                tracer().finish(ctx.id)
            streams_ok = streams_ok and (
                out == expected_stream(prompt, osl, vocab)
            )
        FAULTS.disarm("kvbm.corrupt_frame")
        injected = FAULTS.snapshot().get("kvbm.corrupt_frame", 0) - before
        snap = INTEGRITY.snapshot()
        return {
            "action": action,
            "transport": transport,
            "injected": injected,
            "detected": snap["integrity_failures_frame"],
            "tier_split_clean": (
                snap["integrity_failures_total"]
                == snap["integrity_failures_frame"]
            ),
            "degraded_requests": eng_d.degraded_requests,
            "stream_identical": streams_ok,
        }
    finally:
        FAULTS.disarm("kvbm.corrupt_frame")
        try:
            await inst.stop()
        except Exception:  # noqa: BLE001 — teardown
            pass
        await pw.stop()
        for eng in (eng_d, eng_p):
            await eng.stop()
        await drt_d.shutdown()


async def _ileg_overhead(main) -> dict:
    """The <2% gate: CRC seconds per crossing are measured directly and
    charged against every crossing a real serve causes — an analytic
    bound from measured components, immune to 2%-scale wall noise."""
    import numpy as np

    from dynamo_tpu.block_manager.integrity import block_checksum

    row = np.zeros(_int_layout().block_elems, np.float32)
    reps = 5000
    t0 = time.perf_counter()
    for _ in range(reps):
        block_checksum(row)
    crc_s = (time.perf_counter() - t0) / reps
    # Context figure: envelope throughput on a production-sized row.
    big = np.zeros(2 << 20, np.uint8)
    t0 = time.perf_counter()
    for _ in range(8):
        block_checksum(big)
    crc_gbps = (8 * big.nbytes) / max(time.perf_counter() - t0, 1e-9) / 1e9

    drt, kvbm, eng = await _int_worker(main)
    try:
        t0 = time.monotonic()
        for i in range(6):
            prompt = [(13 * i + j) % 31991 for j in range(1, 130)]
            await _int_generate(eng, prompt)
        await kvbm.drain_offers(20.0)
        wall = max(time.monotonic() - t0, 1e-9)
        stats = kvbm.stats()
        # Upper bound: every stored block is stamped once and verified
        # at most twice more (onboard + scrub) on its way back up.
        crossings = 3 * stats["host_registered"] + stats[
            "scrub_scanned_total"
        ]
        frac = crossings * crc_s / wall
        return {
            "crc_us_per_block": round(crc_s * 1e6, 3),
            "crc_gbps": round(crc_gbps, 2),
            "crossings": crossings,
            "serve_wall_s": round(wall, 3),
            "overhead_fraction": round(frac, 6),
        }
    finally:
        await eng.stop()
        await kvbm.stop()
        await drt.shutdown()


async def run_integrity(seed: int = 20260806) -> dict:
    import tempfile

    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.utils.faults import FAULTS

    rng = random.Random(seed)
    main_drt = await DistributedRuntime.in_process()
    try:
        with tempfile.TemporaryDirectory(prefix="integ-g3-") as tmp:
            host = await _ileg_host_onboard(main_drt, rng)
            disk = await _ileg_disk_scrub(main_drt, rng, tmp)
            peer = await _ileg_peer_pull(main_drt, rng)
            tcp = await _ileg_disagg(main_drt, rng, "tcp")
            native = await _ileg_disagg(main_drt, rng, "native")
            overhead = await _ileg_overhead(main_drt)
    finally:
        FAULTS.clear()
        await main_drt.shutdown()
    return {
        "seed": seed,
        "host_onboard": host,
        "disk_scrub": disk,
        "peer_pull": peer,
        "disagg_tcp": tcp,
        "disagg_native": native,
        "overhead": overhead,
    }


def run_integrity_gates(report: dict) -> list[str]:
    """Hard gates (ISSUE 18 / BENCHMARKS.md "integrity"). Returns
    failures; empty means every injected corruption was detected on the
    right tier and zero streams diverged."""
    failures: list[str] = []
    for leg in (
        "host_onboard", "disk_scrub", "peer_pull",
        "disagg_tcp", "disagg_native",
    ):
        r = report[leg]
        if not r["stream_identical"]:
            failures.append(f"{leg}: stream DIVERGED from the closed form")
        if r["injected"] < 1:
            failures.append(f"{leg}: schedule injected no corruption")
        if r["detected"] != r["injected"]:
            failures.append(
                f"{leg}: detected {r['detected']} != injected "
                f"{r['injected']} — corruption escaped the envelope"
            )
        if not r["tier_split_clean"]:
            failures.append(f"{leg}: corruption attributed to the wrong tier")
    d = report["disk_scrub"]
    if d["scrub_detected"] != d["injected"]:
        failures.append(
            f"disk_scrub: scrubber found {d['scrub_detected']} of "
            f"{d['injected']} rotten block(s)"
        )
    for leg in ("disagg_tcp", "disagg_native"):
        if report[leg]["degraded_requests"] < 1:
            failures.append(
                f"{leg}: no request degraded to recompute (ledger hole "
                f"went unnoticed)"
            )
    ov = report["overhead"]
    if ov["overhead_fraction"] >= 0.02:
        failures.append(
            f"overhead: envelope costs {ov['overhead_fraction']:.2%} of "
            f"serve time (gate 2%)"
        )
    return failures


def main(argv: list[str] | None = None) -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser(
        prog="python benchmarks/chaos_bench.py",
        description="seeded chaos-schedule proof over a mocker fleet",
    )
    ap.add_argument("--seed", type=int,
                    default=int(os.environ.get("BENCH_CHAOS_SEED", 1234)))
    ap.add_argument("--workers", type=int,
                    default=int(os.environ.get("BENCH_CHAOS_WORKERS", 4)))
    ap.add_argument("--requests", type=int,
                    default=int(os.environ.get("BENCH_CHAOS_REQUESTS", 24)))
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.WARNING)
    report = asyncio.run(run_chaos(
        seed=args.seed, decode_workers=args.workers,
        requests=args.requests,
    ))
    print(json.dumps(report, indent=2))
    run_gates(report)
    print("chaos gates: OK", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Randomized chaos-schedule harness: the self-healing-fleet proof.

``BENCH_CHAOS=1 python bench.py`` (ci.sh "mocker chaos fleet" leg)
replays a request trace over a ≥4-decode-worker mocker fleet — the FULL
production planes: bus dispatch, TCP response streams, shared prefill
queue with remote KV transfer, the ingress failover plane
(runtime/failover.py), and two planner worker pools
(planner/pools.py) — while a SEEDED randomized schedule:

- **kills workers** mid-stream (``ServedInstance.kill()``: the pump and
  every in-flight handler die abruptly, response sockets abort with no
  terminal frame, discovery keys linger — exactly a crashed process);
- **partitions the bus** (``bus.publish`` armed ``partition`` for a
  window: every dispatch fails, the mark-dead fast path evicts the
  whole fleet, the store refresh re-resolves it after heal);
- **drops KV frames** (``disagg.recv`` armed ``drop``: lost transfer
  frames degrade remote prefill to local recompute — the PR 2 ledger).

Hard gates (docs/architecture/failure_model.md "Mid-stream failover"):

1. **Every request resolves** — success or a clean typed error — with
   ZERO hangs under a per-request watchdog.
2. **Failover succeeds whenever healthy capacity remains**: a request
   may fail ONLY while (or right after) a bus partition had the whole
   fleet unreachable; worker kills alone never fail a request.
3. **Streams stay byte-identical**: deterministic-token mode makes
   every greedy stream a pure function of the prompt, so each
   successful request's tokens are checked against the closed-form
   expectation — a failover that skipped or repeated a token fails.
4. **The fleet heals to target size**: dead workers are replaced
   immediately by the pools' crash path (``reap_dead`` — no drain
   accounting) and the run ends at target with every worker alive.

The schedule is ``random.Random(seed)``-driven (``BENCH_CHAOS_SEED``):
reruns with one seed replay one schedule.
"""

# dynarace: context[loop]

from __future__ import annotations

import asyncio
import logging
import os
import random
import sys
import time

if __package__ in (None, ""):  # `python benchmarks/chaos_bench.py`
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )

logger = logging.getLogger(__name__)

#: Mirrors mocker _SimRunner._det_next — the closed-form greedy stream.
_A, _C, _D = 1103515245, 12345, 7


def expected_stream(prompt: list[int], osl: int, vocab: int) -> list[int]:
    """The deterministic tokens ANY healthy serving path must produce."""
    out: list[int] = []
    prev, pos = prompt[-1], len(prompt)
    for _ in range(osl):
        prev = (prev * _A + pos * _C + _D) % vocab
        out.append(prev)
        pos += 1
    return out


class _WorkerHandle:
    """One live mocker worker: served instance + engine (+ operator)."""

    def __init__(self, instance, engine, operator=None, prefill=None):
        self.instance = instance
        self.engine = engine
        self.operator = operator
        self.prefill = prefill
        self.alive = True

    @property
    def worker_id(self) -> int:
        return self.instance.instance.instance_id


class _DecodeConnector:
    """Planner connector spawning in-process mocker decode workers —
    ``alive()`` opts the pool into crash healing (pools.reap_dead)."""

    def __init__(self, spawn_fn):
        self._spawn_fn = spawn_fn
        self.spawned = 0

    async def spawn(self) -> _WorkerHandle:
        self.spawned += 1
        return await self._spawn_fn(self.spawned)

    def alive(self, handle: _WorkerHandle) -> bool:
        return handle.alive

    async def drain(self, handle: _WorkerHandle) -> None:
        if handle.alive:
            await handle.instance.drain(grace_s=10.0)
            await handle.engine.stop()


async def run_chaos(
    seed: int = 1234,
    decode_workers: int = 4,
    prefill_workers: int = 2,
    requests: int = 24,
    osl: int = 24,
    vocab: int = 997,
    watchdog_s: float = 60.0,
) -> dict:
    from dynamo_tpu.disagg import (
        DisaggConfig,
        DisaggRouter,
        DecodeOperator,
        PrefillQueue,
        PrefillWorker,
    )
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.llm.protocols.common import (
        DeadlineError,
        FailoverExhausted,
        PreprocessedRequest,
        SamplingOptions,
        ShedError,
        StopConditions,
        WorkerDiedError,
    )
    from dynamo_tpu.mocker import MockerConfig, MockerEngine
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.planner.pools import PoolConfig, PrefillLaw, WorkerPool
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.egress import PushRouter
    from dynamo_tpu.runtime.engine import Context
    from dynamo_tpu.runtime.failover import FAILOVER, FailoverEngine
    from dynamo_tpu.utils.faults import FAULTS
    from dynamo_tpu.utils.tracing import tracer

    rng = random.Random(seed)
    t_start = time.monotonic()
    drt0 = await DistributedRuntime.in_process()
    queue = PrefillQueue(drt0, "chaos")
    dis = DisaggRouter.__new__(DisaggRouter)
    dis.cfg = DisaggConfig(
        max_local_prefill_length=24, max_prefill_queue_size=256,
    )

    def engine_cfg() -> EngineConfig:
        return EngineConfig(
            model=ModelConfig.tiny_test(), num_blocks=512, max_num_seqs=4,
            max_model_len=512, dtype="float32",
        )

    def sim_cfg(i: int) -> MockerConfig:
        # ~20 ms per fused decode step: streams last ~0.5 s, so the
        # kill schedule reliably lands mid-decode; the whole run stays
        # well under a minute.
        return MockerConfig(
            vocab_size=vocab, seed=i, deterministic_tokens=True,
            decode_time_per_step_us=20000.0,
        )

    async def sub_drt():
        return await DistributedRuntime.in_process(
            store=drt0.store, bus=drt0.bus, runtime=drt0.runtime
        )

    async def spawn_decode(i: int) -> _WorkerHandle:
        eng = MockerEngine(engine_cfg(), sim_cfg(i))
        await eng.start()
        op = await DecodeOperator(eng, queue, dis, transport="tcp").start()
        drt = await sub_drt()
        inst = await drt.namespace("chaos").component("w").endpoint(
            "generate"
        ).serve(op)
        return _WorkerHandle(inst, eng, operator=op)

    async def spawn_prefill(i: int) -> _WorkerHandle:
        eng = MockerEngine(engine_cfg(), sim_cfg(1000 + i))
        await eng.start()
        pw = PrefillWorker(eng, queue).start()
        # Prefill workers are queue consumers, not served endpoints —
        # the handle's "instance" is the worker itself.
        h = _WorkerHandle(_NoInstance(), eng, prefill=pw)
        return h

    class _NoInstance:
        async def kill(self):
            pass

        async def drain(self, grace_s: float = 10.0):
            pass

        class instance:
            instance_id = 0

    class _PrefillConnector(_DecodeConnector):
        async def drain(self, handle: _WorkerHandle) -> None:
            if handle.alive:
                await handle.prefill.stop()
                await handle.engine.stop()

    decode_pool = WorkerPool(
        PoolConfig(
            name="decode", min_workers=decode_workers,
            max_workers=decode_workers + 2,
        ),
        _DecodeConnector(spawn_decode),
        law=None,
    )
    prefill_pool = WorkerPool(
        PoolConfig(
            name="prefill", min_workers=prefill_workers,
            max_workers=prefill_workers + 1,
        ),
        _PrefillConnector(spawn_prefill),
        law=PrefillLaw(),
    )
    await decode_pool.ensure_min()
    await prefill_pool.ensure_min()

    push = await PushRouter.create(
        drt0, "chaos.w.generate", connect_timeout_s=2.0
    )
    engine = FailoverEngine(push)

    # -- the healing loop (planner crash path, every 150 ms) -------------
    replaced = {"n": 0}

    async def heal_loop():
        while True:
            for pool in (decode_pool, prefill_pool):
                replaced["n"] += await pool.reap_dead()
            await asyncio.sleep(0.15)

    healer = asyncio.ensure_future(heal_loop())

    # -- the seeded chaos schedule ---------------------------------------
    kills = {"decode": 0, "prefill": 0}
    partitions: list[tuple[float, float]] = []
    graveyard: list[_WorkerHandle] = []  # killed handles, for teardown

    async def kill_decode():
        live = [h for h in decode_pool.handles if h.alive]
        if len(live) <= 1:
            return  # never kill the last healthy worker
        # Prefer a worker with streams in flight: killing an idle corpse
        # proves only the dispatch fast path — the mid-stream replay is
        # the seam this harness exists to drill.
        busy = [h for h in live if h.instance.inflight > 0]
        victim = rng.choice(busy or live)
        victim.alive = False
        kills["decode"] += 1
        graveyard.append(victim)
        logger.warning("CHAOS: killing decode worker %#x", victim.worker_id)
        await victim.instance.kill()

    async def kill_prefill():
        live = [h for h in prefill_pool.handles if h.alive]
        if len(live) <= 1:
            return
        victim = rng.choice(live)
        victim.alive = False
        kills["prefill"] += 1
        graveyard.append(victim)
        logger.warning("CHAOS: killing a prefill worker")
        await victim.prefill.stop()

    async def partition_bus(window_s: float):
        t0 = time.monotonic() - t_start
        logger.warning("CHAOS: partitioning the bus for %.2fs", window_s)
        FAULTS.arm("bus.publish", "partition")
        await asyncio.sleep(window_s)
        FAULTS.disarm("bus.publish")
        partitions.append((t0, time.monotonic() - t_start))

    async def drop_kv_frames():
        logger.warning("CHAOS: dropping the next 2 KV transfer frames")
        FAULTS.arm("disagg.recv", "drop", times=2)

    events = [
        (1.0 + rng.random() * 0.8, kill_decode),
        (2.2 + rng.random() * 0.8, kill_decode),
        (1.6 + rng.random() * 0.6, kill_prefill),
        (1.2 + rng.random() * 0.5, drop_kv_frames),
        (2.8 + rng.random() * 0.5, drop_kv_frames),
        (4.2 + rng.random() * 0.5, lambda: partition_bus(0.4)),
    ]

    async def run_schedule():
        for delay, fn in sorted(events, key=lambda e: e[0]):
            await asyncio.sleep(
                max(0.0, delay - (time.monotonic() - t_start))
            )
            await fn()

    schedule = asyncio.ensure_future(run_schedule())

    # -- the load ---------------------------------------------------------
    prompts = [
        [rng.randrange(1, vocab) for _ in range(rng.choice((16, 48, 64)))]
        for _ in range(requests)
    ]

    async def one(idx: int, prompt: list[int]):
        await asyncio.sleep(idx * (4.0 / max(requests, 1)))
        req = PreprocessedRequest(
            token_ids=list(prompt),
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=osl, ignore_eos=True),
        )
        ctx = Context(req.to_wire())
        out: list[int] = []
        try:
            async for item in engine.generate(ctx):
                out += item.get("token_ids", [])
            want = expected_stream(prompt, osl, vocab)
            if out != want:
                return ("corrupt", time.monotonic() - t_start,
                        f"req {idx}: got {len(out)} tokens, "
                        f"mismatch vs closed form")
            return ("ok", time.monotonic() - t_start, "")
        except (
            ShedError, DeadlineError, FailoverExhausted, WorkerDiedError,
        ) as exc:
            return ("typed_error", time.monotonic() - t_start,
                    f"req {idx}: {type(exc).__name__}: {exc}")
        except Exception as exc:  # noqa: BLE001 — untyped = gate failure
            return ("untyped_error", time.monotonic() - t_start,
                    f"req {idx}: {type(exc).__name__}: {exc}")
        finally:
            tracer().finish(ctx.id)

    async def guarded(idx, prompt):
        try:
            return await asyncio.wait_for(one(idx, prompt), watchdog_s)
        except asyncio.TimeoutError:
            return ("hang", time.monotonic() - t_start, f"req {idx}: WATCHDOG")

    results = await asyncio.gather(
        *[guarded(i, p) for i, p in enumerate(prompts)]
    )
    await schedule
    # Let the healer finish replacing the last kills, then freeze it.
    for _ in range(60):
        live_d = sum(1 for h in decode_pool.handles if h.alive)
        live_p = sum(1 for h in prefill_pool.handles if h.alive)
        if (
            live_d >= decode_workers and live_p >= prefill_workers
            and replaced["n"] >= kills["decode"] + kills["prefill"]
        ):
            break
        await asyncio.sleep(0.15)
    healer.cancel()
    try:
        await healer
    except asyncio.CancelledError:
        pass
    FAULTS.clear()

    # -- gates -------------------------------------------------------------
    counts: dict[str, int] = {}
    for status, _, _ in results:
        counts[status] = counts.get(status, 0) + 1
    failures: list[str] = []
    if counts.get("hang"):
        failures.append(f"{counts['hang']} request(s) HUNG past the watchdog")
    if counts.get("untyped_error"):
        bad = [d for s, _, d in results if s == "untyped_error"]
        failures.append(f"untyped errors (must be typed): {bad[:3]}")
    if counts.get("corrupt"):
        bad = [d for s, _, d in results if s == "corrupt"]
        failures.append(f"corrupted streams across failover: {bad[:3]}")
    # Gate 2: typed errors are legitimate ONLY while a partition had the
    # fleet unreachable (plus settle slack) — kills alone never fail a
    # request when healthy capacity remains.
    pad = 3.0
    for status, t_done, detail in results:
        if status != "typed_error":
            continue
        if not any(w0 <= t_done <= w1 + pad for w0, w1 in partitions):
            failures.append(
                f"request failed OUTSIDE any partition window (healthy "
                f"capacity remained): {detail} at t={t_done:.2f}s "
                f"windows={partitions}"
            )
    live_decode = sum(1 for h in decode_pool.handles if h.alive)
    live_prefill = sum(1 for h in prefill_pool.handles if h.alive)
    if live_decode < decode_workers:
        failures.append(
            f"decode pool did not heal: {live_decode}/{decode_workers} alive"
        )
    if live_prefill < prefill_workers:
        failures.append(
            f"prefill pool did not heal: "
            f"{live_prefill}/{prefill_workers} alive"
        )
    total_kills = kills["decode"] + kills["prefill"]
    if replaced["n"] < total_kills:
        failures.append(
            f"crash path replaced {replaced['n']} < {total_kills} kills"
        )
    if kills["decode"] and FAILOVER.success_total < 1:
        failures.append(
            "decode workers were killed but no failover completed a "
            "request"
        )

    # -- teardown ----------------------------------------------------------
    for h in list(decode_pool.handles):
        try:
            if h.alive:
                await h.instance.stop()
            await h.engine.stop()
        except Exception:  # noqa: BLE001 — teardown
            pass
    for h in list(prefill_pool.handles):
        try:
            if h.alive and h.prefill is not None:
                await h.prefill.stop()
            await h.engine.stop()
        except Exception:  # noqa: BLE001 — teardown
            pass
    for h in graveyard:
        try:
            await h.engine.stop()
        except Exception:  # noqa: BLE001 — teardown
            pass
    await drt0.shutdown()

    degraded = FAILOVER.snapshot()
    report = {
        "seed": seed,
        "requests": requests,
        "resolved": sum(counts.values()),
        "ok": counts.get("ok", 0),
        "typed_errors": counts.get("typed_error", 0),
        "hangs": counts.get("hang", 0),
        "corrupt": counts.get("corrupt", 0),
        "kills": dict(kills),
        "replaced_dead": replaced["n"],
        "partitions": [
            (round(a, 2), round(b, 2)) for a, b in partitions
        ],
        "failover": degraded,
        "failover_success_total": FAILOVER.success_total,
        "workers_marked_dead_total": FAILOVER.marked_dead_total,
        "decode_pool_final": live_decode,
        "prefill_pool_final": live_prefill,
        "duration_s": round(time.monotonic() - t_start, 2),
        "failures": failures,
    }
    return report


def run_gates(report: dict) -> None:
    """Hard-fail on any gate violation (ci.sh leg + BENCH_CHAOS)."""
    if report["failures"]:
        raise RuntimeError(
            "CHAOS GATES FAILED:\n  " + "\n  ".join(report["failures"])
        )
    if report["resolved"] != report["requests"]:
        raise RuntimeError(
            f"only {report['resolved']}/{report['requests']} requests "
            f"resolved"
        )


def main(argv: list[str] | None = None) -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser(
        prog="python benchmarks/chaos_bench.py",
        description="seeded chaos-schedule proof over a mocker fleet",
    )
    ap.add_argument("--seed", type=int,
                    default=int(os.environ.get("BENCH_CHAOS_SEED", 1234)))
    ap.add_argument("--workers", type=int,
                    default=int(os.environ.get("BENCH_CHAOS_WORKERS", 4)))
    ap.add_argument("--requests", type=int,
                    default=int(os.environ.get("BENCH_CHAOS_REQUESTS", 24)))
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.WARNING)
    report = asyncio.run(run_chaos(
        seed=args.seed, decode_workers=args.workers,
        requests=args.requests,
    ))
    print(json.dumps(report, indent=2))
    run_gates(report)
    print("chaos gates: OK", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())

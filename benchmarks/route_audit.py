"""Close the predicted-vs-actual KV-reuse loop from trace captures.

The KV observatory (docs/architecture/observability.md) writes two record
kinds into the ``DYNTPU_TRACE`` capture:

- ``route``      router-side, at decision time (llm/kv_router/audit.py):
                 predicted ``overlap_blocks``, the full candidate score
                 field, the indexer's event watermark (applied/pending),
                 metrics-snapshot age, decision latency.
- ``kv_actual``  engine-side, at admission (engine/engine.py
                 ``_note_kv_actual``): blocks the request ACTUALLY reused,
                 split by tier (device G1 / host G2 / disk G3).

This tool joins them by trace id and reports what the router's one-way
``KVHitRateEvent`` never could: the predicted-vs-actual overlap-error
distribution, how much of the error correlates with indexer staleness
(pending events / stale metrics at score time), and the per-worker route
balance. ``--assert`` is the CI gate (ci.sh BENCH_ROUTE_AUDIT leg):

- join rate >= ``--min-join`` (default 0.95),
- orphan route records (a route whose trace never produced an
  engine-side actual — a seam dropping the loop's closing half) <=
  ``--max-orphan-routes``; the default 0 makes the effective CI
  requirement 100% joined — raise it (with ``--min-join`` as the floor)
  on runs where some routed requests legitimately never admit
  (shed/deadline under overload),
- at least one actual-reuse report (an engine that stops reporting
  actuals would otherwise pass vacuously).

Usage:
    python benchmarks/route_audit.py CAPTURE [CAPTURE ...]
        [--assert] [--min-join 0.95] [--max-orphan-routes 0] [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import defaultdict
from typing import Any

if __package__ in (None, ""):  # `python benchmarks/route_audit.py ...`
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )

from benchmarks.trace_merge import _expand_captures, _pct
from dynamo_tpu.utils.recorder import Recorder


def load_records(
    paths: list[str],
) -> tuple[list[dict], list[dict], list[dict]]:
    """All route / kv_actual / planner records across the capture set
    (pid-suffixed captures expand the same way trace_merge's do). The
    planner's ``kind="planner"`` scale decisions (planner/obs.py) share
    the capture; surfacing them next to the route records lets an audit
    window explain a routing-balance shift by the pool change that
    caused it."""
    routes: list[dict] = []
    actuals: list[dict] = []
    planner: list[dict] = []
    for path in _expand_captures(list(paths)):
        for _ts, rec in Recorder.load(path):
            kind = rec.get("kind")
            if kind == "route":
                routes.append(rec)
            elif kind == "kv_actual":
                actuals.append(rec)
            elif kind == "planner":
                planner.append(rec)
    return routes, actuals, planner


def _pctl(values: list[float], q: float) -> float:
    """Nearest-rank percentile over unsorted values (sorts, then reuses
    trace_merge's helper so the two tools can't drift)."""
    return _pct(sorted(values), q)


def join_report(
    routes: list[dict], actuals: list[dict], stale_pending_threshold: int = 1
) -> dict[str, Any]:
    """Join predicted↔actual by trace id and compute the audit report."""
    by_trace: dict[str, list[dict]] = defaultdict(list)
    for a in actuals:
        if a.get("trace"):
            by_trace[a["trace"]].append(a)

    joined: list[tuple[dict, dict]] = []
    orphan_routes: list[dict] = []
    for r in routes:
        hits = by_trace.get(r.get("trace") or "")
        if hits:
            # Disagg can produce one actual per executing process; the
            # prefill-side report (the one with reuse) wins — max total.
            best = max(
                hits,
                key=lambda a: a.get("device_blocks", 0)
                + a.get("host_blocks", 0)
                + a.get("disk_blocks", 0)
                + a.get("peer_blocks", 0),
            )
            joined.append((r, best))
        else:
            orphan_routes.append(r)

    joined_traces = {r.get("trace") for r, _ in joined}
    orphan_actuals = sum(
        1 for a in actuals if a.get("trace") and a["trace"] not in joined_traces
    )

    errors: list[float] = []
    abs_errors: list[float] = []
    stale_scored = 0
    stale_mispredicted = 0
    fresh_mispredicted = 0
    per_worker: dict[int, dict[str, float]] = defaultdict(
        lambda: {"routes": 0, "predicted_blocks": 0, "actual_blocks": 0}
    )
    # Per-ROUTER-REPLICA error (docs/architecture/ingress_scale.md): a
    # stale rejoined replica's mispredictions must be bounded as ITS
    # error, not averaged away across warm siblings.
    per_replica_abs: dict[int, list[float]] = defaultdict(list)
    per_replica_routes: dict[int, int] = defaultdict(int)
    staleness_pending: list[float] = []
    decision_ms: list[float] = []
    for r in routes:
        per_replica_routes[int(r.get("replica_id", 0))] += 1
    for r, a in joined:
        actual = (
            a.get("device_blocks", 0)
            + a.get("host_blocks", 0)
            + a.get("disk_blocks", 0)
            + a.get("peer_blocks", 0)
        )
        err = r.get("overlap_blocks", 0) - actual
        errors.append(err)
        abs_errors.append(abs(err))
        per_replica_abs[int(r.get("replica_id", 0))].append(abs(err))
        pending = (r.get("indexer") or {}).get("pending", 0)
        staleness_pending.append(pending)
        decision_ms.append(r.get("decision_ms", 0.0))
        stale = pending >= stale_pending_threshold
        if stale:
            stale_scored += 1
        if err != 0:
            if stale:
                stale_mispredicted += 1
            else:
                fresh_mispredicted += 1
        w = per_worker[r.get("worker_id", -1)]
        w["routes"] += 1
        w["predicted_blocks"] += r.get("overlap_blocks", 0)
        w["actual_blocks"] += actual

    tiers = {
        "device_blocks": sum(a.get("device_blocks", 0) for _, a in joined),
        "host_blocks": sum(a.get("host_blocks", 0) for _, a in joined),
        "disk_blocks": sum(a.get("disk_blocks", 0) for _, a in joined),
        "peer_blocks": sum(a.get("peer_blocks", 0) for _, a in joined),
    }
    route_counts = [w["routes"] for w in per_worker.values()]
    mispredicted = stale_mispredicted + fresh_mispredicted
    return {
        "routes": len(routes),
        "actuals": len(actuals),
        "joined": len(joined),
        "join_rate": round(len(joined) / max(len(routes), 1), 4),
        "orphan_routes": len(orphan_routes),
        "orphan_actuals": orphan_actuals,
        "overlap_error": {
            "mean": round(sum(errors) / max(len(errors), 1), 3),
            "abs_p50": _pctl(abs_errors, 0.50),
            "abs_p95": _pctl(abs_errors, 0.95),
            "abs_max": max(abs_errors, default=0),
            "exact": sum(1 for e in errors if e == 0),
            "underpredicted": sum(1 for e in errors if e < 0),
            "overpredicted": sum(1 for e in errors if e > 0),
        },
        "staleness": {
            # Indexer event-watermark staleness at score time, and how
            # mispredictions split across stale vs fresh decisions — the
            # attribution ROADMAP #5 gates router scale-out on.
            "pending_p50": _pctl(staleness_pending, 0.50),
            "pending_p99": _pctl(staleness_pending, 0.99),
            "pending_max": max(staleness_pending, default=0),
            "stale_scored": stale_scored,
            "mispredicted_total": mispredicted,
            "mispredicted_while_stale": stale_mispredicted,
            "mispredicted_while_fresh": fresh_mispredicted,
            "indexer_lag_p99_ms": max(
                ((r.get("indexer") or {}).get("lag_p99_ms", 0.0) for r in routes),
                default=0.0,
            ),
        },
        "decision_ms": {
            "p50": round(_pctl(decision_ms, 0.50), 3),
            "p95": round(_pctl(decision_ms, 0.95), 3),
        },
        "tier_split": tiers,
        "per_replica": {
            str(rid): {
                "routes": per_replica_routes[rid],
                "joined": len(per_replica_abs.get(rid, [])),
                "abs_p50": _pctl(per_replica_abs.get(rid, []), 0.50),
                "abs_p95": _pctl(per_replica_abs.get(rid, []), 0.95),
                "abs_max": max(per_replica_abs.get(rid, []), default=0),
                "exact": sum(
                    1 for e in per_replica_abs.get(rid, []) if e == 0
                ),
            }
            for rid in sorted(per_replica_routes)
        },
        "per_worker": {
            f"{wid:x}" if isinstance(wid, int) and wid >= 0 else str(wid): {
                "routes": int(w["routes"]),
                "predicted_blocks": int(w["predicted_blocks"]),
                "actual_blocks": int(w["actual_blocks"]),
            }
            for wid, w in sorted(per_worker.items(), key=lambda kv: str(kv[0]))
        },
        "balance": {
            "min_routes": min(route_counts, default=0),
            "max_routes": max(route_counts, default=0),
            "workers": len(per_worker),
        },
    }


def run_asserts(
    report: dict, min_join: float, max_orphan_routes: int = 0,
    max_abs_p95: float | None = None,
) -> list[str]:
    """The CI gates; returns the list of failures (empty = green)."""
    failures: list[str] = []
    if report["routes"] == 0:
        failures.append("no route records found — is the router auditing?")
    if report["actuals"] == 0:
        failures.append(
            "ZERO actual-reuse reports from the engine — the loop is open"
        )
    if report["join_rate"] < min_join and report["routes"]:
        failures.append(
            f"join rate {report['join_rate']:.2%} < required {min_join:.2%}"
        )
    if report["orphan_routes"] > max_orphan_routes:
        failures.append(
            f"{report['orphan_routes']} ORPHAN route record(s) "
            f"(allowed {max_orphan_routes}): routed requests whose trace "
            "never produced an engine-side actual"
        )
    if max_abs_p95 is not None:
        # The multi-replica error bound (docs/architecture/
        # ingress_scale.md): EVERY replica's |predicted - actual| p95
        # must hold — one stale replica failing inside a healthy fleet
        # average is exactly the drift this gate exists to catch.
        for rid, rep in sorted(report.get("per_replica", {}).items()):
            if rep["joined"] and rep["abs_p95"] > max_abs_p95:
                failures.append(
                    f"replica {rid}: overlap-error |p95| {rep['abs_p95']}"
                    f" blocks > bound {max_abs_p95} "
                    f"({rep['joined']} joined routes)"
                )
    return failures


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("captures", nargs="+", help="DYNTPU_TRACE capture(s)/base(s)")
    ap.add_argument(
        "--assert", dest="do_assert", action="store_true",
        help="exit 1 unless the CI gates hold",
    )
    ap.add_argument("--min-join", type=float, default=0.95)
    ap.add_argument(
        "--max-orphan-routes", type=int, default=0,
        help="tolerated routes with no engine-side actual (default 0: "
        "every routed request must close the loop)",
    )
    ap.add_argument(
        "--stale-pending", type=int, default=1,
        help="pending events at score time >= N counts as a stale decision",
    )
    ap.add_argument(
        "--max-abs-p95", type=float, default=None,
        help="bound EVERY router replica's |predicted - actual| overlap "
        "error p95 (blocks); off by default",
    )
    ap.add_argument("--json", action="store_true", help="report as JSON only")
    args = ap.parse_args(argv)

    routes, actuals, planner = load_records(args.captures)
    report = join_report(routes, actuals, args.stale_pending)
    # Planner context for the window: pool scale events that reshape the
    # very worker set the routes were balanced across.
    report["planner_decisions"] = {
        "total": len(planner),
        "scale_events": [
            {k: r.get(k) for k in ("pool", "decision", "size", "unix")}
            for r in planner if r.get("decision") in ("up", "down")
        ],
    }

    print(json.dumps(report, indent=2, sort_keys=True))
    if not args.json:
        oe, st = report["overlap_error"], report["staleness"]
        ts = report["tier_split"]
        print(
            f"\nroute audit: {report['joined']}/{report['routes']} joined "
            f"({report['join_rate']:.1%}), overlap error |p95| {oe['abs_p95']}"
            f" blocks, {st['mispredicted_total']} mispredictions "
            f"({st['mispredicted_while_stale']} while the indexer was stale)",
            file=sys.stderr,
        )
        print(
            "tier split (actual reuse blocks): "
            f"G1 {ts['device_blocks']} | G2 {ts['host_blocks']} | "
            f"G3 {ts['disk_blocks']} | G4 {ts['peer_blocks']}",
            file=sys.stderr,
        )

    if args.do_assert:
        failures = run_asserts(
            report, args.min_join, args.max_orphan_routes,
            max_abs_p95=args.max_abs_p95,
        )
        if failures:
            for f in failures:
                print(f"ROUTE AUDIT FAIL: {f}", file=sys.stderr)
            return 1
        print("route audit: all gates passed", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())

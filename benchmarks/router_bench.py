"""KV-aware vs random routing A/B on real engines: follow-up-turn TTFT.

The reference's headline routing claim: KV-aware routing delivers 3x TTFT
and 2x average request latency over random load balancing on a reuse-heavy
workload (100K real R1 queries; reference: docs/architecture/
architecture.md:86-91). This bench is the one-chip analogue: two REAL
TpuEngine workers (shared weight buffers, separate KV arenas) behind the
production routing plane — KvEventPublisher -> bus -> radix indexer ->
PushRouter KV mode — versus the same deployment routed RANDOM. S sessions
each send a long first turn, then a follow-up turn sharing the full
history; KV mode pins the follow-up to the worker holding the prefix
(prefill = the fresh suffix only), random sends ~half of them cold.

Run via `BENCH_ROUTER=1 python bench.py`. Knobs: BENCH_ROUTER_SESSIONS,
BENCH_ROUTER_PREFIX, BENCH_MODEL.
"""

from __future__ import annotations

import asyncio
import os
import time

import numpy as np

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.llm.kv_router.publisher import (
    KvEventPublisher,
    WorkerMetricsPublisher,
)
from dynamo_tpu.llm.kv_router.router import KvRouter
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.egress import PushRouter, RouterMode
from dynamo_tpu.runtime.engine import Context

SESSIONS = int(os.environ.get("BENCH_ROUTER_SESSIONS", 16))
PREFIX = int(os.environ.get("BENCH_ROUTER_PREFIX", 1024))
TURN1_OSL = 16
DELTA = 32
TURN2_OSL = 16
CONCURRENCY = 4


def _cfg() -> EngineConfig:
    model = getattr(ModelConfig, os.environ.get("BENCH_MODEL", "llama32_1b"))()
    return EngineConfig(
        model=model,
        # Each worker can hold every session's prefix (routing decides
        # placement, not capacity).
        num_blocks=SESSIONS * (PREFIX // 16 + 8) + 256,
        block_size=16,
        max_num_seqs=8,
        max_model_len=1 << (PREFIX + TURN1_OSL + DELTA + TURN2_OSL).bit_length(),
        decode_chunk=8,
        prefill_batch=4,
        enable_prefix_caching=True,
        quant=os.environ.get("DYNAMO_TPU_QUANT") or None,
    )


async def _spawn_worker(drt, component, cfg, params):
    wm = WorkerMetricsPublisher()
    pub = KvEventPublisher(drt, component, drt.primary_lease_id)
    if params is not None and cfg.quant:
        # Shared params arrive ALREADY quantized — a quant mode here would
        # re-quantize the int8 tree (same guard as the disagg bench).
        import dataclasses

        cfg = dataclasses.replace(cfg, quant=None)
    engine = TpuEngine(
        cfg,
        params=params,
        on_kv_event=pub.publish_engine_event,
        on_metrics=wm.publish,
    )
    await engine.start()
    await component.endpoint("generate").serve(engine)
    await wm.create_endpoint(component)
    # Buckets: the post-hit suffix, the turn-1 prompt, and the FULL turn-2
    # length (the cold-routed case) — an unwarmed bucket compiling inside
    # the measured phase would masquerade as a routing effect.
    await engine.warmup(
        prompt_buckets=[
            DELTA + TURN1_OSL, PREFIX, PREFIX + TURN1_OSL + DELTA,
        ]
    )
    return engine


async def _send(push, tokens: list[int], osl: int):
    req = PreprocessedRequest(
        token_ids=tokens,
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=osl, ignore_eos=True),
    )
    t0 = time.monotonic()
    ttft = None
    out: list[int] = []
    async for item in push.generate(Context(req.to_wire())):
        if item.get("token_ids") and ttft is None:
            ttft = time.monotonic() - t0
        out += item.get("token_ids", [])
    return ttft, time.monotonic() - t0, out


async def _run_mode(kv_mode: bool, prompts: list[list[int]], params):
    cfg = _cfg()
    drt_a = await DistributedRuntime.in_process()
    drt_b = await DistributedRuntime.in_process(
        store=drt_a.store, bus=drt_a.bus, runtime=drt_a.runtime
    )
    comp_a = drt_a.namespace("bench").component("worker")
    comp_b = drt_b.namespace("bench").component("worker")
    eng_a = await _spawn_worker(drt_a, comp_a, cfg, params)
    # Worker B shares A's (possibly quantized) weight buffers.
    eng_b = await _spawn_worker(drt_b, comp_b, cfg, eng_a.runner.params)

    router = None
    if kv_mode:
        router = await KvRouter(drt_a, comp_a).start()
        push = await PushRouter.create(
            drt_a,
            "bench.worker.generate",
            mode=RouterMode.KV,
            selector=router.selector_fn,
        )
    else:
        push = await PushRouter.create(
            drt_a, "bench.worker.generate", mode=RouterMode.RANDOM
        )

    sem = asyncio.Semaphore(CONCURRENCY)

    async def bounded(tokens, osl):
        async with sem:
            return await _send(push, tokens, osl)

    # Turn 1: build every session's prefix on whichever worker the mode
    # picks.
    t1 = await asyncio.gather(
        *[bounded(p, TURN1_OSL) for p in prompts]
    )
    turn1_out = [out for _, _, out in t1]
    await asyncio.sleep(0.5)  # KV events -> indexer

    # Turn 2: the measured phase — full-history follow-ups.
    t2 = await asyncio.gather(
        *[
            bounded(p + o + p[:DELTA], TURN2_OSL)
            for p, o in zip(prompts, turn1_out)
        ]
    )
    ttfts = [t for t, _, _ in t2]
    lats = [l for _, l, _ in t2]

    hits = eng_a._prefix_hits + eng_b._prefix_hits
    lookups = eng_a._prefix_lookups + eng_b._prefix_lookups
    stats = {
        "p50_ttft_ms": round(1000 * float(np.median(ttfts)), 1),
        "p95_ttft_ms": round(1000 * float(np.percentile(ttfts, 95)), 1),
        "mean_latency_ms": round(1000 * float(np.mean(lats)), 1),
        "prefix_hit_rate": round(hits / max(lookups, 1), 3),
        "worker_split": [eng_a._prefix_lookups, eng_b._prefix_lookups],
    }
    out_params = eng_a.runner.params
    if router is not None:
        await router.stop()
    await eng_a.stop()
    await eng_b.stop()
    await drt_a.shutdown()
    return stats, [o for _, _, o in t2], out_params


def main() -> dict:
    rng = np.random.default_rng(11)
    cfg = _cfg()
    prompts = [
        rng.integers(0, cfg.model.vocab_size, PREFIX).tolist()
        for _ in range(SESSIONS)
    ]

    async def run() -> dict:
        rnd, rnd_outs, params = await _run_mode(False, prompts, None)
        kv, kv_outs, _ = await _run_mode(True, prompts, params)
        return {
            "metric": f"kv_routing_ttft_speedup_prefix{PREFIX}_s{SESSIONS}",
            # Follow-up-turn p50 TTFT, random over KV-aware (reference bar:
            # 3x TTFT / 2x avg latency, architecture.md:86-91).
            "value": round(
                rnd["p50_ttft_ms"] / max(kv["p50_ttft_ms"], 1e-9), 3
            ),
            "unit": "x (random p50 TTFT over kv-aware; ref bar 3x)",
            "vs_baseline": round(
                rnd["p50_ttft_ms"] / max(kv["p50_ttft_ms"], 1e-9), 3
            ),
            "extras": {
                "random": rnd,
                "kv_aware": kv,
                "latency_speedup": round(
                    rnd["mean_latency_ms"] / max(kv["mean_latency_ms"], 1e-9),
                    3,
                ),
                "turn2_tokens_identical": rnd_outs == kv_outs,
                "sessions": SESSIONS,
                "prefix_tokens": PREFIX,
                "concurrency": CONCURRENCY,
            },
        }

    return asyncio.run(run())


if __name__ == "__main__":
    import json

    print(json.dumps(main()))

"""KV-block transfer benchmark: device path (HBM→HBM) vs host-staged TCP.

VERDICT r02 #6's acceptance gate: the same-process device path must move
blocks ≥5× faster than gather→TCP→scatter. Run on the real chip:

    python benchmarks/transfer_bench.py

Prints one JSON line with blocks/s for both paths and the speedup.
"""

from __future__ import annotations

import asyncio
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))
import json
import time

import jax
import numpy as np

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.runner import ModelRunner
from dynamo_tpu.models.config import ModelConfig

N_BLOCKS = 48
N_ROUNDS = 3


def _cfg() -> EngineConfig:
    return EngineConfig(
        model=ModelConfig.llama32_1b(),
        num_blocks=max(64, N_BLOCKS + 2),
        max_num_seqs=4,
        max_model_len=512,
    )


def bench_device(src: ModelRunner, dst: ModelRunner) -> float:
    # warm the two programs
    dst.scatter_block(1, src.gather_block_device(1))
    jax.block_until_ready(dst.kv_caches[0][0])
    t0 = time.monotonic()
    for _ in range(N_ROUNDS):
        for i in range(1, N_BLOCKS + 1):
            dst.scatter_block(i, src.gather_block_device(i))
    jax.block_until_ready(dst.kv_caches[0][0])
    return N_ROUNDS * N_BLOCKS / (time.monotonic() - t0)


async def bench_tcp(src: ModelRunner, dst: ModelRunner) -> float:
    from dynamo_tpu.disagg.transfer import KvReceiver, KvSender

    done = asyncio.Event()

    def on_block(req: str, idx: int, data) -> None:
        dst.scatter_block(idx + 1, data)

    def on_finish(req: str, tok: int) -> None:
        done.set()

    receiver = await KvReceiver(on_block=on_block, on_finish=on_finish).start()
    sender = KvSender()
    # warm connections + programs off the clock
    warm = [np.asarray(src.gather_block(1))]
    await sender.send_blocks(receiver.address, "warm", warm, 0, auth=receiver.auth)
    await asyncio.wait_for(done.wait(), 30)

    t0 = time.monotonic()
    for r in range(N_ROUNDS):
        # The old path end to end: HBM→host gather, TCP, host→HBM scatter.
        blocks = [np.asarray(src.gather_block(i)) for i in range(1, N_BLOCKS + 1)]
        done.clear()
        await sender.send_blocks(
            receiver.address, f"r{r}", blocks, 0, auth=receiver.auth
        )
        await asyncio.wait_for(done.wait(), 60)
    jax.block_until_ready(dst.kv_caches[0][0])
    rate = N_ROUNDS * N_BLOCKS / (time.monotonic() - t0)
    await sender.close()
    await receiver.stop()
    return rate


def bench_host_per_block(src: ModelRunner, dst: ModelRunner) -> float:
    """The r03-era host roundtrip: one dispatch per block each way."""
    dst.scatter_block(1, src.gather_block(1))
    jax.block_until_ready(dst.kv_caches[0][0])
    t0 = time.monotonic()
    for _ in range(N_ROUNDS):
        for i in range(1, N_BLOCKS + 1):
            dst.scatter_block(i, src.gather_block(i))
    jax.block_until_ready(dst.kv_caches[0][0])
    return N_ROUNDS * N_BLOCKS / (time.monotonic() - t0)


def bench_host_batched(src: ModelRunner, dst: ModelRunner) -> float:
    """The batched host roundtrip (one program for all N blocks each way) —
    the KVBM offload/onboard primitive (ops/kv_copy.py gather_blocks/
    scatter_blocks)."""
    idxs = list(range(1, N_BLOCKS + 1))
    dst.scatter_many(idxs, src.gather_many(idxs))
    jax.block_until_ready(dst.kv_caches[0][0])
    t0 = time.monotonic()
    for _ in range(N_ROUNDS):
        dst.scatter_many(idxs, src.gather_many(idxs))
    jax.block_until_ready(dst.kv_caches[0][0])
    return N_ROUNDS * N_BLOCKS / (time.monotonic() - t0)


def bench_device_batched(src: ModelRunner, dst: ModelRunner) -> float:
    """Batched HBM→HBM: one gather program + one scatter program for all N
    blocks, snapshot never leaves the device."""
    idxs = list(range(1, N_BLOCKS + 1))
    from dynamo_tpu.ops.kv_copy import gather_blocks_device, scatter_blocks

    def move():
        snap = gather_blocks_device(src.kv_caches, idxs, src.cfg.block_size)
        dst.kv_caches = scatter_blocks(
            dst.kv_caches, idxs, dst.cfg.block_size, snap
        )

    move()
    jax.block_until_ready(dst.kv_caches[0][0])
    t0 = time.monotonic()
    for _ in range(N_ROUNDS):
        move()
    jax.block_until_ready(dst.kv_caches[0][0])
    return N_ROUNDS * N_BLOCKS / (time.monotonic() - t0)


def main() -> None:
    src = ModelRunner(_cfg())
    dst = ModelRunner(_cfg())
    m = _cfg().model
    block_bytes = (
        m.num_layers * 2 * _cfg().block_size * m.num_kv_heads
        * src.cache_head_dim * np.dtype(_cfg().dtype).itemsize
    )
    dev = bench_device(src, dst)
    dev_b = bench_device_batched(src, dst)
    host_pb = bench_host_per_block(src, dst)
    host_b = bench_host_batched(src, dst)
    tcp = asyncio.run(bench_tcp(src, dst))
    print(
        json.dumps(
            {
                "metric": "kv_block_transfer",
                "block_bytes": block_bytes,
                "device_blocks_per_s": round(dev, 1),
                "device_batched_blocks_per_s": round(dev_b, 1),
                "host_roundtrip_blocks_per_s": round(host_pb, 1),
                "host_roundtrip_batched_blocks_per_s": round(host_b, 1),
                "tcp_blocks_per_s": round(tcp, 1),
                "device_gbps": round(dev * block_bytes / 1e9, 2),
                "device_batched_gbps": round(dev_b * block_bytes / 1e9, 2),
                "host_batched_gbps": round(host_b * block_bytes / 1e9, 2),
                "tcp_gbps": round(tcp * block_bytes / 1e9, 2),
                "speedup": round(dev / tcp, 1),
                "batch_speedup_device": round(dev_b / dev, 1),
                "batch_speedup_host": round(host_b / host_pb, 1),
            }
        )
    )


if __name__ == "__main__":
    main()

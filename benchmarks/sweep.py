"""Concurrency sweep + agg-vs-disagg comparison harness.

The reference's perf story is exactly these comparisons (reference:
examples/llm/benchmarks/perf.sh — genai-perf concurrency 1→256 sweep;
docs/architecture/architecture.md:75-99 — disagg vs agg headline numbers).

`sweep(engine_like, ...)` drives any AsyncEngine with PreprocessedRequest
wire payloads at fixed concurrency levels and reports per-level
throughput + TTFT/ITL percentiles. ITL is per-request mean inter-token
time ((last−first)/(n−1)) — honest under chunked streaming, where raw
inter-chunk gaps would mix 0s with chunk periods.

Run standalone against the mocker (no device needed):

    python benchmarks/sweep.py            # sweep + agg-vs-disagg on mocker
"""

from __future__ import annotations

import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np

from benchmarks.synthesizer import Request, WorkloadConfig, generate
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.engine import Context


def _pct(xs: list[float], q: float) -> float | None:
    return round(1000 * float(np.percentile(xs, q)), 1) if xs else None


async def run_level(engine, reqs: list[Request], concurrency: int) -> dict:
    """Drive `reqs` at a fixed concurrency; returns the level's metrics."""
    sem = asyncio.Semaphore(concurrency)

    async def one(r: Request):
        async with sem:
            pre = PreprocessedRequest(
                token_ids=list(r.token_ids),
                sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=r.max_tokens, ignore_eos=True),
            )
            t0 = time.monotonic()
            first = last = None
            n = 0
            async for out in engine.generate(Context(pre.to_wire())):
                toks = out.get("token_ids") or []
                if toks:
                    now = time.monotonic()
                    if first is None:
                        first = now
                    last = now
                    n += len(toks)
            return t0, first, last, n

    t0 = time.monotonic()
    results = await asyncio.gather(*[one(r) for r in reqs])
    elapsed = time.monotonic() - t0

    ttfts = [f - t for t, f, _, _ in results if f is not None]
    itls = [
        (last - first) / (n - 1)
        for _, first, last, n in results
        if first is not None and last is not None and n > 1
    ]
    total = sum(n for _, _, _, n in results)
    return {
        "concurrency": concurrency,
        "requests": len(reqs),
        "elapsed_s": round(elapsed, 2),
        "tok_per_s": round(total / elapsed, 1),
        "p50_ttft_ms": _pct(ttfts, 50),
        "p95_ttft_ms": _pct(ttfts, 95),
        "p50_itl_ms": _pct(itls, 50),
        "p95_itl_ms": _pct(itls, 95),
    }


async def sweep(
    engine,
    levels: tuple[int, ...] = (1, 2, 4, 8, 16, 32, 64),
    requests_per_level: int = 16,
    workload: WorkloadConfig | None = None,
) -> list[dict]:
    wl = workload or WorkloadConfig(num_requests=requests_per_level)
    out = []
    for c in levels:
        reqs = generate(
            WorkloadConfig(**{**wl.__dict__, "seed": wl.seed + c})
        )[:requests_per_level]
        out.append(await run_level(engine, reqs, c))
    return out


# ---------------------------------------------------------------------------
# Standalone: mocker sweep + agg-vs-disagg comparison.
# ---------------------------------------------------------------------------


def _mock_engine(max_len: int = 512):
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.mocker.engine import MockerConfig, MockerEngine
    from dynamo_tpu.models.config import ModelConfig

    return MockerEngine(
        EngineConfig(
            model=ModelConfig.tiny_test(),
            num_blocks=512,
            max_num_seqs=16,
            max_model_len=max_len,
            decode_chunk=4,
        ),
        MockerConfig(),
    )


async def _agg_vs_disagg(reqs: list[Request]) -> dict:
    """Same workload through one aggregated mocker vs a prefill/decode
    mocker pair over the real disagg operators (queue + transfer plane)."""
    from dynamo_tpu.disagg import (
        DecodeOperator,
        DisaggConfig,
        DisaggRouter,
        PrefillQueue,
        PrefillWorker,
    )
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    agg = _mock_engine()
    await agg.start()
    agg_res = await run_level(agg, reqs, concurrency=16)
    await agg.stop()

    drt = await DistributedRuntime.in_process()
    queue = PrefillQueue(drt, "bench")
    dis = DisaggRouter(
        drt,
        "bench",
        DisaggConfig(max_local_prefill_length=32, max_prefill_queue_size=64),
    )
    decode = _mock_engine()
    await decode.start()
    prefill = _mock_engine()
    await prefill.start()
    op = await DecodeOperator(decode, queue, dis, transport="tcp").start()
    pw = PrefillWorker(prefill, queue).start()
    disagg_res = await run_level(op, reqs, concurrency=16)
    await pw.stop()
    await op.stop()
    await decode.stop()
    await prefill.stop()
    await drt.shutdown()
    return {
        "agg": agg_res,
        "disagg": disagg_res,
        "remote_prefills": op.remote_count,
        "disagg_vs_agg_tok_per_s": round(
            disagg_res["tok_per_s"] / max(agg_res["tok_per_s"], 1e-9), 2
        ),
    }


async def _main() -> None:
    from benchmarks.synthesizer import prefix_stats

    engine = _mock_engine()
    await engine.start()
    wl = WorkloadConfig(num_requests=16, isl_mean=96, osl_mean=16)
    levels = await sweep(engine, levels=(1, 4, 16, 64), workload=wl)
    await engine.stop()

    reqs = generate(WorkloadConfig(num_requests=32, isl_mean=96, osl_mean=16))
    comparison = await _agg_vs_disagg(reqs)
    print(
        json.dumps(
            {
                "metric": "mocker_sweep",
                "workload": prefix_stats(reqs),
                "sweep": levels,
                "agg_vs_disagg": comparison,
            }
        )
    )


if __name__ == "__main__":
    asyncio.run(_main())

"""Synthetic workload generator with prefix-tree structure.

Role of the reference's Mooncake-trace synthesizer (reference:
benchmarks/data_generator/synthesizer.py:48-75 — radix-structure-preserving
prompt generation with tunable length/speedup multipliers): produce
workloads whose prompts share realistic prefix structure, so prefix caching
and KV-aware routing have something to bite on.

Model: a random prefix tree. Each node carries a run of tokens; a request
samples a root→node path (its shared prefix) plus a unique suffix. Depth-1
nodes are "system prompts", deeper nodes are conversation turns. With
``reuse=0`` every prompt is unique; with high reuse most requests share
long prefixes.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass
class WorkloadConfig:
    num_requests: int = 64
    isl_mean: int = 128           # mean prompt length (tokens)
    osl_mean: int = 32            # mean generation length
    reuse: float = 0.5            # fraction of a prompt drawn from the tree
    branching: int = 3            # children per tree node
    depth: int = 3                # tree depth
    vocab_size: int = 32000
    arrival_rate: float = 0.0     # req/s Poisson arrivals; 0 = all at once
    seed: int = 0


@dataclass
class Request:
    token_ids: list[int]
    max_tokens: int
    arrival_s: float = 0.0
    prefix_len: int = 0           # tokens shared with at least one sibling
    request_id: str = ""


@dataclass
class _Node:
    tokens: list[int]
    children: list["_Node"] = field(default_factory=list)


def generate(cfg: WorkloadConfig) -> list[Request]:
    rng = np.random.default_rng(cfg.seed)
    prefix_budget = max(1, int(cfg.isl_mean * cfg.reuse))
    run_len = max(1, prefix_budget // max(cfg.depth, 1))

    def grow(depth: int) -> _Node:
        node = _Node(
            tokens=rng.integers(0, cfg.vocab_size, run_len).tolist()
        )
        if depth < cfg.depth:
            node.children = [grow(depth + 1) for _ in range(cfg.branching)]
        return node

    root = grow(1)

    def sample_path() -> list[int]:
        out: list[int] = []
        node = root
        while True:
            out += node.tokens
            if not node.children or rng.random() < 0.25:
                return out
            node = node.children[int(rng.integers(len(node.children)))]

    reqs: list[Request] = []
    t = 0.0
    for i in range(cfg.num_requests):
        prefix = sample_path() if cfg.reuse > 0 else []
        suffix_len = max(
            1, int(rng.normal(cfg.isl_mean - len(prefix), cfg.isl_mean * 0.1))
        )
        tokens = prefix + rng.integers(0, cfg.vocab_size, suffix_len).tolist()
        osl = max(1, int(rng.normal(cfg.osl_mean, cfg.osl_mean * 0.25)))
        if cfg.arrival_rate > 0:
            t += float(rng.exponential(1.0 / cfg.arrival_rate))
        reqs.append(
            Request(
                token_ids=tokens,
                max_tokens=osl,
                arrival_s=t,
                prefix_len=len(prefix),
                request_id=f"synth-{i}",
            )
        )
    return reqs


def prefix_stats(reqs: list[Request]) -> dict:
    """Prefix-analyzer-style summary (reference: prefix_analyzer.py)."""
    total = sum(len(r.token_ids) for r in reqs)
    shared = sum(r.prefix_len for r in reqs)
    return {
        "requests": len(reqs),
        "total_tokens": total,
        "mean_isl": round(total / max(len(reqs), 1), 1),
        "mean_osl": round(
            sum(r.max_tokens for r in reqs) / max(len(reqs), 1), 1
        ),
        "shared_prefix_fraction": round(shared / max(total, 1), 3),
    }


# ---------------------------------------------------------------------------
# Trace-driven replay (VERDICT r03 missing #5). Two on-disk formats:
#
# - Mooncake-format JSONL (the reference synthesizer's input —
#   reference: benchmarks/data_generator/synthesizer.py:48-75): one record
#   per request, {"timestamp": ms, "input_length": N, "output_length": M,
#   "hash_ids": [...]}, where hash_ids name the request's 512-token prefix
#   blocks and SHARED ids across requests encode the real reuse structure.
#   Tokens are reconstructed deterministically per hash id, so two requests
#   sharing hash ids share the exact same token prefix — the radix
#   structure of the production trace is preserved while the actual text
#   (which the trace does not contain) is synthesized.
#
# - Our own request JSONL ({"token_ids": [...], "max_tokens": N,
#   "arrival_s": t} per line; save_request_jsonl writes it) — capture any
#   served workload and replay it bit-for-bit.
# ---------------------------------------------------------------------------


def from_mooncake_trace(
    path,
    vocab_size: int = 32000,
    block_size: int = 512,
    speedup_ratio: float = 1.0,
    max_requests: int | None = None,
    seed: int = 0,
) -> list[Request]:
    """Rebuild a replayable request list from a Mooncake-format trace,
    preserving its prefix-reuse structure and (speedup-scaled) arrival
    times."""
    import json

    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    if max_requests is not None:
        records = records[:max_requests]

    # Pass 1: hash-id occurrence counts (a block shared by 2+ requests is
    # "context" in the reference's terms — it is what routers/caches can
    # reuse).
    counts: dict[int, int] = {}
    for rec in records:
        for h in rec.get("hash_ids", []):
            counts[h] = counts.get(h, 0) + 1

    runs: dict[int, list[int]] = {}

    def run_for(h: int) -> list[int]:
        if h not in runs:
            rng = np.random.default_rng((seed + 1) * 1_000_003 + int(h))
            runs[h] = rng.integers(0, vocab_size, block_size).tolist()
        return runs[h]

    reqs: list[Request] = []
    t0 = None
    for i, rec in enumerate(records):
        ts = float(rec.get("timestamp", 0)) / 1000.0
        t0 = ts if t0 is None else t0
        hash_ids = list(rec.get("hash_ids", []))
        isl = int(rec.get("input_length", block_size * len(hash_ids)))
        tokens: list[int] = []
        shared = 0
        still_shared = True
        for j, h in enumerate(hash_ids):
            n = min(block_size, isl - j * block_size)
            if n <= 0:
                break
            tokens += run_for(h)[:n]
            if still_shared and counts.get(h, 0) > 1:
                shared += n
            else:
                still_shared = False
        if len(tokens) < isl:  # tail beyond hashed blocks = unique suffix
            rng = np.random.default_rng((seed + 1) * 7_000_003 + i)
            tokens += rng.integers(0, vocab_size, isl - len(tokens)).tolist()
        reqs.append(Request(
            token_ids=tokens,
            max_tokens=max(1, int(rec.get("output_length", 1))),
            arrival_s=max(0.0, (ts - t0) / max(speedup_ratio, 1e-9)),
            prefix_len=shared,
            request_id=f"trace-{i}",
        ))
    return reqs


def save_request_jsonl(reqs: list[Request], path) -> None:
    """Write requests in our replayable capture format."""
    import json

    # Streamed line-by-line; a torn capture fails replay loudly.
    # dynalint: allow[DT013] bench artifact regenerated per run
    with open(path, "w") as f:
        for r in reqs:
            f.write(json.dumps({
                "token_ids": r.token_ids,
                "max_tokens": r.max_tokens,
                "arrival_s": r.arrival_s,
                "prefix_len": r.prefix_len,
                "request_id": r.request_id,
            }) + "\n")


def load_request_jsonl(path) -> list[Request]:
    import json

    reqs = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            reqs.append(Request(
                token_ids=list(rec["token_ids"]),
                max_tokens=int(rec.get("max_tokens", 1)),
                arrival_s=float(rec.get("arrival_s", 0.0)),
                prefix_len=int(rec.get("prefix_len", 0)),
                request_id=rec.get("request_id") or f"replay-{i}",
            ))
    return reqs

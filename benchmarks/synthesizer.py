"""Synthetic workload generator with prefix-tree structure.

Role of the reference's Mooncake-trace synthesizer (reference:
benchmarks/data_generator/synthesizer.py:48-75 — radix-structure-preserving
prompt generation with tunable length/speedup multipliers): produce
workloads whose prompts share realistic prefix structure, so prefix caching
and KV-aware routing have something to bite on.

Model: a random prefix tree. Each node carries a run of tokens; a request
samples a root→node path (its shared prefix) plus a unique suffix. Depth-1
nodes are "system prompts", deeper nodes are conversation turns. With
``reuse=0`` every prompt is unique; with high reuse most requests share
long prefixes.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass
class WorkloadConfig:
    num_requests: int = 64
    isl_mean: int = 128           # mean prompt length (tokens)
    osl_mean: int = 32            # mean generation length
    reuse: float = 0.5            # fraction of a prompt drawn from the tree
    branching: int = 3            # children per tree node
    depth: int = 3                # tree depth
    vocab_size: int = 32000
    arrival_rate: float = 0.0     # req/s Poisson arrivals; 0 = all at once
    seed: int = 0


@dataclass
class Request:
    token_ids: list[int]
    max_tokens: int
    arrival_s: float = 0.0
    prefix_len: int = 0           # tokens shared with at least one sibling
    request_id: str = ""


@dataclass
class _Node:
    tokens: list[int]
    children: list["_Node"] = field(default_factory=list)


def generate(cfg: WorkloadConfig) -> list[Request]:
    rng = np.random.default_rng(cfg.seed)
    prefix_budget = max(1, int(cfg.isl_mean * cfg.reuse))
    run_len = max(1, prefix_budget // max(cfg.depth, 1))

    def grow(depth: int) -> _Node:
        node = _Node(
            tokens=rng.integers(0, cfg.vocab_size, run_len).tolist()
        )
        if depth < cfg.depth:
            node.children = [grow(depth + 1) for _ in range(cfg.branching)]
        return node

    root = grow(1)

    def sample_path() -> list[int]:
        out: list[int] = []
        node = root
        while True:
            out += node.tokens
            if not node.children or rng.random() < 0.25:
                return out
            node = node.children[int(rng.integers(len(node.children)))]

    reqs: list[Request] = []
    t = 0.0
    for i in range(cfg.num_requests):
        prefix = sample_path() if cfg.reuse > 0 else []
        suffix_len = max(
            1, int(rng.normal(cfg.isl_mean - len(prefix), cfg.isl_mean * 0.1))
        )
        tokens = prefix + rng.integers(0, cfg.vocab_size, suffix_len).tolist()
        osl = max(1, int(rng.normal(cfg.osl_mean, cfg.osl_mean * 0.25)))
        if cfg.arrival_rate > 0:
            t += float(rng.exponential(1.0 / cfg.arrival_rate))
        reqs.append(
            Request(
                token_ids=tokens,
                max_tokens=osl,
                arrival_s=t,
                prefix_len=len(prefix),
                request_id=f"synth-{i}",
            )
        )
    return reqs


def prefix_stats(reqs: list[Request]) -> dict:
    """Prefix-analyzer-style summary (reference: prefix_analyzer.py)."""
    total = sum(len(r.token_ids) for r in reqs)
    shared = sum(r.prefix_len for r in reqs)
    return {
        "requests": len(reqs),
        "total_tokens": total,
        "mean_isl": round(total / max(len(reqs), 1), 1),
        "mean_osl": round(
            sum(r.max_tokens for r in reqs) / max(len(reqs), 1), 1
        ),
        "shared_prefix_fraction": round(shared / max(total, 1), 3),
    }

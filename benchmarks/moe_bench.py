"""MoE dispatch microbenchmark: dense (all experts, gate-masked) vs
capacity (per-expert buffers, selected FLOPs only), single-device and
under an ep-sharded mesh (VERDICT r03 #7).

Dense computes E/topk times the selected FLOPs; capacity pays
scatter/gather dispatch. This measures the crossover that backs the
"auto" default (models/moe.py AUTO_CAPACITY_MIN_EXPERTS) and verifies
token-identical outputs between the two formulations (ample capacity).

Run on the real chip: ``python benchmarks/moe_bench.py``
Virtual 8-device ep mesh: ``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 python benchmarks/moe_bench.py --mesh ep=8``
"""

from __future__ import annotations

import argparse
import time

import numpy as np


def run(cfg_kw, T, mesh=None, iters=8):
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.models.moe import MoeConfig, init_moe_params, moe_mlp

    results = {}
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((T, cfg_kw["hidden_size"])),
        jnp.float32,
    )
    params = init_moe_params(
        jax.random.PRNGKey(0), MoeConfig(**cfg_kw), dtype=jnp.float32
    )
    if mesh is not None:
        from dynamo_tpu.models.moe import shard_moe_params

        params = shard_moe_params(params, mesh)
    outs = {}
    for mode in ("dense", "capacity"):
        cfg = MoeConfig(**cfg_kw, dispatch=mode, capacity_factor=4.0)
        fn = jax.jit(lambda p, xx: moe_mlp(p, xx, cfg, mesh=mesh))
        out = fn(params, x)
        out.block_until_ready()
        t0 = time.monotonic()
        for _ in range(iters):
            out = fn(params, x)
        out.block_until_ready()
        results[mode] = (time.monotonic() - t0) / iters * 1000
        outs[mode] = np.asarray(out)
    # Token-identity at ample capacity (factor 4): same experts, same math.
    np.testing.assert_allclose(
        outs["dense"], outs["capacity"], rtol=2e-4, atol=2e-4
    )
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mesh", default=None, help="e.g. ep=8")
    ap.add_argument("--tokens", type=int, default=1024)
    args = ap.parse_args()

    mesh = None
    if args.mesh:
        from dynamo_tpu.parallel.mesh import build_mesh

        shape = {
            k: int(v)
            for k, v in (kv.split("=") for kv in args.mesh.split(","))
        }
        mesh = build_mesh(shape)

    print(f"tokens={args.tokens} mesh={args.mesh or 'single'}")
    print(f"{'E':>4} {'topk':>4} | {'dense ms':>9} {'capacity ms':>11} | winner")
    for E, topk in ((8, 2), (16, 4), (64, 8), (128, 8)):
        r = run(
            dict(
                hidden_size=1024,
                intermediate_size=512,
                num_experts=E,
                num_experts_per_tok=topk,
            ),
            args.tokens,
            mesh=mesh,
        )
        win = "capacity" if r["capacity"] < r["dense"] else "dense"
        print(
            f"{E:>4} {topk:>4} | {r['dense']:>9.2f} {r['capacity']:>11.2f}"
            f" | {win}"
        )


if __name__ == "__main__":
    main()

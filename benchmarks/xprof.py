"""XLA profile capture + parsing: the r04 measurement method, codified.

Through the tunneled chip, wall-clock numbers swing ~2x with shared-infra
load and block_until_ready does not wait — the ONE trustworthy signal is
device time from a captured XLA profile (BENCHMARKS.md r04 methodology).
``measure(fn)`` wraps a callable in jax.profiler trace capture and
returns:

- ``module_ms``: total device time in the "XLA Modules" lane (the
  compiled-program executions — deterministic run to run to <1%);
- ``ops``: per-fusion/op device totals from the "XLA Ops" lane, sorted
  descending — the attribution that says WHICH fusion to attack.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import tempfile
from collections import defaultdict
from typing import Any, Callable


def _load_trace(logdir: str) -> dict:
    paths = glob.glob(
        os.path.join(logdir, "**", "*.trace.json.gz"), recursive=True
    )
    if not paths:
        raise FileNotFoundError(f"no trace.json.gz under {logdir}")
    with gzip.open(sorted(paths)[-1], "rt") as f:
        return json.load(f)


def parse_trace(logdir: str) -> dict[str, Any]:
    trace = _load_trace(logdir)
    events = trace.get("traceEvents", [])
    # pid/tid -> names: find device-side lanes.
    names: dict[tuple, str] = {}
    pid_names: dict[int, str] = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "thread_name":
            names[(ev["pid"], ev["tid"])] = ev["args"]["name"]
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            pid_names[ev["pid"]] = ev["args"]["name"]
    device_pids = {
        pid for pid, n in pid_names.items()
        if "TPU" in n or "/device:" in n
    }

    module_us = 0.0
    op_us: dict[str, float] = defaultdict(float)
    op_lane_us = 0.0
    for ev in events:
        if ev.get("ph") != "X" or ev.get("pid") not in device_pids:
            continue
        lane = names.get((ev["pid"], ev["tid"]), "")
        dur = float(ev.get("dur", 0.0))
        if lane == "XLA Modules":
            module_us += dur
        elif lane == "XLA Ops":
            op_us[ev.get("name", "?")] += dur
            op_lane_us += dur
    ops = sorted(op_us.items(), key=lambda kv: -kv[1])
    return {
        "module_ms": module_us / 1000.0,
        "ops_ms": [(n, round(us / 1000.0, 3)) for n, us in ops],
        "ops_total_ms": op_lane_us / 1000.0,
    }


def measure(fn: Callable[[], Any], logdir: str | None = None) -> dict:
    """Run ``fn`` under a jax profiler trace; return parse_trace output.
    The caller must FORCE results to host inside ``fn`` (float()/
    np.asarray) — block_until_ready does not wait through the tunnel."""
    import jax

    own = logdir is None
    logdir = logdir or tempfile.mkdtemp(prefix="xprof_")
    jax.profiler.start_trace(logdir)
    try:
        fn()
    finally:
        jax.profiler.stop_trace()
    out = parse_trace(logdir)
    out["logdir"] = logdir
    if own:
        pass  # keep for inspection; /tmp cleanup is the host's problem
    return out

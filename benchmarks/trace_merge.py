"""Join multi-process trace captures into per-request timelines.

Each process a request crosses writes its own ``DYNTPU_TRACE`` JSONL
capture (utils/tracing.py): ``span`` records stream out as spans close,
one ``finish`` (or ``abandon``) record lands per process per trace.
This tool joins any number of captures by trace id and reports the
thing the counters can't: WHERE a request's TTFT went —

    admission | tokenize | route | queue_wait | prefill | kv_transfer
    | decode_first

with percentiles over the run, plus the unattributed remainder (clock
gaps, hop transit). Span timestamps are absolute wall clock; captures
from different hosts are assumed NTP-aligned — the report's
``clock_offset_hint_ms`` (worst recv−sent per trace across low-latency
adoption seams; the prefill queue's dwell-measuring stamp is excluded)
flags runs where that assumption broke, the same assumption
``deadline_unix`` already makes.

``--assert-complete`` is the CI gate (ci.sh BENCH_TRACE leg): every
COMPLETED request (its finish record carries a ``first_token`` mark)
must have the full span chain — the core spans present and the covered
timeline gapless within ``--max-gap-ms`` — and any ORPHAN trace (spans
recorded but no finish/abandon anywhere) is a hard failure: an orphan
means some seam opened a capture it never closed, exactly the leak the
tracer's TTL sweep exists to catch.

Usage:
    python benchmarks/trace_merge.py CAPTURE [CAPTURE ...]
        [--assert-complete] [--max-gap-ms 250] [--dump-timelines]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from collections import defaultdict
from typing import Any

if __package__ in (None, ""):  # `python benchmarks/trace_merge.py ...`
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )

from dynamo_tpu.utils.recorder import Recorder
from dynamo_tpu.utils.tracing import SPAN_NAMES

#: Spans every completed request must have regardless of deployment
#: shape (they are recorded by the engine itself). Frontend spans
#: (admission/tokenize/route) and kv_transfer are required only when the
#: trace's marks show it crossed those seams.
CORE_SPANS = ("queue_wait", "prefill", "decode_first", "decode")


class TraceRecord:
    """Everything captured for one trace id, across all processes."""

    __slots__ = ("trace_id", "spans", "marks", "finishes", "abandons",
                 "offset_hints", "request_id")

    def __init__(self, trace_id: str) -> None:
        self.trace_id = trace_id
        self.request_id = ""
        self.spans: list[dict[str, Any]] = []
        self.marks: dict[str, float] = {}
        self.finishes = 0
        self.abandons = 0
        self.offset_hints: list[float] = []

    # -- derived ------------------------------------------------------------
    @property
    def completed(self) -> bool:
        return self.finishes > 0 and "first_token" in self.marks

    @property
    def failed_over(self) -> bool:
        """True when the ingress failover plane re-dispatched this
        request mid-stream (the ``failover`` mark/span). The dead
        worker's process capture holds OPEN spans it could never close —
        its streaming window is a legitimate, un-coverable hole in the
        merged timeline, so the gap gate must not red-bar the chain the
        failure model designed."""
        return "failover" in self.marks or any(
            s["name"] == "failover" for s in self.spans
        )

    @property
    def orphan(self) -> bool:
        return self.finishes == 0 and self.abandons == 0

    def timeline(self) -> list[dict[str, Any]]:
        return sorted(self.spans, key=lambda s: s["start_unix"])

    def span_totals(self) -> dict[str, float]:
        out: dict[str, float] = defaultdict(float)
        for s in self.spans:
            out[s["name"]] += s["dur_ms"]
        return dict(out)

    def ttft_ms(self) -> float | None:
        start = self.marks.get("received", self.marks.get("engine_queued"))
        first = self.marks.get("first_token")
        if start is None or first is None:
            return None
        return 1000.0 * (first - start)

    def max_gap_ms(self) -> float:
        """Largest hole in span coverage from the first span's start to
        the last span's end (0 when coverage is contiguous)."""
        tl = self.timeline()
        if not tl:
            return 0.0
        worst = 0.0
        covered_end = tl[0]["start_unix"]
        for s in tl:
            gap = s["start_unix"] - covered_end
            worst = max(worst, 1000.0 * gap)
            covered_end = max(
                covered_end, s["start_unix"] + s["dur_ms"] / 1000.0
            )
        return worst

    def missing_spans(self) -> list[str]:
        have = {s["name"] for s in self.spans}
        need = list(CORE_SPANS)
        # A degraded request (remote prefill died, decode recomputed
        # locally — the failure model's designed fallback) completes
        # without a kv_transfer span; only an UN-degraded remote request
        # must have one.
        if (
            "remote_prefill" in self.marks
            and "degraded_local" not in self.marks
        ):
            need.append("kv_transfer")
        if "received" in self.marks:
            need.append("admission")
        return [n for n in need if n not in have]


def _expand_captures(paths: list[str]) -> list[str]:
    """Resolve each argument to concrete capture files. A path may be a
    capture itself, or a ``DYNTPU_TRACE`` BASE: every process suffixes
    the base with its pid (utils/tracing.capture_path), so ``base`` on
    the command line expands to ``base.<pid>`` for each writer."""
    out: list[str] = []
    seen_files: set[str] = set()

    def _add(p: str) -> bool:
        """Append a capture path unless every concrete file in its
        rotated set was already covered — a pid-1 worker's capture is
        literally ``<base>.1``, which ALSO names the bare base's first
        rotated generation, and loading it twice would double-count
        finish/abandon records."""
        files = [str(f) for f in Recorder.files(p)]
        if not files or all(f in seen_files for f in files):
            return False
        seen_files.update(files)
        out.append(p)
        return True

    for path in paths:
        any_found = _add(path)
        # ALWAYS also glob the per-pid set: a stray file at the bare
        # base (touch, a pre-upgrade single-process capture) must not
        # shadow the captures the processes actually wrote.
        for p in sorted(
            p for p in glob.glob(f"{path}.*")
            if p[len(path) + 1:].isdigit()
        ):
            any_found = _add(p) or any_found
        if not any_found and not Recorder.files(path):
            raise FileNotFoundError(f"no capture at {path} (or {path}.<pid>)")
    return out


def load_captures(paths: list[str]) -> dict[str, TraceRecord]:
    traces: dict[str, TraceRecord] = {}
    seen_spans: set[tuple] = set()
    for path in _expand_captures(paths):
        for _ts, ev in Recorder.load(path):
            tid = ev.get("trace")
            if not tid:
                continue
            if ev.get("kind") not in ("span", "finish", "abandon"):
                # KV-observatory records (route / kv_actual) share the
                # capture — benchmarks/route_audit.py reads those; a
                # timeline-less kind must not register a trace here and
                # then read as an orphan.
                continue
            tr = traces.get(tid)
            if tr is None:
                tr = traces[tid] = TraceRecord(tid)
            tr.request_id = ev.get("id") or tr.request_id
            kind = ev.get("kind")
            if kind == "span":
                key = (
                    tid, ev.get("pid"), ev["span"],
                    round(ev["start_unix"], 5),
                )
                if key not in seen_spans:
                    seen_spans.add(key)
                    tr.spans.append({
                        "name": ev["span"],
                        "start_unix": ev["start_unix"],
                        "dur_ms": ev["dur_ms"],
                        "pid": ev.get("pid"),
                        "role": ev.get("role", ""),
                    })
            elif kind == "finish":
                tr.finishes += 1
                for name, t in (ev.get("marks") or {}).items():
                    tr.marks.setdefault(name, t)
                if ev.get("offset_hint_ms") is not None:
                    tr.offset_hints.append(ev["offset_hint_ms"])
                # The finish record restates its process's spans (it is
                # self-contained for single-file captures); the dedup key
                # makes restatement idempotent with the streamed records.
                for s in ev.get("spans") or []:
                    key = (
                        tid, ev.get("pid"), s["name"],
                        round(s["start_unix"], 5),
                    )
                    if key not in seen_spans:
                        seen_spans.add(key)
                        tr.spans.append({
                            "name": s["name"],
                            "start_unix": s["start_unix"],
                            "dur_ms": s["dur_ms"],
                            "pid": ev.get("pid"),
                            "role": "",
                        })
            elif kind == "abandon":
                tr.abandons += 1
    return traces


def _pct(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[idx]


def _digest(vals: list[float]) -> dict[str, float]:
    vals = sorted(vals)
    return {
        "count": len(vals),
        "p50_ms": round(_pct(vals, 0.50), 3),
        "p95_ms": round(_pct(vals, 0.95), 3),
        "max_ms": round(vals[-1], 3) if vals else 0.0,
    }


def merge_report(
    traces: dict[str, TraceRecord], max_gap_ms: float = 250.0
) -> dict[str, Any]:
    """The run-level report: per-span TTFT decomposition percentiles and
    the completeness audit --assert-complete gates on."""
    completed = [t for t in traces.values() if t.completed]
    orphans = [t.trace_id for t in traces.values() if t.orphan]
    # Per-trace worst clock-offset hint (recv_unix - sent_unix at each
    # low-latency adoption seam): offset + transit, so values well above
    # hop transit mean the captures' hosts disagree on wall clock and the
    # decomposition below is suspect. The prefill queue strips its hint
    # (its stamp measures dwell, not transit — disagg/worker.py).
    skew_hints = [
        max(abs(h) for h in t.offset_hints)
        for t in traces.values() if t.offset_hints
    ]
    decomposition: dict[str, list[float]] = defaultdict(list)
    ttfts: list[float] = []
    unattributed: list[float] = []
    incomplete: list[dict[str, Any]] = []
    errored = 0
    for t in completed:
        if "error" in t.marks:
            # A request that DIED after its first token (worker fault,
            # exhausted failover) legitimately truncates its chain —
            # completeness is a property of successful requests. Counted
            # so a run full of errors is still visible in the report.
            errored += 1
            continue
        totals = t.span_totals()
        for name in SPAN_NAMES:
            if name in totals:
                decomposition[name].append(totals[name])
        ttft = t.ttft_ms()
        if ttft is not None:
            ttfts.append(ttft)
            pre_decode = sum(
                v for k, v in totals.items() if k != "decode"
            )
            unattributed.append(max(0.0, ttft - pre_decode))
        missing = t.missing_spans()
        gap = t.max_gap_ms()
        # Failover chains keep the missing-span requirement (the REPLAY
        # worker records the full core chain) but not the gap bound: the
        # killed worker streamed tokens inside spans it died too soon to
        # close, and closed spans are all a capture ever exports.
        if missing or (gap > max_gap_ms and not t.failed_over):
            incomplete.append({
                "trace": t.trace_id,
                "request": t.request_id,
                "missing_spans": missing,
                "max_gap_ms": round(gap, 1),
            })
    return {
        "captures_traces": len(traces),
        "completed_requests": len(completed),
        "errored_requests": errored,
        "orphan_traces": orphans,
        "abandoned_traces": sum(
            1 for t in traces.values() if t.abandons and not t.finishes
        ),
        "incomplete": incomplete,
        "max_gap_ms_allowed": max_gap_ms,
        "ttft_ms": _digest(ttfts),
        "unattributed_ms": _digest(unattributed),
        "clock_offset_hint_ms": _digest(skew_hints),
        "ttft_decomposition_ms": {
            name: _digest(vals)
            for name, vals in decomposition.items()
        },
    }


def assert_complete(report: dict[str, Any]) -> list[str]:
    """The CI-gate predicate: returns human-readable failures (empty =
    pass)."""
    failures: list[str] = []
    if report["orphan_traces"]:
        failures.append(
            f"{len(report['orphan_traces'])} orphan trace(s) — spans "
            f"recorded but never finished/abandoned: "
            f"{report['orphan_traces'][:5]}"
        )
    if report["incomplete"]:
        failures.append(
            f"{len(report['incomplete'])} completed request(s) with a "
            f"broken span chain: "
            + "; ".join(
                f"{i['request'] or i['trace']}"
                f" missing={i['missing_spans']}"
                f" max_gap={i['max_gap_ms']}ms"
                for i in report["incomplete"][:5]
            )
        )
    if report["completed_requests"] == 0:
        failures.append("capture contains no completed requests")
    return failures


def _dump_timelines(traces: dict[str, TraceRecord]) -> None:
    for t in sorted(traces.values(), key=lambda t: t.trace_id):
        head = t.request_id or t.trace_id
        state = (
            "completed" if t.completed
            else ("orphan" if t.orphan else "abandoned/partial")
        )
        print(f"-- {head} [{state}]")
        tl = t.timeline()
        t0 = tl[0]["start_unix"] if tl else 0.0
        for s in tl:
            off = 1000.0 * (s["start_unix"] - t0)
            print(
                f"   {off:9.1f}ms +{s['dur_ms']:8.1f}ms  {s['name']:<12}"
                f" pid={s['pid']}"
            )


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python benchmarks/trace_merge.py",
        description="join DYNTPU_TRACE captures into per-request "
                    "timelines and a TTFT decomposition",
    )
    ap.add_argument("captures", nargs="+", help="JSONL capture paths "
                    "(each process's DYNTPU_TRACE file; rotated sets "
                    "are read in full)")
    ap.add_argument("--assert-complete", action="store_true",
                    help="exit 1 unless every completed request has the "
                    "full span chain and no trace is orphaned")
    ap.add_argument("--max-gap-ms", type=float, default=250.0,
                    help="largest allowed hole in a request's span "
                    "coverage before it counts as incomplete")
    ap.add_argument("--dump-timelines", action="store_true",
                    help="print every request's merged span timeline")
    args = ap.parse_args(argv)

    try:
        traces = load_captures(args.captures)
    except FileNotFoundError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    report = merge_report(traces, max_gap_ms=args.max_gap_ms)
    if args.dump_timelines:
        _dump_timelines(traces)
    print(json.dumps(report, indent=2))
    if args.assert_complete:
        failures = assert_complete(report)
        if failures:
            for f in failures:
                print(f"ASSERT-COMPLETE FAIL: {f}", file=sys.stderr)
            return 1
        print("assert-complete: OK", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""xPyD fleet projection on the calibrated mocker cost model.

Replays prefill-heavy workloads through planner/simulate.py (virtual
clock, constants pinned to the recorded r04/r05 chip runs by
planner/calibration.py) across 1P1D / 2P1D / 2P2D disaggregated
topologies and aggregated baselines — both throughput-max ``batch``
mode and the SLO-holding ``coloc`` mode (the PR 8 unified-step shape) —
and emits the projection table BENCHMARKS.md records (ROADMAP #4: the
pillar-#1 "+30 % disagg" claim, finally quantified).

Legs:
  (default)        print the projection JSON (+ markdown with --markdown)
  --assert         gate: calibration reproduces the r04 headline <10 %;
                   2P1D beats the 1-worker aggregated baseline on the
                   prefill-heavy replay; a decode scale-down mid-run
                   drops ZERO requests and shifts traffic to survivors
  --router-ab      network-aware decode selection A/B on heterogeneous
                   simulated links through the REAL DefaultWorkerSelector:
                   the transfer-cost term must shift selection away from
                   the slow link while plain mode splits

Usage: python benchmarks/xpyd_bench.py [--assert] [--router-ab]
       [--markdown] [--isl N] [--osl N] [--requests N] [--rate RPS]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from dynamo_tpu.planner import calibration as cal          # noqa: E402
from dynamo_tpu.planner import simulate as sim             # noqa: E402


def calibration_check() -> dict:
    """Single aggregated worker replaying the recorded r04 headline
    workload — the <10 % reproduction gate (tests/test_xpyd.py runs the
    same check; future mocker edits can't silently drift projections)."""
    cfg = sim.SimConfig()
    wl = sim.synth_workload(cal.R04_NUM_REQUESTS, cal.R04_ISL, cal.R04_OSL)
    r = sim.simulate_aggregated(cfg, wl, 1)
    tok_err = abs(r.tok_s - cal.R04_HEADLINE_TOK_S) / cal.R04_HEADLINE_TOK_S
    ttft_err = abs(r.p50_ttft_ms - cal.R04_P50_TTFT_MS) / cal.R04_P50_TTFT_MS
    return {
        "sim_tok_s": round(r.tok_s, 1),
        "recorded_tok_s": cal.R04_HEADLINE_TOK_S,
        "tok_s_err": round(tok_err, 4),
        "sim_p50_ttft_ms": round(r.p50_ttft_ms, 1),
        "recorded_p50_ttft_ms": cal.R04_P50_TTFT_MS,
        "p50_ttft_err": round(ttft_err, 4),
        "ok": tok_err < 0.10 and ttft_err < 0.10,
    }


def projection(
    n: int = 32, isl: int = 3000, osl: int = 150, rate_rps: float = 0.0
) -> dict:
    """The topology table on a prefill-heavy replay (default: the
    ISL 3000 / OSL 150 reference-harness shape, all-at-once burst)."""
    cfg = sim.SimConfig()

    def wl():
        return sim.synth_workload(n, isl, osl, rate_rps=rate_rps)

    rows = [
        sim.simulate_aggregated(cfg, wl(), 1).to_wire(),
        sim.simulate_aggregated(cfg, wl(), 1, mode="coloc").to_wire(),
        sim.simulate_aggregated(cfg, wl(), 3).to_wire(),
        sim.simulate_aggregated(cfg, wl(), 3, mode="coloc").to_wire(),
        sim.simulate_xpyd(cfg, wl(), 1, 1).to_wire(),
        sim.simulate_xpyd(cfg, wl(), 2, 1).to_wire(),
        sim.simulate_xpyd(cfg, wl(), 2, 2).to_wire(),
    ]
    return {"workload": {"n": n, "isl": isl, "osl": osl,
                         "rate_rps": rate_rps}, "rows": rows}


def drain_leg(
    n: int = 48, isl: int = 3000, osl: int = 150, rate_rps: float = 4.0
) -> dict:
    """Fleet elasticity under open arrivals: decode worker 1 of a 2P2D
    fleet starts DRAINING mid-run — it must finish everything already
    routed to it (zero drops) while new selections shift to the
    survivor (the planner's decode-shrink semantics, simulated)."""
    cfg = sim.SimConfig()
    wl = sim.synth_workload(n, isl, osl, rate_rps=rate_rps)
    r = sim.simulate_xpyd(cfg, wl, 2, 2, drain_decode_at=(6.0, 1))
    served = r.per_decode_worker
    return {
        "row": r.to_wire(),
        "drained_worker_served": served[1],
        "survivor_served": served[0],
        "ok": (
            r.dropped == 0
            and r.completed == n
            and served[0] > served[1] > 0
            # The drain COMPLETED: the draining worker went empty
            # before the run ended (drain ≠ hang, not just drain ≠ kill).
            and r.decode_drained_at_s is not None
        ),
    }


def router_ab(trials: int = 200, seed: int = 0) -> dict:
    """Heterogeneous-link A/B through the production selector
    (llm/kv_router/scheduler.py): worker 1 ingests at the measured
    21.7 GB/s device rate, worker 2 at the measured 0.012 GB/s host-
    roundtrip rate (BENCHMARKS.md "Batched KV block IO"). Identical
    load and overlap otherwise — plain mode has no reason to prefer
    either (ties split via the predicted-load bump), network-aware mode
    must send decode traffic to the fast link."""
    from dynamo_tpu.llm.kv_router.metrics_aggregator import ProcessedEndpoints
    from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics
    from dynamo_tpu.llm.kv_router.scheduler import (
        DefaultWorkerSelector,
        KvRouterConfig,
    )

    def endpoints() -> ProcessedEndpoints:
        return ProcessedEndpoints(
            metrics={
                1: ForwardPassMetrics(
                    kv_total_blocks=4096,
                    kvbm_link_g2g1_bps=cal.HANDOFF_GBPS * 1e9,
                ),
                2: ForwardPassMetrics(
                    kv_total_blocks=4096, kvbm_link_g2g1_bps=0.012e9
                ),
            },
            stamp=1.0,
        )

    out: dict = {}
    for mode in ("plain", "netaware"):
        selector = DefaultWorkerSelector(
            KvRouterConfig(network_aware=(mode == "netaware")), seed=seed
        )
        picks = {1: 0, 2: 0}
        transfer_audited = False
        for _ in range(trials):
            d = selector.select(endpoints(), {}, isl=128)
            picks[d.worker_id] += 1
            transfer_audited = transfer_audited or any(
                "transfer_ms" in c for c in d.candidates
            )
        out[mode] = {
            "fast_link_share": round(picks[1] / trials, 3),
            "picks": picks,
            "transfer_audited": transfer_audited,
        }
    out["ok"] = (
        out["netaware"]["fast_link_share"] >= 0.90
        and out["netaware"]["transfer_audited"]
        and 0.30 <= out["plain"]["fast_link_share"] <= 0.70
        and not out["plain"]["transfer_audited"]
    )
    return out


def run_gates(
    n: int = 32, isl: int = 3000, osl: int = 150, rate_rps: float = 0.0
) -> dict:
    """The full BENCH_XPYD gate pipeline — the ONE source of truth for
    the gates, shared by this CLI's ``--assert`` mode and bench.py's
    ``BENCH_XPYD=1`` leg (a gate added here is enforced in both)."""
    calres = calibration_check()
    proj = projection(n, isl, osl, rate_rps)
    drain = drain_leg()
    by_top = {r["topology"]: r for r in proj["rows"]}
    gates = {
        "calibration_ok": calres["ok"],
        "disagg_beats_single_agg": (
            by_top["2P1D"]["tok_s"] > by_top["1xAGG"]["tok_s"]
        ),
        # The BENCHMARKS.md "+30%" pillar-claim bound, enforced HERE so
        # the ci.sh leg (not just the test suite) fails if a cost-model
        # change erodes the projected margin.
        "disagg_beats_coloc_fleet_by_30pct": (
            by_top["2P1D"]["tok_s"] > 1.30 * by_top["3xcoloc"]["tok_s"]
        ),
        "scale_down_zero_drops": drain["ok"],
    }
    return {
        "calibration": calres,
        "projection": proj,
        "drain": drain,
        "gates": gates,
        # 2P1D over the equal-chip SLO-holding co-located fleet — the
        # headline the projection table exists to quantify.
        "headline_ratio": round(
            by_top["2P1D"]["tok_s"] / max(by_top["3xcoloc"]["tok_s"], 1e-9),
            3,
        ),
    }


def markdown_table(proj: dict) -> str:
    w = proj["workload"]
    lines = [
        f"| topology | chips | tok/s | tok/s/chip | p50 TTFT ms |"
        f" ITL p95 ms | ITL max ms |",
        "|---|---|---|---|---|---|---|",
    ]
    for r in proj["rows"]:
        lines.append(
            f"| {r['topology']} | {r['chips']} | {r['tok_s']} |"
            f" {r['tok_s_per_chip']} | {r['p50_ttft_ms']} |"
            f" {r['itl_p95_ms']} | {r['itl_max_ms']} |"
        )
    head = (
        f"Workload: {w['n']} requests, ISL {w['isl']} / OSL {w['osl']}"
        + (f", open-loop {w['rate_rps']} req/s" if w["rate_rps"] else
           ", all-at-once burst")
    )
    return head + "\n\n" + "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--assert", dest="assert_", action="store_true")
    ap.add_argument("--router-ab", action="store_true")
    ap.add_argument("--markdown", action="store_true")
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--isl", type=int, default=3000)
    ap.add_argument("--osl", type=int, default=150)
    ap.add_argument("--rate", type=float, default=0.0)
    args = ap.parse_args(argv)

    if args.router_ab:
        ab = router_ab()
        print(json.dumps({"router_ab": ab}, indent=2))
        if not ab["ok"]:
            print("ROUTER A/B FAILED: network-aware mode did not shift "
                  "selection off the slow link (or plain mode did)",
                  file=sys.stderr)
            return 1
        return 0

    report = run_gates(args.requests, args.isl, args.osl, args.rate)
    print(json.dumps(report, indent=2))
    if args.markdown:
        print()
        print(markdown_table(report["projection"]))
    if args.assert_ and not all(report["gates"].values()):
        print(f"XPYD GATES FAILED: {report['gates']}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""8B device-efficiency bench (VERDICT r04 weak #2): DEVICE-time decode
byte-rate and prefill MFU with per-fusion attribution.

r04 closed the 1B gap with profile-driven kernel work (86% of the HBM
floor); this points the same method at 8B. All times come from the XLA
Modules/Ops lanes of a captured profile (benchmarks/xprof.py) — the only
deterministic signal through the tunneled chip. The r04 8B table used
WALL per-step times, which undercount effective bandwidth by whatever
the tunnel added; the device numbers here supersede them.

Run: ``BENCH_8B=1 python bench.py`` (env knobs below) — prints one JSON
line with decode_gbps / prefill_mfu + the top fusions for each.
"""

from __future__ import annotations

import os

import numpy as np

V5E_PEAK_FLOPS = 197e12   # bf16
V5E_PEAK_GBPS = 819.0


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


def _matmul_params(cfg) -> int:
    """Parameters participating in per-token matmuls (layers only —
    embedding lookups are gathers; the lm_head counts once per SAMPLED
    position, added separately)."""
    D, I, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    H, KV, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    attn = D * H * hd + 2 * D * KV * hd + H * hd * D  # q,k,v,o
    mlp = 3 * D * I
    return L * (attn + mlp)


def run() -> dict:
    import jax.numpy as jnp

    from benchmarks.xprof import measure
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.runner import ModelRunner
    from dynamo_tpu.models.config import ModelConfig

    model_name = os.environ.get("BENCH_MODEL", "llama31_8b")
    quant = os.environ.get("DYNAMO_TPU_QUANT", "int8") or None
    m = getattr(ModelConfig, model_name)()
    bs = 16
    B = _env_int("BENCH_SEQS", 16)
    chunk = _env_int("BENCH_CHUNK", 16)
    lanes = _env_int("BENCH_PREFILL_BATCH", 4)
    pchunk = 512
    isl_long = _env_int("BENCH_ISL", 3000)
    cfg = EngineConfig(
        model=m, dtype="bfloat16", quant=quant, block_size=bs,
        num_blocks=_env_int("BENCH_BLOCKS", 1600), max_num_seqs=B,
        max_model_len=4096, decode_chunk=chunk, prefill_batch=lanes,
    )
    runner = ModelRunner(cfg)
    out: dict = {
        "model": model_name, "quant": quant or "none",
        "attention_path": "pallas" if runner.attn.use_pallas else "jnp",
    }

    import jax

    weight_bytes = sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(runner.params)
    )
    kv_entry = 2 * m.num_layers * m.num_cache_heads * runner.cache_head_dim * 2

    def tables_for(nlanes: int, ctx: int, extra: int):
        per = (ctx + extra + bs - 1) // bs
        t = np.zeros((nlanes, cfg.max_blocks_per_seq), np.int32)
        nxt = 1
        for b in range(nlanes):
            t[b, :per] = range(nxt, nxt + per)
            nxt += per
        assert nxt <= cfg.num_blocks, "arena too small for the scenario"
        return t

    # ---- decode byte-rate at two contexts (the ISL-3000 droop probe) ----
    long_ctx = isl_long + 150
    long_lanes = _env_int("BENCH_LONG_LANES", 6)
    for label, ctx, nb in (
        ("short", 192, B), (f"isl{isl_long}", long_ctx, long_lanes),
    ):
        tables = tables_for(nb, ctx, chunk)
        if nb < B:
            tables = np.vstack([tables, np.zeros((B - nb, tables.shape[1]), np.int32)])
        ctx_arr = np.array([ctx] * nb + [0] * (B - nb), np.int32)
        zf, zi, of = (
            np.zeros(B, np.float32), np.zeros(B, np.int32),
            np.ones(B, np.float32),
        )
        toks = np.ones(B, np.int32)

        def one():
            r = runner.decode_multi(
                toks, np.maximum(ctx_arr - 1, 0), tables, ctx_arr,
                zf, zi, of, chunk,
            )
            np.asarray(r)

        one()  # compile outside the trace
        N = 3
        prof = measure(lambda: [one() for _ in range(N)])
        step_ms = prof["module_ms"] / (N * chunk)
        bytes_per_step = weight_bytes + nb * ctx * kv_entry
        out[f"decode_{label}"] = {
            "device_step_ms": round(step_ms, 3),
            "effective_gbps": round(bytes_per_step / (step_ms / 1e3) / 1e9, 1),
            "pct_of_peak": round(
                100 * bytes_per_step / (step_ms / 1e3) / 1e9 / V5E_PEAK_GBPS, 1
            ),
            "lanes": nb,
            "top_ops": prof["ops_ms"][:8],
        }

    # ---- prefill MFU at the harness shape (chunked, batched) -------------
    pchunk = min(pchunk, isl_long)
    tables = tables_for(lanes, isl_long, 0)
    prefix = max((isl_long - pchunk) // 2 // bs * bs, 0)  # mid-prompt chunk
    lanes_args = []
    for i in range(lanes):
        toks_l = [1] * pchunk
        lanes_args.append((toks_l, [int(x) for x in tables[i] if x], prefix,
                           (0.0, 0, 1.0)))

    def one_prefill():
        runner.prefill_batch(lanes_args)

    one_prefill()
    N = 3
    prof = measure(lambda: [one_prefill() for _ in range(N)])
    call_ms = prof["module_ms"] / N
    tokens = lanes * pchunk
    # Matmul flops + causal attention (QK^T and PV over the live prefix).
    mm_flops = 2 * _matmul_params(m) * tokens + 2 * m.hidden_size * m.vocab_size * lanes
    avg_ctx = prefix + pchunk / 2
    attn_flops = 4 * m.num_layers * m.num_heads * m.head_dim * tokens * avg_ctx
    flops = mm_flops + attn_flops
    out["prefill"] = {
        "device_call_ms": round(call_ms, 2),
        "lanes": lanes,
        "chunk": pchunk,
        "prefix": prefix,
        "mfu_pct": round(100 * flops / (call_ms / 1e3) / V5E_PEAK_FLOPS, 1),
        "tok_per_s_device": round(tokens / (call_ms / 1e3), 0),
        "top_ops": prof["ops_ms"][:8],
    }
    return out


def main() -> dict:
    r = run()
    return {
        # Default model is llama31_8b; BENCH_MODEL parameterizes the probe
        # (e.g. gemma3_1b — BENCHMARKS.md "Gemma-3 on the chip").
        "metric": f"prefill_mfu_{r['model']}",
        "value": r["prefill"]["mfu_pct"],
        "unit": "% of v5e bf16 peak (device time)",
        "vs_baseline": r["prefill"]["mfu_pct"] / 100.0,
        "extras": r,
    }


if __name__ == "__main__":
    import json

    print(json.dumps(main()))

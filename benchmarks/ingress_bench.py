"""Million-user ingress replay: the sharded-router + SLO-class proof.

``BENCH_INGRESS=1 python bench.py`` (ci.sh "mocker 100k ingress replay"
leg) replays a Mooncake-style trace — ≥100k requests whose prompts share
a prefix tree (benchmarks/synthesizer.py) — through the FULL replicated
ingress (docs/architecture/ingress_scale.md):

    client → admission gate (SLO-class-weighted watermarks,
    load-proportional Retry-After) → FailoverEngine → PushRouter
    (round-robin over ≥2 ROUTER REPLICAS) → bus → RouterService replica
    (own KvIndexerSharded + KvMetricsAggregator, KV-aware worker pick,
    its own FailoverEngine) → bus → one of ≥8 mocker workers → TCP
    response stream relayed back through the replica.

``benchmarks/prefix_analyzer.py`` sizes the simulated prefix cache from
the trace itself (the LRU hit-rate-vs-size curve's knee — ROADMAP #4's
parenthetical), and the curve rides the bench extras.

Chaos mid-replay: one router replica is KILLED abruptly at ~35% of the
trace (``ServedInstance.kill``: frame-less response aborts, discovery
left dirty — exactly a crashed process) and REJOINS at ~55% with a
fresh, EMPTY radix view; the events missed while down are measured as
its applied-watermark lag (``RouterReplicaSet.staleness``), never
assumed away. A mid-run overload burst (injected past the closed-loop
pacing) drives the admission gate into its class-weighted band so the
cheapest-first contract is exercised at its design point.

Replay pacing is CLOSED-LOOP (a concurrency cap in arrival order), not
wall-clock: absolute trace timestamps would make every TTFT gate a bet
on CI host speed. The burst deliberately breaks the loop to create the
overload the shed gates need.

Hard gates (run_gates):

1. **Zero lost or hung requests** — every request resolves (tokens,
   429, or nothing else) under a per-request watchdog, THROUGH the
   replica kill; non-shed typed errors are zero (failover must absorb
   the kill while a healthy replica remains).
2. **Per-class p99 TTFT under its SLO** (interactive and batch).
3. **Zero cross-class SLO inversions**: no completion-time window where
   interactive misses its SLO while batch meets its own.
4. **Cheapest-first shedding**: the overload burst sheds batch (429 +
   load-proportional Retry-After) while interactive sheds ~none and
   interactive p99 holds.
5. **Route-audit error bound across ALL replicas**: route_audit.py's
   gates over the merged multi-replica capture — join rate, orphan
   bound, and EVERY replica's |predicted-actual| overlap-error p95
   under the bound (the rejoined replica is judged separately, stale
   view and all).
6. **Rejoin staleness measured**: the rejoined replica's applied-event
   lag was observed > 0 (its divergence is instrumented, not invisible).
"""

# dynarace: context[loop]

from __future__ import annotations

import asyncio
import logging
import os
import random
import sys
import time

if __package__ in (None, ""):  # `python benchmarks/ingress_bench.py`
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )

logger = logging.getLogger(__name__)

#: Token values stay in [1, 250] — CPython interns small ints, so a
#: 100k-request trace of list[int] prompts costs ~100 MB of pointers
#: instead of gigabytes of int objects. Prefix-hash identity is over
#: token SEQUENCES, so a small alphabet loses no radix structure.
VOCAB = 250


def build_trace(
    requests: int, isl_mean: int, osl: int, seed: int
) -> tuple[list[list[int]], list[str]]:
    """Mooncake-style trace: prompts sampled from a shared prefix tree
    (system prompts / conversation turns) + unique suffixes, with a
    deterministic SLO class per request (~25% batch). Returns
    (prompts, classes)."""
    from benchmarks.synthesizer import WorkloadConfig, generate

    reqs = generate(WorkloadConfig(
        num_requests=requests,
        isl_mean=isl_mean,
        osl_mean=osl,
        reuse=0.5,
        branching=3,
        depth=3,
        vocab_size=VOCAB,
        seed=seed,
    ))
    rng = random.Random(seed + 1)
    prompts = [
        [max(1, t) for t in r.token_ids] for r in reqs
    ]
    classes = [
        "batch" if rng.random() < 0.25 else "interactive"
        for _ in reqs
    ]
    return prompts, classes


def size_prefix_cache(
    prompts: list[list[int]], block_size: int,
    active_floor: int, sample: int = 10_000,
) -> tuple[int, dict]:
    """Size each worker's block arena from the trace's own LRU
    hit-rate-vs-size curve (benchmarks/prefix_analyzer.py): the smallest
    capacity reaching ≥80% of the largest-cache hit rate, floored by
    what concurrent actives need. Returns (num_blocks, analyzer report
    on the sample)."""
    from benchmarks.prefix_analyzer import analyze
    from benchmarks.synthesizer import Request

    sample_reqs = [
        Request(token_ids=p, max_tokens=1)
        for p in prompts[: min(sample, len(prompts))]
    ]
    report = analyze(sample_reqs, block_size=block_size)
    curve = report["curve"]
    best = max((pt["hit_rate"] for pt in curve), default=0.0)
    chosen = curve[-1]["cache_blocks"] if curve else active_floor
    for pt in curve:
        if best > 0 and pt["hit_rate"] >= 0.8 * best:
            chosen = pt["cache_blocks"]
            break
    per_worker = max(active_floor, chosen)
    return per_worker, report


async def run_ingress(
    requests: int = 100_000,
    workers: int = 8,
    replicas: int = 2,
    isl_mean: int = 96,
    osl: int = 3,
    concurrency: int = 128,
    seed: int = 20260805,
    slo_interactive_ms: float = 4_000.0,
    slo_batch_ms: float = 20_000.0,
    kill_at: float = 0.35,
    rejoin_at: float = 0.55,
    burst_at: float = 0.70,
    max_inflight: int = 420,
    max_engine_waiting: int = 400,
    burst_extra: int = 160,
    burst_attempts: int = 600,
    watchdog_s: float = 180.0,
) -> dict:
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.llm.admission import (
        AdmissionConfig,
        AdmissionController,
        AdmissionRejected,
    )
    from dynamo_tpu.llm.kv_router.publisher import (
        KvEventPublisher,
        WorkerMetricsPublisher,
    )
    from dynamo_tpu.llm.kv_router.replicas import RouterReplicaSet
    from dynamo_tpu.llm.kv_router.scheduler import KvRouterConfig
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.mocker import MockerConfig, MockerEngine
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.egress import PushRouter
    from dynamo_tpu.runtime.engine import Context
    from dynamo_tpu.runtime.failover import FailoverEngine
    from dynamo_tpu.utils.tracing import tracer

    t_start = time.monotonic()
    prompts, classes = build_trace(requests, isl_mean, osl, seed)
    block_size = 16
    # Active floor: every lane of every worker funded for prompt + osl.
    blocks_per_seq = (isl_mean + osl) // block_size + 2
    max_num_seqs = 64
    active_floor = max_num_seqs * blocks_per_seq
    num_blocks, prefix_report = size_prefix_cache(
        prompts, block_size, active_floor
    )
    logger.warning(
        "ingress replay: %d requests, %d workers x %d blocks "
        "(prefix-analyzer knee; ideal hit %.1f%%), %d replicas",
        requests, workers, num_blocks, 100 * prefix_report[
            "ideal_hit_rate"
        ], replicas,
    )

    cfg = EngineConfig(
        model=ModelConfig.tiny_test(),
        num_blocks=num_blocks,
        max_num_seqs=max_num_seqs,
        max_model_len=512,
        dtype="float32",
        decode_chunk=4,
        # Overload is shed at the ADMISSION gate (class-weighted, the
        # contract under test); engine-side bounds stay off so every
        # 429 is attributable to the gate.
        max_waiting=0,
    )

    drt0 = await DistributedRuntime.in_process()

    async def sub_drt():
        return await DistributedRuntime.in_process(
            store=drt0.store, bus=drt0.bus, runtime=drt0.runtime
        )

    # -- the worker fleet --------------------------------------------------
    engines = []
    instances = []
    for i in range(workers):
        drt = await sub_drt()
        comp = drt.namespace("ingress").component("worker")
        wm = WorkerMetricsPublisher()
        pub = KvEventPublisher(drt, comp, drt.primary_lease_id)
        eng = MockerEngine(cfg, MockerConfig(
            seed=i,
            vocab_size=VOCAB,
            decode_time_per_step_us=800.0,
            prefill_time_per_token_us=1.0,
        ))
        eng._external_kv_event = pub.publish_engine_event
        eng._on_metrics = wm.publish
        eng._on_kv_actual = pub.publish_hit_actual
        await eng.start()
        instances.append(
            await comp.endpoint("generate").serve(eng)
        )
        await wm.create_endpoint(comp)
        engines.append(eng)

    # -- the router replica set --------------------------------------------
    replica_set = await RouterReplicaSet(
        sub_drt, "ingress.worker.generate",
        cfg=KvRouterConfig(block_size=block_size),
    ).start(replicas)

    # -- the frontend ------------------------------------------------------
    # Tight connect-back bound: a request dispatched INTO the replica
    # kill window stalls exactly this long before the mark-dead fast
    # path + failover re-route it — it is the dominant term in the
    # post-kill TTFT tail.
    push = await PushRouter.create(
        drt0, "ingress.router.generate", connect_timeout_s=2.0
    )
    front = FailoverEngine(push)

    def fleet_stats() -> dict:
        # Aggregate live pressure across the fleet — the admission
        # watermark feed (one frontend, N engines).
        return {
            "num_requests_waiting": sum(
                len(e.scheduler.waiting) for e in engines
                if e.scheduler is not None
            ),
        }

    # The class-weighted gate: the frontend inflight cap is the primary
    # axis (it sees the cell's whole backlog — engine queues, bus, TCP
    # relays — which is exactly what a production ingress caps); the
    # engine-waiting watermark rides as the backstop for deployments
    # whose backlog concentrates at the schedulers. Batch trips either
    # at HALF the configured level (AdmissionConfig defaults).
    admission = AdmissionController(
        AdmissionConfig(
            max_inflight=max_inflight,
            max_engine_waiting=max_engine_waiting,
            retry_after_s=1.0,
            retry_after_max_s=30.0,
        ),
        engine_stats=fleet_stats,
    )

    # -- per-request driver ------------------------------------------------
    # (status, cls, ttft_ms, done_t, origin, detail); appends are
    # loop-thread-only (asyncio tasks), no lock needed.
    results: list[tuple] = []

    async def one(idx: int, prompt: list[int], cls: str,
                  origin: str = "trace") -> tuple:
        status, ttft_ms, detail = "ok", -1.0, ""
        try:
            permit = admission.admit(request_class=cls)
        except AdmissionRejected as exc:
            return ("shed", cls, -1.0, time.monotonic() - t_start,
                    origin, f"{exc.reason}:{exc.retry_after_s:g}")
        req = PreprocessedRequest(
            token_ids=prompt,
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=osl, ignore_eos=True),
            annotations={"request_class": cls},
        )
        ctx = Context(req.to_wire())
        t0 = time.monotonic()
        toks = 0
        try:
            async for item in front.generate(ctx):
                got = item.get("token_ids", [])
                if got and ttft_ms < 0:
                    ttft_ms = 1000.0 * (time.monotonic() - t0)
                toks += len(got)
            if toks < osl:
                status, detail = "short", f"{toks}/{osl} tokens"
        except Exception as exc:  # noqa: BLE001 — classified by the gates
            status, detail = "error", f"{type(exc).__name__}: {exc}"
        finally:
            permit.release()
            tracer().finish(ctx.id)
        return (status, cls, ttft_ms, time.monotonic() - t_start,
                origin, detail)

    async def guarded(idx, prompt, cls, origin="trace"):
        try:
            r = await asyncio.wait_for(
                one(idx, prompt, cls, origin), watchdog_s
            )
        except asyncio.TimeoutError:
            r = ("hang", cls, -1.0, time.monotonic() - t_start,
                 origin, f"req {idx}: WATCHDOG")
        results.append(r)
        return r

    # -- chaos + staleness instrumentation --------------------------------
    progress = {"done": 0}
    kill_n = int(requests * kill_at)
    rejoin_n = int(requests * rejoin_at)
    burst_n = int(requests * burst_at)
    chaos = {
        "killed_at": None, "rejoined_at": None,
        "burst": None, "staleness_samples": [],
    }
    killed_replica = {"handle": None}

    async def chaos_loop():
        while progress["done"] < requests:
            done = progress["done"]
            if chaos["killed_at"] is None and done >= kill_n:
                h = replica_set.replicas[0]
                killed_replica["handle"] = h
                await replica_set.kill(h)
                chaos["killed_at"] = done
            if (
                chaos["killed_at"] is not None
                and chaos["rejoined_at"] is None
                and done >= rejoin_n
            ):
                await replica_set.rejoin(killed_replica["handle"])
                chaos["rejoined_at"] = done
            if chaos["rejoined_at"] is not None:
                st = replica_set.staleness()
                chaos["staleness_samples"].append({
                    "done": done,
                    "rejoined_lag": st["replicas"][0]["applied_lag"],
                    "applied_max": st["applied_max"],
                })
            await asyncio.sleep(0.25)

    chaos_task = asyncio.ensure_future(chaos_loop())

    # -- the replay: closed-loop arrival-order pacing ----------------------
    sem = asyncio.Semaphore(concurrency)
    inflight: set[asyncio.Task] = set()

    async def paced(idx):
        try:
            await guarded(idx, prompts[idx], classes[idx])
        finally:
            progress["done"] += 1
            sem.release()

    burst_tasks: list[asyncio.Task] = []
    burst_stats = {"batch_shed": 0, "batch_sent": 0,
                   "interactive_shed": 0, "interactive_sent": 0}

    async def overload_burst():
        """Extra offered load past the trace's closed loop, itself
        closed-loop at ``burst_extra`` additional in-flight: total
        admitted load is pinned INSIDE the class-weighted band — above
        the batch inflight threshold (``max_inflight/2``), below the
        interactive cap — on any machine speed, which is the
        cheapest-first design point: batch arrivals 429 with a
        load-proportional Retry-After while every interactive arrival
        is admitted and served. Sheds hold no slot, so the burst loop
        keeps offering through its attempt budget. Shed counts come
        from the burst's OWN result rows (origin == "burst"), never a
        delta of the process-global admission counters — the trace loop
        keeps running through the window and its sheds must not be
        misattributed to (or masked by) the burst."""
        rng = random.Random(seed + 2)
        bsem = asyncio.Semaphore(burst_extra)
        sent = []

        async def burst_one(j: int, cls: str, p: list[int]) -> tuple:
            try:
                return await guarded(requests + j, p, cls, origin="burst")
            finally:
                bsem.release()

        for j in range(burst_attempts):
            cls = "batch" if rng.random() < 0.5 else "interactive"
            burst_stats[f"{cls}_sent"] += 1
            p = prompts[rng.randrange(len(prompts))]
            await bsem.acquire()
            sent.append(asyncio.ensure_future(burst_one(j, cls, p)))
        burst_tasks.extend(sent)
        outcomes = await asyncio.gather(*sent)
        for st, cls, _t, _dt, _origin, _d in outcomes:
            if st == "shed":
                burst_stats[f"{cls}_shed"] += 1
        chaos["burst"] = dict(burst_stats)

    burst_fired = {"task": None}
    for idx in range(requests):
        await sem.acquire()
        t = asyncio.ensure_future(paced(idx))
        inflight.add(t)
        t.add_done_callback(inflight.discard)
        if burst_fired["task"] is None and idx >= burst_n:
            burst_fired["task"] = asyncio.ensure_future(overload_burst())
    if burst_fired["task"] is None:  # tiny runs: fire at the end
        burst_fired["task"] = asyncio.ensure_future(overload_burst())
    await asyncio.gather(*list(inflight))
    await burst_fired["task"]
    await chaos_task
    wall_s = time.monotonic() - t_start

    # Let the engines' kv_actual exports + plane broadcasts flush.
    await asyncio.sleep(0.5)

    # -- digest ------------------------------------------------------------
    # The zero-lost/zero-hung gates cover BOTH populations (trace +
    # burst extras); the resolved-count check covers the trace only
    # (burst extras are deliberate over-offer, mostly shed); TTFT
    # samples come from every ADMITTED request — holding interactive
    # p99 THROUGH the burst is the point.
    by_status: dict[str, int] = {}
    sheds_by_class = {"interactive": 0, "batch": 0}
    trace_rows = 0
    for st, cls, _t, _dt, origin, _d in results:
        by_status[st] = by_status.get(st, 0) + 1
        if st == "shed":
            sheds_by_class[cls] = sheds_by_class.get(cls, 0) + 1
        if origin == "trace":
            trace_rows += 1

    # One percentile definition across the tool set (route_audit reuses
    # trace_merge's on purpose — a third local rank rule is drift).
    from benchmarks.route_audit import _pctl as pctl

    ttft: dict[str, list[float]] = {"interactive": [], "batch": []}
    windows: dict[int, dict[str, list[float]]] = {}
    horizon = max(r[3] for r in results) if results else 1.0
    n_windows = 20
    for st, cls, t_ms, done_t, _origin, _d in results:
        if st == "ok" and t_ms >= 0:
            ttft[cls].append(t_ms)
            w = min(n_windows - 1, int(n_windows * done_t / horizon))
            windows.setdefault(w, {"interactive": [], "batch": []})[
                cls
            ].append(t_ms)
    inversions = []
    for w, split in sorted(windows.items()):
        if not split["interactive"] or not split["batch"]:
            continue
        pi = pctl(split["interactive"], 0.99)
        pb = pctl(split["batch"], 0.99)
        # A cross-class SLO inversion is the cell FAVORING batch while
        # interactive suffers: interactive misses its SLO in a window
        # where batch both meets its own AND is being served materially
        # faster. General overload (both classes slow together) is the
        # overall p99 gate's job, not an inversion.
        if (
            pi > slo_interactive_ms
            and pb <= slo_batch_ms
            and pb < 0.9 * pi
        ):
            inversions.append(
                {"window": w, "interactive_p99": round(pi, 1),
                 "batch_p99": round(pb, 1)}
            )

    staleness = replica_set.staleness()
    rejoined_lag_max = max(
        (s["rejoined_lag"] for s in chaos["staleness_samples"]),
        default=0,
    )
    adm = admission.snapshot()

    n_burst = len(results) - trace_rows
    report = {
        "requests": requests,
        "workers": workers,
        "replicas": replicas,
        "resolved": trace_rows,
        "burst_extras": n_burst,
        "by_status": by_status,
        "wall_s": round(wall_s, 1),
        "req_per_s": round((requests + n_burst) / max(wall_s, 1e-9), 1),
        "ttft_p50_ms": {
            cls: round(pctl(v, 0.50), 1) for cls, v in ttft.items()
        },
        "ttft_p99_ms": {
            cls: round(pctl(v, 0.99), 1) for cls, v in ttft.items()
        },
        "slo_ms": {
            "interactive": slo_interactive_ms, "batch": slo_batch_ms,
        },
        "inversions": inversions,
        "sheds_by_class": sheds_by_class,
        "chaos": {
            "killed_at_request": chaos["killed_at"],
            "rejoined_at_request": chaos["rejoined_at"],
            "rejoined_lag_max": rejoined_lag_max,
            "staleness_samples": len(chaos["staleness_samples"]),
            "staleness_final": staleness,
        },
        "burst": dict(burst_stats),
        "admission": adm,
        "prefix_cache": {
            "num_blocks_per_worker": num_blocks,
            "ideal_hit_rate": prefix_report["ideal_hit_rate"],
            "curve": prefix_report["curve"],
        },
        "failover": None,   # filled below
        "trace_capture": os.environ.get("DYNTPU_TRACE", ""),
    }
    from dynamo_tpu.runtime.failover import FAILOVER

    report["failover"] = FAILOVER.snapshot()

    # -- teardown ----------------------------------------------------------
    await replica_set.stop()
    for inst, eng in zip(instances, engines):
        try:
            await inst.stop()
        except Exception:  # noqa: BLE001 — teardown
            pass
        await eng.stop()
    await drt0.shutdown()
    return report


def run_gates(
    report: dict, max_abs_p95: float = 4.0, tail_ratio: float = 8.0,
) -> list[str]:
    """The hard gates over the replay report + the merged multi-replica
    capture (benchmarks/route_audit.py). Returns failures (empty =
    green); bench.py raises on any.

    The per-class TTFT bound is ``max(SLO, tail_ratio * p50)``: the
    nominal SLO on a machine fast enough to be meaningful, and a
    machine-speed-normalized tail check everywhere else — a slow/shared
    CI host raises p50 and p99 together, while the failure this gate
    exists to catch (an overload spiral, a class being starved) blows
    the p99/p50 ratio out regardless of host speed."""
    failures: list[str] = []
    by = report["by_status"]
    if by.get("hang"):
        failures.append(f"{by['hang']} request(s) HUNG past the watchdog")
    if by.get("error"):
        failures.append(
            f"{by['error']} request(s) errored — the replica kill must "
            "be absorbed by failover while a healthy replica remains"
        )
    if by.get("short"):
        failures.append(
            f"{by['short']} request(s) LOST tokens (short streams)"
        )
    if report["resolved"] < report["requests"]:
        failures.append(
            f"only {report['resolved']}/{report['requests']} trace "
            "requests resolved"
        )
    # Per-class SLOs + inversion windows.
    for cls in ("interactive", "batch"):
        p99 = report["ttft_p99_ms"].get(cls, 0.0)
        p50 = report["ttft_p50_ms"].get(cls, 0.0)
        slo = report["slo_ms"][cls]
        bound = max(slo, tail_ratio * p50)
        if p99 > bound:
            failures.append(
                f"{cls} p99 TTFT {p99:.0f} ms > bound {bound:.0f} ms "
                f"(SLO {slo:.0f}, {tail_ratio:g}x p50 {p50:.0f})"
            )
    if report["inversions"]:
        failures.append(
            f"{len(report['inversions'])} cross-class SLO inversion "
            f"window(s): {report['inversions'][:3]}"
        )
    # Cheapest-first shedding: the burst's OWN batch arrivals must have
    # been refused, and interactive sheds — from ANY origin, the trace
    # loop included — must stay negligible next to batch's.
    burst = report["burst"]
    total_sheds = report.get("sheds_by_class", {})
    batch_shed_total = total_sheds.get(
        "batch", burst.get("batch_shed", 0)
    )
    interactive_shed_total = total_sheds.get(
        "interactive", burst.get("interactive_shed", 0)
    )
    if burst.get("batch_shed", 0) <= 0:
        failures.append(
            "overload burst shed ZERO batch requests — the class-"
            "weighted watermark never engaged"
        )
    if interactive_shed_total > max(2, batch_shed_total // 10):
        failures.append(
            f"interactive absorbed sheds ({interactive_shed_total} vs "
            f"batch {batch_shed_total}, all origins) — degradation is "
            "not cheapest-first"
        )
    # Replica chaos actually happened + staleness measured.
    if report["chaos"]["killed_at_request"] is None:
        failures.append("the replica kill never fired")
    if report["chaos"]["rejoined_at_request"] is None:
        failures.append("the replica rejoin never fired")
    elif report["chaos"]["rejoined_lag_max"] <= 0:
        failures.append(
            "rejoined replica's staleness was never measured > 0 — "
            "either no events were missed (implausible under load) or "
            "the instrument is broken"
        )
    # Load-proportional Retry-After actually engaged under the burst.
    hints = report["admission"].get("retry_after_by_reason", {})
    if burst.get("batch_shed", 0) and not hints:
        failures.append("429s carried no derived Retry-After hints")
    # Route-audit bound across ALL replicas, over the merged capture.
    capture = report.get("trace_capture")
    if capture:
        from benchmarks.route_audit import (
            join_report,
            load_records,
            run_asserts,
        )

        routes, actuals, _ = load_records([capture])
        audit = join_report(routes, actuals)
        report["route_audit"] = {
            k: audit[k] for k in (
                "routes", "actuals", "joined", "join_rate",
                "orphan_routes", "overlap_error", "per_replica",
            )
        }
        allowed_orphans = max(20, report["requests"] // 1000)
        failures += run_asserts(
            audit, min_join=0.99, max_orphan_routes=allowed_orphans,
            max_abs_p95=max_abs_p95,
        )
    else:
        failures.append(
            "no DYNTPU_TRACE capture — the multi-replica route-audit "
            "bound cannot be checked (set DYNTPU_TRACE)"
        )
    return failures


def main(argv: list[str] | None = None) -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser(
        prog="python benchmarks/ingress_bench.py",
        description="replicated-ingress trace replay proof",
    )
    ap.add_argument("--requests", type=int, default=int(
        os.environ.get("BENCH_INGRESS_REQUESTS", 100_000)
    ))
    ap.add_argument("--workers", type=int, default=int(
        os.environ.get("BENCH_INGRESS_WORKERS", 8)
    ))
    ap.add_argument("--replicas", type=int, default=int(
        os.environ.get("BENCH_INGRESS_REPLICAS", 2)
    ))
    ap.add_argument("--seed", type=int, default=int(
        os.environ.get("BENCH_INGRESS_SEED", 20260805)
    ))
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.WARNING)
    report = asyncio.run(run_ingress(
        requests=args.requests, workers=args.workers,
        replicas=args.replicas, seed=args.seed,
    ))
    failures = run_gates(report)
    print(json.dumps(report, indent=2))
    if failures:
        print("INGRESS GATES FAILED:\n  " + "\n  ".join(failures),
              file=sys.stderr)
        return 1
    print("ingress gates: OK", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())

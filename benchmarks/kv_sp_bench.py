"""kv_sp per-shard attention cost: does the striped scan deliver
O(ctx/sp) per shard? (VERDICT r04 next-round #1 'done' criterion.)

One real chip cannot host an sp>1 mesh, but it CAN run exactly the
workload ONE sp shard sees: the r05 striped decode kernel
(ops/pallas/attention.py page_stride) over a compacted stripe holding
1/sp of each lane's pages. Sweeping page_stride on the same per-lane
context measures the per-shard cost directly — the cross-shard merge
adds only an O(B*H) psum on top (measured separately by the virtual-mesh
tests; it is noise at these shapes).

Timing: kernel calls folded into jitted scans (q drawn cyclically from a
pool by traced index, so XLA cannot CSE the calls), at TWO rep counts —
the per-call figure is the SLOPE between them, which cancels the
tunneled chip's per-dispatch overhead (~130-200 ms, orders of magnitude
above the kernel itself; BENCHMARKS.md r02 methodology note).
"""

from __future__ import annotations

import time

import numpy as np


def run(
    B: int = 32,
    ctx: int = 4096,
    kvH: int = 8,
    H: int = 32,
    D: int = 128,
    bs: int = 16,
    strides: tuple[int, ...] = (1, 2, 4, 8),
    reps: tuple[int, int] = (64, 512),
    dtype="bfloat16",
) -> dict:
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.ops.pallas.attention import paged_decode_attention_pallas

    rng = np.random.default_rng(0)
    nb_lane = ctx // bs  # logical pages per lane
    POOL = 8
    out: dict[str, float] = {}
    for stride in strides:
        local_lane = -(-nb_lane // stride)  # this shard's pages per lane
        num_blocks = 1 + B * local_lane     # shard-local cache (+ trash)
        slots = num_blocks * bs
        k = jnp.asarray(
            rng.standard_normal((slots, kvH, D)), dtype=jnp.dtype(dtype)
        )
        v = jnp.asarray(
            rng.standard_normal((slots, kvH, D)), dtype=jnp.dtype(dtype)
        )
        tables = np.zeros((B, local_lane), np.int32)
        nxt = 1
        for b in range(B):
            tables[b] = range(nxt, nxt + local_lane)
            nxt += local_lane
        tables = jnp.asarray(tables)
        ctx_arr = jnp.full((B,), ctx, jnp.int32)
        off = jnp.zeros((1,), jnp.int32)
        qs = jnp.asarray(
            rng.standard_normal((POOL, B, H, D)), dtype=jnp.dtype(dtype)
        )

        def many(qs, k, v, tables, ctx_arr, off, R, _stride=stride):
            def step(acc, i):
                q = jax.lax.dynamic_index_in_dim(
                    qs, i % POOL, 0, keepdims=False
                )
                o, m, l = paged_decode_attention_pallas(
                    q, k, v, tables, ctx_arr, bs,
                    page_offset=off, page_stride=_stride, with_stats=True,
                )
                return acc + o.sum() + m.sum() + l.sum(), None

            acc, _ = jax.lax.scan(step, jnp.float32(0), jnp.arange(R))
            return acc

        def timed(R: int) -> float:
            fn = jax.jit(lambda *a: many(*a, R))
            # Sync via HOST materialization: through the tunneled chip,
            # block_until_ready returns before the device work finishes —
            # only a host transfer truly waits (measured; memory of r04).
            float(fn(qs, k, v, tables, ctx_arr, off))
            t0 = time.monotonic()
            N = 3
            for _ in range(N):
                float(fn(qs, k, v, tables, ctx_arr, off))
            return (time.monotonic() - t0) / N

        t_lo, t_hi = timed(reps[0]), timed(reps[1])
        per_call_us = max(t_hi - t_lo, 1e-9) / (reps[1] - reps[0]) * 1e6
        out[f"shard_attn_us_sp{stride}"] = round(per_call_us, 1)
    base = out["shard_attn_us_sp1"]
    for stride in strides[1:]:
        out[f"speedup_sp{stride}"] = round(
            base / out[f"shard_attn_us_sp{stride}"], 2
        )
    out.update({"B": B, "ctx": ctx, "kvH": kvH, "D": D, "block_size": bs})
    return out


def main() -> dict:
    import os

    smoke = bool(os.environ.get("BENCH_SMOKE"))
    r = run(
        B=4 if smoke else 32,
        ctx=128 if smoke else 4096,
        strides=(1, 2) if smoke else (1, 2, 4, 8),
        reps=(4, 16) if smoke else (64, 512),
    )
    sp4 = r.get("speedup_sp2" if smoke else "speedup_sp4", 0.0)
    return {
        "metric": "kv_sp_shard_attention_speedup_sp4",
        "value": sp4,
        "unit": "x (vs full scan; ideal 4.0)",
        "vs_baseline": sp4,
        "extras": r,
    }


if __name__ == "__main__":
    import json

    print(json.dumps(main()))

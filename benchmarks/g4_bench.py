"""G4 peer-tier proof: pull-vs-recompute win, predictive pre-placement,
and the mid-pull peer-death degrade.

``BENCH_G4=1 python bench.py`` (ci.sh "mocker G4 peer tier" leg) runs
three legs over in-process mocker fleets — the FULL G4 planes: blockset
discovery on the store, paced block serving over the transfer plane,
the admission-time pricing law, and the engine park/resume path
(docs/architecture/kvbm_g4.md):

1. **Pull win** — a cold worker whose prompt prefix lives only on a
   fleet peer must reach first token ≥2× faster by PULLING the packed
   rows (priced against the calibrated link,
   planner/calibration.HANDOFF_GBPS) than an identical cold worker
   recomputing the same prompt. The serve side is paced by the mocker
   peer-link model (``MockerConfig.peer_link_gbps`` →
   ``PeerBlockServer.serve_link_gbps``), so the win is measured against
   simulated DCN time, not loopback memcpy.

2. **Predictive pre-placement** — a popularity-skewed prefix workload
   feeds :class:`~dynamo_tpu.block_manager.peer.PrefixHeat`; a joining
   cold worker that gets ``preplace()``'d (the FleetPlanner
   ``on_scale_up`` hook's payload) must reach steady-state WARM hit
   rate ≥2× faster (in requests) than the same join without
   pre-placement. "Warm" counts G1/G2 hits only — an on-demand G4 pull
   still parks the first toucher, which is exactly the latency
   pre-placement deletes.

3. **Peer death mid-pull** — with the transfer held in flight
   (``kvbm.peer_pull`` delay seam) the serving peer is KILLED; the
   parked request must complete via local recompute within its
   deadline — byte-identical stream, counted degraded, fallback on the
   G4 counters, ZERO hangs under the watchdog.

Seeded (``BENCH_G4_SEED``): one seed replays one trace/schedule.
"""

# dynarace: context[loop]

from __future__ import annotations

import asyncio
import logging
import os
import random
import sys
import time

if __package__ in (None, ""):  # `python benchmarks/g4_bench.py`
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )

logger = logging.getLogger(__name__)

#: Mirrors mocker det_next_token — the closed-form greedy stream.
_A, _C, _D = 1103515245, 12345, 7


def expected_stream(prompt: list[int], osl: int, vocab: int) -> list[int]:
    """The deterministic tokens ANY healthy serving path must produce."""
    out: list[int] = []
    prev, pos = prompt[-1], len(prompt)
    for _ in range(osl):
        prev = (prev * _A + pos * _C + _D) % vocab
        out.append(prev)
        pos += 1
    return out


def _ecfg(**kw):
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.models.config import ModelConfig

    kw.setdefault("num_blocks", 192)
    kw.setdefault("max_num_seqs", 4)
    kw.setdefault("max_model_len", 2048)
    # The G2→G1 adaptive gate's probe ramp is the offload bench's story;
    # these legs measure tier PLACEMENT, so onboard the full match.
    kw.setdefault("kvbm_adaptive_gate", False)
    return EngineConfig(model=ModelConfig.tiny_test(), dtype="float32", **kw)


def _layout():
    from dynamo_tpu.block_manager import KvLayoutConfig

    # block_elems == 8: the mocker runner's 8-float block rows.
    return KvLayoutConfig(
        num_layers=1, page_size=1, num_kv_heads=1, head_dim=4,
        dtype="float32",
    )


async def _generate(engine, prompt, n=4):
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    req = PreprocessedRequest(
        token_ids=list(prompt),
        sampling=SamplingOptions(temperature=0.0),
        stop=StopConditions(max_tokens=n, ignore_eos=True),
    )
    out: list[int] = []
    ttft = None
    t0 = time.monotonic()
    async for item in engine.generate(Context(req.to_wire())):
        if ttft is None:
            ttft = time.monotonic() - t0
        out += item.get("token_ids", [])
    return out, (ttft if ttft is not None else time.monotonic() - t0)


async def _spawn_worker(main, *, cfg=None, link_gbps=0.0, host_blocks=128,
                        on_kv_actual=None):
    """One mocker worker on the shared fleet planes: runtime (own
    lease), KVBM, engine. Returns (drt, kvbm, engine)."""
    from dynamo_tpu.block_manager import KvbmConfig, KvBlockManager
    from dynamo_tpu.mocker.engine import MockerConfig, MockerEngine
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    from dynamo_tpu.planner import calibration as cal

    drt = await DistributedRuntime.in_process(
        store=main.store, bus=main.bus
    )
    kvbm = await KvBlockManager(
        KvbmConfig(layout=_layout(), host_blocks=host_blocks)
    ).start()
    eng = MockerEngine(
        cfg or _ecfg(),
        MockerConfig(
            seed=1,
            deterministic_tokens=True,
            peer_link_gbps=link_gbps,
            # Pin prefill cost to the calibrated r04 rate so the
            # recompute side of every pull-vs-recompute comparison is
            # the same one the pricing law uses (planner/calibration).
            prefill_time_per_token_us=cal.PREFILL_TIME_PER_TOKEN_US,
        ),
        block_manager=kvbm,
        on_kv_actual=on_kv_actual,
    )
    await eng.start()
    return drt, kvbm, eng


async def _export_peer(drt, kvbm, eng):
    """Export a worker's host tier as a G4 peer, paced at the worker's
    configured simulated link (MockerConfig.peer_link_gbps)."""
    from dynamo_tpu.block_manager.peer import PeerBlockServer

    comp = drt.namespace("kv").component("tpu")
    return await PeerBlockServer(
        drt, comp, kvbm, layout=_layout(), refresh_s=0.05,
        serve_link_gbps=eng.runner.sim.peer_link_gbps,
    ).start()


async def _attach_client(drt, kvbm, want_hashes, depth, timeout=10.0):
    """A G4 client on ``drt``, attached to ``kvbm`` once discovery shows
    a peer holding ``depth`` blocks of ``want_hashes``."""
    from dynamo_tpu.block_manager.peer import (
        PeerBlockClient,
        layout_fingerprint,
    )

    comp = drt.namespace("kv").component("tpu")
    # Handshake on the mocker layout, but price with the calibrated
    # default geometry (no layout_cfg): the 8-float sim rows are not
    # real KV bytes — pricing them as such would make every pull lose
    # to recomputing "one token", a simulation artifact.
    client = await PeerBlockClient(
        drt, comp, layout_fingerprint(_layout())
    ).start()
    deadline = asyncio.get_running_loop().time() + timeout
    while client.best_peer(want_hashes)[1] < depth:
        if asyncio.get_running_loop().time() >= deadline:
            raise TimeoutError("G4 peer discovery never converged")
        await asyncio.sleep(0.02)
    kvbm.attach_peer_client(client)
    return client


def _chain(tokens, block_size=16):
    from dynamo_tpu.llm.tokens import TokenBlockSequence

    return TokenBlockSequence.from_tokens(
        tokens, block_size=block_size
    ).sequence_hashes()


async def _wait_host(kvbm, n, timeout=10.0):
    deadline = asyncio.get_running_loop().time() + timeout
    while kvbm.stats()["host_registered"] < n:
        if asyncio.get_running_loop().time() >= deadline:
            raise TimeoutError(
                f"host tier never reached {n} registered blocks "
                f"(at {kvbm.stats()['host_registered']})"
            )
        await asyncio.sleep(0.02)


# ---------------------------------------------------------------------------
# leg 1: pull beats recompute at the calibrated link
# ---------------------------------------------------------------------------

async def _leg_pull_win(main) -> dict:
    from dynamo_tpu.planner import calibration as cal

    prompt = [(7 * i + 3) % 31991 for i in range(1600)]  # 100 blocks
    drt_a, kvbm_a, eng_a = await _spawn_worker(
        main, link_gbps=cal.HANDOFF_GBPS
    )
    server = None
    drt_b = kvbm_b = eng_b = client = None
    drt_c = kvbm_c = eng_c = None
    try:
        cold_toks, _ = await _generate(eng_a, prompt)
        prefix_blocks = (len(prompt) - 1) // 16
        await _wait_host(kvbm_a, prefix_blocks)
        server = await _export_peer(drt_a, kvbm_a, eng_a)

        # B: cold, peer-attached — parks at admission, pulls, resumes.
        drt_b, kvbm_b, eng_b = await _spawn_worker(main)
        client = await _attach_client(
            drt_b, kvbm_b, _chain(prompt), prefix_blocks
        )
        pulled_toks, ttft_pull = await _generate(eng_b, prompt)

        # C: cold, NO peer client — the recompute baseline.
        drt_c, kvbm_c, eng_c = await _spawn_worker(main)
        recomputed_toks, ttft_recompute = await _generate(eng_c, prompt)

        rd = eng_b.readiness()
        return {
            "prompt_tokens": len(prompt),
            "prefix_blocks": prefix_blocks,
            "ttft_pull_ms": round(ttft_pull * 1e3, 2),
            "ttft_recompute_ms": round(ttft_recompute * 1e3, 2),
            "speedup": round(ttft_recompute / max(ttft_pull, 1e-9), 2),
            "streams_identical": (
                pulled_toks == cold_toks == recomputed_toks
            ),
            "pulls_total": rd["kvbm_g4_pulls_total"],
            "pull_bytes_total": rd["kvbm_g4_pull_bytes_total"],
            "reused_peer_blocks": rd["kv_reused_peer_blocks_total"],
            "link_peer_bps": rd["kvbm_link_peer_bps"],
        }
    finally:
        for eng in (eng_b, eng_c, eng_a):
            if eng is not None:
                await eng.stop()
        if client is not None:
            await client.stop()
        if server is not None:
            await server.stop()
        for kvbm in (kvbm_b, kvbm_c, kvbm_a):
            if kvbm is not None:
                await kvbm.stop()
        for drt in (drt_b, drt_c, drt_a):
            if drt is not None:
                await drt.shutdown()


# ---------------------------------------------------------------------------
# leg 2: predictive pre-placement — cold join reaches steady state faster
# ---------------------------------------------------------------------------

_PREFIX_BLOCKS = 4          # 64-token shared prefixes
_STEADY_WINDOW = 6          # consecutive warm requests = steady state


def _prefix_tokens(p: int) -> list[int]:
    # Distinct leading token per prefix -> distinct hash chains.
    return [(p + 1) * 1000 + i for i in range(_PREFIX_BLOCKS * 16)]


def _join_trace(rng, prefixes: int, requests: int) -> list[int]:
    """Popularity-skewed prefix draws; every prefix appears at least
    once so the no-preplace join must first-touch all of them."""
    pop = [max(prefixes - p, 1) for p in range(prefixes)]
    trace = list(range(prefixes))
    trace += rng.choices(range(prefixes), weights=pop,
                         k=requests - prefixes)
    rng.shuffle(trace)
    return trace


async def _join_and_serve(main, heat, trace, preplaced: bool) -> dict:
    """One cold join serving ``trace``; returns its warm-up curve."""
    from dynamo_tpu.block_manager.peer import preplace

    hot = heat.hottest(1)[0]
    drt, kvbm, eng = None, None, None
    client = None
    actuals: list[dict] = []
    try:
        drt, kvbm, eng = await _spawn_worker(
            main, host_blocks=96, on_kv_actual=actuals.append
        )
        client = await _attach_client(drt, kvbm, hot, _PREFIX_BLOCKS)
        preplaced_blocks = 0
        if preplaced:
            preplaced_blocks = await preplace(
                client, kvbm, heat, top_k=64
            )
        warm: list[bool] = []
        for i, p in enumerate(trace):
            tail = [29000 + i * 8 + j for j in range(8)]
            pulls_before = client.pulls_total
            await _generate(eng, _prefix_tokens(p) + tail, n=2)
            rec = actuals[-1]
            # Warm = the prefix was served from tiers already ON this
            # worker (G1/G2 — including pre-placed peer-origin rows)
            # with no new G4 pull: a first-touch on-demand pull parks
            # the request on the transfer, which is exactly the latency
            # pre-placement deletes.
            warm.append(
                rec["device_blocks"] + rec["host_blocks"]
                + rec["peer_blocks"] >= _PREFIX_BLOCKS
                and client.pulls_total == pulls_before
            )
        steady = len(trace) + _STEADY_WINDOW  # sentinel: never steady
        for i in range(_STEADY_WINDOW, len(trace) + 1):
            if all(warm[i - _STEADY_WINDOW:i]):
                steady = i
                break
        return {
            "requests": len(trace),
            "warm_hits": sum(warm),
            "requests_to_steady": steady,
            "preplaced_blocks": preplaced_blocks,
        }
    finally:
        if eng is not None:
            await eng.stop()
        if client is not None:
            await client.stop()
        if kvbm is not None:
            await kvbm.stop()
        if drt is not None:
            await drt.shutdown()


async def _leg_preplace(main, seed: int, prefixes: int,
                        join_requests: int) -> dict:
    from dynamo_tpu.block_manager.peer import PrefixHeat

    rng = random.Random(seed)
    drt_a, kvbm_a, eng_a = await _spawn_worker(main, host_blocks=96)
    server = None
    try:
        # Warm the donor with the full prefix set; heat mirrors the
        # popularity the router would have observed.
        heat = PrefixHeat(decay=0.995)
        pop = [max(prefixes - p, 1) for p in range(prefixes)]
        for p in range(prefixes):
            toks = _prefix_tokens(p) + [28000 + p]
            await _generate(eng_a, toks, n=2)
            heat.note(_chain(_prefix_tokens(p)), weight=pop[p])
        await _wait_host(kvbm_a, prefixes * _PREFIX_BLOCKS)
        server = await _export_peer(drt_a, kvbm_a, eng_a)

        trace = _join_trace(rng, prefixes, join_requests)
        nopre = await _join_and_serve(main, heat, trace, preplaced=False)
        pre = await _join_and_serve(main, heat, trace, preplaced=True)
        return {
            "prefixes": prefixes,
            "join_requests": join_requests,
            "no_preplace": nopre,
            "preplace": pre,
            "speedup": round(
                nopre["requests_to_steady"]
                / max(pre["requests_to_steady"], 1),
                2,
            ),
        }
    finally:
        await eng_a.stop()
        if server is not None:
            await server.stop()
        await kvbm_a.stop()
        await drt_a.shutdown()


# ---------------------------------------------------------------------------
# leg 3: peer killed mid-pull — recompute, degraded, zero hangs
# ---------------------------------------------------------------------------

async def _leg_peer_death(main) -> dict:
    from dynamo_tpu.utils.faults import FAULTS

    prompt = [(11 * i + 5) % 31991 for i in range(40)]
    drt_a, kvbm_a, eng_a = await _spawn_worker(main)
    server = None
    drt_b = kvbm_b = eng_b = client = None
    try:
        _cold, _ = await _generate(eng_a, prompt)
        await _wait_host(kvbm_a, 2)
        server = await _export_peer(drt_a, kvbm_a, eng_a)

        drt_b, kvbm_b, eng_b = await _spawn_worker(
            main, cfg=_ecfg(kvbm_peer_timeout_s=0.5)
        )
        client = await _attach_client(drt_b, kvbm_b, _chain(prompt), 2)

        # Hold the transfer in flight, then kill the serving peer under
        # it — the deadline must resume the request via recompute.
        FAULTS.arm("kvbm.peer_pull", "delay", times=None, delay_s=5.0)
        task = asyncio.ensure_future(_generate(eng_b, prompt))
        deadline = asyncio.get_running_loop().time() + 10
        while not eng_b._peer_parked:
            if asyncio.get_running_loop().time() >= deadline:
                raise TimeoutError("request never parked on the pull")
            await asyncio.sleep(0.01)
        await server.stop()
        server = None
        toks, _ttft = await asyncio.wait_for(task, timeout=30)

        vocab = eng_b.runner.sim.vocab_size
        rd = eng_b.readiness()
        return {
            "completed": True,
            "stream_identical": toks == expected_stream(prompt, 4, vocab),
            "degraded_requests": eng_b.degraded_requests,
            "pull_fallbacks_total": rd["kvbm_g4_pull_fallbacks_total"],
            "reused_peer_blocks": rd["kv_reused_peer_blocks_total"],
        }
    finally:
        FAULTS.disarm("kvbm.peer_pull")
        for eng in (eng_b, eng_a):
            if eng is not None:
                await eng.stop()
        if kvbm_b is not None:
            try:
                await kvbm_b.drain_pulls(timeout_s=10)
            except TimeoutError:
                pass
        if client is not None:
            await client.stop()
        if server is not None:
            await server.stop()
        for kvbm in (kvbm_b, kvbm_a):
            if kvbm is not None:
                await kvbm.stop()
        for drt in (drt_b, drt_a):
            if drt is not None:
                await drt.shutdown()


# ---------------------------------------------------------------------------
# harness
# ---------------------------------------------------------------------------

async def run_g4(
    seed: int = 20260806,
    prefixes: int = 8,
    join_requests: int = 24,
) -> dict:
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    main = await DistributedRuntime.in_process()
    try:
        pull = await _leg_pull_win(main)
        pre = await _leg_preplace(main, seed, prefixes, join_requests)
        death = await _leg_peer_death(main)
    finally:
        await main.shutdown()
    return {"seed": seed, "pull": pull, "preplace": pre,
            "peer_death": death}


def run_gates(report: dict) -> list[str]:
    """Hard gates (BENCHMARKS.md 'G4 peer tier'). Returns failures."""
    failures: list[str] = []
    pull = report["pull"]
    if not pull["streams_identical"]:
        failures.append("pull: streams diverged across the tier")
    if pull["speedup"] < 2.0:
        failures.append(
            f"pull: TTFT speedup {pull['speedup']}x < 2x "
            f"(pull {pull['ttft_pull_ms']} ms vs recompute "
            f"{pull['ttft_recompute_ms']} ms)"
        )
    if pull["pulls_total"] < 1 or pull["reused_peer_blocks"] < 1:
        failures.append("pull: no G4 pull was actually taken")
    pre = report["preplace"]
    if pre["speedup"] < 2.0:
        failures.append(
            f"preplace: steady-state speedup {pre['speedup']}x < 2x "
            f"(no-preplace {pre['no_preplace']['requests_to_steady']} "
            f"vs preplace {pre['preplace']['requests_to_steady']} "
            "requests)"
        )
    if pre["preplace"]["preplaced_blocks"] < 1:
        failures.append("preplace: nothing was pre-placed")
    death = report["peer_death"]
    if not death["completed"]:
        failures.append("peer_death: request hung")
    if not death["stream_identical"]:
        failures.append("peer_death: recomputed stream diverged")
    if death["degraded_requests"] != 1:
        failures.append(
            f"peer_death: degraded_requests "
            f"{death['degraded_requests']} != 1"
        )
    if death["pull_fallbacks_total"] < 1:
        failures.append("peer_death: fallback not counted on G4 surface")
    if death["reused_peer_blocks"] != 0:
        failures.append("peer_death: phantom peer reuse counted")
    return failures


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    rep = asyncio.run(run_g4())
    import json

    print(json.dumps(rep, indent=2))
    fails = run_gates(rep)
    if fails:
        print("GATES FAILED:\n  " + "\n  ".join(fails), file=sys.stderr)
        raise SystemExit(1)
    print("all G4 gates passed")
